/* Imperative training from plain C — no executor, no Python in this file.
 *
 * Parity target: the reference's imperative C surface
 * (/root/reference/src/c_api/c_api_ndarray.cc: MXImperativeInvoke :423,
 * MXAutogradSetIsRecording/MarkVariables/BackwardEx :545-621, CachedOp
 * :464-485).  This program exercises the TPU-native equivalents:
 *
 *   1. ops invoked imperatively by registry name (MXTImperativeInvoke)
 *   2. autograd recording + backward outside any bound executor
 *   3. an SGD update applied through the Updater
 *   4. a CachedOp replaying the same graph as one compiled call
 *
 * Task: least-squares regression y = X w (16 features) on synthetic
 * data from a known w*.  Exit 0 iff the imperative loop drives the MSE
 * below 1e-2 AND the CachedOp's prediction matches the imperative
 * forward to 1e-4.
 *
 * Build (see tests/test_native.py::test_c_imperative_autograd_trains):
 *   gcc -std=c99 imperative_train.c -L../../mxnet_tpu -lmxtpu
 */
#include <stdint.h>
#include <stdio.h>
#include <stdlib.h>

/* Training C ABI (src/c_api_train.cc) */
extern const char* MXTTrainGetLastError(void);
extern int MXTNDArrayCreateFromBytes(const uint32_t*, uint32_t,
                                     const float*, int, int, void**);
extern int MXTNDArraySyncCopyToCPU(void*, float*, size_t);
extern void MXTNDArrayFree(void*);
extern int MXTImperativeInvoke(const char*, uint32_t, void**, uint32_t,
                               const char**, const char**, uint32_t*,
                               void**, uint32_t);
extern int MXTAutogradSetIsRecording(int, int*);
extern int MXTAutogradSetIsTraining(int, int*);
extern int MXTAutogradMarkVariables(uint32_t, void**, const char**);
extern int MXTAutogradBackward(uint32_t, void**, int);
extern int MXTNDArrayGetGrad(void*, void**);
extern int MXTUpdaterCreate(const char*, uint32_t, const char**,
                            const char**, void**);
extern int MXTUpdaterStep(void*, int, void*, void*);
extern void MXTUpdaterFree(void*);
extern int MXTSymbolCreateVariable(const char*, void**);
extern int MXTSymbolCreate(const char*, const char*, uint32_t,
                           const char**, const char**, uint32_t,
                           const char**, void**, void**);
extern void MXTSymbolFree(void*);
extern int MXTCachedOpCreate(void*, void**);
extern int MXTCachedOpInvoke(void*, uint32_t, void**, uint32_t*, void**,
                             uint32_t);
extern void MXTCachedOpFree(void*);

#define CHECK(rc, what)                                            \
  do {                                                             \
    if ((rc) != 0) {                                               \
      fprintf(stderr, "%s failed: %s\n", what,                     \
              MXTTrainGetLastError());                             \
      return 1;                                                    \
    }                                                              \
  } while (0)

#define N 256
#define F 16
#define STEPS 200

/* xorshift PRNG so the data is deterministic without libc rand */
static uint32_t rng_state = 2463534242u;
static float absmax(float cur, float v) {
  if (v < 0) v = -v;
  return v > cur ? v : cur;
}

static float frand(void) {
  rng_state ^= rng_state << 13;
  rng_state ^= rng_state >> 17;
  rng_state ^= rng_state << 5;
  return (float)(rng_state & 0xffffff) / (float)0x1000000 - 0.5f;
}

/* one imperative op with no attrs, single output */
static int invoke1(const char* op, uint32_t nin, void** ins, void** out) {
  uint32_t nout = 0;
  return MXTImperativeInvoke(op, nin, ins, 0, NULL, NULL, &nout, out, 1);
}

int main(void) {
  float xs[N * F], ts[N], wstar[F];
  int i, f, step;
  for (f = 0; f < F; ++f) wstar[f] = 2.0f * frand();
  for (i = 0; i < N; ++i) {
    float y = 0.f;
    for (f = 0; f < F; ++f) {
      xs[i * F + f] = frand();
      y += xs[i * F + f] * wstar[f];
    }
    ts[i] = y;
  }

  uint32_t xshape[2] = {N, F}, tshape[2] = {N, 1}, wshape[2] = {1, F};
  float w0[F];
  for (f = 0; f < F; ++f) w0[f] = 0.f;

  void *x, *t, *w;
  CHECK(MXTNDArrayCreateFromBytes(xshape, 2, xs, 1, 0, &x), "create x");
  CHECK(MXTNDArrayCreateFromBytes(tshape, 2, ts, 1, 0, &t), "create t");
  CHECK(MXTNDArrayCreateFromBytes(wshape, 2, w0, 1, 0, &w), "create w");

  CHECK(MXTAutogradMarkVariables(1, &w, NULL), "mark w");

  void* sgd;
  {
    const char* k[] = {"learning_rate"};
    const char* v[] = {"0.5"};
    CHECK(MXTUpdaterCreate("sgd", 1, k, v, &sgd), "updater");
  }

  int prev_rec, prev_train;
  float last_loss = 1e30f, loss_host;
  CHECK(MXTAutogradSetIsTraining(1, &prev_train), "set training");
  for (step = 0; step < STEPS; ++step) {
    CHECK(MXTAutogradSetIsRecording(1, &prev_rec), "set recording");

    /* y = FullyConnected(x, w) -> (N, 1); then mse = mean((y - t)^2) */
    void *y, *d, *sq, *loss;
    {
      void* ins[2];
      ins[0] = x;
      ins[1] = w;
      const char* k[] = {"num_hidden", "no_bias"};
      const char* v[] = {"1", "True"};
      uint32_t nout = 0;
      CHECK(MXTImperativeInvoke("FullyConnected", 2, ins, 2, k, v, &nout,
                                &y, 1),
            "FullyConnected");
    }
    {
      void* ins[2];
      ins[0] = y;
      ins[1] = t;
      CHECK(invoke1("elemwise_sub", 2, ins, &d), "elemwise_sub");
    }
    CHECK(invoke1("square", 1, &d, &sq), "square");
    CHECK(invoke1("mean", 1, &sq, &loss), "mean");

    CHECK(MXTAutogradSetIsRecording(0, &prev_rec), "stop recording");
    CHECK(MXTAutogradBackward(1, &loss, 0), "backward");

    void* g;
    CHECK(MXTNDArrayGetGrad(w, &g), "get grad");
    CHECK(MXTUpdaterStep(sgd, 0, g, w), "sgd step");
    MXTNDArrayFree(g);

    CHECK(MXTNDArraySyncCopyToCPU(loss, &loss_host, 1), "fetch loss");
    if (step % 50 == 0)
      printf("step %3d  mse %.6f\n", step, (double)loss_host);
    last_loss = loss_host;

    MXTNDArrayFree(y);
    MXTNDArrayFree(d);
    MXTNDArrayFree(sq);
    MXTNDArrayFree(loss);
  }
  printf("final mse %.6f\n", (double)last_loss);
  if (!(last_loss < 1e-2f)) {
    fprintf(stderr, "imperative training did not converge\n");
    return 1;
  }

  /* recovered weights should be close to w* */
  {
    float wr[F];
    CHECK(MXTNDArraySyncCopyToCPU(w, wr, F), "fetch w");
    float err = 0.f;
    for (f = 0; f < F; ++f) err = absmax(err, wr[f] - wstar[f]);
    printf("max |w - w*| = %.4f\n", (double)err);
    if (!(err < 0.2f)) {
      fprintf(stderr, "recovered weights too far from truth\n");
      return 1;
    }
  }

  /* CachedOp: same graph as a compiled replay; must match the
   * imperative forward on the trained weights */
  {
    void *vd, *vw, *fc, *cached;
    CHECK(MXTSymbolCreateVariable("data", &vd), "var data");
    CHECK(MXTSymbolCreateVariable("weight", &vw), "var weight");
    {
      const char* k[] = {"num_hidden", "no_bias"};
      const char* v[] = {"1", "True"};
      const char* argn[] = {"data", "weight"};
      void* args[2];
      args[0] = vd;
      args[1] = vw;
      CHECK(MXTSymbolCreate("FullyConnected", "fc", 2, k, v, 2, argn,
                            args, &fc),
            "symbol FC");
    }
    CHECK(MXTCachedOpCreate(fc, &cached), "cached create");

    float ref[N], got[N];
    void* yimp;
    {
      void* ins[2];
      ins[0] = x;
      ins[1] = w;
      const char* k[] = {"num_hidden", "no_bias"};
      const char* v[] = {"1", "True"};
      uint32_t nout = 0;
      CHECK(MXTImperativeInvoke("FullyConnected", 2, ins, 2, k, v, &nout,
                                &yimp, 1),
            "imperative ref");
    }
    CHECK(MXTNDArraySyncCopyToCPU(yimp, ref, N), "fetch ref");

    int rep;
    for (rep = 0; rep < 2; ++rep) { /* second call replays the cache */
      void* ins[2];
      void* outs[1];
      uint32_t nout = 0;
      ins[0] = x;
      ins[1] = w;
      CHECK(MXTCachedOpInvoke(cached, 2, ins, &nout, outs, 1),
            "cached invoke");
      if (nout != 1) {
        fprintf(stderr, "cached op: expected 1 output, got %u\n", nout);
        return 1;
      }
      CHECK(MXTNDArraySyncCopyToCPU(outs[0], got, N), "fetch cached");
      MXTNDArrayFree(outs[0]);
      float err = 0.f;
      for (i = 0; i < N; ++i) err = absmax(err, got[i] - ref[i]);
      printf("cached-op rep %d max err vs imperative: %.2e\n", rep,
             (double)err);
      if (!(err < 1e-4f)) {
        fprintf(stderr, "cached op diverges from imperative forward\n");
        return 1;
      }
    }
    MXTCachedOpFree(cached);
    MXTSymbolFree(fc);
    MXTSymbolFree(vw);
    MXTSymbolFree(vd);
    MXTNDArrayFree(yimp);
  }

  MXTUpdaterFree(sgd);
  MXTNDArrayFree(x);
  MXTNDArrayFree(t);
  MXTNDArrayFree(w);
  printf("C IMPERATIVE/AUTOGRAD/CACHEDOP OK\n");
  return 0;
}
