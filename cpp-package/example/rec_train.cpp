// Train from a RecordIO file end-to-end in C++ — zero Python in this file.
//
// Parity target: the reference's language bindings all train from .rec
// files through the DataIter C API (MXListDataIters /
// MXDataIterCreateIter / Next / GetData / GetLabel,
// /root/reference/src/c_api/c_api.cc; cpp-package MXDataIter).  Same
// flow here: create an ImageRecordIter by name with string params,
// stream batches, feed the bound executor with device-side copies, run
// minibatch SGD.
//
// Usage: rec_train <path.rec> <edge> <classes>
// The .rec holds <edge>x<edge> color images whose class is encoded in
// the dominant color (see tests/test_native.py), so a small MLP
// separates them quickly.  Exit 0 iff train accuracy > 0.9.
#include <algorithm>
#include <cmath>
#include <cstdio>
#include <random>
#include <string>
#include <vector>

#include "mxnet-tpu-cpp/MxTpuCpp.hpp"

namespace mc = mxtpu::cpp;

constexpr int kBatch = 16;
constexpr int kEpochs = 8;

mc::Symbol BuildMLP(int classes) {
  mc::Symbol data = mc::Symbol::Variable("data");
  mc::Symbol label = mc::Symbol::Variable("softmax_label");
  mc::Symbol flat = mc::Symbol::Create("Flatten", "flat", {},
                                       {{"data", &data}});
  mc::Symbol fc1 = mc::Symbol::Create(
      "FullyConnected", "fc1", {{"num_hidden", "32"}}, {{"data", &flat}});
  mc::Symbol act1 = mc::Symbol::Create(
      "Activation", "relu1", {{"act_type", "relu"}}, {{"data", &fc1}});
  mc::Symbol fc2 = mc::Symbol::Create(
      "FullyConnected", "fc2",
      {{"num_hidden", std::to_string(classes)}}, {{"data", &act1}});
  return mc::Symbol::Create("SoftmaxOutput", "softmax", {},
                            {{"data", &fc2}, {"softmax_label", &label}});
}

std::vector<float> InitWeights(size_t n, size_t fan_in, unsigned seed) {
  std::mt19937 gen(seed);
  float bound = std::sqrt(6.f / static_cast<float>(fan_in ? fan_in : 1));
  std::uniform_real_distribution<float> dist(-bound, bound);
  std::vector<float> w(n);
  for (float& v : w) v = dist(gen);
  return w;
}

int main(int argc, char** argv) {
  if (argc < 4) {
    std::fprintf(stderr, "usage: %s <path.rec> <edge> <classes>\n",
                 argv[0]);
    return 2;
  }
  const std::string rec_path = argv[1];
  const int edge = std::atoi(argv[2]);
  const int classes = std::atoi(argv[3]);

  // The registered iterators are discoverable, like MXListDataIters.
  bool have_rec_iter = false;
  for (const std::string& n : mc::DataIter::List())
    if (n == "ImageRecordIter") have_rec_iter = true;
  if (!have_rec_iter) {
    std::fprintf(stderr, "ImageRecordIter not registered\n");
    return 1;
  }

  char shape_buf[64];
  std::snprintf(shape_buf, sizeof(shape_buf), "(3,%d,%d)", edge, edge);
  // mean/std normalization rides the iterator (reference augmenter
  // params) — raw 0-255 pixels would saturate the MLP's first layer
  mc::DataIter train("ImageRecordIter",
                     {{"path_imgrec", rec_path},
                      {"data_shape", shape_buf},
                      {"batch_size", std::to_string(kBatch)},
                      {"shuffle", "true"},
                      {"mean_r", "127"}, {"mean_g", "127"},
                      {"mean_b", "127"},
                      {"std_r", "60"}, {"std_g", "60"}, {"std_b", "60"}});

  mc::Symbol net = BuildMLP(classes);
  mc::Executor exec(net, mc::kCPU, 0, "write",
                    {{"data", {kBatch, 3, static_cast<uint32_t>(edge),
                               static_cast<uint32_t>(edge)}},
                     {"softmax_label", {kBatch}}});

  std::vector<std::string> params;
  for (const std::string& name : net.ListArguments()) {
    if (name == "data" || name == "softmax_label") continue;
    params.push_back(name);
    mc::NDArray arg = exec.Arg(name);
    mc::Shape shape = arg.GetShape();
    size_t n = 1;
    for (uint32_t d : shape) n *= d;
    size_t fan_in = shape.size() > 1 ? shape[1] : shape[0];
    if (name.find("bias") != std::string::npos)
      arg.CopyFrom(std::vector<float>(n, 0.f));
    else
      arg.CopyFrom(InitWeights(n, fan_in, 11 + n));
  }

  // rescale_grad averages the summed per-sample gradients over the
  // batch — Module.init_optimizer does this implicitly; raw Updater
  // callers must say it themselves (reference optimizer contract)
  mc::Updater sgd("sgd", {{"learning_rate", "0.01"},
                          {"momentum", "0.9"},
                          {"rescale_grad",
                           std::to_string(1.0 / kBatch)}});
  mc::NDArray data_arr = exec.Arg("data");
  mc::NDArray label_arr = exec.Arg("softmax_label");
  std::vector<mc::NDArray> weights, grads;
  for (const std::string& name : params) {
    weights.push_back(exec.Arg(name));
    grads.push_back(exec.Grad(name));
  }

  float accuracy = 0.f, best = 0.f;
  for (int epoch = 0; epoch < kEpochs; ++epoch) {
    train.BeforeFirst();
    int correct = 0, seen = 0;
    while (train.Next()) {
      mc::NDArray batch = train.GetData();
      mc::NDArray labels = train.GetLabel();
      // device-side refill of the bound inputs — no host round-trip
      data_arr.CopyFrom(batch);
      label_arr.CopyFrom(labels);
      exec.Forward(true);
      exec.Backward();
      for (size_t p = 0; p < params.size(); ++p)
        sgd.Step(static_cast<int>(p), grads[p], &weights[p]);
      std::vector<float> probs = exec.Output(0).ToVector();
      std::vector<float> yb = labels.ToVector();
      int pad = train.GetPadNum();
      for (int i = 0; i < kBatch - pad; ++i) {
        const float* row = probs.data() + i * classes;
        int pred = static_cast<int>(
            std::max_element(row, row + classes) - row);
        correct += (pred == static_cast<int>(yb[i]));
        ++seen;
      }
    }
    accuracy = seen ? static_cast<float>(correct) / seen : 0.f;
    best = std::max(best, accuracy);
    std::printf("epoch %d train-accuracy %.4f (%d samples)\n", epoch,
                accuracy, seen);
    if (best > 0.97f) break;
  }
  std::printf("final train-accuracy %.4f (best %.4f)\n", accuracy, best);
  return best > 0.9f ? 0 : 1;
}
