// The C predict demo (examples/c_predict/predict.c) rewritten on the
// C++ header API — reference cpp-package example style.
//
//   predict_cpp <checkpoint-prefix> <epoch> <input.f32> <d0> [d1...]
#include <cstdlib>
#include <iostream>

#include "mxnet-tpu-cpp/MxTpuCpp.hpp"

int main(int argc, char** argv) {
  if (argc < 5) {
    std::cerr << "usage: " << argv[0]
              << " prefix epoch in.f32 d0 [d1 d2 d3]\n";
    return 2;
  }
  mxtpu::cpp::Shape shape;
  size_t n = 1;
  for (int i = 4; i < argc; ++i) {
    shape.push_back(std::atoi(argv[i]));
    n *= shape.back();
  }
  std::string raw = mxtpu::cpp::ReadFile(argv[3]);
  std::vector<float> input(
      reinterpret_cast<const float*>(raw.data()),
      reinterpret_cast<const float*>(raw.data()) + n);

  auto pred = mxtpu::cpp::Predictor::FromCheckpoint(
      argv[1], std::atoi(argv[2]), {{"data", shape}});
  pred.SetInput("data", input);
  pred.Forward();
  std::vector<float> out = pred.GetOutput(0);
  size_t best = 0;
  for (size_t i = 1; i < out.size(); ++i)
    if (out[i] > out[best]) best = i;
  std::cout << "predicted=" << best << " score=" << out[best] << "\n";
  return 0;
}
