// Train an MLP classifier end-to-end from C++ — zero Python in this file.
//
// Parity target: the reference cpp-package trains an MLP through its C
// ABI (/root/reference/cpp-package/example/mlp.cpp: build symbols,
// SimpleBind, Forward/Backward, SGD update).  Same flow here over the
// TPU-native training C ABI (src/c_api_train.cc): compose the symbol,
// simple_bind with gradients, run minibatch SGD with momentum via the
// Updater, report train accuracy.
//
// Data: a deterministic synthetic 10-class Gaussian-blobs problem (the
// classic separable-MLP smoke data) so the example is self-contained
// and CI-fast; swap GenerateBlobs for an MNIST reader to train on real
// digits.  Exit code 0 iff final train accuracy > 0.9.
//
// Build (see tests/test_native.py::test_cpp_package_trains_mlp):
//   g++ -std=c++14 mlp_train.cpp -I../include -L../../mxnet_tpu \
//       -lmxtpu -o mlp_train
#include <algorithm>
#include <cmath>
#include <cstdio>
#include <random>
#include <vector>

#include "mxnet-tpu-cpp/MxTpuCpp.hpp"

namespace mc = mxtpu::cpp;

constexpr int kClasses = 10;
constexpr int kFeatures = 32;
constexpr int kTrain = 2048;
constexpr int kBatch = 128;
constexpr int kEpochs = 6;

// 10 Gaussian blobs, one per class, centers drawn once from a fixed
// seed.  Labels cycle 0..9 so every minibatch is class-balanced; swap
// in a real reader (and shuffle) for actual datasets.
void GenerateBlobs(std::vector<float>* xs, std::vector<float>* ys) {
  std::mt19937 gen(42);
  std::normal_distribution<float> unit(0.f, 1.f);
  std::vector<float> centers(kClasses * kFeatures);
  for (float& c : centers) c = 2.5f * unit(gen);
  xs->resize(kTrain * kFeatures);
  ys->resize(kTrain);
  for (int i = 0; i < kTrain; ++i) {
    int label = i % kClasses;
    (*ys)[i] = static_cast<float>(label);
    for (int f = 0; f < kFeatures; ++f)
      (*xs)[i * kFeatures + f] =
          centers[label * kFeatures + f] + unit(gen);
  }
}

mc::Symbol BuildMLP() {
  mc::Symbol data = mc::Symbol::Variable("data");
  mc::Symbol label = mc::Symbol::Variable("softmax_label");
  mc::Symbol fc1 = mc::Symbol::Create(
      "FullyConnected", "fc1", {{"num_hidden", "64"}}, {{"data", &data}});
  mc::Symbol act1 = mc::Symbol::Create(
      "Activation", "relu1", {{"act_type", "relu"}}, {{"data", &fc1}});
  mc::Symbol fc2 = mc::Symbol::Create(
      "FullyConnected", "fc2", {{"num_hidden", "10"}}, {{"data", &act1}});
  return mc::Symbol::Create("SoftmaxOutput", "softmax", {},
                            {{"data", &fc2}, {"softmax_label", &label}});
}

// He-style scaled uniform init, host-side (no Python).
std::vector<float> InitWeights(size_t n, size_t fan_in, unsigned seed) {
  std::mt19937 gen(seed);
  float bound = std::sqrt(6.f / static_cast<float>(fan_in ? fan_in : 1));
  std::uniform_real_distribution<float> dist(-bound, bound);
  std::vector<float> w(n);
  for (float& v : w) v = dist(gen);
  return w;
}

int main() {
  std::vector<float> xs, ys;
  GenerateBlobs(&xs, &ys);

  mc::Symbol net = BuildMLP();
  mc::Executor exec(net, mc::kCPU, 0, "write",
                    {{"data", {kBatch, kFeatures}},
                     {"softmax_label", {kBatch}}});

  // Initialize every learnable parameter (inputs are fed per batch).
  std::vector<std::string> params;
  for (const std::string& name : net.ListArguments()) {
    if (name == "data" || name == "softmax_label") continue;
    params.push_back(name);
    mc::NDArray arg = exec.Arg(name);
    mc::Shape shape = arg.GetShape();
    size_t n = 1;
    for (uint32_t d : shape) n *= d;
    size_t fan_in = shape.size() > 1 ? shape[1] : shape[0];
    if (name.find("bias") != std::string::npos)
      arg.CopyFrom(std::vector<float>(n, 0.f));
    else
      arg.CopyFrom(InitWeights(n, fan_in, 7 + n));
  }

  mc::Updater sgd("sgd", {{"learning_rate", "0.005"},
                          {"momentum", "0.9"},
                          {"wd", "0.0001"}});
  mc::NDArray data_arr = exec.Arg("data");
  mc::NDArray label_arr = exec.Arg("softmax_label");
  // Hoist the per-parameter weight/grad handles out of the hot loop —
  // they alias the executor's buffers, so one fetch each suffices.
  std::vector<mc::NDArray> weights, grads;
  for (const std::string& name : params) {
    weights.push_back(exec.Arg(name));
    grads.push_back(exec.Grad(name));
  }

  const int batches = kTrain / kBatch;
  float accuracy = 0.f, best = 0.f;
  for (int epoch = 0; epoch < kEpochs; ++epoch) {
    int correct = 0;
    for (int b = 0; b < batches; ++b) {
      std::vector<float> xb(xs.begin() + b * kBatch * kFeatures,
                            xs.begin() + (b + 1) * kBatch * kFeatures);
      std::vector<float> yb(ys.begin() + b * kBatch,
                            ys.begin() + (b + 1) * kBatch);
      data_arr.CopyFrom(xb);
      label_arr.CopyFrom(yb);
      exec.Forward(true);
      exec.Backward();
      for (size_t p = 0; p < params.size(); ++p)
        sgd.Step(static_cast<int>(p), grads[p], &weights[p]);
      std::vector<float> probs = exec.Output(0).ToVector();
      for (int i = 0; i < kBatch; ++i) {
        const float* row = probs.data() + i * kClasses;
        int pred = static_cast<int>(
            std::max_element(row, row + kClasses) - row);
        correct += (pred == static_cast<int>(yb[i]));
      }
    }
    accuracy = static_cast<float>(correct) / (batches * kBatch);
    best = std::max(best, accuracy);
    std::printf("epoch %d train-accuracy %.4f\n", epoch, accuracy);
    if (best > 0.95f) break;  // converged; spare the CI budget
  }
  std::printf("final train-accuracy %.4f (best %.4f)\n", accuracy, best);
  return best > 0.9f ? 0 : 1;
}
