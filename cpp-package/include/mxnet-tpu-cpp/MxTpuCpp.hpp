// Header-only C++ API over the C predict ABI (libmxtpu.so).
//
// TPU-native counterpart of the reference's cpp-package
// (/root/reference/cpp-package/include/mxnet-cpp/: NDArray.hpp,
// predictor usage in example/image-classification/predict-cpp): thin
// RAII types over the same C ABI every binding consumes.  The training
// surface of the reference cpp-package maps to the Python/JAX runtime;
// this header covers the deployment path (load checkpoint, forward,
// read outputs) plus the param-blob reader.
//
//   #include "mxnet-tpu-cpp/MxTpuCpp.hpp"
//   mxtpu::cpp::Predictor pred(json, params, {{"data", {1, 12}}});
//   pred.SetInput("data", x);
//   pred.Forward();
//   std::vector<float> out = pred.GetOutput(0);
#ifndef MXNET_TPU_CPP_MXTPUCPP_HPP_
#define MXNET_TPU_CPP_MXTPUCPP_HPP_

#include <cstdint>
#include <fstream>
#include <map>
#include <sstream>
#include <stdexcept>
#include <string>
#include <utility>
#include <vector>

extern "C" {
int MXTPredCreate(const char*, const void*, int, int, int, uint32_t,
                  const char**, const uint32_t*, const uint32_t*, void**);
int MXTPredCreatePartialOut(const char*, const void*, int, int, int,
                            uint32_t, const char**, const uint32_t*,
                            const uint32_t*, uint32_t, const char**,
                            void**);
int MXTPredGetOutputShape(void*, uint32_t, const uint32_t**, uint32_t*);
int MXTPredSetInput(void*, const char*, const float*, uint32_t);
int MXTPredForward(void*);
int MXTPredPartialForward(void*, int, int*);
int MXTPredGetOutput(void*, uint32_t, float*, uint32_t);
int MXTPredReshape(void*, uint32_t, const char**, const uint32_t*,
                   const uint32_t*);
void MXTPredFree(void*);
int MXTNDListCreate(const char*, int, void**, uint32_t*);
int MXTNDListGet(void*, uint32_t, const char**, const float**,
                 const uint32_t**, uint32_t*);
void MXTNDListFree(void*);
const char* MXTPredGetLastError(void);
}

namespace mxtpu {
namespace cpp {

class Error : public std::runtime_error {
 public:
  explicit Error(const std::string& what) : std::runtime_error(what) {}
};

inline void Check(int rc, const char* what) {
  if (rc != 0)
    throw Error(std::string(what) + ": " + MXTPredGetLastError());
}

inline std::string ReadFile(const std::string& path) {
  std::ifstream f(path, std::ios::binary);
  if (!f) throw Error("cannot open " + path);
  std::ostringstream ss;
  ss << f.rdbuf();
  return ss.str();
}

using Shape = std::vector<uint32_t>;

enum DeviceType { kCPU = 1, kTPU = 2 };

// Forward-only model server over a Module.save_checkpoint artifact
// pair (reference MXPredCreate contract).
class Predictor {
 public:
  Predictor(const std::string& symbol_json, const std::string& param_blob,
            const std::map<std::string, Shape>& input_shapes,
            DeviceType dev = kCPU, int dev_id = 0,
            const std::vector<std::string>& output_keys = {}) {
    std::vector<const char*> keys;
    std::vector<uint32_t> indptr{0}, dims;
    for (const auto& kv : input_shapes) {
      keys.push_back(kv.first.c_str());
      dims.insert(dims.end(), kv.second.begin(), kv.second.end());
      indptr.push_back(static_cast<uint32_t>(dims.size()));
    }
    if (output_keys.empty()) {
      Check(MXTPredCreate(symbol_json.c_str(), param_blob.data(),
                          static_cast<int>(param_blob.size()), dev,
                          dev_id, static_cast<uint32_t>(keys.size()),
                          keys.data(), indptr.data(), dims.data(),
                          &handle_),
            "MXTPredCreate");
    } else {
      std::vector<const char*> outs;
      for (const auto& k : output_keys) outs.push_back(k.c_str());
      Check(MXTPredCreatePartialOut(
                symbol_json.c_str(), param_blob.data(),
                static_cast<int>(param_blob.size()), dev, dev_id,
                static_cast<uint32_t>(keys.size()), keys.data(),
                indptr.data(), dims.data(),
                static_cast<uint32_t>(outs.size()), outs.data(),
                &handle_),
            "MXTPredCreatePartialOut");
    }
  }

  // Load prefix-symbol.json + prefix-%04d.params from disk.
  static Predictor FromCheckpoint(
      const std::string& prefix, int epoch,
      const std::map<std::string, Shape>& input_shapes,
      DeviceType dev = kCPU, int dev_id = 0) {
    char buf[32];
    std::snprintf(buf, sizeof(buf), "-%04d.params", epoch);
    return Predictor(ReadFile(prefix + "-symbol.json"),
                     ReadFile(prefix + buf), input_shapes, dev, dev_id);
  }

  Predictor(Predictor&& o) noexcept : handle_(o.handle_) {
    o.handle_ = nullptr;
  }
  Predictor& operator=(Predictor&& o) noexcept {
    std::swap(handle_, o.handle_);
    return *this;
  }
  Predictor(const Predictor&) = delete;
  Predictor& operator=(const Predictor&) = delete;
  ~Predictor() {
    if (handle_ != nullptr) MXTPredFree(handle_);
  }

  void SetInput(const std::string& key, const std::vector<float>& data) {
    Check(MXTPredSetInput(handle_, key.c_str(), data.data(),
                          static_cast<uint32_t>(data.size())),
          "MXTPredSetInput");
  }

  void Forward() { Check(MXTPredForward(handle_), "MXTPredForward"); }

  // Run the first `step` op nodes; returns how many remain.
  int PartialForward(int step) {
    int left = 0;
    Check(MXTPredPartialForward(handle_, step, &left),
          "MXTPredPartialForward");
    return left;
  }

  Shape GetOutputShape(uint32_t index = 0) const {
    const uint32_t* data = nullptr;
    uint32_t ndim = 0;
    Check(MXTPredGetOutputShape(handle_, index, &data, &ndim),
          "MXTPredGetOutputShape");
    return Shape(data, data + ndim);
  }

  std::vector<float> GetOutput(uint32_t index = 0) const {
    Shape s = GetOutputShape(index);
    uint32_t n = 1;
    for (uint32_t d : s) n *= d;
    std::vector<float> out(n);
    Check(MXTPredGetOutput(handle_, index, out.data(), n),
          "MXTPredGetOutput");
    return out;
  }

  void Reshape(const std::map<std::string, Shape>& input_shapes) {
    std::vector<const char*> keys;
    std::vector<uint32_t> indptr{0}, dims;
    for (const auto& kv : input_shapes) {
      keys.push_back(kv.first.c_str());
      dims.insert(dims.end(), kv.second.begin(), kv.second.end());
      indptr.push_back(static_cast<uint32_t>(dims.size()));
    }
    Check(MXTPredReshape(handle_, static_cast<uint32_t>(keys.size()),
                         keys.data(), indptr.data(), dims.data()),
          "MXTPredReshape");
  }

 private:
  void* handle_ = nullptr;
};

// Named float32 array view into a loaded .params blob (reference
// MXNDListCreate consumers: mean images, standalone weight readers).
struct NDArrayView {
  std::string name;
  Shape shape;
  const float* data;  // owned by the NDList
  size_t size;
};

class NDList {
 public:
  explicit NDList(const std::string& blob) {
    uint32_t n = 0;
    Check(MXTNDListCreate(blob.data(), static_cast<int>(blob.size()),
                          &handle_, &n),
          "MXTNDListCreate");
    for (uint32_t i = 0; i < n; ++i) {
      const char* key = nullptr;
      const float* data = nullptr;
      const uint32_t* shp = nullptr;
      uint32_t ndim = 0;
      Check(MXTNDListGet(handle_, i, &key, &data, &shp, &ndim),
            "MXTNDListGet");
      NDArrayView v;
      v.name = key;
      v.shape.assign(shp, shp + ndim);
      v.data = data;
      v.size = 1;
      for (uint32_t d : v.shape) v.size *= d;
      items_.push_back(std::move(v));
    }
  }
  NDList(const NDList&) = delete;
  NDList& operator=(const NDList&) = delete;
  ~NDList() {
    if (handle_ != nullptr) MXTNDListFree(handle_);
  }

  size_t size() const { return items_.size(); }
  const NDArrayView& operator[](size_t i) const { return items_[i]; }
  std::vector<NDArrayView>::const_iterator begin() const {
    return items_.begin();
  }
  std::vector<NDArrayView>::const_iterator end() const {
    return items_.end();
  }

 private:
  void* handle_ = nullptr;
  std::vector<NDArrayView> items_;
};

}  // namespace cpp
}  // namespace mxtpu

#endif  // MXNET_TPU_CPP_MXTPUCPP_HPP_
