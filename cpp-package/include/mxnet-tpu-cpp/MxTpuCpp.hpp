// Header-only C++ API over the C predict ABI (libmxtpu.so).
//
// TPU-native counterpart of the reference's cpp-package
// (/root/reference/cpp-package/include/mxnet-cpp/: NDArray.hpp,
// predictor usage in example/image-classification/predict-cpp): thin
// RAII types over the same C ABI every binding consumes.  The training
// surface of the reference cpp-package maps to the Python/JAX runtime;
// this header covers the deployment path (load checkpoint, forward,
// read outputs) plus the param-blob reader.
//
//   #include "mxnet-tpu-cpp/MxTpuCpp.hpp"
//   mxtpu::cpp::Predictor pred(json, params, {{"data", {1, 12}}});
//   pred.SetInput("data", x);
//   pred.Forward();
//   std::vector<float> out = pred.GetOutput(0);
#ifndef MXNET_TPU_CPP_MXTPUCPP_HPP_
#define MXNET_TPU_CPP_MXTPUCPP_HPP_

#include <cstdint>
#include <fstream>
#include <map>
#include <sstream>
#include <stdexcept>
#include <string>
#include <utility>
#include <vector>

extern "C" {
int MXTPredCreate(const char*, const void*, int, int, int, uint32_t,
                  const char**, const uint32_t*, const uint32_t*, void**);
int MXTPredCreatePartialOut(const char*, const void*, int, int, int,
                            uint32_t, const char**, const uint32_t*,
                            const uint32_t*, uint32_t, const char**,
                            void**);
int MXTPredGetOutputShape(void*, uint32_t, const uint32_t**, uint32_t*);
int MXTPredSetInput(void*, const char*, const float*, uint32_t);
int MXTPredForward(void*);
int MXTPredPartialForward(void*, int, int*);
int MXTPredGetOutput(void*, uint32_t, float*, uint32_t);
int MXTPredReshape(void*, uint32_t, const char**, const uint32_t*,
                   const uint32_t*);
void MXTPredFree(void*);
int MXTNDListCreate(const char*, int, void**, uint32_t*);
int MXTNDListGet(void*, uint32_t, const char**, const float**,
                 const uint32_t**, uint32_t*);
void MXTNDListFree(void*);
const char* MXTPredGetLastError(void);

// training ABI (src/c_api_train.cc)
//
// Threading contract (all MXT* entry points): calls may come from any
// thread — each entry point acquires the embedded interpreter's GIL, so
// the runtime itself is safe — but a HANDLE is single-caller: pointers
// returned for a handle (shapes, strings, lists) stay valid only until
// the next call on the SAME handle, and handles are mutated without a
// lock, so two threads must not operate on one handle concurrently.
// Distinct handles can be used from distinct threads freely.  This is
// the reference's MXAPIThreadLocalEntry discipline restated per-handle.
const char* MXTTrainGetLastError(void);
int MXTNDArrayCreate(const uint32_t*, uint32_t, int, int, void**);
int MXTNDArrayCreateFromBytes(const uint32_t*, uint32_t, const float*,
                              int, int, void**);
int MXTNDArraySyncCopyFromCPU(void*, const float*, size_t);
int MXTNDArraySyncCopyToCPU(void*, float*, size_t);
int MXTNDArrayGetShape(void*, uint32_t*, const uint32_t**);
void MXTNDArrayFree(void*);
int MXTNDArraySave(const char*, uint32_t, void**, const char**);
int MXTNDArrayLoad(const char*, void**, uint32_t*);
int MXTNDArrayLoadGet(void*, uint32_t, const char**, void**);
int MXTNDArraySlice(void*, uint32_t, uint32_t, void**);
int MXTNDArrayReshape(void*, uint32_t, const uint32_t*, void**);
int MXTSymbolCreateVariable(const char*, void**);
int MXTSymbolCreate(const char*, const char*, uint32_t, const char**,
                    const char**, uint32_t, const char**, void**, void**);
int MXTSymbolCreateFromJSON(const char*, void**);
int MXTSymbolSaveToJSON(void*, const char**);
int MXTSymbolListArguments(void*, uint32_t*, const char***);
int MXTSymbolListOutputs(void*, uint32_t*, const char***);
int MXTSymbolListAuxiliaryStates(void*, uint32_t*, const char***);
int MXTSymbolInferShape(void*, uint32_t, const char**, const uint32_t*,
                        const uint32_t*, uint32_t*, const uint32_t**,
                        const uint32_t**, uint32_t*, const uint32_t**,
                        const uint32_t**, uint32_t*, const uint32_t**,
                        const uint32_t**);
int MXTSymbolGetInternals(void*, void**);
int MXTSymbolGetOutput(void*, uint32_t, void**);
int MXTSymbolGetInternalByName(void*, const char*, void**);
int MXTSymbolGetAttr(void*, const char*, const char**, int*);
int MXTSymbolSetAttr(void*, const char*, const char*);
void MXTSymbolFree(void*);
int MXTExecutorSimpleBind(void*, int, int, const char*, uint32_t,
                          const char**, const uint32_t*, const uint32_t*,
                          void**);
int MXTExecutorForward(void*, int);
int MXTExecutorBackward(void*);
int MXTExecutorNumOutputs(void*, uint32_t*);
int MXTExecutorOutput(void*, uint32_t, void**);
int MXTExecutorArgArray(void*, const char*, void**);
int MXTExecutorGradArray(void*, const char*, void**);
void MXTExecutorFree(void*);
int MXTUpdaterCreate(const char*, uint32_t, const char**, const char**,
                     void**);
int MXTUpdaterStep(void*, int, void*, void*);
void MXTUpdaterFree(void*);
int MXTKVStoreCreate(const char*, void**);
int MXTKVStoreInit(void*, const char*, void*);
int MXTKVStorePush(void*, const char*, void*);
int MXTKVStorePull(void*, const char*, void*);
void MXTKVStoreFree(void*);
int MXTImperativeInvoke(const char*, uint32_t, void**, uint32_t,
                        const char**, const char**, uint32_t*, void**,
                        uint32_t);
int MXTAutogradSetIsRecording(int, int*);
int MXTAutogradSetIsTraining(int, int*);
int MXTAutogradMarkVariables(uint32_t, void**, const char**);
int MXTAutogradBackward(uint32_t, void**, int);
int MXTNDArrayGetGrad(void*, void**);
int MXTCachedOpCreate(void*, void**);
int MXTCachedOpInvoke(void*, uint32_t, void**, uint32_t*, void**,
                      uint32_t);
void MXTCachedOpFree(void*);
int MXTListDataIters(uint32_t*, const char***);
int MXTRandomSeed(int);
int MXTNDArrayWaitAll(void);
int MXTListOpNames(uint32_t*, const char***);
int MXTOpGetInfo(const char*, const char**, const char**, uint32_t*,
                 const char***);
int MXTDataIterCreate(const char*, uint32_t, const char**, const char**,
                      void**);
int MXTDataIterBeforeFirst(void*);
int MXTDataIterNext(void*, int*);
int MXTDataIterGetData(void*, void**);
int MXTDataIterGetLabel(void*, void**);
int MXTDataIterGetPadNum(void*, int*);
void MXTDataIterFree(void*);
int MXTNDArrayCopyFromNDArray(void*, void*);
}

namespace mxtpu {
namespace cpp {

class Error : public std::runtime_error {
 public:
  explicit Error(const std::string& what) : std::runtime_error(what) {}
};

inline void Check(int rc, const char* what) {
  if (rc != 0)
    throw Error(std::string(what) + ": " + MXTPredGetLastError());
}

inline std::string ReadFile(const std::string& path) {
  std::ifstream f(path, std::ios::binary);
  if (!f) throw Error("cannot open " + path);
  std::ostringstream ss;
  ss << f.rdbuf();
  return ss.str();
}

using Shape = std::vector<uint32_t>;

enum DeviceType { kCPU = 1, kTPU = 2 };

// Forward-only model server over a Module.save_checkpoint artifact
// pair (reference MXPredCreate contract).
class Predictor {
 public:
  Predictor(const std::string& symbol_json, const std::string& param_blob,
            const std::map<std::string, Shape>& input_shapes,
            DeviceType dev = kCPU, int dev_id = 0,
            const std::vector<std::string>& output_keys = {}) {
    std::vector<const char*> keys;
    std::vector<uint32_t> indptr{0}, dims;
    for (const auto& kv : input_shapes) {
      keys.push_back(kv.first.c_str());
      dims.insert(dims.end(), kv.second.begin(), kv.second.end());
      indptr.push_back(static_cast<uint32_t>(dims.size()));
    }
    if (output_keys.empty()) {
      Check(MXTPredCreate(symbol_json.c_str(), param_blob.data(),
                          static_cast<int>(param_blob.size()), dev,
                          dev_id, static_cast<uint32_t>(keys.size()),
                          keys.data(), indptr.data(), dims.data(),
                          &handle_),
            "MXTPredCreate");
    } else {
      std::vector<const char*> outs;
      for (const auto& k : output_keys) outs.push_back(k.c_str());
      Check(MXTPredCreatePartialOut(
                symbol_json.c_str(), param_blob.data(),
                static_cast<int>(param_blob.size()), dev, dev_id,
                static_cast<uint32_t>(keys.size()), keys.data(),
                indptr.data(), dims.data(),
                static_cast<uint32_t>(outs.size()), outs.data(),
                &handle_),
            "MXTPredCreatePartialOut");
    }
  }

  // Load prefix-symbol.json + prefix-%04d.params from disk.
  static Predictor FromCheckpoint(
      const std::string& prefix, int epoch,
      const std::map<std::string, Shape>& input_shapes,
      DeviceType dev = kCPU, int dev_id = 0) {
    char buf[32];
    std::snprintf(buf, sizeof(buf), "-%04d.params", epoch);
    return Predictor(ReadFile(prefix + "-symbol.json"),
                     ReadFile(prefix + buf), input_shapes, dev, dev_id);
  }

  Predictor(Predictor&& o) noexcept : handle_(o.handle_) {
    o.handle_ = nullptr;
  }
  Predictor& operator=(Predictor&& o) noexcept {
    std::swap(handle_, o.handle_);
    return *this;
  }
  Predictor(const Predictor&) = delete;
  Predictor& operator=(const Predictor&) = delete;
  ~Predictor() {
    if (handle_ != nullptr) MXTPredFree(handle_);
  }

  void SetInput(const std::string& key, const std::vector<float>& data) {
    Check(MXTPredSetInput(handle_, key.c_str(), data.data(),
                          static_cast<uint32_t>(data.size())),
          "MXTPredSetInput");
  }

  void Forward() { Check(MXTPredForward(handle_), "MXTPredForward"); }

  // Run the first `step` op nodes; returns how many remain.
  int PartialForward(int step) {
    int left = 0;
    Check(MXTPredPartialForward(handle_, step, &left),
          "MXTPredPartialForward");
    return left;
  }

  Shape GetOutputShape(uint32_t index = 0) const {
    const uint32_t* data = nullptr;
    uint32_t ndim = 0;
    Check(MXTPredGetOutputShape(handle_, index, &data, &ndim),
          "MXTPredGetOutputShape");
    return Shape(data, data + ndim);
  }

  std::vector<float> GetOutput(uint32_t index = 0) const {
    Shape s = GetOutputShape(index);
    uint32_t n = 1;
    for (uint32_t d : s) n *= d;
    std::vector<float> out(n);
    Check(MXTPredGetOutput(handle_, index, out.data(), n),
          "MXTPredGetOutput");
    return out;
  }

  void Reshape(const std::map<std::string, Shape>& input_shapes) {
    std::vector<const char*> keys;
    std::vector<uint32_t> indptr{0}, dims;
    for (const auto& kv : input_shapes) {
      keys.push_back(kv.first.c_str());
      dims.insert(dims.end(), kv.second.begin(), kv.second.end());
      indptr.push_back(static_cast<uint32_t>(dims.size()));
    }
    Check(MXTPredReshape(handle_, static_cast<uint32_t>(keys.size()),
                         keys.data(), indptr.data(), dims.data()),
          "MXTPredReshape");
  }

 private:
  void* handle_ = nullptr;
};

// Named float32 array view into a loaded .params blob (reference
// MXNDListCreate consumers: mean images, standalone weight readers).
struct NDArrayView {
  std::string name;
  Shape shape;
  const float* data;  // owned by the NDList
  size_t size;
};

class NDList {
 public:
  explicit NDList(const std::string& blob) {
    uint32_t n = 0;
    Check(MXTNDListCreate(blob.data(), static_cast<int>(blob.size()),
                          &handle_, &n),
          "MXTNDListCreate");
    for (uint32_t i = 0; i < n; ++i) {
      const char* key = nullptr;
      const float* data = nullptr;
      const uint32_t* shp = nullptr;
      uint32_t ndim = 0;
      Check(MXTNDListGet(handle_, i, &key, &data, &shp, &ndim),
            "MXTNDListGet");
      NDArrayView v;
      v.name = key;
      v.shape.assign(shp, shp + ndim);
      v.data = data;
      v.size = 1;
      for (uint32_t d : v.shape) v.size *= d;
      items_.push_back(std::move(v));
    }
  }
  NDList(const NDList&) = delete;
  NDList& operator=(const NDList&) = delete;
  ~NDList() {
    if (handle_ != nullptr) MXTNDListFree(handle_);
  }

  size_t size() const { return items_.size(); }
  const NDArrayView& operator[](size_t i) const { return items_[i]; }
  std::vector<NDArrayView>::const_iterator begin() const {
    return items_.begin();
  }
  std::vector<NDArrayView>::const_iterator end() const {
    return items_.end();
  }

 private:
  void* handle_ = nullptr;
  std::vector<NDArrayView> items_;
};

// ---------------------------------------------------------------------------
// Training surface (reference cpp-package trains an MLP end-to-end from
// C++, /root/reference/cpp-package/example/mlp.cpp; these RAII types sit
// on the training C ABI in src/c_api_train.cc).
// ---------------------------------------------------------------------------

inline void CheckT(int rc, const char* what) {
  if (rc != 0)
    throw Error(std::string(what) + ": " + MXTTrainGetLastError());
}

class NDArray {
 public:
  NDArray() = default;
  NDArray(const Shape& shape, DeviceType dev = kCPU, int dev_id = 0) {
    CheckT(MXTNDArrayCreate(shape.data(),
                            static_cast<uint32_t>(shape.size()), dev,
                            dev_id, &handle_),
           "MXTNDArrayCreate");
  }
  NDArray(const Shape& shape, const std::vector<float>& data,
          DeviceType dev = kCPU, int dev_id = 0) {
    CheckT(MXTNDArrayCreateFromBytes(
               shape.data(), static_cast<uint32_t>(shape.size()),
               data.data(), dev, dev_id, &handle_),
           "MXTNDArrayCreateFromBytes");
  }
  static NDArray FromHandle(void* h) {
    NDArray a;
    a.handle_ = h;
    return a;
  }
  NDArray(NDArray&& o) noexcept : handle_(o.handle_) {
    o.handle_ = nullptr;
  }
  NDArray& operator=(NDArray&& o) noexcept {
    std::swap(handle_, o.handle_);
    return *this;
  }
  NDArray(const NDArray&) = delete;
  NDArray& operator=(const NDArray&) = delete;
  ~NDArray() {
    if (handle_ != nullptr) MXTNDArrayFree(handle_);
  }

  void CopyFrom(const std::vector<float>& data) {
    CheckT(MXTNDArraySyncCopyFromCPU(handle_, data.data(), data.size()),
           "MXTNDArraySyncCopyFromCPU");
  }
  // Device-side refill from another NDArray (no host round-trip).
  void CopyFrom(const NDArray& src) {
    CheckT(MXTNDArrayCopyFromNDArray(handle_, src.handle()),
           "MXTNDArrayCopyFromNDArray");
  }
  std::vector<float> ToVector() const {
    Shape s = GetShape();
    size_t n = 1;
    for (uint32_t d : s) n *= d;
    std::vector<float> out(n);
    CheckT(MXTNDArraySyncCopyToCPU(handle_, out.data(), n),
           "MXTNDArraySyncCopyToCPU");
    return out;
  }
  Shape GetShape() const {
    uint32_t ndim = 0;
    const uint32_t* dims = nullptr;
    CheckT(MXTNDArrayGetShape(handle_, &ndim, &dims),
           "MXTNDArrayGetShape");
    return Shape(dims, dims + ndim);
  }
  // Row-range COPY of [begin, end).  Unlike the reference's slice
  // views, writes to the result do not propagate to the parent
  // (functional arrays underneath); refill the parent via CopyFrom.
  NDArray Slice(uint32_t begin, uint32_t end) const {
    void* h = nullptr;
    CheckT(MXTNDArraySlice(handle_, begin, end, &h), "MXTNDArraySlice");
    return FromHandle(h);
  }
  NDArray Reshape(const Shape& shape) const {
    void* h = nullptr;
    CheckT(MXTNDArrayReshape(handle_, static_cast<uint32_t>(shape.size()),
                             shape.data(), &h),
           "MXTNDArrayReshape");
    return FromHandle(h);
  }
  // Save named arrays in the .params container format.
  static void Save(const std::string& fname,
                   const std::vector<std::pair<std::string,
                                               const NDArray*>>& items) {
    std::vector<const char*> keys;
    std::vector<void*> handles;
    for (const auto& kv : items) {
      keys.push_back(kv.first.c_str());
      handles.push_back(kv.second->handle());
    }
    CheckT(MXTNDArraySave(fname.c_str(),
                          static_cast<uint32_t>(handles.size()),
                          handles.data(), keys.data()),
           "MXTNDArraySave");
  }
  static std::vector<std::pair<std::string, NDArray>> Load(
      const std::string& fname) {
    void* list = nullptr;
    uint32_t n = 0;
    CheckT(MXTNDArrayLoad(fname.c_str(), &list, &n), "MXTNDArrayLoad");
    std::vector<std::pair<std::string, NDArray>> out;
    for (uint32_t i = 0; i < n; ++i) {
      const char* key = nullptr;
      void* nd = nullptr;
      int rc = MXTNDArrayLoadGet(list, i, &key, &nd);
      if (rc != 0) {
        MXTNDArrayFree(list);
        CheckT(rc, "MXTNDArrayLoadGet");
      }
      out.emplace_back(key, FromHandle(nd));
    }
    MXTNDArrayFree(list);
    return out;
  }
  void* handle() const { return handle_; }

 private:
  void* handle_ = nullptr;
};

class Symbol {
 public:
  Symbol() = default;
  static Symbol Variable(const std::string& name) {
    Symbol s;
    CheckT(MXTSymbolCreateVariable(name.c_str(), &s.handle_),
           "MXTSymbolCreateVariable");
    return s;
  }
  // Operator application: attrs as strings, inputs as named symbols.
  static Symbol Create(
      const std::string& op, const std::string& name,
      const std::vector<std::pair<std::string, std::string>>& attrs,
      const std::vector<std::pair<std::string, const Symbol*>>& args) {
    std::vector<const char*> ak, av, an;
    std::vector<void*> ah;
    for (const auto& kv : attrs) {
      ak.push_back(kv.first.c_str());
      av.push_back(kv.second.c_str());
    }
    for (const auto& kv : args) {
      an.push_back(kv.first.c_str());
      ah.push_back(kv.second->handle_);
    }
    Symbol s;
    CheckT(MXTSymbolCreate(op.c_str(), name.c_str(),
                           static_cast<uint32_t>(ak.size()), ak.data(),
                           av.data(), static_cast<uint32_t>(an.size()),
                           an.data(), ah.data(), &s.handle_),
           "MXTSymbolCreate");
    return s;
  }
  static Symbol FromJSON(const std::string& json) {
    Symbol s;
    CheckT(MXTSymbolCreateFromJSON(json.c_str(), &s.handle_),
           "MXTSymbolCreateFromJSON");
    return s;
  }
  std::string ToJSON() const {
    const char* out = nullptr;
    CheckT(MXTSymbolSaveToJSON(handle_, &out), "MXTSymbolSaveToJSON");
    return out;
  }
  std::vector<std::string> ListArguments() const {
    return NameList(&MXTSymbolListArguments);
  }
  std::vector<std::string> ListOutputs() const {
    return NameList(&MXTSymbolListOutputs);
  }
  std::vector<std::string> ListAuxiliaryStates() const {
    return NameList(&MXTSymbolListAuxiliaryStates);
  }

  // Graph surgery: every internal node's outputs as one grouped symbol,
  // or a single tap by index / internal name.
  Symbol GetInternals() const {
    Symbol s;
    CheckT(MXTSymbolGetInternals(handle_, &s.handle_),
           "MXTSymbolGetInternals");
    return s;
  }
  Symbol GetOutput(uint32_t index) const {
    Symbol s;
    CheckT(MXTSymbolGetOutput(handle_, index, &s.handle_),
           "MXTSymbolGetOutput");
    return s;
  }
  Symbol GetInternalByName(const std::string& name) const {
    Symbol s;
    CheckT(MXTSymbolGetInternalByName(handle_, name.c_str(), &s.handle_),
           "MXTSymbolGetInternalByName");
    return s;
  }
  // Presence-aware lookup: returns false for unset keys (an attribute
  // explicitly set to "" returns true with *value empty).
  bool TryGetAttr(const std::string& key, std::string* value) const {
    const char* out = nullptr;
    int present = 0;
    CheckT(MXTSymbolGetAttr(handle_, key.c_str(), &out, &present),
           "MXTSymbolGetAttr");
    if (value != nullptr) *value = out;
    return present != 0;
  }
  // Convenience: '' for unset keys.
  std::string GetAttr(const std::string& key) const {
    std::string value;
    TryGetAttr(key, &value);
    return value;
  }
  void SetAttr(const std::string& key, const std::string& value) {
    CheckT(MXTSymbolSetAttr(handle_, key.c_str(), value.c_str()),
           "MXTSymbolSetAttr");
  }

  // Bidirectional shape inference: given shapes for some arguments,
  // returns the complete (args, outputs, auxes) shape lists.
  void InferShape(const std::map<std::string, Shape>& known,
                  std::vector<Shape>* arg_shapes,
                  std::vector<Shape>* out_shapes,
                  std::vector<Shape>* aux_shapes) const {
    std::vector<const char*> keys;
    std::vector<uint32_t> indptr{0}, dims;
    for (const auto& kv : known) {
      keys.push_back(kv.first.c_str());
      dims.insert(dims.end(), kv.second.begin(), kv.second.end());
      indptr.push_back(static_cast<uint32_t>(dims.size()));
    }
    uint32_t counts[3] = {0, 0, 0};
    const uint32_t* iptr[3] = {nullptr, nullptr, nullptr};
    const uint32_t* data[3] = {nullptr, nullptr, nullptr};
    CheckT(MXTSymbolInferShape(handle_,
                               static_cast<uint32_t>(keys.size()),
                               keys.data(), indptr.data(), dims.data(),
                               &counts[0], &iptr[0], &data[0],
                               &counts[1], &iptr[1], &data[1],
                               &counts[2], &iptr[2], &data[2]),
           "MXTSymbolInferShape");
    std::vector<Shape>* outs[3] = {arg_shapes, out_shapes, aux_shapes};
    for (int g = 0; g < 3; ++g) {
      if (outs[g] == nullptr) continue;
      outs[g]->clear();
      for (uint32_t i = 0; i < counts[g]; ++i)
        outs[g]->emplace_back(data[g] + iptr[g][i],
                              data[g] + iptr[g][i + 1]);
    }
  }

  Symbol(Symbol&& o) noexcept : handle_(o.handle_) { o.handle_ = nullptr; }
  Symbol& operator=(Symbol&& o) noexcept {
    std::swap(handle_, o.handle_);
    return *this;
  }
  Symbol(const Symbol&) = delete;
  Symbol& operator=(const Symbol&) = delete;
  ~Symbol() {
    if (handle_ != nullptr) MXTSymbolFree(handle_);
  }
  void* handle() const { return handle_; }

 private:
  std::vector<std::string> NameList(
      int (*fn)(void*, uint32_t*, const char***)) const {
    uint32_t n = 0;
    const char** items = nullptr;
    CheckT(fn(handle_, &n, &items), "MXTSymbolList*");
    std::vector<std::string> out;
    for (uint32_t i = 0; i < n; ++i) out.emplace_back(items[i]);
    return out;
  }
  void* handle_ = nullptr;
};

class Executor {
 public:
  Executor(const Symbol& sym, DeviceType dev, int dev_id,
           const std::string& grad_req,
           const std::map<std::string, Shape>& shapes) {
    std::vector<const char*> keys;
    std::vector<uint32_t> indptr{0}, dims;
    for (const auto& kv : shapes) {
      keys.push_back(kv.first.c_str());
      dims.insert(dims.end(), kv.second.begin(), kv.second.end());
      indptr.push_back(static_cast<uint32_t>(dims.size()));
    }
    CheckT(MXTExecutorSimpleBind(sym.handle(), dev, dev_id,
                                 grad_req.c_str(),
                                 static_cast<uint32_t>(keys.size()),
                                 keys.data(), indptr.data(), dims.data(),
                                 &handle_),
           "MXTExecutorSimpleBind");
  }
  Executor(Executor&& o) noexcept : handle_(o.handle_) {
    o.handle_ = nullptr;
  }
  Executor& operator=(Executor&& o) noexcept {
    std::swap(handle_, o.handle_);
    return *this;
  }
  Executor(const Executor&) = delete;
  Executor& operator=(const Executor&) = delete;
  ~Executor() {
    if (handle_ != nullptr) MXTExecutorFree(handle_);
  }

  void Forward(bool is_train) {
    CheckT(MXTExecutorForward(handle_, is_train ? 1 : 0),
           "MXTExecutorForward");
  }
  void Backward() {
    CheckT(MXTExecutorBackward(handle_), "MXTExecutorBackward");
  }
  uint32_t NumOutputs() const {
    uint32_t n = 0;
    CheckT(MXTExecutorNumOutputs(handle_, &n), "MXTExecutorNumOutputs");
    return n;
  }
  NDArray Output(uint32_t index) const {
    void* h = nullptr;
    CheckT(MXTExecutorOutput(handle_, index, &h), "MXTExecutorOutput");
    return NDArray::FromHandle(h);
  }
  NDArray Arg(const std::string& name) const {
    void* h = nullptr;
    CheckT(MXTExecutorArgArray(handle_, name.c_str(), &h),
           "MXTExecutorArgArray");
    return NDArray::FromHandle(h);
  }
  NDArray Grad(const std::string& name) const {
    void* h = nullptr;
    CheckT(MXTExecutorGradArray(handle_, name.c_str(), &h),
           "MXTExecutorGradArray");
    return NDArray::FromHandle(h);
  }

 private:
  void* handle_ = nullptr;
};

// Optimizer updater (same index -> same state slot, the reference's
// kvstore-updater contract).
class Updater {
 public:
  Updater(const std::string& opt,
          const std::vector<std::pair<std::string, std::string>>& attrs) {
    std::vector<const char*> ak, av;
    for (const auto& kv : attrs) {
      ak.push_back(kv.first.c_str());
      av.push_back(kv.second.c_str());
    }
    CheckT(MXTUpdaterCreate(opt.c_str(),
                            static_cast<uint32_t>(ak.size()), ak.data(),
                            av.data(), &handle_),
           "MXTUpdaterCreate");
  }
  Updater(Updater&& o) noexcept : handle_(o.handle_) { o.handle_ = nullptr; }
  Updater& operator=(Updater&& o) noexcept {
    std::swap(handle_, o.handle_);
    return *this;
  }
  Updater(const Updater&) = delete;
  Updater& operator=(const Updater&) = delete;
  ~Updater() {
    if (handle_ != nullptr) MXTUpdaterFree(handle_);
  }
  void Step(int index, const NDArray& grad, NDArray* weight) {
    CheckT(MXTUpdaterStep(handle_, index, grad.handle(),
                          weight->handle()),
           "MXTUpdaterStep");
  }

 private:
  void* handle_ = nullptr;
};

class KVStore {
 public:
  explicit KVStore(const std::string& kind = "local") {
    CheckT(MXTKVStoreCreate(kind.c_str(), &handle_), "MXTKVStoreCreate");
  }
  KVStore(KVStore&& o) noexcept : handle_(o.handle_) { o.handle_ = nullptr; }
  KVStore& operator=(KVStore&& o) noexcept {
    std::swap(handle_, o.handle_);
    return *this;
  }
  KVStore(const KVStore&) = delete;
  KVStore& operator=(const KVStore&) = delete;
  ~KVStore() {
    if (handle_ != nullptr) MXTKVStoreFree(handle_);
  }
  void Init(const std::string& key, const NDArray& value) {
    CheckT(MXTKVStoreInit(handle_, key.c_str(), value.handle()),
           "MXTKVStoreInit");
  }
  void Push(const std::string& key, const NDArray& value) {
    CheckT(MXTKVStorePush(handle_, key.c_str(), value.handle()),
           "MXTKVStorePush");
  }
  void Pull(const std::string& key, NDArray* out) {
    CheckT(MXTKVStorePull(handle_, key.c_str(), out->handle()),
           "MXTKVStorePull");
  }

 private:
  void* handle_ = nullptr;
};

// Data iterator over the framework's IO pipeline (reference
// MXDataIterCreateIter family; trains from .rec/.csv files without
// Python in the caller).  Params are the same strings the Python
// constructors take, e.g. {{"path_imgrec", "train.rec"},
// {"data_shape", "(3,28,28)"}, {"batch_size", "16"}}.
class DataIter {
 public:
  DataIter(const std::string& name,
           const std::vector<std::pair<std::string, std::string>>& params) {
    std::vector<const char*> pk, pv;
    for (const auto& kv : params) {
      pk.push_back(kv.first.c_str());
      pv.push_back(kv.second.c_str());
    }
    CheckT(MXTDataIterCreate(name.c_str(),
                             static_cast<uint32_t>(pk.size()), pk.data(),
                             pv.data(), &handle_),
           "MXTDataIterCreate");
  }
  DataIter(DataIter&& o) noexcept : handle_(o.handle_) {
    o.handle_ = nullptr;
  }
  DataIter& operator=(DataIter&& o) noexcept {
    std::swap(handle_, o.handle_);
    return *this;
  }
  DataIter(const DataIter&) = delete;
  DataIter& operator=(const DataIter&) = delete;
  ~DataIter() {
    if (handle_ != nullptr) MXTDataIterFree(handle_);
  }
  static std::vector<std::string> List() {
    uint32_t n = 0;
    const char** names = nullptr;
    CheckT(MXTListDataIters(&n, &names), "MXTListDataIters");
    return std::vector<std::string>(names, names + n);
  }
  void BeforeFirst() {
    CheckT(MXTDataIterBeforeFirst(handle_), "MXTDataIterBeforeFirst");
  }
  bool Next() {
    int has = 0;
    CheckT(MXTDataIterNext(handle_, &has), "MXTDataIterNext");
    return has != 0;
  }
  NDArray GetData() const {
    void* h = nullptr;
    CheckT(MXTDataIterGetData(handle_, &h), "MXTDataIterGetData");
    return NDArray::FromHandle(h);
  }
  NDArray GetLabel() const {
    void* h = nullptr;
    CheckT(MXTDataIterGetLabel(handle_, &h), "MXTDataIterGetLabel");
    return NDArray::FromHandle(h);
  }
  int GetPadNum() const {
    int pad = 0;
    CheckT(MXTDataIterGetPadNum(handle_, &pad), "MXTDataIterGetPadNum");
    return pad;
  }

 private:
  void* handle_ = nullptr;
};

}  // namespace cpp
}  // namespace mxtpu

#endif  // MXNET_TPU_CPP_MXTPUCPP_HPP_
