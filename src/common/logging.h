// Minimal logging/CHECK facility.
// TPU-native rebuild of the dmlc-core logging surface the reference uses
// everywhere (reference /root/reference usage: dmlc/logging.h CHECK/LOG,
// SURVEY.md §2.9 dmlc-core row).
#ifndef MXTPU_COMMON_LOGGING_H_
#define MXTPU_COMMON_LOGGING_H_

#include <cstdlib>
#include <iostream>
#include <sstream>
#include <stdexcept>
#include <string>

namespace mxtpu {

struct Error : public std::runtime_error {
  explicit Error(const std::string& msg) : std::runtime_error(msg) {}
};

class LogMessage {
 public:
  LogMessage(const char* file, int line, bool fatal)
      : fatal_(fatal) {
    stream_ << "[" << file << ":" << line << "] ";
  }
  std::ostringstream& stream() { return stream_; }
  ~LogMessage() noexcept(false) {
    if (fatal_) {
      throw Error(stream_.str());
    } else {
      std::cerr << stream_.str() << std::endl;
    }
  }

 private:
  std::ostringstream stream_;
  bool fatal_;
};

}  // namespace mxtpu

#define MXTPU_LOG_INFO ::mxtpu::LogMessage(__FILE__, __LINE__, false).stream()
#define MXTPU_LOG_FATAL ::mxtpu::LogMessage(__FILE__, __LINE__, true).stream()

#define MXTPU_CHECK(x)                                   \
  if (!(x))                                              \
  ::mxtpu::LogMessage(__FILE__, __LINE__, true).stream() \
      << "Check failed: " #x " "

#define MXTPU_CHECK_EQ(a, b) MXTPU_CHECK((a) == (b))
#define MXTPU_CHECK_NE(a, b) MXTPU_CHECK((a) != (b))
#define MXTPU_CHECK_GT(a, b) MXTPU_CHECK((a) > (b))
#define MXTPU_CHECK_GE(a, b) MXTPU_CHECK((a) >= (b))
#define MXTPU_CHECK_LT(a, b) MXTPU_CHECK((a) < (b))
#define MXTPU_CHECK_LE(a, b) MXTPU_CHECK((a) <= (b))

#endif  // MXTPU_COMMON_LOGGING_H_
