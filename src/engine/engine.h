// Async dependency-scheduling engine.
//
// TPU-native rebuild of the reference's ThreadedEngine
// (reference src/engine/threaded_engine.{h,cc}: ThreadedVar queues of
// VersionedVarBlock, OprBlock atomic wait counts; and
// threaded_engine_perdevice.cc worker pools — SURVEY.md §2.1).
// Ops declare const (read) and mutable (write) variables; an op runs
// when all its dependencies clear, on a fixed worker pool.  On TPU the
// device-side scheduling is XLA/PJRT's job; this engine orders
// *host-side* work: IO pipeline stages, checkpoint writes, parameter
// updates touching host state — the same role the reference engine
// plays for its CPU ops.
#ifndef MXTPU_ENGINE_ENGINE_H_
#define MXTPU_ENGINE_ENGINE_H_

#include <atomic>
#include <condition_variable>
#include <cstdint>
#include <deque>
#include <functional>
#include <memory>
#include <mutex>
#include <queue>
#include <thread>
#include <unordered_map>
#include <vector>

namespace mxtpu {
namespace engine {

using OpFn = std::function<void()>;
using VarHandle = int64_t;

class ThreadedEngine {
 public:
  explicit ThreadedEngine(int num_workers);
  ~ThreadedEngine();

  VarHandle NewVariable();
  // Push an operation reading const_vars and writing mutable_vars.
  // Duplicate handles within/across the two lists are invalid
  // (reference CheckDuplicate, threaded_engine.h:376).
  void Push(OpFn fn, const std::vector<VarHandle>& const_vars,
            const std::vector<VarHandle>& mutable_vars);
  // Both wait calls throw std::runtime_error if any op failed since the
  // last wait (the reference propagates op errors through on_complete;
  // here the first error is latched and surfaced at the next sync point).
  void WaitForVar(VarHandle var);
  void WaitForAll();
  // Delete a variable once all pending ops on it complete.
  void DeleteVariable(VarHandle var);
  int num_workers() const { return static_cast<int>(workers_.size()); }

 private:
  struct Opr;

  // Per-variable dependency queue (reference ThreadedVar,
  // threaded_engine.h:111): pending readers/writer entries in order.
  struct Var {
    struct Block {
      Opr* opr;
      bool write;
    };
    std::mutex mu;
    std::deque<Block> queue;
    // number of currently running readers; -1 if a writer is running
    int running_readers = 0;
    bool writer_running = false;
    bool to_delete = false;
  };

  struct Opr {
    OpFn fn;
    std::vector<Var*> const_vars;
    std::vector<Var*> mutable_vars;
    std::atomic<int> wait{0};
  };

  void WorkerLoop();
  void Schedule(Opr* opr);
  void OnComplete(Opr* opr);
  // returns true if the op at the head can start now
  void TryDispatchHead(Var* v, std::vector<Opr*>* ready);

  std::vector<std::thread> workers_;
  std::queue<Opr*> task_queue_;
  std::mutex queue_mu_;
  std::condition_variable queue_cv_;
  bool shutdown_ = false;

  std::mutex vars_mu_;
  std::unordered_map<VarHandle, std::unique_ptr<Var>> vars_;
  std::atomic<int64_t> next_var_{1};

  std::atomic<int64_t> pending_{0};
  std::mutex finished_mu_;
  std::condition_variable finished_cv_;

  // first op failure since the last wait (latched, reported once)
  std::mutex error_mu_;
  std::string first_error_;
  void RethrowPendingError();
};

}  // namespace engine
}  // namespace mxtpu

#endif  // MXTPU_ENGINE_ENGINE_H_
