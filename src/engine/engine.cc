// ThreadedEngine implementation — see engine.h.
// Dependency semantics mirror the reference scheduler
// (reference src/engine/threaded_engine.cc CompleteReadDependency /
// CompleteWriteDependency :144-156): per-var FIFO, concurrent readers,
// exclusive writers, atomic op wait counts.
#include "engine.h"

#include "../common/logging.h"

namespace mxtpu {
namespace engine {

ThreadedEngine::ThreadedEngine(int num_workers) {
  if (num_workers < 1) num_workers = 1;
  for (int i = 0; i < num_workers; ++i) {
    workers_.emplace_back([this] { WorkerLoop(); });
  }
}

ThreadedEngine::~ThreadedEngine() {
  // drain WITHOUT RethrowPendingError: destructors are noexcept and a
  // latched op error must not std::terminate the process
  {
    std::unique_lock<std::mutex> lk(finished_mu_);
    finished_cv_.wait(lk, [this] { return pending_.load() == 0; });
  }
  {
    std::lock_guard<std::mutex> lk(queue_mu_);
    shutdown_ = true;
  }
  queue_cv_.notify_all();
  for (auto& t : workers_) t.join();
}

VarHandle ThreadedEngine::NewVariable() {
  VarHandle h = next_var_.fetch_add(1);
  std::lock_guard<std::mutex> lk(vars_mu_);
  vars_[h] = std::unique_ptr<Var>(new Var());
  return h;
}

void ThreadedEngine::TryDispatchHead(Var* v, std::vector<Opr*>* ready) {
  // caller holds v->mu
  while (!v->queue.empty()) {
    Var::Block head = v->queue.front();
    if (head.write) {
      if (v->running_readers == 0 && !v->writer_running) {
        v->writer_running = true;
        v->queue.pop_front();
        if (head.opr->wait.fetch_sub(1) == 1) ready->push_back(head.opr);
      }
      break;
    }
    if (v->writer_running) break;
    ++v->running_readers;
    v->queue.pop_front();
    if (head.opr->wait.fetch_sub(1) == 1) ready->push_back(head.opr);
  }
}

void ThreadedEngine::Push(OpFn fn,
                          const std::vector<VarHandle>& const_vars,
                          const std::vector<VarHandle>& mutable_vars) {
  // unique_ptr until fully validated, so a CHECK throw doesn't leak
  std::unique_ptr<Opr> guard(new Opr());
  Opr* opr = guard.get();
  opr->fn = std::move(fn);
  {
    std::lock_guard<std::mutex> lk(vars_mu_);
    for (VarHandle h : const_vars) {
      auto it = vars_.find(h);
      MXTPU_CHECK(it != vars_.end()) << "unknown const var " << h;
      opr->const_vars.push_back(it->second.get());
    }
    for (VarHandle h : mutable_vars) {
      auto it = vars_.find(h);
      MXTPU_CHECK(it != vars_.end()) << "unknown mutable var " << h;
      opr->mutable_vars.push_back(it->second.get());
    }
  }
  // full CheckDuplicate semantics (reference threaded_engine.h:376):
  // no overlap across lists AND no duplicates within either list
  for (size_t i = 0; i < opr->const_vars.size(); ++i)
    for (size_t j = i + 1; j < opr->const_vars.size(); ++j)
      MXTPU_CHECK(opr->const_vars[i] != opr->const_vars[j])
          << "duplicate var in const_vars";
  for (size_t i = 0; i < opr->mutable_vars.size(); ++i)
    for (size_t j = i + 1; j < opr->mutable_vars.size(); ++j)
      MXTPU_CHECK(opr->mutable_vars[i] != opr->mutable_vars[j])
          << "duplicate var in mutable_vars";
  for (Var* cv : opr->const_vars) {
    for (Var* mv : opr->mutable_vars) {
      MXTPU_CHECK(cv != mv)
          << "a var may not be both const and mutable in one op";
    }
  }
  guard.release();
  pending_.fetch_add(1);
  opr->wait.store(static_cast<int>(opr->const_vars.size() +
                                   opr->mutable_vars.size()) + 1);
  std::vector<Opr*> ready;
  for (Var* v : opr->const_vars) {
    std::lock_guard<std::mutex> lk(v->mu);
    v->queue.push_back({opr, false});
    TryDispatchHead(v, &ready);
  }
  for (Var* v : opr->mutable_vars) {
    std::lock_guard<std::mutex> lk(v->mu);
    v->queue.push_back({opr, true});
    TryDispatchHead(v, &ready);
  }
  // release the +1 guard (covers the zero-deps case exactly once)
  if (opr->wait.fetch_sub(1) == 1) ready.push_back(opr);
  for (Opr* r : ready) Schedule(r);
}

void ThreadedEngine::Schedule(Opr* opr) {
  {
    std::lock_guard<std::mutex> lk(queue_mu_);
    task_queue_.push(opr);
  }
  queue_cv_.notify_one();
}

void ThreadedEngine::WorkerLoop() {
  for (;;) {
    Opr* opr = nullptr;
    {
      std::unique_lock<std::mutex> lk(queue_mu_);
      queue_cv_.wait(lk, [this] { return shutdown_ || !task_queue_.empty(); });
      if (task_queue_.empty()) return;  // shutdown
      opr = task_queue_.front();
      task_queue_.pop();
    }
    try {
      opr->fn();
    } catch (const std::exception& e) {
      std::cerr << "[mxtpu engine] op threw: " << e.what() << std::endl;
      std::lock_guard<std::mutex> lk(error_mu_);
      if (first_error_.empty()) first_error_ = e.what();
    }
    OnComplete(opr);
  }
}

void ThreadedEngine::OnComplete(Opr* opr) {
  std::vector<Opr*> ready;
  std::vector<Var*> maybe_delete;
  for (Var* v : opr->const_vars) {
    std::lock_guard<std::mutex> lk(v->mu);
    --v->running_readers;
    TryDispatchHead(v, &ready);
    if (v->to_delete && v->queue.empty() && v->running_readers == 0 &&
        !v->writer_running) {
      maybe_delete.push_back(v);
    }
  }
  for (Var* v : opr->mutable_vars) {
    std::lock_guard<std::mutex> lk(v->mu);
    v->writer_running = false;
    TryDispatchHead(v, &ready);
    if (v->to_delete && v->queue.empty() && v->running_readers == 0 &&
        !v->writer_running) {
      maybe_delete.push_back(v);
    }
  }
  delete opr;
  for (Opr* r : ready) Schedule(r);
  if (!maybe_delete.empty()) {
    std::lock_guard<std::mutex> lk(vars_mu_);
    for (auto it = vars_.begin(); it != vars_.end();) {
      bool erase = false;
      for (Var* v : maybe_delete) {
        if (it->second.get() == v) { erase = true; break; }
      }
      it = erase ? vars_.erase(it) : std::next(it);
    }
  }
  if (pending_.fetch_sub(1) == 1) {
    std::lock_guard<std::mutex> lk(finished_mu_);
    finished_cv_.notify_all();
  }
}

void ThreadedEngine::WaitForVar(VarHandle var) {
  std::mutex mu;
  std::condition_variable cv;
  bool done = false;
  Push(
      [&] {
        std::lock_guard<std::mutex> lk(mu);
        done = true;
        cv.notify_all();
      },
      {var}, {});
  std::unique_lock<std::mutex> lk(mu);
  cv.wait(lk, [&] { return done; });
  RethrowPendingError();
}

void ThreadedEngine::WaitForAll() {
  std::unique_lock<std::mutex> lk(finished_mu_);
  finished_cv_.wait(lk, [this] { return pending_.load() == 0; });
  RethrowPendingError();
}

void ThreadedEngine::RethrowPendingError() {
  std::string err;
  {
    std::lock_guard<std::mutex> lk(error_mu_);
    err.swap(first_error_);
  }
  if (!err.empty()) throw std::runtime_error("engine op failed: " + err);
}

void ThreadedEngine::DeleteVariable(VarHandle var) {
  std::lock_guard<std::mutex> gl(vars_mu_);
  auto it = vars_.find(var);
  if (it == vars_.end()) return;
  Var* v = it->second.get();
  bool idle;
  {
    std::lock_guard<std::mutex> lk(v->mu);
    v->to_delete = true;
    idle = v->queue.empty() && v->running_readers == 0 &&
           !v->writer_running;
  }
  if (idle) vars_.erase(it);
}

}  // namespace engine
}  // namespace mxtpu
