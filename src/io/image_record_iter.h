// Multithreaded RecordIO image iterator.
// TPU-native rebuild of the reference's default training data path
// (reference src/io/iter_image_recordio_2.cc: threaded chunk read ->
// JPEG decode -> augment -> batch assembly; SURVEY.md §2.5/§3.5).
// One producer thread walks the (sharded, optionally shuffled) index,
// a decode worker pool runs OpenCV decode + augmentation straight into
// preallocated batch buffers, and a bounded ready-queue hands finished
// batches to the consumer — decode overlaps with TPU compute exactly
// like the reference overlaps decode with GPU kernels.
#ifndef MXTPU_IO_IMAGE_RECORD_ITER_H_
#define MXTPU_IO_IMAGE_RECORD_ITER_H_

#include <atomic>
#include <condition_variable>
#include <cstdint>
#include <deque>
#include <memory>
#include <mutex>
#include <random>
#include <string>
#include <thread>
#include <vector>

namespace mxtpu {
namespace io {

struct ImageRecordParam {
  std::string path_imgrec;
  std::string path_imgidx;
  int batch_size = 1;
  int channels = 3;
  int height = 224;
  int width = 224;
  int label_width = 1;
  bool shuffle = false;
  bool rand_crop = false;
  bool rand_mirror = false;
  int resize = 0;  // resize shorter side first if > 0
  float mean[3] = {0.f, 0.f, 0.f};
  float std_[3] = {1.f, 1.f, 1.f};
  int num_parts = 1;
  int part_index = 0;
  int num_threads = 4;
  int prefetch = 4;  // ready-batch queue depth
  uint64_t seed = 0;
  bool round_batch = true;  // wrap the last partial batch
};

class ImageRecordIter {
 public:
  explicit ImageRecordIter(const ImageRecordParam& p);
  ~ImageRecordIter();

  // Advance to the next batch. Returns false at epoch end.
  bool Next();
  const float* data() const { return current_->data.data(); }
  const float* label() const { return current_->label.data(); }
  int pad() const { return current_->pad; }
  void Reset();
  size_t data_size() const;
  size_t label_size() const;

 private:
  struct Batch {
    std::vector<float> data;
    std::vector<float> label;
    int pad = 0;
    std::atomic<int> remaining{0};
  };
  struct Task {
    std::string raw;
    Batch* batch;
    int slot;
    uint64_t rng_seed;
  };

  void ProducerLoop(uint64_t epoch_seed);
  void ProducerBody(uint64_t epoch_seed);
  void WorkerLoop();
  void DecodeInto(const Task& t);
  void StopThreads();
  void StartEpoch();
  void CheckFailed();

  ImageRecordParam p_;
  std::vector<uint64_t> offsets_;  // sharded record offsets

  // decode task queue
  std::deque<Task> tasks_;
  std::mutex task_mu_;
  std::condition_variable task_cv_;

  // ready batches
  std::deque<std::unique_ptr<Batch>> ready_;
  std::mutex ready_mu_;
  std::condition_variable ready_cv_, space_cv_;
  int batches_emitted_ = 0;   // produced to ready_ so far (epoch)
  int batches_consumed_ = 0;
  int batches_per_epoch_ = 0;

  std::unique_ptr<Batch> current_;
  std::vector<std::thread> workers_;
  std::thread producer_;
  std::atomic<bool> stop_{false};
  std::atomic<bool> failed_{false};
  std::string error_;  // guarded by ready_mu_
  uint64_t epoch_ = 0;
};

}  // namespace io
}  // namespace mxtpu

#endif  // MXTPU_IO_IMAGE_RECORD_ITER_H_
