// RecordIO binary framing — reader/writer.
// TPU-native rebuild of the reference's record format
// (reference src/io/image_recordio.h + dmlc-core recordio spec usage,
// SURVEY.md §2.5): each record is
//   uint32 magic(0xced7230a) | uint32 (cflag<<29|len) | payload | pad4
// Matches mxnet_tpu/recordio.py bit-for-bit.
#ifndef MXTPU_IO_RECORDIO_H_
#define MXTPU_IO_RECORDIO_H_

#include <cstdint>
#include <cstdio>
#include <string>
#include <vector>

namespace mxtpu {
namespace io {

constexpr uint32_t kRecordMagic = 0xced7230a;

class RecordReader {
 public:
  explicit RecordReader(const std::string& path);
  ~RecordReader();
  // Read the next logical record into *out. Returns false at EOF.
  bool Next(std::string* out);
  void Reset();
  // Seek to a byte offset (for indexed access).
  void Seek(uint64_t pos);

 private:
  bool FillChunk();
  std::FILE* fp_;
  std::vector<char> chunk_;   // buffered chunk
  size_t chunk_pos_ = 0;
  size_t chunk_len_ = 0;
  size_t chunk_capacity_;
};

class RecordWriter {
 public:
  explicit RecordWriter(const std::string& path);
  ~RecordWriter();
  // Returns the byte offset the record was written at.
  uint64_t Write(const char* data, size_t size);

 private:
  std::FILE* fp_;
};

// Image record header (reference python/mxnet/recordio.py IRHeader,
// struct IfQQ little-endian).
#pragma pack(push, 1)
struct IRHeader {
  uint32_t flag;
  float label;
  uint64_t id;
  uint64_t id2;
};
#pragma pack(pop)
static_assert(sizeof(IRHeader) == 24, "IRHeader must pack to 24 bytes");

}  // namespace io
}  // namespace mxtpu

#endif  // MXTPU_IO_RECORDIO_H_
