#include "recordio.h"

#include <cstring>

#include "../common/logging.h"

namespace mxtpu {
namespace io {

namespace {
constexpr size_t kChunkSize = 4 << 20;  // 4 MiB buffered reads
inline uint32_t DecodeFlag(uint32_t lrec) { return lrec >> 29; }
inline uint32_t DecodeLen(uint32_t lrec) {
  return lrec & ((1u << 29) - 1);
}
}  // namespace

RecordReader::RecordReader(const std::string& path)
    : chunk_capacity_(kChunkSize) {
  fp_ = std::fopen(path.c_str(), "rb");
  MXTPU_CHECK(fp_ != nullptr) << "cannot open " << path;
  chunk_.resize(chunk_capacity_);
}

RecordReader::~RecordReader() {
  if (fp_) std::fclose(fp_);
}

void RecordReader::Reset() { Seek(0); }

void RecordReader::Seek(uint64_t pos) {
  MXTPU_CHECK_EQ(std::fseek(fp_, static_cast<long>(pos), SEEK_SET), 0);
  chunk_pos_ = chunk_len_ = 0;  // drop buffered data
}

bool RecordReader::FillChunk() {
  // move any tail bytes to the front, refill the rest
  size_t remain = chunk_len_ - chunk_pos_;
  if (remain > 0) {
    std::memmove(chunk_.data(), chunk_.data() + chunk_pos_, remain);
  }
  chunk_pos_ = 0;
  chunk_len_ = remain;
  size_t got = std::fread(chunk_.data() + remain, 1,
                          chunk_capacity_ - remain, fp_);
  chunk_len_ += got;
  return chunk_len_ > 0;
}

bool RecordReader::Next(std::string* out) {
  out->clear();
  for (;;) {  // loop over multi-part records
    // ensure 8-byte header available
    while (chunk_len_ - chunk_pos_ < 8) {
      size_t before = chunk_len_ - chunk_pos_;
      if (!FillChunk() || chunk_len_ - chunk_pos_ == before) {
        MXTPU_CHECK(out->empty() && before == 0)
            << "truncated record at EOF";
        return false;
      }
    }
    uint32_t magic, lrec;
    std::memcpy(&magic, chunk_.data() + chunk_pos_, 4);
    std::memcpy(&lrec, chunk_.data() + chunk_pos_ + 4, 4);
    MXTPU_CHECK_EQ(magic, kRecordMagic) << "bad RecordIO magic";
    chunk_pos_ += 8;
    uint32_t cflag = DecodeFlag(lrec);
    uint32_t len = DecodeLen(lrec);
    uint32_t padded = len + ((4 - len % 4) % 4);
    size_t old = out->size();
    out->resize(old + len);
    size_t copied = 0;
    // copy payload (may span chunk refills)
    size_t to_skip = padded;
    while (copied < len) {
      if (chunk_pos_ == chunk_len_) {
        MXTPU_CHECK(FillChunk()) << "truncated record payload";
      }
      size_t avail = chunk_len_ - chunk_pos_;
      size_t take = std::min(avail, static_cast<size_t>(len) - copied);
      std::memcpy(&(*out)[old + copied], chunk_.data() + chunk_pos_, take);
      copied += take;
      chunk_pos_ += take;
      to_skip -= take;
    }
    // skip padding
    while (to_skip > 0) {
      if (chunk_pos_ == chunk_len_) {
        MXTPU_CHECK(FillChunk()) << "truncated record padding";
      }
      size_t take = std::min(chunk_len_ - chunk_pos_, to_skip);
      chunk_pos_ += take;
      to_skip -= take;
    }
    if (cflag == 0 || cflag == 3) return true;  // whole or end
  }
}

RecordWriter::RecordWriter(const std::string& path) {
  fp_ = std::fopen(path.c_str(), "wb");
  MXTPU_CHECK(fp_ != nullptr) << "cannot open " << path;
}

RecordWriter::~RecordWriter() {
  if (fp_) std::fclose(fp_);
}

uint64_t RecordWriter::Write(const char* data, size_t size) {
  uint64_t pos = static_cast<uint64_t>(std::ftell(fp_));
  uint32_t magic = kRecordMagic;
  uint32_t lrec = static_cast<uint32_t>(size);  // cflag=0 (whole)
  std::fwrite(&magic, 4, 1, fp_);
  std::fwrite(&lrec, 4, 1, fp_);
  std::fwrite(data, 1, size, fp_);
  static const char zeros[4] = {0, 0, 0, 0};
  size_t pad = (4 - size % 4) % 4;
  if (pad) std::fwrite(zeros, 1, pad, fp_);
  return pos;
}

}  // namespace io
}  // namespace mxtpu
