#include "image_record_iter.h"

#include <algorithm>
#include <cstring>
#include <fstream>

#include <opencv2/imgcodecs.hpp>
#include <opencv2/imgproc.hpp>

#include "../common/logging.h"
#include "recordio.h"

namespace mxtpu {
namespace io {

ImageRecordIter::ImageRecordIter(const ImageRecordParam& p) : p_(p) {
  if (p_.prefetch < 1) p_.prefetch = 1;  // 0 would deadlock the bound
  if (p_.batch_size < 1) p_.batch_size = 1;
  // load .idx offsets (key \t offset per line)
  std::ifstream fin(p_.path_imgidx);
  MXTPU_CHECK(fin.good()) << "cannot open idx " << p_.path_imgidx;
  std::vector<uint64_t> all;
  int64_t key;
  uint64_t off;
  while (fin >> key >> off) all.push_back(off);
  MXTPU_CHECK(!all.empty()) << "empty index " << p_.path_imgidx;
  // shard (reference dist-aware num_parts/part_index)
  if (p_.num_parts > 1) {
    size_t per = all.size() / p_.num_parts;
    MXTPU_CHECK_GT(per, 0u) << "fewer records than parts";
    size_t begin = per * p_.part_index;
    size_t end = (p_.part_index == p_.num_parts - 1) ? all.size()
                                                     : begin + per;
    offsets_.assign(all.begin() + begin, all.begin() + end);
  } else {
    offsets_ = std::move(all);
  }
  int n = static_cast<int>(offsets_.size());
  batches_per_epoch_ = p_.round_batch
                           ? (n + p_.batch_size - 1) / p_.batch_size
                           : n / p_.batch_size;
  MXTPU_CHECK_GT(batches_per_epoch_, 0) << "not enough records for a batch";
  for (int i = 0; i < std::max(1, p_.num_threads); ++i) {
    workers_.emplace_back([this] { WorkerLoop(); });
  }
  StartEpoch();
}

void ImageRecordIter::StartEpoch() {
  batches_emitted_ = 0;
  batches_consumed_ = 0;
  uint64_t seed = p_.seed + 0x9e3779b97f4a7c15ULL * (++epoch_);
  producer_ = std::thread([this, seed] { ProducerLoop(seed); });
}

ImageRecordIter::~ImageRecordIter() { StopThreads(); }

void ImageRecordIter::StopThreads() {
  stop_.store(true);
  task_cv_.notify_all();
  ready_cv_.notify_all();
  space_cv_.notify_all();
  if (producer_.joinable()) producer_.join();
  for (auto& w : workers_) {
    if (w.joinable()) w.join();
  }
  workers_.clear();
}

size_t ImageRecordIter::data_size() const {
  return static_cast<size_t>(p_.batch_size) * p_.channels * p_.height *
         p_.width;
}

size_t ImageRecordIter::label_size() const {
  return static_cast<size_t>(p_.batch_size) * p_.label_width;
}

void ImageRecordIter::ProducerLoop(uint64_t epoch_seed) {
  // exceptions must not escape the thread (std::terminate): capture
  // and surface through Next()
  try {
    ProducerBody(epoch_seed);
  } catch (const std::exception& e) {
    {
      std::lock_guard<std::mutex> lk(ready_mu_);
      error_ = e.what();
      failed_.store(true);
    }
    ready_cv_.notify_all();
  }
}

void ImageRecordIter::ProducerBody(uint64_t epoch_seed) {
  std::vector<uint64_t> order = offsets_;
  if (p_.shuffle) {
    std::mt19937_64 rng(epoch_seed);
    std::shuffle(order.begin(), order.end(), rng);
  }
  RecordReader reader(p_.path_imgrec);
  int n = static_cast<int>(order.size());
  // keep several batches' decode tasks in flight so the worker pool is
  // never idle across batch boundaries; emit completed batches in order
  const int max_inflight = std::max(2, p_.prefetch);
  std::deque<std::unique_ptr<Batch>> inflight;

  auto emit_front = [&]() -> bool {  // false on stop
    Batch* bp = inflight.front().get();
    std::unique_lock<std::mutex> lk(ready_mu_);
    ready_cv_.wait(lk, [&] {
      return stop_.load() || bp->remaining.load() == 0;
    });
    if (stop_.load()) return false;
    space_cv_.wait(lk, [&] {
      return stop_.load() ||
             static_cast<int>(ready_.size()) < p_.prefetch;
    });
    if (stop_.load()) return false;
    ready_.push_back(std::move(inflight.front()));
    inflight.pop_front();
    ++batches_emitted_;
    lk.unlock();
    ready_cv_.notify_all();
    return true;
  };

  for (int b = 0; b < batches_per_epoch_ && !stop_.load(); ++b) {
    auto batch = std::unique_ptr<Batch>(new Batch());
    batch->data.resize(data_size());
    batch->label.assign(label_size(), 0.f);
    int start = b * p_.batch_size;
    int real = std::min(p_.batch_size, n - start);
    batch->pad = p_.batch_size - real;
    batch->remaining.store(p_.batch_size);
    Batch* bp = batch.get();
    // reads are sequential (cheap); decode runs on the pool
    for (int i = 0; i < p_.batch_size; ++i) {
      int idx = (start + i) % n;  // wrap for the padded tail
      std::string raw;
      reader.Seek(order[idx]);
      MXTPU_CHECK(reader.Next(&raw)) << "record read failed";
      Task t;
      t.raw = std::move(raw);
      t.batch = bp;
      t.slot = i;
      t.rng_seed = epoch_seed ^ (0x853c49e6748fea9bULL *
                                 (uint64_t)(start + i + 1));
      {
        std::unique_lock<std::mutex> lk(task_mu_);
        tasks_.push_back(std::move(t));
      }
      task_cv_.notify_one();
    }
    inflight.push_back(std::move(batch));
    if (static_cast<int>(inflight.size()) >= max_inflight) {
      if (!emit_front()) return;
    }
  }
  while (!inflight.empty()) {
    if (!emit_front()) return;
  }
}

void ImageRecordIter::WorkerLoop() {
  for (;;) {
    Task t;
    {
      std::unique_lock<std::mutex> lk(task_mu_);
      task_cv_.wait(lk, [this] { return stop_.load() || !tasks_.empty(); });
      if (stop_.load()) return;
      t = std::move(tasks_.front());
      tasks_.pop_front();
    }
    try {
      DecodeInto(t);
    } catch (const std::exception& e) {
      std::cerr << "[mxtpu io] decode failed: " << e.what() << std::endl;
    }
    if (t.batch->remaining.fetch_sub(1) == 1) {
      // batch complete — wake the producer
      std::lock_guard<std::mutex> lk(ready_mu_);
      ready_cv_.notify_all();
    }
  }
}

void ImageRecordIter::DecodeInto(const Task& t) {
  const IRHeader* hdr =
      reinterpret_cast<const IRHeader*>(t.raw.data());
  const char* payload = t.raw.data() + sizeof(IRHeader);
  size_t payload_len = t.raw.size() - sizeof(IRHeader);
  // labels: flag>0 means flag floats prepended (recordio.py pack)
  float* lab = t.batch->label.data() +
               static_cast<size_t>(t.slot) * p_.label_width;
  if (hdr->flag > 0) {
    const float* labels = reinterpret_cast<const float*>(payload);
    int nl = std::min<int>(hdr->flag, p_.label_width);
    for (int i = 0; i < nl; ++i) lab[i] = labels[i];
    payload += hdr->flag * 4;
    payload_len -= hdr->flag * 4;
  } else {
    lab[0] = hdr->label;
  }
  cv::Mat buf(1, static_cast<int>(payload_len), CV_8U,
              const_cast<char*>(payload));
  cv::Mat img = cv::imdecode(buf, p_.channels == 1 ? cv::IMREAD_GRAYSCALE
                                                   : cv::IMREAD_COLOR);
  MXTPU_CHECK(!img.empty()) << "imdecode failed";
  if (p_.channels == 3) cv::cvtColor(img, img, cv::COLOR_BGR2RGB);

  std::mt19937_64 rng(t.rng_seed);
  // resize shorter side
  if (p_.resize > 0) {
    int h = img.rows, w = img.cols;
    int nh, nw;
    if (h > w) {
      nw = p_.resize;
      nh = p_.resize * h / w;
    } else {
      nh = p_.resize;
      nw = p_.resize * w / h;
    }
    cv::resize(img, img, cv::Size(nw, nh), 0, 0, cv::INTER_AREA);
  }
  // crop to (H, W): random or center; upscale first if too small
  if (img.rows < p_.height || img.cols < p_.width) {
    cv::resize(img, img,
               cv::Size(std::max(img.cols, p_.width),
                        std::max(img.rows, p_.height)),
               0, 0, cv::INTER_LINEAR);
  }
  int y0, x0;
  if (p_.rand_crop) {
    y0 = static_cast<int>(rng() % (img.rows - p_.height + 1));
    x0 = static_cast<int>(rng() % (img.cols - p_.width + 1));
  } else {
    y0 = (img.rows - p_.height) / 2;
    x0 = (img.cols - p_.width) / 2;
  }
  cv::Mat crop = img(cv::Rect(x0, y0, p_.width, p_.height));
  bool mirror = p_.rand_mirror && (rng() & 1);
  if (mirror) cv::flip(crop, crop, 1);

  // cast + normalize + HWC->CHW into the batch slot
  float* out = t.batch->data.data() +
               static_cast<size_t>(t.slot) * p_.channels * p_.height *
                   p_.width;
  const size_t plane = static_cast<size_t>(p_.height) * p_.width;
  for (int y = 0; y < p_.height; ++y) {
    const uint8_t* row = crop.ptr<uint8_t>(y);
    for (int x = 0; x < p_.width; ++x) {
      for (int c = 0; c < p_.channels; ++c) {
        float v = static_cast<float>(row[x * p_.channels + c]);
        out[c * plane + y * p_.width + x] =
            (v - p_.mean[c]) / p_.std_[c];
      }
    }
  }
}

bool ImageRecordIter::Next() {
  std::unique_lock<std::mutex> lk(ready_mu_);
  if (failed_.load()) throw mxtpu::Error(error_);
  if (batches_consumed_ >= batches_per_epoch_) return false;
  ready_cv_.wait(lk, [this] {
    return stop_.load() || failed_.load() || !ready_.empty();
  });
  if (failed_.load()) throw mxtpu::Error(error_);
  if (stop_.load() && ready_.empty()) return false;
  current_ = std::move(ready_.front());
  ready_.pop_front();
  ++batches_consumed_;
  space_cv_.notify_all();
  return true;
}

void ImageRecordIter::Reset() {
  // stop + join everything, clear queues, restart pool and epoch
  StopThreads();
  tasks_.clear();
  ready_.clear();
  current_.reset();
  stop_.store(false);
  for (int i = 0; i < std::max(1, p_.num_threads); ++i) {
    workers_.emplace_back([this] { WorkerLoop(); });
  }
  StartEpoch();
}

}  // namespace io
}  // namespace mxtpu
