// Training C ABI — NDArray / Symbol / Executor / KVStore from plain C.
//
// TPU-native counterpart of the reference's training c_api surface
// (/root/reference/include/mxnet/c_api.h, 139 MXNET_DLL functions;
// src/c_api.cc) — the subset every language binding needs to TRAIN, not
// just predict: create NDArrays, compose symbols, simple_bind an
// executor, forward/backward, run an optimizer step, talk to a kvstore.
// The reference's cpp-package example trains an MLP end-to-end on
// exactly this surface (/root/reference/cpp-package/example/mlp.cpp).
//
// Architecture: same embedded-CPython pattern as c_predict_api.cc — the
// compute runtime is JAX/XLA, so each C call acquires the GIL and
// drives mxnet_tpu/_c_api_bridge.py; opaque handles returned to C are
// PyObject* (NDArray / Symbol / Executor / KVStore / updater).
// String/shape lists returned to C are cached per-handle with
// C-pointer lifetime (valid until the next call on the same handle),
// like the reference's MXAPIThreadLocalEntry scratch space.
//
// Threading contract: entry points are callable from any thread (each
// takes the GIL), but a handle is single-caller — per-handle caches
// and handle state are mutated without a lock, so concurrent calls on
// the SAME handle are undefined; use one handle per thread.  The rule
// is documented at the declaration site (MxTpuCpp.hpp) too.
#include "py_embed.h"

#include <cstdint>
#include <cstring>
#include <map>
#include <string>
#include <vector>

namespace {

thread_local std::string train_last_error;

using pyembed::GIL;

std::string py_err_str() { return pyembed::err_string(); }

bool ensure_python_rt() {
  return pyembed::ensure_interpreter(&train_last_error);
}

PyObject* bridge() {
  PyObject* mod = PyImport_ImportModule("mxnet_tpu._c_api_bridge");
  if (mod == nullptr) train_last_error = py_err_str();
  return mod;
}

// Every handle wraps the bridge object plus per-handle caches for
// C-lifetime string/shape/byte returns.
struct Handle {
  PyObject* obj = nullptr;
  std::vector<std::string> str_store;
  std::vector<const char*> str_ptrs;
  std::vector<uint32_t> shape_store;
  std::string byte_store;
  // infer_shape result caches: CSR (indptr, data) per group.
  std::vector<uint32_t> infer_indptr[3];
  std::vector<uint32_t> infer_data[3];
};

Handle* wrap(PyObject* obj) {
  Handle* h = new Handle();
  h->obj = obj;
  return h;
}

PyObject* obj_of(void* h) { return static_cast<Handle*>(h)->obj; }

PyObject* str_list(uint32_t n, const char** items) {
  PyObject* list = PyList_New(n);
  if (list == nullptr) return nullptr;
  for (uint32_t i = 0; i < n; ++i)
    PyList_SET_ITEM(list, i, PyUnicode_FromString(items[i]));
  return list;
}

PyObject* shape_tuple(uint32_t ndim, const uint32_t* dims) {
  PyObject* tup = PyTuple_New(ndim);
  if (tup == nullptr) return nullptr;
  for (uint32_t i = 0; i < ndim; ++i)
    PyTuple_SET_ITEM(tup, i, PyLong_FromUnsignedLong(dims[i]));
  return tup;
}

// CSR-style shape pack (indptr[i]..indptr[i+1] owns input i's dims).
PyObject* shapes_csr(uint32_t num, const uint32_t* indptr,
                     const uint32_t* data) {
  PyObject* list = PyList_New(num);
  if (list == nullptr) return nullptr;
  for (uint32_t i = 0; i < num; ++i) {
    PyObject* tup = shape_tuple(indptr[i + 1] - indptr[i],
                                data + indptr[i]);
    if (tup == nullptr) {
      Py_DECREF(list);
      return nullptr;
    }
    PyList_SET_ITEM(list, i, tup);
  }
  return list;
}

// Call bridge.<fn>(...) returning a new reference (nullptr on error).
PyObject* call(const char* fn, const char* fmt, ...) {
  PyObject* mod = bridge();
  if (mod == nullptr) return nullptr;
  PyObject* meth = PyObject_GetAttrString(mod, fn);
  Py_DECREF(mod);
  if (meth == nullptr) {
    train_last_error = py_err_str();
    return nullptr;
  }
  va_list va;
  va_start(va, fmt);
  PyObject* args = Py_VaBuildValue(fmt, va);
  va_end(va);
  PyObject* out = nullptr;
  if (args != nullptr) {
    out = PyObject_CallObject(meth, args);
    Py_DECREF(args);
  }
  Py_DECREF(meth);
  if (out == nullptr) train_last_error = py_err_str();
  return out;
}

int store_strings(PyObject* list, Handle* h, uint32_t* out_n,
                  const char*** out) {
  h->str_store.clear();
  h->str_ptrs.clear();
  for (Py_ssize_t i = 0; i < PyList_GET_SIZE(list); ++i) {
    const char* c = PyUnicode_AsUTF8(PyList_GET_ITEM(list, i));
    if (c == nullptr) {
      train_last_error = py_err_str();
      return -1;
    }
    h->str_store.emplace_back(c);
  }
  for (const std::string& s : h->str_store) h->str_ptrs.push_back(s.c_str());
  *out_n = static_cast<uint32_t>(h->str_ptrs.size());
  if (out != nullptr)
    *out = h->str_ptrs.empty() ? nullptr : h->str_ptrs.data();
  return 0;
}

}  // namespace

extern "C" {

const char* MXTTrainGetLastError() { return train_last_error.c_str(); }

// -- NDArray ---------------------------------------------------------------

// Zero-filled float32 NDArray.  dev_type: 1 = cpu, 2 = accelerator.
int MXTNDArrayCreate(const uint32_t* shape, uint32_t ndim, int dev_type,
                     int dev_id, void** out) {
  *out = nullptr;
  if (!ensure_python_rt()) return -1;
  GIL gil;
  PyObject* tup = shape_tuple(ndim, shape);
  if (tup == nullptr) return -1;
  PyObject* arr = call("nd_create", "(Oii)", tup, dev_type, dev_id);
  Py_DECREF(tup);
  if (arr == nullptr) return -1;
  *out = wrap(arr);
  return 0;
}

// Create + fill from a flat little-endian float32 buffer.
int MXTNDArrayCreateFromBytes(const uint32_t* shape, uint32_t ndim,
                              const float* data, int dev_type, int dev_id,
                              void** out) {
  *out = nullptr;
  if (!ensure_python_rt()) return -1;
  GIL gil;
  size_t n = 1;
  for (uint32_t i = 0; i < ndim; ++i) n *= shape[i];
  PyObject* tup = shape_tuple(ndim, shape);
  if (tup == nullptr) return -1;
  PyObject* arr = call("nd_from_bytes", "(Oy#ii)", tup,
                       reinterpret_cast<const char*>(data),
                       static_cast<Py_ssize_t>(n * sizeof(float)),
                       dev_type, dev_id);
  Py_DECREF(tup);
  if (arr == nullptr) return -1;
  *out = wrap(arr);
  return 0;
}

// Refill an existing NDArray in place from host memory (reference
// MXNDArraySyncCopyFromCPU).
int MXTNDArraySyncCopyFromCPU(void* handle, const float* data,
                              size_t size) {
  GIL gil;
  PyObject* r = call("nd_copy_from", "(Oy#)", obj_of(handle),
                     reinterpret_cast<const char*>(data),
                     static_cast<Py_ssize_t>(size * sizeof(float)));
  if (r == nullptr) return -1;
  Py_DECREF(r);
  return 0;
}

// Fetch to host memory as float32 (reference MXNDArraySyncCopyToCPU).
int MXTNDArraySyncCopyToCPU(void* handle, float* data, size_t size) {
  GIL gil;
  PyObject* bytes = call("nd_to_bytes", "(O)", obj_of(handle));
  if (bytes == nullptr) return -1;
  char* buf = nullptr;
  Py_ssize_t blen = 0;
  if (PyBytes_AsStringAndSize(bytes, &buf, &blen) != 0 ||
      static_cast<size_t>(blen) != size * sizeof(float)) {
    train_last_error = "MXTNDArraySyncCopyToCPU: size mismatch";
    Py_DECREF(bytes);
    return -1;
  }
  std::memcpy(data, buf, blen);
  Py_DECREF(bytes);
  return 0;
}

int MXTNDArrayGetShape(void* handle, uint32_t* out_dim,
                       const uint32_t** out_data) {
  GIL gil;
  Handle* h = static_cast<Handle*>(handle);
  PyObject* tup = call("nd_shape", "(O)", h->obj);
  if (tup == nullptr) return -1;
  h->shape_store.clear();
  for (Py_ssize_t i = 0; i < PyTuple_GET_SIZE(tup); ++i)
    h->shape_store.push_back(static_cast<uint32_t>(
        PyLong_AsUnsignedLong(PyTuple_GET_ITEM(tup, i))));
  Py_DECREF(tup);
  *out_dim = static_cast<uint32_t>(h->shape_store.size());
  *out_data = h->shape_store.empty() ? nullptr : h->shape_store.data();
  return 0;
}

void MXTNDArrayFree(void* handle) {
  if (handle == nullptr) return;
  GIL gil;
  Handle* h = static_cast<Handle*>(handle);
  Py_XDECREF(h->obj);
  delete h;
}

// Save named NDArrays to the .params container format (reference
// MXNDArraySave).  keys may be null for list-style files.
int MXTNDArraySave(const char* fname, uint32_t num, void** handles,
                   const char** keys) {
  GIL gil;
  PyObject* names = keys != nullptr ? str_list(num, keys)
                                    : PyList_New(0);
  PyObject* arrays = PyList_New(num);
  if (names != nullptr && arrays != nullptr) {
    for (uint32_t i = 0; i < num; ++i) {
      PyObject* o = obj_of(handles[i]);
      Py_INCREF(o);
      PyList_SET_ITEM(arrays, i, o);
    }
  }
  PyObject* r = nullptr;
  if (names != nullptr && arrays != nullptr)
    r = call("nd_save", "(sOO)", fname, names, arrays);
  Py_XDECREF(names);
  Py_XDECREF(arrays);
  if (r == nullptr) return -1;
  Py_DECREF(r);
  return 0;
}

// Load a .params container.  The returned list handle owns the
// (keys, arrays) pair; fetch entries with MXTNDArrayLoadGet and free
// it with MXTNDArrayFree.  All key pointers stay valid until the list
// handle is freed (they are materialized up front into the handle's
// string cache).
int MXTNDArrayLoad(const char* fname, void** out_list, uint32_t* out_n) {
  *out_list = nullptr;
  if (!ensure_python_rt()) return -1;
  GIL gil;
  PyObject* pair = call("nd_load", "(s)", fname);
  if (pair == nullptr) return -1;
  Handle* h = wrap(pair);
  uint32_t n = 0;
  if (store_strings(PyTuple_GET_ITEM(pair, 0), h, &n, nullptr) != 0) {
    MXTNDArrayFree(h);
    return -1;
  }
  *out_n = n;
  *out_list = h;
  return 0;
}

int MXTNDArrayLoadGet(void* list, uint32_t index, const char** out_key,
                      void** out_nd) {
  *out_nd = nullptr;
  GIL gil;
  Handle* h = static_cast<Handle*>(list);
  PyObject* arrays = PyTuple_GET_ITEM(h->obj, 1);
  if (index >= h->str_ptrs.size()) {
    train_last_error = "MXTNDArrayLoadGet: index out of range";
    return -1;
  }
  *out_key = h->str_ptrs[index];
  PyObject* arr = PyList_GET_ITEM(arrays, index);
  Py_INCREF(arr);
  *out_nd = wrap(arr);
  return 0;
}

// Row-range COPY of [begin, end) (functional arrays underneath: unlike
// the reference's MXNDArraySlice view, writes to the result do NOT
// propagate to the parent — refill the parent with SyncCopyFromCPU).
int MXTNDArraySlice(void* handle, uint32_t begin, uint32_t end,
                    void** out) {
  *out = nullptr;
  GIL gil;
  PyObject* o = call("nd_slice", "(OII)", obj_of(handle), begin, end);
  if (o == nullptr) return -1;
  *out = wrap(o);
  return 0;
}

int MXTNDArrayReshape(void* handle, uint32_t ndim, const uint32_t* dims,
                      void** out) {
  *out = nullptr;
  GIL gil;
  PyObject* tup = shape_tuple(ndim, dims);
  if (tup == nullptr) return -1;
  PyObject* o = call("nd_reshape", "(OO)", obj_of(handle), tup);
  Py_DECREF(tup);
  if (o == nullptr) return -1;
  *out = wrap(o);
  return 0;
}

// -- Symbol ----------------------------------------------------------------

int MXTSymbolCreateVariable(const char* name, void** out) {
  *out = nullptr;
  if (!ensure_python_rt()) return -1;
  GIL gil;
  PyObject* s = call("sym_variable", "(s)", name);
  if (s == nullptr) return -1;
  *out = wrap(s);
  return 0;
}

// Atomic symbol creation + composition in one call: op attrs as
// key/value strings, symbol inputs as (arg_keys[i], args[i]) pairs.
// (The reference splits this into CreateAtomicSymbol + Compose.)
int MXTSymbolCreate(const char* op, const char* name, uint32_t num_attr,
                    const char** attr_keys, const char** attr_vals,
                    uint32_t num_args, const char** arg_keys, void** args,
                    void** out) {
  *out = nullptr;
  if (!ensure_python_rt()) return -1;
  GIL gil;
  PyObject* keys = str_list(num_attr, attr_keys);
  PyObject* vals = str_list(num_attr, attr_vals);
  PyObject* anames = str_list(num_args, arg_keys);
  PyObject* asyms = PyList_New(num_args);
  if (keys && vals && anames && asyms) {
    for (uint32_t i = 0; i < num_args; ++i) {
      PyObject* o = obj_of(args[i]);
      Py_INCREF(o);
      PyList_SET_ITEM(asyms, i, o);
    }
  }
  PyObject* s = nullptr;
  if (keys && vals && anames && asyms)
    s = call("sym_create", "(ssOOOO)", op, name ? name : "", keys, vals,
             anames, asyms);
  Py_XDECREF(keys);
  Py_XDECREF(vals);
  Py_XDECREF(anames);
  Py_XDECREF(asyms);
  if (s == nullptr) return -1;
  *out = wrap(s);
  return 0;
}

int MXTSymbolCreateFromJSON(const char* json, void** out) {
  *out = nullptr;
  if (!ensure_python_rt()) return -1;
  GIL gil;
  PyObject* s = call("sym_from_json", "(s)", json);
  if (s == nullptr) return -1;
  *out = wrap(s);
  return 0;
}

int MXTSymbolSaveToJSON(void* handle, const char** out_json) {
  GIL gil;
  Handle* h = static_cast<Handle*>(handle);
  PyObject* s = call("sym_to_json", "(O)", h->obj);
  if (s == nullptr) return -1;
  const char* c = PyUnicode_AsUTF8(s);
  if (c == nullptr) {
    train_last_error = py_err_str();
    Py_DECREF(s);
    return -1;
  }
  h->byte_store = c;
  Py_DECREF(s);
  *out_json = h->byte_store.c_str();
  return 0;
}

static int sym_name_list(void* handle, const char* fn, uint32_t* out_n,
                         const char*** out) {
  GIL gil;
  Handle* h = static_cast<Handle*>(handle);
  PyObject* list = call(fn, "(O)", h->obj);
  if (list == nullptr) return -1;
  int rc = store_strings(list, h, out_n, out);
  Py_DECREF(list);
  return rc;
}

int MXTSymbolListArguments(void* handle, uint32_t* out_n,
                           const char*** out) {
  return sym_name_list(handle, "sym_list_arguments", out_n, out);
}

int MXTSymbolListOutputs(void* handle, uint32_t* out_n,
                         const char*** out) {
  return sym_name_list(handle, "sym_list_outputs", out_n, out);
}

int MXTSymbolListAuxiliaryStates(void* handle, uint32_t* out_n,
                                 const char*** out) {
  return sym_name_list(handle, "sym_list_aux", out_n, out);
}

static int handle_by_index(const char* fn, void* handle, uint32_t idx,
                           void** out);
static int handle_by_name(const char* fn, void* handle, const char* name,
                          void** out);

static int handle_plain(const char* fn, void* handle, void** out) {
  GIL gil;
  PyObject* o = call(fn, "(O)", obj_of(handle));
  if (o == nullptr) return -1;
  *out = wrap(o);
  return 0;
}

// Graph surgery handles (reference MXSymbolGetInternals/GetOutput).
int MXTSymbolGetInternals(void* handle, void** out) {
  *out = nullptr;
  return handle_plain("sym_get_internals", handle, out);
}

int MXTSymbolGetOutput(void* handle, uint32_t index, void** out) {
  *out = nullptr;
  return handle_by_index("sym_get_output", handle, index, out);
}

int MXTSymbolGetInternalByName(void* handle, const char* name,
                               void** out) {
  *out = nullptr;
  return handle_by_name("sym_get_internal_by_name", handle, name, out);
}

// Attribute get/set (reference MXSymbolGetAttr/SetAttr).  out_present
// carries the set/unset distinction (an attribute explicitly set to ""
// reports present=1); the string pointer is handle-cached.
int MXTSymbolGetAttr(void* handle, const char* key, const char** out,
                     int* out_present) {
  GIL gil;
  Handle* h = static_cast<Handle*>(handle);
  PyObject* pair = call("sym_attr_get", "(Os)", h->obj, key);
  if (pair == nullptr) return -1;
  long present = PyLong_AsLong(PyTuple_GET_ITEM(pair, 0));
  const char* c = PyUnicode_AsUTF8(PyTuple_GET_ITEM(pair, 1));
  if (c == nullptr) {
    train_last_error = py_err_str();
    Py_DECREF(pair);
    return -1;
  }
  h->byte_store = c;
  Py_DECREF(pair);
  *out = h->byte_store.c_str();
  if (out_present != nullptr) *out_present = static_cast<int>(present);
  return 0;
}

int MXTSymbolSetAttr(void* handle, const char* key, const char* value) {
  GIL gil;
  PyObject* r = call("sym_attr_set", "(Oss)", obj_of(handle), key,
                     value);
  if (r == nullptr) return -1;
  Py_DECREF(r);
  return 0;
}

// Bidirectional shape inference (reference MXSymbolInferShape): provide
// shapes for some args CSR-style; receive complete arg/out/aux shape
// lists, each returned CSR-style with handle-cached lifetime.
int MXTSymbolInferShape(void* handle, uint32_t num_provided,
                        const char** keys, const uint32_t* indptr,
                        const uint32_t* shape_data,
                        uint32_t* arg_count, const uint32_t** arg_indptr,
                        const uint32_t** arg_data,
                        uint32_t* out_count, const uint32_t** out_indptr,
                        const uint32_t** out_data,
                        uint32_t* aux_count, const uint32_t** aux_indptr,
                        const uint32_t** aux_data) {
  GIL gil;
  Handle* h = static_cast<Handle*>(handle);
  PyObject* names = str_list(num_provided, keys);
  PyObject* shapes = shapes_csr(num_provided, indptr, shape_data);
  PyObject* triple = nullptr;
  if (names && shapes)
    triple = call("sym_infer_shape", "(OOO)", h->obj, names, shapes);
  Py_XDECREF(names);
  Py_XDECREF(shapes);
  if (triple == nullptr) return -1;
  uint32_t* counts[3] = {arg_count, out_count, aux_count};
  const uint32_t** iptrs[3] = {arg_indptr, out_indptr, aux_indptr};
  const uint32_t** datas[3] = {arg_data, out_data, aux_data};
  for (int g = 0; g < 3; ++g) {
    PyObject* group = PyTuple_GET_ITEM(triple, g);
    h->infer_indptr[g].assign(1, 0);
    h->infer_data[g].clear();
    Py_ssize_t n = PyList_GET_SIZE(group);
    for (Py_ssize_t i = 0; i < n; ++i) {
      PyObject* tup = PyList_GET_ITEM(group, i);
      if (PyTuple_Check(tup)) {
        for (Py_ssize_t j = 0; j < PyTuple_GET_SIZE(tup); ++j)
          h->infer_data[g].push_back(static_cast<uint32_t>(
              PyLong_AsUnsignedLong(PyTuple_GET_ITEM(tup, j))));
      }
      h->infer_indptr[g].push_back(
          static_cast<uint32_t>(h->infer_data[g].size()));
    }
    *counts[g] = static_cast<uint32_t>(n);
    *iptrs[g] = h->infer_indptr[g].data();
    *datas[g] = h->infer_data[g].empty() ? nullptr
                                         : h->infer_data[g].data();
  }
  Py_DECREF(triple);
  if (PyErr_Occurred()) {
    train_last_error = py_err_str();
    return -1;
  }
  return 0;
}

void MXTSymbolFree(void* handle) { MXTNDArrayFree(handle); }

// -- Executor --------------------------------------------------------------

// simple_bind: shapes for the named args arrive CSR-style.
int MXTExecutorSimpleBind(void* sym, int dev_type, int dev_id,
                          const char* grad_req, uint32_t num_provided,
                          const char** keys, const uint32_t* indptr,
                          const uint32_t* shape_data, void** out) {
  *out = nullptr;
  if (!ensure_python_rt()) return -1;
  GIL gil;
  PyObject* names = str_list(num_provided, keys);
  PyObject* shapes = shapes_csr(num_provided, indptr, shape_data);
  PyObject* ex = nullptr;
  if (names && shapes)
    ex = call("simple_bind", "(OiisOO)", obj_of(sym), dev_type, dev_id,
              grad_req, names, shapes);
  Py_XDECREF(names);
  Py_XDECREF(shapes);
  if (ex == nullptr) return -1;
  *out = wrap(ex);
  return 0;
}

int MXTExecutorForward(void* handle, int is_train) {
  GIL gil;
  PyObject* r = call("ex_forward", "(Oi)", obj_of(handle), is_train);
  if (r == nullptr) return -1;
  Py_DECREF(r);
  return 0;
}

int MXTExecutorBackward(void* handle) {
  GIL gil;
  PyObject* r = call("ex_backward", "(O)", obj_of(handle));
  if (r == nullptr) return -1;
  Py_DECREF(r);
  return 0;
}

int MXTExecutorNumOutputs(void* handle, uint32_t* out_n) {
  GIL gil;
  PyObject* r = call("ex_num_outputs", "(O)", obj_of(handle));
  if (r == nullptr) return -1;
  *out_n = static_cast<uint32_t>(PyLong_AsUnsignedLong(r));
  Py_DECREF(r);
  return 0;
}

static int handle_by_index(const char* fn, void* handle, uint32_t idx,
                           void** out) {
  GIL gil;
  PyObject* o = call(fn, "(OI)", obj_of(handle), idx);
  if (o == nullptr) return -1;
  *out = wrap(o);
  return 0;
}

static int handle_by_name(const char* fn, void* handle, const char* name,
                          void** out) {
  GIL gil;
  PyObject* o = call(fn, "(Os)", obj_of(handle), name);
  if (o == nullptr) return -1;
  *out = wrap(o);
  return 0;
}

// Output i as a new NDArray handle (shares the device buffer).
int MXTExecutorOutput(void* handle, uint32_t index, void** out) {
  *out = nullptr;
  return handle_by_index("ex_output", handle, index, out);
}

// Bound argument / gradient arrays by name (the reference returns
// positional arrays from Bind; by-name is the simpler contract and maps
// 1:1 onto arg_dict/grad_dict).
int MXTExecutorArgArray(void* handle, const char* name, void** out) {
  *out = nullptr;
  return handle_by_name("ex_arg", handle, name, out);
}

int MXTExecutorGradArray(void* handle, const char* name, void** out) {
  *out = nullptr;
  return handle_by_name("ex_grad", handle, name, out);
}

void MXTExecutorFree(void* handle) { MXTNDArrayFree(handle); }

// -- Optimizer -------------------------------------------------------------

// An updater = optimizer instance + per-index state (reference
// kvstore updater semantics: same index -> same state slot).
int MXTUpdaterCreate(const char* opt_name, uint32_t num_attr,
                     const char** attr_keys, const char** attr_vals,
                     void** out) {
  *out = nullptr;
  if (!ensure_python_rt()) return -1;
  GIL gil;
  PyObject* keys = str_list(num_attr, attr_keys);
  PyObject* vals = str_list(num_attr, attr_vals);
  PyObject* u = nullptr;
  if (keys && vals)
    u = call("updater_create", "(sOO)", opt_name, keys, vals);
  Py_XDECREF(keys);
  Py_XDECREF(vals);
  if (u == nullptr) return -1;
  *out = wrap(u);
  return 0;
}

int MXTUpdaterStep(void* updater, int index, void* grad, void* weight) {
  GIL gil;
  PyObject* r = call("updater_step", "(OiOO)", obj_of(updater), index,
                     obj_of(grad), obj_of(weight));
  if (r == nullptr) return -1;
  Py_DECREF(r);
  return 0;
}

void MXTUpdaterFree(void* handle) { MXTNDArrayFree(handle); }

// -- KVStore ---------------------------------------------------------------

int MXTKVStoreCreate(const char* kind, void** out) {
  *out = nullptr;
  if (!ensure_python_rt()) return -1;
  GIL gil;
  PyObject* kv = call("kv_create", "(s)", kind);
  if (kv == nullptr) return -1;
  *out = wrap(kv);
  return 0;
}

static int kv_op(const char* fn, void* kv, const char* key, void* nd) {
  GIL gil;
  PyObject* r = call(fn, "(OsO)", obj_of(kv), key, obj_of(nd));
  if (r == nullptr) return -1;
  Py_DECREF(r);
  return 0;
}

int MXTKVStoreInit(void* kv, const char* key, void* nd) {
  return kv_op("kv_init", kv, key, nd);
}

int MXTKVStorePush(void* kv, const char* key, void* nd) {
  return kv_op("kv_push", kv, key, nd);
}

int MXTKVStorePull(void* kv, const char* key, void* nd) {
  return kv_op("kv_pull", kv, key, nd);
}

void MXTKVStoreFree(void* handle) { MXTNDArrayFree(handle); }

// -- Imperative invoke + autograd ------------------------------------------
//
// The reference's imperative heart (MXImperativeInvoke,
// /root/reference/src/c_api/c_api_ndarray.cc:423): any registered op,
// by name, on NDArray handles — plus autograd record/backward
// (c_api_ndarray.cc:545-621) so a C caller can differentiate outside a
// bound executor, and the CachedOp mini-JIT (c_api_ndarray.cc:464-485).

namespace {

PyObject* handle_list(uint32_t n, void** handles) {
  PyObject* list = PyList_New(n);
  if (list == nullptr) return nullptr;
  for (uint32_t i = 0; i < n; ++i) {
    PyObject* o = obj_of(handles[i]);
    Py_INCREF(o);
    PyList_SET_ITEM(list, i, o);
  }
  return list;
}

// Unpack a bridge list of NDArrays into caller-supplied handle slots.
int unpack_outputs(PyObject* list, uint32_t max_outputs,
                   uint32_t* num_outputs, void** outputs) {
  Py_ssize_t n = PyList_GET_SIZE(list);
  if (static_cast<uint32_t>(n) > max_outputs) {
    train_last_error = "output array too small: need " +
                       std::to_string(n) + " slots";
    return -1;
  }
  for (Py_ssize_t i = 0; i < n; ++i) {
    PyObject* o = PyList_GET_ITEM(list, i);
    Py_INCREF(o);
    outputs[i] = wrap(o);
  }
  *num_outputs = static_cast<uint32_t>(n);
  return 0;
}

}  // namespace

// Global runtime controls (reference MXRandomSeed / MXNDArrayWaitAll).
int MXTRandomSeed(int seed) {
  if (!ensure_python_rt()) return -1;
  GIL gil;
  PyObject* r = call("random_seed", "(i)", seed);
  if (r == nullptr) return -1;
  Py_DECREF(r);
  return 0;
}

int MXTNDArrayWaitAll() {
  if (!ensure_python_rt()) return -1;
  GIL gil;
  PyObject* r = call("wait_all", "()");
  if (r == nullptr) return -1;
  Py_DECREF(r);
  return 0;
}

// Op introspection — the reference's MXSymbolListAtomicSymbolCreators
// + MXSymbolGetAtomicSymbolInfo pair, which binding codegen walks to
// build a language's op namespace.  The caches below rebuild whenever
// the Python registry's generation stamp changes, so ops registered at
// runtime (CustomOp) appear instead of a stale first-call snapshot
// silently diverging from the live registry imperative_invoke
// consults.  Returned pointers keep the original static-lifetime
// contract: superseded cache entries are retired, not freed, so a
// caller holding a pre-refresh list never dereferences freed memory
// (it just sees a stale snapshot).

// Live registry generation stamp (bumped on every registration,
// including re-registration of an existing name); -1 on bridge
// failure.  Caller holds the GIL.
static long op_registry_generation_now() {
  PyObject* r = call("op_registry_generation", "()");
  if (r == nullptr) return -1;
  long n = PyLong_AsLong(r);
  Py_DECREF(r);
  return n;
}

// Superseded cache entries are retired, never freed: the pre-refresh
// contract gave returned pointers registry (static) lifetime, and a
// caller iterating a name list while another thread registers an op
// must not land on freed memory.  Growth is bounded by the number of
// runtime registrations observed by the introspection calls.
static void retire_handle(void* h) {
  static std::vector<void*>* retired = new std::vector<void*>();
  if (h != nullptr) retired->push_back(h);
}

int MXTListOpNames(uint32_t* out_n, const char*** out_names) {
  if (!ensure_python_rt()) return -1;
  GIL gil;
  static Handle* cache = nullptr;
  static long cache_gen = -1;
  long gen = op_registry_generation_now();
  if (gen < 0) return -1;
  if (cache == nullptr || gen != cache_gen) {
    PyObject* names = call("list_op_names", "()");
    if (names == nullptr) return -1;
    Handle* h = wrap(names);
    uint32_t n = 0;
    if (store_strings(names, h, &n, nullptr) != 0) {
      MXTNDArrayFree(h);
      return -1;
    }
    retire_handle(cache);   // old pointers stay valid (never freed)
    cache = h;
    cache_gen = gen;
  }
  *out_n = static_cast<uint32_t>(cache->str_ptrs.size());
  *out_names = cache->str_ptrs.data();
  return 0;
}

int MXTOpGetInfo(const char* name, const char** canonical_name,
                 const char** description, uint32_t* num_inputs,
                 const char*** input_names) {
  if (!ensure_python_rt()) return -1;
  GIL gil;
  static std::map<std::string, Handle*>* cache = nullptr;
  static long cache_gen = -1;
  if (cache == nullptr) cache = new std::map<std::string, Handle*>();
  long gen = op_registry_generation_now();
  if (gen < 0) return -1;
  if (gen != cache_gen) {
    // registry changed: a cached name may now resolve differently
    // (e.g. a CustomOp re-registered with new inputs) — retire it
    // all (old pointers stay valid, see retire_handle)
    for (auto& kv : *cache) retire_handle(kv.second);
    cache->clear();
    cache_gen = gen;
  }
  Handle* h;
  auto it = cache->find(name);
  if (it != cache->end()) {
    h = it->second;
  } else {
    // bridge returns [canonical, description, in0, in1, ...]
    PyObject* info = call("op_info", "(s)", name);
    if (info == nullptr) return -1;
    h = wrap(info);
    uint32_t n = 0;
    int src = store_strings(info, h, &n, nullptr);
    if (src != 0 || n < 2) {
      // store_strings failure already carries the real Python error;
      // only a successful-but-short reply needs its own message
      if (src == 0) train_last_error = "op_info: short reply from bridge";
      MXTNDArrayFree(h);
      return -1;
    }
    // call() may release the GIL: the registry can mutate (and
    // another caller advance cache_gen) while op_info ran, so only
    // insert if the generation still matches the one observed at
    // ENTRY (not cache_gen, which a concurrent refresher may already
    // have advanced past our pre-mutation info) — a stale insert
    // under the new generation would be served until the NEXT bump.
    // The answer itself is still returned (retired, never freed).
    if (op_registry_generation_now() == gen) {
      cache->emplace(name, h);
    } else {
      retire_handle(h);
    }
  }
  *canonical_name = h->str_ptrs[0];
  *description = h->str_ptrs[1];
  *num_inputs = static_cast<uint32_t>(h->str_ptrs.size() - 2);
  *input_names = *num_inputs ? h->str_ptrs.data() + 2 : nullptr;
  return 0;
}

// Run a registered operator imperatively.  `outputs` is a caller array
// with `max_outputs` slots; on success `*num_outputs` handles are
// written (each freed with MXTNDArrayFree).
int MXTImperativeInvoke(const char* op_name, uint32_t num_inputs,
                        void** inputs, uint32_t num_params,
                        const char** param_keys, const char** param_vals,
                        uint32_t* num_outputs, void** outputs,
                        uint32_t max_outputs) {
  *num_outputs = 0;
  if (!ensure_python_rt()) return -1;
  GIL gil;
  PyObject* ins = handle_list(num_inputs, inputs);
  PyObject* keys = str_list(num_params, param_keys);
  PyObject* vals = str_list(num_params, param_vals);
  PyObject* outs = nullptr;
  if (ins && keys && vals)
    outs = call("imperative_invoke", "(sOOO)", op_name, ins, keys, vals);
  Py_XDECREF(ins);
  Py_XDECREF(keys);
  Py_XDECREF(vals);
  if (outs == nullptr) return -1;
  int rc = unpack_outputs(outs, max_outputs, num_outputs, outputs);
  Py_DECREF(outs);
  return rc;
}

// Toggle tape recording / train mode; previous state lands in *prev
// (reference MXAutogradSetIsRecording / MXAutogradSetIsTraining).
int MXTAutogradSetIsRecording(int flag, int* prev) {
  if (!ensure_python_rt()) return -1;
  GIL gil;
  PyObject* r = call("autograd_set_recording", "(i)", flag);
  if (r == nullptr) return -1;
  if (prev != nullptr) *prev = static_cast<int>(PyLong_AsLong(r));
  Py_DECREF(r);
  return 0;
}

int MXTAutogradSetIsTraining(int flag, int* prev) {
  if (!ensure_python_rt()) return -1;
  GIL gil;
  PyObject* r = call("autograd_set_training", "(i)", flag);
  if (r == nullptr) return -1;
  if (prev != nullptr) *prev = static_cast<int>(PyLong_AsLong(r));
  Py_DECREF(r);
  return 0;
}

// Attach gradient buffers to arrays (reference MXAutogradMarkVariables).
// grad_reqs may be null (every variable gets 'write').
int MXTAutogradMarkVariables(uint32_t num, void** vars,
                             const char** grad_reqs) {
  if (!ensure_python_rt()) return -1;
  GIL gil;
  PyObject* vs = handle_list(num, vars);
  PyObject* reqs;
  if (grad_reqs != nullptr) {
    reqs = str_list(num, grad_reqs);
  } else {
    reqs = PyList_New(num);
    if (reqs != nullptr)
      for (uint32_t i = 0; i < num; ++i)
        PyList_SET_ITEM(reqs, i, PyUnicode_FromString("write"));
  }
  PyObject* r = nullptr;
  if (vs && reqs) r = call("autograd_mark_variables", "(OO)", vs, reqs);
  Py_XDECREF(vs);
  Py_XDECREF(reqs);
  if (r == nullptr) return -1;
  Py_DECREF(r);
  return 0;
}

// Backprop from heads through the recorded tape (reference
// MXAutogradBackwardEx); gradients land in the marked variables'
// buffers, readable via MXTNDArrayGetGrad.
int MXTAutogradBackward(uint32_t num_heads, void** heads,
                        int retain_graph) {
  if (!ensure_python_rt()) return -1;
  GIL gil;
  PyObject* hs = handle_list(num_heads, heads);
  if (hs == nullptr) return -1;
  PyObject* r = call("autograd_backward", "(Oi)", hs, retain_graph);
  Py_DECREF(hs);
  if (r == nullptr) return -1;
  Py_DECREF(r);
  return 0;
}

// The gradient buffer of a marked variable (reference MXNDArrayGetGrad).
int MXTNDArrayGetGrad(void* handle, void** out) {
  *out = nullptr;
  GIL gil;
  PyObject* g = call("nd_get_grad", "(O)", obj_of(handle));
  if (g == nullptr) return -1;
  *out = wrap(g);
  return 0;
}

// -- CachedOp --------------------------------------------------------------

// Compile a symbol for repeated imperative invocation (reference
// MXCreateCachedOp).  Invocation inputs arrive in list_arguments() +
// list_auxiliary_states() order; each distinct input signature jits
// once and replays thereafter.  Invoked under recording, the whole
// cached graph differentiates as one tape op.
int MXTCachedOpCreate(void* sym, void** out) {
  *out = nullptr;
  if (!ensure_python_rt()) return -1;
  GIL gil;
  PyObject* op = call("cached_op_create", "(O)", obj_of(sym));
  if (op == nullptr) return -1;
  *out = wrap(op);
  return 0;
}

int MXTCachedOpInvoke(void* cached, uint32_t num_inputs, void** inputs,
                      uint32_t* num_outputs, void** outputs,
                      uint32_t max_outputs) {
  *num_outputs = 0;
  GIL gil;
  PyObject* ins = handle_list(num_inputs, inputs);
  if (ins == nullptr) return -1;
  PyObject* outs = call("cached_op_invoke", "(OO)", obj_of(cached), ins);
  Py_DECREF(ins);
  if (outs == nullptr) return -1;
  int rc = unpack_outputs(outs, max_outputs, num_outputs, outputs);
  Py_DECREF(outs);
  return rc;
}

void MXTCachedOpFree(void* handle) { MXTNDArrayFree(handle); }

// -- DataIter --------------------------------------------------------------
//
// The reference's iterator C surface (MXListDataIters /
// MXDataIterCreateIter / Next / GetData / GetLabel,
// /root/reference/src/c_api/c_api.cc) — what lets every language
// binding train from .rec/.csv files without touching Python.

// List the string-creatable iterators.  Pointers stay valid for the
// process lifetime (cached in a static handle).
int MXTListDataIters(uint32_t* out_n, const char*** out_names) {
  if (!ensure_python_rt()) return -1;
  GIL gil;
  static Handle* cache = nullptr;
  if (cache == nullptr) {
    PyObject* names = call("list_data_iters", "()");
    if (names == nullptr) return -1;
    Handle* h = wrap(names);
    uint32_t n = 0;
    if (store_strings(names, h, &n, nullptr) != 0) {
      MXTNDArrayFree(h);
      return -1;
    }
    cache = h;
  }
  *out_n = static_cast<uint32_t>(cache->str_ptrs.size());
  *out_names = cache->str_ptrs.data();
  return 0;
}

// Create an iterator by registered name with string params (reference
// MXDataIterCreateIter; params are the same key=value strings the
// Python constructors take).
int MXTDataIterCreate(const char* name, uint32_t num_param,
                      const char** keys, const char** vals, void** out) {
  *out = nullptr;
  if (!ensure_python_rt()) return -1;
  GIL gil;
  PyObject* k = str_list(num_param, keys);
  PyObject* v = str_list(num_param, vals);
  PyObject* it = nullptr;
  if (k && v) it = call("data_iter_create", "(sOO)", name, k, v);
  Py_XDECREF(k);
  Py_XDECREF(v);
  if (it == nullptr) return -1;
  *out = wrap(it);
  return 0;
}

int MXTDataIterBeforeFirst(void* handle) {
  GIL gil;
  PyObject* r = call("data_iter_before_first", "(O)", obj_of(handle));
  if (r == nullptr) return -1;
  Py_DECREF(r);
  return 0;
}

// Advance; *out_has_next = 0 at end of epoch (reference MXDataIterNext).
int MXTDataIterNext(void* handle, int* out_has_next) {
  *out_has_next = 0;
  GIL gil;
  PyObject* r = call("data_iter_next", "(O)", obj_of(handle));
  if (r == nullptr) return -1;
  *out_has_next = static_cast<int>(PyLong_AsLong(r));
  Py_DECREF(r);
  return 0;
}

static int iter_get(const char* fn, void* handle, void** out) {
  *out = nullptr;
  GIL gil;
  PyObject* arr = call(fn, "(O)", obj_of(handle));
  if (arr == nullptr) return -1;
  *out = wrap(arr);
  return 0;
}

// Current batch's data / label as NDArray handles (freed by caller).
int MXTDataIterGetData(void* handle, void** out) {
  return iter_get("data_iter_get_data", handle, out);
}

int MXTDataIterGetLabel(void* handle, void** out) {
  return iter_get("data_iter_get_label", handle, out);
}

// Pad count of the current batch (tail-batch refill, reference
// MXDataIterGetPadNum).
int MXTDataIterGetPadNum(void* handle, int* out_pad) {
  *out_pad = 0;
  GIL gil;
  PyObject* r = call("data_iter_get_pad", "(O)", obj_of(handle));
  if (r == nullptr) return -1;
  *out_pad = static_cast<int>(PyLong_AsLong(r));
  Py_DECREF(r);
  return 0;
}

void MXTDataIterFree(void* handle) { MXTNDArrayFree(handle); }

// Device-side copy dst[:] = src — feeds executor-bound arrays straight
// from iterator batches (reference _copyto / executor _load_general).
int MXTNDArrayCopyFromNDArray(void* dst, void* src) {
  GIL gil;
  PyObject* r = call("nd_copy_from_nd", "(OO)", obj_of(dst),
                     obj_of(src));
  if (r == nullptr) return -1;
  Py_DECREF(r);
  return 0;
}

}  // extern "C"
