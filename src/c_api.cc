// C ABI for the native runtime — consumed by mxnet_tpu/_core.py via
// ctypes.  TPU-native counterpart of the reference's C API surface for
// the engine and IO (reference src/c_api/c_api.cc NDArray/engine/
// recordio sections; SURVEY.md §2.6) — the tensor/executor parts of the
// reference C API live in JAX/XLA instead.
#include <cstring>
#include <string>

#include "engine/engine.h"
#include "io/image_record_iter.h"
#include "io/recordio.h"

extern "C" {

// ---- error handling (reference c_api_common.h API_BEGIN/END) ----------
static thread_local std::string last_error;
const char* MXTGetLastError() { return last_error.c_str(); }

#define API_BEGIN() try {
#define API_END()                     \
  }                                   \
  catch (const std::exception& e) {   \
    last_error = e.what();            \
    return -1;                        \
  }                                   \
  return 0;

// ---- engine ------------------------------------------------------------
typedef void (*MXTOpCallback)(void* payload);

void* MXTEngineCreate(int num_workers) {
  return new mxtpu::engine::ThreadedEngine(num_workers);
}

void MXTEngineFree(void* h) {
  delete static_cast<mxtpu::engine::ThreadedEngine*>(h);
}

int64_t MXTEngineNewVar(void* h) {
  return static_cast<mxtpu::engine::ThreadedEngine*>(h)->NewVariable();
}

int MXTEnginePush(void* h, MXTOpCallback cb, void* payload,
                  const int64_t* const_vars, int n_const,
                  const int64_t* mutable_vars, int n_mut) {
  API_BEGIN()
  auto* eng = static_cast<mxtpu::engine::ThreadedEngine*>(h);
  std::vector<int64_t> cv(const_vars, const_vars + n_const);
  std::vector<int64_t> mv(mutable_vars, mutable_vars + n_mut);
  eng->Push([cb, payload] { cb(payload); }, cv, mv);
  API_END()
}

int MXTEngineWaitForVar(void* h, int64_t var) {
  API_BEGIN()
  static_cast<mxtpu::engine::ThreadedEngine*>(h)->WaitForVar(var);
  API_END()
}

int MXTEngineWaitAll(void* h) {
  API_BEGIN()
  static_cast<mxtpu::engine::ThreadedEngine*>(h)->WaitForAll();
  API_END()
}

int MXTEngineDeleteVar(void* h, int64_t var) {
  API_BEGIN()
  static_cast<mxtpu::engine::ThreadedEngine*>(h)->DeleteVariable(var);
  API_END()
}

// ---- recordio ----------------------------------------------------------
// Reader handle owns its record buffer so returned pointers stay valid
// until the next call on the SAME reader (not just the same thread).
struct MXTReaderHandle {
  explicit MXTReaderHandle(const char* path) : reader(path) {}
  mxtpu::io::RecordReader reader;
  std::string buf;
};

void* MXTRecordReaderCreate(const char* path) {
  try {
    return new MXTReaderHandle(path);
  } catch (const std::exception& e) {
    last_error = e.what();
    return nullptr;
  }
}

void MXTRecordReaderFree(void* h) {
  delete static_cast<MXTReaderHandle*>(h);
}

// Returns 1 if a record was read, 0 at EOF, -1 on error.  The pointer
// is valid until the next call on this reader.
int MXTRecordReaderNext(void* h, const char** data, uint64_t* size) {
  try {
    auto* r = static_cast<MXTReaderHandle*>(h);
    if (!r->reader.Next(&r->buf)) return 0;
    *data = r->buf.data();
    *size = r->buf.size();
    return 1;
  } catch (const std::exception& e) {
    last_error = e.what();
    return -1;
  }
}

int MXTRecordReaderSeek(void* h, uint64_t pos) {
  API_BEGIN()
  static_cast<MXTReaderHandle*>(h)->reader.Seek(pos);
  API_END()
}

void* MXTRecordWriterCreate(const char* path) {
  try {
    return new mxtpu::io::RecordWriter(path);
  } catch (const std::exception& e) {
    last_error = e.what();
    return nullptr;
  }
}

void MXTRecordWriterFree(void* h) {
  delete static_cast<mxtpu::io::RecordWriter*>(h);
}

int64_t MXTRecordWriterWrite(void* h, const char* data, uint64_t size) {
  try {
    return static_cast<int64_t>(
        static_cast<mxtpu::io::RecordWriter*>(h)->Write(data, size));
  } catch (const std::exception& e) {
    last_error = e.what();
    return -1;
  }
}

// ---- image record iterator ---------------------------------------------
void* MXTImageRecordIterCreate(const char* rec_path, const char* idx_path,
                               int batch_size, int channels, int height,
                               int width, int label_width, int shuffle,
                               int rand_crop, int rand_mirror, int resize,
                               const float* mean, const float* stdv,
                               int num_parts, int part_index,
                               int num_threads, int prefetch,
                               uint64_t seed) {
  try {
    mxtpu::io::ImageRecordParam p;
    p.path_imgrec = rec_path;
    p.path_imgidx = idx_path;
    p.batch_size = batch_size;
    p.channels = channels;
    p.height = height;
    p.width = width;
    p.label_width = label_width;
    p.shuffle = shuffle != 0;
    p.rand_crop = rand_crop != 0;
    p.rand_mirror = rand_mirror != 0;
    p.resize = resize;
    for (int i = 0; i < 3; ++i) {
      p.mean[i] = mean ? mean[i] : 0.f;
      p.std_[i] = stdv ? stdv[i] : 1.f;
    }
    p.num_parts = num_parts;
    p.part_index = part_index;
    p.num_threads = num_threads;
    p.prefetch = prefetch;
    p.seed = seed;
    return new mxtpu::io::ImageRecordIter(p);
  } catch (const std::exception& e) {
    last_error = e.what();
    return nullptr;
  }
}

void MXTImageRecordIterFree(void* h) {
  delete static_cast<mxtpu::io::ImageRecordIter*>(h);
}

// Returns 1 with pointers set, 0 at epoch end, -1 on error.
int MXTImageRecordIterNext(void* h, const float** data,
                           const float** label, int* pad) {
  try {
    auto* it = static_cast<mxtpu::io::ImageRecordIter*>(h);
    if (!it->Next()) return 0;
    *data = it->data();
    *label = it->label();
    *pad = it->pad();
    return 1;
  } catch (const std::exception& e) {
    last_error = e.what();
    return -1;
  }
}

int MXTImageRecordIterReset(void* h) {
  API_BEGIN()
  static_cast<mxtpu::io::ImageRecordIter*>(h)->Reset();
  API_END()
}

}  // extern "C"
