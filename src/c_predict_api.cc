// Standalone C predict ABI — the deployment surface of the framework.
//
// TPU-native counterpart of the reference's c_predict_api
// (/root/reference/src/c_predict_api.cc, 362 LoC; include/mxnet/
// c_predict_api.h): create a predictor from a symbol JSON string + a
// param blob, set inputs, forward, read outputs — consumable from any
// language with a C FFI, no Python required in the caller.
//
// Architecture note: in the reference the predict API sits on the C++
// engine; here the inference runtime is JAX/XLA, so this ABI hosts an
// embedded CPython interpreter (initialized lazily on first
// MXTPredCreate; a no-op when the library is already loaded inside a
// Python process) and drives mxnet_tpu/_c_predict_bridge.py through a
// minimal str/bytes/int call surface.  Handles returned to C cache
// shape/output buffers on the C++ side so returned pointers have
// C-pointer lifetime (valid until the next call on the same handle),
// exactly like the reference's MXAPIThreadLocalEntry scratch.
#include "py_embed.h"

#include <cstdint>
#include <cstring>
#include <string>
#include <vector>

namespace {

thread_local std::string pred_last_error;

using pyembed::GIL;

std::string py_err_string() { return pyembed::err_string(); }

bool ensure_python() {
  return pyembed::ensure_interpreter(&pred_last_error);
}

PyObject* bridge_module() {
  PyObject* mod = PyImport_ImportModule("mxnet_tpu._c_predict_bridge");
  if (mod == nullptr) pred_last_error = py_err_string();
  return mod;
}

struct PredHandle {
  PyObject* obj = nullptr;                       // bridge Predictor
  std::vector<std::vector<uint32_t>> shapes;     // per-output shape cache
  std::string out_buf;                           // last GetOutput bytes
};

struct NDListHandle {
  std::vector<std::string> keys;
  std::vector<std::vector<uint32_t>> shapes;
  std::vector<std::string> data;                 // float32 bytes
};

// Build the [(key, (shape...)), ...] argument pair for create/reshape.
PyObject* shapes_to_pylist(uint32_t num, const uint32_t* indptr,
                           const uint32_t* shape_data) {
  PyObject* list = PyList_New(num);
  if (list == nullptr) return nullptr;
  for (uint32_t i = 0; i < num; ++i) {
    uint32_t lo = indptr[i], hi = indptr[i + 1];
    PyObject* tup = PyTuple_New(hi - lo);
    if (tup == nullptr) {
      Py_DECREF(list);
      return nullptr;
    }
    for (uint32_t j = lo; j < hi; ++j)
      PyTuple_SET_ITEM(tup, j - lo, PyLong_FromLong(shape_data[j]));
    PyList_SET_ITEM(list, i, tup);
  }
  return list;
}

PyObject* keys_to_pylist(uint32_t num, const char** keys) {
  PyObject* list = PyList_New(num);
  if (list == nullptr) return nullptr;
  for (uint32_t i = 0; i < num; ++i)
    PyList_SET_ITEM(list, i, PyUnicode_FromString(keys[i]));
  return list;
}

bool fill_shape(PyObject* tup, std::vector<uint32_t>* out) {
  if (!PyTuple_Check(tup)) return false;
  Py_ssize_t n = PyTuple_GET_SIZE(tup);
  out->resize(n);
  for (Py_ssize_t i = 0; i < n; ++i)
    (*out)[i] = static_cast<uint32_t>(
        PyLong_AsUnsignedLong(PyTuple_GET_ITEM(tup, i)));
  return !PyErr_Occurred();
}

int create_impl(const char* symbol_json, const void* param_bytes,
                int param_size, int dev_type, int dev_id,
                uint32_t num_input_nodes, const char** input_keys,
                const uint32_t* input_shape_indptr,
                const uint32_t* input_shape_data,
                uint32_t num_output_nodes, const char** output_keys,
                void** out) {
  *out = nullptr;
  if (!ensure_python()) return -1;
  GIL gil;
  PyObject* mod = bridge_module();
  if (mod == nullptr) return -1;
  PyObject* keys = keys_to_pylist(num_input_nodes, input_keys);
  PyObject* shapes = shapes_to_pylist(num_input_nodes, input_shape_indptr,
                                      input_shape_data);
  PyObject* outs = num_output_nodes
      ? keys_to_pylist(num_output_nodes, output_keys)
      : (Py_INCREF(Py_None), Py_None);
  PyObject* pred = nullptr;
  if (keys != nullptr && shapes != nullptr && outs != nullptr) {
    pred = PyObject_CallMethod(
        mod, "create", "sy#iiOOO", symbol_json,
        static_cast<const char*>(param_bytes),
        static_cast<Py_ssize_t>(param_size), dev_type, dev_id, keys,
        shapes, outs);
  }
  Py_XDECREF(keys);
  Py_XDECREF(shapes);
  Py_XDECREF(outs);
  Py_DECREF(mod);
  if (pred == nullptr) {
    pred_last_error = py_err_string();
    return -1;
  }
  PredHandle* h = new PredHandle();
  h->obj = pred;
  *out = h;
  return 0;
}

}  // namespace

extern "C" {

// Mirrors reference c_predict_api.h MXPredCreate.  dev_type: 1 = cpu,
// 2 = accelerator (TPU).  Shapes arrive CSR-style: input i owns
// shape_data[indptr[i]:indptr[i+1]].
int MXTPredCreate(const char* symbol_json, const void* param_bytes,
                  int param_size, int dev_type, int dev_id,
                  uint32_t num_input_nodes, const char** input_keys,
                  const uint32_t* input_shape_indptr,
                  const uint32_t* input_shape_data, void** out) {
  return create_impl(symbol_json, param_bytes, param_size, dev_type,
                     dev_id, num_input_nodes, input_keys,
                     input_shape_indptr, input_shape_data, 0, nullptr,
                     out);
}

// Reference MXPredCreatePartialOut: expose internal nodes as outputs.
int MXTPredCreatePartialOut(const char* symbol_json,
                            const void* param_bytes, int param_size,
                            int dev_type, int dev_id,
                            uint32_t num_input_nodes,
                            const char** input_keys,
                            const uint32_t* input_shape_indptr,
                            const uint32_t* input_shape_data,
                            uint32_t num_output_nodes,
                            const char** output_keys, void** out) {
  return create_impl(symbol_json, param_bytes, param_size, dev_type,
                     dev_id, num_input_nodes, input_keys,
                     input_shape_indptr, input_shape_data,
                     num_output_nodes, output_keys, out);
}

int MXTPredGetOutputShape(void* handle, uint32_t index,
                          const uint32_t** shape_data,
                          uint32_t* shape_ndim) {
  auto* h = static_cast<PredHandle*>(handle);
  GIL gil;
  PyObject* mod = bridge_module();
  if (mod == nullptr) return -1;
  PyObject* tup = PyObject_CallMethod(mod, "get_output_shape", "OI",
                                      h->obj, index);
  Py_DECREF(mod);
  if (tup == nullptr) {
    pred_last_error = py_err_string();
    return -1;
  }
  if (h->shapes.size() <= index) h->shapes.resize(index + 1);
  bool ok = fill_shape(tup, &h->shapes[index]);
  Py_DECREF(tup);
  if (!ok) {
    pred_last_error = py_err_string();
    return -1;
  }
  *shape_data = h->shapes[index].data();
  *shape_ndim = static_cast<uint32_t>(h->shapes[index].size());
  return 0;
}

int MXTPredSetInput(void* handle, const char* key, const float* data,
                    uint32_t size) {
  auto* h = static_cast<PredHandle*>(handle);
  GIL gil;
  PyObject* mod = bridge_module();
  if (mod == nullptr) return -1;
  PyObject* r = PyObject_CallMethod(
      mod, "set_input", "Osy#", h->obj, key,
      reinterpret_cast<const char*>(data),
      static_cast<Py_ssize_t>(size * sizeof(float)));
  Py_DECREF(mod);
  if (r == nullptr) {
    pred_last_error = py_err_string();
    return -1;
  }
  Py_DECREF(r);
  return 0;
}

int MXTPredForward(void* handle) {
  auto* h = static_cast<PredHandle*>(handle);
  GIL gil;
  PyObject* mod = bridge_module();
  if (mod == nullptr) return -1;
  PyObject* r = PyObject_CallMethod(mod, "forward", "O", h->obj);
  Py_DECREF(mod);
  if (r == nullptr) {
    pred_last_error = py_err_string();
    return -1;
  }
  Py_DECREF(r);
  return 0;
}

// Reference MXPredPartialForward (graph_executor.cc:54): run the first
// `step` op nodes; *step_left reports how many remain.
int MXTPredPartialForward(void* handle, int step, int* step_left) {
  auto* h = static_cast<PredHandle*>(handle);
  GIL gil;
  PyObject* mod = bridge_module();
  if (mod == nullptr) return -1;
  PyObject* r = PyObject_CallMethod(mod, "partial_forward", "Oi",
                                    h->obj, step);
  Py_DECREF(mod);
  if (r == nullptr) {
    pred_last_error = py_err_string();
    return -1;
  }
  *step_left = static_cast<int>(PyLong_AsLong(r));
  Py_DECREF(r);
  return 0;
}

int MXTPredGetOutput(void* handle, uint32_t index, float* data,
                     uint32_t size) {
  auto* h = static_cast<PredHandle*>(handle);
  GIL gil;
  PyObject* mod = bridge_module();
  if (mod == nullptr) return -1;
  PyObject* r = PyObject_CallMethod(mod, "get_output", "OI", h->obj,
                                    index);
  Py_DECREF(mod);
  if (r == nullptr) {
    pred_last_error = py_err_string();
    return -1;
  }
  char* buf = nullptr;
  Py_ssize_t len = 0;
  if (PyBytes_AsStringAndSize(r, &buf, &len) != 0) {
    Py_DECREF(r);
    pred_last_error = py_err_string();
    return -1;
  }
  if (static_cast<uint64_t>(len) != uint64_t{size} * sizeof(float)) {
    Py_DECREF(r);
    pred_last_error = "MXTPredGetOutput: caller buffer holds " +
                      std::to_string(size) + " floats, output has " +
                      std::to_string(len / sizeof(float));
    return -1;
  }
  std::memcpy(data, buf, len);
  Py_DECREF(r);
  return 0;
}

// Reference MXPredReshape (in place here: same handle, new shapes).
int MXTPredReshape(void* handle, uint32_t num_input_nodes,
                   const char** input_keys,
                   const uint32_t* input_shape_indptr,
                   const uint32_t* input_shape_data) {
  auto* h = static_cast<PredHandle*>(handle);
  GIL gil;
  PyObject* mod = bridge_module();
  if (mod == nullptr) return -1;
  PyObject* keys = keys_to_pylist(num_input_nodes, input_keys);
  PyObject* shapes = shapes_to_pylist(num_input_nodes,
                                      input_shape_indptr,
                                      input_shape_data);
  PyObject* r = nullptr;
  if (keys != nullptr && shapes != nullptr)
    r = PyObject_CallMethod(mod, "reshape", "OOO", h->obj, keys, shapes);
  Py_XDECREF(keys);
  Py_XDECREF(shapes);
  Py_DECREF(mod);
  if (r == nullptr) {
    pred_last_error = py_err_string();
    return -1;
  }
  Py_DECREF(r);
  return 0;
}

void MXTPredFree(void* handle) {
  auto* h = static_cast<PredHandle*>(handle);
  if (h == nullptr) return;
  if (Py_IsInitialized()) {
    GIL gil;
    Py_XDECREF(h->obj);
  }
  delete h;
}

// ---- NDArray list (reference MXNDListCreate/Get/Free) -----------------
// Parse a .params blob into named float32 arrays — lets C callers read
// mean/std blobs and checkpoints without the full framework.
int MXTNDListCreate(const char* nd_file_bytes, int size, void** out,
                    uint32_t* out_length) {
  *out = nullptr;
  if (!ensure_python()) return -1;
  GIL gil;
  PyObject* mod = bridge_module();
  if (mod == nullptr) return -1;
  PyObject* lst = PyObject_CallMethod(
      mod, "ndlist_create", "y#", nd_file_bytes,
      static_cast<Py_ssize_t>(size));
  Py_DECREF(mod);
  if (lst == nullptr) {
    pred_last_error = py_err_string();
    return -1;
  }
  NDListHandle* h = new NDListHandle();
  Py_ssize_t n = PyList_Size(lst);
  for (Py_ssize_t i = 0; i < n; ++i) {
    PyObject* item = PyList_GetItem(lst, i);  // (name, shape, bytes)
    const char* name = PyUnicode_AsUTF8(PyTuple_GetItem(item, 0));
    std::vector<uint32_t> shape;
    char* buf = nullptr;
    Py_ssize_t len = 0;
    if (name == nullptr ||
        !fill_shape(PyTuple_GetItem(item, 1), &shape) ||
        PyBytes_AsStringAndSize(PyTuple_GetItem(item, 2), &buf, &len)
            != 0) {
      pred_last_error = py_err_string();
      Py_DECREF(lst);
      delete h;
      return -1;
    }
    h->keys.emplace_back(name);
    h->shapes.push_back(std::move(shape));
    h->data.emplace_back(buf, len);
  }
  Py_DECREF(lst);
  *out_length = static_cast<uint32_t>(h->keys.size());
  *out = h;
  return 0;
}

int MXTNDListGet(void* handle, uint32_t index, const char** out_key,
                 const float** out_data, const uint32_t** out_shape,
                 uint32_t* out_ndim) {
  auto* h = static_cast<NDListHandle*>(handle);
  if (index >= h->keys.size()) {
    pred_last_error = "MXTNDListGet: index out of range";
    return -1;
  }
  *out_key = h->keys[index].c_str();
  *out_data = reinterpret_cast<const float*>(h->data[index].data());
  *out_shape = h->shapes[index].data();
  *out_ndim = static_cast<uint32_t>(h->shapes[index].size());
  return 0;
}

void MXTNDListFree(void* handle) {
  delete static_cast<NDListHandle*>(handle);
}

// Same polling convention as MXTGetLastError in c_api.cc, separate
// thread-local channel for the predict surface.
const char* MXTPredGetLastError() { return pred_last_error.c_str(); }

}  // extern "C"
