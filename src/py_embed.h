// Shared embedded-CPython glue for the C ABI translation units
// (c_predict_api.cc, c_api_train.cc): interpreter bring-up, GIL RAII,
// and python-exception -> string capture.  Header-only; each TU keeps
// its own thread_local last-error string (separate polling domains,
// like the reference's per-API error slots).
#ifndef MXNET_TPU_SRC_PY_EMBED_H_
#define MXNET_TPU_SRC_PY_EMBED_H_

#define PY_SSIZE_T_CLEAN
#include <Python.h>

#include <string>

namespace pyembed {

inline std::string err_string() {
  PyObject *type = nullptr, *value = nullptr, *tb = nullptr;
  PyErr_Fetch(&type, &value, &tb);
  PyErr_NormalizeException(&type, &value, &tb);
  std::string msg = "unknown python error";
  if (value != nullptr) {
    PyObject* s = PyObject_Str(value);
    if (s != nullptr) {
      const char* c = PyUnicode_AsUTF8(s);
      if (c != nullptr) msg = c;
      Py_DECREF(s);
    }
  }
  Py_XDECREF(type);
  Py_XDECREF(value);
  Py_XDECREF(tb);
  return msg;
}

// Lazily bring up the interpreter when the library is used from a plain
// C program; inside a Python process Py_IsInitialized() is already true
// and this is a no-op.  (First call from multiple raw threads at once
// would race Py_InitializeEx; callers start single-threaded, matching
// the reference's implicit init contract.)
inline bool ensure_interpreter(std::string* err) {
  if (!Py_IsInitialized()) {
    Py_InitializeEx(0);
    if (!Py_IsInitialized()) {
      if (err != nullptr) *err = "failed to initialize embedded Python";
      return false;
    }
    // Drop the GIL the init acquired so every API call can use the
    // uniform PyGILState_Ensure/Release pairing regardless of thread.
    PyEval_SaveThread();
  }
  return true;
}

struct GIL {
  GIL() : state(PyGILState_Ensure()) {}
  ~GIL() { PyGILState_Release(state); }
  PyGILState_STATE state;
};

}  // namespace pyembed

#endif  // MXNET_TPU_SRC_PY_EMBED_H_
