// Shared embedded-CPython glue for the C ABI translation units
// (c_predict_api.cc, c_api_train.cc): interpreter bring-up, GIL RAII,
// and python-exception -> string capture.  Header-only; each TU keeps
// its own thread_local last-error string (separate polling domains,
// like the reference's per-API error slots).
#ifndef MXNET_TPU_SRC_PY_EMBED_H_
#define MXNET_TPU_SRC_PY_EMBED_H_

#define PY_SSIZE_T_CLEAN
#include <Python.h>

#include <dlfcn.h>

#include <string>

namespace pyembed {

#define PYEMBED_STR_(x) #x
#define PYEMBED_STR(x) PYEMBED_STR_(x)

// Python C-extension modules (numpy etc.) resolve Py* symbols from the
// process's GLOBAL dynamic namespace — they do not link libpython
// themselves.  When this library is loaded by a plugin host that uses
// RTLD_LOCAL (perl XS, ruby, lua...), the libpython our embedded
// interpreter came from is invisible to them and every extension
// import fails.  Re-open the already-loaded libpython with
// RTLD_GLOBAL (RTLD_NOLOAD: never load a second copy) to promote its
// symbols.  No-op in ordinary C programs and inside real Python.
inline void promote_libpython() {
  const char* names[] = {
      "libpython" PYEMBED_STR(PY_MAJOR_VERSION) "."
      PYEMBED_STR(PY_MINOR_VERSION) ".so.1.0",
      "libpython" PYEMBED_STR(PY_MAJOR_VERSION) "."
      PYEMBED_STR(PY_MINOR_VERSION) ".so",
  };
  for (const char* n : names) {
    if (dlopen(n, RTLD_NOW | RTLD_GLOBAL | RTLD_NOLOAD) != nullptr)
      return;
  }
}

inline std::string err_string() {
  PyObject *type = nullptr, *value = nullptr, *tb = nullptr;
  PyErr_Fetch(&type, &value, &tb);
  PyErr_NormalizeException(&type, &value, &tb);
  std::string msg = "unknown python error";
  if (value != nullptr) {
    PyObject* s = PyObject_Str(value);
    if (s != nullptr) {
      const char* c = PyUnicode_AsUTF8(s);
      if (c != nullptr) msg = c;
      Py_DECREF(s);
    }
  }
  Py_XDECREF(type);
  Py_XDECREF(value);
  Py_XDECREF(tb);
  return msg;
}

// Lazily bring up the interpreter when the library is used from a plain
// C program; inside a Python process Py_IsInitialized() is already true
// and this is a no-op.  (First call from multiple raw threads at once
// would race Py_InitializeEx; callers start single-threaded, matching
// the reference's implicit init contract.)
inline bool ensure_interpreter(std::string* err) {
  if (!Py_IsInitialized()) {
    promote_libpython();
    Py_InitializeEx(0);
    if (!Py_IsInitialized()) {
      if (err != nullptr) *err = "failed to initialize embedded Python";
      return false;
    }
    // Drop the GIL the init acquired so every API call can use the
    // uniform PyGILState_Ensure/Release pairing regardless of thread.
    PyEval_SaveThread();
  }
  return true;
}

struct GIL {
  GIL() : state(PyGILState_Ensure()) {}
  ~GIL() { PyGILState_Release(state); }
  PyGILState_STATE state;
};

}  // namespace pyembed

#endif  // MXNET_TPU_SRC_PY_EMBED_H_
