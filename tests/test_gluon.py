"""Gluon API tests (modeled on reference tests/python/unittest/
test_gluon.py + test_nn.py coverage)."""
import numpy as np
import pytest

import mxnet_tpu as mx
from mxnet_tpu import gluon
from mxnet_tpu.gluon import nn
from mxnet_tpu import autograd


def test_parameter_basic():
    p = gluon.Parameter('weight', shape=(4, 3))
    p.initialize(init='xavier', ctx=[mx.cpu(0), mx.cpu(1)])
    assert len(p.list_data()) == 2
    assert len(p.list_grad()) == 2
    assert p.data(mx.cpu(1)).context == mx.cpu(1)
    assert p.data(mx.cpu(0)).shape == (4, 3)
    assert p.var().name == 'weight'


def test_paramdict():
    params = gluon.ParameterDict('net_')
    params.get('weight', shape=(10, 10))
    assert list(params.keys()) == ['net_weight']
    params.initialize(ctx=mx.cpu())
    params.save('/tmp/test_paramdict.params')
    params.load('/tmp/test_paramdict.params', mx.cpu())


def test_dense_forward_backward():
    net = nn.Dense(8, in_units=4, activation='relu')
    net.initialize()
    x = mx.nd.array(np.random.rand(2, 4).astype(np.float32))
    with autograd.record():
        y = net(x)
        loss = mx.nd.sum(y)
    loss.backward()
    w = net.weight
    assert y.shape == (2, 8)
    assert w.grad().shape == (8, 4)
    assert np.isfinite(w.grad().asnumpy()).all()


def test_dense_deferred_init():
    net = nn.Dense(5)
    net.initialize()
    x = mx.nd.array(np.random.rand(3, 7).astype(np.float32))
    y = net(x)
    assert y.shape == (3, 5)
    assert net.weight.shape == (5, 7)


def test_sequential_and_trainer():
    net = nn.HybridSequential()
    with net.name_scope():
        net.add(nn.Dense(16, activation='relu'))
        net.add(nn.Dense(4))
    net.initialize()
    trainer = gluon.Trainer(net.collect_params(), 'sgd',
                            {'learning_rate': 0.1})
    x = mx.nd.array(np.random.rand(8, 10).astype(np.float32))
    label = mx.nd.array(np.random.randint(0, 4, (8,)).astype(np.float32))
    loss_fn = gluon.loss.SoftmaxCrossEntropyLoss()
    net(x)  # trigger deferred shape init
    w_before = net[0].weight.data().asnumpy().copy()
    with autograd.record():
        out = net(x)
        loss = loss_fn(out, label)
    loss.backward()
    trainer.step(8)
    w_after = net[0].weight.data().asnumpy()
    assert not np.allclose(w_before, w_after)


def test_hybridize_matches_imperative():
    np.random.seed(0)
    net = nn.HybridSequential()
    with net.name_scope():
        net.add(nn.Dense(16, activation='relu'))
        net.add(nn.Dense(4))
    net.initialize()
    x = mx.nd.array(np.random.rand(2, 8).astype(np.float32))
    y_imp = net(x).asnumpy()
    net.hybridize()
    y_hyb = net(x).asnumpy()
    np.testing.assert_allclose(y_imp, y_hyb, rtol=1e-5, atol=1e-6)


def test_hybridize_backward():
    net = nn.HybridSequential()
    with net.name_scope():
        net.add(nn.Dense(6, activation='tanh'))
        net.add(nn.Dense(2))
    net.initialize()
    x = mx.nd.array(np.random.rand(3, 5).astype(np.float32))
    # imperative grads
    with autograd.record():
        loss = mx.nd.sum(net(x))
    loss.backward()
    g_imp = net[0].weight.grad().asnumpy().copy()
    # hybridized grads
    net.hybridize()
    with autograd.record():
        loss = mx.nd.sum(net(x))
    loss.backward()
    g_hyb = net[0].weight.grad().asnumpy()
    np.testing.assert_allclose(g_imp, g_hyb, rtol=1e-5, atol=1e-6)


def test_conv2d_layer():
    net = nn.Conv2D(4, kernel_size=3, padding=1, activation='relu')
    net.initialize()
    x = mx.nd.array(np.random.rand(2, 3, 8, 8).astype(np.float32))
    y = net(x)
    assert y.shape == (2, 4, 8, 8)
    assert net.weight.shape == (4, 3, 3, 3)


def test_batchnorm_updates_stats():
    net = nn.BatchNorm(in_channels=3)
    net.initialize()
    x = mx.nd.array((np.random.rand(4, 3, 5, 5) * 10).astype(np.float32))
    before = net.running_mean.data().asnumpy().copy()
    with autograd.record():
        net(x)
    after = net.running_mean.data().asnumpy()
    assert not np.allclose(before, after)


def test_batchnorm_hybrid_updates_stats():
    net = nn.BatchNorm(in_channels=3)
    net.initialize()
    net.hybridize()
    x = mx.nd.array((np.random.rand(4, 3, 5, 5) * 10).astype(np.float32))
    before = net.running_mean.data().asnumpy().copy()
    with autograd.record():
        net(x)
    after = net.running_mean.data().asnumpy()
    assert not np.allclose(before, after)


def test_pool_layers():
    x = mx.nd.array(np.random.rand(2, 3, 8, 8).astype(np.float32))
    assert nn.MaxPool2D(2, 2)(x).shape == (2, 3, 4, 4)
    assert nn.AvgPool2D(2, 2)(x).shape == (2, 3, 4, 4)
    assert nn.GlobalAvgPool2D()(x).shape == (2, 3, 1, 1)


def test_embedding_flatten_dropout():
    emb = nn.Embedding(10, 4)
    emb.initialize()
    idx = mx.nd.array(np.array([[1, 2], [3, 4]], dtype=np.float32))
    out = emb(idx)
    assert out.shape == (2, 2, 4)
    f = nn.Flatten()
    assert f(out).shape == (2, 8)
    d = nn.Dropout(0.5)
    y = d(out)  # predict mode: identity
    np.testing.assert_allclose(y.asnumpy(), out.asnumpy())


def test_losses():
    pred = mx.nd.array(np.random.rand(4, 5).astype(np.float32))
    label_idx = mx.nd.array(np.array([0, 1, 2, 3], dtype=np.float32))
    l = gluon.loss.SoftmaxCrossEntropyLoss()(pred, label_idx)
    assert l.shape == (4,)
    # cross-check with numpy
    logits = pred.asnumpy()
    p = np.exp(logits - logits.max(1, keepdims=True))
    p = p / p.sum(1, keepdims=True)
    expected = -np.log(p[np.arange(4), label_idx.asnumpy().astype(int)])
    np.testing.assert_allclose(l.asnumpy(), expected, rtol=1e-5)

    l2 = gluon.loss.L2Loss()(pred, pred)
    np.testing.assert_allclose(l2.asnumpy(), np.zeros(4), atol=1e-7)
    l1 = gluon.loss.L1Loss(weight=2.0)(pred, pred * 0)
    np.testing.assert_allclose(l1.asnumpy(),
                               2 * np.abs(logits).mean(axis=1), rtol=1e-5)


def test_block_save_load_params():
    net = nn.Dense(3, in_units=2)
    net.initialize()
    net.save_params('/tmp/test_gluon_dense.params')
    net2 = nn.Dense(3, in_units=2, prefix=net.prefix)
    net2.load_params('/tmp/test_gluon_dense.params')
    x = mx.nd.array(np.random.rand(1, 2).astype(np.float32))
    np.testing.assert_allclose(net(x).asnumpy(), net2(x).asnumpy())


def test_split_and_load():
    data = np.random.rand(8, 3).astype(np.float32)
    parts = gluon.utils.split_and_load(data, [mx.cpu(0), mx.cpu(1)])
    assert len(parts) == 2
    assert parts[0].shape == (4, 3)
    assert parts[1].context == mx.cpu(1)


def test_constant_param():
    c = gluon.Constant('const', np.array([1., 2., 3.]))
    c.initialize()
    np.testing.assert_allclose(c.data().asnumpy(), [1., 2., 3.])
    assert c.grad_req == 'null'


def test_hybridized_cell_with_states():
    """Hybridizing a cell whose forward returns nested (out, [states])
    must work (code-review regression)."""
    from mxnet_tpu import gluon
    cell = gluon.rnn.LSTMCell(4, input_size=3)
    cell.initialize()
    x = mx.nd.array(np.random.rand(2, 3).astype(np.float32))
    states = cell.begin_state(2)
    out_imp, st_imp = cell(x, states)
    cell.hybridize()
    out_hyb, st_hyb = cell(x, states)
    np.testing.assert_allclose(out_imp.asnumpy(), out_hyb.asnumpy(),
                               rtol=1e-5, atol=1e-6)
    assert len(st_hyb) == 2
    np.testing.assert_allclose(st_imp[1].asnumpy(), st_hyb[1].asnumpy(),
                               rtol=1e-5, atol=1e-6)


def test_block_attr_replacement():
    net = nn.HybridSequential()
    net.fc = nn.Dense(3)
    net.fc = nn.Dense(5)
    assert len(net._children) == 1
    assert net._children[0]._units == 5
