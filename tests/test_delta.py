"""Weight-delta channel tests (mxnet_tpu/delta.py, PERF round 22):
the move-only-what-changed layer across its three consumers.

* core: versioned delta format — touched-rows COO for tables, raw /
  int8-with-error-feedback for dense params — with typed chain gates
  (DeltaChainError / DeltaParityError) that mutate NOTHING on refusal.
* elastic: CheckpointManager(incremental=K) delta commits between full
  bases, bit-exact chain-replay resume (params AND optimizer state),
  torn-delta fallback to the newest intact prefix, chain-aware
  retention that never reaps a base referenced by a retained delta,
  chain replay across a virtual dp-width change.
* serving/fleet: InferenceEngine.apply_delta bitwise vs full reload at
  zero re-warm compiles, ModelRegistry paged-image deltas, the replica
  `:delta` admin op with its typed 409 refusal, the pusher's delta
  channel (chain advances only on promote; fingerprint mismatch falls
  back to a full push and the next promote rebases), and the
  LrBackoff on_verdict hook that turns consecutive rollbacks into a
  learning-rate cut instead of a RollbackStop.
"""
import json
import os
import time

import numpy as np
import pytest

import mxnet_tpu as mx
from mxnet_tpu import elastic, model as model_mod, nd, profiler
from mxnet_tpu import delta as delta_mod
from mxnet_tpu import sym as S
from mxnet_tpu import fleet_supervisor as fs
from mxnet_tpu.base import MXNetError
from mxnet_tpu.delta import (DeltaChainError, DeltaConfig,
                             DeltaParityError, apply_delta,
                             fingerprint, make_delta)
from mxnet_tpu.fleet_supervisor import (CheckpointPusher,
                                        FleetSupervisor, PushVerdict,
                                        ReplicaServer)
from mxnet_tpu.predictor import Predictor
from mxnet_tpu.serving import InferenceEngine

DIM, HID, OUT = 6, 8, 3


# ---------------------------------------------------------------------------
# fixtures
# ---------------------------------------------------------------------------

def _head(hid=HID):
    data = S.Variable('data')
    fc1 = S.FullyConnected(data, num_hidden=hid, name='fc1')
    act = S.Activation(fc1, act_type='relu')
    return S.FullyConnected(act, num_hidden=OUT, name='fc2')


def _module(seed=3, momentum=0.9):
    net = S.SoftmaxOutput(_head(), name='softmax')
    mod = mx.mod.Module(net)
    mod.bind(data_shapes=[mx.io.DataDesc('data', (4, DIM))],
             label_shapes=[mx.io.DataDesc('softmax_label', (4,))])
    mx.random.seed(seed)
    mod.init_params(initializer=mx.init.Xavier())
    mod.init_optimizer(optimizer='sgd',
                       optimizer_params={'learning_rate': 0.1,
                                         'momentum': momentum})
    return mod


def _batches(n, seed=0):
    rng = np.random.RandomState(seed)
    return [mx.io.DataBatch(
        data=[mx.nd.array(rng.rand(4, DIM).astype(np.float32))],
        label=[mx.nd.array((rng.rand(4) * OUT).astype(np.float32))])
        for _ in range(n)]


def _train(mod, batches):
    for b in batches:
        mod.forward_backward(b)
        mod.update()


def _state(seed=0, rows=64):
    rs = np.random.RandomState(seed)
    return {
        'arg:table': rs.randn(rows, 8).astype(np.float32),
        'arg:w': rs.randn(32, 16).astype(np.float32),
        'arg:b': rs.randn(16).astype(np.float32),
        'aux:m': rs.randn(4).astype(np.float32),
    }


def _frozen(state):
    return {n: a.copy() for n, a in state.items()}


def _assert_unchanged(state, frozen):
    for n in frozen:
        np.testing.assert_array_equal(state[n], frozen[n], err_msg=n)


def _wait(pred, timeout=60, msg='condition'):
    deadline = time.time() + timeout
    while time.time() < deadline:
        if pred():
            return
        time.sleep(0.02)
    raise AssertionError('timed out waiting for %s' % msg)


# ---------------------------------------------------------------------------
# core format: kinds, bitwise roundtrip, error feedback
# ---------------------------------------------------------------------------

def test_make_apply_roundtrip_kinds_and_bitwise():
    base = _state(0)
    rs = np.random.RandomState(1)
    cur = _frozen(base)
    cur['arg:table'][rs.choice(64, 5, replace=False)] += \
        rs.randn(5, 8).astype(np.float32)
    cur['arg:b'] += rs.randn(16).astype(np.float32) * 0.1
    # arg:w and aux:m untouched -> must be OMITTED from the payload
    cfg = DeltaConfig(dense='raw', min_dense=1)
    entries, meta, new_state = make_delta(
        base, cur, seq=1, base_fp=fingerprint(base), config=cfg)
    kinds = {n: e['kind'] for n, e in meta['entries'].items()}
    assert kinds['arg:table'] == 'rows'
    assert 'arg:w' not in kinds and 'aux:m' not in kinds
    assert meta['seq'] == 1 and meta['base_fp'] == fingerprint(base)
    assert 0 < meta['bytes'] < meta['full_bytes']
    out = apply_delta(base, meta, dict(entries),
                      expect_fp=fingerprint(base), expect_seq=1)
    for n in cur:
        np.testing.assert_array_equal(out[n], cur[n], err_msg=n)
    assert fingerprint(out) == meta['new_fp']
    # the encoder's resident new_state is the SAME state the applier
    # lands on (the chain both sides walk)
    for n in cur:
        np.testing.assert_array_equal(new_state[n], out[n], err_msg=n)


def test_int8_dense_delta_error_feedback_and_parity_meta():
    base = _state(2)
    rs = np.random.RandomState(3)
    cur = _frozen(base)
    cur['arg:w'] += rs.randn(32, 16).astype(np.float32) * 0.05
    cfg = DeltaConfig(dense='int8', min_dense=1, sparse_frac=0.0)
    entries, meta, new_state = make_delta(
        base, cur, seq=1, base_fp=fingerprint(base), config=cfg)
    assert meta['entries']['arg:w']['kind'] == 'int8'
    assert meta['rel_err'] > 0           # random diffs never exact
    out = apply_delta(base, meta, dict(entries),
                      expect_fp=fingerprint(base))
    # bit-identical to the ENCODER's resident state (base + dequant),
    # close to the true target (the int8 quantization error)
    np.testing.assert_array_equal(out['arg:w'], new_state['arg:w'])
    rel = np.abs(out['arg:w'] - cur['arg:w']).max() / \
        np.abs(cur['arg:w']).max()
    assert rel < 0.01


def test_typed_gates_refuse_with_nothing_mutated():
    base = _state(4)
    rs = np.random.RandomState(5)
    cur = _frozen(base)
    cur['arg:table'][:3] += rs.randn(3, 8).astype(np.float32)
    cfg = DeltaConfig(dense='raw', min_dense=1)
    entries, meta, _ = make_delta(base, cur, seq=2,
                                  base_fp=fingerprint(base),
                                  config=cfg)
    frozen = _frozen(base)
    with pytest.raises(DeltaChainError, match='fingerprint'):
        apply_delta(base, meta, dict(entries),
                    expect_fp='deadbeefdeadbeef')
    with pytest.raises(DeltaChainError, match='seq'):
        apply_delta(base, meta, dict(entries),
                    expect_fp=fingerprint(base), expect_seq=7)
    # corrupt payload bytes -> per-entry crc gate
    bad = dict(entries)
    key = [k for k in bad if k.startswith('drows:')][0]
    bad[key] = np.asarray(bad[key]).copy()
    bad[key].ravel()[0] += 1.0
    with pytest.raises(DeltaChainError, match='crc'):
        apply_delta(base, meta, bad, expect_fp=fingerprint(base))
    # parity gate on a lossy dense delta
    cur2 = _frozen(base)
    cur2['arg:w'] += rs.randn(32, 16).astype(np.float32) * 0.05
    e2, m2, _ = make_delta(base, cur2, seq=1,
                           base_fp=fingerprint(base),
                           config=DeltaConfig(dense='int8',
                                              min_dense=1,
                                              sparse_frac=0.0))
    with pytest.raises(DeltaParityError):
        apply_delta(base, m2, dict(e2), expect_fp=fingerprint(base),
                    parity_tol=1e-12)
    _assert_unchanged(base, frozen)       # every refusal staged first


def test_shape_or_nameset_change_needs_rebase():
    base = _state(6)
    cur = _frozen(base)
    cur['arg:w'] = np.zeros((8, 8), np.float32)        # shape change
    with pytest.raises(MXNetError):
        make_delta(base, cur, seq=1, base_fp=fingerprint(base))
    cur2 = _frozen(base)
    del cur2['arg:b']                                  # name-set change
    with pytest.raises(MXNetError):
        make_delta(base, cur2, seq=1, base_fp=fingerprint(base))


# ---------------------------------------------------------------------------
# elastic: incremental commits, chain replay, fallback, retention
# ---------------------------------------------------------------------------

def test_incremental_layout_and_chain_resume_bit_parity(tmp_path):
    """K delta commits between full bases; resuming from the chain
    TAIL replays base + deltas and lands bit-identical (params and
    momentum — the default delta_config keeps dense diffs raw)."""
    profiler.clear()
    mod = _module()
    mgr = elastic.CheckpointManager(str(tmp_path), every_n_steps=1,
                                    async_=False, incremental=3)
    mgr.attach(mod)
    for b in _batches(6):
        mod.forward_backward(b)
        mod.update()
        mgr.step_end()
    # commits 1..6 with incremental=3: fulls at 1 and 5, deltas else
    assert elastic.list_checkpoints(str(tmp_path)) == [5, 1]
    assert elastic.list_deltas(str(tmp_path)) == [6, 4, 3, 2]
    st = profiler.delta_stats()
    assert st['delta_committed'] == 4
    # tiny fully-dense model: every array moves every step, so the
    # raw-exact deltas carry ~full bytes — the byte WIN is measured on
    # the embedding workload (BENCH_DELTA); here the contract is the
    # chain replay, not the ratio
    assert 0 < st['delta_bytes'] <= st['delta_full_bytes']
    # newest intact is the chain tail; replay == live module, bitwise
    man, arrays, tail = elastic.load_newest_intact(str(tmp_path))
    assert os.path.basename(tail).startswith('delta-')
    assert man['step'] == 6
    pa, aa = mod.get_params()
    for n in pa:
        np.testing.assert_array_equal(arrays['param:%s' % n],
                                      pa[n].asnumpy(), err_msg=n)
    # full restore into a twin: params AND optimizer state bit-equal
    twin = _module(seed=9)
    info = elastic.CheckpointManager(str(tmp_path)).attach(twin) \
        .restore()
    assert info is not None and info.step == 6
    pb, _ = twin.get_params()
    for n in pa:
        np.testing.assert_array_equal(pa[n].asnumpy(),
                                      pb[n].asnumpy(), err_msg=n)
    import pickle
    sa = pickle.loads(mod._fused_updater.get_states())[0]
    sb = pickle.loads(twin._fused_updater.get_states())[0]
    assert sorted(sa) == sorted(sb)
    for k in sa:
        np.testing.assert_array_equal(np.asarray(sa[k]),
                                      np.asarray(sb[k]), err_msg=str(k))
    mgr.close()


def test_torn_delta_falls_back_to_newest_intact_prefix(tmp_path,
                                                       monkeypatch):
    profiler.clear()
    mod = _module()
    mgr = elastic.CheckpointManager(str(tmp_path), every_n_steps=1,
                                    async_=False, incremental=4)
    mgr.attach(mod)
    for i, b in enumerate(_batches(4)):
        mod.forward_backward(b)
        mod.update()
        if i == 3:
            # crash mid-write on the LAST delta commit
            monkeypatch.setenv('MXNET_TPU_FAULT_TORN_CKPT', '1')
        mgr.step_end()
    monkeypatch.delenv('MXNET_TPU_FAULT_TORN_CKPT')
    # chain: full-1, delta-2, delta-3, delta-4(torn).  Every chain
    # prefix is itself a committed checkpoint -> fall back to delta-3
    res = elastic.load_newest_intact(str(tmp_path))
    assert res is not None and res[0]['step'] == 3
    assert os.path.basename(res[2]).startswith('delta-')
    assert profiler.delta_stats()['delta_fallbacks'] >= 1
    mgr.close()


def test_chain_aware_retention_never_orphans_a_base(tmp_path):
    """Regression (satellite): keep-last-K counted only full dirs
    once, letting a base slide out while deltas chained on it were
    retained — every survivor must replay end-to-end after pruning,
    and a retain_refs pin (the fleet's in-flight push) holds its
    whole chain."""
    pinned = {2}
    mod = _module()
    mgr = elastic.CheckpointManager(str(tmp_path), every_n_steps=1,
                                    async_=False, incremental=2,
                                    keep=2)
    mgr.retain_refs = lambda: pinned
    mgr.attach(mod)
    for b in _batches(8):
        mod.forward_backward(b)
        mod.update()
        mgr.step_end()
    fulls = elastic.list_checkpoints(str(tmp_path))
    deltas = elastic.list_deltas(str(tmp_path))
    # the pinned delta-2 survived retention, and so did its base
    assert 2 in deltas and 1 in fulls
    # EVERY surviving commit (either kind) must load end-to-end —
    # chain-aware pruning may never leave an unloadable delta behind
    for s in deltas:
        man, arrays = elastic.load_state(
            os.path.join(str(tmp_path), 'delta-%08d' % s))
        assert man['step'] == s and arrays
    # dropping the pin lets the old chain go at the next commit
    pinned.clear()
    _train(mod, _batches(1, seed=9))
    mgr.step_end()
    assert 2 not in elastic.list_deltas(str(tmp_path))
    mgr.close()


def test_abandoned_writer_chain_resumes_and_prunes(tmp_path):
    """SIGKILL-mid-chain shape: a writer dies (no close) with a live
    chain; a NEW manager in the same dir resumes from the tail,
    starts a FRESH full base (the dead writer's resident chain state
    is gone), and retention with the old chain present stays safe."""
    mod = _module()
    mgr = elastic.CheckpointManager(str(tmp_path), every_n_steps=1,
                                    async_=False, incremental=3)
    mgr.attach(mod)
    for b in _batches(3):
        mod.forward_backward(b)
        mod.update()
        mgr.step_end()
    del mgr                      # abandoned: no close(), like SIGKILL
    twin = _module(seed=9)
    mgr2 = elastic.CheckpointManager(str(tmp_path), every_n_steps=1,
                                     async_=False, incremental=3,
                                     keep=2)
    mgr2.attach(twin)
    info = mgr2.restore()
    assert info is not None and info.step == 3
    pa, _ = mod.get_params()
    pb, _ = twin.get_params()
    for n in pa:
        np.testing.assert_array_equal(pa[n].asnumpy(),
                                      pb[n].asnumpy(), err_msg=n)
    # post-resume commits start a fresh FULL base (step 4), then chain
    for b in _batches(2, seed=7):
        twin.forward_backward(b)
        twin.update()
        mgr2.step_end()
    assert 4 in elastic.list_checkpoints(str(tmp_path))
    assert 5 in elastic.list_deltas(str(tmp_path))
    man, _arr, tail = elastic.load_newest_intact(str(tmp_path))
    assert man['step'] == 5
    mgr2.close()


def test_chain_replay_across_virtual_dp_width_change(tmp_path):
    """Satellite: a chain written under a world=2 manager (full base
    sharded into per-rank files) resumes bit-exactly through a
    world=1 manager — delta replay is mode-portable like full
    checkpoints."""
    mod = _module()
    mgr = elastic.CheckpointManager(str(tmp_path), every_n_steps=1,
                                    async_=False, incremental=2,
                                    world=2)
    mgr.attach(mod)
    for b in _batches(3):
        mod.forward_backward(b)
        mod.update()
        mgr.step_end()
    assert elastic.list_deltas(str(tmp_path)) == [3, 2]
    twin = _module(seed=11)
    info = elastic.CheckpointManager(str(tmp_path), world=1) \
        .attach(twin).restore()
    assert info is not None and info.step == 3
    pa, _ = mod.get_params()
    pb, _ = twin.get_params()
    for n in pa:
        np.testing.assert_array_equal(pa[n].asnumpy(),
                                      pb[n].asnumpy(), err_msg=n)
    mgr.close()


# ---------------------------------------------------------------------------
# serving: engine + registry deltas
# ---------------------------------------------------------------------------

def _save_ckpt(tmp_path, name='m0', hid=64, seed=3):
    net = S.SoftmaxOutput(_head(hid=hid), name='softmax')
    mod = mx.mod.Module(net)
    mod.bind(data_shapes=[mx.io.DataDesc('data', (4, DIM))],
             label_shapes=[mx.io.DataDesc('softmax_label', (4,))])
    mx.random.seed(seed)
    mod.init_params(initializer=mx.init.Xavier())
    args, auxs = mod.get_params()
    prefix = os.path.join(str(tmp_path), name)
    model_mod.save_checkpoint(prefix, 0, _head(hid=hid),
                              {n: a for n, a in args.items()}, auxs)
    return prefix


def test_engine_apply_delta_bitwise_and_typed_refusals(tmp_path):
    from mxnet_tpu import exec_cache
    profiler.clear()
    prefix = _save_ckpt(tmp_path)
    eng = InferenceEngine(
        Predictor.from_checkpoint(prefix, 0, {'data': (1, DIM)}),
        max_batch=1, max_wait_us=0)
    x = np.random.RandomState(0).randn(1, DIM).astype(np.float32)
    eng.predict(x)                       # warm every program
    rs = np.random.RandomState(1)

    def ref_out(state):
        args = {k[4:]: nd.array(v) for k, v in state.items()
                if k.startswith('arg:')}
        auxs = {k[4:]: nd.array(v) for k, v in state.items()
                if k.startswith('aux:')}
        ref = Predictor(symbol=_head(hid=64), arg_params=args,
                        aux_params=auxs,
                        input_shapes={'data': (1, DIM)})
        return ref.forward(data=nd.array(x))[0].asnumpy()

    # mixed sparse+raw delta -> BITWISE parity with a full reload, at
    # zero new compiles
    base = eng._resident_host_state()
    new = {n: a.copy() for n, a in base.items()}
    new['arg:fc1_weight'][rs.choice(64, 4, replace=False)] += \
        rs.randn(4, DIM).astype(np.float32) * 0.1
    new['arg:fc2_bias'] += rs.randn(OUT).astype(np.float32) * 0.1
    ent, meta, _ = make_delta(base, new, seq=1,
                              base_fp=fingerprint(base),
                              config=DeltaConfig(dense='raw',
                                                 min_dense=1))
    assert meta['entries']['arg:fc1_weight']['kind'] == 'rows'
    c0 = exec_cache.stats()['total_compile_s']
    fp = eng.apply_delta(dict(ent), meta,
                         expect_fp=fingerprint(base))
    assert fp == meta['new_fp']
    assert exec_cache.stats()['total_compile_s'] == c0
    np.testing.assert_array_equal(np.asarray(eng.predict(x)),
                                  ref_out(new))
    assert profiler.delta_stats()['delta_applied'] >= 1

    # chain gate: the delta's base_fp no longer matches the resident
    # state (it already advanced) -> typed refusal, nothing mutated
    before = np.asarray(eng.predict(x)).copy()
    with pytest.raises(DeltaChainError, match='fingerprint'):
        eng.apply_delta(dict(ent), meta,
                        expect_fp=fingerprint(
                            eng._resident_host_state()))
    np.testing.assert_array_equal(np.asarray(eng.predict(x)), before)

    # parity gate on a lossy int8 delta: tight tol refuses typed with
    # NOTHING mutated; the default tol applies
    base2 = eng._resident_host_state()
    new2 = {n: a.copy() for n, a in base2.items()}
    new2['arg:fc2_weight'] += \
        rs.randn(OUT, 64).astype(np.float32) * 0.05
    e2, m2, _ = make_delta(base2, new2, seq=1,
                           base_fp=fingerprint(base2),
                           config=DeltaConfig(dense='int8',
                                              min_dense=1,
                                              sparse_frac=0.0))
    assert m2['entries']['arg:fc2_weight']['kind'] == 'int8'
    with pytest.raises(DeltaParityError):
        eng.apply_delta(dict(e2), m2, expect_fp=fingerprint(base2),
                        parity_tol=1e-12)
    np.testing.assert_array_equal(np.asarray(eng.predict(x)), before)
    assert profiler.delta_stats()['delta_parity_refusals'] >= 1
    eng.apply_delta(dict(e2), m2, expect_fp=fingerprint(base2))
    assert not np.array_equal(np.asarray(eng.predict(x)), before)
    eng.close()


def test_registry_delta_resident_and_paged_image(tmp_path):
    from mxnet_tpu.serving_fleet import ModelRegistry
    profiler.clear()
    prefix = _save_ckpt(tmp_path)
    x = np.random.RandomState(0).randn(1, DIM).astype(np.float32)
    reg = ModelRegistry()
    reg.register('p', prefix=prefix, epoch=0,
                 input_shapes={'data': (1, DIM)}, max_batch=1,
                 max_wait_us=0, page_dtype='int8')
    y0 = np.asarray(reg.predict('p', x)).copy()
    # resident path: in-place engine delta
    eng = reg.engine('p')
    base = eng._resident_host_state()
    rs = np.random.RandomState(2)
    new = {n: a.copy() for n, a in base.items()}
    new['arg:fc1_weight'][rs.choice(64, 4, replace=False)] += \
        rs.randn(4, DIM).astype(np.float32) * 0.2
    ent, meta, _ = make_delta(base, new, seq=1,
                              base_fp=fingerprint(base),
                              config=DeltaConfig(dense='raw',
                                                 min_dense=1))
    reg.apply_delta('p', dict(ent), meta,
                    expect_fp=fingerprint(base))
    y1 = np.asarray(reg.predict('p', x)).copy()
    assert not np.array_equal(y1, y0)
    # paged path: evict to the quantized host image, delta the IMAGE,
    # and the next page-in already reflects the push
    reg.evict('p')
    assert reg.stats()['models']['p']['paged']
    new2 = {n: a.copy() for n, a in new.items()}
    new2['arg:fc1_weight'][rs.choice(64, 4, replace=False)] += \
        rs.randn(4, DIM).astype(np.float32) * 0.2
    e2, m2, _ = make_delta(new, new2, seq=2, base_fp=meta['new_fp'],
                           config=DeltaConfig(dense='raw',
                                              min_dense=1))
    reg.apply_delta('p', dict(e2), m2)     # lossy image: no expect_fp
    assert profiler.delta_stats()['delta_page_applies'] >= 1
    y2 = np.asarray(reg.predict('p', x))   # page-in from the image
    assert reg.stats()['page_ins'] >= 1
    # int8 image roundtrip is lossy but must track the delta's target
    ref = Predictor(symbol=_head(hid=64),
                    arg_params={k[4:]: nd.array(v)
                                for k, v in new2.items()
                                if k.startswith('arg:')},
                    aux_params={k[4:]: nd.array(v)
                                for k, v in new2.items()
                                if k.startswith('aux:')},
                    input_shapes={'data': (1, DIM)})
    want = ref.forward(data=nd.array(x))[0].asnumpy()
    assert np.abs(y2 - want).max() < 0.05
    # a model that is neither resident nor imaged refuses typed
    reg.register('bare', prefix=prefix, epoch=0,
                 input_shapes={'data': (1, DIM)}, max_batch=1,
                 max_wait_us=0)
    with pytest.raises(MXNetError, match='neither resident'):
        reg.apply_delta('bare', dict(ent), meta)
    reg.close()


# ---------------------------------------------------------------------------
# fleet: replica :delta op, pusher delta channel, fallback + rebase
# ---------------------------------------------------------------------------

def _perturb(mod, seed, scale=0.05):
    rs = np.random.RandomState(seed)
    args, auxs = mod.get_params()
    new = {n: nd.array(a.asnumpy() +
                       rs.randn(*a.shape).astype(np.float32) * scale)
           for n, a in args.items()}
    mod.set_params(new, auxs)


def test_pusher_delta_channel_end_to_end(tmp_path):
    """Full push -> promote commits the chain base; the next push
    ships a DELTA the replica applies onto its resident arm (bitwise
    vs a full reload of the same export); a tampered chain draws the
    replica's typed 409 and the pusher falls back to a FULL push whose
    promote rebases the chain to seq 0."""
    profiler.clear()
    mod = _module(momentum=0.0)
    prefix0 = _save_ckpt(tmp_path, name='stable', hid=HID)
    spec = {'name': 'm', 'prefix': prefix0, 'epoch': 0,
            'input_shapes': {'data': [1, DIM]},
            'max_batch': 4, 'max_wait_us': 0}
    live = ReplicaServer(models=[spec], index=0).start()
    sup = FleetSupervisor(models=[spec], replicas=1)
    rep = fs._Replica(0)
    rep.host, rep.port = live.address
    sup._replicas = [rep]
    pusher = CheckpointPusher(sup, 'm', symbol=_head(),
                              push_dir=str(tmp_path / 'push'),
                              delta=True, delta_rebase=8)
    mgr = pusher.attach(elastic.CheckpointManager(
        str(tmp_path / 'ck'), every_n_steps=1))
    mgr.attach(mod)
    try:
        # push 1: no promoted base yet -> full
        mgr.step_end()
        mgr.wait()
        _wait(lambda: profiler.loop_stats()['loop_pushes'] == 1,
              msg='push 1')
        cand1 = [n for n in live.registry.models() if '@' in n][0]
        assert profiler.delta_stats()['delta_pushes'] == 0
        sup._on_router_event('promote', 'm', {'candidate': cand1,
                                              'report': None})
        _wait(lambda: pusher._base is not None, msg='chain base')
        assert pusher._base['seq'] == 0

        # push 2: delta ships; replica builds the candidate from its
        # RESIDENT arm + payload, bitwise vs full reload
        _perturb(mod, seed=11)
        mgr.step_end()
        mgr.wait()
        _wait(lambda: profiler.loop_stats()['loop_pushes'] == 2,
              msg='push 2')
        st = profiler.delta_stats()
        assert st['delta_pushes'] == 1
        assert st['delta_applied'] >= 1
        assert 0 < st['delta_bytes'] < st['delta_full_bytes']
        cand2 = sorted(n for n in live.registry.models()
                       if '@' in n)[-1]
        _s, fargs, fauxs = model_mod.load_checkpoint(
            str(tmp_path / 'push' / ('push-%08d' % 2)), 0)
        ref = Predictor(symbol=_head(), arg_params=fargs,
                        aux_params=fauxs,
                        input_shapes={'data': (1, DIM)})
        x = np.random.RandomState(0).randn(1, DIM) \
            .astype(np.float32)
        np.testing.assert_array_equal(
            np.asarray(live.registry.engine(cand2).predict(x)),
            ref.forward(data=nd.array(x))[0].asnumpy())
        sup._on_router_event('promote', 'm', {'candidate': cand2,
                                              'report': None})
        _wait(lambda: pusher._base is not None and
              pusher._base['seq'] == 1, msg='chain seq 1')

        # push 3: tampered chain -> 409 -> full-push fallback; the
        # fallback's promote REBASES the chain
        _perturb(mod, seed=13)
        with pusher._lock:
            pusher._base['fp'] = 'deadbeefdeadbeef'
        mgr.step_end()
        mgr.wait()
        _wait(lambda: profiler.loop_stats()['loop_pushes'] == 3,
              msg='push 3')
        st = profiler.delta_stats()
        assert st['delta_push_fallbacks'] == 1
        assert st['delta_pushes'] == 1           # fallback went FULL
        cand3 = sorted(n for n in live.registry.models()
                       if '@' in n)[-1]
        assert cand3 != cand2
        sup._on_router_event('promote', 'm', {'candidate': cand3,
                                              'report': None})
        _wait(lambda: pusher._base is not None and
              pusher._base['seq'] == 0, msg='chain rebased')
    finally:
        pusher.close()
        mgr.close()
        sup.router.close()
        live.close()


# ---------------------------------------------------------------------------
# verdict hook: LrBackoff instead of RollbackStop
# ---------------------------------------------------------------------------

class _StubSupervisor(object):
    """Scripted fleet (the test_train_serve_loop stub): push()
    accepts; verdicts fire on demand through on_push_verdict."""

    def __init__(self):
        self.pushes = []
        self._cbs = []
        self._seq = 0
        self._active = set()

    def on_push_verdict(self, cb):
        self._cbs.append(cb)
        return self

    def push_active(self, name):
        return name in self._active

    def active_prefixes(self, name):
        return set()

    def push(self, name, prefix, epoch=0, frac=None, mode='canary',
             tag=None):
        self._seq += 1
        cand = '%s@v%d' % (name, self._seq)
        self.pushes.append((name, prefix, cand))
        self._active.add(name)
        return cand

    def decide(self, kind, cand, model='m'):
        self._active.discard(model)
        v = PushVerdict(kind, model, cand)
        for cb in self._cbs:
            cb(v)
        return v


def test_lr_backoff_hook_replaces_rollback_stop(tmp_path):
    """Satellite: with an on_verdict hook installed the pusher's
    consecutive-rollback limit does NOT stop training — LrBackoff
    owns the response and cuts the lr every `after` rollbacks."""
    profiler.clear()
    sup = _StubSupervisor()
    mod = _module()
    pusher = CheckpointPusher(sup, 'm', symbol=_head(),
                              push_dir=str(tmp_path / 'push'),
                              max_consecutive_rollbacks=2)
    mgr = pusher.attach(elastic.CheckpointManager(
        str(tmp_path / 'ck'), every_n_steps=1))
    mgr.attach(mod)
    lb = elastic.LrBackoff(mgr, factor=0.5, after=2)
    assert mgr.on_verdict is lb
    opt = lb._optimizer()
    assert opt is not None and opt.lr == pytest.approx(0.1)
    for i in range(4):
        mgr.step_end()                    # commit -> push
        mgr.wait()
        _wait(lambda: len(sup.pushes) == i + 1, msg='push %d' % i)
        sup.decide('rolled_back', sup.pushes[-1][2])
        _wait(lambda: len(pusher.verdicts()) == i + 1,
              msg='verdict %d' % i)
    assert pusher.consecutive_rollbacks == 4
    # past max_consecutive_rollbacks=2, but the hook owns it: the next
    # step boundary must NOT raise RollbackStop...
    mgr.step_end()
    mgr.wait()
    _wait(lambda: len(sup.pushes) == 5, msg='push 5')
    # ...and the lr was cut at streaks 2 and 4 (0.1 -> 0.05 -> 0.025)
    assert lb.backoffs == 2
    assert opt.lr == pytest.approx(0.025)
    assert profiler.loop_stats()['loop_lr_backoffs'] == 2
    # never below min_lr; a promote resets the streak
    sup.decide('promoted', sup.pushes[-1][2])
    _wait(lambda: pusher.consecutive_rollbacks == 0, msg='reset')
    pusher.close()
    mgr.close()


# ---------------------------------------------------------------------------
# observability: counters in summary + dump lane
# ---------------------------------------------------------------------------

def test_delta_counters_in_summary_and_dump(tmp_path):
    profiler.clear()
    profiler.add_delta_stats(committed=2, applied=1, bytes=100,
                             full_bytes=1000, chain_len=2, pushes=1,
                             parity_refusals=1)
    text = profiler.summary(print_out=False)
    assert 'delta_committed=2' in text
    assert 'delta_parity_refusals=1' in text
    fname = str(tmp_path / 'prof.json')
    profiler.profiler_set_config(mode='symbolic', filename=fname)
    path = profiler.dump_profile()
    lane = [e for e in json.load(open(path))['traceEvents']
            if e.get('name') == 'delta']
    assert lane and lane[0]['args']['delta_committed'] == 2
    assert lane[0]['args']['delta_chain_len'] == 2
