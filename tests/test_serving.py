"""Dynamic-batching inference engine tests (serving.InferenceEngine).

Covers the ISSUE-4 serving contract: batcher coalescing under
concurrency, bucket padding/slicing bit-parity against serial
Predictor.forward, zero-compile steady state (exec_cache counters),
timeout flush of underfull batches, shutdown joining the worker
threads, and the new profiler serving counters surfacing in
summary() / dump_profile metadata.  All models are CPU-sized.
"""
import json
import threading
import time
import types

import numpy as np
import pytest

import mxnet_tpu as mx
from mxnet_tpu import exec_cache, nd, profiler, sym
from mxnet_tpu.base import MXNetError
from mxnet_tpu.predictor import Predictor
from mxnet_tpu.serving import InferenceEngine

DIM = 6
HID = 8
OUT = 3


def _mlp():
    data = sym.Variable('data')
    fc1 = sym.FullyConnected(data, num_hidden=HID, name='fc1')
    act = sym.Activation(fc1, act_type='relu')
    return sym.FullyConnected(act, num_hidden=OUT, name='fc2')


def _params(seed=7):
    rs = np.random.RandomState(seed)
    return {
        'fc1_weight': nd.array(rs.randn(HID, DIM).astype(np.float32) * .5),
        'fc1_bias': nd.array(rs.randn(HID).astype(np.float32) * .1),
        'fc2_weight': nd.array(rs.randn(OUT, HID).astype(np.float32) * .5),
        'fc2_bias': nd.array(rs.randn(OUT).astype(np.float32) * .1),
    }


def _predictor(batch=1):
    return Predictor(symbol=_mlp(), arg_params=_params(),
                     input_shapes={'data': (batch, DIM)})


def _x(rows, seed=0, dim=DIM):
    return np.random.RandomState(seed).randn(rows, dim).astype(np.float32)


# ---------------------------------------------------------------------------
# coalescing
# ---------------------------------------------------------------------------

def test_coalesces_concurrent_requests():
    # 8 single-row clients behind a barrier, batcher holding batches
    # open 300ms: they must merge into far fewer dispatches than 8
    with _predictor().serve(max_batch=8, max_wait_us=300000) as eng:
        barrier = threading.Barrier(8)
        outs = [None] * 8
        xs = [_x(1, seed=i) for i in range(8)]

        def client(i):
            barrier.wait()
            outs[i] = eng.infer(xs[i])

        ts = [threading.Thread(target=client, args=(i,)) for i in range(8)]
        for t in ts:
            t.start()
        for t in ts:
            t.join()
        st = eng.stats()
    assert st['requests'] == 8
    assert st['batches'] <= 3          # coalescing actually happened
    assert st['batch_fill_avg'] > 0.5
    for i in range(8):                  # everyone got *their* answer
        solo = _predictor(batch=1).forward(data=xs[i])[0].asnumpy()
        np.testing.assert_allclose(outs[i][0], solo, rtol=2e-6, atol=1e-6)


def test_oversized_request_splits():
    # rows > max_batch: split into max_batch chunks, re-concatenated
    with _predictor().serve(max_batch=4, max_wait_us=0) as eng:
        x = _x(11)
        out = eng.infer(x)[0]
    assert out.shape == (11, OUT)
    ref = _predictor(batch=11).forward(data=x)[0].asnumpy()
    np.testing.assert_allclose(out, ref, rtol=2e-6, atol=1e-6)


# ---------------------------------------------------------------------------
# padding / slicing parity
# ---------------------------------------------------------------------------

def test_full_bucket_bit_parity_vs_serial_forward():
    # a request that exactly fills its bucket runs the identical graph
    # as a serial Predictor.forward at that shape: bit-identical
    x = _x(8, seed=3)
    with _predictor().serve(max_batch=8, batch_buckets=(8,),
                            max_wait_us=0) as eng:
        got = eng.infer(x)[0]
    ref = _predictor(batch=8).forward(data=x)[0].asnumpy()
    assert np.array_equal(got, ref)


def test_padded_request_bit_parity_vs_padded_serial():
    # rows=3 padded up to the 4-bucket must equal manually padding to
    # 4, serial forward at (4, DIM), slicing 3 rows — bit-identical
    x = _x(3, seed=5)
    with _predictor().serve(max_batch=4, batch_buckets=(4,),
                            max_wait_us=0, pad_value=0.0) as eng:
        got = eng.infer(x)[0]
    assert got.shape == (3, OUT)
    xp = np.zeros((4, DIM), np.float32)
    xp[:3] = x
    ref = _predictor(batch=4).forward(data=xp)[0].asnumpy()[:3]
    assert np.array_equal(got, ref)


def test_cobatch_slicing_is_row_independent():
    # a request's rows must not depend on what it was co-batched with:
    # same request solo vs coalesced with another gives identical bits
    x_a = _x(2, seed=11)
    x_b = _x(2, seed=12)

    def run_pair(first, second):
        with _predictor().serve(max_batch=4, batch_buckets=(4,),
                                max_wait_us=300000) as eng:
            res = {}
            barrier = threading.Barrier(2)

            def client(name, arr):
                barrier.wait()
                res[name] = eng.infer(arr)[0]

            ts = [threading.Thread(target=client, args=('a', first)),
                  threading.Thread(target=client, args=('b', second))]
            for t in ts:
                t.start()
            for t in ts:
                t.join()
            return res

    together = run_pair(x_a, x_b)
    with _predictor().serve(max_batch=4, batch_buckets=(4,),
                            max_wait_us=0) as eng:
        solo = eng.infer(x_a)[0]
    assert np.array_equal(together['a'], solo)


def test_default_engine_requires_exact_free_dims():
    # without an explicit free_dim_buckets opt-in the engine keeps
    # the serial forward contract: a request narrower than the bound
    # width is REJECTED, not silently zero-padded (free-dim padding
    # parity is model-dependent — wrong for e.g. BatchNorm/softmax
    # over the padded axis); exact-width requests serve with full,
    # untruncated output dims even when a trailing output dim
    # coincidentally equals the input width
    data = sym.Variable('data')
    net = sym.FullyConnected(data, num_hidden=8, name='fc')
    rs = np.random.RandomState(2)
    params = {'fc_weight': nd.array(rs.randn(8, 8).astype(np.float32)),
              'fc_bias': nd.array(np.zeros(8, np.float32))}
    pred = Predictor(symbol=net, arg_params=params,
                     input_shapes={'data': (1, 8)})
    x = rs.randn(2, 8).astype(np.float32)
    with InferenceEngine(pred, max_batch=4, max_wait_us=0) as eng:
        with pytest.raises(MXNetError, match='free-dim padding'):
            eng.infer(rs.randn(2, 5).astype(np.float32))
        out = eng.infer(x)[0]
    assert out.shape == (2, 8)          # all 8 class scores survive
    ref = Predictor(symbol=net, arg_params=params,
                    input_shapes={'data': (2, 8)}).forward(
                        data=x)[0].asnumpy()
    np.testing.assert_allclose(out, ref, rtol=2e-6, atol=1e-6)


def test_free_dim_bucket_padding_and_slicing():
    # per-position model: free-dim padding must slice back to the
    # request's own extent with untouched real elements
    data = sym.Variable('data')
    net = sym.Activation(data, act_type='relu')
    pred = Predictor(symbol=net, arg_params={},
                     input_shapes={'data': (1, 8)})
    x = np.random.RandomState(0).randn(2, 5).astype(np.float32)
    with InferenceEngine(pred, max_batch=4,
                         free_dim_buckets=[((8,),), ((16,),)],
                         max_wait_us=0) as eng:
        out = eng.infer(x)[0]
    assert out.shape == (2, 5)
    assert np.array_equal(out, np.maximum(x, 0))
    with pytest.raises(MXNetError):
        # nothing on the ladder covers a 32-wide request
        with InferenceEngine(pred, max_batch=4,
                             free_dim_buckets=[((8,),), ((16,),)],
                             max_wait_us=0) as eng:
            eng.infer(np.zeros((1, 32), np.float32))


def test_free_dim_slicing_spares_fixed_output_dims():
    # two outputs: relu mirrors the padded input (slice back), while
    # slice_axis(0:8) is a FIXED 8-wide head that coincidentally
    # equals the 8-rung's bucket extent — the mirror mask (axes that
    # vary across rungs, shape-inferred) must slice the first and
    # spare the second
    data = sym.Variable('data')
    net = sym.Group([sym.Activation(data, act_type='relu'),
                     sym.slice_axis(data, axis=1, begin=0, end=8)])
    pred = Predictor(symbol=net, arg_params={},
                     input_shapes={'data': (1, 8)})
    x = np.random.RandomState(1).randn(2, 5).astype(np.float32)
    with InferenceEngine(pred, max_batch=4,
                         free_dim_buckets=[((8,),), ((16,),)],
                         max_wait_us=0) as eng:
        relu_out, head_out = eng.infer(x)
    assert relu_out.shape == (2, 5)
    assert np.array_equal(relu_out, np.maximum(x, 0))
    # the fixed head keeps its full 8 columns: 5 real + 3 pad zeros,
    # exactly what a serial forward on the padded input returns
    assert head_out.shape == (2, 8)
    xp = np.zeros((2, 8), np.float32)
    xp[:, :5] = x
    assert np.array_equal(head_out, xp)


def test_full_batch_in_other_group_preempts_held_deadline():
    # two free-dim rungs: a lone rung-A request holds the batcher on
    # a LONG deadline while rung B fills to max_batch — B must
    # dispatch promptly instead of idling out A's deadline
    data = sym.Variable('data')
    net = sym.Activation(data, act_type='relu')
    pred = Predictor(symbol=net, arg_params={},
                     input_shapes={'data': (1, 8)})
    with InferenceEngine(pred, max_batch=4,
                         free_dim_buckets=[((8,),), ((16,),)],
                         max_wait_us=30000000) as eng:
        t_a = threading.Thread(
            target=lambda: eng.infer(np.zeros((1, 8), np.float32)))
        t_a.start()
        deadline = time.time() + 10      # wait until A is queued/held
        while time.time() < deadline and \
                not any(eng._queues.values()):
            time.sleep(0.005)
        tic = time.perf_counter()
        done = []

        def b_client():
            done.append(eng.infer(np.zeros((1, 16), np.float32)))

        t_bs = [threading.Thread(target=b_client) for _ in range(4)]
        for t in t_bs:
            t.start()
        for t in t_bs:
            t.join(timeout=30)
        elapsed = time.perf_counter() - tic
        assert len(done) == 4
        # far below A's 30s deadline, with enough margin that this
        # rig's documented multi-second cpu-shares throttle bursts
        # cannot flake a correct preemption
        assert elapsed < 10, elapsed
        # close() drains the held rung-A request without its deadline
    t_a.join(timeout=30)
    assert not t_a.is_alive()


# ---------------------------------------------------------------------------
# zero-compile steady state
# ---------------------------------------------------------------------------

def test_zero_compiles_after_warmup():
    with _predictor().serve(max_batch=8, max_wait_us=0) as eng:
        for rows in (1, 2, 3, 5, 7, 8, 4, 6, 1, 8):
            eng.infer(_x(rows, seed=rows))
        st = eng.stats()
    assert st['compiles_after_warmup'] == 0
    assert st['compile_s_after_warmup'] == 0
    assert st['requests'] == 10


def test_recreated_engine_reuses_cached_programs():
    # an equivalent engine hits exec_cache for every ladder rung: its
    # construction (warmup included) triggers zero cache misses
    with _predictor().serve(max_batch=4, max_wait_us=0) as eng:
        eng.infer(_x(2))
    before = exec_cache.stats()['misses']
    with _predictor().serve(max_batch=4, max_wait_us=0) as eng:
        eng.infer(_x(2))
    assert exec_cache.stats()['misses'] == before


def test_late_warmup_on_live_engine():
    # warmup=False starts the workers immediately; a later warmup()
    # runs concurrently with live traffic — rung builds and cold
    # serve calls serialize on _prog_lock, so neither thread races
    # the other and the zero-compile snapshot still lands
    eng = _predictor().serve(max_batch=4, max_wait_us=0, warmup=False)
    try:
        errs = []

        def traffic():
            try:
                for i in range(10):
                    eng.infer(_x(1 + i % 4, seed=i))
            except Exception as e:      # surface in the main thread
                errs.append(e)

        t = threading.Thread(target=traffic)
        t.start()
        eng.warmup()
        t.join(timeout=60)
        assert not t.is_alive() and not errs, errs
        out = eng.infer(_x(2, seed=42))[0]
        assert eng.stats()['compiles_after_warmup'] == 0
    finally:
        eng.close()
    ref = _predictor(batch=2).forward(data=_x(2, seed=42))[0].asnumpy()
    np.testing.assert_allclose(out, ref, rtol=2e-6, atol=1e-6)


# ---------------------------------------------------------------------------
# timeout flush
# ---------------------------------------------------------------------------

def test_timeout_flushes_underfull_batch():
    # one lone request against max_batch=8 must still complete (after
    # ~max_wait_us), padded up to its bucket
    with _predictor().serve(max_batch=8, max_wait_us=2000) as eng:
        out = eng.infer(_x(1))
        st = eng.stats()
    assert out[0].shape == (1, OUT)
    assert st['batches'] == 1
    assert st['padded_rows'] == 0      # bucket ladder: 1 -> bucket 1
    with _predictor().serve(max_batch=8, batch_buckets=(8,),
                            max_wait_us=2000) as eng:
        eng.infer(_x(3))
        st = eng.stats()
    assert st['padded_rows'] == 5      # 3 rows padded to the 8-bucket
    assert st['pad_waste_frac'] == pytest.approx(5 / 8)


# ---------------------------------------------------------------------------
# lifecycle
# ---------------------------------------------------------------------------

def test_close_joins_workers_and_rejects_new_work():
    eng = _predictor().serve(max_batch=4, max_wait_us=0)
    eng.infer(_x(2))
    workers = [eng._dispatcher, eng._completer]
    eng.close()
    for t in workers:
        assert not t.is_alive()
    with pytest.raises(MXNetError):
        eng.infer(_x(1))
    eng.close()                        # idempotent


def test_close_drains_queued_requests():
    # requests enqueued before close() are answered, not dropped
    with _predictor().serve(max_batch=8, max_wait_us=100000) as eng:
        res = {}

        def client():
            res['out'] = eng.infer(_x(2))[0]

        t = threading.Thread(target=client)
        t.start()
        # wait until the request is actually enqueued (or already
        # answered) before close() flushes the held-open batch
        deadline = time.time() + 10
        while time.time() < deadline and 'out' not in res and \
                not any(eng._queues.values()):
            time.sleep(0.005)
    t.join(timeout=30)
    assert not t.is_alive()
    assert res['out'].shape == (2, OUT)


def test_multi_input_names_out_of_graph_order():
    # a Module's data_names order is caller-chosen and need not match
    # graph argument order: the serve program must bind each input by
    # NAME (regression: position-by-rank silently swapped a-b to b-a)
    av = np.full((1, 4), 5.0, np.float32)
    bv = np.full((1, 4), 2.0, np.float32)

    def engine(order):
        a = sym.Variable('a')
        b = sym.Variable('b')
        mod = mx.mod.Module(a - b, data_names=order, label_names=[])
        mod.bind(data_shapes=[(n, (1, 4)) for n in order],
                 for_training=False)
        mod.init_params()
        return InferenceEngine(mod, max_batch=2, max_wait_us=0)

    with engine(('b', 'a')) as eng:
        named = eng.infer(a=av, b=bv)[0]
        pos = eng.infer(bv, av)[0]      # positional = data_names order
    np.testing.assert_array_equal(named, av - bv)
    np.testing.assert_array_equal(pos, av - bv)
    # graph signatures alpha-rename names away: a SECOND engine over
    # the same graph with the other data_names order must not hit the
    # first engine's cached serve closure (input order is part of the
    # serve program's cache key)
    with engine(('a', 'b')) as eng:
        np.testing.assert_array_equal(eng.infer(a=av, b=bv)[0], av - bv)
        np.testing.assert_array_equal(eng.infer(av, bv)[0], av - bv)


def test_batch_reducing_model_rejected():
    # sum over all axes: each caller would receive the co-batched
    # (and pad-row) aggregate — warmup checks every output keeps the
    # bucket batch dim and refuses (same policy as the ctx_group
    # guard: silent wrong answers are worse than an error)
    data = sym.Variable('data')
    net = sym.sum(data)
    pred = Predictor(symbol=net, arg_params={},
                     input_shapes={'data': (1, 4)})
    with pytest.raises(MXNetError, match='row-independent'):
        InferenceEngine(pred, max_batch=4, max_wait_us=0)


def test_model_parallel_source_rejected():
    # rung executors rebind WITHOUT group2ctx, so a ctx_group
    # (model-parallel) source would silently collapse its placement
    # onto one device — the engine must refuse instead
    with mx.AttrScope(ctx_group='dev1'):
        data = sym.Variable('data')
        fc1 = sym.FullyConnected(data, num_hidden=4, name='fc1')
    with mx.AttrScope(ctx_group='dev2'):
        net = sym.FullyConnected(fc1, num_hidden=2, name='fc2')
    ex = net.simple_bind(mx.cpu(0), grad_req='null', data=(2, 3),
                         group2ctx={'dev1': mx.cpu(0),
                                    'dev2': mx.cpu(1)})
    assert ex._grouped
    src = types.SimpleNamespace(_executor=ex, _symbol=net,
                                _ctx=mx.cpu(0), _input_names=['data'])
    with pytest.raises(MXNetError, match='ctx_group'):
        InferenceEngine(src, max_batch=2, max_wait_us=0)


def test_engine_over_module_source():
    # the engine also wraps a bound Module (forward only)
    mod = mx.mod.Module(_mlp(), label_names=[])
    mod.bind(data_shapes=[('data', (1, DIM))], for_training=False)
    mod.init_params()
    mod.set_params(_params(), {})
    x = _x(2, seed=9)
    with InferenceEngine(mod, max_batch=4, max_wait_us=0) as eng:
        out = eng.infer(x)[0]
    ref = _predictor(batch=2).forward(data=x)[0].asnumpy()
    np.testing.assert_allclose(out, ref, rtol=2e-6, atol=1e-6)


# ---------------------------------------------------------------------------
# profiler counters
# ---------------------------------------------------------------------------

def test_serving_counters_in_summary_and_dump(tmp_path):
    profiler.clear()
    with _predictor().serve(max_batch=4, max_wait_us=0) as eng:
        eng.infer(_x(3))
        eng.infer(_x(1))
    sv = profiler.serving_stats()
    assert sv['serve_requests'] >= 2
    assert sv['serve_batches'] >= 2
    assert sv['serve_latency_p50_ms'] > 0
    assert sv['serve_latency_p99_ms'] >= sv['serve_latency_p50_ms']
    assert 0 <= sv['serve_pad_waste_frac'] < 1
    text = profiler.summary(print_out=False)
    for key in ('serve_requests', 'serve_queue_depth_avg',
                'serve_batch_fill_avg', 'serve_pad_waste_frac',
                'serve_latency_p50_ms', 'serve_latency_p99_ms'):
        assert key in text
    out = tmp_path / 'serve_profile.json'
    profiler.profiler_set_config(filename=str(out))
    profiler.dump_profile()
    events = json.loads(out.read_text())['traceEvents']
    meta = [e for e in events if e.get('name') == 'serving']
    assert meta and meta[0]['args']['serve_requests'] >= 2
