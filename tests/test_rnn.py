"""mx.rnn symbolic cell tests (model: reference
tests/python/unittest/test_rnn.py) plus fused-RNN-op numerics."""
import numpy as np

import mxnet_tpu as mx
from mxnet_tpu import sym, nd


def test_rnn_cell_unroll_shapes():
    cell = mx.rnn.RNNCell(100, prefix='rnn_')
    inputs = [sym.Variable('rnn_t%d_data' % i) for i in range(3)]
    outputs, _ = cell.unroll(3, inputs)
    outputs = sym.Group(outputs)
    assert sorted(cell.params._params.keys()) == [
        'rnn_h2h_bias', 'rnn_h2h_weight', 'rnn_i2h_bias', 'rnn_i2h_weight']
    _, outs, _ = outputs.infer_shape(rnn_t0_data=(10, 50),
                                     rnn_t1_data=(10, 50),
                                     rnn_t2_data=(10, 50))
    assert outs == [(10, 100)] * 3


def test_lstm_cell_unroll_shapes():
    cell = mx.rnn.LSTMCell(100, prefix='rnn_', forget_bias=1.0)
    inputs = [sym.Variable('rnn_t%d_data' % i) for i in range(3)]
    outputs, _ = cell.unroll(3, inputs)
    outputs = sym.Group(outputs)
    assert sorted(cell.params._params.keys()) == [
        'rnn_h2h_bias', 'rnn_h2h_weight', 'rnn_i2h_bias', 'rnn_i2h_weight']
    _, outs, _ = outputs.infer_shape(rnn_t0_data=(10, 50),
                                     rnn_t1_data=(10, 50),
                                     rnn_t2_data=(10, 50))
    assert outs == [(10, 100)] * 3


def test_gru_and_residual_and_zoneout():
    cell = mx.rnn.ResidualCell(mx.rnn.GRUCell(50, prefix='gru_'))
    inputs = [sym.Variable('t%d_data' % i) for i in range(2)]
    outputs, _ = cell.unroll(2, inputs)
    outputs = sym.Group(outputs)
    _, outs, _ = outputs.infer_shape(t0_data=(10, 50), t1_data=(10, 50))
    assert outs == [(10, 50)] * 2

    cell = mx.rnn.ZoneoutCell(mx.rnn.RNNCell(100, prefix='rnn_'),
                              zoneout_outputs=0.5, zoneout_states=0.5)
    inputs = [sym.Variable('z%d_data' % i) for i in range(2)]
    outputs, _ = cell.unroll(2, inputs)
    outputs = sym.Group(outputs)
    _, outs, _ = outputs.infer_shape(z0_data=(10, 50), z1_data=(10, 50))
    assert outs == [(10, 100)] * 2


def test_stack_bidirectional_unroll():
    stack = mx.rnn.SequentialRNNCell()
    stack.add(mx.rnn.BidirectionalCell(
        mx.rnn.LSTMCell(16, prefix='l0_'),
        mx.rnn.LSTMCell(16, prefix='r0_'),
        output_prefix='bi_'))
    stack.add(mx.rnn.DropoutCell(0.5, prefix='drop_'))
    stack.add(mx.rnn.GRUCell(20, prefix='g1_'))
    data = sym.Variable('data')
    outputs, states = stack.unroll(4, data, layout='NTC',
                                   merge_outputs=True)
    _, outs, _ = outputs.infer_shape(data=(8, 4, 12))
    assert outs == [(8, 4, 20)]


def _np_lstm_ref(x, cells, h0, c0):
    """Single-layer LSTM with cuDNN gate order, numpy reference."""
    T, N, _ = x.shape
    H = h0.shape[-1]
    w_i2h, w_h2h, b_i2h, b_h2h = cells
    h, c = h0, c0
    outs = []
    sig = lambda v: 1.0 / (1.0 + np.exp(-v))
    for t in range(T):
        g = x[t] @ w_i2h.T + b_i2h + h @ w_h2h.T + b_h2h
        i, f, gg, o = np.split(g, 4, axis=-1)
        c = sig(f) * c + sig(i) * np.tanh(gg)
        h = sig(o) * np.tanh(c)
        outs.append(h)
    return np.stack(outs), h, c


def test_fused_rnn_op_matches_numpy_lstm():
    T, N, I, H = 4, 3, 5, 6
    rs = np.random.RandomState(7)
    w_i2h = rs.randn(4 * H, I).astype(np.float32) * 0.2
    w_h2h = rs.randn(4 * H, H).astype(np.float32) * 0.2
    b_i2h = rs.randn(4 * H).astype(np.float32) * 0.1
    b_h2h = rs.randn(4 * H).astype(np.float32) * 0.1
    params = np.concatenate([w_i2h.ravel(), w_h2h.ravel(), b_i2h, b_h2h])
    x = rs.randn(T, N, I).astype(np.float32)
    h0 = np.zeros((1, N, H), np.float32)

    out = nd.RNN(data=nd.array(x), parameters=nd.array(params),
                 state=nd.array(h0), state_cell=nd.array(h0),
                 mode='lstm', state_size=H, num_layers=1,
                 state_outputs=True)
    ref_out, ref_h, ref_c = _np_lstm_ref(
        x, (w_i2h, w_h2h, b_i2h, b_h2h), h0[0], h0[0])
    np.testing.assert_allclose(out[0].asnumpy(), ref_out, rtol=1e-5,
                               atol=1e-5)
    np.testing.assert_allclose(out[1].asnumpy()[0], ref_h, rtol=1e-5,
                               atol=1e-5)
    np.testing.assert_allclose(out[2].asnumpy()[0], ref_c, rtol=1e-5,
                               atol=1e-5)


def test_fused_vs_unfused_consistency():
    """FusedRNNCell.unroll == its unfuse()'d stack with weights moved
    through unpack_weights (reference test_rnn.py test_unfuse +
    test_convert semantics)."""
    T, N, I, H, L = 3, 2, 4, 5, 2
    fused = mx.rnn.FusedRNNCell(H, num_layers=L, mode='lstm',
                                prefix='lstm_', get_next_state=True)
    data = sym.Variable('data')
    f_out, f_states = fused.unroll(T, data, layout='NTC',
                                   merge_outputs=True)
    f_grp = sym.Group([f_out] + f_states)

    ex = f_grp.simple_bind(mx.cpu(), data=(N, T, I), grad_req='null')
    rs = np.random.RandomState(3)
    x = rs.randn(N, T, I).astype(np.float32)
    pshape = ex.arg_dict['lstm_parameters'].shape
    pvals = (rs.rand(*pshape).astype(np.float32) - 0.5) * 0.4
    ex.arg_dict['data'][:] = x
    ex.arg_dict['lstm_parameters'][:] = pvals
    f_vals = [o.asnumpy() for o in ex.forward(is_train=False)]

    unfused = fused.unfuse()
    u_out, u_states = unfused.unroll(T, sym.Variable('data'),
                                     layout='NTC', merge_outputs=True)
    u_grp = sym.Group([u_out] + u_states)
    args = fused.unpack_weights({'lstm_parameters': nd.array(pvals)})
    ex2 = u_grp.simple_bind(mx.cpu(), data=(N, T, I), grad_req='null')
    ex2.arg_dict['data'][:] = x
    for k, v in args.items():
        ex2.arg_dict[k][:] = v.asnumpy()
    u_vals = [o.asnumpy() for o in ex2.forward(is_train=False)]

    # fused output vs unfused output
    np.testing.assert_allclose(f_vals[0], u_vals[0], rtol=1e-5, atol=1e-5)
    # final states: fused stacks (L, N, H); unfused returns per-layer
    fused_h = f_vals[1]
    fused_c = f_vals[2]
    # unfused states ordering: [h_l0, c_l0, h_l1, c_l1]
    np.testing.assert_allclose(fused_h[0], u_vals[1], rtol=1e-5, atol=1e-5)
    np.testing.assert_allclose(fused_c[0], u_vals[2], rtol=1e-5, atol=1e-5)
    np.testing.assert_allclose(fused_h[1], u_vals[3], rtol=1e-5, atol=1e-5)
    np.testing.assert_allclose(fused_c[1], u_vals[4], rtol=1e-5, atol=1e-5)


def test_pack_unpack_roundtrip():
    fused = mx.rnn.FusedRNNCell(6, num_layers=2, mode='gru',
                                bidirectional=True, prefix='gru_')
    from mxnet_tpu.ops.rnn_op import rnn_param_size
    psize = rnn_param_size({'mode': 'gru', 'state_size': 6,
                            'num_layers': 2, 'bidirectional': True}, 4)
    rs = np.random.RandomState(0)
    pvals = rs.rand(psize).astype(np.float32)
    unpacked = fused.unpack_weights({'gru_parameters': nd.array(pvals)})
    assert 'gru_parameters' not in unpacked
    packed = fused.pack_weights(unpacked)
    np.testing.assert_allclose(packed['gru_parameters'].asnumpy(), pvals)


def test_bucket_sentence_iter():
    rs = np.random.RandomState(0)
    sentences = [[int(w) + 1 for w in
                  rs.randint(0, 20, size=rs.randint(2, 12))]
                 for _ in range(200)]
    it = mx.rnn.BucketSentenceIter(sentences, batch_size=8,
                                   buckets=[4, 8, 12], invalid_label=0)
    nbatch = 0
    for batch in it:
        assert batch.data[0].shape == (8, batch.bucket_key)
        assert batch.label[0].shape == (8, batch.bucket_key)
        d = batch.data[0].asnumpy()
        l = batch.label[0].asnumpy()
        np.testing.assert_allclose(l[:, :-1], d[:, 1:])
        nbatch += 1
    assert nbatch > 0


def test_encode_sentences():
    sents = [['a', 'b', 'c'], ['b', 'c', 'd']]
    coded, vocab = mx.rnn.encode_sentences(sents, invalid_label=0,
                                           start_label=1)
    assert len(vocab) == 5  # 4 words + invalid key
    assert coded[0][1] == coded[1][0]  # 'b' consistent


def test_lstm_bucketing_training():
    """End-to-end: BucketingModule + LSTMCell.unroll on a toy
    next-token task (reference example/rnn/lstm_bucketing.py shape,
    tests/python/train/test_bucketing.py scale-down)."""
    vocab = 16
    hidden = 16
    embed = 8
    rs = np.random.RandomState(0)
    # toy language: token t+1 = (t + 1) % vocab, start random
    sentences = []
    for _ in range(120):
        ln = rs.choice([4, 8])
        s0 = rs.randint(1, vocab)
        sentences.append([(s0 + i) % vocab for i in range(ln)])
    it = mx.rnn.BucketSentenceIter(sentences, batch_size=8,
                                   buckets=[4, 8], invalid_label=0)

    def sym_gen(seq_len):
        data = sym.Variable('data')
        label = sym.Variable('softmax_label')
        emb = sym.Embedding(data, input_dim=vocab, output_dim=embed,
                            name='embed')
        cell = mx.rnn.LSTMCell(hidden, prefix='lstm_')
        outputs, _ = cell.unroll(seq_len, emb, layout='NTC',
                                 merge_outputs=True)
        pred = sym.Reshape(outputs, shape=(-1, hidden))
        pred = sym.FullyConnected(pred, num_hidden=vocab, name='pred')
        lab = sym.Reshape(label, shape=(-1,))
        pred = sym.SoftmaxOutput(pred, label=lab, name='softmax')
        return pred, ('data',), ('softmax_label',)

    mod = mx.mod.BucketingModule(sym_gen,
                                 default_bucket_key=it.default_bucket_key)
    mod.bind(data_shapes=it.provide_data, label_shapes=it.provide_label)
    mod.init_params(initializer=mx.init.Xavier())
    mod.init_optimizer(optimizer='adam',
                       optimizer_params={'learning_rate': 0.02})
    metric = mx.metric.Perplexity(ignore_label=None)
    for epoch in range(15):
        it.reset()
        metric.reset()
        for batch in it:
            mod.forward_backward(batch)
            mod.update()
            mod.update_metric(metric, batch.label)
    # toy task is deterministic; a fitted LSTM should reach low perplexity
    assert metric.get()[1] < 2.5, metric.get()
