"""Backward-interleaved gradient reduction + epoch-level fusion
(docs/PERF.md round 11): GradReducePlan bucketing/scheduling,
interleaved-vs-end-of-backward parity (mesh, ZeRO on/off),
device-resident metric folds vs the host metric loop, per-step lr
schedule stacks vs the host scheduler, the weight-EMA carry, the
fit(bulk=K) epoch loop, program-cache separation, and the round-11
profiler counters.

Tolerance notes: the packed bucket psum is elementwise-identical to
per-parameter reduces and the barrier is identity on values, so
schedule A/B parity asserts float32-ulp.  Integer-sum metrics
(Accuracy) match the host loop EXACTLY; float-sum metrics compute the
identical per-batch statistic but XLA's reduce order differs from
numpy's pairwise summation, so they assert ulp-level closeness.
"""
import json
import os
import tempfile

import numpy as np
import pytest
import jax.numpy as jnp

import mxnet_tpu as mx
from mxnet_tpu import autograd, exec_cache, gluon, lr_scheduler, metric
from mxnet_tpu import ndarray as nd
from mxnet_tpu import profiler, sym
from mxnet_tpu.gluon import nn
from mxnet_tpu.parallel import collectives

BATCH = 8
FEAT = 6
NCLS = 4
OPT_MOM = {'learning_rate': 0.1, 'momentum': 0.9, 'wd': 1e-3}

_LOSS = gluon.loss.SoftmaxCrossEntropyLoss()


def _make_net(seed, ctx=None):
    net = nn.HybridSequential()
    with net.name_scope():
        net.add(nn.Dense(16, activation='relu', in_units=FEAT))
        net.add(nn.Dense(NCLS, in_units=16))
    net.initialize(ctx=ctx)
    rs = np.random.RandomState(seed)
    for _, p in sorted(net.collect_params().items()):
        p.set_data(mx.nd.array(
            (rs.rand(*p.shape).astype(np.float32) - 0.5) * 0.4))
    return net


def _pvals(net):
    return [p.list_data()[0].asnumpy().astype(np.float32)
            for _, p in sorted(net.collect_params().items())]


def _batches(k=3, seed=42):
    rs = np.random.RandomState(seed)
    return [(mx.nd.array(rs.rand(BATCH, FEAT).astype(np.float32)),
             mx.nd.array((rs.rand(BATCH) * NCLS).astype(np.float32)))
            for _ in range(k)]


def _assert_close(a_vals, b_vals, atol=1e-6, rtol=1e-5):
    for a, b in zip(a_vals, b_vals):
        np.testing.assert_allclose(a, b, atol=atol, rtol=rtol)


# ---------------------------------------------------------------------------
# GradReducePlan mechanics
# ---------------------------------------------------------------------------

def test_reduce_plan_mechanics(monkeypatch):
    shapes = [(4, 3), (4,), (8, 4), (8,), (2, 8)]
    dtypes = ['float32'] * 5
    # byte-target mode: everything fits one bucket at the default MB
    plan = collectives.GradReducePlan(shapes, dtypes)
    assert plan.n_buckets == 1
    # reverse availability order: last param's grads first
    assert plan.buckets[0][0] == 4 and plan.buckets[0][-1] == 0
    # exact-count knob
    p3 = collectives.GradReducePlan(shapes, dtypes, n_buckets=3)
    assert p3.n_buckets >= 3
    assert [i for b in p3.buckets for i in b] == [4, 3, 2, 1, 0]
    assert p3.key != plan.key
    # a dtype change always closes the bucket
    pmix = collectives.GradReducePlan(
        [(4,), (4,), (4,)], ['float32', 'bfloat16', 'float32'])
    assert pmix.n_buckets == 3
    # env knobs
    monkeypatch.setenv('MXNET_TPU_REDUCE_BUCKETS', '2')
    assert collectives.GradReducePlan(shapes, dtypes).n_buckets >= 2
    monkeypatch.setenv('MXNET_TPU_INTERLEAVE_REDUCE', '0')
    pe = collectives.GradReducePlan(shapes, dtypes)
    assert pe.interleave is False and pe.key != plan.key
    assert collectives.interleave_reduce_enabled(True) is True


def test_grad_barrier_identity():
    gs = [jnp.arange(4.0), jnp.ones((2, 2))]
    out = collectives.grad_barrier(gs)
    for a, b in zip(gs, out):
        np.testing.assert_array_equal(np.asarray(a), np.asarray(b))
    assert collectives.grad_barrier([]) == []


# ---------------------------------------------------------------------------
# interleaved vs end-of-backward parity (the A/B the bench measures)
# ---------------------------------------------------------------------------

def _train_fused(seed, ctxs, batches, **kw):
    net = _make_net(seed, ctx=ctxs)
    tr = gluon.Trainer(net.collect_params(), 'sgd', dict(OPT_MOM))
    fs = gluon.fuse_step(net, _LOSS, tr, **kw)
    for x, y in batches:
        fs(x, y)
    return net, fs


def test_interleaved_vs_end_parity_mesh():
    batches = _batches()
    ctx4 = [mx.cpu(i) for i in range(4)]
    ni, fi = _train_fused(3, ctx4, batches, interleave=True)
    ne, fe = _train_fused(3, ctx4, batches, interleave=False)
    assert fi._reduce_plan.interleave and not fe._reduce_plan.interleave
    # barrier + packed-bucket psum are identity on values
    _assert_close(_pvals(ni), _pvals(ne), atol=1e-7, rtol=1e-6)
    # and the interleaved mesh run matches the single-device run
    n1, _ = _train_fused(3, None, batches)
    _assert_close(_pvals(n1), _pvals(ni), atol=1e-6)


def test_interleave_zero_composition(monkeypatch):
    batches = _batches()
    ctx4 = [mx.cpu(i) for i in range(4)]
    nz_on, fs_on = _train_fused(5, ctx4, batches, zero=1)
    assert fs_on._trainer._fused_updater._interleave is True
    # the explicit API value reaches the ZeRO updater (not just env)
    nz_off, fs_off = _train_fused(5, ctx4, batches, zero=1,
                                  interleave=False)
    fu = fs_off._trainer._fused_updater
    assert fu._interleave is False
    assert fu.cache_key() != \
        fs_on._trainer._fused_updater.cache_key()
    _assert_close(_pvals(nz_on), _pvals(nz_off), atol=1e-7, rtol=1e-6)


def test_reduce_counters_and_dump():
    profiler.clear()
    batches = _batches()
    ctx4 = [mx.cpu(i) for i in range(4)]
    _train_fused(3, ctx4, batches)
    st = profiler.comm_stats()
    # one bucket collective per step (tiny net -> one bucket)
    assert st['reduce_buckets_issued'] == len(batches)
    assert 'reduce_buckets_issued' in profiler.summary(print_out=False)
    fname = os.path.join(tempfile.mkdtemp(), 'prof.json')
    profiler.profiler_set_config(filename=fname)
    profiler.dump_profile()
    with open(fname) as f:
        events = json.load(f)['traceEvents']
    meta = [e for e in events if e.get('name') == 'comm']
    assert meta and 'reduce_buckets_issued' in meta[0]['args']
    assert 'scan_fused_metric_steps' in meta[0]['args']


# ---------------------------------------------------------------------------
# device-resident metrics
# ---------------------------------------------------------------------------

def test_device_metric_accuracy_exact_vs_host():
    k = 4
    batches = _batches(k, seed=7)
    # host reference: imperative loop + host Accuracy
    host_m = metric.Accuracy()
    nh = _make_net(11)
    th = gluon.Trainer(nh.collect_params(), 'sgd', dict(OPT_MOM))
    for x, y in batches:
        with autograd.record():
            out = nh(x)
            l = _LOSS(out, y)
        l.backward()
        th.step(BATCH)
        host_m.update([y], [out])
    # fused bulk with the metric folded into the scan
    dev_m = metric.Accuracy()
    nf = _make_net(11)
    tf = gluon.Trainer(nf.collect_params(), 'sgd', dict(OPT_MOM))
    fs = gluon.fuse_step(nf, _LOSS, tf, metric=dev_m)
    xs = mx.nd.NDArray(jnp.stack([x._data for x, _ in batches]))
    ys = mx.nd.NDArray(jnp.stack([y._data for _, y in batches]))
    fs.bulk(xs, ys)
    # integer sums: EXACT match at the same step index
    assert dev_m.get() == host_m.get()
    assert dev_m.num_inst == host_m.num_inst == k * BATCH
    assert dev_m.sum_metric == host_m.sum_metric


def test_device_metric_float_and_composite():
    """A composite ['acc', 'loss'] folds both leaves into one scan
    carry; the float-sum leaf agrees with the host loop to ulp and
    the integer-sum leaf exactly."""
    k = 3
    batches = _batches(k, seed=9)
    host_m = metric.create(['acc', 'loss'])
    dev_m = metric.create(['acc', 'loss'])
    assert metric.device_fold(dev_m) is not None
    nh = _make_net(13)
    th = gluon.Trainer(nh.collect_params(), 'sgd', dict(OPT_MOM))
    for x, y in batches:
        with autograd.record():
            out = nh(x)
            l = _LOSS(out, y)
        l.backward()
        th.step(BATCH)
        host_m.update([y], [out])
    nf = _make_net(13)
    tf = gluon.Trainer(nf.collect_params(), 'sgd', dict(OPT_MOM))
    fs = gluon.fuse_step(nf, _LOSS, tf, metric=dev_m)
    xs = mx.nd.NDArray(jnp.stack([x._data for x, _ in batches]))
    ys = mx.nd.NDArray(jnp.stack([y._data for _, y in batches]))
    fs.bulk(xs, ys)
    (hn, hv), (dn, dv) = host_m.get(), dev_m.get()
    assert hn == dn
    assert dv[0] == hv[0]                       # integer sums: exact
    np.testing.assert_allclose(dv[1], hv[1], rtol=1e-6)
    _assert_close(_pvals(nh), _pvals(nf), atol=1e-6)


def test_metric_device_kernels_match_host():
    """Leaf kernels vs the host update on identical inputs: the
    regression family, CrossEntropy, TopK, and Loss."""
    rs = np.random.RandomState(3)
    label = rs.rand(BATCH).astype(np.float32)
    pred = rs.rand(BATCH, 1).astype(np.float32)
    for cls in (metric.MAE, metric.MSE, metric.RMSE, metric.Loss):
        host = cls()
        host.update([mx.nd.array(label)], [mx.nd.array(pred)])
        dev = cls()
        ds, dc = dev._device_delta([jnp.asarray(label)],
                                   [jnp.asarray(pred)])
        dev.update_device(ds, dc)
        (_, hv), (_, dv) = host.get(), dev.get()
        np.testing.assert_allclose(dv, hv, rtol=1e-6)
    prob = rs.rand(BATCH, NCLS).astype(np.float32) + 0.05
    prob /= prob.sum(axis=1, keepdims=True)
    cls_lab = (rs.rand(BATCH) * NCLS).astype(np.float32)
    for m_host, m_dev in ((metric.CrossEntropy(), metric.CrossEntropy()),
                          (metric.TopKAccuracy(top_k=2),
                           metric.TopKAccuracy(top_k=2))):
        m_host.update([mx.nd.array(cls_lab)], [mx.nd.array(prob)])
        ds, dc = m_dev._device_delta([jnp.asarray(cls_lab)],
                                     [jnp.asarray(prob)])
        m_dev.update_device(ds, dc)
        (_, hv), (_, dv) = m_host.get(), m_dev.get()
        np.testing.assert_allclose(dv, hv, rtol=1e-6)


def test_metric_deferred_drain_and_reset():
    m = metric.Accuracy()
    m.update_device(jnp.asarray(3, jnp.int32), jnp.asarray(8, jnp.int32))
    # queued, not folded: no host sync happened yet
    assert m.sum_metric == 0.0 and m.num_inst == 0
    assert m.get() == ('accuracy', 3 / 8)
    assert m.num_inst == 8
    m.update_device(jnp.asarray(1, jnp.int32), jnp.asarray(8, jnp.int32))
    m.reset()      # reset DISCARDS undrained deltas
    assert np.isnan(m.get()[1]) and m.num_inst == 0
    # unsupported metrics report no fold
    assert metric.device_fold(metric.CustomMetric(lambda l, p: 0.0)) \
        is None
    assert metric.device_fold(None) is None


# ---------------------------------------------------------------------------
# per-step lr schedule stacks
# ---------------------------------------------------------------------------

def test_lr_at_closed_forms():
    fs = lr_scheduler.FactorScheduler(step=2, factor=0.5,
                                      stop_factor_lr=0.02)
    fs.base_lr = 0.1
    for n in range(1, 25):
        assert fs.lr_at(n) == fs(n), n   # incl. the stop pin
    mf = lr_scheduler.MultiFactorScheduler(step=[3, 5, 9], factor=0.1)
    mf.base_lr = 1.0
    for n in range(1, 15):
        assert mf.lr_at(n) == mf(n), n
    po = lr_scheduler.PolyScheduler(max_update=10, base_lr=0.5, pwr=2)
    for n in range(1, 15):
        assert po.lr_at(n) == po(n), n
    co = lr_scheduler.CosineScheduler(max_update=12, base_lr=0.4,
                                      final_lr=0.04, warmup_steps=4,
                                      warmup_begin_lr=0.01)
    for n in range(0, 16):     # warmup edges included
        assert co.lr_at(n) == co(n), n


def test_bulk_lr_schedule_matches_per_step_loop():
    k = 6
    batches = _batches(k, seed=21)

    def trainer(net):
        return gluon.Trainer(
            net.collect_params(), 'sgd',
            {'learning_rate': 0.1, 'momentum': 0.9,
             'lr_scheduler': lr_scheduler.FactorScheduler(
                 step=2, factor=0.5)})

    # per-step host loop (the scheduler decays at steps 3 and 5)
    n1 = _make_net(17)
    t1 = trainer(n1)
    fs1 = gluon.fuse_step(n1, _LOSS, t1)
    for x, y in batches:
        fs1(x, y)
    # one bulk dispatch: per-step schedule columns inside the scan
    nb = _make_net(17)
    tb = trainer(nb)
    fsb = gluon.fuse_step(nb, _LOSS, tb)
    xs = mx.nd.NDArray(jnp.stack([x._data for x, _ in batches]))
    ys = mx.nd.NDArray(jnp.stack([y._data for _, y in batches]))
    fsb.bulk(xs, ys)
    assert tb._optimizer.num_update == t1._optimizer.num_update == k
    # schedules advanced per STEP, not per dispatch: both see the
    # decayed lr at the same indices, so the trained params agree
    _assert_close(_pvals(n1), _pvals(nb), atol=1e-6)
    assert tb._optimizer._get_lr(0) == t1._optimizer._get_lr(0)


# ---------------------------------------------------------------------------
# weight EMA carry
# ---------------------------------------------------------------------------

def test_ema_parity_vs_host_replay():
    decay = 0.9
    batches = _batches(4, seed=31)
    net = _make_net(23)
    tr = gluon.Trainer(net.collect_params(), 'sgd', dict(OPT_MOM))
    fs = gluon.fuse_step(net, _LOSS, tr, ema_decay=decay)
    # host replay of ema <- d*ema + (1-d)*w after every step
    ema_host = {name: p.list_data()[0].asnumpy()
                for name, p in net.collect_params().items()}
    for x, y in batches[:2]:
        fs(x, y)
        for name, p in net.collect_params().items():
            w = p.list_data()[0].asnumpy()
            ema_host[name] = (np.float32(decay) * ema_host[name] +
                              np.float32(1 - decay) * w)
    # bulk continues the same carry
    xs = mx.nd.NDArray(jnp.stack([x._data for x, y in batches[2:]]))
    ys = mx.nd.NDArray(jnp.stack([y._data for x, y in batches[2:]]))
    fs.bulk(xs, ys)
    for x, y in batches[2:]:
        pass
    # replay the bulk steps from the recorded trajectory is not
    # possible host-side (weights only visible after the dispatch), so
    # replay the last two steps analytically: run a twin net per-step
    twin = _make_net(23)
    ttr = gluon.Trainer(twin.collect_params(), 'sgd', dict(OPT_MOM))
    tfs = gluon.fuse_step(twin, _LOSS, ttr, ema_decay=decay)
    ema_twin = {name: p.list_data()[0].asnumpy()
                for name, p in twin.collect_params().items()}
    for x, y in batches:
        tfs(x, y)
        for name, p in twin.collect_params().items():
            w = p.list_data()[0].asnumpy()
            ema_twin[name] = (np.float32(decay) * ema_twin[name] +
                              np.float32(1 - decay) * w)
    def by_order(d):
        # prefixes differ between independently-built nets; the
        # sorted-name order (Dense0 weight/bias, Dense1 ...) aligns
        return [d[k] for k in sorted(d)]

    ema_dev = {name: v.asnumpy() for name, v in tfs.ema().items()}
    for a, b in zip(by_order(ema_dev), by_order(ema_twin)):
        np.testing.assert_allclose(a, b, atol=1e-7, rtol=1e-6)
    # single-step and bulk carries agree too
    ema_bulk = {name: v.asnumpy() for name, v in fs.ema().items()}
    for a, b in zip(by_order(ema_bulk), by_order(ema_twin)):
        np.testing.assert_allclose(a, b, atol=1e-6, rtol=1e-5)
    # misuse guards
    with pytest.raises(ValueError):
        gluon.fuse_step(net, _LOSS, tr, ema_decay=1.5)
    with pytest.raises(ValueError):
        fs2 = gluon.fuse_step(net, _LOSS, tr)
        fs2.ema()


def test_zero_bulk_scan_writeback_shapes():
    """Regression: under ZeRO the bulk scan's weight carry can come
    out dp-SHARDED (GSPMD picks the carry layout; the in-body
    all-gather constraint doesn't bind it) — the mesh writeback then
    handed each context a 1/dp shard VIEW, silently corrupting
    parameter shapes.  The scan output now pins ws/aux/ema
    replicated; shapes and values must survive a zero=1 bulk and
    match the replicated bulk."""
    batches = _batches(3, seed=51)
    ctx4 = [mx.cpu(i) for i in range(4)]
    xs = mx.nd.NDArray(jnp.stack([x._data for x, _ in batches]))
    ys = mx.nd.NDArray(jnp.stack([y._data for _, y in batches]))

    def bulk_train(zero):
        net = _make_net(9, ctx=ctx4)
        tr = gluon.Trainer(net.collect_params(), 'sgd', dict(OPT_MOM))
        fs = gluon.fuse_step(net, _LOSS, tr, zero=zero,
                             ema_decay=0.9)
        fs.bulk(xs, ys)
        return net, fs

    nz, fz = bulk_train(1)
    for _, p in nz.collect_params().items():
        assert tuple(p.list_data()[0].shape) == tuple(p.shape), p.name
    for name, v in fz.ema().items():
        assert tuple(v.shape) == tuple(
            dict(nz.collect_params().items())[name].shape)
    nr, _ = bulk_train(0)
    _assert_close(_pvals(nr), _pvals(nz), atol=2e-6)


# ---------------------------------------------------------------------------
# cache separation + zero-compile re-creation with the new carries
# ---------------------------------------------------------------------------

def test_recreation_zero_compiles_with_metric_and_ema():
    batches = _batches(2)

    def build(seed):
        net = _make_net(seed)
        tr = gluon.Trainer(net.collect_params(), 'sgd', dict(OPT_MOM))
        fs = gluon.fuse_step(net, _LOSS, tr, metric=metric.Accuracy(),
                             ema_decay=0.99)
        for x, y in batches:
            fs(x, y)
        return fs

    build(1)
    st0 = exec_cache.stats()
    build(77)      # same architecture, fresh params/prefixes
    st1 = exec_cache.stats()
    assert st1['misses'] == st0['misses']
    assert st1['total_compile_s'] == st0['total_compile_s']


def test_metric_and_plain_programs_do_not_alias():
    batches = _batches(1)
    net = _make_net(41)
    tr = gluon.Trainer(net.collect_params(), 'sgd', dict(OPT_MOM))
    fs_plain = gluon.fuse_step(net, _LOSS, tr)
    fs_plain(*batches[0])
    m = metric.Accuracy()
    net2 = _make_net(41)
    tr2 = gluon.Trainer(net2.collect_params(), 'sgd', dict(OPT_MOM))
    fs_m = gluon.fuse_step(net2, _LOSS, tr2, metric=m)
    fs_m(*batches[0])      # must build its OWN program...
    assert m.get()[1] >= 0.0   # ...that actually feeds the metric
    k_plain = fs_plain._full_step_key(('x',))
    k_m = fs_m._full_step_key(('x',))
    assert k_plain != k_m


# ---------------------------------------------------------------------------
# Module path: bulk_step metrics + fit(bulk=K)
# ---------------------------------------------------------------------------

def _sym_mod(ctxs, ap=None, ax=None, batch=16, lr_sched=None):
    data = sym.Variable('data')
    fc1 = sym.FullyConnected(data, name='fc1', num_hidden=16)
    act = sym.Activation(fc1, act_type='relu')
    fc2 = sym.FullyConnected(act, name='fc2', num_hidden=4)
    net = sym.SoftmaxOutput(fc2, name='softmax')
    mod = mx.mod.Module(net, context=ctxs)
    mod.bind(data_shapes=[mx.io.DataDesc('data', (batch, 8))],
             label_shapes=[mx.io.DataDesc('softmax_label', (batch,))])
    if ap is None:
        mod.init_params(initializer=mx.init.Xavier())
    else:
        mod.init_params(initializer=None, arg_params=ap, aux_params=ax)
    opt_params = {'learning_rate': 0.1, 'momentum': 0.9}
    if lr_sched is not None:
        opt_params['lr_scheduler'] = lr_sched
    mod.init_optimizer(optimizer='sgd', optimizer_params=opt_params)
    return mod


def test_module_bulk_step_device_metric_exact():
    rng = np.random.RandomState(0)
    batches = [mx.io.DataBatch(
        data=[nd.array(rng.rand(16, 8).astype(np.float32))],
        label=[nd.array((rng.rand(16) * 4).astype(np.float32))])
        for _ in range(4)]
    seed = _sym_mod([mx.cpu(0)])
    ap, ax = seed.get_params()
    ap = {k: v.copy() for k, v in ap.items()}
    a = _sym_mod([mx.cpu(0)], ap, ax)
    b = _sym_mod([mx.cpu(0)], ap, ax)
    ma, mb = metric.Accuracy(), metric.Accuracy()
    for bt in batches:
        a.forward_backward(bt)
        a.update()
        a.update_metric(ma, bt.label)
    b.bulk_step(batches=batches, eval_metric=mb)
    assert mb.get() == ma.get()
    pa, _ = a.get_params()
    pb, _ = b.get_params()
    for k in pa:
        np.testing.assert_allclose(pa[k].asnumpy(), pb[k].asnumpy(),
                                   rtol=2e-5, atol=2e-5)
    # a metric without a device fold refuses loudly
    with pytest.raises(ValueError):
        _sym_mod([mx.cpu(0)], ap, ax).bulk_step(
            batches=batches,
            eval_metric=metric.CustomMetric(lambda l, p: 0.0))


@pytest.mark.parametrize('n_ctx', [1, 4])
def test_fit_bulk_matches_per_batch_fit(n_ctx):
    """fit(bulk=4): 6 batches/epoch run as dispatches of 4 + 2, the
    metric accumulates inside the scan, the FactorScheduler decays at
    the same step indices, and the result matches the per-batch fit
    loop (seeded: the two program partitions agree to float32-ulp,
    far below any argmax decision boundary in this data)."""
    rng = np.random.RandomState(5)
    X = rng.rand(96, 8).astype(np.float32)
    y = (rng.rand(96) * 4).astype(np.float32)
    ctxs = [mx.cpu(i) for i in range(n_ctx)]
    seed = _sym_mod(ctxs)
    ap, ax = seed.get_params()
    ap = {k: v.copy() for k, v in ap.items()}

    def run(bulk):
        # fresh module (fit's bind/init/init_optimizer are no-ops on
        # an already-prepared module, so the scheduler comes from
        # _sym_mod)
        mod = _sym_mod(ctxs, ap, ax,
                       lr_sched=lr_scheduler.FactorScheduler(
                           step=3, factor=0.5))
        it = mx.io.NDArrayIter(X, y, batch_size=16,
                               label_name='softmax_label')
        m = metric.Accuracy()
        mod.fit(it, eval_metric=m, num_epoch=2, bulk=bulk)
        return m.get(), mod.get_params()[0], mod

    profiler.clear()
    (mn_p, mv_p), pp, _ = run(None)
    st_plain = profiler.comm_stats()
    profiler.clear()
    (mn_b, mv_b), pb, mod_b = run(4)
    st_bulk = profiler.comm_stats()
    # last-epoch metric identical (Accuracy: integer sums)
    assert mn_p == mn_b and mv_p == mv_b
    for k in pp:
        np.testing.assert_allclose(pp[k].asnumpy(), pb[k].asnumpy(),
                                   rtol=2e-5, atol=2e-5)
    # the bulk run's metric steps ran inside the scan
    assert st_bulk['scan_fused_metric_steps'] == 12  # 6/epoch x 2
    assert st_plain['scan_fused_metric_steps'] == 0
    # steps_per_dispatch stretched across the former metric boundary:
    # 2 epochs x 6 batches in 4 dispatches (groups of 4 + 2)
    ex = mod_b._exec_group.executor
    assert ex.fused_dispatches <= 4
    # the same schedule decayed inside the dispatch: lr state agrees
    assert mod_b._optimizer.num_update == 12
