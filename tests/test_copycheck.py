"""CI gate for the local copy-paste sweep (tools/copycheck_local.py).

Guards the no-verbatim-blocks bar: no contiguous run of >= 6 identical
normalized lines may exist between mxnet_tpu/ and the reference's
python/mxnet/ tree unless it is allowlisted with a written parity
justification inside the tool.
"""
import os
import subprocess
import sys

import pytest

REPO = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))
REF = os.environ.get('MXNET_TPU_REFERENCE', '/root/reference')


@pytest.mark.skipif(not os.path.isdir(os.path.join(REF, 'python', 'mxnet')),
                    reason='reference tree not available')
def test_no_verbatim_blocks_vs_reference():
    proc = subprocess.run(
        [sys.executable, os.path.join(REPO, 'tools', 'copycheck_local.py'),
         '--threshold', '6'],
        capture_output=True, text=True, timeout=600)
    assert proc.returncode == 0, proc.stdout + proc.stderr
