"""Gluon data + RecordIO tests (reference tests/python/unittest/
test_gluon_data.py, test_recordio.py)."""
import os

import numpy as np

import mxnet_tpu as mx
from mxnet_tpu import gluon, recordio


def test_array_dataset_dataloader():
    X = np.random.rand(10, 3).astype(np.float32)
    y = np.arange(10).astype(np.float32)
    dataset = gluon.data.ArrayDataset(X, y)
    assert len(dataset) == 10
    loader = gluon.data.DataLoader(dataset, batch_size=4)
    batches = list(loader)
    assert len(batches) == 3
    data, label = batches[0]
    assert data.shape == (4, 3)
    assert label.shape == (4,)
    np.testing.assert_allclose(batches[0][0].asnumpy(), X[:4])


def test_dataloader_shuffle_discard():
    dataset = gluon.data.ArrayDataset(np.arange(10).astype(np.float32))
    loader = gluon.data.DataLoader(dataset, batch_size=3, shuffle=True,
                                   last_batch='discard')
    batches = list(loader)
    assert len(batches) == 3
    seen = np.concatenate([b.asnumpy() for b in batches])
    assert len(set(seen.tolist())) == 9


def test_dataset_transform():
    dataset = gluon.data.SimpleDataset(list(range(5))).transform(
        lambda x: x * 2)
    assert dataset[2] == 4


def test_samplers():
    s = gluon.data.SequentialSampler(5)
    assert list(s) == [0, 1, 2, 3, 4]
    r = list(gluon.data.RandomSampler(5))
    assert sorted(r) == [0, 1, 2, 3, 4]
    b = gluon.data.BatchSampler(s, 2, 'rollover')
    assert len(list(b)) == 2


def test_recordio_roundtrip(tmp_path):
    path = str(tmp_path / 'test.rec')
    rec = recordio.MXRecordIO(path, 'w')
    for i in range(5):
        rec.write(('record_%d' % i).encode())
    rec.close()
    rec = recordio.MXRecordIO(path, 'r')
    for i in range(5):
        assert rec.read() == ('record_%d' % i).encode()
    assert rec.read() is None
    rec.close()


def test_indexed_recordio(tmp_path):
    path = str(tmp_path / 'test_idx.rec')
    idxp = str(tmp_path / 'test_idx.idx')
    rec = recordio.MXIndexedRecordIO(idxp, path, 'w')
    for i in range(6):
        rec.write_idx(i, ('rec_%d' % i).encode())
    rec.close()
    rec = recordio.MXIndexedRecordIO(idxp, path, 'r')
    assert rec.keys == list(range(6))
    assert rec.read_idx(3) == b'rec_3'
    assert rec.read_idx(0) == b'rec_0'
    rec.close()


def test_pack_unpack_label():
    header = recordio.IRHeader(0, [1.0, 2.0, 3.0], 7, 0)
    payload = recordio.pack(header, b'imagedata')
    h2, data = recordio.unpack(payload)
    assert data == b'imagedata'
    np.testing.assert_allclose(h2.label, [1.0, 2.0, 3.0])
    assert h2.id == 7

    header = recordio.IRHeader(0, 5.0, 9, 0)
    h3, data3 = recordio.unpack(recordio.pack(header, b'xyz'))
    assert h3.label == 5.0
    assert data3 == b'xyz'


def test_record_file_dataset(tmp_path):
    path = str(tmp_path / 'ds.rec')
    idxp = str(tmp_path / 'ds.idx')
    rec = recordio.MXIndexedRecordIO(idxp, path, 'w')
    for i in range(4):
        rec.write_idx(i, ('item%d' % i).encode())
    rec.close()
    ds = gluon.data.RecordFileDataset(path)
    assert len(ds) == 4
    assert ds[1] == b'item1'


def test_synthetic_vision_dataset():
    ds = gluon.data.vision.SyntheticImageDataset(num_samples=20,
                                                 shape=(8, 8, 3))
    assert len(ds) == 20
    img, label = ds[0]
    assert img.shape == (8, 8, 3)
    loader = gluon.data.DataLoader(ds, batch_size=5)
    data, labels = next(iter(loader))
    assert data.shape == (5, 8, 8, 3)
