"""Gluon model zoo smoke tests (reference tests/python/unittest/
test_gluon_model_zoo.py) — small inputs, structural checks."""
import numpy as np
import pytest

import mxnet_tpu as mx
from mxnet_tpu.gluon import model_zoo
from mxnet_tpu import autograd


def _smoke(net, shape=(1, 3, 32, 32), classes=10):
    net.initialize()
    x = mx.nd.array(np.random.rand(*shape).astype(np.float32))
    y = net(x)
    assert y.shape == (shape[0], classes)
    assert np.isfinite(y.asnumpy()).all()


def test_resnet18_v1_thumbnail():
    net = model_zoo.vision.get_resnet(1, 18, classes=10, thumbnail=True)
    _smoke(net)


def test_resnet18_v2_thumbnail():
    net = model_zoo.vision.get_resnet(2, 18, classes=10, thumbnail=True)
    _smoke(net)


@pytest.mark.slow
def test_resnet50_v1_structure():
    # slow (~6s, round-16 headroom): the bottleneck-block resnet zoo
    # path stays tier-1 via test_resnet18_v1_thumbnail (same builder,
    # basic block) and test_train's resnet mixed-precision bind
    net = model_zoo.vision.get_resnet(1, 50, classes=10, thumbnail=True)
    _smoke(net)


@pytest.mark.slow
def test_squeezenet():
    # slow (~6s, round-16 headroom): concat-branch zoo structures stay
    # tier-1 via test_densenet_small; plain conv stacks via
    # test_alexnet/test_vgg11
    net = model_zoo.vision.squeezenet1_1(classes=10)
    _smoke(net, shape=(1, 3, 64, 64))


def test_densenet_small():
    net = model_zoo.vision.DenseNet(8, 4, [2, 2], classes=10)
    _smoke(net)


def test_vgg11():
    net = model_zoo.vision.vgg11(classes=10)
    _smoke(net, shape=(1, 3, 32, 32))


def test_alexnet():
    net = model_zoo.vision.alexnet(classes=10)
    _smoke(net, shape=(1, 3, 224, 224))


def test_get_model_names():
    with pytest.raises(ValueError):
        model_zoo.get_model('no_such_model')
    net = model_zoo.get_model('resnet18_v1', classes=4, thumbnail=True)
    _smoke(net, classes=4)


@pytest.mark.slow
def test_model_zoo_train_step():
    # slow (~18s, round-14 headroom): the zoo nets' structure/forward
    # stays tier-1 via the surrounding zoo tests, and gluon train
    # steps (tape backward + Trainer.step) via test_gluon and
    # test_gluon_fused; this resnet18 end-to-end step runs in full CI
    net = model_zoo.vision.get_resnet(1, 18, classes=4, thumbnail=True)
    net.initialize()
    from mxnet_tpu import gluon
    x = mx.nd.array(np.random.rand(2, 3, 16, 16).astype(np.float32))
    label = mx.nd.array(np.array([0, 1], dtype=np.float32))
    net(x)
    trainer = gluon.Trainer(net.collect_params(), 'sgd',
                            {'learning_rate': 0.1})
    loss_fn = gluon.loss.SoftmaxCrossEntropyLoss()
    with autograd.record():
        loss = loss_fn(net(x), label)
    loss.backward()
    trainer.step(2)
    assert np.isfinite(loss.asnumpy()).all()


def test_pretrained_raises():
    with pytest.raises(RuntimeError):
        model_zoo.vision.resnet18_v1(pretrained=True)
