"""Image pipeline tests (reference tests/python/unittest/test_image.py
and test_io.py ImageRecordIter coverage)."""
import os
import subprocess
import sys

import numpy as np
import pytest

import mxnet_tpu as mx
from mxnet_tpu import image, recordio


def _make_img(h=40, w=50, seed=0):
    rng = np.random.RandomState(seed)
    return rng.randint(0, 255, (h, w, 3)).astype(np.uint8)


def _encode(img):
    import cv2
    ret, buf = cv2.imencode('.png', img)
    assert ret
    return buf.tobytes()


def test_imdecode_imresize():
    img = _make_img()
    dec = image.imdecode(_encode(img), to_rgb=False)
    np.testing.assert_array_equal(dec.asnumpy(), img)
    resized = image.imresize(dec, 20, 10)
    assert resized.shape == (10, 20, 3)


def test_crops():
    img = mx.nd.array(_make_img(), dtype=np.uint8)
    out, roi = image.center_crop(img, (24, 24))
    assert out.shape == (24, 24, 3)
    out, roi = image.random_crop(img, (16, 16))
    assert out.shape == (16, 16, 3)
    out = image.fixed_crop(img, 0, 0, 10, 12)
    assert out.shape == (12, 10, 3)
    out = image.resize_short(img, 30)
    assert min(out.shape[:2]) == 30


def test_color_normalize():
    img = mx.nd.array(np.ones((4, 4, 3), np.float32) * 100)
    out = image.color_normalize(img, mean=np.array([100., 100., 100.]),
                                std=np.array([2., 2., 2.]))
    np.testing.assert_allclose(out.asnumpy(), np.zeros((4, 4, 3)))


def test_augmenter_list():
    augs = image.CreateAugmenter((3, 24, 24), rand_crop=True,
                                 rand_mirror=True, mean=True, std=True)
    img = mx.nd.array(_make_img(), dtype=np.uint8)
    for aug in augs:
        img = aug(img)[0]
    assert img.shape == (24, 24, 3)
    assert img.dtype == np.float32


def _write_rec(tmp_path, n=12, size=32):
    prefix = str(tmp_path / 'data')
    rec = recordio.MXIndexedRecordIO(prefix + '.idx', prefix + '.rec', 'w')
    for i in range(n):
        img = _make_img(size, size, seed=i)
        header = recordio.IRHeader(0, float(i % 3), i, 0)
        rec.write_idx(i, recordio.pack(header, _encode(img)))
    rec.close()
    return prefix


def test_image_iter_rec(tmp_path):
    prefix = _write_rec(tmp_path)
    it = image.ImageIter(batch_size=4, data_shape=(3, 24, 24),
                         path_imgrec=prefix + '.rec', shuffle=True)
    batch = it.next()
    assert batch.data[0].shape == (4, 3, 24, 24)
    assert batch.label[0].shape == (4,)
    n = 1
    for batch in it:
        n += 1
    assert n == 3
    it.reset()
    assert it.next().data[0].shape == (4, 3, 24, 24)


def test_image_record_iter(tmp_path):
    prefix = _write_rec(tmp_path)
    it = mx.io.ImageRecordIter(
        path_imgrec=prefix + '.rec', data_shape=(3, 28, 28), batch_size=3,
        shuffle=False, rand_mirror=True, mean_r=123, mean_g=117,
        mean_b=104)
    batch = it.next()
    assert batch.data[0].shape == (3, 3, 28, 28)
    it.reset()
    batches = list(it)
    assert len(batches) == 4


def test_image_iter_sharding(tmp_path):
    prefix = _write_rec(tmp_path)
    it0 = image.ImageIter(batch_size=2, data_shape=(3, 24, 24),
                          path_imgrec=prefix + '.rec', num_parts=2,
                          part_index=0)
    it1 = image.ImageIter(batch_size=2, data_shape=(3, 24, 24),
                          path_imgrec=prefix + '.rec', num_parts=2,
                          part_index=1)
    l0 = np.concatenate([b.label[0].asnumpy() for b in it0])
    l1 = np.concatenate([b.label[0].asnumpy() for b in it1])
    assert len(l0) == len(l1) == 6


def test_im2rec_tool(tmp_path):
    import cv2
    root = tmp_path / 'imgs' / 'class0'
    root.mkdir(parents=True)
    for i in range(3):
        cv2.imwrite(str(root / ('img%d.png' % i)), _make_img(16, 16, i))
    prefix = str(tmp_path / 'out')
    tool = os.path.join(os.path.dirname(__file__), '..', 'tools',
                        'im2rec.py')
    subprocess.check_call([sys.executable, tool, '--list', '--recursive',
                           prefix, str(tmp_path / 'imgs')])
    assert os.path.isfile(prefix + '.lst')
    subprocess.check_call([sys.executable, tool, prefix,
                           str(tmp_path / 'imgs')])
    assert os.path.isfile(prefix + '.rec')
    it = image.ImageIter(batch_size=3, data_shape=(3, 16, 16),
                         path_imgrec=prefix + '.rec')
    batch = it.next()
    assert batch.data[0].shape == (3, 3, 16, 16)


def test_mnist_iter(tmp_path):
    import gzip
    import struct
    # write tiny fake mnist idx files
    n = 20
    rng = np.random.RandomState(0)
    imgs = rng.randint(0, 255, (n, 28, 28)).astype(np.uint8)
    labels = rng.randint(0, 10, n).astype(np.uint8)
    ip = str(tmp_path / 'img.gz')
    lp = str(tmp_path / 'lab.gz')
    with gzip.open(ip, 'wb') as f:
        f.write(struct.pack('>IIII', 2051, n, 28, 28))
        f.write(imgs.tobytes())
    with gzip.open(lp, 'wb') as f:
        f.write(struct.pack('>II', 2049, n))
        f.write(labels.tobytes())
    it = mx.io.MNISTIter(image=ip, label=lp, batch_size=5, flat=True)
    batch = it.next()
    assert batch.data[0].shape == (5, 784)
    it2 = mx.io.MNISTIter(image=ip, label=lp, batch_size=5, flat=False,
                          shuffle=False)
    assert it2.next().data[0].shape == (5, 1, 28, 28)


# ---------------------------------------------------------------------------
# Detection pipeline (mx.image.ImageDetIter; reference
# python/mxnet/image/detection.py)
# ---------------------------------------------------------------------------

def _write_det_rec(tmp_path, n=8, size=64):
    import cv2
    from mxnet_tpu import recordio
    prefix = str(tmp_path / 'det')
    rec = recordio.MXIndexedRecordIO(prefix + '.idx', prefix + '.rec', 'w')
    rng = np.random.RandomState(0)
    for i in range(n):
        img = rng.randint(0, 255, (size, size, 3)).astype(np.uint8)
        ret, buf = cv2.imencode('.png', img)
        # label: header_w=2, obj_w=5, then objects
        nobj = 1 + i % 3
        label = [2, 5]
        for j in range(nobj):
            label += [float(j % 4), 0.1, 0.1, 0.6, 0.6]
        header = recordio.IRHeader(0, np.array(label, np.float32), i, 0)
        rec.write_idx(i, recordio.pack(header, buf.tobytes()))
    rec.close()
    return prefix


def test_image_det_iter(tmp_path):
    prefix = _write_det_rec(tmp_path, n=8)
    it = mx.image.ImageDetIter(batch_size=4, data_shape=(3, 32, 32),
                               path_imgrec=prefix + '.rec', shuffle=False)
    assert it.max_objects == 3
    batch = it.next()
    assert batch.data[0].shape == (4, 3, 32, 32)
    assert batch.label[0].shape == (4, 3, 5)
    lab = batch.label[0].asnumpy()
    # sample 0 has 1 object, padded rows are -1
    assert lab[0, 0, 0] == 0.0
    assert (lab[0, 1:] == -1).all()


def test_det_hflip_updates_boxes():
    from mxnet_tpu.image.detection import DetHorizontalFlipAug
    import random as pyrandom
    pyrandom.seed(0)
    img = np.zeros((10, 10, 3), np.uint8)
    label = np.array([[1, 0.1, 0.2, 0.4, 0.6],
                      [-1, -1, -1, -1, -1]], np.float32)
    aug = DetHorizontalFlipAug(p=1.0)
    _, out = aug(img, label)
    np.testing.assert_allclose(out[0], [1, 0.6, 0.2, 0.9, 0.6], atol=1e-6)
    assert (out[1] == -1).all()


def test_det_random_crop_keeps_box(tmp_path):
    from mxnet_tpu.image.detection import DetRandomCropAug
    import random as pyrandom
    pyrandom.seed(3)
    img = np.random.RandomState(0).randint(
        0, 255, (40, 40, 3)).astype(np.uint8)
    label = np.array([[0, 0.3, 0.3, 0.7, 0.7]], np.float32)
    aug = DetRandomCropAug(min_object_covered=0.5, max_attempts=50)
    out_img, out_label = aug(img, label)
    valid = out_label[out_label[:, 0] >= 0]
    assert len(valid) >= 1
    assert (valid[:, 1:] >= -1e-6).all() and (valid[:, 1:] <= 1 + 1e-6).all()


def test_det_iter_feeds_multibox_target(tmp_path):
    """End-to-end: ImageDetIter batch drives MultiBoxTarget."""
    prefix = _write_det_rec(tmp_path, n=4)
    it = mx.image.ImageDetIter(batch_size=2, data_shape=(3, 32, 32),
                               path_imgrec=prefix + '.rec')
    batch = it.next()
    anchors = mx.contrib.nd.MultiBoxPrior(batch.data[0], sizes=(0.5,),
                                          ratios=(1, 2))
    A = anchors.shape[1]
    cls_pred = mx.nd.zeros((2, 5, A))
    loc_t, loc_m, cls_t = mx.contrib.nd.MultiBoxTarget(
        anchors, batch.label[0], cls_pred)
    assert cls_t.shape == (2, A)
    assert (cls_t.asnumpy() >= 0).all()  # matched or background
