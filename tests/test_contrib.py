"""Contrib operator tests (model: reference
tests/python/unittest/test_operator.py sections for multibox/ctc/fft +
contrib op behavior documented in SURVEY.md §2.3)."""
import numpy as np
import pytest

import mxnet_tpu as mx
from mxnet_tpu import nd


def test_multibox_prior_layout():
    data = nd.zeros((1, 3, 4, 6))
    boxes = mx.contrib.nd.MultiBoxPrior(data, sizes=(0.5, 0.25),
                                        ratios=(1, 2, 0.5))
    # anchors per loc = num_sizes - 1 + num_ratios = 4
    assert boxes.shape == (1, 4 * 6 * 4, 4)
    b = boxes.asnumpy().reshape(4, 6, 4, 4)
    # first anchor at (0,0): center ((0.5)/6, 0.5/4), size 0.5
    cx, cy = 0.5 / 6, 0.5 / 4
    np.testing.assert_allclose(b[0, 0, 0],
                               [cx - 0.25, cy - 0.25, cx + 0.25, cy + 0.25],
                               rtol=1e-5)
    # ratio-2 anchor: w = 0.5*sqrt(2)/2, h = 0.5/sqrt(2)/2
    w = 0.5 * np.sqrt(2) / 2
    h = 0.5 / np.sqrt(2) / 2
    np.testing.assert_allclose(b[0, 0, 2],
                               [cx - w, cy - h, cx + w, cy + h], rtol=1e-5)


def test_multibox_target_basic():
    # one anchor exactly on the gt, one far away
    anchors = nd.array(np.array(
        [[[0.1, 0.1, 0.5, 0.5], [0.6, 0.6, 0.9, 0.9]]], np.float32))
    labels = nd.array(np.array(
        [[[0, 0.1, 0.1, 0.5, 0.5], [-1, -1, -1, -1, -1]]], np.float32))
    cls_pred = nd.zeros((1, 3, 2))
    loc_t, loc_m, cls_t = mx.contrib.nd.MultiBoxTarget(
        anchors, labels, cls_pred, overlap_threshold=0.5)
    ct = cls_t.asnumpy()
    assert ct.shape == (1, 2)
    assert ct[0, 0] == 1.0          # matched -> class 0 + 1
    assert ct[0, 1] == 0.0          # unmatched -> background
    lm = loc_m.asnumpy().reshape(1, 2, 4)
    assert lm[0, 0].sum() == 4.0
    assert lm[0, 1].sum() == 0.0
    # perfect match -> zero regression target
    lt = loc_t.asnumpy().reshape(1, 2, 4)
    np.testing.assert_allclose(lt[0, 0], np.zeros(4), atol=1e-5)


def test_multibox_target_no_gt():
    anchors = nd.array(np.random.rand(1, 5, 4).astype(np.float32))
    labels = nd.array(np.full((1, 2, 5), -1, np.float32))
    cls_pred = nd.zeros((1, 4, 5))
    loc_t, loc_m, cls_t = mx.contrib.nd.MultiBoxTarget(
        anchors, labels, cls_pred)
    assert np.all(cls_t.asnumpy() == 0)
    assert np.all(loc_m.asnumpy() == 0)


def test_multibox_detection_roundtrip():
    """Encode a gt box as a target then decode via detection; NMS keeps
    the best anchor and recovers the gt box."""
    anchors = np.array([[0.1, 0.1, 0.5, 0.5],
                        [0.12, 0.12, 0.52, 0.52],
                        [0.7, 0.7, 0.9, 0.9]], np.float32)
    # class scores: anchor 0/1 -> class 1, anchor 2 below threshold
    cls_prob = np.array([[0.05, 0.1, 0.9],
                         [0.9, 0.8, 0.05],
                         [0.05, 0.1, 0.05]], np.float32)[None]
    loc_pred = np.zeros((1, 12), np.float32)
    out = mx.contrib.nd.MultiBoxDetection(
        nd.array(cls_prob), nd.array(loc_pred), nd.array(anchors[None]),
        nms_threshold=0.5, threshold=0.4)
    o = out.asnumpy()[0]
    kept = o[o[:, 0] >= 0]
    # NMS suppresses overlapping anchor 1; only anchor 0 survives
    assert kept.shape[0] == 1
    assert kept[0, 0] == 0.0         # class id 0 (= class 1 - background)
    np.testing.assert_allclose(kept[0, 1], 0.9, rtol=1e-5)
    np.testing.assert_allclose(kept[0, 2:], anchors[0], atol=1e-5)


def test_proposal_shapes():
    rs = np.random.RandomState(0)
    A = 12  # 3 ratios x 4 scales (defaults)
    h, w = 4, 5
    cls = nd.array(rs.rand(1, 2 * A, h, w).astype(np.float32))
    bbox = nd.array((rs.rand(1, 4 * A, h, w).astype(np.float32) - 0.5) * 0.1)
    im_info = nd.array(np.array([[64, 80, 1.0]], np.float32))
    rois = mx.contrib.nd.Proposal(cls, bbox, im_info,
                                  rpn_pre_nms_top_n=50,
                                  rpn_post_nms_top_n=16,
                                  feature_stride=16, threshold=0.7,
                                  rpn_min_size=4)
    assert rois.shape == (16, 5)
    r = rois.asnumpy()
    # rois are clipped to the image
    assert r[:, 1].min() >= 0 and r[:, 3].max() <= 80 - 1
    assert r[:, 2].min() >= 0 and r[:, 4].max() <= 64 - 1


def test_psroi_pooling():
    """Constant-valued channel blocks -> each output bin picks its
    group's constant."""
    dim, g = 2, 2
    data = np.zeros((1, dim * g * g, 8, 8), np.float32)
    for c in range(dim * g * g):
        data[0, c] = c
    rois = np.array([[0, 0, 0, 7, 7]], np.float32)
    out = mx.contrib.nd.PSROIPooling(nd.array(data), nd.array(rois),
                                     spatial_scale=1.0, output_dim=dim,
                                     pooled_size=2, group_size=g)
    o = out.asnumpy()
    assert o.shape == (1, dim, 2, 2)
    for d in range(dim):
        for ph in range(2):
            for pw in range(2):
                assert o[0, d, ph, pw] == (d * g + ph) * g + pw


def test_deformable_conv_zero_offset_matches_conv():
    rs = np.random.RandomState(0)
    x = rs.rand(2, 3, 8, 8).astype(np.float32)
    wgt = rs.rand(4, 3, 3, 3).astype(np.float32)
    off = np.zeros((2, 2 * 9, 6, 6), np.float32)
    out_d = mx.contrib.nd.DeformableConvolution(
        nd.array(x), nd.array(off), nd.array(wgt), num_filter=4,
        kernel=(3, 3), no_bias=True)
    out_c = nd.Convolution(nd.array(x), nd.array(wgt), num_filter=4,
                           kernel=(3, 3), no_bias=True)
    np.testing.assert_allclose(out_d.asnumpy(), out_c.asnumpy(),
                               rtol=1e-4, atol=1e-4)


def test_ctc_loss_vs_manual():
    """T=1 single label: loss = -log softmax(label)."""
    logits = np.array([[[1.0, 2.0, 0.5]]], np.float32)  # (T=1, N=1, C=3)
    label = np.array([[1, 0]], np.float32)
    out = mx.contrib.nd.ctc_loss(nd.array(logits), nd.array(label))
    p = np.exp(logits[0, 0]) / np.exp(logits[0, 0]).sum()
    np.testing.assert_allclose(out.asnumpy()[0], -np.log(p[1]), rtol=1e-5)


def test_ctc_loss_two_steps():
    """T=2, label 'a': paths = {blank,a}, {a,blank}, {a,a}."""
    rs = np.random.RandomState(3)
    logits = rs.rand(2, 1, 3).astype(np.float32)
    label = np.array([[2, 0]], np.float32)
    out = mx.contrib.nd.ctc_loss(nd.array(logits), nd.array(label))
    p = np.exp(logits) / np.exp(logits).sum(-1, keepdims=True)
    p = p[:, 0, :]
    lik = p[0, 0] * p[1, 2] + p[0, 2] * p[1, 0] + p[0, 2] * p[1, 2]
    np.testing.assert_allclose(out.asnumpy()[0], -np.log(lik), rtol=1e-5)


def test_ctc_loss_grad_flows():
    import jax
    from mxnet_tpu import autograd
    logits = nd.array(np.random.RandomState(0)
                      .rand(4, 2, 5).astype(np.float32))
    label = nd.array(np.array([[1, 2], [3, 0]], np.float32))
    logits.attach_grad()
    with autograd.record():
        loss = mx.contrib.nd.ctc_loss(logits, label)
        s = nd.sum(loss)
    s.backward()
    g = logits.grad.asnumpy()
    assert np.isfinite(g).all() and np.abs(g).sum() > 0


def test_fft_ifft_roundtrip():
    rs = np.random.RandomState(0)
    x = rs.rand(3, 8).astype(np.float32)
    y = mx.contrib.nd.fft(nd.array(x))
    assert y.shape == (3, 16)
    # packed layout: interleaved re/im matches numpy fft
    ref = np.fft.fft(x, axis=-1)
    packed = np.stack([ref.real, ref.imag], -1).reshape(3, 16)
    np.testing.assert_allclose(y.asnumpy(), packed, rtol=1e-4, atol=1e-4)
    # reference ifft is unnormalized: ifft(fft(x)) = x * d
    z = mx.contrib.nd.ifft(y)
    np.testing.assert_allclose(z.asnumpy(), x * 8, rtol=1e-4, atol=1e-4)


def test_count_sketch():
    x = np.array([[1.0, 2.0, 3.0]], np.float32)
    h = np.array([[0, 1, 0]], np.float32)
    s = np.array([[1, -1, 1]], np.float32)
    out = mx.contrib.nd.count_sketch(nd.array(x), nd.array(h), nd.array(s),
                                     out_dim=2)
    np.testing.assert_allclose(out.asnumpy(), [[4.0, -2.0]], rtol=1e-6)


def test_quantize_dequantize_roundtrip():
    x = np.linspace(-1, 1, 16).astype(np.float32).reshape(4, 4)
    q, mn, mx_ = mx.contrib.nd.quantize(
        nd.array(x), nd.array([-1.0]), nd.array([1.0]))
    assert q.asnumpy().dtype == np.uint8
    back = mx.contrib.nd.dequantize(q, mn, mx_)
    np.testing.assert_allclose(back.asnumpy(), x, atol=2.0 / 255 + 1e-6)


def test_contrib_symbol_compose():
    """SSD head fragment composes symbolically and binds."""
    from mxnet_tpu import sym
    data = sym.Variable('data')
    anchors = sym.MultiBoxPrior(data, sizes=(0.4,), ratios=(1, 2))
    cls_prob = sym.Variable('cls_prob')
    loc_pred = sym.Variable('loc_pred')
    det = sym.MultiBoxDetection(cls_prob, loc_pred, anchors)
    A = 3 * 3 * 2
    ex = det.simple_bind(mx.cpu(), data=(1, 8, 3, 3),
                         cls_prob=(1, 2, A), loc_pred=(1, A * 4),
                         grad_req='null')
    out = ex.forward(is_train=False)[0]
    assert out.shape == (1, A, 6)


def test_proposal_batch_index_stamped():
    """ROIs carry their image index in column 0 (reference MultiProposal);
    batch>1 must not all point at image 0."""
    rs = np.random.RandomState(0)
    A, h, w = 12, 4, 4
    cls = nd.array(rs.rand(3, 2 * A, h, w).astype(np.float32))
    bbox = nd.array(np.zeros((3, 4 * A, h, w), np.float32))
    im_info = nd.array(np.tile([64, 64, 1.0], (3, 1)).astype(np.float32))
    rois = mx.contrib.nd.MultiProposal(cls, bbox, im_info,
                                       rpn_pre_nms_top_n=20,
                                       rpn_post_nms_top_n=8,
                                       rpn_min_size=2)
    r = rois.asnumpy().reshape(3, 8, 5)
    for b in range(3):
        assert (r[b, :, 0] == b).all()
