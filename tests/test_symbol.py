"""Symbol graph API tests (model: reference
tests/python/unittest/test_symbol.py + test_infer_shape.py)."""
import numpy as np

import mxnet_tpu as mx
from mxnet_tpu import sym


def _mlp():
    data = sym.Variable('data')
    fc1 = sym.FullyConnected(data, name='fc1', num_hidden=128)
    act1 = sym.Activation(fc1, name='relu1', act_type='relu')
    fc2 = sym.FullyConnected(act1, name='fc2', num_hidden=10)
    out = sym.SoftmaxOutput(fc2, name='softmax')
    return out


def test_compose_and_list_arguments():
    net = _mlp()
    args = net.list_arguments()
    assert args == ['data', 'fc1_weight', 'fc1_bias', 'fc2_weight',
                    'fc2_bias', 'softmax_label']
    assert net.list_outputs() == ['softmax_output']
    assert net.name == 'softmax'


def test_auto_naming():
    with mx.NameManager():
        data = sym.Variable('data')
        fc = sym.FullyConnected(data, num_hidden=4)
        assert fc.name == 'fullyconnected0'
        fc2 = sym.FullyConnected(fc, num_hidden=4)
        assert fc2.name == 'fullyconnected1'


def test_infer_shape_mlp():
    net = _mlp()
    arg_shapes, out_shapes, aux_shapes = net.infer_shape(data=(32, 784))
    args = net.list_arguments()
    d = dict(zip(args, arg_shapes))
    assert d['fc1_weight'] == (128, 784)
    assert d['fc1_bias'] == (128,)
    assert d['fc2_weight'] == (10, 128)
    assert d['softmax_label'] == (32,)
    assert out_shapes == [(32, 10)]
    assert aux_shapes == []


def test_infer_shape_conv():
    data = sym.Variable('data')
    conv = sym.Convolution(data, name='conv', kernel=(3, 3), num_filter=8,
                           pad=(1, 1))
    bn = sym.BatchNorm(conv, name='bn')
    pool = sym.Pooling(bn, kernel=(2, 2), stride=(2, 2), pool_type='max')
    arg_shapes, out_shapes, aux_shapes = pool.infer_shape(data=(4, 3, 8, 8))
    d = dict(zip(pool.list_arguments(), arg_shapes))
    assert d['conv_weight'] == (8, 3, 3, 3)
    assert d['conv_bias'] == (8,)
    assert d['bn_gamma'] == (8,)
    assert out_shapes == [(4, 8, 4, 4)]
    assert pool.list_auxiliary_states() == ['bn_moving_mean', 'bn_moving_var']
    assert aux_shapes == [(8,), (8,)]


def test_infer_shape_partial():
    net = _mlp()
    arg_shapes, out_shapes, _ = net.infer_shape_partial()
    d = dict(zip(net.list_arguments(), arg_shapes))
    # data/weights unknown; biases are inferable from num_hidden alone
    assert d['data'] is None
    assert d['fc1_weight'] is None
    assert d['fc1_bias'] == (128,)


def test_group_and_internals():
    a = sym.Variable('a')
    b = sym.Variable('b')
    c = a + b
    g = sym.Group([c, a])
    assert len(g) == 2
    net = _mlp()
    internals = net.get_internals()
    assert 'fc1_output' in internals.list_outputs()
    fc1 = internals['fc1_output']
    assert fc1.list_arguments() == ['data', 'fc1_weight', 'fc1_bias']


def test_json_roundtrip():
    net = _mlp()
    js = net.tojson()
    net2 = sym.load_json(js)
    assert net2.list_arguments() == net.list_arguments()
    assert net2.list_outputs() == net.list_outputs()
    _, out_shapes, _ = net2.infer_shape(data=(8, 100))
    assert out_shapes == [(8, 10)]


def test_symbol_arithmetic_eval():
    a = sym.Variable('a')
    b = sym.Variable('b')
    c = 2 * a + b ** 2 - 1
    ex = c.bind(mx.cpu(), {'a': mx.nd.array([1.0, 2.0]),
                           'b': mx.nd.array([3.0, 4.0])})
    out = ex.forward()[0]
    np.testing.assert_allclose(out.asnumpy(), [2 * 1 + 9 - 1, 2 * 2 + 16 - 1])


def test_variable_shape_attr():
    v = sym.Variable('x', shape=(3, 4), lr_mult=2.0)
    assert v.attr('__shape__') == str((3, 4))


def test_slice_channel_multi_output():
    data = sym.Variable('data')
    s = sym.SliceChannel(data, num_outputs=3, axis=1)
    assert len(s) == 3
    assert s.list_outputs() == ['slicechannel0_output0',
                                'slicechannel0_output1',
                                'slicechannel0_output2'] or len(s.list_outputs()) == 3
    _, out_shapes, _ = s.infer_shape(data=(2, 6, 4))
    assert out_shapes == [(2, 2, 4)] * 3


def test_bidirectional_shape_inference():
    """nnvm InferShape parity (graph_executor.cc:506): a 0 dim means
    unknown and is resolved from the rest of the graph, in both
    directions."""
    data = sym.Variable('data')
    z = sym.zeros(shape=(0, 8), name='z0')
    fc = sym.FullyConnected(data, num_hidden=8, name='fc')
    out = z + fc
    args, outs, _ = out.infer_shape(data=(4, 5))
    assert outs[0] == (4, 8)
    # partial inference: unknowns stay partial, no raise
    pargs, pouts, _ = out.infer_shape_partial()
    assert pouts[0] == (0, 8)
    # execution resolves the zeros node to the full batch shape
    ex = out.simple_bind(mx.cpu(), grad_req='null', data=(4, 5))
    ex.forward(is_train=False, data=np.ones((4, 5), np.float32))
    assert ex.outputs[0].shape == (4, 8)
    np.testing.assert_allclose(ex.outputs[0].asnumpy().shape, (4, 8))


def test_fc_backward_batch_inference():
    """Batch dim propagates backward through FullyConnected into a
    zeros(shape=(0, H)) initial state (the rnn begin_state pattern)."""
    h = sym.zeros(shape=(0, 6), name='h0')
    h2h = sym.FullyConnected(h, num_hidden=12, name='h2h')
    x = sym.Variable('x')
    i2h = sym.FullyConnected(x, num_hidden=12, name='i2h')
    out = h2h + i2h
    args, outs, _ = out.infer_shape(x=(3, 5))
    assert outs[0] == (3, 12)
    names = out.list_arguments()
    shapes = dict(zip(names, args))
    assert shapes['h2h_weight'] == (12, 6)


def test_rnn_default_begin_state_binds():
    """cell.unroll with no begin_state uses sym.zeros((0, H)) like the
    reference; bind + forward must work end to end."""
    import mxnet_tpu.rnn as rnn_mod
    cell = rnn_mod.LSTMCell(num_hidden=16, prefix='bs_')
    seq = [sym.Variable('t%d' % i) for i in range(3)]
    outs, states = cell.unroll(3, seq)
    net = sym.Group(list(outs) + list(states))
    shapes = {('t%d' % i): (2, 6) for i in range(3)}
    ex = net.simple_bind(mx.cpu(), grad_req='null', **shapes)
    ex.forward(is_train=False,
               **{('t%d' % i): np.random.rand(2, 6).astype(np.float32)
                  for i in range(3)})
    assert ex.outputs[0].shape == (2, 16)
