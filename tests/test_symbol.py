"""Symbol graph API tests (model: reference
tests/python/unittest/test_symbol.py + test_infer_shape.py)."""
import numpy as np

import mxnet_tpu as mx
from mxnet_tpu import sym


def _mlp():
    data = sym.Variable('data')
    fc1 = sym.FullyConnected(data, name='fc1', num_hidden=128)
    act1 = sym.Activation(fc1, name='relu1', act_type='relu')
    fc2 = sym.FullyConnected(act1, name='fc2', num_hidden=10)
    out = sym.SoftmaxOutput(fc2, name='softmax')
    return out


def test_compose_and_list_arguments():
    net = _mlp()
    args = net.list_arguments()
    assert args == ['data', 'fc1_weight', 'fc1_bias', 'fc2_weight',
                    'fc2_bias', 'softmax_label']
    assert net.list_outputs() == ['softmax_output']
    assert net.name == 'softmax'


def test_auto_naming():
    with mx.NameManager():
        data = sym.Variable('data')
        fc = sym.FullyConnected(data, num_hidden=4)
        assert fc.name == 'fullyconnected0'
        fc2 = sym.FullyConnected(fc, num_hidden=4)
        assert fc2.name == 'fullyconnected1'


def test_infer_shape_mlp():
    net = _mlp()
    arg_shapes, out_shapes, aux_shapes = net.infer_shape(data=(32, 784))
    args = net.list_arguments()
    d = dict(zip(args, arg_shapes))
    assert d['fc1_weight'] == (128, 784)
    assert d['fc1_bias'] == (128,)
    assert d['fc2_weight'] == (10, 128)
    assert d['softmax_label'] == (32,)
    assert out_shapes == [(32, 10)]
    assert aux_shapes == []


def test_infer_shape_conv():
    data = sym.Variable('data')
    conv = sym.Convolution(data, name='conv', kernel=(3, 3), num_filter=8,
                           pad=(1, 1))
    bn = sym.BatchNorm(conv, name='bn')
    pool = sym.Pooling(bn, kernel=(2, 2), stride=(2, 2), pool_type='max')
    arg_shapes, out_shapes, aux_shapes = pool.infer_shape(data=(4, 3, 8, 8))
    d = dict(zip(pool.list_arguments(), arg_shapes))
    assert d['conv_weight'] == (8, 3, 3, 3)
    assert d['conv_bias'] == (8,)
    assert d['bn_gamma'] == (8,)
    assert out_shapes == [(4, 8, 4, 4)]
    assert pool.list_auxiliary_states() == ['bn_moving_mean', 'bn_moving_var']
    assert aux_shapes == [(8,), (8,)]


def test_infer_shape_partial():
    net = _mlp()
    arg_shapes, out_shapes, _ = net.infer_shape_partial()
    d = dict(zip(net.list_arguments(), arg_shapes))
    # data/weights unknown; biases are inferable from num_hidden alone
    assert d['data'] is None
    assert d['fc1_weight'] is None
    assert d['fc1_bias'] == (128,)


def test_group_and_internals():
    a = sym.Variable('a')
    b = sym.Variable('b')
    c = a + b
    g = sym.Group([c, a])
    assert len(g) == 2
    net = _mlp()
    internals = net.get_internals()
    assert 'fc1_output' in internals.list_outputs()
    fc1 = internals['fc1_output']
    assert fc1.list_arguments() == ['data', 'fc1_weight', 'fc1_bias']


def test_json_roundtrip():
    net = _mlp()
    js = net.tojson()
    net2 = sym.load_json(js)
    assert net2.list_arguments() == net.list_arguments()
    assert net2.list_outputs() == net.list_outputs()
    _, out_shapes, _ = net2.infer_shape(data=(8, 100))
    assert out_shapes == [(8, 10)]


def test_symbol_arithmetic_eval():
    a = sym.Variable('a')
    b = sym.Variable('b')
    c = 2 * a + b ** 2 - 1
    ex = c.bind(mx.cpu(), {'a': mx.nd.array([1.0, 2.0]),
                           'b': mx.nd.array([3.0, 4.0])})
    out = ex.forward()[0]
    np.testing.assert_allclose(out.asnumpy(), [2 * 1 + 9 - 1, 2 * 2 + 16 - 1])


def test_variable_shape_attr():
    v = sym.Variable('x', shape=(3, 4), lr_mult=2.0)
    assert v.attr('__shape__') == str((3, 4))


def test_slice_channel_multi_output():
    data = sym.Variable('data')
    s = sym.SliceChannel(data, num_outputs=3, axis=1)
    assert len(s) == 3
    assert s.list_outputs() == ['slicechannel0_output0',
                                'slicechannel0_output1',
                                'slicechannel0_output2'] or len(s.list_outputs()) == 3
    _, out_shapes, _ = s.infer_shape(data=(2, 6, 4))
    assert out_shapes == [(2, 2, 4)] * 3
