"""ZeRO-1 sharded optimizer update (parallel/zero.py) on the 8-device
virtual CPU mesh: numeric parity with the replicated fused step,
bucket-layout mechanics, cache-key separation (no program aliasing),
per-device state-memory accounting, checkpoint portability, and the
KVStore multi-value push merge fix."""
import os
import pickle

import numpy as np
import jax.numpy as jnp
import pytest

import mxnet_tpu as mx
from mxnet_tpu import exec_cache, optimizer as opt_mod, profiler
from mxnet_tpu import sym as S
from mxnet_tpu.parallel import zero as zero_mod

N_DEV = 8
BATCH = 16
FEAT = 12


def _net(dtype='float32'):
    data = S.Variable('data')
    x = data if dtype == 'float32' else S.Cast(data, dtype=dtype)
    fc1 = S.FullyConnected(x, name='fc1', num_hidden=24)
    act = S.Activation(fc1, act_type='relu')
    fc2 = S.FullyConnected(act, name='fc2', num_hidden=5)
    if dtype != 'float32':
        fc2 = S.Cast(fc2, dtype='float32')
    return S.SoftmaxOutput(fc2, name='softmax')


def _params(net, seed=3):
    rs = np.random.RandomState(seed)
    shapes, _, _ = net.infer_shape(data=(BATCH, FEAT))
    out = {}
    for name, shape in zip(net.list_arguments(), shapes):
        if name in ('data', 'softmax_label'):
            continue
        out[name] = mx.nd.array(
            (rs.rand(*shape).astype(np.float32) - 0.5) * 0.2)
    return out


def _batches(k=4, seed=5):
    rs = np.random.RandomState(seed)
    return [mx.io.DataBatch(
        data=[mx.nd.array(rs.rand(BATCH, FEAT).astype(np.float32))],
        label=[mx.nd.array((rs.rand(BATCH) * 5).astype(np.float32))])
        for _ in range(k)]


def _train(zero, dtype='float32', steps=4, opt_kwargs=None,
           n_ctx=N_DEV, bulk=False):
    net = _net(dtype)
    mod = mx.mod.Module(net, context=[mx.cpu(i) for i in range(n_ctx)])
    mod.bind(data_shapes=[mx.io.DataDesc('data', (BATCH, FEAT))],
             label_shapes=[mx.io.DataDesc('softmax_label', (BATCH,))])
    mod.init_params(initializer=None, arg_params=_params(net),
                    aux_params={})
    kw = {'learning_rate': 0.1, 'momentum': 0.9, 'wd': 1e-3,
          'multi_precision': dtype != 'float32'}
    kw.update(opt_kwargs or {})
    mod.init_optimizer(optimizer='sgd', optimizer_params=kw, zero=zero)
    assert mod._fused_updater is not None
    if zero is not None:
        assert mod._fused_updater.zero == zero
    batches = _batches(steps)
    if bulk:
        mod.bulk_step(batches=batches)
    else:
        for b in batches:
            mod.forward_backward(b)
            mod.update()
    params, _ = mod.get_params()
    return mod, {k: v.asnumpy().astype(np.float32)
                 for k, v in params.items()}


def _assert_params_close(pa, pb, rtol, atol):
    assert set(pa) == set(pb)
    for k in pa:
        np.testing.assert_allclose(pa[k], pb[k], rtol=rtol, atol=atol,
                                   err_msg=k)


# ---------------------------------------------------------------------------
# numeric parity: sharded step == replicated step
# ---------------------------------------------------------------------------

def test_zero_parity_sgd_momentum_wd():
    _, pr = _train(zero=0)
    _, pz = _train(zero=1)
    _assert_params_close(pr, pz, rtol=1e-5, atol=1e-6)


def test_zero_parity_clip_gradient():
    kw = {'clip_gradient': 0.05}
    _, pr = _train(zero=0, opt_kwargs=kw)
    _, pz = _train(zero=1, opt_kwargs=kw)
    _assert_params_close(pr, pz, rtol=1e-5, atol=1e-6)


def test_zero_parity_bf16_fp32_masters():
    """bf16 weights with fp32 masters: the masters live sharded under
    ZeRO and the all-gather runs in bf16; parity within bf16 noise."""
    _, pr = _train(zero=0, dtype='bfloat16')
    _, pz = _train(zero=1, dtype='bfloat16')
    _assert_params_close(pr, pz, rtol=1e-2, atol=1e-2)


def test_zero_parity_bulk_multistep():
    """The K-step lax.scan fused dispatch with the sharded update."""
    _, pr = _train(zero=0, bulk=True)
    _, pz = _train(zero=1, bulk=True)
    _assert_params_close(pr, pz, rtol=1e-5, atol=1e-6)


def test_zero_parity_tiny_buckets(monkeypatch):
    """Force multi-bucket layouts (bucket target smaller than any one
    param) — parity must survive arbitrary bucket boundaries."""
    monkeypatch.setenv('MXNET_TPU_ZERO_BUCKET_MB', '0.0001')
    _, pz = _train(zero=1)
    monkeypatch.delenv('MXNET_TPU_ZERO_BUCKET_MB')
    _, pr = _train(zero=0)
    _assert_params_close(pr, pz, rtol=1e-5, atol=1e-6)


def test_zero_single_device_runs():
    """dp=1 (no mesh): the bucketed path degenerates to no collectives
    but must still match the replicated math exactly."""
    _, pr = _train(zero=0, n_ctx=1)
    _, pz = _train(zero=1, n_ctx=1)
    _assert_params_close(pr, pz, rtol=1e-6, atol=1e-7)


def test_zero_env_knob(monkeypatch):
    """MXNET_TPU_ZERO=1 turns the mode on without API changes."""
    monkeypatch.setenv('MXNET_TPU_ZERO', '1')
    mod, _ = _train(zero=None, steps=1)
    assert mod._fused_updater.zero == 1


# ---------------------------------------------------------------------------
# bucket layout mechanics
# ---------------------------------------------------------------------------

def test_bucket_layout_padding_and_grouping():
    layout = zero_mod.ZeroBucketLayout(
        shapes=[(3, 5), (7,), (2, 2)],
        dtypes=[np.float32, np.float32, np.float32],
        mp_flags=[False, False, False], dp=8,
        max_bytes=1 << 30)
    assert len(layout.buckets) == 1
    b = layout.buckets[0]
    assert b.size == 15 + 7 + 4
    assert b.padded % 8 == 0 and b.padded >= b.size
    # mp params bucket separately from non-mp ones
    layout2 = zero_mod.ZeroBucketLayout(
        shapes=[(4,), (4,)], dtypes=[jnp.bfloat16, np.float32],
        mp_flags=[True, False], dp=2, max_bytes=1 << 30)
    assert len(layout2.buckets) == 2
    assert layout2.buckets[0].mp and not layout2.buckets[1].mp
    assert layout2.buckets[0].acc_dtype == np.dtype(np.float32)


def test_bucket_pack_unpack_roundtrip():
    layout = zero_mod.ZeroBucketLayout(
        shapes=[(2, 3), (5,)], dtypes=[np.float32, np.float32],
        mp_flags=[False, False], dp=4, max_bytes=1 << 30)
    b = layout.buckets[0]
    vals = [jnp.arange(6.0).reshape(2, 3), jnp.arange(5.0) + 10]
    flat = layout.pack(b, vals)
    assert flat.shape == (b.padded,)
    back = layout.unpack(b, flat)
    for v, r in zip(vals, back):
        np.testing.assert_array_equal(np.asarray(v), np.asarray(r))


def test_bucket_split_over_target():
    """Greedy fill: params overflow into new buckets at the byte
    target instead of growing one giant buffer."""
    layout = zero_mod.ZeroBucketLayout(
        shapes=[(100,)] * 5, dtypes=[np.float32] * 5,
        mp_flags=[False] * 5, dp=2, max_bytes=400)
    assert len(layout.buckets) == 5


def test_state_and_comm_accounting():
    layout = zero_mod.ZeroBucketLayout(
        shapes=[(64,)], dtypes=[jnp.bfloat16], mp_flags=[True], dp=8,
        max_bytes=1 << 30)
    # per device: 8 fp32 momentum + 8 fp32 master elements
    assert layout.state_bytes_per_device() == 8 * 4 + 8 * 4
    rs, ag = layout.comm_bytes_per_step()
    assert rs == 64 * 4          # grads reduce-scatter in fp32 (acc)
    assert ag == 64 * 2          # params all-gather in bf16
    # dp=1 emits no collectives
    l1 = zero_mod.ZeroBucketLayout([(64,)], [np.float32], [False], 1)
    assert l1.comm_bytes_per_step() == (0, 0)


def test_zero_state_bytes_drop_8x():
    """Acceptance: per-device optimizer-state bytes drop ~8x on the
    8-device mesh."""
    mr, _ = _train(zero=0, steps=1)
    mz, _ = _train(zero=1, steps=1)
    rep = mr._fused_updater.state_bytes_per_device()
    shard = mz._fused_updater.state_bytes_per_device()
    assert rep > 0 and shard > 0
    assert rep / shard >= 6.0, (rep, shard)
    # profiler counter mirrors the updater's accounting
    assert profiler.comm_stats()['optimizer_state_bytes_per_device'] \
        in (rep, shard)


def test_zero_states_actually_sharded():
    """The momenta/masters must be committed dp-sharded (that IS the
    memory win), while the weights stay replicated."""
    mod, _ = _train(zero=1, dtype='bfloat16', steps=1)
    fu = mod._fused_updater
    for buf in fu._zero_moms + [m for m in fu._zero_masters
                                if m is not None]:
        assert not buf.sharding.is_fully_replicated
    ex = mod._exec_group.executor
    for name in fu.param_names:
        assert ex.arg_dict[name]._data.sharding.is_fully_replicated


def test_zero_comm_counters_accumulate():
    profiler.clear()
    mod, _ = _train(zero=1, steps=3)
    st = profiler.comm_stats()
    rs, ag = mod._fused_updater.comm_bytes_per_step()
    assert rs > 0 and ag > 0
    assert st['bytes_reduce_scattered'] == 3 * rs
    assert st['bytes_all_gathered'] == 3 * ag
    # summary() surfaces them
    assert 'bytes_reduce_scattered' in profiler.summary(print_out=False)


# ---------------------------------------------------------------------------
# compiled-program cache: no aliasing between sharded and replicated
# ---------------------------------------------------------------------------

def test_zero_and_replicated_programs_never_alias():
    exec_cache.clear()
    _train(zero=0, steps=1)
    _train(zero=1, steps=1)
    with exec_cache._LOCK:
        multistep_keys = [k for k in exec_cache._CACHE
                          if isinstance(k, tuple) and len(k) > 1
                          and k[1] == 'multistep']
    assert len(multistep_keys) == 2, multistep_keys
    # the step_key component (FusedSGD.cache_key) differs by zero cfg
    assert multistep_keys[0][-1] != multistep_keys[1][-1]


def test_fused_sgd_cache_key_carries_zero_and_layout():
    o1 = opt_mod.create('sgd', learning_rate=0.1, momentum=0.9)
    o2 = opt_mod.create('sgd', learning_rate=0.1, momentum=0.9)
    fr = opt_mod.FusedSGD(o1, ['w'])
    fz = opt_mod.FusedSGD(o2, ['w'], zero=1, mesh=None)
    assert fr.cache_key() != fz.cache_key()
    # layout joins the key once built
    w = mx.nd.array(np.zeros((4, 4), np.float32))
    fz.host_prep([w])
    k1 = fz.cache_key()
    assert any('zero' in str(part) for part in k1)
    o3 = opt_mod.create('sgd', learning_rate=0.1, momentum=0.9)
    fz2 = opt_mod.FusedSGD(o3, ['w'], zero=1, mesh=None)
    fz2.host_prep([mx.nd.array(np.zeros((8, 4), np.float32))])
    assert fz2.cache_key() != k1           # different bucket layout


# ---------------------------------------------------------------------------
# checkpoint portability across modes
# ---------------------------------------------------------------------------

def test_zero_checkpoint_roundtrip_cross_mode():
    """A sharded run's optimizer states restore into a replicated
    updater (and back): the wire format stays per-param."""
    mz, _ = _train(zero=1, steps=2)
    blob = mz._fused_updater.get_states()
    states, counts, masters = pickle.loads(blob)
    assert set(states) == set(mz._fused_updater.param_names)
    # momenta are real (training moved them off zero)
    assert any(np.abs(v).sum() > 0 for v in states.values())

    # restore into a replicated updater: per-param arrays, full shapes
    o = opt_mod.create('sgd', learning_rate=0.1, momentum=0.9)
    fr = opt_mod.FusedSGD(o, list(states))
    fr.set_states(blob)
    for n, v in states.items():
        np.testing.assert_allclose(np.asarray(fr.states[n]).ravel(),
                                   np.asarray(v).ravel())

    # and back into a fresh sharded updater via Module API
    net = _net()
    mod = mx.mod.Module(net, context=[mx.cpu(i) for i in range(N_DEV)])
    mod.bind(data_shapes=[mx.io.DataDesc('data', (BATCH, FEAT))],
             label_shapes=[mx.io.DataDesc('softmax_label', (BATCH,))])
    mod.init_params(initializer=None, arg_params=_params(net),
                    aux_params={})
    mod.init_optimizer(optimizer='sgd',
                       optimizer_params={'learning_rate': 0.1,
                                         'momentum': 0.9, 'wd': 1e-3},
                       zero=1)
    mod._fused_updater.set_states(blob)
    b = _batches(1, seed=99)[0]
    mod.forward_backward(b)
    mod.update()       # host_prep re-buckets the staged states
    blob2 = mod._fused_updater.get_states()
    states2, _, _ = pickle.loads(blob2)
    assert set(states2) == set(states)


def test_zero_get_states_before_first_step_preserves_staged():
    """Regression: set_states then get_states WITHOUT an intervening
    step must round-trip the restored values, not write an empty
    (state-resetting) checkpoint."""
    mz, _ = _train(zero=1, steps=2)
    blob = mz._fused_updater.get_states()
    states, _, _ = pickle.loads(blob)
    net = _net()
    mod = mx.mod.Module(net, context=[mx.cpu(i) for i in range(N_DEV)])
    mod.bind(data_shapes=[mx.io.DataDesc('data', (BATCH, FEAT))],
             label_shapes=[mx.io.DataDesc('softmax_label', (BATCH,))])
    mod.init_params(initializer=None, arg_params=_params(net),
                    aux_params={})
    mod.init_optimizer(optimizer='sgd',
                       optimizer_params={'learning_rate': 0.1,
                                         'momentum': 0.9},
                       zero=1)
    mod._fused_updater.set_states(blob)
    states2, _, _ = pickle.loads(mod._fused_updater.get_states())
    assert set(states2) == set(states)
    for n in states:
        np.testing.assert_allclose(np.asarray(states2[n]),
                                   np.asarray(states[n]))


def test_zero_bucket_relayout_mid_run(monkeypatch):
    """Regression: changing the bucket layout between steps (env knob
    re-read per step) must rebuild the fused step, not run the stale
    program against new-shape bucket states."""
    batches = _batches(4)
    net = _net()
    mods = {}
    for zero in (0, 1):
        mod = mx.mod.Module(net,
                            context=[mx.cpu(i) for i in range(N_DEV)])
        mod.bind(data_shapes=[mx.io.DataDesc('data', (BATCH, FEAT))],
                 label_shapes=[mx.io.DataDesc('softmax_label',
                                              (BATCH,))])
        mod.init_params(initializer=None, arg_params=_params(net),
                        aux_params={})
        mod.init_optimizer(optimizer='sgd',
                           optimizer_params={'learning_rate': 0.1,
                                             'momentum': 0.9,
                                             'wd': 1e-3}, zero=zero)
        for i, b in enumerate(batches):
            if zero and i == 2:   # shrink buckets mid-run
                monkeypatch.setenv('MXNET_TPU_ZERO_BUCKET_MB', '0.0001')
            mod.forward_backward(b)
            mod.update()
        monkeypatch.delenv('MXNET_TPU_ZERO_BUCKET_MB', raising=False)
        mods[zero] = mod
    pr, _ = mods[0].get_params()
    pz, _ = mods[1].get_params()
    _assert_params_close({k: v.asnumpy() for k, v in pr.items()},
                         {k: v.asnumpy() for k, v in pz.items()},
                         rtol=1e-5, atol=1e-6)


def test_zero_stage_validation():
    assert zero_mod.zero_stage(None) == 0
    assert zero_mod.zero_stage(1) == 1
    with pytest.raises(ValueError):
        zero_mod.zero_stage(2)


def test_kvstore_zero_stage_facade(monkeypatch):
    kv = mx.kvstore.create('local', zero=1)
    assert kv.zero_stage == 1
    monkeypatch.setenv('MXNET_TPU_ZERO', '1')
    assert mx.kvstore.create('local').zero_stage == 1
    monkeypatch.delenv('MXNET_TPU_ZERO')
    assert mx.kvstore.create('local').zero_stage == 0


# ---------------------------------------------------------------------------
# satellite: KVStore multi-value push merges with ONE stacked reduction
# ---------------------------------------------------------------------------

def test_kvstore_push_multi_value_merge():
    kv = mx.kvstore.create('local')
    kv.init('g', mx.nd.zeros((3, 2)))
    vals = [mx.nd.array(np.full((3, 2), float(i + 1), np.float32))
            for i in range(5)]
    kv.push('g', vals)                       # no updater: staged merge
    out = mx.nd.zeros((3, 2))
    kv.pull('g', out=out)
    np.testing.assert_allclose(out.asnumpy(), np.full((3, 2), 15.0))

    kv2 = mx.kvstore.create('local')
    kv2.init('w', mx.nd.ones((2, 2)))
    kv2.set_optimizer(opt_mod.create('test', rescale_grad=1.0))
    kv2.push('w', [mx.nd.ones((2, 2)) * 2, mx.nd.ones((2, 2)) * 3])
    out2 = mx.nd.zeros((2, 2))
    kv2.pull('w', out=out2)
    np.testing.assert_allclose(out2.asnumpy(), np.full((2, 2), 6.0))
