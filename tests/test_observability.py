"""Profiler / Monitor / visualization / CustomOp / rtc tests
(models: reference tests/python/unittest/{test_profiler,test_operator
(CustomOp section),test_rtc}.py and monitor usage in docs)."""
import json
import os

import numpy as np
import pytest

import mxnet_tpu as mx
from mxnet_tpu import nd, sym


def _mlp():
    data = sym.Variable('data')
    fc1 = sym.FullyConnected(data, name='fc1', num_hidden=8)
    act = sym.Activation(fc1, name='relu1', act_type='relu')
    fc2 = sym.FullyConnected(act, name='fc2', num_hidden=2)
    return sym.SoftmaxOutput(fc2, name='softmax')


def test_profiler_chrome_trace(tmp_path):
    fname = str(tmp_path / 'profile.json')
    mx.profiler.profiler_set_config(mode='symbolic', filename=fname)
    mx.profiler.profiler_set_state('run')
    net = _mlp()
    ex = net.simple_bind(mx.cpu(), data=(4, 10))
    ex.forward(is_train=True)
    ex.backward()
    ex.forward_backward()
    mx.profiler.profiler_set_state('stop')
    out = mx.profiler.dump_profile()
    assert out == fname
    with open(fname) as f:
        trace = json.load(f)
    names = [e['name'] for e in trace['traceEvents']]
    assert any('forward' in n for n in names)
    assert any('backward' in n for n in names)
    for e in trace['traceEvents']:
        assert e['ph'] in ('X', 'M')
        if e['ph'] == 'X':
            assert e['dur'] >= 0
    mx.profiler.clear()


def test_monitor_collects_layer_stats():
    net = _mlp()
    ex = net.simple_bind(mx.cpu(), data=(4, 10))
    for v in ex.arg_dict.values():
        v[:] = np.random.RandomState(0).rand(*v.shape).astype(np.float32)
    mon = mx.mon.Monitor(interval=1, pattern='.*')
    mon.install(ex)
    mon.tic()
    ex.forward(is_train=False)
    res = mon.toc()
    names = [k for _, k, _ in res]
    # intermediate layers observed, not just graph outputs
    assert any(k.startswith('fc1') for k in names), names
    assert any(k.startswith('relu1') for k in names), names
    assert any(k.startswith('softmax') for k in names), names
    # params included at toc
    assert 'fc1_weight' in names


def test_monitor_interval():
    net = _mlp()
    ex = net.simple_bind(mx.cpu(), data=(2, 10))
    mon = mx.mon.Monitor(interval=2, pattern='fc1.*')
    mon.install(ex)
    collected = []
    for i in range(4):
        mon.tic()
        ex.forward(is_train=False)
        collected.append(len(mon.toc()))
    # fires on steps 0 and 2 only
    assert (np.array(collected) > 0).sum() == 2


def test_print_summary(capsys):
    net = _mlp()
    total = mx.viz.print_summary(net, shape={'data': (4, 10)})
    out = capsys.readouterr().out
    assert 'fc1' in out and 'softmax' in out
    # 10*8+8 + 8*2+2 params
    assert total == 10 * 8 + 8 + 8 * 2 + 2


# ---------------------------------------------------------------------------
# CustomOp
# ---------------------------------------------------------------------------

class _SigmoidOp(mx.operator.CustomOp):
    def forward(self, is_train, req, in_data, out_data, aux):
        x = in_data[0]
        self.assign(out_data[0], req[0], 1.0 / (1.0 + np.exp(-x)))

    def backward(self, req, out_grad, in_data, out_data, in_grad, aux):
        y = out_data[0]
        self.assign(in_grad[0], req[0], out_grad[0] * y * (1.0 - y))


@mx.operator.register('test_sigmoid')
class _SigmoidProp(mx.operator.CustomOpProp):
    def __init__(self):
        super(_SigmoidProp, self).__init__(need_top_grad=True)

    def create_operator(self, ctx, in_shapes, in_dtypes):
        return _SigmoidOp()


def test_custom_op_imperative():
    x = nd.array(np.array([[-1.0, 0.0, 2.0]], np.float32))
    y = nd.Custom(x, op_type='test_sigmoid')
    ref = 1 / (1 + np.exp(-x.asnumpy()))
    np.testing.assert_allclose(y.asnumpy(), ref, rtol=1e-6)


def test_custom_op_autograd():
    from mxnet_tpu import autograd
    x = nd.array(np.array([0.5, -0.5], np.float32))
    x.attach_grad()
    with autograd.record():
        y = nd.Custom(x, op_type='test_sigmoid')
        s = nd.sum(y)
    s.backward()
    sig = 1 / (1 + np.exp(-x.asnumpy()))
    np.testing.assert_allclose(x.grad.asnumpy(), sig * (1 - sig),
                               rtol=1e-5)


def test_custom_op_symbolic_training():
    """Custom op inside a compiled symbol graph, gradient checked against
    the built-in sigmoid."""
    data = sym.Variable('data')
    net_c = sym.Custom(data, op_type='test_sigmoid', name='csig')
    net_c = sym.make_loss(nd_sum_sym(net_c))
    net_b = sym.Activation(sym.Variable('data'), act_type='sigmoid')
    net_b = sym.make_loss(nd_sum_sym(net_b))

    x = np.random.RandomState(0).randn(3, 4).astype(np.float32)
    ex_c = net_c.simple_bind(mx.cpu(), data=(3, 4))
    ex_b = net_b.simple_bind(mx.cpu(), data=(3, 4))
    for ex in (ex_c, ex_b):
        ex.arg_dict['data'][:] = x
        ex.forward(is_train=True)
        ex.backward()
    np.testing.assert_allclose(ex_c.grad_dict['data'].asnumpy(),
                               ex_b.grad_dict['data'].asnumpy(),
                               rtol=1e-5, atol=1e-6)


def nd_sum_sym(s):
    return sym.sum(s)


class _ConcatProp(mx.operator.CustomOpProp):
    """Two-input one-output custom op to exercise arity plumbing."""

    def list_arguments(self):
        return ['a', 'b']

    def infer_shape(self, in_shape):
        out = list(in_shape[0])
        out[-1] = in_shape[0][-1] + in_shape[1][-1]
        return in_shape, [out], []


@mx.operator.register('test_concat')
class _ConcatPropReg(_ConcatProp):
    def create_operator(self, ctx, in_shapes, in_dtypes):
        class _Op(mx.operator.CustomOp):
            def forward(self, is_train, req, in_data, out_data, aux):
                self.assign(out_data[0], req[0],
                            np.concatenate([in_data[0], in_data[1]], -1))

            def backward(self, req, out_grad, in_data, out_data,
                         in_grad, aux):
                k = in_data[0].shape[-1]
                self.assign(in_grad[0], req[0], out_grad[0][..., :k])
                self.assign(in_grad[1], req[1], out_grad[0][..., k:])
        return _Op()


def test_custom_op_multi_input():
    a = nd.array(np.ones((2, 3), np.float32))
    b = nd.array(np.full((2, 5), 2.0, np.float32))
    out = nd.Custom(a, b, op_type='test_concat')
    assert out.shape == (2, 8)
    ref = np.concatenate([a.asnumpy(), b.asnumpy()], -1)
    np.testing.assert_allclose(out.asnumpy(), ref)


# ---------------------------------------------------------------------------
# rtc (Pallas runtime kernels)
# ---------------------------------------------------------------------------

def test_rtc_kernel():
    def body(x_ref, y_ref, out_ref):
        out_ref[...] = x_ref[...] * y_ref[...] + 1.0

    k = mx.rtc.Rtc('saxpy1', ['x', 'y'], ['out'], body)
    rs = np.random.RandomState(0)
    x = nd.array(rs.rand(8, 128).astype(np.float32))
    y = nd.array(rs.rand(8, 128).astype(np.float32))
    out = k.push([x, y], out_shapes=[(8, 128)])
    np.testing.assert_allclose(out.asnumpy(),
                               x.asnumpy() * y.asnumpy() + 1.0,
                               rtol=1e-6)
    # into existing output buffer (reference push(ins, outs, ...) form)
    dst = nd.zeros((8, 128))
    k.push([x, y], outs=[dst])
    np.testing.assert_allclose(dst.asnumpy(),
                               x.asnumpy() * y.asnumpy() + 1.0, rtol=1e-6)


def test_profiler_mode_all_records_imperative_ops(tmp_path):
    fname = str(tmp_path / 'prof_all.json')
    mx.profiler.clear()
    mx.profiler.profiler_set_config(mode='all', filename=fname)
    mx.profiler.profiler_set_state('run')
    a = nd.array(np.ones((4, 4), np.float32))
    _ = nd.dot(a, a).asnumpy()
    mx.profiler.profiler_set_state('stop')
    mx.profiler.dump_profile()
    trace = json.load(open(fname))
    assert any(e['name'] == 'dot' for e in trace['traceEvents'])
    mx.profiler.clear()


def test_monitor_inactive_steps_use_fast_path():
    net = _mlp()
    ex = net.simple_bind(mx.cpu(), data=(2, 10))
    mon = mx.mon.Monitor(interval=3, pattern='.*')
    mon.install(ex)
    calls = []
    orig = ex._fwd_monitor
    ex._fwd_monitor = lambda *a, **k: (calls.append(1), orig(*a, **k))[1]
    for _ in range(6):
        mon.tic()
        ex.forward(is_train=False)
        mon.toc()
    # collect-all jit ran only on the 2 active batches (steps 0 and 3)
    assert len(calls) == 2


def test_rtc_grid_as_list():
    def body(x_ref, o_ref):
        o_ref[...] = x_ref[...] * 2.0
    k = mx.rtc.Rtc('dbl', ['x'], ['o'], body)
    x = nd.array(np.ones((8, 128), np.float32))
    out = k.push([x], out_shapes=[(8, 128)])
    np.testing.assert_allclose(out.asnumpy(), 2.0)


def test_profiler_device_lanes(tmp_path):
    """profile_xla=True merges XLA per-op spans into dump_profile()'s
    chrome trace as extra process lanes (pid >= 100) — the reference's
    per-op device attribution (SURVEY.md §5.1)."""
    import json
    out = str(tmp_path / 'prof.json')
    mx.profiler.profiler_set_config(mode='symbolic', filename=out,
                                    profile_xla=True,
                                    xla_trace_dir=str(tmp_path / 'xla'))
    mx.profiler.profiler_set_state('run')
    a = nd.array(np.random.rand(64, 64).astype(np.float32))
    for _ in range(3):
        b = nd.dot(a, a)
        b.asnumpy()
    mx.profiler.profiler_set_state('stop')
    path = mx.profiler.dump_profile()
    with open(path) as f:
        trace = json.load(f)
    events = trace['traceEvents']
    lanes = [e for e in events if e.get('ph') == 'M' and
             e['pid'] >= 100]
    assert lanes, 'no XLA lanes merged into the dump'
    xla_spans = [e for e in events if e.get('ph') == 'X' and
                 e['pid'] >= 100]
    assert xla_spans, 'no XLA op spans in the dump'
    # reset so later tests see a clean profiler
    mx.profiler.profiler_set_config(mode='symbolic',
                                    filename='profile.json')
    mx.profiler.clear()


def test_composite_metric_routes_named_heads():
    """Per-child output_names/label_names routing must survive the
    composite: each child sees ONLY its head (regression guard for the
    bug where CompositeEvalMetric.update_dict degraded to positional
    zipping and children scored the wrong heads)."""
    comp = mx.metric.CompositeEvalMetric()
    comp.add(mx.metric.Accuracy(output_names=['cls_output'],
                                label_names=['cls_label']))
    comp.add(mx.metric.RMSE(output_names=['reg_output'],
                            label_names=['reg_label']))
    preds = {'cls_output': nd.array(np.array([[0.1, 0.9], [0.8, 0.2]],
                                             np.float32)),
             'reg_output': nd.array(np.array([[1.0], [2.0]], np.float32))}
    labels = {'cls_label': nd.array(np.array([1.0, 0.0], np.float32)),
              'reg_label': nd.array(np.array([1.5, 2.5], np.float32))}
    comp.update_dict(labels, preds)
    scores = dict(comp.get_name_value())
    assert scores['accuracy'] == 1.0, scores
    np.testing.assert_allclose(scores['rmse'], 0.5, rtol=1e-6)
