"""Compiled-program cache + device-resident input prefetch tests.

Covers: graph-signature canonicalization (same net built twice -> same
key; attr / dtype / donation changes -> different keys), zero-recompile
rebinds (simple_bind twice, Module.reshape back to a seen shape), fused
train-step sharing across Modules, the memory_cost AOT reuse, profiler
counter exposure, prefetch_to_device equivalence/placement, and
PrefetchingIter worker-thread lifecycle."""
import gc
import threading

import numpy as np
import pytest

import mxnet_tpu as mx
from mxnet_tpu import exec_cache, io as mxio, nd, profiler, sym


def _mlp(num_hidden=16, n_out=3):
    data = sym.Variable('data')
    fc1 = sym.FullyConnected(data, num_hidden=num_hidden)
    act = sym.Activation(fc1, act_type='relu')
    fc2 = sym.FullyConnected(act, num_hidden=n_out)
    return sym.SoftmaxOutput(fc2, name='softmax')


# ---------------------------------------------------------------------------
# graph-signature canonicalization
# ---------------------------------------------------------------------------

def test_signature_same_symbol_built_twice():
    # two builds of the same net get different auto-generated node
    # names; the signature alpha-renames them away
    ex1 = _mlp().simple_bind(mx.cpu(), data=(8, 20))
    ex2 = _mlp().simple_bind(mx.cpu(), data=(8, 20))
    assert ex1._sig is not None
    assert ex1._sig == ex2._sig


def test_signature_attr_change():
    ex1 = _mlp(num_hidden=16).simple_bind(mx.cpu(), data=(8, 20))
    ex2 = _mlp(num_hidden=17).simple_bind(mx.cpu(), data=(8, 20))
    assert ex1._sig != ex2._sig


def test_signature_shape_change():
    ex1 = _mlp().simple_bind(mx.cpu(), data=(8, 20))
    ex2 = _mlp().simple_bind(mx.cpu(), data=(4, 20))
    assert ex1._sig != ex2._sig


def test_signature_dtype_change():
    a = sym.Variable('a')
    b = sym.Variable('b')
    c = a * b
    ex1 = c.bind(mx.cpu(), {'a': nd.array([1.0, 2.0]),
                            'b': nd.array([3.0, 4.0])})
    ex2 = c.bind(mx.cpu(), {'a': nd.array(np.array([1, 2], np.float16)),
                            'b': nd.array(np.array([3, 4], np.float16))})
    assert ex1._sig != ex2._sig


def test_signature_donation_change():
    # grad_req is part of the key: the traced backward differs
    net = _mlp()
    ex_w = net.simple_bind(mx.cpu(), grad_req='write', data=(8, 20))
    ex_n = net.simple_bind(mx.cpu(), grad_req='null', data=(8, 20))
    assert ex_w._sig != ex_n._sig


# ---------------------------------------------------------------------------
# zero-recompile rebinds
# ---------------------------------------------------------------------------

def test_simple_bind_twice_zero_new_compiles():
    exec_cache.clear()      # other tests may have seeded this topology
    net = _mlp()
    before = exec_cache.stats()
    ex1 = net.simple_bind(mx.cpu(), data=(8, 20))
    ex1.arg_dict['data'][:] = np.random.rand(8, 20)
    out1 = ex1.forward()[0].asnumpy()
    compiled = ex1._fwd_eval.fn._cache_size()
    mid = exec_cache.stats()
    assert mid['misses'] == before['misses'] + 1

    ex2 = net.simple_bind(mx.cpu(), data=(8, 20))
    after = exec_cache.stats()
    assert after['hits'] == mid['hits'] + 1
    assert after['misses'] == mid['misses']
    # the jitted step functions are literally shared...
    assert ex2._fwd_eval is ex1._fwd_eval
    assert ex2._fwd_bwd is ex1._fwd_bwd
    # ...so running the second executor compiles NOTHING new
    ex2.arg_dict['data'][:] = ex1.arg_dict['data'].asnumpy()
    out2 = ex2.forward()[0].asnumpy()
    assert ex1._fwd_eval.fn._cache_size() == compiled
    np.testing.assert_allclose(out1, out2, rtol=1e-6)


def test_module_reshape_back_to_seen_shape_hits_cache():
    exec_cache.clear()
    net = _mlp()
    mod = mx.mod.Module(net, context=mx.cpu())
    mod.bind(data_shapes=[mxio.DataDesc('data', (8, 20))],
             label_shapes=[mxio.DataDesc('softmax_label', (8,))])
    mod.init_params()
    ex0 = mod._exec_group.executor
    fwd0 = ex0._fwd_train
    # populate the jit cache at the original shape first
    batch = mxio.DataBatch(data=[nd.array(np.random.rand(8, 20))],
                           label=[nd.array(np.arange(8.0) % 3)])
    mod.forward(batch, is_train=True)
    compiled0 = fwd0.fn._cache_size()

    mod.reshape(data_shapes=[mxio.DataDesc('data', (4, 20))],
                label_shapes=[mxio.DataDesc('softmax_label', (4,))])
    before = exec_cache.stats()
    mod.reshape(data_shapes=[mxio.DataDesc('data', (8, 20))],
                label_shapes=[mxio.DataDesc('softmax_label', (8,))])
    after = exec_cache.stats()
    assert after['hits'] == before['hits'] + 1
    assert after['misses'] == before['misses']
    ex2 = mod._exec_group.executor
    assert ex2._fwd_train is fwd0
    # run a forward at the seen shape: zero new XLA compilations
    batch = mxio.DataBatch(data=[nd.array(np.random.rand(8, 20))],
                           label=[nd.array(np.arange(8.0) % 3)])
    mod.forward(batch, is_train=True)
    assert fwd0.fn._cache_size() == compiled0


def test_fused_train_step_shared_across_modules():
    X = np.random.rand(16, 10).astype(np.float32)
    y = (np.random.rand(16) * 3).astype(np.float32)
    batch = mxio.DataBatch(data=[nd.array(X)], label=[nd.array(y)])

    def train_one():
        mod = mx.mod.Module(_mlp(num_hidden=9), context=mx.cpu())
        mod.bind(data_shapes=[mxio.DataDesc('data', (16, 10))],
                 label_shapes=[mxio.DataDesc('softmax_label', (16,))])
        mod.init_params()
        mod.init_optimizer(optimizer='sgd',
                           optimizer_params={'learning_rate': 0.1,
                                             'momentum': 0.9})
        mod.forward_backward(batch)
        mod.update()
        return mod

    mod1 = train_one()
    before = exec_cache.stats()
    mod2 = train_one()
    after = exec_cache.stats()
    assert mod2._fused_step is mod1._fused_step
    assert after['total_compile_s'] == before['total_compile_s']


def test_memory_cost_reuses_cache():
    net = _mlp()
    ex1 = net.simple_bind(mx.cpu(), data=(8, 20))
    stats1 = ex1.memory_cost('forward')
    before = exec_cache.stats()['total_compile_s']
    # second call (and a second equivalent executor) reuse the AOT
    # compile instead of triggering another one
    ex2 = net.simple_bind(mx.cpu(), data=(8, 20))
    stats2 = ex2.memory_cost('forward')
    assert exec_cache.stats()['total_compile_s'] == before
    assert stats1 == stats2


def test_exec_cache_disabled(monkeypatch):
    monkeypatch.setenv('MXNET_TPU_EXEC_CACHE', '0')
    net = _mlp()
    ex1 = net.simple_bind(mx.cpu(), data=(8, 20))
    ex2 = net.simple_bind(mx.cpu(), data=(8, 20))
    assert ex1._sig is None and ex2._sig is None
    assert ex1._fwd_eval is not ex2._fwd_eval
    ex1.arg_dict['data'][:] = np.random.rand(8, 20)
    assert ex1.forward()[0].shape == (8, 3)


def test_profiler_counters_exposed():
    st = profiler.exec_cache_stats()
    assert set(st) == {'exec_cache_hits', 'exec_cache_misses',
                       'total_compile_s'}
    text = profiler.summary(print_out=False)
    assert 'exec_cache_hits=' in text and 'total_compile_s=' in text


def test_persistent_cache_writes_to_disk(tmp_path, monkeypatch):
    import jax
    cc = pytest.importorskip('jax._src.compilation_cache')
    monkeypatch.setenv('MXNET_TPU_PERSISTENT_CACHE_DIR', str(tmp_path))
    # the CPU-backend corruption guard (exec_cache round 12) would
    # no-op this test's write; force-enable for the mechanics check
    monkeypatch.setenv('MXNET_TPU_PERSISTENT_CACHE_FORCE', '1')
    # jax memoizes cache usability at first compile; reset so the
    # fresh dir takes effect inside this already-compiling process
    monkeypatch.setattr(exec_cache, '_PERSISTENT_DIR', None)
    assert exec_cache.setup_persistent_cache() == str(tmp_path)
    try:
        cc.reset_cache()
        ex = _mlp(num_hidden=21).simple_bind(mx.cpu(), data=(2, 6))
        ex.arg_dict['data'][:] = np.random.rand(2, 6)
        ex.forward()
        assert list(tmp_path.iterdir()), \
            'no on-disk compilation cache entry'
    finally:
        # turn the disk cache back OFF for the rest of the suite
        # (every later compile would otherwise pay disk writes)
        jax.config.update('jax_compilation_cache_dir', None)
        cc.reset_cache()


# ---------------------------------------------------------------------------
# device-resident input prefetch
# ---------------------------------------------------------------------------

def test_prefetch_to_device_matches_source():
    X = np.random.rand(40, 4).astype(np.float32)
    y = (np.random.rand(40) * 3).astype(np.float32)
    raw = mxio.NDArrayIter(X, y, batch_size=8)
    pf = mxio.prefetch_to_device(mxio.NDArrayIter(X, y, batch_size=8),
                                 size=2, device=mx.cpu())
    assert pf.provide_data == raw.provide_data
    assert pf.provide_label == raw.provide_label
    for _epoch in range(2):
        raw.reset()
        pf.reset()
        n = 0
        for braw, bpf in zip(raw, pf):
            np.testing.assert_array_equal(braw.data[0].asnumpy(),
                                          bpf.data[0].asnumpy())
            np.testing.assert_array_equal(braw.label[0].asnumpy(),
                                          bpf.label[0].asnumpy())
            assert braw.pad == bpf.pad
            n += 1
        assert n == 5
    assert pf.batches_served == 10
    assert pf.stall_ms_per_batch() >= 0.0


def test_prefetch_to_device_commits_batches():
    X = np.random.rand(16, 4).astype(np.float32)
    pf = mxio.prefetch_to_device(
        mxio.NDArrayIter(X, None, batch_size=8), size=2, device=mx.cpu())
    dev = mx.cpu().jax_device()
    for batch in pf:
        assert batch.data[0]._data.devices() == {dev}


def test_fit_wraps_train_iter_with_prefetch(monkeypatch):
    mod = mx.mod.Module(_mlp(), context=mx.cpu())
    mod.bind(data_shapes=[mxio.DataDesc('data', (8, 20))],
             label_shapes=[mxio.DataDesc('softmax_label', (8,))])
    it = mxio.NDArrayIter(np.random.rand(16, 20).astype(np.float32),
                          np.zeros(16, np.float32), batch_size=8)
    wrapped = mod._wrap_train_iter(it)
    assert isinstance(wrapped, mxio.PrefetchToDeviceIter)
    # idempotent: an already-wrapped iterator is not double-wrapped
    assert mod._wrap_train_iter(wrapped) is wrapped
    monkeypatch.setenv('MXNET_TPU_PREFETCH', '0')
    assert mod._wrap_train_iter(it) is it


def test_fit_end_to_end_with_prefetch():
    X = np.random.rand(32, 10).astype(np.float32)
    y = (np.random.rand(32) * 3).astype(np.float32)
    mod = mx.mod.Module(_mlp(num_hidden=8), context=mx.cpu())
    it = mxio.NDArrayIter(X, y, batch_size=8, label_name='softmax_label')
    mod.fit(it, num_epoch=2, optimizer_params={'learning_rate': 0.1})
    args, _ = mod.get_params()
    assert all(np.isfinite(v.asnumpy()).all() for v in args.values())


# ---------------------------------------------------------------------------
# PrefetchingIter worker-thread lifecycle
# ---------------------------------------------------------------------------

def _drain(it):
    n = 0
    while it.iter_next():
        n += 1
    return n


def test_prefetching_iter_joins_threads_on_close():
    X = np.random.rand(24, 4).astype(np.float32)
    y = np.zeros(24, np.float32)
    pf = mxio.PrefetchingIter(mxio.NDArrayIter(X, y, batch_size=8))
    workers = list(pf.prefetch_threads)
    assert workers and all(w.daemon for w in workers)
    assert _drain(pf) == 3
    pf.reset()
    assert _drain(pf) == 3          # second epoch
    pf.close()
    assert all(not w.is_alive() for w in workers)
    assert pf.prefetch_threads == []
    pf.close()                      # idempotent


def test_prefetching_iter_joins_threads_on_del():
    X = np.random.rand(16, 4).astype(np.float32)
    pf = mxio.PrefetchingIter(
        mxio.NDArrayIter(X, np.zeros(16, np.float32), batch_size=8))
    workers = list(pf.prefetch_threads)
    _drain(pf)
    del pf
    gc.collect()
    for w in workers:
        w.join(timeout=5)
    assert all(not w.is_alive() for w in workers)
    assert all(w not in threading.enumerate() for w in workers)
