"""Executor tests (model: reference tests/python/unittest/test_executor.py
+ numeric-gradient style checks from test_operator.py)."""
import numpy as np
import pytest

import mxnet_tpu as mx
from mxnet_tpu import sym, nd


def test_bind_forward():
    a = sym.Variable('a')
    b = sym.Variable('b')
    c = a * b
    ex = c.bind(mx.cpu(), {'a': nd.array([1.0, 2.0]), 'b': nd.array([3.0, 4.0])})
    out = ex.forward()[0]
    np.testing.assert_allclose(out.asnumpy(), [3, 8])


def test_bind_backward():
    a = sym.Variable('a')
    b = sym.Variable('b')
    c = a * b
    ex = c.bind(mx.cpu(), {'a': nd.array([1.0, 2.0]), 'b': nd.array([3.0, 4.0])})
    ex.forward(is_train=True)
    ex.backward(nd.array([1.0, 1.0]))
    np.testing.assert_allclose(ex.grad_dict['a'].asnumpy(), [3, 4])
    np.testing.assert_allclose(ex.grad_dict['b'].asnumpy(), [1, 2])


def test_simple_bind_mlp():
    data = sym.Variable('data')
    fc1 = sym.FullyConnected(data, name='fc1', num_hidden=16)
    act = sym.Activation(fc1, act_type='relu')
    fc2 = sym.FullyConnected(act, name='fc2', num_hidden=3)
    out = sym.SoftmaxOutput(fc2, name='softmax')
    ex = out.simple_bind(mx.cpu(), data=(8, 20))
    assert ex.arg_dict['fc1_weight'].shape == (16, 20)
    assert ex.grad_dict['fc1_weight'].shape == (16, 20)
    ex.arg_dict['data'][:] = np.random.rand(8, 20)
    ex.arg_dict['fc1_weight'][:] = np.random.rand(16, 20) * 0.1
    ex.arg_dict['fc2_weight'][:] = np.random.rand(3, 16) * 0.1
    ex.arg_dict['softmax_label'][:] = np.arange(8) % 3
    outs = ex.forward(is_train=True)
    assert outs[0].shape == (8, 3)
    np.testing.assert_allclose(outs[0].asnumpy().sum(axis=1),
                               np.ones(8), rtol=1e-5)
    ex.backward()
    g = ex.grad_dict['fc2_weight'].asnumpy()
    assert np.abs(g).sum() > 0


def test_softmax_grad_matches_formula():
    data = sym.Variable('data')
    out = sym.SoftmaxOutput(data, name='softmax')
    x = np.random.rand(4, 5).astype(np.float32)
    label = (np.arange(4) % 5).astype(np.float32)
    ex = out.simple_bind(mx.cpu(), data=(4, 5),
                         grad_req={'data': 'write', 'softmax_label': 'null'})
    ex.forward(is_train=True, data=nd.array(x),
               softmax_label=nd.array(label))
    ex.backward()
    p = np.exp(x) / np.exp(x).sum(1, keepdims=True)
    expect = p.copy()
    expect[np.arange(4), label.astype(int)] -= 1
    np.testing.assert_allclose(ex.grad_dict['data'].asnumpy(), expect,
                               rtol=1e-4)


def test_grad_req_add_and_null():
    a = sym.Variable('a')
    out = a * 2
    ex = out.bind(mx.cpu(), {'a': nd.array([1.0])},
                  grad_req='add')
    ex.forward(is_train=True)
    ex.backward(nd.array([1.0]))
    ex.forward(is_train=True)
    ex.backward(nd.array([1.0]))
    np.testing.assert_allclose(ex.grad_dict['a'].asnumpy(), [4.0])

    ex2 = out.bind(mx.cpu(), {'a': nd.array([1.0])}, grad_req='null')
    ex2.forward(is_train=True)
    ex2.backward(nd.array([1.0]))
    assert ex2.grad_dict.get('a') is None


def test_batchnorm_aux_update():
    data = sym.Variable('data')
    bn = sym.BatchNorm(data, name='bn', momentum=0.5, fix_gamma=False)
    ex = bn.simple_bind(mx.cpu(), data=(16, 4))
    ex.arg_dict['bn_gamma'][:] = 1
    x = np.random.rand(16, 4).astype(np.float32) * 3 + 7
    ex.forward(is_train=True, data=nd.array(x))
    mm = ex.aux_dict['bn_moving_mean'].asnumpy()
    # moving_mean = 0*0.5 + batch_mean*0.5
    np.testing.assert_allclose(mm, x.mean(0) * 0.5, rtol=1e-4)
    # eval mode uses moving stats, does not update them
    ex.forward(is_train=False, data=nd.array(x))
    np.testing.assert_allclose(ex.aux_dict['bn_moving_mean'].asnumpy(), mm,
                               rtol=1e-6)


def test_dropout_train_vs_eval():
    data = sym.Variable('data')
    d = sym.Dropout(data, p=0.5)
    ex = d.simple_bind(mx.cpu(), data=(1000,), grad_req='null')
    x = np.ones(1000, dtype=np.float32)
    out_eval = ex.forward(is_train=False, data=nd.array(x))[0].asnumpy()
    np.testing.assert_allclose(out_eval, x)
    out_train = ex.forward(is_train=True, data=nd.array(x))[0].asnumpy()
    assert 0.3 < (out_train == 0).mean() < 0.7


def test_numeric_gradient_conv():
    """Finite-difference check of conv gradients (the reference's
    check_numeric_gradient oracle, test_utils.py:439)."""
    data = sym.Variable('data')
    conv = sym.Convolution(data, name='conv', kernel=(2, 2), num_filter=2,
                           no_bias=True)
    loss = sym.make_loss(sym.sum(sym.square(conv)))
    x = np.random.rand(1, 1, 4, 4).astype(np.float32)
    w = np.random.rand(2, 1, 2, 2).astype(np.float32)
    ex = loss.bind(mx.cpu(), {'data': nd.array(x), 'conv_weight': nd.array(w)})
    ex.forward(is_train=True)
    ex.backward()
    gw = ex.grad_dict['conv_weight'].asnumpy()
    eps = 1e-3
    fd = np.zeros_like(w)

    def f(wv):
        # reuse the same executor (same compiled XLA module)
        return ex.forward(conv_weight=nd.array(wv.reshape(w.shape))
                          )[0].asnumpy().sum()

    for i in range(w.size):
        wp = w.copy().reshape(-1)
        wp[i] += eps
        wm = w.copy().reshape(-1)
        wm[i] -= eps
        fd.reshape(-1)[i] = (f(wp) - f(wm)) / (2 * eps)
    np.testing.assert_allclose(gw, fd, rtol=1e-2, atol=1e-2)


def test_executor_reshape():
    data = sym.Variable('data')
    fc = sym.FullyConnected(data, name='fc', num_hidden=4)
    ex = fc.simple_bind(mx.cpu(), data=(8, 10))
    ex2 = ex.reshape(data=(16, 10))
    assert ex2.arg_dict['data'].shape == (16, 10)
    # weights shared
    assert ex2.arg_dict['fc_weight'] is ex.arg_dict['fc_weight']
    out = ex2.forward()
    assert out[0].shape == (16, 4)


def test_multi_output_executor():
    data = sym.Variable('data')
    parts = sym.SliceChannel(data, num_outputs=2, axis=1)
    ex = parts.bind(mx.cpu(), {'data': nd.array(np.arange(8).reshape(2, 4))})
    outs = ex.forward()
    assert len(outs) == 2
    np.testing.assert_allclose(outs[0].asnumpy(), [[0, 1], [4, 5]])


def test_debug_str():
    """Plan dump (reference MXExecutorPrint)."""
    net = sym.SoftmaxOutput(sym.FullyConnected(sym.Variable('data'),
                                               num_hidden=4, name='fc'),
                            name='softmax')
    ex = net.simple_bind(mx.cpu(), data=(2, 8))
    s = ex.debug_str()
    assert 'fc (FullyConnected)' in s
    assert 'Total bytes' in s
    assert 'fused XLA' in s


def test_partial_forward():
    """Reference Executor::PartialForward (graph_executor.cc:54):
    stepwise execution that continues across calls."""
    data = sym.Variable('data')
    a = sym.Activation(data, act_type='relu')
    b = a * 2.0
    c = b + 1.0
    ex = c.simple_bind(mx.cpu(), grad_req='null', data=(2, 3))
    x = np.random.rand(2, 3).astype(np.float32)
    left = ex.partial_forward(step=1, data=x)
    assert left > 0
    left = ex.partial_forward(step=2)
    assert left > 0
    left = ex.partial_forward()  # finish
    assert left == 0
    np.testing.assert_allclose(ex.outputs[0].asnumpy(),
                               np.maximum(x, 0) * 2 + 1, rtol=1e-6)


def test_multi_output_head_grad_warning():
    a = sym.Variable('a')
    net = sym.Group([a * 2.0, a * 3.0])
    ex = net.simple_bind(mx.cpu(), grad_req='write', a=(2,))
    ex.forward(is_train=True, a=np.ones(2, np.float32))
    import warnings as _w
    with _w.catch_warnings(record=True) as rec:
        _w.simplefilter('always')
        ex.backward()
        assert any('head gradients' in str(r.message) for r in rec)
    np.testing.assert_allclose(ex.grad_dict['a'].asnumpy(), [5.0, 5.0])


def test_work_load_list_rejected_when_uneven():
    from mxnet_tpu.module.executor_group import decide_slices
    decide_slices(8, [1, 1])  # uniform ok
    with pytest.raises(mx.base.MXNetError):
        decide_slices(8, [1, 3])
    net = sym.SoftmaxOutput(
        sym.FullyConnected(sym.Variable('data'), num_hidden=3,
                           name='fc'), name='softmax')
    with pytest.raises(mx.base.MXNetError):
        mx.mod.Module(net, context=[mx.cpu(0), mx.cpu(1)],
                      work_load_list=[1, 2]).bind(
            data_shapes=[mx.io.DataDesc('data', (4, 4))],
            label_shapes=[mx.io.DataDesc('softmax_label', (4,))])


def test_partial_forward_resolves_init_shapes():
    """partial_forward must thread bidirectionally-resolved shapes into
    zeros(shape=(0,H)) init nodes, same as the full forward."""
    z = sym.zeros(shape=(0, 4), name='z0')
    fc = sym.FullyConnected(sym.Variable('data'), num_hidden=4,
                            name='pfc')
    out = z + fc
    ex = out.simple_bind(mx.cpu(), grad_req='null', data=(3, 5))
    x = np.random.rand(3, 5).astype(np.float32)
    left = ex.partial_forward(step=1, data=x)
    assert left > 0
    assert ex.partial_forward() == 0
    assert ex.outputs[0].shape == (3, 4)


def test_symbolic_optimizer_op_state_persists_in_eval_forward():
    """aux_always ops (sgd_mom_update & co) advance their states even
    under forward(is_train=False) — graph-mode parity with the
    reference's in-place state mutation."""
    w = sym.Variable('w')
    g = sym.Variable('g')
    net = sym.sgd_mom_update(w, g, lr=0.1, momentum=0.9,
                             name='upd')
    ex = net.simple_bind(mx.cpu(), grad_req='null', w=(3,), g=(3,))
    ex.arg_dict['w'][:] = 1.0
    ex.arg_dict['g'][:] = 1.0
    mom_name = ex.aux_dict and list(ex.aux_dict)[0]
    ex.forward(is_train=False)
    m1 = ex.aux_dict[mom_name].asnumpy().copy()
    np.testing.assert_allclose(m1, -0.1, rtol=1e-6)
    ex.forward(is_train=False)
    m2 = ex.aux_dict[mom_name].asnumpy()
    np.testing.assert_allclose(m2, 0.9 * -0.1 - 0.1, rtol=1e-6)


def test_input_bn_conv_split_equivalence(monkeypatch):
    """The MXNET_TPU_STEM_SPLIT executor optimization (docs/PERF.md
    round 5): Convolution(no_bias) fed by BatchNorm(fix_gamma=True) on
    a gradient-free input computes conv(x̂γ) + conv(β·1) instead of
    conv(x̂γ + β·1) — autodiff's β path then costs a batch-1 dgrad
    instead of a full-batch one.  Outputs, every gradient (incl. dβ),
    and the BN aux-stat updates must match the straight form."""
    def run(split):
        monkeypatch.setenv('MXNET_TPU_STEM_SPLIT', split)
        rng = np.random.RandomState(0)
        data = sym.Variable('data')
        bn = sym.BatchNorm(data, fix_gamma=True, eps=2e-5,
                           momentum=0.9, name='bn_data')
        conv = sym.Convolution(bn, num_filter=8, kernel=(3, 3),
                               stride=(2, 2), pad=(1, 1), no_bias=True,
                               name='conv0')
        bn2 = sym.BatchNorm(conv, fix_gamma=False, name='bn2')
        out = sym.sum(sym.square(bn2), name='loss')
        # data must be gradient-free for the pattern to fire — the
        # Module binding convention (inputs grad_req null)
        req = {n: ('null' if n == 'data' else 'write')
               for n in out.list_arguments()}
        ex = out.simple_bind(mx.cpu(), grad_req=req,
                             data=(4, 3, 16, 16))
        assert bool(ex._split_conv) == (split == '1'), \
            'split engagement mismatch: %r' % (ex._split_conv,)
        for n, a in ex.arg_dict.items():
            if 'gamma' in n:
                a[:] = nd.array(np.ones(a.shape, np.float32))
            else:
                scale = 1.0 if n in ('data', 'bn_data_beta') else 0.1
                a[:] = nd.array(
                    rng.randn(*a.shape).astype(np.float32) * scale)
        ex.forward(is_train=True)
        y = ex.outputs[0].asnumpy().copy()
        ex.backward()
        grads = {n: g.asnumpy().copy()
                 for n, g in ex.grad_dict.items() if g is not None}
        auxs = {n: a.asnumpy().copy() for n, a in ex.aux_dict.items()}
        return y, grads, auxs

    y1, g1, a1 = run('1')
    y0, g0, a0 = run('0')
    np.testing.assert_allclose(y1, y0, rtol=1e-4, atol=1e-4)
    assert np.abs(g0['bn_data_beta']).max() > 0
    for n in g0:
        np.testing.assert_allclose(g1[n], g0[n], rtol=1e-3, atol=1e-4,
                                   err_msg=n)
    for n in a0:
        np.testing.assert_allclose(a1[n], a0[n], rtol=1e-5, atol=1e-6,
                                   err_msg=n)
