"""Gluon RNN tests (modeled on reference tests/python/unittest/
test_gluon_rnn.py)."""
import numpy as np

import mxnet_tpu as mx
from mxnet_tpu import gluon
from mxnet_tpu import autograd


def test_rnn_cell_unroll():
    cell = gluon.rnn.RNNCell(8, input_size=4)
    cell.initialize()
    x = mx.nd.array(np.random.rand(2, 3, 4).astype(np.float32))
    outputs, states = cell.unroll(3, x, layout='NTC', merge_outputs=True)
    assert outputs.shape == (2, 3, 8)
    assert states[0].shape == (2, 8)


def test_lstm_cell_unroll_backward():
    cell = gluon.rnn.LSTMCell(6)
    cell.initialize()
    x = mx.nd.array(np.random.rand(2, 4, 3).astype(np.float32))
    with autograd.record():
        outputs, states = cell.unroll(4, x, layout='NTC',
                                      merge_outputs=True)
        loss = mx.nd.sum(outputs)
    loss.backward()
    g = cell.i2h_weight.grad()
    assert g.shape == (24, 3)
    assert np.isfinite(g.asnumpy()).all()


def test_gru_cell():
    cell = gluon.rnn.GRUCell(5, input_size=2)
    cell.initialize()
    x = mx.nd.array(np.random.rand(3, 2).astype(np.float32))
    states = cell.begin_state(3)
    out, new_states = cell(x, states)
    assert out.shape == (3, 5)
    assert new_states[0].shape == (3, 5)


def test_sequential_rnn_cell():
    stack = gluon.rnn.SequentialRNNCell()
    stack.add(gluon.rnn.LSTMCell(4))
    stack.add(gluon.rnn.GRUCell(3))
    stack.initialize()
    x = mx.nd.array(np.random.rand(2, 5, 6).astype(np.float32))
    outputs, states = stack.unroll(5, x, merge_outputs=True)
    assert outputs.shape == (2, 5, 3)
    assert len(states) == 3  # lstm h,c + gru h


def test_bidirectional_cell():
    bi = gluon.rnn.BidirectionalCell(gluon.rnn.LSTMCell(4),
                                     gluon.rnn.LSTMCell(4))
    bi.initialize()
    x = mx.nd.array(np.random.rand(2, 3, 5).astype(np.float32))
    outputs, states = bi.unroll(3, x, merge_outputs=True)
    assert outputs.shape == (2, 3, 8)


def test_residual_dropout_cells():
    cell = gluon.rnn.ResidualCell(gluon.rnn.RNNCell(4, input_size=4))
    cell.initialize()
    x = mx.nd.array(np.random.rand(2, 3, 4).astype(np.float32))
    outputs, _ = cell.unroll(3, x, merge_outputs=True)
    assert outputs.shape == (2, 3, 4)

    dcell = gluon.rnn.DropoutCell(0.5)
    y, s = dcell(mx.nd.ones((2, 3)), [])
    assert y.shape == (2, 3)


def test_fused_lstm_layer():
    layer = gluon.rnn.LSTM(7, num_layers=2)
    layer.initialize()
    x = mx.nd.array(np.random.rand(5, 2, 3).astype(np.float32))  # TNC
    out = layer(x)
    assert out.shape == (5, 2, 7)
    states = layer.begin_state(2)
    out, new_states = layer(x, states)
    assert out.shape == (5, 2, 7)
    assert new_states[0].shape == (2, 2, 7)
    assert new_states[1].shape == (2, 2, 7)


def test_fused_lstm_matches_cell():
    """Fused scan-based LSTM == per-step LSTMCell when sharing weights."""
    np.random.seed(42)
    T, N, C, H = 4, 2, 3, 5
    layer = gluon.rnn.LSTM(H, num_layers=1, input_size=C)
    layer.initialize()
    x_np = np.random.rand(T, N, C).astype(np.float32)
    x = mx.nd.array(x_np)
    out = layer(x)

    cell = gluon.rnn.LSTMCell(H, input_size=C)
    cell.initialize()
    # copy weights from the fused layer
    cell.i2h_weight.set_data(layer.l0_i2h_weight.data())
    cell.h2h_weight.set_data(layer.l0_h2h_weight.data())
    cell.i2h_bias.set_data(layer.l0_i2h_bias.data())
    cell.h2h_bias.set_data(layer.l0_h2h_bias.data())
    outs, _ = cell.unroll(T, mx.nd.array(x_np.transpose(1, 0, 2)),
                          layout='NTC', merge_outputs=True)
    np.testing.assert_allclose(out.asnumpy(),
                               outs.asnumpy().transpose(1, 0, 2),
                               rtol=1e-5, atol=1e-6)


def test_fused_gru_backward():
    layer = gluon.rnn.GRU(4, num_layers=1, bidirectional=True)
    layer.initialize()
    x = mx.nd.array(np.random.rand(3, 2, 5).astype(np.float32))
    with autograd.record():
        out = layer(x)
        loss = mx.nd.sum(out)
    loss.backward()
    g = layer.l0_i2h_weight.grad()
    assert g.shape == (12, 5)
    assert np.abs(g.asnumpy()).sum() > 0


def test_rnn_layer_ntc():
    layer = gluon.rnn.RNN(6, num_layers=1, layout='NTC',
                          activation='tanh')
    layer.initialize()
    x = mx.nd.array(np.random.rand(2, 5, 3).astype(np.float32))
    out = layer(x)
    assert out.shape == (2, 5, 6)
