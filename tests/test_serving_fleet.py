"""Fleet serving tier tests (serving_fleet: ModelRegistry + SLO
batching + HTTP front + continuous batching).

Covers the ISSUE-10 contract: registry LRU evict/re-warm with ZERO
recompiles, SLO deadline-derived batcher holds, shed-on-backlog as a
typed Overloaded error, HTTP 200/404/429/healthz/statsz round-trips
over localhost, continuous-batch admit/retire bit-parity vs solo runs
(and its deterministic tick win over convoy batching), the per-engine
counter scoping satellite, and close()-vs-eviction safety.  All
models CPU-sized.

Chunked continuous serving (ISSUE 17): tick_chunk=K bit-parity vs the
unchunked loop (retire mid-chunk + re-admit), chunk-boundary admission
quantization + the boundary_wait_ms estimate, chunked/unchunked
program-family non-aliasing at zero recompiles, the lone-request /
exact-fill fast-path counters, the shared knob parser
(MXNET_TPU_SERVE_TICK_CHUNK, K > slots typed reject), the SLO-derived
default K, registry tick_chunk= forwarding, and the cont_chunk*
profiler flow.

Host-hiding (ISSUE 18): double-buffered chunk staging bit-parity vs
the serialized loop at identical K (sequential + concurrent clients),
the MXNET_TPU_SERVE_STAGE_AHEAD knob, tick_chunk='auto' (typed reject
without an SLO deadline, EMA convergence onto a warmed rung at zero
compiles, zero-miss engine re-creation across an initial-K change,
registry 'auto' passthrough), and the overlap_* profiler family.
"""
import json
import threading
import time
import urllib.error
import urllib.request

import numpy as np
import pytest

import mxnet_tpu as mx
from mxnet_tpu import exec_cache, model as model_mod, nd, profiler, sym
from mxnet_tpu.base import MXNetError
from mxnet_tpu.predictor import Predictor
from mxnet_tpu.serving import (TICK_CHUNK_KNOB, InferenceEngine,
                               chunk_for_deadline, resolve_tick_chunk)
from mxnet_tpu.serving_fleet import (SLO, BudgetExceeded,
                                     ContinuousEngine, HttpFront,
                                     ModelRegistry, Overloaded)

DIM = 6
HID = 8
OUT = 3


def _mlp():
    data = sym.Variable('data')
    fc1 = sym.FullyConnected(data, num_hidden=HID, name='fc1')
    act = sym.Activation(fc1, act_type='relu')
    return sym.FullyConnected(act, num_hidden=OUT, name='fc2')


def _params(seed=7):
    rs = np.random.RandomState(seed)
    return {
        'fc1_weight': nd.array(rs.randn(HID, DIM).astype(np.float32) * .5),
        'fc1_bias': nd.array(rs.randn(HID).astype(np.float32) * .1),
        'fc2_weight': nd.array(rs.randn(OUT, HID).astype(np.float32) * .5),
        'fc2_bias': nd.array(rs.randn(OUT).astype(np.float32) * .1),
    }


def _loader(seed):
    return lambda: Predictor(symbol=_mlp(), arg_params=_params(seed),
                             input_shapes={'data': (1, DIM)})


def _ref(seed, x):
    return Predictor(symbol=_mlp(), arg_params=_params(seed),
                     input_shapes={'data': (x.shape[0], DIM)}).forward(
                         data=x)[0].asnumpy()


def _x(rows, seed=0):
    return np.random.RandomState(seed).randn(rows, DIM).astype(np.float32)


# ---------------------------------------------------------------------------
# registry: residency, paging, re-warm
# ---------------------------------------------------------------------------

def test_registry_infer_parity_and_unknown_model():
    with ModelRegistry() as reg:
        reg.register('m', loader=_loader(1), max_batch=4, max_wait_us=0)
        x = _x(2, seed=3)
        out = reg.infer('m', x)
        np.testing.assert_allclose(out[0], _ref(1, x), rtol=2e-6,
                                   atol=1e-6)
        np.testing.assert_allclose(reg.predict('m', x), out[0])
        with pytest.raises(MXNetError, match='unknown model'):
            reg.infer('nope', x)
        with pytest.raises(MXNetError, match='already registered'):
            reg.register('m', loader=_loader(1))
    with pytest.raises(MXNetError, match='closed'):
        reg.infer('m', x)


def test_registry_lru_evict_rewarm_zero_compiles():
    # budget fits ONE tiny model: alternating traffic pages m1/m2 in
    # and out; after each model warmed once, further evict/re-warm
    # cycles must hit exec_cache for every rung — zero new compiles
    x = _x(2, seed=5)
    ref1, ref2 = _ref(1, x), _ref(2, x)
    with ModelRegistry(budget_bytes=400) as reg:
        reg.register('m1', loader=_loader(1), max_batch=4,
                     max_wait_us=0)
        reg.register('m2', loader=_loader(2), max_batch=4,
                     max_wait_us=0)
        np.testing.assert_allclose(reg.infer('m1', x)[0], ref1,
                                   rtol=2e-6, atol=1e-6)
        np.testing.assert_allclose(reg.infer('m2', x)[0], ref2,
                                   rtol=2e-6, atol=1e-6)
        st = reg.stats()
        assert st['evictions'] >= 1          # m1 was paged out
        assert st['resident_bytes'] <= 400
        before = exec_cache.stats()['misses']
        for _ in range(2):
            np.testing.assert_allclose(reg.infer('m1', x)[0], ref1,
                                       rtol=2e-6, atol=1e-6)
            np.testing.assert_allclose(reg.infer('m2', x)[0], ref2,
                                       rtol=2e-6, atol=1e-6)
        assert exec_cache.stats()['misses'] == before
        st = reg.stats()
        assert st['evictions'] >= 4
        assert st['models']['m2']['resident']
        assert not st['models']['m1']['resident']


def test_registry_pinned_source_never_evicted():
    # a live Predictor's weights exist only in memory: registered
    # pinned, counted in the ledger, never paged out even when a
    # colder-by-LRU load overshoots the budget
    pred = _loader(1)()
    with ModelRegistry(budget_bytes=400) as reg:
        reg.register('pinned', source=pred, max_batch=4, max_wait_us=0)
        reg.register('pageable', loader=_loader(2), max_batch=4,
                     max_wait_us=0)
        x = _x(1)
        reg.infer('pageable', x)
        reg.infer('pinned', x)           # over budget: pageable pays
        st = reg.stats()
        assert st['models']['pinned']['resident']
        assert st['models']['pinned']['pinned']
        assert not st['models']['pageable']['resident']
        # even a hopeless budget never pages the pinned model out
        reg.budget_bytes = 1
        reg._enforce_budget()
        assert reg.stats()['models']['pinned']['resident']
        # manual evict refuses too: the loader would hand back the
        # same closed object forever (regression)
        with pytest.raises(MXNetError, match='pinned'):
            reg.evict('pinned')
        assert reg.stats()['models']['pinned']['resident']


def test_registry_priority_evict_order():
    # three resident models over budget: the LOWEST priority goes
    # first even when it is the most recently used
    with ModelRegistry() as reg:      # budget set after warm
        reg.register('low', loader=_loader(1), slo=SLO(priority=0),
                     max_batch=2, max_wait_us=0)
        reg.register('high', loader=_loader(2), slo=SLO(priority=2),
                     max_batch=2, max_wait_us=0)
        x = _x(1)
        reg.infer('high', x)
        time.sleep(0.01)
        reg.infer('low', x)           # most recent, lowest priority
        reg.budget_bytes = 400
        reg._enforce_budget()
        st = reg.stats()
        assert not st['models']['low']['resident']
        assert st['models']['high']['resident']


def test_registry_prefix_loader_from_checkpoint(tmp_path):
    # the production shape: register by checkpoint prefix; re-warm
    # after manual eviction reloads params from disk
    prefix = str(tmp_path / 'fleet_model')
    model_mod.save_checkpoint(prefix, 3, _mlp(), _params(9), {})
    x = _x(2, seed=1)
    with ModelRegistry() as reg:
        reg.register('ckpt', prefix=prefix, epoch=3,
                     input_shapes={'data': (1, DIM)}, max_batch=4,
                     max_wait_us=0)
        np.testing.assert_allclose(reg.infer('ckpt', x)[0], _ref(9, x),
                                   rtol=2e-6, atol=1e-6)
        reg.evict('ckpt')
        assert not reg.stats()['models']['ckpt']['resident']
        np.testing.assert_allclose(reg.infer('ckpt', x)[0], _ref(9, x),
                                   rtol=2e-6, atol=1e-6)
    with pytest.raises(MXNetError, match='exactly one of'):
        ModelRegistry().register('bad', prefix=prefix,
                                 loader=_loader(1))


def test_registry_unregister_removes_and_frees():
    x = _x(1)
    with ModelRegistry() as reg:
        reg.register('m', loader=_loader(1), max_batch=2,
                     max_wait_us=0)
        reg.infer('m', x)
        assert reg.stats()['resident_bytes'] > 0
        reg.unregister('m')
        assert reg.stats()['resident_bytes'] == 0
        with pytest.raises(MXNetError, match='unknown model'):
            reg.infer('m', x)
        with pytest.raises(MXNetError, match='unknown model'):
            reg.unregister('m')
        # the name is free for a new registration (version hot-swap)
        reg.register('m', loader=_loader(2), max_batch=2,
                     max_wait_us=0)
        np.testing.assert_allclose(reg.infer('m', x)[0], _ref(2, x),
                                   rtol=2e-6, atol=1e-6)
        # unregister applies to pinned models too: it is explicit
        # destruction, unlike budget eviction
        reg.register('pinned', source=_loader(1)(), max_batch=2,
                     max_wait_us=0)
        reg.infer('pinned', x)
        reg.unregister('pinned')
        assert 'pinned' not in reg.models()


def test_registry_strict_budget_refuses_typed(monkeypatch):
    # budget fits one model; the other is PINNED so nothing is
    # evictable: non-strict overshoots transiently (documented PR-10
    # behavior), strict refuses with the typed error and undoes the
    # load
    x = _x(1)
    monkeypatch.delenv('MXNET_TPU_SERVE_STRICT_BUDGET', raising=False)
    with ModelRegistry(budget_bytes=400) as reg:
        reg.register('pinned', source=_loader(1)(), max_batch=2,
                     max_wait_us=0)
        reg.register('extra', loader=_loader(2), max_batch=2,
                     max_wait_us=0)
        reg.infer('pinned', x)
        reg.infer('extra', x)            # non-strict: overshoot stands
        assert reg.stats()['resident_bytes'] > 400
    monkeypatch.setenv('MXNET_TPU_SERVE_STRICT_BUDGET', '1')
    with ModelRegistry(budget_bytes=400) as reg:
        reg.register('pinned', source=_loader(1)(), max_batch=2,
                     max_wait_us=0)
        reg.register('extra', loader=_loader(2), max_batch=2,
                     max_wait_us=0)
        reg.infer('pinned', x)
        with pytest.raises(BudgetExceeded) as ei:
            reg.infer('extra', x)
        assert isinstance(ei.value, MXNetError)   # typed AND catchable
        assert ei.value.budget_bytes == 400
        st = reg.stats()
        assert st['strict_budget'] is True
        assert not st['models']['extra']['resident']  # load undone
        assert st['resident_bytes'] <= 400
        # the pinned tenant keeps serving
        np.testing.assert_allclose(reg.infer('pinned', x)[0],
                                   _ref(1, x), rtol=2e-6, atol=1e-6)


def test_registry_strict_budget_preload_refusal(monkeypatch, tmp_path):
    # a prefix model carries a size estimate (the params file): under
    # strict budget an unsatisfiable load is refused BEFORE the load
    # spends memory — the loads counter must not move
    monkeypatch.setenv('MXNET_TPU_SERVE_STRICT_BUDGET', '1')
    prefix = str(tmp_path / 'big')
    model_mod.save_checkpoint(prefix, 0, _mlp(), _params(3), {})
    with ModelRegistry(budget_bytes=100) as reg:   # < params bytes
        reg.register('big', prefix=prefix, epoch=0,
                     input_shapes={'data': (1, DIM)}, max_batch=2,
                     max_wait_us=0)
        with pytest.raises(BudgetExceeded):
            reg.infer('big', _x(1))
        st = reg.stats()
        assert st['loads'] == 0          # refused before loading
        assert st['resident_bytes'] == 0


def test_registry_preload_eviction_keeps_peak_under_budget(tmp_path):
    # with a known size estimate the budget is enforced BEFORE the
    # load: the colder model pages out first and the resident
    # high-water mark never overshoots (the PR-10 "transient
    # overshoot" caveat, closed when the estimate exists).  The
    # budget must sit ABOVE one model's ESTIMATE (params file ~588
    # bytes here) — an over-budget estimate skips pre-eviction
    # entirely (hopeless loads must not destroy resident tenants)
    # — and below two models' actual bytes so paging happens.
    prefix = str(tmp_path / 'est')
    model_mod.save_checkpoint(prefix, 0, _mlp(), _params(4), {})
    x = _x(1)
    with ModelRegistry(budget_bytes=620) as reg:
        reg.register('a', prefix=prefix, epoch=0,
                     input_shapes={'data': (1, DIM)}, max_batch=2,
                     max_wait_us=0)
        reg.register('b', prefix=prefix, epoch=0,
                     input_shapes={'data': (1, DIM)}, max_batch=2,
                     max_wait_us=0)
        reg.infer('a', x)
        reg.infer('b', x)                # evicts 'a' BEFORE loading
        reg.infer('a', x)                # and back again
        st = reg.stats()
        assert st['evictions'] >= 2
        assert st['peak_resident_bytes'] <= 620
        # and the hopeless-load guard: an estimate OVER the whole
        # budget skips pre-eviction (no point destroying resident
        # tenants) but non-strict still serves via the post-load path
        reg.budget_bytes = 200
        out = reg.infer('b', x)
        assert out[0].shape == (1, OUT)


# ---------------------------------------------------------------------------
# SLO: deadline-derived holds, shed-on-backlog
# ---------------------------------------------------------------------------

def test_slo_deadline_drives_batcher_hold():
    # deadline 40ms, default WAIT_FRACTION 0.25 -> 10ms hold, NOT the
    # global MXNET_TPU_SERVE_WAIT_US knob; a lone request therefore
    # flushes after ~10ms instead of the single-knob engine's hold
    assert SLO(deadline_ms=40).wait_us() == 10000
    assert SLO().wait_us() is None       # no deadline: global knob
    with ModelRegistry() as reg:
        reg.register('m', loader=_loader(1),
                     slo=SLO(deadline_ms=40), max_batch=8)
        eng = reg.engine('m')
        assert eng.max_wait_us == 10000
        # explicit engine kwarg still wins over the derivation
        reg.register('m2', loader=_loader(2),
                     slo=SLO(deadline_ms=40), max_batch=8,
                     max_wait_us=123)
        assert reg.engine('m2').max_wait_us == 123


def test_shed_on_backlog_typed_error():
    profiler.clear()
    with ModelRegistry() as reg:
        # 500ms per-row hint against a 1ms deadline: the very first
        # request is already hopeless — typed shed, never enqueued
        reg.register('m', loader=_loader(1),
                     slo=SLO(deadline_ms=1.0, service_ms_hint=500.0),
                     max_batch=4, max_wait_us=0)
        with pytest.raises(Overloaded) as ei:
            reg.infer('m', _x(1))
        e = ei.value
        assert e.model == 'm'
        assert e.est_ms > e.deadline_ms == 1.0
        assert e.retry_after_ms >= 1.0
        assert isinstance(e, MXNetError)     # typed AND catchable as
        assert reg.engine('m').stats()['requests'] == 0
        assert reg.stats()['shed_requests'] == 1
    assert profiler.fleet_stats()['fleet_shed_requests'] == 1


def test_shed_hard_queue_cap():
    with ModelRegistry() as reg:
        reg.max_queue_rows = 0           # every backlog is too deep
        reg.register('m', loader=_loader(1), max_batch=4,
                     max_wait_us=1000000)
        eng = reg.engine('m')
        t = threading.Thread(target=lambda: eng.infer(_x(1)))
        t.start()                        # parks one row in the queue
        deadline = time.time() + 10
        while time.time() < deadline and eng.backlog_rows() == 0:
            time.sleep(0.005)
        with pytest.raises(Overloaded):
            reg.infer('m', _x(1))
        eng.close()                      # drains the parked request
        t.join(timeout=30)
        assert not t.is_alive()


def test_measured_service_rate_takes_over_hint():
    # after real traffic the engine-local EMA replaces the hint: a
    # generous deadline admits even with a catastrophic hint
    with ModelRegistry() as reg:
        reg.register('m', loader=_loader(1),
                     slo=SLO(deadline_ms=60000.0,
                             service_ms_hint=50000.0),
                     max_batch=4, max_wait_us=0)
        out = reg.infer('m', _x(1))      # admitted: 50s < 60s deadline
        assert out[0].shape == (1, OUT)
        eng = reg.engine('m')
        est = eng.service_estimate()
        assert est is not None
        svc_ms, rows = est
        assert 0 < svc_ms < 50000.0 and rows >= 1.0


# ---------------------------------------------------------------------------
# HTTP front
# ---------------------------------------------------------------------------

def _post(url, payload):
    req = urllib.request.Request(
        url, data=json.dumps(payload).encode(),
        headers={'Content-Type': 'application/json'})
    return urllib.request.urlopen(req, timeout=30)


def test_http_predict_healthz_statsz_roundtrip():
    with ModelRegistry() as reg:
        reg.register('m', loader=_loader(1), max_batch=4,
                     max_wait_us=0)
        with HttpFront(reg, port=0).start() as front:
            host, port = front.address
            base = 'http://%s:%d' % (host, port)
            x = _x(2, seed=8)
            resp = _post('%s/v1/models/m:predict' % base,
                         {'instances': x.tolist()})
            assert resp.status == 200
            outs = json.loads(resp.read())['outputs']
            np.testing.assert_allclose(np.asarray(outs[0]), _ref(1, x),
                                       rtol=2e-6, atol=1e-5)
            # named-inputs form
            resp = _post('%s/v1/models/m:predict' % base,
                         {'inputs': {'data': x.tolist()}})
            assert resp.status == 200
            h = urllib.request.urlopen('%s/healthz' % base, timeout=30)
            assert h.status == 200
            assert json.loads(h.read())['models'] == ['m']
            s = urllib.request.urlopen('%s/statsz' % base, timeout=30)
            st = json.loads(s.read())
            assert st['models']['m']['resident']
            assert st['models']['m']['engine']['requests'] >= 2
            assert st['http']['requests'] >= 2
            # error mapping
            with pytest.raises(urllib.error.HTTPError) as ei:
                _post('%s/v1/models/ghost:predict' % base,
                      {'instances': x.tolist()})
            assert ei.value.code == 404
            with pytest.raises(urllib.error.HTTPError) as ei:
                _post('%s/v1/models/m:predict' % base, {'bogus': 1})
            assert ei.value.code == 400
            with pytest.raises(urllib.error.HTTPError) as ei:
                urllib.request.urlopen('%s/nothing' % base, timeout=30)
            assert ei.value.code == 404


def test_http_backpressure_429_and_shed_mapping():
    profiler.clear()
    with ModelRegistry() as reg:
        reg.register('m', loader=_loader(1), max_batch=4,
                     max_wait_us=0)
        reg.register('shed', loader=_loader(2),
                     slo=SLO(deadline_ms=1.0, service_ms_hint=500.0),
                     max_batch=4, max_wait_us=0)
        # max_inflight=0: the bounded admission gate itself 429s
        with HttpFront(reg, port=0, max_inflight=0).start() as front:
            base = 'http://%s:%d' % front.address
            with pytest.raises(urllib.error.HTTPError) as ei:
                _post('%s/v1/models/m:predict' % base,
                      {'instances': _x(1).tolist()})
            assert ei.value.code == 429
            assert int(ei.value.headers['Retry-After']) >= 1
            # health stays green: backpressure is not sickness
            h = urllib.request.urlopen('%s/healthz' % base, timeout=30)
            assert h.status == 200
        # an SLO shed maps to 429 with the Overloaded detail
        with HttpFront(reg, port=0).start() as front:
            base = 'http://%s:%d' % front.address
            with pytest.raises(urllib.error.HTTPError) as ei:
                _post('%s/v1/models/shed:predict' % base,
                      {'instances': _x(1).tolist()})
            assert ei.value.code == 429
            body = json.loads(ei.value.read())
            assert body['error'] == 'overloaded'
            assert body['deadline_ms'] == 1.0
            assert 'Retry-After' in ei.value.headers
    fl = profiler.fleet_stats()
    assert fl['fleet_http_requests'] >= 2
    assert fl['fleet_http_429'] >= 2


def test_http_keepalive_survives_early_replies():
    # HTTP/1.1 keep-alive: an early 404/429 must DRAIN the request
    # body first — unread bytes would be parsed as the next request
    # line on the persistent connection (regression)
    import http.client
    with ModelRegistry() as reg:
        reg.register('m', loader=_loader(1), max_batch=4,
                     max_wait_us=0)
        with HttpFront(reg, port=0).start() as front:
            host, port = front.address
            conn = http.client.HTTPConnection(host, port, timeout=30)
            try:
                body = json.dumps(
                    {'instances': _x(1).tolist()}).encode()
                # 1: unknown model -> 404 replied before body consumed
                conn.request('POST', '/v1/models/ghost:predict', body,
                             {'Content-Type': 'application/json'})
                r = conn.getresponse()
                assert r.status == 404
                r.read()
                # 2: SAME connection must still serve a good request
                conn.request('POST', '/v1/models/m:predict', body,
                             {'Content-Type': 'application/json'})
                r = conn.getresponse()
                assert r.status == 200
                out = json.loads(r.read())['outputs']
                assert np.asarray(out[0]).shape == (1, OUT)
            finally:
                conn.close()


def test_http_priority_reserve_admits_interactive_tenant():
    # one in-flight slot total, reserved for priority >= 1: the
    # batch tenant 429s at the gate while the interactive one serves
    with ModelRegistry() as reg:
        reg.register('batch', loader=_loader(1), max_batch=4,
                     max_wait_us=0)                    # priority 0
        reg.register('inter', loader=_loader(2), slo=SLO(priority=1),
                     max_batch=4, max_wait_us=0)
        with HttpFront(reg, port=0, max_inflight=1,
                       priority_reserve=1).start() as front:
            base = 'http://%s:%d' % front.address
            with pytest.raises(urllib.error.HTTPError) as ei:
                _post('%s/v1/models/batch:predict' % base,
                      {'instances': _x(1).tolist()})
            assert ei.value.code == 429
            resp = _post('%s/v1/models/inter:predict' % base,
                         {'instances': _x(1).tolist()})
            assert resp.status == 200


# ---------------------------------------------------------------------------
# continuous batching
# ---------------------------------------------------------------------------

CDIM, CHID, COUT = 5, 4, 2


def _cell():
    data = sym.Variable('data')
    h_in = sym.Variable('h')
    pre = sym.FullyConnected(data, num_hidden=CHID, name='ix') + \
        sym.FullyConnected(h_in, num_hidden=CHID, no_bias=True,
                           name='hh')
    h_new = sym.Activation(pre, act_type='tanh')
    head = sym.FullyConnected(h_new, num_hidden=COUT, name='out')
    return sym.Group([head, h_new])


def _cell_params(seed=3):
    rs = np.random.RandomState(seed)
    return {
        'ix_weight': nd.array(rs.randn(CHID, CDIM).astype(np.float32)
                              * .3),
        'ix_bias': nd.array(np.zeros(CHID, np.float32)),
        'hh_weight': nd.array(rs.randn(CHID, CHID).astype(np.float32)
                              * .3),
        'out_weight': nd.array(rs.randn(COUT, CHID).astype(np.float32)
                               * .3),
        'out_bias': nd.array(np.zeros(COUT, np.float32)),
    }


def _cont(slots=2, convoy=False, **kw):
    return ContinuousEngine(_cell(), arg_params=_cell_params(),
                            data_shape=(CDIM,),
                            state_shapes={'h': (CHID,)},
                            state_outputs={'h': 1}, slots=slots,
                            convoy=convoy, **kw)


def _seqs(lens, seed=0):
    rs = np.random.RandomState(seed)
    return [rs.randn(L, CDIM).astype(np.float32) for L in lens]


def test_continuous_matches_host_recurrence():
    p = {k: v.asnumpy() for k, v in _cell_params().items()}
    seq = _seqs([6])[0]
    with _cont(slots=3) as eng:
        out = eng.infer(seq)
    assert [o.shape for o in out] == [(6, COUT)]
    h = np.zeros(CHID, np.float32)
    ys = []
    for t in range(6):
        h = np.tanh(seq[t] @ p['ix_weight'].T + p['ix_bias'] +
                    h @ p['hh_weight'].T)
        ys.append(h @ p['out_weight'].T + p['out_bias'])
    np.testing.assert_allclose(out[0], np.stack(ys), rtol=1e-5,
                               atol=1e-5)


def test_continuous_admit_retire_bit_parity_vs_solo():
    # mixed lengths co-resident (admit/retire mid-flight) must be
    # BIT-identical to each sequence run alone: same program shape,
    # row-independent cell
    seqs = _seqs([3, 9, 2, 6, 4], seed=4)
    with _cont(slots=2) as eng:
        solo = [eng.infer(s) for s in seqs]      # one at a time
        res = [None] * len(seqs)

        def client(i):
            res[i] = eng.infer(seqs[i])

        ts = [threading.Thread(target=client, args=(i,))
              for i in range(len(seqs))]
        for t in ts:
            t.start()
        for t in ts:
            t.join()
        st = eng.stats()
    for i in range(len(seqs)):
        for a, b in zip(res[i], solo[i]):
            assert np.array_equal(a, b)
    assert st['admitted'] == st['retired'] == 2 * len(seqs)
    assert st['compiles_after_warmup'] == 0


def test_continuous_beats_convoy_ticks_deterministic():
    # 2 slots, lengths [2, 8, 2, 8] submitted atomically:
    # continuous packs freed slots mid-flight -> 12 ticks; convoy
    # admits only into an empty batch -> two 8-tick waves = 16
    seqs = _seqs([2, 8, 2, 8], seed=6)
    with _cont(slots=2) as eng:
        cont_res = eng.infer_many(seqs)
        cont = eng.stats()
    with _cont(slots=2, convoy=True) as eng:
        conv_res = eng.infer_many(seqs)
        conv = eng.stats()
    assert cont['ticks'] == 12
    assert conv['ticks'] == 16
    assert cont['utilization'] > conv['utilization']
    for a, b in zip(cont_res, conv_res):     # same answers either way
        for u, v in zip(a, b):
            assert np.array_equal(u, v)


def test_continuous_recreated_engine_zero_compiles():
    with _cont(slots=2) as eng:
        eng.infer(_seqs([3])[0])
    before = exec_cache.stats()['misses']
    with _cont(slots=2) as eng:
        eng.infer(_seqs([3])[0])
        assert eng.stats()['compiles_after_warmup'] == 0
    assert exec_cache.stats()['misses'] == before


def test_continuous_rejects_bad_specs():
    with pytest.raises(MXNetError, match='data_shape'):
        ContinuousEngine(_cell(), arg_params=_cell_params())
    with pytest.raises(MXNetError, match='same states'):
        ContinuousEngine(_cell(), arg_params=_cell_params(),
                         data_shape=(CDIM,),
                         state_shapes={'h': (CHID,)},
                         state_outputs={'g': 1})
    with pytest.raises(MXNetError, match='out of range'):
        ContinuousEngine(_cell(), arg_params=_cell_params(),
                         data_shape=(CDIM,),
                         state_shapes={'h': (CHID,)},
                         state_outputs={'h': 5})
    with _cont(slots=2) as eng:
        with pytest.raises(MXNetError, match='sequence shape'):
            eng.infer(np.zeros((4, CDIM + 1), np.float32))
        with pytest.raises(MXNetError, match='sequence shape'):
            eng.infer(np.zeros((0, CDIM), np.float32))


def test_continuous_close_rejects_new_and_drains():
    eng = _cont(slots=2)
    res = {}

    def client():
        res['out'] = eng.infer(_seqs([30])[0])

    t = threading.Thread(target=client)
    t.start()
    deadline = time.time() + 10
    while time.time() < deadline and eng.stats()['admitted'] == 0:
        time.sleep(0.005)
    eng.close()                          # in-flight sequence finishes
    t.join(timeout=30)
    assert not t.is_alive()
    assert res['out'][0].shape == (30, COUT)
    with pytest.raises(MXNetError, match='closed'):
        eng.infer(_seqs([2])[0])
    eng.close()                          # idempotent


# ---------------------------------------------------------------------------
# chunked continuous serving (tick_chunk=K)
# ---------------------------------------------------------------------------

def test_chunked_matches_unchunked_bitwise():
    # lengths NOT multiples of K and more sequences than slots: slots
    # retire mid-chunk (masked to the boundary) and re-admit — the
    # K-tick scan program must stay BIT-identical to the unchunked
    # tick loop, while dispatching K timesteps per XLA call
    seqs = _seqs([3, 9, 2, 6, 4], seed=4)
    with _cont(slots=2) as eng:
        ref = eng.infer_many(seqs)
    with _cont(slots=4, tick_chunk=4) as eng:
        got = eng.infer_many(seqs)
        st = eng.stats()
    for a, b in zip(ref, got):
        for u, v in zip(a, b):
            assert np.array_equal(u, v)
    assert st['tick_chunk'] == 4
    assert st['ticks'] == 4 * st['chunks']
    assert st['compiles_after_warmup'] == 0


def test_chunk_admit_quantization_and_boundary_wait():
    # 4 slots, K=4, lengths [2, 6, 4, 4, 4] submitted atomically.
    # Chunk 1 admits the first four; seq0 retires after 2 ticks but
    # its freed slot stays masked to the boundary while seq4 waits in
    # the queue — those 2 stranded slot-ticks are priced into
    # boundary_wait_ms.  Chunk 2 admits seq4 and retires everything:
    # 8 ticks in 2 dispatches, deterministic
    seqs = _seqs([2, 6, 4, 4, 4], seed=8)
    with _cont(slots=4, tick_chunk=4) as eng:
        res = eng.infer_many(seqs)
        st = eng.stats()
    with _cont(slots=2) as eng:
        ref = eng.infer_many(seqs)
    for a, b in zip(ref, res):
        for u, v in zip(a, b):
            assert np.array_equal(u, v)
    assert st['chunks'] == 2 and st['ticks'] == 8
    assert st['admitted'] == 5 and st['retired'] == 5
    assert st['boundary_wait_ms'] > 0


def test_chunked_recreated_engine_zero_compiles():
    with _cont(slots=4, tick_chunk=4) as eng:
        eng.infer(_seqs([6])[0])
    before = exec_cache.stats()['misses']
    with _cont(slots=4, tick_chunk=4) as eng:
        eng.infer(_seqs([6])[0])
        assert eng.stats()['compiles_after_warmup'] == 0
    assert exec_cache.stats()['misses'] == before


def test_chunked_programs_never_alias_unchunked():
    # same cell + slot count at K=1 vs K=4: distinct exec_cache
    # program families.  With both warmed, re-creating EITHER flavor
    # hits its own cached programs — no cross-aliasing, no recompiles,
    # and the two engines still agree bit-for-bit
    with _cont(slots=4) as eng:
        eng.infer(_seqs([5])[0])
    with _cont(slots=4, tick_chunk=4) as eng:
        eng.infer(_seqs([5])[0])
    before = exec_cache.stats()['misses']
    with _cont(slots=4) as eng:
        a = eng.infer(_seqs([5])[0])
    with _cont(slots=4, tick_chunk=4) as eng:
        b = eng.infer(_seqs([5])[0])
    assert exec_cache.stats()['misses'] == before
    for u, v in zip(a, b):
        assert np.array_equal(u, v)


def test_chunk_lone_and_exact_fill_fast_paths():
    # the two request-shaped shortcuts ported from the coalescer: a
    # LONE active request runs the narrow probe-gated rung, an
    # exact-fill chunk (every slot active all K ticks) skips the
    # staging memset — both counted, both bit-identical
    with _cont(slots=4, tick_chunk=4) as eng:
        st0 = eng.stats()
        assert st0['lone_fast_path'], \
            'lone rung disabled (probe failed at widths 1 and 2)'
        assert st0['lone_fast_path_width'] in (1, 2)
        exact_seqs = _seqs([8] * 4, seed=9)
        res = eng.infer_many(exact_seqs)     # 2 exact-fill chunks
        lone_seq = _seqs([8], seed=10)[0]
        lone_res = eng.infer(lone_seq)       # 2 lone chunks
        st = eng.stats()
    assert st['exact_fill_admits'] == 2
    assert st['lone_fast_path_hits'] == 2
    with _cont(slots=2) as eng:
        ref = eng.infer_many(exact_seqs)
        lone_ref = eng.infer(lone_seq)
    for a, b in zip(ref, res):
        for u, v in zip(a, b):
            assert np.array_equal(u, v)
    for u, v in zip(lone_ref, lone_res):
        assert np.array_equal(u, v)


def test_tick_chunk_knob_parse_and_reject(monkeypatch):
    monkeypatch.delenv(TICK_CHUNK_KNOB, raising=False)
    assert resolve_tick_chunk(None) == 1
    for off in (0, '0', 'off', 'none', 'false', '', 1, '1'):
        assert resolve_tick_chunk(off) == 1
    assert resolve_tick_chunk(4, slots=8) == 4
    assert resolve_tick_chunk('6', slots=8) == 6
    monkeypatch.setenv(TICK_CHUNK_KNOB, '4')
    assert resolve_tick_chunk(None, slots=8) == 4
    monkeypatch.setenv(TICK_CHUNK_KNOB, 'off')
    assert resolve_tick_chunk(None, slots=8) == 1
    monkeypatch.delenv(TICK_CHUNK_KNOB)
    with pytest.raises(MXNetError, match=TICK_CHUNK_KNOB):
        resolve_tick_chunk('garbage')
    with pytest.raises(MXNetError, match='K <= slots'):
        resolve_tick_chunk(8, slots=4)
    with pytest.raises(MXNetError, match='>= 0'):
        resolve_tick_chunk(-2)
    # the engine routes through the same parser against its slots
    with pytest.raises(MXNetError, match=TICK_CHUNK_KNOB):
        _cont(slots=2, tick_chunk=5)
    # ...including the env knob
    monkeypatch.setenv(TICK_CHUNK_KNOB, '2')
    with _cont(slots=2) as eng:
        assert eng.stats()['tick_chunk'] == 2


def test_tick_chunk_slo_derived_default(monkeypatch):
    monkeypatch.delenv(TICK_CHUNK_KNOB, raising=False)
    monkeypatch.delenv('MXNET_TPU_SERVE_WAIT_FRACTION', raising=False)
    # spend the SLO wait fraction (0.25) of the deadline on boundary
    # ticks: K = 1 + int(40 * 0.25 / 1.0), capped at the slot count
    assert chunk_for_deadline(40.0, 1.0) == 11
    assert chunk_for_deadline(40.0, 1.0, slots=4) == 4
    assert resolve_tick_chunk(None, slots=4, slo=SLO(deadline_ms=40.0),
                              tick_ms_hint=1.0) == 4
    # no per-tick service hint -> no derivation -> unchunked
    assert resolve_tick_chunk(None, slots=4,
                              slo=SLO(deadline_ms=40.0)) == 1
    with _cont(slots=4, slo=SLO(deadline_ms=40.0),
               tick_ms_hint=1.0) as eng:
        assert eng.stats()['tick_chunk'] == 4


def test_registry_forwards_tick_chunk():
    seen = {}

    def cont_loader(tick_chunk=None):
        seen['tick_chunk'] = tick_chunk
        return _cont(slots=4, tick_chunk=tick_chunk)

    with ModelRegistry() as reg:
        reg.register('seq', loader=cont_loader, tick_chunk=4)
        eng = reg.engine('seq')
        assert seen['tick_chunk'] == 4
        assert eng.stats()['tick_chunk'] == 4
        # 0/'off'/1 resolve to unchunked at register time: the loader
        # is called WITHOUT the kwarg (its own default applies)
        reg.register('seq2', loader=cont_loader, tick_chunk='off')
        reg.engine('seq2')
        assert seen['tick_chunk'] is None
        with pytest.raises(MXNetError, match='tick_chunk'):
            reg.register('ckpt', prefix='/nonexistent/model',
                         tick_chunk=4)
        with pytest.raises(MXNetError, match=TICK_CHUNK_KNOB):
            reg.register('bad', loader=cont_loader,
                         tick_chunk='garbage')


def test_chunk_profiler_counters_flow():
    profiler.clear()
    with _cont(slots=4, tick_chunk=4) as eng:
        eng.infer_many(_seqs([6, 6], seed=11))
    fs = profiler.fleet_stats()
    assert fs['cont_chunks_dispatched'] >= 2
    assert fs['cont_chunk_ticks'] == 4 * fs['cont_chunks_dispatched']
    assert isinstance(fs['cont_boundary_wait_ms'], float)
    for key in ('cont_lone_fast_path', 'cont_exact_fill_admits'):
        assert key in fs
    text = profiler.summary(print_out=False)
    assert 'cont_chunks_dispatched' in text
    assert 'cont_boundary_wait_ms' in text
    profiler.clear()
    # type-preserving clear: the float-seeded counter must keep
    # accumulating fractional ms after a reset
    assert profiler.fleet_stats()['cont_boundary_wait_ms'] == 0.0
    profiler.add_fleet_stats(cont_boundary_wait_ms=0.5)
    assert profiler.fleet_stats()['cont_boundary_wait_ms'] == 0.5
    profiler.clear()


# ---------------------------------------------------------------------------
# double-buffered chunk staging (stage_ahead) + tick_chunk='auto'
# ---------------------------------------------------------------------------

def test_staged_chunks_bit_parity_vs_serialized():
    # stage_ahead=1 pipelines chunk t+1's staging+dispatch behind
    # chunk t's in-flight execution; stage_ahead=0 is the PR-17
    # serialized stage->dispatch->drain loop.  Identical K: answers
    # must stay bitwise equal — sequential, AND under concurrent
    # clients racing admission into staged chunks
    seqs = _seqs([3, 9, 2, 6, 4], seed=4)
    with _cont(slots=4, tick_chunk=4, stage_ahead=0) as eng:
        ref = eng.infer_many(seqs)
        st0 = eng.stats()
    with _cont(slots=4, tick_chunk=4, stage_ahead=1) as eng:
        got = eng.infer_many(seqs)
        res = [None] * len(seqs)
        ts = [threading.Thread(target=lambda i=i:
                               res.__setitem__(i, eng.infer(seqs[i])))
              for i in range(len(seqs))]
        for t in ts:
            t.start()
        for t in ts:
            t.join()
        st1 = eng.stats()
    assert st0['stage_ahead'] == 0 and st0['staged_chunks'] == 0
    assert st1['stage_ahead'] == 1 and st1['staged_chunks'] >= 1
    assert st1['stage_overlap_ms'] >= 0.0
    assert st1['compiles_after_warmup'] == 0
    for a, b in zip(ref, got):
        for u, v in zip(a, b):
            assert np.array_equal(u, v)
    for i in range(len(seqs)):
        for a, b in zip(res[i], ref[i]):
            assert np.array_equal(a, b)


def test_stage_ahead_env_knob(monkeypatch):
    # MXNET_TPU_SERVE_STAGE_AHEAD: 'off' forces the serialized loop,
    # an integer sets the shadow-buffer depth; answers identical
    seqs = _seqs([6, 6], seed=5)
    monkeypatch.setenv('MXNET_TPU_SERVE_STAGE_AHEAD', 'off')
    with _cont(slots=4, tick_chunk=4) as eng:
        a = eng.infer_many(seqs)
        st = eng.stats()
        assert st['stage_ahead'] == 0 and st['staged_chunks'] == 0
    monkeypatch.setenv('MXNET_TPU_SERVE_STAGE_AHEAD', '2')
    with _cont(slots=4, tick_chunk=4) as eng:
        b = eng.infer_many(seqs)
        st = eng.stats()
        assert st['stage_ahead'] == 2 and st['staged_chunks'] >= 1
    for x, y in zip(a, b):
        for u, v in zip(x, y):
            assert np.array_equal(u, v)


def test_tick_chunk_auto_requires_deadline():
    # 'auto' without an SLO deadline has nothing to derive K against:
    # typed reject at parse time, at construction, and for a
    # deadline-less (priority-only) SLO
    with pytest.raises(MXNetError, match="'auto' needs an SLO"):
        resolve_tick_chunk('auto', slots=4)
    with pytest.raises(MXNetError, match="'auto' needs an SLO"):
        _cont(slots=4, tick_chunk='auto')
    with pytest.raises(MXNetError, match="'auto' needs an SLO"):
        _cont(slots=4, tick_chunk='auto', slo=SLO(priority=1))


def test_tick_chunk_auto_converges_to_rung_zero_compiles():
    # hintless auto starts at K=1; the first chunk's tick-time EMA
    # against a generous deadline re-derives K onto the top warmed
    # rung (chunk_for_deadline caps at slots) and stays — every rung
    # is warmed at construction, so the climb never compiles.  The
    # mixed K=1-then-K=4 run stays bit-identical to fixed K
    seqs = _seqs([8, 8, 8, 8], seed=6)
    with _cont(slots=4, tick_chunk=4) as eng:
        ref = eng.infer_many(seqs)
    with _cont(slots=4, tick_chunk='auto',
               slo=SLO(deadline_ms=200.0)) as eng:
        got = eng.infer_many(seqs)
        st = eng.stats()
    assert st['auto_tick_chunk'] is True
    assert st['tick_chunk'] == 4, \
        'EMA did not climb onto the slot rung: %r' % (st,)
    assert st['auto_k_decisions'] >= 1
    assert st['tick_ms_ema'] > 0.0
    assert st['compiles_after_warmup'] == 0
    for a, b in zip(ref, got):
        for u, v in zip(a, b):
            assert np.array_equal(u, v)


def test_auto_recreated_engine_zero_compiles_across_k_change():
    # the warmed rung ladder is exec_cache-backed: a re-created auto
    # engine — even one whose tick_ms_hint starts it on a DIFFERENT
    # initial K than the hintless climb — warms at zero cache misses
    kw = dict(slots=4, tick_chunk='auto', slo=SLO(deadline_ms=200.0))
    with _cont(**kw) as eng:
        eng.infer(_seqs([8])[0])
    before = exec_cache.stats()['misses']
    with _cont(tick_ms_hint=0.5, **kw) as eng:   # starts at K=4
        eng.infer(_seqs([8])[0])
        assert eng.stats()['compiles_after_warmup'] == 0
    assert exec_cache.stats()['misses'] == before


def test_registry_forwards_auto_tick_chunk():
    # registry passes the literal 'auto' through unresolved — only
    # the engine holds the SLO deadline the chooser derives against
    seen = {}

    def cont_loader(tick_chunk=None):
        seen['tick_chunk'] = tick_chunk
        return _cont(slots=4, tick_chunk=tick_chunk,
                     slo=SLO(deadline_ms=200.0))

    with ModelRegistry() as reg:
        reg.register('seq', loader=cont_loader, tick_chunk='auto')
        eng = reg.engine('seq')
        assert seen['tick_chunk'] == 'auto'
        assert eng.stats()['auto_tick_chunk'] is True


def test_overlap_profiler_counters_flow():
    # the overlap_* family: staged chunks + auto-K decisions land in
    # overlap_stats(), summary() and the dump_profile 'overlap' lane
    profiler.clear()
    with _cont(slots=4, tick_chunk='auto', stage_ahead=1,
               slo=SLO(deadline_ms=200.0)) as eng:
        eng.infer_many(_seqs([8, 8, 8, 8], seed=7))
    ov = profiler.overlap_stats()
    assert ov['overlap_stage_chunks'] >= 1
    assert ov['overlap_auto_k_decisions'] >= 1
    assert ov['overlap_auto_k'] == 4            # gauge: last choice
    assert isinstance(ov['overlap_stage_overlap_ms'], float)
    text = profiler.summary(print_out=False)
    assert 'overlap_stage_chunks' in text
    assert 'overlap_auto_k' in text
    profiler.clear()


# ---------------------------------------------------------------------------
# close() vs eviction (satellite 2) + per-engine scoping (satellite 1)
# ---------------------------------------------------------------------------

def test_engine_close_safe_under_concurrent_infer_storm():
    # many client threads hammer infer() while another thread closes
    # mid-flight: every call either returns a correct answer or
    # raises the typed closed error — no deadlock, no lost caller
    eng = InferenceEngine(_loader(1)(), max_batch=4, max_wait_us=500)
    x = _x(1, seed=2)
    ref = _ref(1, x)
    results = []
    errors = []

    def client():
        for _ in range(20):
            try:
                results.append(eng.infer(x)[0])
            except MXNetError as e:
                assert 'closed' in str(e)
                errors.append(e)
                return

    ts = [threading.Thread(target=client) for _ in range(6)]
    for t in ts:
        t.start()
    time.sleep(0.05)
    closers = [threading.Thread(target=eng.close) for _ in range(3)]
    for c in closers:
        c.start()
    for t in ts + closers:
        t.join(timeout=60)
    assert not any(t.is_alive() for t in ts + closers)
    assert results                        # some traffic got through
    for out in results:
        np.testing.assert_allclose(out, ref, rtol=2e-6, atol=1e-6)
    eng.close()                           # still idempotent after


def test_registry_eviction_race_is_absorbed():
    # traffic on both models with a budget that fits one: every
    # infer() rides an evict/re-warm storm; the registry retries the
    # closed-engine race internally so callers never see it
    x = _x(1, seed=7)
    ref1, ref2 = _ref(1, x), _ref(2, x)
    with ModelRegistry(budget_bytes=400) as reg:
        reg.register('m1', loader=_loader(1), max_batch=2,
                     max_wait_us=0)
        reg.register('m2', loader=_loader(2), max_batch=2,
                     max_wait_us=0)
        errors = []

        def traffic(name, ref):
            try:
                for _ in range(12):
                    np.testing.assert_allclose(
                        reg.infer(name, x)[0], ref, rtol=2e-6,
                        atol=1e-6)
            except Exception as e:
                errors.append(e)

        ts = [threading.Thread(target=traffic, args=('m1', ref1)),
              threading.Thread(target=traffic, args=('m2', ref2))]
        for t in ts:
            t.start()
        for t in ts:
            t.join(timeout=120)
        assert not any(t.is_alive() for t in ts)
        assert not errors, errors
        assert reg.stats()['evictions'] >= 1


def test_per_engine_counter_scoping():
    # two engines in one process: each stats() attributes ONLY its
    # own traffic in the un-prefixed local window, while the serve_*
    # profiler family stays process-global (documented)
    profiler.clear()
    e1 = InferenceEngine(_loader(1)(), max_batch=4, max_wait_us=0)
    e2 = InferenceEngine(_loader(2)(), max_batch=4, max_wait_us=0)
    try:
        for i in range(3):
            e1.infer(_x(1, seed=i))
        e2.infer(_x(2, seed=9))
        s1, s2 = e1.stats(), e2.stats()
        assert s1['requests'] == 3 and s2['requests'] == 1
        assert s1['latency_p50_ms'] > 0 and s2['latency_p50_ms'] > 0
        assert s1['latency_p99_ms'] >= s1['latency_p50_ms']
        assert s1['service_ms_ema'] > 0
        assert s2['rows_per_batch_ema'] == pytest.approx(2.0)
        assert s1['backlog_rows'] == 0
        # global family spans both engines
        assert s1['serve_requests'] >= 4
    finally:
        e1.close()
        e2.close()


def test_fleet_counters_in_summary_and_dump(tmp_path):
    profiler.clear()
    with ModelRegistry(budget_bytes=400) as reg:
        reg.register('m1', loader=_loader(1), max_batch=2,
                     max_wait_us=0)
        reg.register('m2', loader=_loader(2), max_batch=2,
                     max_wait_us=0)
        reg.infer('m1', _x(1))
        reg.infer('m2', _x(1))
    with _cont(slots=2) as eng:
        eng.infer(_seqs([3])[0])
    fl = profiler.fleet_stats()
    assert fl['fleet_loads'] >= 2
    assert fl['fleet_evictions'] >= 1
    assert fl['cont_ticks'] >= 3
    assert 0 < fl['cont_utilization'] <= 1
    text = profiler.summary(print_out=False)
    for key in ('fleet_loads', 'fleet_evictions', 'fleet_http_requests',
                'fleet_resident_bytes', 'cont_utilization'):
        assert key in text
    out = tmp_path / 'fleet_profile.json'
    profiler.profiler_set_config(filename=str(out))
    profiler.dump_profile()
    events = json.loads(out.read_text())['traceEvents']
    meta = [e for e in events if e.get('name') == 'fleet']
    assert meta and meta[0]['args']['fleet_loads'] >= 2
