"""Imperative autograd tests (model: reference
tests/python/unittest/test_autograd.py)."""
import numpy as np

import mxnet_tpu as mx
from mxnet_tpu import nd, autograd


def test_simple_grad():
    x = nd.array([1.0, 2.0, 3.0])
    x.attach_grad()
    with autograd.record():
        y = (x * x).sum()
    y.backward()
    np.testing.assert_allclose(x.grad.asnumpy(), [2, 4, 6])


def test_chain_grad():
    x = nd.array([0.5, 1.0])
    x.attach_grad()
    with autograd.record():
        y = nd.exp(x) * 2
        z = y.sum()
    z.backward()
    np.testing.assert_allclose(x.grad.asnumpy(), 2 * np.exp([0.5, 1.0]),
                               rtol=1e-5)


def test_grad_accumulation_two_uses():
    x = nd.array([2.0])
    x.attach_grad()
    with autograd.record():
        y = x * x + x * 3
    y.backward()
    np.testing.assert_allclose(x.grad.asnumpy(), [2 * 2 + 3])


def test_head_grad():
    x = nd.array([1.0, 2.0])
    x.attach_grad()
    with autograd.record():
        y = x * 2
    y.backward(nd.array([10.0, 100.0]))
    np.testing.assert_allclose(x.grad.asnumpy(), [20, 200])


def test_is_training_flags():
    assert not autograd.is_recording()
    assert not autograd.is_training()
    with autograd.record():
        assert autograd.is_recording()
        assert autograd.is_training()
        with autograd.pause():
            assert not autograd.is_recording()
    assert not autograd.is_recording()


def test_dropout_respects_mode():
    x = nd.ones((1000,))
    out_predict = nd.Dropout(x, p=0.5)
    np.testing.assert_allclose(out_predict.asnumpy(), x.asnumpy())
    with autograd.record():
        out_train = nd.Dropout(x, p=0.5)
    frac = (out_train.asnumpy() == 0).mean()
    assert 0.3 < frac < 0.7


def test_mark_variables_grad_fn():
    x = nd.array([3.0])
    w = nd.array([4.0])
    autograd.mark_variables([x, w], [nd.zeros((1,)), nd.zeros((1,))])
    with autograd.record():
        y = x * w
    autograd.backward([y])
    np.testing.assert_allclose(x.grad.asnumpy(), [4.0])
    np.testing.assert_allclose(w.grad.asnumpy(), [3.0])


def test_grad_function():
    x = nd.array([1.0, 2.0])
    g = autograd.grad([(nd.exp(x)).sum()], [x])  # not recorded -> zeros/None
    x2 = nd.array([1.0, 2.0])
    with autograd.record():
        y = nd.tanh(x2)
    gs = autograd.grad([y], [x2])
    np.testing.assert_allclose(gs[0].asnumpy(), 1 - np.tanh([1.0, 2.0]) ** 2,
                               rtol=1e-5)


def test_softmax_output_backward_semantics():
    # SoftmaxOutput backward = softmax - onehot(label), ignoring head grads
    data = nd.array([[1.0, 2.0, 3.0], [1.0, 1.0, 1.0]])
    label = nd.array([2.0, 0.0])
    data.attach_grad()
    with autograd.record():
        out = nd.SoftmaxOutput(data, label)
    out.backward()
    sm = np.exp(data.asnumpy()) / np.exp(data.asnumpy()).sum(1, keepdims=True)
    expect = sm.copy()
    expect[0, 2] -= 1
    expect[1, 0] -= 1
    np.testing.assert_allclose(data.grad.asnumpy(), expect, rtol=1e-5)


def test_backward_releases_tape_refs():
    """backward(retain_graph=False) must clear the tape IN PLACE and
    drop node->NDArray references, so a step's activations free at the
    step boundary even while something else still holds the tape list
    or a node — not at the next record()."""
    import gc
    import weakref

    del autograd._st().tape[:]   # residue from recorded-only tests
    x = nd.array([1.0, 2.0])
    x.attach_grad()
    with autograd.record():
        y = x * 2
        z = y + 1
    tape = autograd._st().tape
    assert len(tape) == 2
    node = tape[0]
    wr = weakref.ref(y)
    z.backward()
    del y, z
    gc.collect()
    # in-place clear: the captured list emptied, the captured node
    # dropped its array references, the intermediate activation died
    assert tape is autograd._st().tape and len(tape) == 0
    assert node.inputs == () and node.outputs == ()
    assert wr() is None
    np.testing.assert_allclose(x.grad.asnumpy(), [2.0, 2.0])


def test_backward_retain_graph_keeps_tape():
    del autograd._st().tape[:]   # residue from recorded-only tests
    x = nd.array([3.0])
    x.attach_grad()
    with autograd.record():
        y = x * x
    y.backward(retain_graph=True)
    assert len(autograd._st().tape) == 1
    np.testing.assert_allclose(x.grad.asnumpy(), [6.0])
    y.backward()   # second replay, then the graph frees
    assert len(autograd._st().tape) == 0


def test_custom_function():
    class Sigmoid(autograd.Function):
        def forward(self, x):
            import mxnet_tpu.ndarray as ndm
            y = 1 / (1 + ndm.exp(-x))
            self._saved = y
            return y

        def backward(self, dy):
            y = self._saved
            return dy * y * (1 - y)

    x = nd.array([0.0, 1.0])
    x.attach_grad()
    f = Sigmoid()
    with autograd.record():
        y = f(x)
    y.backward()
    s = 1 / (1 + np.exp(-np.array([0.0, 1.0])))
    np.testing.assert_allclose(x.grad.asnumpy(), s * (1 - s), rtol=1e-5)
