"""Train->serve loop tests (ISSUE 14): the elastic on_commit ->
CheckpointPusher -> FleetSupervisor.push -> PushVerdict feedback
pipeline, and ContinuousEngine sequence-state migration across an
engine hot-swap.

The pusher's robustness contract runs against an in-process STUB
supervisor (scripted push behavior: accept / refuse typed / wedge
forever) so every failure shape is exact and fast; the verdict channel
is the same `on_push_verdict` registration the real FleetSupervisor
serves.  The real-supervisor halves (push fan-out racing a dead
replica, respawn reconcile) live in test_fleet_supervisor.py next to
the raw-socket stubs; the full closed-loop drill (live 2-replica
fleet, injected rollback, SIGKILL mid-push) is dryrun_multichip phase
(k).

ContinuousEngine migration: bit-identical completion across a swap
when the model is unchanged, replay-from-zero under the injected
MXNET_TPU_FAULT_SWAP_DROP_STATE, counted divergence when the model
changed, queued-request migration, and incompatible-engine rejection.
"""
import json
import os
import threading
import time

import numpy as np
import pytest

import mxnet_tpu as mx
from mxnet_tpu import elastic, model as model_mod, nd, profiler
from mxnet_tpu import sym
from mxnet_tpu.base import MXNetError
from mxnet_tpu.fleet_supervisor import (CheckpointPusher, PushVerdict,
                                        RollbackStop)
from mxnet_tpu.serving import export_serving_checkpoint
from mxnet_tpu.serving_fleet import ContinuousEngine
from mxnet_tpu.serving_fleet import BudgetExceeded

DIM, HID, OUT = 6, 8, 3


def _head():
    data = sym.Variable('data')
    fc1 = sym.FullyConnected(data, num_hidden=HID, name='fc1')
    act = sym.Activation(fc1, act_type='relu')
    return sym.FullyConnected(act, num_hidden=OUT, name='fc2')


def _module(seed=3):
    net = sym.SoftmaxOutput(_head(), name='softmax')
    mod = mx.mod.Module(net)
    mod.bind(data_shapes=[mx.io.DataDesc('data', (4, DIM))],
             label_shapes=[mx.io.DataDesc('softmax_label', (4,))])
    mx.random.seed(seed)
    mod.init_params(initializer=mx.init.Xavier())
    return mod


class _StubSupervisor(object):
    """Scripted fleet: push() accepts / raises / wedges; verdicts are
    fired on demand through the same on_push_verdict channel the real
    FleetSupervisor serves."""

    def __init__(self, fail=None, block=None):
        self.fail = fail                # exception each push raises
        self.block = block              # Event a push waits on (wedge)
        self.pushes = []                # (name, prefix, cand)
        self._cbs = []
        self._seq = 0
        self._active = set()

    def on_push_verdict(self, cb):
        self._cbs.append(cb)
        return self

    def push_active(self, name):
        return name in self._active

    def active_prefixes(self, name):
        return set()

    def push(self, name, prefix, epoch=0, frac=None, mode='canary',
             tag=None):
        if self.block is not None:
            self.block.wait()
        if self.fail is not None:
            raise self.fail
        self._seq += 1
        cand = '%s@v%d' % (name, self._seq)
        self.pushes.append((name, prefix, cand))
        self._active.add(name)
        self.tags = getattr(self, 'tags', {})
        self.tags[cand] = tag
        return cand

    def decide(self, kind, cand, model='m', report=None):
        self._active.discard(model)
        v = PushVerdict(kind, model, cand, report=report)
        for cb in self._cbs:
            cb(v)
        return v


def _wait(pred, timeout=30, msg='condition'):
    deadline = time.time() + timeout
    while time.time() < deadline:
        if pred():
            return
        time.sleep(0.01)
    raise AssertionError('timed out waiting for %s' % msg)


def _mgr_with_pusher(tmp_path, sup, **pk):
    pusher = CheckpointPusher(sup, 'm', symbol=_head(),
                              push_dir=str(tmp_path / 'push'), **pk)
    mgr = pusher.attach(elastic.CheckpointManager(
        str(tmp_path / 'ck'), every_n_steps=1))
    mgr.attach(_module())
    return mgr, pusher


# ---------------------------------------------------------------------------
# commit hook + export + promote feedback
# ---------------------------------------------------------------------------

def test_on_commit_fires_after_manifest_commit(tmp_path):
    mod = _module()
    seen = []

    def hook(step_dir, manifest):
        # the manifest must already be DURABLE when the hook fires (a
        # push must never advertise an uncommitted prefix)
        assert os.path.isfile(os.path.join(step_dir, 'manifest.json'))
        seen.append((step_dir, manifest['step']))

    mgr = elastic.CheckpointManager(str(tmp_path / 'ck'),
                                    on_commit=hook)
    mgr.attach(mod)
    mgr._step = 5
    mgr.save(sync=True)
    assert seen and seen[0][1] == 5
    # a RAISING hook is contained: the commit (and training) survive
    mgr.on_commit = lambda *_a: 1 / 0
    mgr._step = 6
    mgr.save(sync=True)
    assert elastic.list_checkpoints(str(tmp_path / 'ck')) == [6, 5]
    # pusher.attach CHAINS a pre-existing hook instead of dropping it
    mgr.on_commit = hook
    pusher = CheckpointPusher(_StubSupervisor(), 'm', symbol=_head(),
                              push_dir=str(tmp_path / 'push'))
    pusher.attach(mgr)
    mgr._step = 7
    mgr.save(sync=True)
    assert seen[-1][1] == 7             # user hook still fired
    _wait(lambda: len(pusher.supervisor.pushes) == 1,
          msg='chained push')
    pusher.close()
    mgr.close()


def test_pusher_promote_verdict_flows_back(tmp_path):
    profiler.clear()
    sup = _StubSupervisor()
    mgr, pusher = _mgr_with_pusher(tmp_path, sup)
    mod = mgr._target
    mgr.step_end()                       # step 1: commit -> push
    mgr.wait()
    _wait(lambda: len(sup.pushes) == 1, msg='push')
    name, prefix, cand = sup.pushes[0]
    assert name == 'm'
    # the exported prefix is a REAL serving checkpoint: weights equal
    # the module's, loadable by the replica-side registry machinery
    _s, args, _aux = model_mod.load_checkpoint(prefix, 0)
    want, _ = mod.get_params()
    for n in ('fc1_weight', 'fc1_bias', 'fc2_weight', 'fc2_bias'):
        np.testing.assert_array_equal(args[n].asnumpy(),
                                      want[n].asnumpy())
    # the verdict flows BACK, correlated to the committing train step
    sup.decide('promoted', cand,
               report={'cand_p50_ms': 1.0, 'stable_p50_ms': 1.0,
                       'cand_err_frac': 0.0})
    _wait(lambda: pusher.last_verdict is not None, msg='verdict')
    v = pusher.last_verdict
    assert v.kind == 'promoted' and v.candidate == cand
    assert v.step == 1
    assert pusher.consecutive_rollbacks == 0
    # step_end drains poll_verdicts into the training log stream
    mgr.step_end()
    assert pusher.poll_verdicts() == []  # drained by step_end
    assert pusher.verdicts()[-1] is v    # history kept
    st = profiler.loop_stats()
    assert st['loop_pushes'] == 1
    assert st['loop_verdicts_promoted'] == 1
    pusher.close()
    mgr.close()


def test_export_serving_checkpoint_validates_and_serves(tmp_path):
    mod = _module(seed=9)
    mgr = elastic.CheckpointManager(str(tmp_path / 'ck'))
    mgr.attach(mod)
    mgr._step = 3
    step_dir = mgr.save(sync=True)
    prefix = str(tmp_path / 'serve_m')
    export_serving_checkpoint(step_dir, _head(), prefix)
    from mxnet_tpu.predictor import Predictor
    _s, args, auxs = model_mod.load_checkpoint(prefix, 0)
    pred = Predictor(symbol=_head(), arg_params=args, aux_params=auxs,
                     input_shapes={'data': (1, DIM)})
    x = np.random.RandomState(0).randn(1, DIM).astype(np.float32)
    out = pred.forward(data=nd.array(x))[0].asnumpy()
    assert out.shape == (1, OUT) and np.isfinite(out).all()
    # a non-checkpoint dir is refused with a typed error
    with pytest.raises(MXNetError):
        export_serving_checkpoint(str(tmp_path), _head(),
                                  str(tmp_path / 'bad'))
    mgr.close()


# ---------------------------------------------------------------------------
# rollback feedback: consecutive-rollback stop
# ---------------------------------------------------------------------------

def test_consecutive_rollbacks_stop_training(tmp_path):
    profiler.clear()
    sup = _StubSupervisor()
    mgr, pusher = _mgr_with_pusher(tmp_path, sup,
                                   max_consecutive_rollbacks=3)
    for i in range(3):
        mgr.step_end()                  # commit -> push
        mgr.wait()
        _wait(lambda: len(sup.pushes) == i + 1, msg='push %d' % i)
        sup.decide('rolled_back', sup.pushes[-1][2])
        _wait(lambda: len(pusher.verdicts()) == i + 1, msg='verdict')
    assert pusher.consecutive_rollbacks == 3
    assert profiler.loop_stats()['loop_consecutive_rollbacks'] == 3
    # the stop lands Preempted-style at the NEXT step boundary
    with pytest.raises(RollbackStop) as ei:
        mgr.step_end()
    assert ei.value.model == 'm'
    assert len(ei.value.verdicts) == 3
    assert all(v.kind == 'rolled_back' for v in ei.value.verdicts)
    pusher.close()
    mgr.close()


def test_promote_resets_rollback_streak(tmp_path):
    sup = _StubSupervisor()
    mgr, pusher = _mgr_with_pusher(tmp_path, sup,
                                   max_consecutive_rollbacks=2)
    for i, kind in enumerate(('rolled_back', 'promoted',
                              'rolled_back')):
        mgr.step_end()
        mgr.wait()
        _wait(lambda: len(sup.pushes) == i + 1, msg='push %d' % i)
        sup.decide(kind, sup.pushes[-1][2])
        _wait(lambda: len(pusher.verdicts()) == i + 1,
              msg='verdict %d' % i)
    assert pusher.consecutive_rollbacks == 1    # reset by the promote
    mgr.step_end()                               # no stop raised
    pusher.close()
    mgr.close()


# ---------------------------------------------------------------------------
# degradation: wedged fleet, typed failures, fault knob
# ---------------------------------------------------------------------------

def test_wedged_fleet_never_stalls_training(tmp_path):
    profiler.clear()
    release = threading.Event()
    sup = _StubSupervisor(block=release)     # push wedges forever
    mgr, pusher = _mgr_with_pusher(tmp_path, sup)
    t0 = time.monotonic()
    for _ in range(6):
        mgr.step_end()                  # cadence commit every step
        mgr.wait()                      # all 6 commits really land
    dt = time.monotonic() - t0
    # six commits against a WEDGED fleet: one push blocks on its
    # worker thread, one queues, the rest skip with a counter —
    # nothing ever blocks the training thread
    assert dt < 20.0, 'training stalled on a wedged fleet (%.1fs)' % dt
    assert elastic.list_checkpoints(str(tmp_path / 'ck'))
    _wait(lambda: profiler.loop_stats()['loop_push_queue_skipped'] >= 3,
          msg='skip counter')
    release.set()                       # unwedge so the worker exits
    pusher.close()
    mgr.close()


def test_push_failure_is_typed_not_fatal(tmp_path):
    profiler.clear()
    sup = _StubSupervisor(fail=BudgetExceeded('m', 100, 10, 0))
    mgr, pusher = _mgr_with_pusher(tmp_path, sup)
    mgr.step_end()
    mgr.wait()
    _wait(lambda: pusher.last_verdict is not None, msg='failed verdict')
    v = pusher.last_verdict
    assert v.kind == 'failed' and v.error
    assert pusher.consecutive_rollbacks == 0    # failures != rollbacks
    assert profiler.loop_stats()['loop_push_failures'] == 1
    mgr.step_end()                      # training continues
    pusher.close()
    mgr.close()


def test_fault_push_fail_knob(tmp_path, monkeypatch):
    profiler.clear()
    monkeypatch.setenv('MXNET_TPU_FAULT_PUSH_FAIL', '2')
    sup = _StubSupervisor()
    mgr, pusher = _mgr_with_pusher(tmp_path, sup)
    mgr.step_end()
    mgr.wait()
    _wait(lambda: len(sup.pushes) == 1, msg='push 1')
    sup.decide('promoted', sup.pushes[-1][2])
    mgr.step_end()
    mgr.wait()
    _wait(lambda: any(v.kind == 'failed' and 'PUSH_FAIL' in v.error
                      for v in pusher.verdicts()),
          msg='injected failure')
    assert len(sup.pushes) == 1         # the 2nd attempt never landed
    sup.decide('promoted', 'unused')    # noop for correlation
    mgr.step_end()                      # 3rd attempt goes through
    mgr.wait()
    _wait(lambda: len(sup.pushes) == 2, msg='push 3')
    pusher.close()
    mgr.close()


# ---------------------------------------------------------------------------
# ContinuousEngine: sequence migration across a hot-swap
# ---------------------------------------------------------------------------

CDIM, CHID, COUT = 5, 4, 2


def _cell():
    data = sym.Variable('data')
    h_in = sym.Variable('h')
    pre = sym.FullyConnected(data, num_hidden=CHID, name='ix') + \
        sym.FullyConnected(h_in, num_hidden=CHID, no_bias=True,
                           name='hh')
    h_new = sym.Activation(pre, act_type='tanh')
    head = sym.FullyConnected(h_new, num_hidden=COUT, name='out')
    return sym.Group([head, h_new])


def _cell_params(seed=3):
    rs = np.random.RandomState(seed)
    return {
        'ix_weight': nd.array(rs.randn(CHID, CDIM).astype(np.float32)
                              * .3),
        'ix_bias': nd.array(np.zeros(CHID, np.float32)),
        'hh_weight': nd.array(rs.randn(CHID, CHID).astype(np.float32)
                              * .3),
        'out_weight': nd.array(rs.randn(COUT, CHID).astype(np.float32)
                               * .3),
        'out_bias': nd.array(np.zeros(COUT, np.float32)),
    }


def _cont(slots=2, seed=3, **kw):
    return ContinuousEngine(_cell(), arg_params=_cell_params(seed),
                            data_shape=(CDIM,),
                            state_shapes={'h': (CHID,)},
                            state_outputs={'h': 1}, slots=slots, **kw)


def _seqs(lens, seed=0):
    rs = np.random.RandomState(seed)
    return [rs.randn(L, CDIM).astype(np.float32) for L in lens]


def _swap_run(seqs, drop=False, new_seed=3, min_ticks=4, a_kw=None,
              b_kw=None):
    """Submit `seqs` to engine A, hot-swap mid-flight into a fresh
    engine (seeded `new_seed`), return the completed outputs + the
    export payload (with each request's position AT export stashed
    under 't_at_export' — the live objects mutate as engine B runs
    them)."""
    eng_a = _cont(**dict({'slots': 2}, **(a_kw or {})))
    res = [None] * len(seqs)
    ts = [threading.Thread(target=lambda i=i:
                           res.__setitem__(i, eng_a.infer(seqs[i])))
          for i in range(len(seqs))]
    for t in ts:
        t.start()
    _wait(lambda: eng_a.stats()['ticks'] >= min_ticks and
          eng_a.stats()['admitted'] >= 1, msg='mid-flight')
    if drop:
        os.environ['MXNET_TPU_FAULT_SWAP_DROP_STATE'] = '1'
    try:
        exported = eng_a.export_state()
    finally:
        os.environ.pop('MXNET_TPU_FAULT_SWAP_DROP_STATE', None)
    exported['t_at_export'] = [r.t for r in exported['requests']]
    eng_b = _cont(**dict({'slots': 2, 'seed': new_seed},
                         **(b_kw or {})))
    migrated = eng_b.admit_state(exported,
                                 model_changed=new_seed != 3)
    for t in ts:
        t.join(timeout=60)
    assert all(r is not None for r in res), 'a request was lost'
    eng_a.close()
    eng_b.close()
    return res, exported, migrated


def test_swap_mid_flight_bit_identical_same_model():
    profiler.clear()
    # long sequences: the export must reliably land MID-flight (a
    # short one can finish on engine A between the tick check and the
    # halt — the tick loop runs ~1ms/tick on this rig)
    seqs = _seqs([400, 250], seed=4)
    with _cont(slots=2) as ref:
        solo = ref.infer_many(seqs)
    res, exported, migrated = _swap_run(seqs)
    assert migrated >= 1
    for i in range(len(seqs)):
        for a, b in zip(res[i], solo[i]):
            assert np.array_equal(a, b), \
                'sequence %d diverged across the swap' % i
    st = profiler.loop_stats()
    assert st['loop_swap_migrated_slots'] >= 1
    assert st['loop_swap_divergent_slots'] == 0
    assert st['loop_swap_dropped_slots'] == 0


def test_swap_dropped_state_replays_and_counts():
    profiler.clear()
    seqs = _seqs([400], seed=7)         # long: export lands mid-flight
    with _cont(slots=2) as ref:
        solo = ref.infer_many(seqs)
    res, exported, migrated = _swap_run(seqs, drop=True, min_ticks=2)
    assert migrated == 0                # state lost: replayed instead
    assert exported['dropped'] >= 1
    for a, b in zip(res[0], solo[0]):   # deterministic cell: replay
        assert np.array_equal(a, b)     # still answers correctly
    assert profiler.loop_stats()['loop_swap_dropped_slots'] >= 1


def test_swap_model_changed_counts_divergence():
    profiler.clear()
    # long sequence so the export reliably lands MID-flight (a short
    # one can finish on engine A between the tick check and the halt)
    seqs = _seqs([400], seed=5)
    with _cont(slots=2) as ref:
        solo = ref.infer_many(seqs)
    res, exported, migrated = _swap_run(seqs, new_seed=11,
                                        min_ticks=2)
    assert migrated >= 1, \
        'sequence finished before the swap (exported %d requests)' \
        % len(exported['requests'])
    # the migrated tail ran under DIFFERENT weights: outputs diverge
    # from the unswapped run — visible, and counted, never hidden
    assert not all(np.array_equal(a, b)
                   for a, b in zip(res[0], solo[0]))
    assert profiler.loop_stats()['loop_swap_divergent_slots'] >= 1


def test_swap_migrates_queued_requests_too():
    # 2 slots + 3 requests: the third waits in the queue at export
    # time (the slots are busy with long sequences); all three
    # complete on the new engine
    seqs = _seqs([400, 400, 20], seed=8)
    with _cont(slots=2) as ref:
        solo = ref.infer_many(seqs)
    res, exported, _m = _swap_run(seqs, min_ticks=2)
    assert len(exported['requests']) == 3
    for i in range(3):
        for a, b in zip(res[i], solo[i]):
            assert np.array_equal(a, b)


def test_swap_rejects_incompatible_engine_and_closed_source():
    eng_a = _cont(slots=2)
    exported = eng_a.export_state()     # idle engine: empty payload
    assert exported['requests'] == []
    with pytest.raises(MXNetError, match='closed'):
        eng_a.export_state()            # already exported/closed
    with pytest.raises(MXNetError, match='closed'):
        eng_a.infer(_seqs([2])[0])      # rejects new submits
    bad = ContinuousEngine(_cell(), arg_params=_cell_params(),
                           data_shape=(CDIM,),
                           state_shapes={'h': (CHID,)},
                           state_outputs={'h': 1}, slots=2,
                           convoy=True)
    try:
        exported['data_shape'] = (CDIM + 1,)
        with pytest.raises(MXNetError, match='incompatible'):
            bad.admit_state(exported)
    finally:
        bad.close()
    eng_a.close()


def test_swap_chunked_halts_at_chunk_boundary_bit_identical():
    profiler.clear()
    # chunked engines on BOTH sides of the swap (K=4): the tick loop
    # halts only at chunk boundaries, so every exported in-flight
    # position is a multiple of K — and the migrated run stays
    # bit-identical to a never-swapped unchunked reference
    seqs = _seqs([400, 250], seed=9)
    with _cont(slots=2) as ref:
        solo = ref.infer_many(seqs)
    res, exported, migrated = _swap_run(
        seqs, a_kw=dict(slots=4, tick_chunk=4),
        b_kw=dict(slots=4, tick_chunk=4), min_ticks=8)
    assert migrated >= 1
    assert exported['t_at_export']
    assert all(t % 4 == 0 for t in exported['t_at_export'])
    for i in range(len(seqs)):
        for a, b in zip(res[i], solo[i]):
            assert np.array_equal(a, b), \
                'sequence %d diverged across the chunked swap' % i


def test_swap_mid_stage_drains_shadow_chunks_bit_identical():
    profiler.clear()
    # double-buffered staging (stage_ahead=2 here: up to two shadow
    # chunks queued behind the in-flight dispatch) with an
    # export_state landing mid-stage: the halt must DRAIN every
    # in-flight staged chunk to a consistent boundary — never discard
    # a shadow buffer whose admissions/slot-resets are already
    # recorded.  Evidence: every exported position is a chunk
    # boundary, zero lost sequences, and the migrated run stays
    # bit-identical to a never-swapped reference
    seqs = _seqs([400, 250, 30], seed=13)
    with _cont(slots=2) as ref:
        solo = ref.infer_many(seqs)
    res, exported, migrated = _swap_run(
        seqs, a_kw=dict(slots=4, tick_chunk=4, stage_ahead=2),
        b_kw=dict(slots=4, tick_chunk=4, stage_ahead=1), min_ticks=8)
    assert migrated >= 1
    assert all(t % 4 == 0 for t in exported['t_at_export'])
    # the drill actually exercised the pipelined loop on both sides
    assert profiler.fleet_stats()['cont_staged_chunks'] >= 1
    for i in range(len(seqs)):
        for a, b in zip(res[i], solo[i]):
            assert np.array_equal(a, b), \
                'sequence %d diverged across the mid-stage swap' % i


def test_swap_chunked_to_unchunked_engine_bit_identical():
    # the migration payload is tick-config agnostic: a chunked
    # engine's export admits into an UNCHUNKED replacement and the
    # answers stay bit-identical (the replacement just resumes the
    # state rows one tick at a time)
    seqs = _seqs([400], seed=12)
    with _cont(slots=2) as ref:
        solo = ref.infer_many(seqs)
    res, exported, migrated = _swap_run(
        seqs, a_kw=dict(slots=4, tick_chunk=4), min_ticks=8)
    assert migrated >= 1
    for a, b in zip(res[0], solo[0]):
        assert np.array_equal(a, b)


# ---------------------------------------------------------------------------
# profiler family
# ---------------------------------------------------------------------------

def test_loop_counters_in_summary_and_dump(tmp_path):
    profiler.clear()
    profiler.add_loop_stats(pushes=2, push_failures=1,
                            push_queue_skipped=3, verdicts_promoted=1,
                            verdicts_rolled_back=2,
                            swap_migrated_slots=4,
                            swap_dropped_slots=1,
                            swap_divergent_slots=2,
                            consecutive_rollbacks=2)
    st = profiler.loop_stats()
    assert st['loop_pushes'] == 2
    assert st['loop_consecutive_rollbacks'] == 2    # gauge
    profiler.add_loop_stats(consecutive_rollbacks=0)
    assert profiler.loop_stats()['loop_consecutive_rollbacks'] == 0
    text = profiler.summary(print_out=False)
    for key in ('loop_pushes', 'loop_push_queue_skipped',
                'loop_verdicts_rolled_back',
                'loop_swap_migrated_slots'):
        assert key in text
    out = tmp_path / 'loop_profile.json'
    profiler.profiler_set_config(filename=str(out))
    profiler.dump_profile()
    events = json.loads(out.read_text())['traceEvents']
    meta = [e for e in events if e.get('name') == 'loop']
    assert meta and meta[0]['args']['loop_pushes'] == 2
    profiler.clear()
    assert profiler.loop_stats()['loop_pushes'] == 0
