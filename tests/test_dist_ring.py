"""Cross-host gradient transport topologies (mxnet_tpu/dist.py ring
reduce-scatter + all-gather, async overlap handles, sparse COO wire;
kvstore.py mark_sparse rows-only application; tools/launch.py ring
port contract).

The invariants under test, per ISSUE 20:
  * every rank decodes IDENTICAL bytes per topology mode (the PR 9/13
    bitwise-determinism contract), and at world 2 the ring's rotation
    order coincides with the star's rank order, so the two topologies
    agree bitwise there;
  * int8/bf16 WireCodec composition rides per-chunk on the ring
    (MXNET_TPU_DIST_WIRE_DTYPE composes unchanged) with integer
    arrays kept exact;
  * dead/stalled peers are NAMED in the error, never a hang;
  * sparse COO rounds match the densified dense-wire result;
  * async handles overlap the round with local work (dist_overlap_ms)
    without changing the summed bytes;
  * per-topology tx/rx byte counters split star/ring/sparse.
"""
import os
import subprocess
import sys
import threading
import time

import numpy as np
import pytest

import mxnet_tpu as mx
from mxnet_tpu import dist, elastic, profiler
from mxnet_tpu import ndarray as nd
from mxnet_tpu import optimizer as opt_mod
from mxnet_tpu.base import MXNetError

_REPO = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))
_LAUNCH = os.path.join(_REPO, 'tools', 'launch.py')
_DIST_WORKER = os.path.join(os.path.dirname(os.path.abspath(__file__)),
                            'test_dist_runtime.py')


def _pair(dead_after=30.0, hb=0.1, world=2):
    coord = dist.Coordinator(port=0, world=world,
                             bind_addr='127.0.0.1',
                             dead_after=dead_after).start()
    rts = [None] * world
    errs = [None] * world

    def mk(r):
        try:
            rts[r] = dist.DistRuntime(
                r, world, address='127.0.0.1', port=coord.port,
                start_coordinator=False, timeout=15,
                hb_interval=hb, dead_after=dead_after)
        except BaseException as e:
            errs[r] = e
    ts = [threading.Thread(target=mk, args=(r,)) for r in range(world)]
    for t in ts:
        t.start()
    for t in ts:
        t.join()
    assert all(e is None for e in errs), errs
    return coord, rts


def _teardown(coord, rts):
    for rt in reversed(rts):
        if rt is not None:
            rt.shutdown()
    coord.stop()


def _all_ranks(rts, fn, timeout=40):
    """Run fn(rank) on every runtime concurrently; surface errors."""
    out = [None] * len(rts)
    errs = []

    def go(r):
        try:
            out[r] = fn(r)
        except BaseException as e:
            errs.append((r, e))
    ts = [threading.Thread(target=go, args=(r,))
          for r in range(len(rts))]
    for t in ts:
        t.start()
    for t in ts:
        t.join(timeout)
    return out, errs


def _contrib(r):
    return [np.arange(11, dtype=np.float32) * (r + 1) / 3.0,
            np.full((3, 5), r + 0.25, np.float32),
            np.arange(4, dtype=np.int64) * (r + 1)]


# ---------------------------------------------------------------------------
# topology knob
# ---------------------------------------------------------------------------

def test_topology_knob(monkeypatch):
    monkeypatch.delenv('MXNET_TPU_DIST_TOPOLOGY', raising=False)
    assert dist.topology_from_env() == 'star'
    monkeypatch.setenv('MXNET_TPU_DIST_TOPOLOGY', 'ring')
    assert dist.topology_from_env() == 'ring'
    assert dist.topology_from_env('star') == 'star'   # explicit wins
    with pytest.raises(MXNetError, match='topology'):
        dist.topology_from_env('mesh')
    # the ring stall knob falls back to the barrier stall knob (one
    # injection covers both collective shapes)
    monkeypatch.setenv('MXNET_TPU_FAULT_BARRIER_STALL_S', '1:0.4')
    assert elastic.ring_stall_s(1) == 0.4
    assert elastic.ring_stall_s(0) is None
    monkeypatch.setenv('MXNET_TPU_FAULT_RING_STALL_S', '0:0.2')
    assert elastic.ring_stall_s(0) == 0.2
    assert elastic.ring_stall_s(1) is None


# ---------------------------------------------------------------------------
# ring allreduce: bitwise parity + counters
# ---------------------------------------------------------------------------

def test_ring_matches_star_bitwise_at_world2():
    profiler.clear()

    def round_of(topo):
        coord, rts = _pair()
        try:
            out, errs = _all_ranks(
                rts, lambda r: rts[r].allreduce(
                    _contrib(r), name='g', topology=topo, timeout=20))
            assert not errs, errs
            for a, b in zip(out[0], out[1]):
                assert a.dtype == b.dtype
                assert a.tobytes() == b.tobytes()   # identical bytes
            return out[0]
        finally:
            _teardown(coord, rts)

    star = round_of('star')
    ring = round_of('ring')
    # world 2: rank order == rotation order, so the topologies agree
    # BITWISE (IEEE addition is commutative; it is associativity that
    # breaks at world >= 3)
    for a, b in zip(star, ring):
        assert a.tobytes() == b.tobytes()
    np.testing.assert_array_equal(ring[2],
                                  np.arange(4, dtype=np.int64) * 3)
    st = profiler.dist_stats()
    assert st['dist_star_bytes'] > 0 and st['dist_ring_bytes'] > 0
    assert st['dist_tx_bytes'] > 0 and st['dist_rx_bytes'] > 0
    assert st['dist_allreduce_bytes'] == \
        st['dist_tx_bytes'] + st['dist_rx_bytes']
    text = profiler.summary(print_out=False)
    assert 'dist_tx_bytes=' in text and 'dist_ring_bytes=' in text


def test_ring_world3_identical_bytes_and_correct_sums():
    coord, rts = _pair(world=3)
    try:
        for rnd in range(2):     # round 2 reuses the built links
            out, errs = _all_ranks(
                rts, lambda r: rts[r].allreduce(
                    _contrib(r), name='g', topology='ring',
                    timeout=20))
            assert not errs, errs
            for r in (1, 2):
                for a, b in zip(out[0], out[r]):
                    assert a.tobytes() == b.tobytes()
            expect = [np.sum([np.asarray(c, np.float64) for c in cols],
                             axis=0)
                      for cols in zip(*[_contrib(r) for r in range(3)])]
            for got, want in zip(out[0], expect):
                np.testing.assert_allclose(
                    np.asarray(got, np.float64), want, rtol=1e-5)
    finally:
        _teardown(coord, rts)


def test_ring_int8_wire_composes():
    profiler.clear()
    coord, rts = _pair()
    try:
        out, errs = _all_ranks(
            rts, lambda r: rts[r].allreduce(
                _contrib(r), name='g8', topology='ring', wire='int8',
                timeout=20))
        assert not errs, errs
        for a, b in zip(out[0], out[1]):
            assert a.tobytes() == b.tobytes()
        # integer groups ride the ring RAW — exact even on the
        # compressed wire (the star path quantizes them)
        np.testing.assert_array_equal(out[0][2],
                                      np.arange(4, dtype=np.int64) * 3)
        exact = np.arange(11, dtype=np.float64) * (1 + 2) / 3.0
        np.testing.assert_allclose(np.asarray(out[0][0], np.float64),
                                   exact, atol=0.5)
        # compressed hops move fewer bytes than fp32 hops would
        qs = profiler.quant_stats()
        assert qs['quant_wire_bytes_saved'] > 0
    finally:
        _teardown(coord, rts)


# ---------------------------------------------------------------------------
# failure paths: stalled / dead peers NAMED
# ---------------------------------------------------------------------------

def test_ring_stalled_peer_names_rank(monkeypatch):
    coord, rts = _pair()
    try:
        out, errs = _all_ranks(       # round 1 builds the links
            rts, lambda r: rts[r].allreduce(
                [np.ones(6, np.float32)], name='w', topology='ring',
                timeout=20))
        assert not errs, errs
        # rank 1 stalls 3s at round entry; rank 0's 1s deadline must
        # convert the silence into an error NAMING rank 1 (its left
        # neighbor on a 2-ring), never a hang
        monkeypatch.setenv('MXNET_TPU_FAULT_RING_STALL_S', '1:3')
        res = {}

        def go(r):
            try:
                res[r] = rts[r].allreduce(
                    [np.ones(6, np.float32)], name='w',
                    topology='ring', timeout=1.0)
            except MXNetError as e:
                res[r] = e
        ts = [threading.Thread(target=go, args=(r,)) for r in (0, 1)]
        for t in ts:
            t.start()
        for t in ts:
            t.join(20)
        assert isinstance(res[0], MXNetError), res
        msg = str(res[0])
        assert 'rank 1' in msg and 'stalled or dead' in msg
    finally:
        monkeypatch.delenv('MXNET_TPU_FAULT_RING_STALL_S',
                           raising=False)
        _teardown(coord, rts)


def test_ring_dead_peer_names_rank(monkeypatch):
    coord, rts = _pair(dead_after=0.5)
    try:
        out, errs = _all_ranks(
            rts, lambda r: rts[r].allreduce(
                [np.ones(6, np.float32)], name='w', topology='ring',
                timeout=20))
        assert not errs, errs
        # rank 1 goes silent (injected partition): rank 0's next ring
        # round sees the heartbeat-declared death and fails fast
        # naming the dead set
        monkeypatch.setenv('MXNET_TPU_FAULT_HEARTBEAT_DROP', '1')
        with pytest.raises(MXNetError, match=r'\[1\]'):
            rts[0].allreduce([np.ones(6, np.float32)], name='w',
                             topology='ring', timeout=15)
    finally:
        _teardown(coord, rts)


# ---------------------------------------------------------------------------
# sparse COO wire
# ---------------------------------------------------------------------------

def _coo_contrib(world, vocab=50, dim=4, n=12):
    rngs = [np.random.RandomState(7 + r) for r in range(world)]
    return [(rngs[r].randint(0, vocab, n),
             rngs[r].randn(n, dim).astype(np.float32))
            for r in range(world)]


@pytest.mark.parametrize('world,topo', [(2, 'star'), (2, 'ring'),
                                        (3, 'ring')])
def test_coo_allreduce_parity_vs_densified(world, topo):
    profiler.clear()
    VOCAB, DIM = 50, 4
    contrib = _coo_contrib(world, VOCAB, DIM)
    coord, rts = _pair(world=world)
    try:
        out, errs = _all_ranks(
            rts, lambda r: rts[r].allreduce_coo(
                contrib[r][0], contrib[r][1], name='e', vocab=VOCAB,
                topology=topo, timeout=20))
        assert not errs, errs
        for r in range(1, world):
            assert out[r][0].tobytes() == out[0][0].tobytes()
            assert out[r][1].tobytes() == out[0][1].tobytes()
        dense = np.zeros((VOCAB, DIM), np.float64)
        for ids, rows in contrib:
            np.add.at(dense, ids, rows.astype(np.float64))
        uids, rows = out[0]
        assert np.all(np.diff(uids) > 0)        # sorted unique ids
        got = np.zeros((VOCAB, DIM), np.float64)
        got[uids] = rows
        np.testing.assert_allclose(got, dense, atol=1e-5)
        assert profiler.dist_stats()['dist_sparse_bytes'] > 0
    finally:
        _teardown(coord, rts)


def test_coo_requires_vocab_on_ring_and_dedups_locally():
    coord, rts = _pair(world=2)
    try:
        # the ring chunks the id space — without a vocab bound there
        # is no chunking, and the error must say so before any peer
        # traffic happens
        with pytest.raises(MXNetError, match='vocab'):
            rts[0].allreduce_coo(np.arange(3),
                                 np.ones((3, 2), np.float32),
                                 topology='ring')
    finally:
        _teardown(coord, rts)
    # before initialize(): identity plus local dedup + sort
    ids, rows = dist.allreduce_coo(
        np.array([5, 2, 5]), np.ones((3, 2), np.float32))
    np.testing.assert_array_equal(ids, [2, 5])
    np.testing.assert_allclose(rows, [[1, 1], [2, 2]])


# ---------------------------------------------------------------------------
# async overlap
# ---------------------------------------------------------------------------

@pytest.mark.parametrize('topo', ['star', 'ring'])
def test_allreduce_async_parity_and_overlap_gauge(topo):
    profiler.clear()
    coord, rts = _pair()
    try:
        def go(r):
            hs = [rts[r].allreduce_async(
                [np.full(8, (r + 1) * (i + 1), np.float32)],
                name='k%d' % i, topology=topo) for i in range(4)]
            time.sleep(0.05)          # the "local optimizer work"
            return [h.wait(20) for h in hs]
        out, errs = _all_ranks(rts, go)
        assert not errs, errs
        for i in range(4):
            # per-key rounds sum in rank order: bitwise equal to the
            # same sum computed directly
            want = np.full(8, 3.0 * (i + 1), np.float32)
            assert out[0][i][0].tobytes() == want.tobytes()
            assert out[1][i][0].tobytes() == want.tobytes()
        assert profiler.dist_stats()['dist_overlap_ms'] > 0
    finally:
        _teardown(coord, rts)


# ---------------------------------------------------------------------------
# kvstore: mark_sparse rows-only application + overlap mode
# ---------------------------------------------------------------------------

def _kv_with_runtime(monkeypatch, rt, sparse, overlap=False):
    monkeypatch.setattr(dist, '_RUNTIME', rt)
    if overlap:
        monkeypatch.setenv('MXNET_TPU_DIST_OVERLAP', '1')
    else:
        monkeypatch.delenv('MXNET_TPU_DIST_OVERLAP', raising=False)
    kv = mx.kvstore.KVStore('dist_sync')
    opt = opt_mod.SGD(learning_rate=0.1, momentum=0.9)
    kv.set_optimizer(opt)
    return kv


def test_kvstore_sparse_coo_matches_densified(monkeypatch):
    """The rows-only sparse application must land on the same weights
    as the dense wire + dense updater when the same rows are touched
    (fresh momentum state — the lazy-semantics caveat in
    docs/SPARSE.md only appears once an UNtouched row has nonzero
    momentum)."""
    VOCAB, DIM = 10, 3
    w0 = np.random.RandomState(0).randn(VOCAB, DIM).astype(np.float32)
    grad = np.zeros((VOCAB, DIM), np.float32)
    grad[[1, 4, 7]] = np.random.RandomState(1).randn(3, DIM)
    coord, rts = _pair(world=1)
    try:
        results = {}
        for mode in ('dense', 'sparse', 'sparse_overlap'):
            kv = _kv_with_runtime(monkeypatch, rts[0], mode,
                                  overlap=(mode == 'sparse_overlap'))
            kv.init('emb', nd.array(w0))
            if mode != 'dense':
                kv.mark_sparse('emb', VOCAB)
            out = nd.array(w0)
            for _ in range(2):       # same rows touched both steps
                kv.push_pull_all(['emb'], [nd.array(grad)], [out])
            results[mode] = out.asnumpy()
        np.testing.assert_allclose(results['sparse'],
                                   results['dense'], atol=1e-5)
        np.testing.assert_allclose(results['sparse_overlap'],
                                   results['dense'], atol=1e-5)
        # untouched rows never move
        np.testing.assert_array_equal(results['sparse'][0], w0[0])
    finally:
        _teardown(coord, rts)


def test_kvstore_overlap_dense_matches_batched(monkeypatch):
    w0 = np.random.RandomState(3).randn(6, 4).astype(np.float32)
    grad = np.random.RandomState(4).randn(6, 4).astype(np.float32)
    coord, rts = _pair(world=1)
    try:
        outs = {}
        for overlap in (False, True):
            kv = _kv_with_runtime(monkeypatch, rts[0], 'd',
                                  overlap=overlap)
            kv.init('fc', nd.array(w0))
            out = nd.array(w0)
            kv.push_pull_all(['fc'], [nd.array(grad)], [out])
            outs[overlap] = out.asnumpy()
        np.testing.assert_array_equal(outs[False], outs[True])
    finally:
        _teardown(coord, rts)


# ---------------------------------------------------------------------------
# launcher contract + E2E kill-resume under ring
# ---------------------------------------------------------------------------

def test_launch_exports_ring_port_contract(tmp_path):
    prog = ("import os\n"
            "base = int(os.environ['MXNET_TPU_DIST_RING_PORT'])\n"
            "dist = int(os.environ['MXNET_TPU_DIST_PORT'])\n"
            "assert base == dist + 2, (base, dist)\n"
            "print('RINGPORT_OK', base)\n")
    script = tmp_path / 'w.py'
    script.write_text(prog)
    env = dict(os.environ, PYTHONPATH=_REPO + os.pathsep +
               os.environ.get('PYTHONPATH', ''))
    for stale in ('DMLC_PS_ROOT_URI', 'DMLC_PS_ROOT_PORT', 'DMLC_ROLE',
                  'DMLC_NUM_WORKER', 'DMLC_NUM_SERVER',
                  'MXNET_TPU_DIST_PORT', 'MXNET_TPU_DIST_RING_PORT'):
        env.pop(stale, None)
    proc = subprocess.run(
        [sys.executable, _LAUNCH, '-n', '2', '-s', '0',
         '--launcher', 'local', sys.executable, str(script)],
        env=env, capture_output=True, text=True, timeout=120)
    assert proc.returncode == 0, (proc.stdout, proc.stderr)
    assert proc.stdout.count('RINGPORT_OK') == 2, proc.stdout


@pytest.mark.slow
def test_ring_kill_one_of_two_workers_coordinated_restart(tmp_path):
    """slow (~35s): the star-topology twin
    (test_kill_one_of_two_workers_coordinated_restart) plus the ring
    pieces stay tier-1 via test_ring_matches_star_bitwise_at_world2 /
    test_ring_dead_peer_names_rank (ring transport + death naming)
    and test_launch_exports_ring_port_contract (port contract).

    End to end under MXNET_TPU_DIST_TOPOLOGY=ring: launcher-spawned
    workers form the peer ring from the exported port contract,
    SIGKILL of rank 1 mid-epoch surfaces as a named ring/death error,
    the survivor commits a final checkpoint + exits PREEMPTED_EXIT,
    the --elastic supervisor relaunches shrunk, and the final weights
    are BIT-IDENTICAL to the uninterrupted run."""
    def run(tag, n, elastic_mode=False, **fault):
        env = dict(os.environ,
                   PYTHONPATH=_REPO + os.pathsep +
                   os.environ.get('PYTHONPATH', ''))
        for stale in ('DMLC_PS_ROOT_URI', 'DMLC_PS_ROOT_PORT',
                      'DMLC_ROLE', 'DMLC_NUM_WORKER',
                      'DMLC_NUM_SERVER', 'MXNET_TPU_DIST_PORT',
                      'MXNET_TPU_DIST_RING_PORT'):
            env.pop(stale, None)
        env.update({'MXNET_TPU_DIST_HEARTBEAT_S': '0.1',
                    'MXNET_TPU_DIST_DEAD_AFTER_S': '0.8',
                    'MXNET_TPU_BARRIER_TIMEOUT_S': '30',
                    'MXNET_TPU_DIST_TOPOLOGY': 'ring',
                    'JAX_PLATFORMS': 'cpu'})
        env.update({k: str(v) for k, v in fault.items()})
        cmd = [sys.executable, _LAUNCH, '-n', str(n), '-s', '0',
               '--launcher', 'local']
        if elastic_mode:
            cmd += ['--elastic', '--elastic-shrink', '--max-restarts',
                    '2', '--elastic-grace', '30']
        cmd += [sys.executable, _DIST_WORKER, 'dist-worker',
                str(tmp_path), tag]
        return subprocess.run(cmd, env=env, capture_output=True,
                              text=True, timeout=300)

    proc = run('rstraight', 1)
    assert proc.returncode == 0, (proc.stdout, proc.stderr)
    proc = run('relastic', 2, elastic_mode=True,
               MXNET_TPU_FAULT_KILL_AT_STEP='5',
               MXNET_TPU_FAULT_KILL_RANK='1')
    assert proc.returncode == 0, (proc.stdout, proc.stderr)
    assert 'PREEMPTED' in proc.stdout and 'dead_ranks=[1]' in \
        proc.stdout, (proc.stdout, proc.stderr)
    assert 'RESUMED step=' in proc.stdout, proc.stdout
    a = np.load(str(tmp_path / 'params_rstraight_r0.npz'))
    b = np.load(str(tmp_path / 'params_relastic_r0.npz'))
    assert sorted(a.files) == sorted(b.files)
    for name in a.files:
        np.testing.assert_array_equal(a[name], b[name], err_msg=name)
