"""Multi-host distributed runtime tests (mxnet_tpu/dist.py +
tools/launch.py): coordinator bootstrap with retry/deadline,
health-checked barriers that NAME absent/dead ranks instead of
hanging, heartbeat-loss death detection feeding coordinated elastic
restart (Preempted carries the dead-rank set), the KVStore
rank/size/barrier/num_dead_node facade, the launcher's fail-fast +
signal-forwarding + --elastic supervision, and the dist_* counters.

The coordinated-restart contract under test: SIGKILL one of two
launcher-spawned workers mid-epoch -> the survivor detects the death
by heartbeat loss within the deadline, drains, commits a final
elastic checkpoint, exits PREEMPTED_EXIT -> the supervisor relaunches
at reduced world size -> training finishes BIT-IDENTICAL to the
uninterrupted run.
"""
import json
import os
import signal
import socket
import subprocess
import sys
import threading
import time

import numpy as np
import pytest

import mxnet_tpu as mx
from mxnet_tpu import dist, elastic, profiler
from mxnet_tpu import sym as S
from mxnet_tpu.base import MXNetError

_REPO = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))
_LAUNCH = os.path.join(_REPO, 'tools', 'launch.py')


def _mlp_symbol():
    data = S.Variable('data')
    fc1 = S.FullyConnected(data, name='fc1', num_hidden=16)
    act = S.Activation(fc1, act_type='relu')
    return S.SoftmaxOutput(
        S.FullyConnected(act, name='fc2', num_hidden=4), name='softmax')


def _pair(dead_after=0.5, hb=0.1, world=2):
    """A coordinator + `world` in-process runtimes (virtual ranks) —
    the single-process harness for the cross-process protocol."""
    coord = dist.Coordinator(port=0, world=world,
                             bind_addr='127.0.0.1',
                             dead_after=dead_after).start()
    rts = [None] * world
    errs = [None] * world

    def mk(r):
        try:
            rts[r] = dist.DistRuntime(
                r, world, address='127.0.0.1', port=coord.port,
                start_coordinator=False, timeout=15,
                hb_interval=hb, dead_after=dead_after)
        except BaseException as e:      # surfaced by the caller
            errs[r] = e
    ts = [threading.Thread(target=mk, args=(r,)) for r in range(world)]
    for t in ts:
        t.start()
    for t in ts:
        t.join()
    assert all(e is None for e in errs), errs
    return coord, rts


def _teardown(coord, rts):
    # rank 0 last: an owning rank 0 waits for its peers to say bye
    # before stopping the coordinator (here the coordinator is
    # standalone, but keep the canonical order anyway)
    for rt in reversed(rts):
        if rt is not None:
            rt.shutdown()
    coord.stop()


# ---------------------------------------------------------------------------
# bootstrap: connect retry + deadline, startup barrier naming ranks
# ---------------------------------------------------------------------------

def test_bootstrap_deadline_names_coordinator():
    # nothing listens on this port: the connect retry must give up at
    # the hard deadline with an error naming the address — not hang
    probe = socket.socket()
    probe.bind(('127.0.0.1', 0))
    port = probe.getsockname()[1]
    probe.close()
    t0 = time.monotonic()
    with pytest.raises(MXNetError, match='could not reach'):
        dist.DistRuntime(1, 2, address='127.0.0.1', port=port,
                         start_coordinator=False, timeout=1.2)
    dt = time.monotonic() - t0
    assert 1.0 <= dt < 10, dt


def test_bootstrap_retries_until_late_coordinator():
    # the coordinator comes up 0.6s AFTER the worker starts dialing:
    # exponential-backoff retry under the deadline must succeed (a
    # late-starting rank 0 is normal, not an abort)
    probe = socket.socket()
    probe.bind(('127.0.0.1', 0))
    port = probe.getsockname()[1]
    probe.close()
    box = {}

    def late():
        time.sleep(0.6)
        box['coord'] = dist.Coordinator(
            port=port, world=1, bind_addr='127.0.0.1').start()
    t = threading.Thread(target=late)
    t.start()
    try:
        rt = dist.DistRuntime(0, 1, address='127.0.0.1', port=port,
                              start_coordinator=False, timeout=15,
                              heartbeat=False)
        assert rt.rank == 0 and rt.world == 1
        rt.shutdown()
    finally:
        t.join()
        box['coord'].stop()


def test_startup_barrier_names_missing_rank():
    # rank 1 never starts: rank 0's bootstrap must fail within the
    # deadline with the MISSING rank named (the reference's
    # worker+server+scheduler startup-barrier role, minus the hang)
    coord = dist.Coordinator(port=0, world=2,
                             bind_addr='127.0.0.1').start()
    try:
        with pytest.raises(MXNetError) as excinfo:
            dist.DistRuntime(0, 2, address='127.0.0.1',
                             port=coord.port, start_coordinator=False,
                             timeout=1.5, heartbeat=False)
        assert '[1]' in str(excinfo.value)
        assert 'never arrived' in str(excinfo.value)
    finally:
        coord.stop()


# ---------------------------------------------------------------------------
# barriers: timeout naming absent ranks, stall knob, dead-rank failure
# ---------------------------------------------------------------------------

def test_barrier_timeout_names_absent_ranks():
    coord, rts = _pair(dead_after=30)   # nobody dies; rank 1 just
    try:                                # never shows up at the barrier
        with pytest.raises(MXNetError) as excinfo:
            rts[0].barrier('late', timeout=1.0)
        msg = str(excinfo.value)
        assert '[1]' in msg and 'never arrived' in msg
        assert 'MXNET_TPU_BARRIER_TIMEOUT_S' in msg
    finally:
        _teardown(coord, rts)


def test_barrier_stall_fault_arrives_late(monkeypatch):
    # MXNET_TPU_FAULT_BARRIER_STALL_S='1:0.4': rank 1 arrives 0.4s
    # late; within the timeout the barrier completes and the wait is
    # visible in dist_barrier_wait_ms
    profiler.clear()
    coord, rts = _pair(dead_after=30)
    monkeypatch.setenv('MXNET_TPU_FAULT_BARRIER_STALL_S', '1:0.4')
    res = [None, None]

    def bar(r):
        try:
            rts[r].barrier('stalled', timeout=10)
            res[r] = 'ok'
        except MXNetError as e:
            res[r] = e
    try:
        ts = [threading.Thread(target=bar, args=(r,)) for r in (0, 1)]
        for t in ts:
            t.start()
        for t in ts:
            t.join()
        assert res == ['ok', 'ok'], res
        st = profiler.dist_stats()
        assert st['dist_barriers'] >= 2
        assert st['dist_barrier_wait_ms'] >= 300
    finally:
        _teardown(coord, rts)


def test_heartbeat_loss_fails_barrier_naming_dead_rank(monkeypatch):
    # rank 1 keeps running but its heartbeats are dropped (injected
    # partition): the coordinator declares it dead and a waiting
    # barrier FAILS FAST naming it, instead of hanging the collective
    coord, rts = _pair(dead_after=0.5)
    monkeypatch.setenv('MXNET_TPU_FAULT_HEARTBEAT_DROP', '1')
    try:
        with pytest.raises(MXNetError, match=r'\[1\] are dead'):
            rts[0].barrier('doomed', timeout=15)
    finally:
        _teardown(coord, rts)


# ---------------------------------------------------------------------------
# death detection -> coordinated preemption + KVStore facade
# ---------------------------------------------------------------------------

def test_heartbeat_loss_preempts_with_dead_rank_set(monkeypatch):
    profiler.clear()
    coord, rts = _pair(dead_after=0.5)
    mod = mx.mod.Module(_mlp_symbol())
    mod.bind(data_shapes=[mx.io.DataDesc('data', (8, 6))],
             label_shapes=[mx.io.DataDesc('softmax_label', (8,))])
    mod.init_params()
    mod.init_optimizer()
    mgr = elastic.CheckpointManager(
        os.path.join(os.environ.get('TMPDIR', '/tmp'),
                     'dist_preempt_%d' % os.getpid()),
        rank=0, world=1)
    mgr.attach(mod)
    rts[0].watch(mgr)
    monkeypatch.setenv('MXNET_TPU_FAULT_HEARTBEAT_DROP', '1')
    monkeypatch.setattr(dist, '_RUNTIME', rts[0])
    try:
        deadline = time.monotonic() + 10
        while time.monotonic() < deadline and not mgr.preempted:
            time.sleep(0.05)
        assert mgr.preempted, 'heartbeat loss never preempted the mgr'
        assert mgr.preempt_dead_ranks == frozenset({1})
        # the next step boundary commits a final checkpoint and
        # raises Preempted carrying the dead-rank set
        with pytest.raises(elastic.Preempted) as excinfo:
            mgr.step_end(epoch=0, batches_in_epoch=3, batch_size=8)
        assert excinfo.value.dead_ranks == frozenset({1})
        assert excinfo.value.checkpoint_dir is not None
        # KVStore facade: num_dead_node reports the REAL death, the
        # barrier fails fast naming it, rank/size ride the runtime
        kv = mx.kvstore.KVStore('dist_sync')
        assert kv.num_dead_node == 1
        assert kv.rank == 0 and kv.num_workers == 2
        with pytest.raises(MXNetError, match=r'\[1\]'):
            kv.barrier()
        assert elastic.num_dead_node() == 1
        st = profiler.dist_stats()
        assert st['dist_dead_hosts_detected'] >= 1
        assert st['dist_heartbeats_sent'] > 0
        assert st['dist_heartbeats_missed'] > 0
    finally:
        _teardown(coord, rts)
    mgr.close()


def test_allreduce_bitwise_and_dead_rank_failure(monkeypatch):
    coord, rts = _pair(dead_after=0.5)
    try:
        out = [None, None]

        def ar(r):
            out[r] = rts[r].allreduce(
                [np.full((3, 2), float(r + 1), np.float32),
                 np.arange(4, dtype=np.int64) * (r + 1)], name='g')
        ts = [threading.Thread(target=ar, args=(r,)) for r in (0, 1)]
        for t in ts:
            t.start()
        for t in ts:
            t.join()
        # every rank receives IDENTICAL bytes (sum in rank order)
        for i in range(2):
            np.testing.assert_array_equal(out[0][i], out[1][i])
        assert out[0][0][0, 0] == 3.0
        np.testing.assert_array_equal(out[0][1],
                                      np.arange(4, dtype=np.int64) * 3)
        # a dead contributor fails the round with the rank named
        monkeypatch.setenv('MXNET_TPU_FAULT_HEARTBEAT_DROP', '1')
        with pytest.raises(MXNetError, match=r'\[1\] died'):
            rts[0].allreduce([np.ones(2, np.float32)], name='g2',
                             timeout=15)
    finally:
        _teardown(coord, rts)


def test_dist_counters_in_summary_and_dump(tmp_path):
    profiler.clear()
    profiler.add_dist_stats(heartbeats_sent=4, barriers=2,
                            barrier_wait_ms=12.5,
                            dead_hosts_detected=1, restarts=1)
    text = profiler.summary(print_out=False)
    assert 'dist_heartbeats_sent=4' in text
    assert 'dist_dead_hosts_detected=1' in text
    assert 'dist_restarts=1' in text
    fname = str(tmp_path / 'prof.json')
    profiler.profiler_set_config(mode='symbolic', filename=fname)
    path = profiler.dump_profile()
    meta = [e for e in json.load(open(path))['traceEvents']
            if e.get('name') == 'dist']
    assert meta and meta[0]['args']['dist_barriers'] == 2
    profiler.clear()


# ---------------------------------------------------------------------------
# tools/launch.py: fail-fast, signal forwarding, --elastic supervision
# ---------------------------------------------------------------------------

def _launch_env(**extra):
    env = dict(os.environ,
               PYTHONPATH=_REPO + os.pathsep +
               os.environ.get('PYTHONPATH', ''))
    for stale in ('DMLC_PS_ROOT_URI', 'DMLC_PS_ROOT_PORT', 'DMLC_ROLE',
                  'DMLC_NUM_WORKER', 'DMLC_NUM_SERVER',
                  'MXNET_TPU_DIST_PORT',
                  'MXNET_TPU_FAULT_KILL_AT_STEP',
                  'MXNET_TPU_FAULT_KILL_RANK'):
        env.pop(stale, None)
    env.update({k: str(v) for k, v in extra.items()})
    return env


def test_launcher_fail_fast_kills_siblings_and_names_rank(tmp_path):
    # worker 1 exits 3 immediately; worker 0 would sleep forever (the
    # "blocked in a barrier" shape).  The launcher must kill it and
    # exit promptly with worker 1's code and rank in the message.
    prog = ("import os,sys,time\n"
            "rank=int(os.environ['DMLC_WORKER_ID'])\n"
            "sys.exit(3) if rank==1 else time.sleep(120)\n")
    script = tmp_path / 'w.py'
    script.write_text(prog)
    t0 = time.monotonic()
    proc = subprocess.run(
        [sys.executable, _LAUNCH, '-n', '2', '-s', '0', '--grace', '3',
         '--launcher', 'local', sys.executable, str(script)],
        env=_launch_env(), capture_output=True, text=True, timeout=60)
    dt = time.monotonic() - t0
    assert proc.returncode == 3, (proc.returncode, proc.stderr)
    assert 'worker 1' in proc.stderr and 'code 3' in proc.stderr
    assert dt < 30, 'fail-fast took %.1fs (sibling not killed?)' % dt


def test_launcher_forwards_sigterm_to_children(tmp_path):
    # SIGTERM to the launcher must reach the children (the elastic
    # final-checkpoint path runs under the launcher too): each child
    # traps it, writes a marker, exits 0
    prog = ("import os,signal,sys,time\n"
            "rank=os.environ['DMLC_WORKER_ID']\n"
            "out=sys.argv[1]\n"
            "def h(s,f):\n"
            "    open(os.path.join(out,'term_'+rank),'w').write('x')\n"
            "    sys.exit(0)\n"
            "signal.signal(signal.SIGTERM,h)\n"
            "open(os.path.join(out,'ready_'+rank),'w').write('x')\n"
            "time.sleep(60)\n")
    script = tmp_path / 'w.py'
    script.write_text(prog)
    proc = subprocess.Popen(
        [sys.executable, _LAUNCH, '-n', '2', '-s', '0', '--grace', '5',
         '--launcher', 'local', sys.executable, str(script),
         str(tmp_path)],
        env=_launch_env(), stdout=subprocess.PIPE,
        stderr=subprocess.PIPE, text=True)
    deadline = time.monotonic() + 30
    while time.monotonic() < deadline and not (
            (tmp_path / 'ready_0').exists() and
            (tmp_path / 'ready_1').exists()):
        time.sleep(0.1)
    assert (tmp_path / 'ready_0').exists(), 'workers never started'
    proc.send_signal(signal.SIGTERM)
    proc.wait(timeout=30)
    assert (tmp_path / 'term_0').exists(), 'worker 0 never got SIGTERM'
    assert (tmp_path / 'term_1').exists(), 'worker 1 never got SIGTERM'


@pytest.mark.slow
def test_kill_one_of_two_workers_coordinated_restart(tmp_path):
    """slow (~26s, round-16 headroom): the launcher-spawned E2E also
    runs in dryrun phase (i); the pieces stay tier-1 via
    test_heartbeat_loss_preempts_with_dead_rank_set (death detection
    -> Preempted), test_launcher_fail_fast_kills_siblings_and_names_rank
    and test_launcher_forwards_sigterm_to_children (launcher
    semantics), and test_elastic's kill/resume parity tests.

    The acceptance-criteria path end to end: launcher-spawned
    workers, SIGKILL of rank 1 mid-epoch detected by heartbeat loss,
    survivor commits a final checkpoint + exits PREEMPTED_EXIT, the
    --elastic supervisor relaunches at reduced world size, and the
    final weights are BIT-IDENTICAL to the uninterrupted run."""
    def run(tag, n, elastic_mode=False, **fault):
        env = _launch_env(MXNET_TPU_DIST_HEARTBEAT_S='0.1',
                          MXNET_TPU_DIST_DEAD_AFTER_S='0.8',
                          MXNET_TPU_BARRIER_TIMEOUT_S='30',
                          JAX_PLATFORMS='cpu', **fault)
        cmd = [sys.executable, _LAUNCH, '-n', str(n), '-s', '0',
               '--launcher', 'local']
        if elastic_mode:
            cmd += ['--elastic', '--elastic-shrink', '--max-restarts',
                    '2', '--elastic-grace', '30']
        cmd += [sys.executable, os.path.abspath(__file__),
                'dist-worker', str(tmp_path), tag]
        return subprocess.run(cmd, env=env, capture_output=True,
                              text=True, timeout=300)

    proc = run('straight', 1)
    assert proc.returncode == 0, (proc.stdout, proc.stderr)
    proc = run('elastic', 2, elastic_mode=True,
               MXNET_TPU_FAULT_KILL_AT_STEP='5',
               MXNET_TPU_FAULT_KILL_RANK='1')
    assert proc.returncode == 0, (proc.stdout, proc.stderr)
    assert 'PREEMPTED' in proc.stdout and 'dead_ranks=[1]' in \
        proc.stdout, (proc.stdout, proc.stderr)
    assert 'RESUMED step=' in proc.stdout, proc.stdout
    assert 'elastic restart 1/' in proc.stderr, proc.stderr
    a = np.load(str(tmp_path / 'params_straight_r0.npz'))
    b = np.load(str(tmp_path / 'params_elastic_r0.npz'))
    assert sorted(a.files) == sorted(b.files)
    for n in a.files:
        np.testing.assert_array_equal(a[n], b[n], err_msg=n)


# ---------------------------------------------------------------------------
# subprocess dist worker (test_kill_one_of_two_workers_*)
# ---------------------------------------------------------------------------

def _dist_worker(out_dir, tag):
    """Child (under tools/launch.py): dist bootstrap, dist_sync
    kvstore dp (cross-host grad sum through the coordinator), elastic
    checkpoints watched by the runtime.  MXNET_TPU_FAULT_KILL_RANK=1
    SIGKILLs rank 1 at KILL_AT_STEP; survivors preempt, commit and
    exit PREEMPTED_EXIT for the --elastic supervisor."""
    rt = dist.initialize()
    mod = mx.mod.Module(_mlp_symbol())
    bsz = 8
    mod.bind(data_shapes=[mx.io.DataDesc('data', (bsz, 6))],
             label_shapes=[mx.io.DataDesc('softmax_label', (bsz,))])
    mx.random.seed(7)
    mod.init_params(initializer=mx.init.Xavier())
    kv = mx.kvstore.create('dist_sync')
    mod.init_optimizer(kvstore=kv, optimizer='sgd',
                       optimizer_params={'learning_rate': 0.1,
                                         'momentum': 0.9})
    mgr = elastic.CheckpointManager(
        os.path.join(out_dir, 'ck_' + tag), every_n_steps=2)
    mgr.attach(mod)
    rt.watch(mgr)
    info = mgr.restore()
    start = info.step if info is not None else 0
    if info is not None:
        print('RESUMED step=%d world=%d' % (start, rt.world))
    feed = np.random.RandomState(3)
    try:
        for s in range(10):
            x = feed.rand(bsz, 6).astype(np.float32)
            y = (feed.rand(bsz) * 4).astype(np.float32)
            if s < start:
                continue
            batch = mx.io.DataBatch(data=[mx.nd.array(x)],
                                    label=[mx.nd.array(y)])
            try:
                mod.forward_backward(batch)
                mod.update()
            except MXNetError:
                dead = dist.detect_dead()
                if not dead:
                    raise
                mgr.request_preempt(dead_ranks=dead)
                mgr.step_end(epoch=0, batches_in_epoch=s,
                             batch_size=bsz, steps=0)
            time.sleep(0.04)
            mgr.step_end(epoch=0, batches_in_epoch=s + 1,
                         batch_size=bsz)
    except elastic.Preempted as e:
        print('PREEMPTED step=%d dead_ranks=%s'
              % (e.step, sorted(e.dead_ranks)))
        mgr.close()
        sys.stdout.flush()
        os._exit(dist.PREEMPTED_EXIT)
    mgr.close()
    params, _ = mod.get_params()
    np.savez(os.path.join(out_dir, 'params_%s_r%d.npz'
                          % (tag, rt.rank)),
             **{n: v.asnumpy() for n, v in params.items()})
    kv.barrier()
    rt.shutdown()
    print('DIST_WORKER_OK rank=%d world=%d' % (rt.rank, rt.world))


if __name__ == '__main__':
    if len(sys.argv) >= 4 and sys.argv[1] == 'dist-worker':
        _dist_worker(sys.argv[2], sys.argv[3])
    else:
        raise SystemExit('usage: test_dist_runtime.py dist-worker '
                         '<out_dir> <tag>')
