"""Parallel host decode pipeline tests (image.ImageIter
preprocess_threads / MXNET_TPU_DECODE_WORKERS; reference
src/io/iter_image_recordio.cc semantics): deterministic in-order
reassembly, per-sample seeded augmentation streams, sharding,
shutdown, and failure propagation."""
import random as pyrandom
import threading

import numpy as np
import pytest

import mxnet_tpu as mx
from mxnet_tpu import image, profiler, recordio


def _make_img(h, w, seed):
    rng = np.random.RandomState(seed)
    return rng.randint(0, 255, (h, w, 3)).astype(np.uint8)


def _encode(img):
    import cv2
    ret, buf = cv2.imencode('.png', img)
    assert ret
    return buf.tobytes()


def _write_rec(tmp_path, n=22, size=33):
    prefix = str(tmp_path / 'data')
    rec = recordio.MXIndexedRecordIO(prefix + '.idx', prefix + '.rec', 'w')
    for i in range(n):
        img = _make_img(size, size + 4, seed=i)
        header = recordio.IRHeader(0, float(i), i, 0)
        rec.write_idx(i, recordio.pack(header, _encode(img)))
    rec.close()
    return prefix


def _epoch(it, reset=True):
    """Materialize one epoch as [(data, label, pad), ...]."""
    if reset:
        it.reset()
    out = []
    while True:
        try:
            b = it.next()
        except StopIteration:
            break
        out.append((b.data[0].asnumpy(), b.label[0].asnumpy(), b.pad))
    return out


def _assert_epochs_equal(a, b):
    assert len(a) == len(b)
    for (da, la, pa), (db, lb, pb) in zip(a, b):
        np.testing.assert_array_equal(da, db)
        np.testing.assert_array_equal(la, lb)
        assert pa == pb


def _decode_threads():
    return [t for t in threading.enumerate()
            if 'decode' in t.name and t.is_alive()]


def test_parallel_matches_sequential_deterministic_augs(tmp_path):
    """No random augs: parallel output is bit-identical to the
    sequential iterator batch-for-batch, including the padded final
    partial batch."""
    prefix = _write_rec(tmp_path, n=22)
    seq = image.ImageIter(batch_size=4, data_shape=(3, 24, 24),
                          path_imgrec=prefix + '.rec',
                          preprocess_threads=0)
    par = image.ImageIter(batch_size=4, data_shape=(3, 24, 24),
                          path_imgrec=prefix + '.rec',
                          preprocess_threads=3)
    a, b = _epoch(seq), _epoch(par)
    assert len(a) == 6 and a[-1][2] == 2     # 22 = 5*4 + 2 -> pad 2
    _assert_epochs_equal(a, b)
    # a second epoch from the pool matches the sequential one too
    _assert_epochs_equal(_epoch(seq), _epoch(par))
    par.close()


def test_workers1_is_the_sequential_path(tmp_path):
    """preprocess_threads=1 takes the pre-pipeline code path: with
    random augs and the same python-random seed it is bit-identical to
    preprocess_threads=0 (the acceptance bar for workers=1)."""
    prefix = _write_rec(tmp_path, n=12)

    def run(workers):
        pyrandom.seed(11)
        mx.random.seed(11)
        it = image.ImageIter(batch_size=4, data_shape=(3, 16, 16),
                             path_imgrec=prefix + '.rec',
                             rand_crop=True, rand_mirror=True,
                             preprocess_threads=workers)
        return _epoch(it)

    _assert_epochs_equal(run(0), run(1))


def test_determinism_across_worker_counts(tmp_path):
    """Random augs: a fixed mx.random.seed gives the SAME epoch for any
    parallel worker count (per-sample streams are keyed on epoch
    position, not on worker identity)."""
    prefix = _write_rec(tmp_path, n=18)

    def run(workers):
        mx.random.seed(42)
        it = image.ImageIter(batch_size=4, data_shape=(3, 16, 16),
                             path_imgrec=prefix + '.rec',
                             rand_crop=True, rand_mirror=True,
                             preprocess_threads=workers)
        ep = _epoch(it)
        it.close()
        return ep

    e2 = run(2)
    _assert_epochs_equal(e2, run(5))
    _assert_epochs_equal(e2, run(8))
    # and it IS random: a different seed changes the epoch
    mx.random.seed(43)
    it = image.ImageIter(batch_size=4, data_shape=(3, 16, 16),
                         path_imgrec=prefix + '.rec', rand_crop=True,
                         rand_mirror=True, preprocess_threads=2)
    other = _epoch(it)
    it.close()
    assert not all(np.array_equal(x[0], y[0])
                   for x, y in zip(e2, other))


def test_epochs_advance_augmentation_streams(tmp_path):
    """Consecutive epochs draw different augmentations (streams are
    keyed on the epoch counter), and re-seeding reproduces epoch 0."""
    prefix = _write_rec(tmp_path, n=12)
    mx.random.seed(7)
    it = image.ImageIter(batch_size=4, data_shape=(3, 16, 16),
                         path_imgrec=prefix + '.rec', rand_crop=True,
                         rand_mirror=True, preprocess_threads=3)
    e0, e1 = _epoch(it), _epoch(it)
    assert not all(np.array_equal(x[0], y[0]) for x, y in zip(e0, e1))
    it.close()
    mx.random.seed(7)
    it2 = image.ImageIter(batch_size=4, data_shape=(3, 16, 16),
                          path_imgrec=prefix + '.rec', rand_crop=True,
                          rand_mirror=True, preprocess_threads=4)
    _assert_epochs_equal(e0, _epoch(it2))
    it2.close()


def test_worker_exception_propagates(tmp_path):
    """A record the workers cannot decode re-raises at next()."""
    prefix = str(tmp_path / 'bad')
    rec = recordio.MXIndexedRecordIO(prefix + '.idx', prefix + '.rec', 'w')
    for i in range(8):
        if i == 5:
            payload = b'this is not an image'
        else:
            payload = _encode(_make_img(16, 16, i))
        rec.write_idx(i, recordio.pack(
            recordio.IRHeader(0, float(i), i, 0), payload))
    rec.close()
    it = image.ImageIter(batch_size=4, data_shape=(3, 16, 16),
                         path_imgrec=prefix + '.rec',
                         preprocess_threads=3)
    with pytest.raises(Exception) as excinfo:
        _epoch(it)
    assert 'decode' in str(excinfo.value).lower()
    it.close()
    assert not _decode_threads()


def test_shutdown_leaves_no_live_threads(tmp_path):
    prefix = _write_rec(tmp_path, n=12)
    before = set(_decode_threads())
    it = image.ImageIter(batch_size=4, data_shape=(3, 16, 16),
                         path_imgrec=prefix + '.rec',
                         preprocess_threads=4)
    it.next()
    assert len(set(_decode_threads()) - before) == 4
    it.close()
    assert not set(_decode_threads()) - before
    # close() is not terminal: the pool restarts on demand
    batch = it.next()
    assert batch.data[0].shape == (4, 3, 16, 16)
    it.close()
    assert not set(_decode_threads()) - before


def test_del_joins_workers(tmp_path):
    """Dropping the iterator (no explicit close) must still reap the
    pool: workers hold the sample source, never the iterator."""
    import gc
    prefix = _write_rec(tmp_path, n=12)
    before = set(_decode_threads())
    it = image.ImageIter(batch_size=4, data_shape=(3, 16, 16),
                         path_imgrec=prefix + '.rec',
                         preprocess_threads=3)
    it.next()
    del it
    gc.collect()
    deadline = [t for t in set(_decode_threads()) - before]
    for t in deadline:
        t.join(timeout=5)
    assert not set(_decode_threads()) - before


def test_num_parts_sharding_disjoint(tmp_path):
    """num_parts partitions stay disjoint under the parallel pool and
    cover the same records as the sequential shards."""
    prefix = _write_rec(tmp_path, n=20)
    labels = {}
    for part in (0, 1):
        it = image.ImageIter(batch_size=2, data_shape=(3, 16, 16),
                             path_imgrec=prefix + '.rec', num_parts=2,
                             part_index=part, preprocess_threads=3)
        labels[part] = np.concatenate(
            [lab[:2 - pad if pad else 2] for _, lab, pad in _epoch(it)])
        it.close()
    assert len(labels[0]) == len(labels[1]) == 10
    assert not set(labels[0]) & set(labels[1])
    assert sorted(set(labels[0]) | set(labels[1])) == list(range(20))


def test_host_sharding_env(tmp_path, monkeypatch):
    """MXNET_TPU_HOST_SHARD composes with num_parts: each virtual host
    decodes a disjoint slice; the union matches the full dataset."""
    prefix = _write_rec(tmp_path, n=16)
    full = image.ImageIter(batch_size=4, data_shape=(3, 16, 16),
                           path_imgrec=prefix + '.rec',
                           preprocess_threads=0)
    ref = {}
    for data, lab, pad in _epoch(full):
        for row, y in zip(data, lab):
            ref[float(y)] = row
    shards = {}
    for host in (0, 1):
        monkeypatch.setenv('MXNET_TPU_HOST_SHARD', '%d/2' % host)
        it = image.ImageIter(batch_size=4, data_shape=(3, 16, 16),
                             path_imgrec=prefix + '.rec',
                             preprocess_threads=2)
        shards[host] = {}
        for data, lab, pad in _epoch(it):
            for row, y in zip(data, lab):
                shards[host][float(y)] = row
        it.close()
    assert len(shards[0]) == len(shards[1]) == 8
    assert not set(shards[0]) & set(shards[1])
    merged = dict(shards[0])
    merged.update(shards[1])
    assert set(merged) == set(ref)
    for y, row in merged.items():
        np.testing.assert_array_equal(row, ref[y])     # batch parity


def test_image_det_iter_parallel(tmp_path):
    """ImageDetIter runs through the pool: parity with the sequential
    detection pipeline (deterministic augs) incl. padded label rows."""
    import cv2
    prefix = str(tmp_path / 'det')
    rec = recordio.MXIndexedRecordIO(prefix + '.idx', prefix + '.rec', 'w')
    rng = np.random.RandomState(0)
    for i in range(10):
        img = rng.randint(0, 255, (48, 48, 3)).astype(np.uint8)
        ret, buf = cv2.imencode('.png', img)
        nobj = 1 + i % 3
        label = [2, 5]
        for j in range(nobj):
            label += [float(j % 4), 0.1, 0.1, 0.6, 0.6]
        rec.write_idx(i, recordio.pack(
            recordio.IRHeader(0, np.array(label, np.float32), i, 0),
            buf.tobytes()))
    rec.close()
    seq = mx.image.ImageDetIter(batch_size=4, data_shape=(3, 32, 32),
                                path_imgrec=prefix + '.rec',
                                preprocess_threads=0)
    par = mx.image.ImageDetIter(batch_size=4, data_shape=(3, 32, 32),
                                path_imgrec=prefix + '.rec',
                                preprocess_threads=3)
    assert par.max_objects == seq.max_objects == 3
    _assert_epochs_equal(_epoch(seq), _epoch(par))
    par.close()


def test_det_iter_max_objects_agrees_across_shards(tmp_path):
    """max_objects derives from the FULL dataset, not the local shard,
    so partitioned/per-host iterators bind identical label shapes."""
    import cv2
    prefix = str(tmp_path / 'detshard')
    rec = recordio.MXIndexedRecordIO(prefix + '.idx', prefix + '.rec', 'w')
    rng = np.random.RandomState(0)
    for i in range(8):
        img = rng.randint(0, 255, (24, 24, 3)).astype(np.uint8)
        ret, buf = cv2.imencode('.png', img)
        nobj = 4 if i >= 4 else 1   # big labels live in one half only
        label = [2, 5]
        for j in range(nobj):
            label += [float(j), 0.1, 0.1, 0.6, 0.6]
        rec.write_idx(i, recordio.pack(
            recordio.IRHeader(0, np.array(label, np.float32), i, 0),
            buf.tobytes()))
    rec.close()
    its = [mx.image.ImageDetIter(batch_size=2, data_shape=(3, 16, 16),
                                 path_imgrec=prefix + '.rec',
                                 num_parts=2, part_index=p)
           for p in (0, 1)]
    assert its[0].max_objects == its[1].max_objects == 4
    assert its[0].provide_label[0].shape == its[1].provide_label[0].shape


def _write_det_rec_n(prefix, n, nobj_fn):
    import cv2
    rec = recordio.MXIndexedRecordIO(prefix + '.idx', prefix + '.rec', 'w')
    rng = np.random.RandomState(0)
    for i in range(n):
        img = rng.randint(0, 255, (24, 24, 3)).astype(np.uint8)
        ret, buf = cv2.imencode('.png', img)
        label = [2, 5]
        for j in range(nobj_fn(i)):
            label += [float(j), 0.1, 0.1, 0.6, 0.6]
        rec.write_idx(i, recordio.pack(
            recordio.IRHeader(0, np.array(label, np.float32), i, 0),
            buf.tobytes()))
    rec.close()


def test_det_sync_label_shape_mid_pool(tmp_path):
    """Growing max_objects after the pool has staged samples discards
    the old-shape staging and re-decodes with the new padding."""
    pa = str(tmp_path / 'a')
    pb = str(tmp_path / 'b')
    _write_det_rec_n(pa, 12, lambda i: 2)
    _write_det_rec_n(pb, 4, lambda i: 5)
    ita = mx.image.ImageDetIter(batch_size=2, data_shape=(3, 16, 16),
                                path_imgrec=pa + '.rec',
                                preprocess_threads=3)
    itb = mx.image.ImageDetIter(batch_size=2, data_shape=(3, 16, 16),
                                path_imgrec=pb + '.rec')
    first = ita.next()           # pool stages chunks padded to 2
    assert first.label[0].shape == (2, 2, 5)
    ita.sync_label_shape(itb)
    assert ita.max_objects == 5
    nxt = ita.next()             # staged old-shape samples discarded
    assert nxt.label[0].shape == (2, 5, 5)
    ita.close()


def test_image_record_iter_python_pipeline(tmp_path):
    """ImageRecordIter's python fallback threads preprocess_threads
    through to the decode pool (stacked under PrefetchingIter)."""
    prefix = _write_rec(tmp_path, n=12, size=30)
    it = mx.io.ImageRecordIter(
        path_imgrec=prefix + '.rec', data_shape=(3, 24, 24),
        batch_size=3, shuffle=False, use_native=False,
        preprocess_threads=3)
    batches = list(it)
    assert len(batches) == 4
    assert batches[0].data[0].shape == (3, 3, 24, 24)
    it._inner.close()


def test_profiler_input_counters(tmp_path):
    prefix = _write_rec(tmp_path, n=12)
    profiler.clear()
    it = image.ImageIter(batch_size=4, data_shape=(3, 16, 16),
                         path_imgrec=prefix + '.rec',
                         preprocess_threads=3)
    _epoch(it)
    it.close()
    st = profiler.input_stats()
    assert st['decoded_samples'] >= 12
    assert st['decode_ms'] > 0
    assert st['queue_depth_obs'] > 0
    text = profiler.summary(print_out=False)
    assert 'decode_ms' in text and 'queue_depth_avg' in text


def test_prefetch_to_device_feeds_stall_counter():
    profiler.clear()
    X = np.random.RandomState(0).rand(8, 3).astype(np.float32)
    y = np.arange(8, dtype=np.float32)
    src = mx.io.NDArrayIter(X, y, batch_size=4)
    pf = mx.io.prefetch_to_device(src, size=2)
    list(pf)
    st = profiler.input_stats()
    assert st['input_batches'] == 2
    assert st['input_stall_ms'] >= 0


def test_fit_auto_wires_decode_workers(tmp_path, monkeypatch):
    """Module._wrap_train_iter upgrades a default-constructed ImageIter
    to the env's worker count (explicit preprocess_threads wins)."""
    prefix = _write_rec(tmp_path, n=12)
    monkeypatch.delenv('MXNET_TPU_DECODE_WORKERS', raising=False)
    it = image.ImageIter(batch_size=4, data_shape=(3, 16, 16),
                         path_imgrec=prefix + '.rec')
    assert it.preprocess_threads == 0 and it._workers_explicit is False
    monkeypatch.setenv('MXNET_TPU_DECODE_WORKERS', '3')
    from mxnet_tpu import sym as S
    net = S.SoftmaxOutput(S.FullyConnected(S.Variable('data'),
                                           num_hidden=4), name='softmax')
    mod = mx.mod.Module(net)
    wrapped = mod._wrap_train_iter(it)
    assert it.preprocess_threads == 3
    # explicit worker counts are left alone
    it2 = image.ImageIter(batch_size=4, data_shape=(3, 16, 16),
                          path_imgrec=prefix + '.rec',
                          preprocess_threads=0)
    it2._workers_explicit = True
    mod._wrap_train_iter(it2)
    assert it2.preprocess_threads == 0
    del wrapped
    it.close()


def test_seed_generation_counter_reaches_running_threads():
    """random.seed() re-derives streams in threads that already drew
    (the generation-counter satellite)."""
    from mxnet_tpu import random as mxrandom
    import jax
    results = {}
    gate_drawn = threading.Event()
    gate_reseeded = threading.Event()

    def worker():
        results['first'] = np.asarray(mxrandom.next_key())
        gate_drawn.set()
        assert gate_reseeded.wait(10)
        # after the main thread reseeded, this thread's NEXT draw must
        # restart from the new seed, not continue its old stream
        results['second'] = np.asarray(mxrandom.next_key())

    t = threading.Thread(target=worker)
    t.start()
    assert gate_drawn.wait(10)
    mxrandom.seed(12345)
    expected = np.asarray(jax.random.split(jax.random.PRNGKey(12345))[1])
    gate_reseeded.set()
    t.join(10)
    np.testing.assert_array_equal(results['second'], expected)


def test_stream_seed_reproducible():
    from mxnet_tpu import random as mxrandom
    mxrandom.seed(5)
    a = mxrandom.stream_seed('image-aug', 0, 3)
    assert a == mxrandom.stream_seed('image-aug', 0, 3)
    assert a != mxrandom.stream_seed('image-aug', 0, 4)
    assert a != mxrandom.stream_seed('image-aug', 1, 3)
    mxrandom.seed(6)
    assert a != mxrandom.stream_seed('image-aug', 0, 3)
    mxrandom.seed(5)
    assert a == mxrandom.stream_seed('image-aug', 0, 3)


def test_recordio_read_at_concurrent(tmp_path):
    """read_idx is positional (os.pread): concurrent readers through
    ONE handle see correct records and the cursor never moves."""
    prefix = _write_rec(tmp_path, n=16)
    rec = recordio.MXIndexedRecordIO(prefix + '.idx', prefix + '.rec', 'r')
    errors = []

    def hammer(worker_seed):
        order = list(rec.keys)
        pyrandom.Random(worker_seed).shuffle(order)
        try:
            for k in order * 4:
                header, _ = recordio.unpack(rec.read_idx(k))
                if float(header.label) != float(k):
                    errors.append((k, float(header.label)))
        except Exception as e:  # pragma: no cover - failure reporting
            errors.append(repr(e))

    threads = [threading.Thread(target=hammer, args=(i,))
               for i in range(6)]
    for t in threads:
        t.start()
    for t in threads:
        t.join(20)
    assert not errors
    rec.close()
