"""Fused Gluon training step (gluon/fused.py): whole-step compilation
for the imperative train loop.  Parity vs the imperative path (SGD +
momentum/wd/clip, bf16 params with fp32 masters, multi-device mesh,
ZeRO-1 on/off), lax.scan bulking, trainer re-creation hitting
exec_cache with zero new compiles, checkpoint round-trips across the
fused/un-fused paths, and the un-fused Trainer.step batched
multi-device gradient reduce.

Note on tolerances: the fused step compiles forward+loss+backward+
update into ONE XLA program, while the imperative path dispatches
per tape node — XLA fuses (and FMA-contracts) the two partitions
differently, so agreement is float32-ulp-level (measured ~1.5e-8),
not bitwise.  The fused path itself is bitwise deterministic
(test_fused_determinism_bitwise), as is single-vs-bulk.
"""
import os
import tempfile
from collections import deque

import numpy as np
import pytest
import jax.numpy as jnp

import mxnet_tpu as mx
from mxnet_tpu import autograd, exec_cache, gluon, profiler
from mxnet_tpu.gluon import nn
from mxnet_tpu.gluon.utils import split_and_load

BATCH = 8
FEAT = 6
NCLS = 4
OPT_MOM = {'learning_rate': 0.1, 'momentum': 0.9, 'wd': 1e-3}
OPT_PLAIN = {'learning_rate': 0.1}


def _make_net(seed, ctx=None, in_units=FEAT):
    net = nn.HybridSequential()
    with net.name_scope():
        net.add(nn.Dense(16, activation='relu', in_units=in_units))
        net.add(nn.Dense(NCLS, in_units=16))
    net.initialize(ctx=ctx)
    if in_units:
        _seed_params(net, seed)
    return net


def _seed_params(net, seed):
    rs = np.random.RandomState(seed)
    for _, p in sorted(net.collect_params().items()):
        p.set_data(mx.nd.array(
            (rs.rand(*p.shape).astype(np.float32) - 0.5) * 0.4))


def _pvals(net):
    return [p.list_data()[0].asnumpy().astype(np.float32)
            for _, p in sorted(net.collect_params().items())]


def _set_pvals(net, vals):
    for (_, p), v in zip(sorted(net.collect_params().items()), vals):
        p.set_data(mx.nd.array(v))


def _batches(k=3, seed=42):
    rs = np.random.RandomState(seed)
    return [(mx.nd.array(rs.rand(BATCH, FEAT).astype(np.float32)),
             mx.nd.array((rs.rand(BATCH) * NCLS).astype(np.float32)))
            for _ in range(k)]


_LOSS = gluon.loss.SoftmaxCrossEntropyLoss()


def _imperative_train(net, trainer, batches):
    for x, y in batches:
        with autograd.record():
            l = _LOSS(net(x), y)
        l.backward()
        trainer.step(BATCH)


def _fused_train(net, trainer, batches, **fuse_kw):
    fs = gluon.fuse_step(net, _LOSS, trainer, **fuse_kw)
    for x, y in batches:
        fs(x, y)
    return fs


def _assert_close(a_vals, b_vals, atol=1e-6, rtol=1e-5):
    for a, b in zip(a_vals, b_vals):
        np.testing.assert_allclose(a, b, atol=atol, rtol=rtol)


# ---------------------------------------------------------------------------
# parity vs the imperative path
# ---------------------------------------------------------------------------

def test_fused_parity_plain_sgd():
    batches = _batches()
    ni = _make_net(1)
    _imperative_train(ni, gluon.Trainer(ni.collect_params(), 'sgd',
                                        dict(OPT_PLAIN)), batches)
    nf = _make_net(1)
    fs = _fused_train(nf, gluon.Trainer(nf.collect_params(), 'sgd',
                                        dict(OPT_PLAIN)), batches)
    # float32-ulp agreement (see module docstring)
    _assert_close(_pvals(ni), _pvals(nf), atol=5e-8, rtol=1e-6)
    # the returned loss is the per-sample loss
    x, y = batches[0]
    assert fs(x, y).shape == (BATCH,)


def test_fused_determinism_bitwise():
    batches = _batches()
    runs = []
    for _ in range(2):
        mx.random.seed(11)
        net = _make_net(1)
        _fused_train(net, gluon.Trainer(net.collect_params(), 'sgd',
                                        dict(OPT_MOM)), batches)
        runs.append(_pvals(net))
    for a, b in zip(*runs):
        assert np.array_equal(a, b)


def test_fused_parity_momentum_wd_clip():
    kw = dict(OPT_MOM, clip_gradient=0.05)
    batches = _batches()
    ni = _make_net(2)
    _imperative_train(ni, gluon.Trainer(ni.collect_params(), 'sgd',
                                        dict(kw)), batches)
    nf = _make_net(2)
    _fused_train(nf, gluon.Trainer(nf.collect_params(), 'sgd',
                                   dict(kw)), batches)
    _assert_close(_pvals(ni), _pvals(nf))


def test_fused_bf16_fp32_masters():
    kw = {'learning_rate': 0.1, 'momentum': 0.9, 'multi_precision': True}
    batches = [(x.astype(jnp.bfloat16), y) for x, y in _batches()]
    nets = []
    for arm in ('imperative', 'fused'):
        net = _make_net(5)
        net.cast('bfloat16')
        tr = gluon.Trainer(net.collect_params(), 'sgd', dict(kw))
        if arm == 'imperative':
            _imperative_train(net, tr, batches)
        else:
            _fused_train(net, tr, batches)
            fu = tr._fused_updater
            # fp32 masters live inside the fused step
            assert sum(m is not None for m in fu.masters.values()) == 4
        nets.append(net)
    # bf16 weights: one-ulp agreement
    _assert_close(_pvals(nets[0]), _pvals(nets[1]), atol=2e-3, rtol=1e-2)


def test_fused_deferred_init():
    net = _make_net(0, in_units=0)   # shapes complete on first forward
    tr = gluon.Trainer(net.collect_params(), 'sgd', dict(OPT_PLAIN))
    fs = gluon.fuse_step(net, _LOSS, tr)
    x, y = _batches(1)[0]
    before_missing = net[0].weight.shape is None or \
        0 in net[0].weight.shape
    assert before_missing
    fs(x, y)
    assert net[0].weight.shape == (16, FEAT)


def test_fused_batchnorm_aux_updates():
    def bn_net(seed):
        net = nn.HybridSequential()
        with net.name_scope():
            net.add(nn.Dense(16, in_units=FEAT))
            net.add(nn.BatchNorm(in_channels=16))
            net.add(nn.Dense(NCLS, in_units=16))
        net.initialize()
        _seed_params(net, seed)
        return net

    batches = _batches()
    ni = bn_net(4)
    _imperative_train(ni, gluon.Trainer(ni.collect_params(), 'sgd',
                                        dict(OPT_PLAIN)), batches)
    nf = bn_net(4)
    tr = gluon.Trainer(nf.collect_params(), 'sgd', dict(OPT_PLAIN))
    fs = gluon.fuse_step(nf, _LOSS, tr)
    before = nf[1].running_mean.data().asnumpy().copy()
    for x, y in batches:
        fs(x, y)
    # running stats are non-trainable: they ride the fused step's
    # mutable-aux path, not the optimizer
    assert len(fs._aux_params) == 2
    after = nf[1].running_mean.data().asnumpy()
    assert not np.allclose(before, after)
    np.testing.assert_allclose(
        ni[1].running_mean.data().asnumpy(), after, atol=1e-6, rtol=1e-5)
    _assert_close(_pvals(ni), _pvals(nf))


def test_fused_frozen_params_stay_frozen():
    net = _make_net(6)
    batches = _batches()
    # train only the second Dense; the first is frozen (still traced as
    # an input — never constant-folded into the program)
    sub = {k: v for k, v in net.collect_params().items()
           if 'dense1' in k}
    assert len(sub) == 2
    tr = gluon.Trainer(sub, 'sgd', dict(OPT_PLAIN))
    fs = gluon.fuse_step(net, _LOSS, tr)
    before = _pvals(net)
    for x, y in batches:
        fs(x, y)
    after = _pvals(net)
    assert len(fs._frozen_params) == 2
    changed = [not np.array_equal(a, b) for a, b in zip(before, after)]
    names = [k for k, _ in sorted(net.collect_params().items())]
    for name, ch in zip(names, changed):
        assert ch == ('dense1' in name), name


def test_fused_loss_none():
    class SelfLoss(gluon.HybridBlock):
        def __init__(self):
            super().__init__()
            with self.name_scope():
                self.fc = nn.Dense(1, in_units=FEAT)

        def hybrid_forward(self, F, x):
            out = self.fc(x)
            return F.square(out)

    net = SelfLoss()
    net.initialize()
    tr = gluon.Trainer(net.collect_params(), 'sgd', dict(OPT_PLAIN))
    fs = gluon.fuse_step(net, None, tr)
    x, _ = _batches(1)[0]
    before = _pvals(net)
    l = fs(x)
    assert l.shape == (BATCH, 1)
    assert any(not np.array_equal(a, b)
               for a, b in zip(before, _pvals(net)))


# ---------------------------------------------------------------------------
# mesh / ZeRO
# ---------------------------------------------------------------------------

def test_fused_mesh_multi_device():
    batches = _batches()
    n1 = _make_net(3)
    _fused_train(n1, gluon.Trainer(n1.collect_params(), 'sgd',
                                   dict(OPT_MOM)), batches)
    ctx4 = [mx.cpu(i) for i in range(4)]
    n4 = _make_net(3, ctx=ctx4)
    fs = _fused_train(n4, gluon.Trainer(n4.collect_params(), 'sgd',
                                        dict(OPT_MOM)), batches)
    assert fs._mesh is not None and fs._mesh.devices.size == 4
    _assert_close(_pvals(n1), _pvals(n4), atol=1e-6)
    # every context copy observes the updated value
    p = n4[0].weight
    assert np.array_equal(p.data(ctx4[0]).asnumpy(),
                          p.data(ctx4[3]).asnumpy())
    # eager eval after mesh training still works: the per-context
    # slots hold single-device shard views, not the mesh-committed
    # parent (verify-drive regression)
    x, _ = _batches(1)[0]
    out = n4(mx.nd.array(x.asnumpy(), ctx=ctx4[0]))
    assert out.shape == (BATCH, NCLS)
    # user set_data after fused training is honored: the staleness
    # check re-replicates from the slot instead of reusing the parent
    w0 = n4[0].weight
    w0.set_data(mx.nd.array(np.zeros(w0.shape, np.float32)))
    assert float(np.abs(np.asarray(fs._gather_param(w0))).max()) == 0.0
    fs(*_batches(1)[0])   # and the step still dispatches cleanly


def test_fused_zero_parity_and_sharded_state():
    batches = _batches()
    ctx4 = [mx.cpu(i) for i in range(4)]
    n0 = _make_net(3, ctx=ctx4)
    t0 = gluon.Trainer(n0.collect_params(), 'sgd', dict(OPT_MOM))
    _fused_train(n0, t0, batches, zero=0)
    nz = _make_net(3, ctx=ctx4)
    tz = gluon.Trainer(nz.collect_params(), 'sgd', dict(OPT_MOM))
    _fused_train(nz, tz, batches, zero=1)
    _assert_close(_pvals(n0), _pvals(nz), atol=1e-6)
    # optimizer state is dp-sharded: 1/4 of the replicated residency
    assert tz._fused_updater.zero == 1
    repl = t0._fused_updater.state_bytes_per_device()
    shard = tz._fused_updater.state_bytes_per_device()
    assert 0 < shard <= -(-repl // 4) + 4 * 16  # + dp padding slack


# ---------------------------------------------------------------------------
# bulking, cache, counters
# ---------------------------------------------------------------------------

def test_bulk_matches_single_steps():
    k = 3
    batches = _batches(k)
    n1 = _make_net(8)
    _fused_train(n1, gluon.Trainer(n1.collect_params(), 'sgd',
                                   dict(OPT_MOM)), batches)
    nb = _make_net(8)
    tr = gluon.Trainer(nb.collect_params(), 'sgd', dict(OPT_MOM))
    fs = gluon.fuse_step(nb, _LOSS, tr)
    xs = mx.nd.NDArray(jnp.stack([x._data for x, _ in batches]))
    ys = mx.nd.NDArray(jnp.stack([y._data for _, y in batches]))
    losses = fs.bulk(xs, ys)
    assert losses.shape == (k, BATCH)
    _assert_close(_pvals(n1), _pvals(nb), atol=1e-7)
    # lr schedules advanced k steps
    assert tr._optimizer.num_update == k


def test_trainer_recreation_zero_compiles():
    batches = _batches(2)
    net = _make_net(1)
    _fused_train(net, gluon.Trainer(net.collect_params(), 'sgd',
                                    dict(OPT_MOM)), batches)
    st0 = exec_cache.stats()
    # same architecture, fresh Parameters, different auto-prefix
    net2 = _make_net(77)
    tr2 = gluon.Trainer(net2.collect_params(), 'sgd', dict(OPT_MOM))
    fs2 = gluon.fuse_step(net2, _LOSS, tr2)
    for x, y in batches:
        fs2(x, y)
    st1 = exec_cache.stats()
    assert st1['misses'] == st0['misses']
    assert st1['hits'] >= st0['hits'] + 1
    assert st1['total_compile_s'] == st0['total_compile_s']


def test_fused_counters_and_summary():
    profiler.clear()
    net = _make_net(1)
    tr = gluon.Trainer(net.collect_params(), 'sgd', dict(OPT_MOM))
    fs = gluon.fuse_step(net, _LOSS, tr)
    batches = _batches(2)
    for x, y in batches:
        fs(x, y)
    xs = mx.nd.NDArray(jnp.stack([x._data for x, _ in batches]))
    ys = mx.nd.NDArray(jnp.stack([y._data for _, y in batches]))
    fs.bulk(xs, ys)
    st = profiler.gluon_fused_stats()
    assert st['gluon_fused_steps'] == 4
    assert st['gluon_fused_dispatches'] == 3
    assert st['gluon_fused_steps_per_dispatch'] == pytest.approx(4 / 3)
    assert 'gluon_fused_steps=4' in profiler.summary(print_out=False)
    # dump metadata carries the counters
    fname = os.path.join(tempfile.mkdtemp(), 'prof.json')
    profiler.profiler_set_config(filename=fname)
    profiler.dump_profile()
    import json
    with open(fname) as f:
        events = json.load(f)['traceEvents']
    meta = [e for e in events if e.get('name') == 'gluon_fused']
    assert meta and meta[0]['args']['gluon_fused_steps'] == 4


def test_step_ahead_loss_bit_parity_and_counters(monkeypatch):
    # bounded in-flight depth (overlapped train-step I/O) changes
    # only WHEN the host waits on a dispatch, never what's computed:
    # loss curves at step_ahead=1 must be bitwise identical to the
    # serialized step_ahead=0 run, with the pipeline visible in the
    # overlap_* counters
    from mxnet_tpu.gluon.fused import resolve_step_ahead
    monkeypatch.delenv('MXNET_TPU_TRAIN_STEP_AHEAD', raising=False)
    assert resolve_step_ahead() == 1            # default: 1 ahead
    assert resolve_step_ahead(3) == 3           # explicit arg wins
    for off in ('0', 'off', 'none', 'false'):
        monkeypatch.setenv('MXNET_TPU_TRAIN_STEP_AHEAD', off)
        assert resolve_step_ahead() == 0
    monkeypatch.setenv('MXNET_TPU_TRAIN_STEP_AHEAD', '2')
    assert resolve_step_ahead() == 2
    monkeypatch.delenv('MXNET_TPU_TRAIN_STEP_AHEAD')

    batches = _batches(k=4)
    curves, params = {}, {}
    for ahead in (0, 1):
        profiler.clear()
        net = _make_net(3)
        fs = gluon.fuse_step(
            net, _LOSS,
            gluon.Trainer(net.collect_params(), 'sgd', dict(OPT_MOM)),
            step_ahead=ahead)
        curves[ahead] = [fs(x, y).asnumpy().copy() for x, y in batches]
        params[ahead] = _pvals(net)
        ov = profiler.overlap_stats()
        assert ov['overlap_train_steps'] == len(batches)
        assert ov['overlap_steps_ahead'] == ahead   # gauge at depth
        if ahead == 0:
            assert fs._inflight == deque()          # fully drained
    for a, b in zip(curves[0], curves[1]):
        assert np.array_equal(a, b)
    for a, b in zip(params[0], params[1]):
        assert np.array_equal(a, b)
    profiler.clear()


def test_step_fused_entry_and_unsupported_optimizer():
    net = _make_net(1)
    tr = gluon.Trainer(net.collect_params(), 'sgd', dict(OPT_PLAIN))
    with pytest.raises(ValueError, match='no fused step'):
        tr.step_fused(BATCH, *_batches(1)[0])
    gluon.fuse_step(net, _LOSS, tr)
    x, y = _batches(1)[0]
    before = _pvals(net)
    l = tr.step_fused(BATCH, x, y)
    assert l.shape == (BATCH,)
    assert any(not np.array_equal(a, b)
               for a, b in zip(before, _pvals(net)))

    net2 = _make_net(1)
    tr2 = gluon.Trainer(net2.collect_params(), 'adam')
    with pytest.raises(ValueError, match='no fused whole-model update'):
        gluon.fuse_step(net2, _LOSS, tr2)   # rejected at build time


# ---------------------------------------------------------------------------
# checkpoints
# ---------------------------------------------------------------------------

def _tmpfile():
    fd, name = tempfile.mkstemp()
    os.close(fd)
    return name


def test_checkpoint_roundtrip_fused():
    batches = _batches(5)
    truth_net = _make_net(3)
    _fused_train(truth_net, gluon.Trainer(truth_net.collect_params(),
                                          'sgd', dict(OPT_MOM)), batches)
    truth = _pvals(truth_net)

    fname = _tmpfile()
    n1 = _make_net(3)
    t1 = gluon.Trainer(n1.collect_params(), 'sgd', dict(OPT_MOM))
    _fused_train(n1, t1, batches[:3])
    t1.save_states(fname)
    mid = _pvals(n1)

    n2 = _make_net(99)
    _set_pvals(n2, mid)
    t2 = gluon.Trainer(n2.collect_params(), 'sgd', dict(OPT_MOM))
    t2.load_states(fname)        # load BEFORE the fused step exists
    _fused_train(n2, t2, batches[3:])
    _assert_close(truth, _pvals(n2), atol=1e-7)
    os.remove(fname)


def test_checkpoint_save_before_first_step():
    fname = _tmpfile()
    net = _make_net(3)
    tr = gluon.Trainer(net.collect_params(), 'sgd', dict(OPT_MOM))
    gluon.fuse_step(net, _LOSS, tr)
    tr.save_states(fname)        # nothing ran yet — must round-trip
    net2 = _make_net(3)
    tr2 = gluon.Trainer(net2.collect_params(), 'sgd', dict(OPT_MOM))
    tr2.load_states(fname)
    batches = _batches()
    _fused_train(net2, tr2, batches)
    _fused_train(net, tr, batches)
    _assert_close(_pvals(net), _pvals(net2), atol=1e-7)
    os.remove(fname)


def test_checkpoint_cross_mode():
    """A fused run's states restore into an un-fused trainer (and the
    momentum history carries) — the mode-portable format contract."""
    batches = _batches(5)
    truth_net = _make_net(3)
    _fused_train(truth_net, gluon.Trainer(truth_net.collect_params(),
                                          'sgd', dict(OPT_MOM)), batches)
    truth = _pvals(truth_net)

    fname = _tmpfile()
    n1 = _make_net(3)
    t1 = gluon.Trainer(n1.collect_params(), 'sgd', dict(OPT_MOM))
    _fused_train(n1, t1, batches[:3])
    t1.save_states(fname)
    n2 = _make_net(98)
    _set_pvals(n2, _pvals(n1))
    t2 = gluon.Trainer(n2.collect_params(), 'sgd', dict(OPT_MOM))
    t2.load_states(fname)
    _imperative_train(n2, t2, batches[3:])
    _assert_close(truth, _pvals(n2), atol=1e-6)
    os.remove(fname)


def test_checkpoint_unfused_to_fused():
    """The reverse restore: a PER-KEY Updater checkpoint (None states
    for momentum-free SGD) loads into the fused path (review catch:
    jnp.asarray(None) crashed)."""
    batches = _batches(5)
    truth_net = _make_net(3)
    _imperative_train(truth_net,
                      gluon.Trainer(truth_net.collect_params(), 'sgd',
                                    dict(OPT_PLAIN)), batches)
    truth = _pvals(truth_net)

    fname = _tmpfile()
    n1 = _make_net(3)
    t1 = gluon.Trainer(n1.collect_params(), 'sgd', dict(OPT_PLAIN))
    _imperative_train(n1, t1, batches[:3])
    t1.save_states(fname)
    n2 = _make_net(97)
    _set_pvals(n2, _pvals(n1))
    t2 = gluon.Trainer(n2.collect_params(), 'sgd', dict(OPT_PLAIN))
    t2.load_states(fname)
    _fused_train(n2, t2, batches[3:])
    _assert_close(truth, _pvals(n2), atol=1e-6)
    os.remove(fname)


def test_checkpoint_unfused_mp_to_fused():
    """Per-key multi-precision checkpoints store [momentum, master]
    PAIRS per state — the fused restore must split them (review
    catch: they were silently stacked into a wrong-shaped momentum)."""
    kw = {'learning_rate': 0.1, 'momentum': 0.9, 'multi_precision': True}
    batches = [(x.astype(jnp.bfloat16), y) for x, y in _batches(4)]
    truth_net = _make_net(5)
    truth_net.cast('bfloat16')
    _imperative_train(truth_net,
                      gluon.Trainer(truth_net.collect_params(), 'sgd',
                                    dict(kw)), batches)
    truth = _pvals(truth_net)

    fname = _tmpfile()
    n1 = _make_net(5)
    n1.cast('bfloat16')
    t1 = gluon.Trainer(n1.collect_params(), 'sgd', dict(kw))
    _imperative_train(n1, t1, batches[:2])
    t1.save_states(fname)
    n2 = _make_net(96)
    n2.cast('bfloat16')
    for (_, a), (_, b) in zip(sorted(n1.collect_params().items()),
                              sorted(n2.collect_params().items())):
        b.set_data(a.data())
    t2 = gluon.Trainer(n2.collect_params(), 'sgd', dict(kw))
    t2.load_states(fname)
    _fused_train(n2, t2, batches[2:])
    assert sum(m is not None
               for m in t2._fused_updater.masters.values()) == 4
    _assert_close(truth, _pvals(n2), atol=2e-2, rtol=5e-2)
    os.remove(fname)


def test_mode_switch_shares_optimizer_state():
    """Interleaving trainer.step() and fused() must train against ONE
    momentum history (review catch: the two paths each kept their own
    states, so switching silently reset momenta)."""
    batches = _batches(4)
    truth_net = _make_net(3)
    _imperative_train(truth_net,
                      gluon.Trainer(truth_net.collect_params(), 'sgd',
                                    dict(OPT_MOM)), batches)
    truth = _pvals(truth_net)

    # warm un-fused momentum, then switch to fused
    n1 = _make_net(3)
    t1 = gluon.Trainer(n1.collect_params(), 'sgd', dict(OPT_MOM))
    _imperative_train(n1, t1, batches[:2])
    fs = gluon.fuse_step(n1, _LOSS, t1)
    for x, y in batches[2:]:
        fs(x, y)
    _assert_close(truth, _pvals(n1), atol=1e-6)

    # fused first, then back to the per-key path
    n2 = _make_net(3)
    t2 = gluon.Trainer(n2.collect_params(), 'sgd', dict(OPT_MOM))
    fs2 = gluon.fuse_step(n2, _LOSS, t2)
    for x, y in batches[:2]:
        fs2(x, y)
    _imperative_train(n2, t2, batches[2:])
    _assert_close(truth, _pvals(n2), atol=1e-6)


def test_mode_switch_mp_keeps_masters_and_dtype():
    """Fused -> per-key switch with multi_precision: the adopted
    states must keep the fp32 masters as (momentum, master) pairs
    (review catch: Updater.set_states dropped them, silently promoting
    bf16 weights to float32 on the next per-key update)."""
    kw = {'learning_rate': 0.1, 'momentum': 0.9, 'multi_precision': True}
    batches = [(x.astype(jnp.bfloat16), y) for x, y in _batches(4)]
    truth_net = _make_net(5)
    truth_net.cast('bfloat16')
    _imperative_train(truth_net,
                      gluon.Trainer(truth_net.collect_params(), 'sgd',
                                    dict(kw)), batches)
    truth = _pvals(truth_net)

    net = _make_net(5)
    net.cast('bfloat16')
    tr = gluon.Trainer(net.collect_params(), 'sgd', dict(kw))
    fs = gluon.fuse_step(net, _LOSS, tr)
    for x, y in batches[:2]:
        fs(x, y)
    _imperative_train(net, tr, batches[2:])
    for _, p in sorted(net.collect_params().items()):
        assert p.data().dtype == jnp.bfloat16, p.name
    # momenta AND masters carried across the switch
    _assert_close(truth, _pvals(net), atol=2e-2, rtol=5e-2)


# ---------------------------------------------------------------------------
# un-fused Trainer.step: batched multi-device reduce
# ---------------------------------------------------------------------------

def test_trainer_step_batched_multi_device_reduce():
    batches = _batches()
    ctx2 = [mx.cpu(0), mx.cpu(1)]
    nm = _make_net(3, ctx=ctx2)
    tm = gluon.Trainer(nm.collect_params(), 'sgd', dict(OPT_MOM))
    ns = _make_net(3)
    ts = gluon.Trainer(ns.collect_params(), 'sgd', dict(OPT_MOM))
    for x, y in batches:
        xs = split_and_load(x.asnumpy(), ctx2)
        ys = split_and_load(y.asnumpy(), ctx2)
        with autograd.record():
            losses = [_LOSS(nm(xi), yi) for xi, yi in zip(xs, ys)]
        autograd.backward(losses)
        tm.step(BATCH)
        with autograd.record():
            l = _LOSS(ns(x), y)
        l.backward()
        ts.step(BATCH)
    _assert_close(_pvals(nm), _pvals(ns), atol=1e-6)
    # the summed gradient was broadcast back to every device copy
    p = nm[0].weight
    assert np.array_equal(p.data(ctx2[0]).asnumpy(),
                          p.data(ctx2[1]).asnumpy())
