"""Tests for the round-2 op-registry completions (VERDICT.md item 5):
optimizer update ops, slice-assign graph ops, LSoftmax / MultiLogistic /
WeightedL1 / Correlation1D, Convolution_v1 alias, and the legacy
_Native/_NDArray python-op bridges (reference python/mxnet/operator.py
NumpyOp/NDArrayOp)."""
import numpy as np
import pytest

import mxnet_tpu as mx
from mxnet_tpu import nd, sym


def test_sgd_update_ops():
    w = nd.array(np.ones((4, 3), np.float32))
    g = nd.array(np.full((4, 3), 2.0, np.float32))
    out = nd.sgd_update(w, g, lr=0.1, wd=0.0)
    np.testing.assert_allclose(out.asnumpy(), 1.0 - 0.1 * 2.0, rtol=1e-6)
    # reference SGDKernel: wd folds into (1-lr*wd)*weight
    out = nd.sgd_update(w, g, lr=0.1, wd=0.5, rescale_grad=0.5)
    np.testing.assert_allclose(
        out.asnumpy(), (1 - 0.1 * 0.5) * 1.0 - 0.1 * (0.5 * 2.0),
        rtol=1e-6)


def test_sgd_mom_update_mutates_state():
    w = nd.array(np.ones((5,), np.float32))
    g = nd.array(np.full((5,), 1.0, np.float32))
    mom = nd.zeros((5,))
    nd.sgd_mom_update(w, g, mom, out=w, lr=0.1, momentum=0.9)
    np.testing.assert_allclose(mom.asnumpy(), -0.1, rtol=1e-6)
    np.testing.assert_allclose(w.asnumpy(), 0.9, rtol=1e-6)
    nd.sgd_mom_update(w, g, mom, out=w, lr=0.1, momentum=0.9)
    np.testing.assert_allclose(mom.asnumpy(), 0.9 * -0.1 - 0.1, rtol=1e-6)


def test_mp_sgd_update():
    import jax.numpy as jnp
    w = nd.array(np.ones((4,), np.float32)).astype('float16')
    g = nd.array(np.full((4,), 1.0, np.float32)).astype('float16')
    w32 = nd.array(np.ones((4,), np.float32))
    out = nd.mp_sgd_update(w, g, w32, lr=0.25)
    np.testing.assert_allclose(w32.asnumpy(), 0.75, rtol=1e-6)
    assert out.dtype == np.float16


def test_adam_and_rmsprop_updates_descend():
    for op, states in [
            (lambda w, g, s: nd.adam_update(w, g, s[0], s[1], lr=0.1),
             lambda w: [nd.zeros(w.shape), nd.zeros(w.shape)]),
            (lambda w, g, s: nd.rmsprop_update(w, g, s[0], lr=0.05),
             lambda w: [nd.zeros(w.shape)]),
            (lambda w, g, s: nd.rmspropalex_update(
                w, g, s[0], s[1], s[2], lr=0.05),
             lambda w: [nd.zeros(w.shape), nd.zeros(w.shape),
                        nd.zeros(w.shape)])]:
        w = nd.array(np.array([4.0], np.float32))
        st = states(w)
        for _ in range(40):
            g = 2 * w
            w = op(w, g, st)
        assert abs(w.asscalar()) < 4.0


def test_slice_assign_ops():
    lhs = nd.zeros((4, 4))
    rhs = nd.array(np.ones((2, 2), np.float32))
    out = nd.invoke('_slice_assign', [lhs, rhs],
                    {'begin': (1, 1), 'end': (3, 3)})
    expect = np.zeros((4, 4), np.float32)
    expect[1:3, 1:3] = 1
    np.testing.assert_allclose(out.asnumpy(), expect)
    out2 = nd.invoke('_crop_assign_scalar', [lhs],
                     {'begin': (0, 0), 'end': (2, 2), 'scalar': 5.0})
    assert out2.asnumpy()[0, 0] == 5.0 and out2.asnumpy()[3, 3] == 0.0
    # symbolic form
    a = sym.Variable('a')
    b = sym.Variable('b')
    s = sym._slice_assign(a, b, begin=(1, 1), end=(3, 3))
    ex = s.simple_bind(mx.cpu(), grad_req='null', a=(4, 4), b=(2, 2))
    ex.forward(a=np.zeros((4, 4), np.float32),
               b=np.ones((2, 2), np.float32))
    np.testing.assert_allclose(ex.outputs[0].asnumpy(), expect)


def test_lsoftmax():
    rng = np.random.RandomState(0)
    x = rng.randn(6, 8).astype(np.float32)
    w = rng.randn(4, 8).astype(np.float32)
    lab = (rng.rand(6) * 4).astype(np.float32)
    data = sym.Variable('data')
    weight = sym.Variable('weight')
    label = sym.Variable('label')
    net = sym.LSoftmax(data, weight=weight, label=label, num_hidden=4,
                       margin=2, beta=1.0)
    ex = net.simple_bind(mx.cpu(), grad_req='write',
                         data=(6, 8), weight=(4, 8), label=(6,))
    # eval mode: plain inner product
    ex.forward(is_train=False, data=x, weight=w, label=lab)
    np.testing.assert_allclose(ex.outputs[0].asnumpy(), x @ w.T,
                               rtol=1e-5, atol=1e-5)
    np.testing.assert_allclose(ex.outputs[1].asnumpy(),
                               np.linalg.norm(x, axis=1), rtol=1e-5)
    # train mode: label column shrinks (margin penalty), others intact
    ex.forward(is_train=True, data=x, weight=w, label=lab)
    out = ex.outputs[0].asnumpy()
    ref = x @ w.T
    yi = lab.astype(int)
    rows = np.arange(6)
    mask = np.ones_like(ref, bool)
    mask[rows, yi] = False
    np.testing.assert_allclose(out[mask], ref[mask], rtol=1e-5, atol=1e-5)
    assert (out[rows, yi] <= ref[rows, yi] + 1e-5).all()
    ex.backward()
    assert np.isfinite(ex.grad_dict['data'].asnumpy()).all()
    assert np.isfinite(ex.grad_dict['weight'].asnumpy()).all()


def test_multi_logistic_and_weighted_l1():
    rng = np.random.RandomState(1)
    x = rng.randn(5, 3).astype(np.float32)
    lab = (rng.rand(5, 3) > 0.5).astype(np.float32)
    data = sym.Variable('data')
    label = sym.Variable('label')
    net = sym.MultiLogistic(data, label=label, grad_scale=2.0, weight=3.0)
    ex = net.simple_bind(mx.cpu(), grad_req='write', data=(5, 3),
                         label=(5, 3))
    ex.forward(is_train=True, data=x, label=lab)
    out = ex.outputs[0].asnumpy()
    np.testing.assert_allclose(out, 1 / (1 + np.exp(-x)), rtol=1e-5)
    ex.backward()
    d = out - lab
    expect = 2.0 * (d * lab * 3.0 + d * (1 - lab))
    np.testing.assert_allclose(ex.grad_dict['data'].asnumpy(), expect,
                               rtol=1e-5, atol=1e-6)

    net = sym.WeightedL1(data, label=label, grad_scale=0.5)
    ex = net.simple_bind(mx.cpu(), grad_req='write', data=(5, 3),
                         label=(5, 3))
    ex.forward(is_train=True, data=x, label=lab)
    np.testing.assert_allclose(ex.outputs[0].asnumpy(), x, rtol=1e-6)
    ex.backward()
    expect = 0.5 * np.sign(x - lab) * (lab > 0)
    np.testing.assert_allclose(ex.grad_dict['data'].asnumpy(), expect,
                               rtol=1e-5, atol=1e-6)


def test_correlation1d():
    rng = np.random.RandomState(2)
    a = rng.rand(2, 3, 5, 9).astype(np.float32)
    b = rng.rand(2, 3, 5, 9).astype(np.float32)
    out = nd.invoke('Correlation1D', [nd.array(a), nd.array(b)],
                    {'kernel_size': 1, 'max_displacement': 2,
                     'stride1': 1, 'stride2': 1, 'pad_size': 2,
                     'single_side': 0})
    n, c, h, w = out.shape
    assert c == 5  # 2*2+1 displacement channels
    # center channel (zero displacement) = mean over input channels of
    # a*b at the same position
    pa = np.pad(a, ((0, 0), (0, 0), (0, 0), (2, 2)))
    pb = np.pad(b, ((0, 0), (0, 0), (0, 0), (2, 2)))
    got = out.asnumpy()
    expect_c2 = (pa[:, :, :, 2:2 + w] * pb[:, :, :, 2:2 + w]).mean(1)
    np.testing.assert_allclose(got[:, 2], expect_c2, rtol=1e-5, atol=1e-6)


def test_convolution_v1_alias():
    # explicit name: the auto-name counter ('convolution0') is global
    # per-process state any earlier test may have advanced
    data = sym.Variable('data')
    c = sym.Convolution_v1(data, kernel=(3, 3), num_filter=2, pad=(1, 1),
                           name='convv1')
    ex = c.simple_bind(mx.cpu(), grad_req='null', data=(1, 1, 4, 4))
    ex.forward(is_train=False,
               data=np.ones((1, 1, 4, 4), np.float32),
               convv1_weight=np.ones((2, 1, 3, 3), np.float32),
               convv1_bias=np.zeros((2,), np.float32))
    assert ex.outputs[0].shape == (1, 2, 4, 4)


def test_legacy_numpy_op_bridge():
    class Square(mx.operator.NumpyOp):
        def forward(self, in_data, out_data):
            out_data[0][:] = in_data[0] ** 2

        def backward(self, out_grad, in_data, out_data, in_grad):
            in_grad[0][:] = 2 * in_data[0] * out_grad[0]

    op = Square(need_top_grad=True)
    x = sym.Variable('x')
    net = op.get_symbol(x, name='sq')
    ex = net.simple_bind(mx.cpu(), grad_req='write', x=(3,))
    xv = np.array([1.0, 2.0, 3.0], np.float32)
    ex.forward(is_train=True, x=xv)
    np.testing.assert_allclose(ex.outputs[0].asnumpy(), xv ** 2)
    ex.backward(out_grads=nd.array(np.ones(3, np.float32)))
    np.testing.assert_allclose(ex.grad_dict['x'].asnumpy(), 2 * xv)

    class Neg(mx.operator.NDArrayOp):
        def forward(self, in_data, out_data):
            out_data[0][:] = -np.asarray(in_data[0])

        def backward(self, out_grad, in_data, out_data, in_grad):
            in_grad[0][:] = -np.asarray(out_grad[0])

    net2 = Neg().get_symbol(sym.Variable('y'))
    ex2 = net2.simple_bind(mx.cpu(), grad_req='write', y=(2,))
    ex2.forward(is_train=True, y=np.array([1.0, -2.0], np.float32))
    np.testing.assert_allclose(ex2.outputs[0].asnumpy(), [-1.0, 2.0])


def test_registry_has_all_verdict_ops():
    from mxnet_tpu import ops
    for name in ['Correlation1D', 'LSoftmax', 'MultiLogistic',
                 'WeightedL1', 'Convolution_v1', '_slice_assign',
                 '_crop_assign', '_crop_assign_scalar', 'sgd_update',
                 'sgd_mom_update', 'mp_sgd_update', 'mp_sgd_mom_update',
                 'adam_update', 'rmsprop_update', 'rmspropalex_update',
                 '_Native', '_NDArray']:
        assert ops.exists(name), name
