"""Train-tier integration tests (model: reference tests/python/train/
test_mlp.py + test_conv.py — end-to-end fit() convergence to accuracy
thresholds — with synthetic data instead of MNIST downloads)."""
import os

import numpy as np
import pytest

import mxnet_tpu as mx
from mxnet_tpu import nd, sym


def _blobs(n=512, num_classes=4, dim=16, seed=0):
    """Linearly separable-ish gaussian blobs (class centers fixed
    across seeds so train/val share the task)."""
    centers = np.random.RandomState(42).randn(num_classes, dim) * 3.0
    rs = np.random.RandomState(seed)
    y = rs.randint(0, num_classes, n)
    X = centers[y] + rs.randn(n, dim)
    return X.astype(np.float32), y.astype(np.float32)


def _digits(n=512, seed=0):
    """Synthetic 'digit' images: class = quadrant of a bright square."""
    rs = np.random.RandomState(seed)
    X = rs.rand(n, 1, 16, 16).astype(np.float32) * 0.2
    y = rs.randint(0, 4, n)
    for i in range(n):
        r, c = divmod(int(y[i]), 2)
        X[i, 0, r * 8:r * 8 + 8, c * 8:c * 8 + 8] += 0.8
    return X, y.astype(np.float32)


def _mlp_sym(num_classes=4):
    data = sym.Variable('data')
    net = sym.FullyConnected(data, name='fc1', num_hidden=32)
    net = sym.Activation(net, act_type='relu')
    net = sym.FullyConnected(net, name='fc2', num_hidden=num_classes)
    return sym.SoftmaxOutput(net, name='softmax')


def _lenet_sym(num_classes=4):
    data = sym.Variable('data')
    net = sym.Convolution(data, kernel=(5, 5), num_filter=8, name='conv1')
    net = sym.Activation(net, act_type='tanh')
    net = sym.Pooling(net, pool_type='max', kernel=(2, 2), stride=(2, 2))
    net = sym.Convolution(net, kernel=(3, 3), num_filter=16, name='conv2')
    net = sym.Activation(net, act_type='tanh')
    net = sym.Pooling(net, pool_type='max', kernel=(2, 2), stride=(2, 2))
    net = sym.Flatten(net)
    net = sym.FullyConnected(net, num_hidden=num_classes, name='fc')
    return sym.SoftmaxOutput(net, name='softmax')


def test_mlp_fit_convergence(tmp_path):
    """Module.fit to >95% train acc with checkpoint + Speedometer
    callbacks (reference test_mlp.py)."""
    X, y = _blobs()
    Xv, yv = _blobs(128, seed=1)
    train = mx.io.NDArrayIter(X, y, batch_size=32, shuffle=True,
                              label_name='softmax_label')
    val = mx.io.NDArrayIter(Xv, yv, batch_size=32,
                            label_name='softmax_label')
    mod = mx.mod.Module(_mlp_sym())
    prefix = str(tmp_path / 'mlp')
    mod.fit(train, eval_data=val, num_epoch=8,
            optimizer_params={'learning_rate': 0.1},
            initializer=mx.init.Xavier(),
            batch_end_callback=mx.callback.Speedometer(32, 50),
            epoch_end_callback=mx.callback.do_checkpoint(prefix))
    score = mod.score(val, 'acc')
    assert score[0][1] > 0.95, score

    # checkpoint artifacts exist and resume restores accuracy
    assert os.path.exists(prefix + '-symbol.json')
    assert os.path.exists(prefix + '-0008.params')
    symbol, arg_params, aux_params = mx.model.load_checkpoint(prefix, 8)
    mod2 = mx.mod.Module(symbol)
    mod2.bind(data_shapes=val.provide_data,
              label_shapes=val.provide_label, for_training=False)
    mod2.set_params(arg_params, aux_params)
    score2 = mod2.score(val, 'acc')
    assert abs(score2[0][1] - score[0][1]) < 1e-6


def test_conv_fit_convergence():
    """LeNet-style convnet on synthetic quadrant digits
    (reference test_conv.py, MNIST swapped for synthetic)."""
    X, y = _digits()
    train = mx.io.NDArrayIter(X, y, batch_size=32, shuffle=True,
                              label_name='softmax_label')
    mod = mx.mod.Module(_lenet_sym())
    mod.fit(train, num_epoch=6,
            optimizer_params={'learning_rate': 0.1, 'momentum': 0.9},
            initializer=mx.init.Xavier())
    score = mod.score(train, 'acc')
    assert score[0][1] > 0.95, score


def test_feedforward_legacy_api(tmp_path):
    """The v0.8 FeedForward facade: create/fit/predict/score/save/load
    (reference model.py FeedForward; R/Perl frontends use this shape)."""
    X, y = _blobs(256)
    model = mx.model.FeedForward.create(
        _mlp_sym(), X, y, num_epoch=6, learning_rate=0.1,
        initializer=mx.init.Xavier(), numpy_batch_size=32)
    preds = model.predict(X)
    assert preds.shape == (256, 4)
    acc = (preds.argmax(1) == y).mean()
    assert acc > 0.9, acc
    assert model.score(mx.io.NDArrayIter(
        X, y, batch_size=32, label_name='softmax_label')) > 0.9

    prefix = str(tmp_path / 'ff')
    model.save(prefix, 6)
    loaded = mx.model.FeedForward.load(prefix, 6)
    preds2 = loaded.predict(X)
    np.testing.assert_allclose(preds2, preds, rtol=1e-5, atol=1e-6)


def test_predictor_deploy(tmp_path):
    """Deployment predictor over checkpoint artifacts
    (reference c_predict_api flow)."""
    X, y = _blobs(128)
    train = mx.io.NDArrayIter(X, y, batch_size=32,
                              label_name='softmax_label')
    mod = mx.mod.Module(_mlp_sym())
    mod.fit(train, num_epoch=4, optimizer_params={'learning_rate': 0.1},
            initializer=mx.init.Xavier())
    prefix = str(tmp_path / 'deploy')
    mod.save_checkpoint(prefix, 4)

    pred = mx.predictor.Predictor.from_checkpoint(
        prefix, 4, input_shapes={'data': (32, 16)})
    out = pred.predict(X[:32])
    assert out.shape == (32, 4)
    # matches the module's own outputs (same bound batch size)
    mod_out = mod.predict(mx.io.NDArrayIter(
        X[:32], y[:32], batch_size=32, label_name='softmax_label'))
    np.testing.assert_allclose(out, mod_out.asnumpy(), rtol=1e-5,
                               atol=1e-6)
    # reshape rebinds with shared weights
    pred.reshape({'data': (4, 16)})
    out2 = pred.predict(X[:4])
    np.testing.assert_allclose(out2, out[:4], rtol=1e-5, atol=1e-6)
    # AOT export produces a StableHLO module
    exported = pred.export_compiled()
    assert 'stablehlo' in exported and 'func' in exported['stablehlo']


def test_model_factory_new_symbols():
    from mxnet_tpu import models
    inc = models.get_symbol('inception-v3', num_classes=10)
    _, outs, _ = inc.infer_shape(data=(1, 3, 299, 299))
    assert outs == [(1, 10)]
    rx = models.get_symbol('resnext', num_classes=10, num_layers=50,
                           num_group=32)
    _, outs, _ = rx.infer_shape(data=(1, 3, 224, 224))
    assert outs == [(1, 10)]


def test_mixed_precision_training():
    """bfloat16 compute with fp32 master weights (reference
    tests/python/train/test_dtype.py + fp16 multi_precision SGD,
    NEWS.md:18): params downstream of the cast allocate in bf16, the
    fused SGD keeps fp32 masters, and training converges."""
    import jax.numpy as jnp
    X, y = _blobs(256)
    data = sym.Variable('data')
    net = sym.Cast(data, dtype='bfloat16')
    net = sym.FullyConnected(net, name='fc1', num_hidden=32)
    net = sym.Activation(net, act_type='relu')
    net = sym.FullyConnected(net, name='fc2', num_hidden=4)
    net = sym.Cast(net, dtype='float32')
    net = sym.SoftmaxOutput(net, name='softmax')

    train = mx.io.NDArrayIter(X, y, batch_size=32, shuffle=True,
                              label_name='softmax_label')
    mod = mx.mod.Module(net)
    mod.bind(data_shapes=train.provide_data,
             label_shapes=train.provide_label)
    mod.init_params(initializer=mx.init.Xavier())
    # weights allocated in the compute dtype via dtype inference
    w = mod._exec_group.executor.arg_dict['fc1_weight']
    assert w.dtype == jnp.bfloat16, w.dtype
    mod.init_optimizer(optimizer='sgd',
                       optimizer_params={'learning_rate': 0.1,
                                         'multi_precision': True})
    for _ in range(6):
        train.reset()
        for batch in train:
            mod.forward_backward(batch)
            mod.update()
    # fused updater holds fp32 masters for the bf16 params
    fu = mod._fused_updater
    assert fu is not None and fu.multi_precision
    assert any(m is not None and m.dtype == np.float32
               for m in fu.masters.values())
    score = mod.score(train, 'acc')
    assert score[0][1] > 0.9, score


def test_fused_sgd_state_roundtrip(tmp_path):
    """save_optimizer_states/load_optimizer_states through the fused
    updater, including fp32 masters (regression: restore used to
    KeyError on the first update)."""
    import jax.numpy as jnp
    X, y = _blobs(128)
    data = sym.Variable('data')
    net = sym.Cast(data, dtype='bfloat16')
    net = sym.FullyConnected(net, name='fc1', num_hidden=8)
    net = sym.Cast(net, dtype='float32')
    net = sym.SoftmaxOutput(net, name='softmax')
    train = mx.io.NDArrayIter(X, y, batch_size=32,
                              label_name='softmax_label')

    def make():
        m = mx.mod.Module(net)
        m.bind(data_shapes=train.provide_data,
               label_shapes=train.provide_label)
        m.init_params(initializer=mx.init.Xavier())
        m.init_optimizer(optimizer='sgd',
                         optimizer_params={'learning_rate': 0.1,
                                           'momentum': 0.9,
                                           'multi_precision': True})
        return m

    mod = make()
    batch = next(iter(train))
    for _ in range(3):
        mod.forward_backward(batch)
        mod.update()
    fname = str(tmp_path / 'opt.states')
    mod.save_optimizer_states(fname)

    mod2 = make()
    mod2.set_params(*mod.get_params())
    mod2.load_optimizer_states(fname)
    # the regression: first update after restore crashed
    mod2.forward_backward(batch)
    mod2.update()
    fu = mod2._fused_updater
    assert any(m is not None and m.dtype == np.float32
               for m in fu.masters.values())


def test_batchnorm_fp32_stats_in_bf16_graph():
    """BN scale/bias/moving stats stay float32 in a bfloat16 graph
    (reference cuDNN BN behavior for fp16)."""
    import jax.numpy as jnp
    data = sym.Variable('data')
    net = sym.Cast(data, dtype='bfloat16')
    net = sym.Convolution(net, kernel=(3, 3), num_filter=4, pad=(1, 1),
                          name='conv')
    net = sym.BatchNorm(net, fix_gamma=False, name='bn')
    net = sym.Cast(net, dtype='float32')
    net = sym.make_loss(sym.sum(net))
    ex = net.simple_bind(mx.cpu(), data=(2, 3, 8, 8))
    assert ex.arg_dict['conv_weight'].dtype == jnp.bfloat16
    assert ex.arg_dict['bn_gamma'].dtype == np.float32
    assert ex.aux_dict['bn_moving_mean'].dtype == np.float32
    ex.arg_dict['data'][:] = np.random.RandomState(0).rand(
        2, 3, 8, 8).astype(np.float32)
    ex.arg_dict['conv_weight'][:] = np.random.RandomState(1).rand(
        4, 3, 3, 3).astype(np.float32) * 0.1
    ex.forward(is_train=True)
    ex.backward()
    # stats updated in fp32
    assert ex.aux_dict['bn_moving_mean'].dtype == np.float32
    assert np.abs(ex.aux_dict['bn_moving_mean'].asnumpy()).sum() > 0


def test_optimizer_states_portable_between_update_paths(tmp_path):
    """Checkpoints written by the fused updater load through the per-key
    Updater path (kvstore='local') and vice versa; and the per-key SGD
    recognizes bfloat16 for multi_precision."""
    import pickle
    import jax.numpy as jnp
    from mxnet_tpu import optimizer as opt_mod

    # fused 3-tuple payload loads into a per-key Updater
    o = mx.optimizer.create('sgd', momentum=0.9)
    fu = opt_mod.FusedSGD(o, ['w0'])
    w = [mx.nd.array(np.ones(3, np.float32))]
    g = [mx.nd.array(np.ones(3, np.float32))]
    fu(w, g)
    blob = fu.get_states()
    upd = opt_mod.get_updater(mx.optimizer.create('sgd', momentum=0.9))
    upd.set_states(blob)     # regression: used to ValueError

    # bf16 weights get fp32 masters on the per-key path too
    o2 = mx.optimizer.create('sgd', momentum=0.9, multi_precision=True)
    wbf = mx.nd.array(np.ones(3, np.float32)).astype('bfloat16') if \
        hasattr(mx.nd.NDArray, 'astype') else None
    state = o2.create_state(0, wbf)
    assert isinstance(state, tuple)
    mom, master = state
    assert master.dtype == np.float32


def _cifar_like(n, seed):
    """A CIFAR-class stand-in this rig can generate offline: 6 classes
    of 3x28x28 color images where the class is a (shape, hue) pair —
    textured backgrounds, per-image jitter, enough structure that a
    plain linear model fails but a small resnet separates it."""
    rs = np.random.RandomState(seed)
    X = rs.rand(n, 3, 28, 28).astype(np.float32) * 0.3
    y = rs.randint(0, 6, n)
    yy, xx = np.mgrid[0:28, 0:28]
    for i in range(n):
        shape = int(y[i]) % 2           # 0: disk, 1: square
        hue = int(y[i]) // 2            # dominant channel 0/1/2
        cy, cx = rs.randint(10, 18, 2)
        r = rs.randint(6, 9)
        if shape == 0:
            m = (yy - cy) ** 2 + (xx - cx) ** 2 <= r * r
        else:
            m = (np.abs(yy - cy) <= r) & (np.abs(xx - cx) <= r)
        X[i, hue][m] += 0.8 + 0.2 * rs.rand()
        X[i, (hue + 1) % 3][m] += 0.2 * rs.rand()
    return X.astype(np.float32), y.astype(np.float32)


@pytest.mark.slow
def test_resnet_convergence_parity_fp32_vs_bf16():
    """The convergence-parity proxy for the BASELINE 'identical top-1'
    gate this rig cannot run (no ImageNet, one chip) — round-5 VERDICT
    item: the SAME small resnet on the same CIFAR-class data must reach
    a pinned accuracy under fp32 AND bfloat16 multi_precision, within
    tolerance of each other (reference role:
    tests/python/train/test_dtype.py + image-classification
    test_score.py).  Numbers recorded in docs/PERF.md round 5."""
    from mxnet_tpu.models import resnet

    Xtr, ytr = _cifar_like(1536, seed=0)
    Xte, yte = _cifar_like(384, seed=1)
    accs = {}
    for dtype in ('float32', 'bfloat16'):
        mx.random.seed(5)
        np.random.seed(5)
        net = resnet.get_symbol(num_classes=6, num_layers=8,
                                image_shape='3,28,28', dtype=dtype)
        mod = mx.mod.Module(net, label_names=['softmax_label'])
        train = mx.io.NDArrayIter(Xtr, ytr, 64, shuffle=True,
                                  label_name='softmax_label')
        test = mx.io.NDArrayIter(Xte, yte, 64,
                                 label_name='softmax_label')
        # the reference's own recipe shape: lr steps late in training
        # (--lr-step-epochs).  Without the decay this tiny-data recipe
        # sits at the edge of stability and bf16 rounding amplifies
        # batch-stat variance until eval-mode BN moving stats lag the
        # live activations (train-mode accuracy stays ~1.0 in both
        # dtypes; fp32 shows the same gap smaller) — docs/PERF.md
        sched = mx.lr_scheduler.MultiFactorScheduler(
            step=[24 * 8, 24 * 12], factor=0.1)
        mod.fit(train, num_epoch=16,
                optimizer='sgd',
                optimizer_params={'learning_rate': 0.05, 'momentum': 0.9,
                                  'wd': 1e-4, 'lr_scheduler': sched,
                                  'multi_precision': dtype != 'float32'},
                initializer=mx.init.Xavier(rnd_type='gaussian',
                                           factor_type='in',
                                           magnitude=2))
        accs[dtype] = float(mod.score(test, mx.metric.Accuracy())[0][1])
    print('convergence parity: fp32 %.3f bf16 %.3f' %
          (accs['float32'], accs['bfloat16']))
    assert accs['float32'] > 0.95, accs
    assert accs['bfloat16'] > 0.95, accs
    assert abs(accs['float32'] - accs['bfloat16']) < 0.03, accs


@pytest.mark.parametrize('name,shape,kw', [
    # tier-1 keeps one BN-heavy and one plain-conv representative;
    # the rest of the zoo sweep (~18s of full-model XLA compiles that
    # exercise the same dtype plumbing) runs in full CI
    pytest.param('alexnet', (2, 3, 224, 224), {}, marks=pytest.mark.slow),
    pytest.param('vgg', (2, 3, 224, 224), {'num_layers': 11},
                 marks=pytest.mark.slow),
    ('inception-bn', (2, 3, 128, 128), {}),
    pytest.param('inception-v3', (2, 3, 299, 299), {},
                 marks=pytest.mark.slow),
    pytest.param('resnext', (2, 3, 64, 64), {'num_layers': 50},
                 marks=pytest.mark.slow),
    ('resnet', (2, 3, 64, 64), {'num_layers': 18}),
])
def test_model_zoo_mixed_precision_binds(name, shape, kw):
    """Every imagenet zoo model accepts the dtype knob train_imagenet
    forwards (round 5: models swallowing it via **kwargs silently
    computed fp32 under a bf16 label — a 1.77x perf mislabel for
    inception-bn): params allocate in the compute dtype, BN
    scale/shift stays fp32, outputs come back fp32."""
    import jax.numpy as jnp
    from mxnet_tpu import models
    s = models.get_symbol(name, num_classes=4, dtype='bfloat16', **kw)
    ex = s.simple_bind(mx.cpu(), data=shape, softmax_label=(2,),
                       grad_req='null')
    n_bf16 = sum(1 for a in ex.arg_dict.values()
                 if a.dtype == jnp.bfloat16)
    assert n_bf16 > 0, name
    ex.forward(is_train=False,
               data=np.zeros(shape, np.float32),
               softmax_label=np.zeros((2,), np.float32))
    assert ex.outputs[0].dtype == np.float32, name
