"""Elastic training runtime tests (mxnet_tpu/elastic.py): async
sharded checkpoints, preemption-safe resume, kill-resume bit-parity
(plain / ZeRO-1 / bucket-ladder), torn-checkpoint fallback, fault
injection, and the atomic-write / load-validation satellites.

The kill-resume contract under test: a run SIGKILLed mid-epoch and
resumed from its newest intact checkpoint finishes with weights,
optimizer state, and metric BIT-IDENTICAL to the uninterrupted run.
"""
import json
import os
import pickle
import signal
import subprocess
import sys

import numpy as np
import pytest

import mxnet_tpu as mx
from mxnet_tpu import elastic, profiler
from mxnet_tpu import sym as S
from mxnet_tpu.base import MXNetError

_REPO = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))


# ---------------------------------------------------------------------------
# tiny deterministic training fixtures
# ---------------------------------------------------------------------------

def _mlp_symbol():
    data = S.Variable('data')
    fc1 = S.FullyConnected(data, name='fc1', num_hidden=16)
    act = S.Activation(fc1, act_type='relu')
    fc2 = S.FullyConnected(act, name='fc2', num_hidden=4)
    return S.SoftmaxOutput(fc2, name='softmax')


def _make_module(seed=5, ndev=1, zero=None, momentum=0.9, bsz=8):
    ctxs = [mx.Context('cpu', i) for i in range(ndev)] if ndev > 1 \
        else None
    mod = mx.mod.Module(_mlp_symbol(), context=ctxs)
    mod.bind(data_shapes=[mx.io.DataDesc('data', (bsz, 6))],
             label_shapes=[mx.io.DataDesc('softmax_label', (bsz,))])
    mx.random.seed(seed)
    mod.init_params(initializer=mx.init.Xavier())
    mod.init_optimizer(optimizer='sgd',
                       optimizer_params={'learning_rate': 0.1,
                                         'momentum': momentum},
                       zero=zero)
    return mod


def _batches(n, bsz=8, width=6, seed=0):
    rng = np.random.RandomState(seed)
    return [mx.io.DataBatch(
        data=[mx.nd.array(rng.rand(bsz, width).astype(np.float32))],
        label=[mx.nd.array((rng.rand(bsz) * 4).astype(np.float32))])
        for _ in range(n)]


def _train(mod, batches):
    for b in batches:
        mod.forward_backward(b)
        mod.update()


def _assert_params_equal(mod_a, mod_b):
    pa, aa = mod_a.get_params()
    pb, ab = mod_b.get_params()
    for n in pa:
        np.testing.assert_array_equal(pa[n].asnumpy(), pb[n].asnumpy(),
                                      err_msg=n)
    for n in aa:
        np.testing.assert_array_equal(aa[n].asnumpy(), ab[n].asnumpy(),
                                      err_msg=n)


def _opt_states(mod):
    states, counts, masters = pickle.loads(
        mod._fused_updater.get_states())
    return ({n: np.asarray(v) for n, v in states.items()}, counts)


# ---------------------------------------------------------------------------
# shard-file container + satellites
# ---------------------------------------------------------------------------

def test_shard_file_roundtrip_and_torn(tmp_path):
    import jax.numpy as jnp
    path = str(tmp_path / 's.bin')
    entries = [('a', np.arange(12, dtype=np.float32).reshape(3, 4)),
               ('b:0:4', np.array([1, 2, 3], np.int64)),
               # bfloat16 (ml_dtypes) rejects memoryview — the writer
               # must reinterpret its buffer, and the reader must get
               # the dtype back (mixed-precision masters checkpoint)
               ('bf', np.asarray(jnp.arange(6, dtype=jnp.bfloat16)))]
    nbytes, crc = elastic.write_shard_file(path, entries)
    assert nbytes == os.path.getsize(path) and crc
    out = elastic.read_shard_file(path)
    np.testing.assert_array_equal(out['a'], entries[0][1])
    np.testing.assert_array_equal(out['b:0:4'], entries[1][1])
    assert out['bf'].dtype == jnp.bfloat16
    np.testing.assert_array_equal(
        out['bf'].astype(np.float32), entries[2][1].astype(np.float32))
    # truncation (torn write on a non-atomic store)
    blob = open(path, 'rb').read()
    with open(path, 'wb') as f:
        f.write(blob[:len(blob) // 2])
    with pytest.raises(MXNetError, match='torn'):
        elastic.read_shard_file(path)
    # single flipped payload bit fails the checksum
    flipped = bytearray(blob)
    flipped[len(elastic._CKPT_MAGIC) + 30] ^= 0x40
    with open(path, 'wb') as f:
        f.write(bytes(flipped))
    with pytest.raises(MXNetError, match='checksum'):
        elastic.read_shard_file(path)


def test_nd_save_is_atomic_and_load_validates(tmp_path):
    fname = str(tmp_path / 'p.params')
    good = {'arg:w': mx.nd.array(np.arange(6).reshape(2, 3)
                                 .astype(np.float32))}
    mx.nd.save(fname, good)
    # a failing later save must leave the original intact (temp +
    # os.replace — the old in-place writer left a torn file)
    with pytest.raises(TypeError):
        mx.nd.save(fname, {'arg:w': good['arg:w'],
                           'arg:bad': 'not an ndarray'})
    out = mx.nd.load(fname)
    np.testing.assert_array_equal(out['arg:w'].asnumpy(),
                                  good['arg:w'].asnumpy())
    assert not [n for n in os.listdir(str(tmp_path))
                if '.tmp' in n], 'temp files must not leak'
    # truncated blob -> clear MXNetError naming the file (was an
    # opaque struct.error deep in the decoder)
    blob = open(fname, 'rb').read()
    for cut in (4, len(blob) - 3):
        with open(fname, 'wb') as f:
            f.write(blob[:cut])
        with pytest.raises(MXNetError, match='p.params'):
            mx.nd.load(fname)
    # bad magic
    with open(fname, 'wb') as f:
        f.write(b'NOTAPARAMSFILE' + blob)
    with pytest.raises(MXNetError, match='magic'):
        mx.nd.load(fname)
    # implausible entry count
    with open(fname, 'wb') as f:
        f.write(blob[:8] + b'\xff' * 8 + blob[16:])
    with pytest.raises(MXNetError):
        mx.nd.load(fname)


def test_model_checkpoint_atomic_and_validated(tmp_path):
    from mxnet_tpu import model as model_mod
    mod = _make_module()
    prefix = str(tmp_path / 'ck')
    mod.save_checkpoint(prefix, 1, save_optimizer_states=True)
    sym2, args, auxs = model_mod.load_checkpoint(prefix, 1)
    assert set(args) == {'fc1_weight', 'fc1_bias', 'fc2_weight',
                         'fc2_bias'}
    # corrupt the params blob: load_checkpoint raises a clear error
    pfile = '%s-0001.params' % prefix
    blob = open(pfile, 'rb').read()
    with open(pfile, 'wb') as f:
        f.write(blob[:len(blob) - 9])
    with pytest.raises(MXNetError, match='ck-0001.params'):
        model_mod.load_checkpoint(prefix, 1)
    assert not [n for n in os.listdir(str(tmp_path)) if '.tmp' in n]


# ---------------------------------------------------------------------------
# kill-resume parity (in-process crash simulation = fresh objects)
# ---------------------------------------------------------------------------

def test_module_kill_resume_parity(tmp_path):
    batches = _batches(10)
    straight = _make_module()
    _train(straight, batches)

    victim = _make_module()
    _train(victim, batches[:5])
    mgr = elastic.CheckpointManager(str(tmp_path), async_=False)
    mgr.attach(victim)
    mgr._step = 5
    mgr.save(epoch=0, batches_in_epoch=5, batch_size=8, sync=True)

    resumed = _make_module(seed=11)   # different init: must be overwritten
    info = elastic.resume(elastic.CheckpointManager(str(tmp_path)),
                          resumed)
    assert info is not None and info.step == 5
    assert info.samples_consumed == 40
    _train(resumed, batches[5:])
    _assert_params_equal(straight, resumed)
    sa, ca = _opt_states(straight)
    sb, cb = _opt_states(resumed)
    assert ca == cb
    for n in sa:
        np.testing.assert_array_equal(sa[n], sb[n], err_msg=n)


def test_save_before_first_step_restores(tmp_path):
    mod = _make_module()
    mgr = elastic.CheckpointManager(str(tmp_path), async_=False)
    mgr.attach(mod)
    mgr.save(sync=True)
    other = _make_module(seed=9)
    assert elastic.resume(elastic.CheckpointManager(str(tmp_path)),
                          other) is not None
    _assert_params_equal(mod, other)
    batches = _batches(3)
    _train(mod, batches)
    _train(other, batches)
    _assert_params_equal(mod, other)


def test_zero_sharded_kill_resume_and_resharding(tmp_path):
    ndev, bsz = 4, 8
    batches = _batches(8, bsz=bsz)
    straight = _make_module(ndev=ndev, zero=1)
    assert straight._fused_updater.zero == 1
    _train(straight, batches)

    victim = _make_module(ndev=ndev, zero=1)
    _train(victim, batches[:4])
    # virtual world=2: the dp-sharded momentum buckets split across two
    # per-rank shard files (the LOCAL-shard-only save path)
    mgr = elastic.CheckpointManager(str(tmp_path), async_=False,
                                    world=2)
    mgr.attach(victim)
    mgr._step = 4
    d = mgr.save(sync=True)
    assert sorted(os.listdir(d)) == ['manifest.json',
                                    'state-r00000.bin',
                                    'state-r00001.bin']
    man = json.load(open(os.path.join(d, 'manifest.json')))
    assert man['opt']['mode'] == 'zero' and man['opt']['zero_buckets']

    # same-width ZeRO resume: bit-exact continuation
    resumed_mod = _make_module(seed=11, ndev=ndev, zero=1)
    assert elastic.resume(elastic.CheckpointManager(str(tmp_path)),
                          resumed_mod) is not None
    _train(resumed_mod, batches[4:])
    _assert_params_equal(straight, resumed_mod)

    # mode portability: the same shard files restore into zero=0
    repl = _make_module(seed=12, ndev=ndev, zero=0)
    assert elastic.resume(elastic.CheckpointManager(str(tmp_path)),
                          repl) is not None
    _train(repl, batches[4:])
    pa, _ = straight.get_params()
    pb, _ = repl.get_params()
    for n in pa:
        np.testing.assert_allclose(pa[n].asnumpy(), pb[n].asnumpy(),
                                   rtol=2e-6, atol=1e-7, err_msg=n)

    # dp re-sharding: dp=4 buckets reassemble and re-bucket at dp=2,
    # momenta surviving bit-exactly through the flat-bucket round trip
    narrow = _make_module(seed=13, ndev=2, zero=1)
    assert elastic.resume(elastic.CheckpointManager(str(tmp_path)),
                          narrow) is not None
    sv, _ = _opt_states(victim)
    sn, _ = _opt_states(narrow)
    for n in sv:
        np.testing.assert_array_equal(sv[n], sn[n], err_msg=n)


def _bucket_sym_gen(nrows):
    data = S.Variable('data')
    fc1 = S.FullyConnected(data, name='fc1', num_hidden=16)
    act = S.Activation(fc1, act_type='relu')
    fc2 = S.FullyConnected(act, name='fc2', num_hidden=4)
    net = S.SoftmaxOutput(fc2, name='softmax', use_ignore=True,
                          ignore_label=-1)
    return net, ['data'], ['softmax_label']


def _make_bucket_module(seed=5):
    mod = mx.mod.BucketingModule(_bucket_sym_gen, default_bucket_key=8,
                                 bucket_ladder=[4, 8], mask_label=-1)
    mod.bind(data_shapes=[mx.io.DataDesc('data', (8, 6))],
             label_shapes=[mx.io.DataDesc('softmax_label', (8,))])
    mx.random.seed(seed)
    mod.init_params(initializer=mx.init.Xavier())
    mod.init_optimizer(optimizer='sgd',
                       optimizer_params={'learning_rate': 0.1,
                                         'momentum': 0.9})
    return mod


def _bucket_batches(n, seed=0):
    rng = np.random.RandomState(seed)
    out = []
    for i in range(n):
        w = 4 if i % 2 else 8   # rows -> bucket key (ladder rungs 4/8)
        out.append(mx.io.DataBatch(
            data=[mx.nd.array(rng.rand(w, 6).astype(np.float32))],
            label=[mx.nd.array((rng.rand(w) * 4).astype(np.float32))],
            bucket_key=w,
            provide_data=[mx.io.DataDesc('data', (w, 6))],
            provide_label=[mx.io.DataDesc('softmax_label', (w,))]))
    return out


def test_bucket_ladder_kill_resume_parity(tmp_path):
    batches = _bucket_batches(8)
    straight = _make_bucket_module()
    _train(straight, batches)

    victim = _make_bucket_module()
    _train(victim, batches[:4])
    mgr = elastic.CheckpointManager(str(tmp_path), async_=False)
    mgr.attach(victim)
    mgr._step = 4
    d = mgr.save(sync=True)
    man = json.load(open(os.path.join(d, 'manifest.json')))
    assert man['rung'] == 4       # ladder rung at the snapshot

    resumed = _make_bucket_module(seed=11)
    info = elastic.resume(elastic.CheckpointManager(str(tmp_path)),
                          resumed)
    assert info is not None and info.rung == 4
    _train(resumed, batches[4:])
    _assert_params_equal(straight, resumed)


# ---------------------------------------------------------------------------
# fit() wiring: auto-resume, watermark fast-forward, metric continuity
# ---------------------------------------------------------------------------

def _fit_iter():
    rng = np.random.RandomState(3)
    return mx.io.NDArrayIter(rng.rand(48, 6).astype(np.float32),
                             (rng.rand(48) * 4).astype(np.float32),
                             batch_size=8)


def _fit(mod, ckpt=None, cb=None, log=None):
    def tail_cb(param):
        if cb is not None:
            cb(param)
        if log is not None:
            log[(param.epoch, param.nbatch)] = \
                param.eval_metric.get_name_value()[0][1]
    mx.random.seed(7)
    mod.fit(_fit_iter(), eval_metric='acc', optimizer='sgd',
            optimizer_params={'learning_rate': 0.1, 'momentum': 0.9},
            initializer=mx.init.Xavier(), num_epoch=2,
            checkpoint=ckpt, batch_end_callback=tail_cb)


def test_fit_preempt_resume_bit_parity(tmp_path):
    log_a = {}
    straight = mx.mod.Module(_mlp_symbol())
    _fit(straight, log=log_a)

    mgr = elastic.CheckpointManager(str(tmp_path), every_n_steps=4)
    victim = mx.mod.Module(_mlp_symbol())
    fired = []

    def preempt_cb(param):
        if param.epoch == 1 and param.nbatch == 2 and not fired:
            fired.append(1)
            mgr.request_preempt()   # what the SIGTERM handler does

    with pytest.raises(elastic.Preempted):
        _fit(victim, ckpt=mgr, cb=preempt_cb)
    mgr.close()
    assert elastic.list_checkpoints(str(tmp_path))

    log_c = {}
    resumed = mx.mod.Module(_mlp_symbol())
    mgr2 = elastic.CheckpointManager(str(tmp_path))
    _fit(resumed, ckpt=mgr2, log=log_c)
    info = mgr2.last_resume
    assert info is not None and info.epoch == 1
    assert info.batches_in_epoch == 3    # mid-epoch watermark
    _assert_params_equal(straight, resumed)
    # metric continuity: the resumed epoch's running train metric
    # matches the uninterrupted run at every post-resume batch —
    # the restored partial-epoch (sum, count) carried forward
    resumed_points = [k for k in log_c if k[0] == 1]
    assert resumed_points
    for k in resumed_points:
        assert log_a[k] == log_c[k], k
    mgr2.close()


def test_checkpoint_will_act_predicts_cadence(tmp_path):
    # will_act(k) is the overlapped-fit drain predicate: it must say
    # True exactly when the NEXT step_end would take a cadence
    # checkpoint or commit a pending preemption — never on the
    # common no-op steps that keep the async pipeline unbroken
    mgr = elastic.CheckpointManager(str(tmp_path), every_n_steps=4,
                                    async_=False)
    assert not mgr.will_act(1)          # step 0 -> 1: not due
    mgr._step = 3
    assert mgr.will_act(1)              # 3 -> 4: cadence fires
    mgr._step = 0
    mgr.request_preempt()
    assert mgr.will_act(1)              # pending preempt always acts
    mgr.close()


def test_fit_deferred_metric_pipeline_parity(tmp_path, monkeypatch):
    # overlapped fit (MXNET_TPU_TRAIN_STEP_AHEAD): metric folds and
    # batch_end_callbacks defer up to `ahead` batches behind the
    # dispatches.  Depth changes only WHEN the host folds, never what
    # is folded — the per-batch metric log and final params must
    # match the serialized run exactly, including across a blocking
    # checkpoint cadence where will_act() drains the pipeline to a
    # consistent step boundary first
    log_a, log_b = {}, {}
    monkeypatch.setenv('MXNET_TPU_TRAIN_STEP_AHEAD', '0')
    a = mx.mod.Module(_mlp_symbol())
    mgr_a = elastic.CheckpointManager(str(tmp_path / 'a'),
                                      every_n_steps=4, async_=False)
    _fit(a, ckpt=mgr_a, log=log_a)
    mgr_a.close()
    monkeypatch.setenv('MXNET_TPU_TRAIN_STEP_AHEAD', '2')
    profiler.clear()
    b = mx.mod.Module(_mlp_symbol())
    mgr_b = elastic.CheckpointManager(str(tmp_path / 'b'),
                                      every_n_steps=4, async_=False)
    _fit(b, ckpt=mgr_b, log=log_b)
    mgr_b.close()
    assert log_a == log_b
    _assert_params_equal(a, b)
    ov = profiler.overlap_stats()
    assert ov['overlap_train_steps'] >= 1
    assert ov['overlap_deferred_metric_folds'] >= 1
    # both cadences actually checkpointed through the drain
    assert elastic.list_checkpoints(str(tmp_path / 'a'))
    assert elastic.list_checkpoints(str(tmp_path / 'b'))
    profiler.clear()


def test_preempt_during_validation_not_swallowed(tmp_path):
    """A signal landing AFTER the epoch's last step (during
    validation) must still commit a final checkpoint and raise — not
    be silently absorbed by fit's handler teardown."""
    mgr = elastic.CheckpointManager(str(tmp_path), every_n_steps=1000)
    mod = mx.mod.Module(_mlp_symbol())
    mx.random.seed(7)
    with pytest.raises(elastic.Preempted):
        mod.fit(_fit_iter(), eval_data=_fit_iter(), eval_metric='acc',
                optimizer='sgd',
                optimizer_params={'learning_rate': 0.1},
                initializer=mx.init.Xavier(), num_epoch=2,
                checkpoint=mgr,
                eval_batch_end_callback=lambda p: mgr.request_preempt())
    res = elastic.load_newest_intact(str(tmp_path))
    assert res is not None
    # the boundary checkpoint marks the START of the next epoch
    assert res[0]['epoch'] == 1 and res[0]['batches_in_epoch'] == 0
    mgr.close()


def test_sigterm_commits_final_checkpoint(tmp_path):
    mgr = elastic.CheckpointManager(str(tmp_path), every_n_steps=1000)
    mod = _make_module()
    mgr.attach(mod).install_signal_handlers()
    try:
        batches = _batches(4)
        _train(mod, batches[:2])
        mgr.step_end(epoch=0, batches_in_epoch=1, batch_size=8)
        os.kill(os.getpid(), signal.SIGTERM)   # delivered to main thread
        _train(mod, batches[2:3])              # drain: one more dispatch
        with pytest.raises(elastic.Preempted):
            mgr.step_end(epoch=0, batches_in_epoch=2, batch_size=8)
    finally:
        mgr.close()
    res = elastic.load_newest_intact(str(tmp_path))
    assert res is not None
    manifest, arrays, _ = res
    assert manifest['step'] == 2
    pm, _ = mod.get_params()
    np.testing.assert_array_equal(
        np.asarray(arrays['param:fc1_weight']),
        pm['fc1_weight'].asnumpy())


@pytest.mark.slow
def test_fit_sigkill_subprocess_resume(tmp_path):
    """slow (~20s, round-16 headroom): the subprocess SIGKILL E2E also
    runs in dryrun phase (h); kill/resume bit-parity and the final
    commit stay tier-1 via test_module_kill_resume_parity,
    test_fit_preempt_resume_bit_parity and
    test_sigterm_commits_final_checkpoint.

    The real preemption path: a fit() child is SIGKILLed mid-epoch
    by MXNET_TPU_FAULT_KILL_AT_STEP (no warning, no cleanup), a second
    child resumes from the cadence checkpoint, and the final weights
    match an uninterrupted child bit-exactly."""
    def run(tag, kill_at=None):
        env = dict(os.environ, JAX_PLATFORMS='cpu',
                   PYTHONPATH=_REPO + os.pathsep +
                   os.environ.get('PYTHONPATH', ''))
        env.pop('MXNET_TPU_FAULT_KILL_AT_STEP', None)
        if kill_at is not None:
            env['MXNET_TPU_FAULT_KILL_AT_STEP'] = str(kill_at)
        out = str(tmp_path / ('%s.npz' % tag))
        ck = str(tmp_path / ('ck_%s' % ('straight' if tag == 'straight'
                                        else 'elastic')))
        proc = subprocess.run(
            [sys.executable, os.path.abspath(__file__), 'fit-worker',
             ck, out], env=env, capture_output=True, text=True,
            timeout=300)
        return proc, out

    proc, out_a = run('straight')
    assert proc.returncode == 0, (proc.stdout, proc.stderr)
    proc, _ = run('killed', kill_at=7)
    assert proc.returncode == -signal.SIGKILL
    assert elastic.list_checkpoints(str(tmp_path / 'ck_elastic')), \
        'cadence checkpoint must exist before the kill'
    proc, out_b = run('resumed')
    assert proc.returncode == 0, (proc.stdout, proc.stderr)
    # the SIGKILL may land while step-6's async write is mid-flight:
    # resume comes from 6 when its manifest committed, else falls
    # back to the step-4 checkpoint — parity holds either way
    assert 'RESUMED step=' in proc.stdout, proc.stdout
    a = np.load(out_a)
    b = np.load(out_b)
    assert sorted(a.files) == sorted(b.files)
    for n in a.files:
        np.testing.assert_array_equal(a[n], b[n], err_msg=n)


# ---------------------------------------------------------------------------
# async overlap, fault injection, retention
# ---------------------------------------------------------------------------

def test_async_save_overlaps_training(tmp_path, monkeypatch):
    import time as _time
    profiler.clear()
    mod = _make_module()
    batches = _batches(3)
    _train(mod, batches[:1])
    mgr = elastic.CheckpointManager(str(tmp_path), async_=True)
    mgr.attach(mod)
    mgr._step = 0
    mgr.save(sync=True)   # warm the per-shape device-copy programs
    monkeypatch.setenv('MXNET_TPU_FAULT_WRITE_DELAY_MS', '120')
    mgr._step = 1
    t0 = _time.perf_counter()
    d = mgr.save()
    enqueue_ms = (_time.perf_counter() - t0) * 1e3
    assert d is not None
    assert enqueue_ms < 100, \
        'async save blocked the train thread %.1fms' % enqueue_ms
    _train(mod, batches[1:2])     # training overlaps the write
    # a cadence save while the write is in flight is SKIPPED, not a
    # stall
    assert mgr.save() is None
    assert mgr.wait(10)
    st = profiler.ckpt_stats()
    assert st['ckpt_snapshots'] == 2   # warm + timed
    assert st['ckpt_skipped'] == 1
    assert st['ckpt_async_overlap_ms'] > 0
    # the committed checkpoint holds the PRE-overlap-step weights
    # (snapshot semantics: state at save() time, not at commit time)
    res = elastic.load_newest_intact(str(tmp_path))
    assert res is not None and res[0]['step'] == 1
    mgr.close()


def test_write_failure_keeps_training_alive(tmp_path, monkeypatch):
    profiler.clear()
    mod = _make_module()
    mgr = elastic.CheckpointManager(str(tmp_path), async_=True)
    mgr.attach(mod)
    monkeypatch.setenv('MXNET_TPU_FAULT_WRITE_FAIL', '1')
    mgr._step = 1
    mgr.save()
    assert mgr.wait(10)
    monkeypatch.delenv('MXNET_TPU_FAULT_WRITE_FAIL')
    assert profiler.ckpt_stats()['ckpt_failed_writes'] == 1
    # training continues; the next checkpoint lands fine
    _train(mod, _batches(1))
    mgr._step = 2
    mgr.save(sync=True)
    assert elastic.load_newest_intact(str(tmp_path))[0]['step'] == 2
    mgr.close()


def test_torn_checkpoint_falls_back_and_retention(tmp_path,
                                                  monkeypatch):
    profiler.clear()
    mod = _make_module()
    mgr = elastic.CheckpointManager(str(tmp_path), async_=False, keep=2)
    mgr.attach(mod)
    for s in (1, 2):
        mgr._step = s
        mgr.save(sync=True)
    monkeypatch.setenv('MXNET_TPU_FAULT_TORN_CKPT', '1')
    mgr._step = 3
    mgr.save(sync=True)
    monkeypatch.delenv('MXNET_TPU_FAULT_TORN_CKPT')
    # keep=2 retention pruned step-1; newest (3) is torn -> fall back
    # to 2
    assert elastic.list_checkpoints(str(tmp_path)) == [3, 2]
    res = elastic.load_newest_intact(str(tmp_path))
    assert res is not None and res[0]['step'] == 2
    assert profiler.ckpt_stats()['ckpt_torn_fallbacks'] >= 1
    # restore() (not just load) also lands on the intact one
    other = _make_module(seed=9)
    info = elastic.resume(elastic.CheckpointManager(str(tmp_path)),
                          other)
    assert info is not None and info.step == 2
    # a SIGKILL mid-write leaves a manifest-less orphan dir: retention
    # reaps it (it can never become valid) once it is older than the
    # newest real checkpoint
    orphan = tmp_path / 'step-00000001'
    orphan.mkdir()
    (orphan / 'state-r00000.bin.tmpdead').write_bytes(b'partial')
    mgr._step = 4
    mgr.save(sync=True)
    assert not orphan.exists()
    assert elastic.load_newest_intact(str(tmp_path))[0]['step'] == 4


def test_dead_virtual_host_and_kvstore_facade(tmp_path, monkeypatch):
    # a ZeRO run's shards are UNIQUE state: withholding a dead host's
    # file makes that checkpoint incomplete and resume falls back
    mod = _make_module(ndev=4, zero=1)
    _train(mod, _batches(1))
    mgr = elastic.CheckpointManager(str(tmp_path), async_=False,
                                    world=2)
    mgr.attach(mod)
    mgr._step = 1
    mgr.save(sync=True)
    monkeypatch.setenv('MXNET_TPU_FAULT_DEAD_HOST', '1')
    mgr._step = 2
    mgr.save(sync=True)
    res = elastic.load_newest_intact(str(tmp_path))
    assert res is not None and res[0]['step'] == 1
    # the KVStore facade reports the dead node honestly and the
    # barrier fails fast instead of hanging the collective
    kv = mx.kvstore.create('local')
    assert kv.num_dead_node == 1
    with pytest.raises(MXNetError, match='dead node'):
        kv.barrier()
    monkeypatch.delenv('MXNET_TPU_FAULT_DEAD_HOST')
    assert kv.num_dead_node == 0
    kv.barrier()


def test_restore_falls_back_past_bucket_incomplete_checkpoint(tmp_path):
    """A live-only FINAL checkpoint (dist runtime: a peer died, the
    survivors' manifest lists only their files) can validate file-by-
    file while a dead rank's unique ZeRO shards are simply gone.
    restore() must assemble-validate the optimizer BEFORE mutating the
    target and fall back to the older complete checkpoint — not crash
    the resume with 'checkpoint bucket incomplete'."""
    import shutil
    profiler.clear()
    mod = _make_module(ndev=4, zero=1)
    _train(mod, _batches(1))
    mgr = elastic.CheckpointManager(str(tmp_path), async_=False,
                                    world=2)
    mgr.attach(mod)
    mgr._step = 1
    mgr.save(sync=True)
    _train(mod, _batches(1, seed=1))
    mgr._step = 2
    mgr.save(sync=True)
    # simulate the live-only commit: rank 1's shard never landed and
    # the manifest lists only rank 0's file (all listed files intact)
    newest = os.path.join(str(tmp_path), 'step-%08d' % 2)
    os.unlink(os.path.join(newest, 'state-r00001.bin'))
    mpath = os.path.join(newest, elastic._MANIFEST)
    with open(mpath) as f:
        manifest = json.load(f)
    manifest['files'] = ['state-r00000.bin']
    with open(mpath, 'w') as f:
        json.dump(manifest, f)
    ref = os.path.join(str(tmp_path), 'ref')
    shutil.copytree(os.path.join(str(tmp_path), 'step-%08d' % 1),
                    os.path.join(ref, 'step-%08d' % 1))
    other = _make_module(seed=9, ndev=4, zero=1)
    info = elastic.CheckpointManager(str(tmp_path),
                                     world=2).attach(other).restore()
    assert info is not None and info.step == 1
    assert profiler.ckpt_stats()['ckpt_torn_fallbacks'] >= 1
    # ...and the state it applied is exactly the step-1 checkpoint's
    twin = _make_module(seed=11, ndev=4, zero=1)
    elastic.CheckpointManager(ref, world=2).attach(twin).restore()
    _assert_params_equal(other, twin)
    mgr.close()


# ---------------------------------------------------------------------------
# gluon fused wiring
# ---------------------------------------------------------------------------

def _gluon_run(ckpt=None, start=0, upto=8):
    from mxnet_tpu import gluon
    from mxnet_tpu.gluon import nn
    mx.random.seed(2)
    net = nn.HybridSequential()
    net.add(nn.Dense(16, activation='relu'), nn.Dense(4))
    net.initialize(mx.init.Xavier())
    tr = gluon.Trainer(net.collect_params(), 'sgd',
                       {'learning_rate': 0.1, 'momentum': 0.9})
    loss = gluon.loss.SoftmaxCrossEntropyLoss()
    fused = gluon.fuse_step(net, loss, tr, checkpoint=ckpt)
    rng = np.random.RandomState(0)
    xs = [mx.nd.array(rng.rand(8, 6).astype(np.float32))
          for _ in range(8)]
    ys = [mx.nd.array((rng.rand(8) * 4).astype(np.float32))
          for _ in range(8)]
    for i in range(start, upto):
        fused(xs[i], ys[i])
    return net


def test_gluon_fused_checkpoint_resume(tmp_path):
    net_a = _gluon_run()

    mgr = elastic.CheckpointManager(str(tmp_path), every_n_steps=4,
                                    async_=False)
    _gluon_run(ckpt=mgr, upto=4)   # cadence fires at step 4
    mgr.close()
    assert elastic.list_checkpoints(str(tmp_path)) == [4]

    mgr2 = elastic.CheckpointManager(str(tmp_path))
    net_c = _gluon_run(ckpt=mgr2, start=4)
    assert mgr2.last_resume is not None and mgr2.last_resume.step == 4
    # re-created nets carry different auto-prefixes: compare by the
    # positional order the checkpoint itself uses
    pa = [v.data().asnumpy() for v in net_a.collect_params().values()]
    pc = [v.data().asnumpy() for v in net_c.collect_params().values()]
    assert len(pa) == len(pc)
    for i, (a, c) in enumerate(zip(pa, pc)):
        np.testing.assert_array_equal(a, c, err_msg=str(i))
    mgr2.close()


# ---------------------------------------------------------------------------
# data pipeline: fast-forward + worker-error satellites
# ---------------------------------------------------------------------------

def test_fast_forward_ndarray_iter_matches_drain():
    it_a = _fit_iter()
    for _ in range(3):
        next(it_a)
    b_ref = next(it_a)
    it_b = _fit_iter()
    assert elastic.fast_forward(it_b, batches=3, batch_size=8) == 3
    b = next(it_b)
    np.testing.assert_array_equal(b.data[0].asnumpy(),
                                  b_ref.data[0].asnumpy())


def test_fast_forward_imageiter_positional(tmp_path):
    from mxnet_tpu import image, recordio
    import cv2
    prefix = str(tmp_path / 'ff')
    rec = recordio.MXIndexedRecordIO(prefix + '.idx', prefix + '.rec',
                                     'w')
    rng = np.random.RandomState(0)
    for i in range(16):
        ok, buf = cv2.imencode('.png', rng.randint(
            0, 255, (12, 12, 3)).astype(np.uint8))
        assert ok
        rec.write_idx(i, recordio.pack(
            recordio.IRHeader(0, float(i), i, 0), buf.tobytes()))
    rec.close()

    def make():
        return image.ImageIter(batch_size=4, data_shape=(3, 12, 12),
                               path_imgrec=prefix + '.rec',
                               preprocess_threads=2)
    ref = make()
    for _ in range(2):
        ref.next()
    b_ref = ref.next()
    ref.close()
    ff = make()
    elastic.fast_forward(ff, batches=2, batch_size=4)
    b = ff.next()   # positional jump, no re-decode of skipped batches
    np.testing.assert_array_equal(b.data[0].asnumpy(),
                                  b_ref.data[0].asnumpy())
    np.testing.assert_array_equal(b.label[0].asnumpy(),
                                  b_ref.label[0].asnumpy())
    ff.close()


def test_worker_error_carries_record_position(tmp_path):
    from mxnet_tpu import image, recordio
    import cv2
    prefix = str(tmp_path / 'bad')
    rec = recordio.MXIndexedRecordIO(prefix + '.idx', prefix + '.rec',
                                     'w')
    rng = np.random.RandomState(0)
    for i in range(10):
        if i == 6:
            payload = b'definitely not an image'
        else:
            ok, buf = cv2.imencode('.png', rng.randint(
                0, 255, (12, 12, 3)).astype(np.uint8))
            assert ok
            payload = buf.tobytes()
        rec.write_idx(i, recordio.pack(
            recordio.IRHeader(0, float(i), i, 0), payload))
    rec.close()
    it = image.ImageIter(batch_size=4, data_shape=(3, 12, 12),
                         path_imgrec=prefix + '.rec',
                         preprocess_threads=3)
    with pytest.raises(MXNetError) as excinfo:
        for _ in range(3):
            it.next()
    err = excinfo.value
    assert err.record_key == 6 and err.position == 6
    assert 'key=6' in str(err) and 'position 6' in str(err)
    assert err.__cause__ is not None
    # close() after the worker error still joins the pool cleanly and
    # the iterator stays usable (restarts from the watermark)
    it.close()
    import threading
    assert not [t for t in threading.enumerate()
                if 'decode' in t.name and t.is_alive()]
    it.reset()
    assert it.next().data[0].shape == (4, 3, 12, 12)
    it.close()


# ---------------------------------------------------------------------------
# observability
# ---------------------------------------------------------------------------

def test_ckpt_counters_in_summary_and_dump(tmp_path):
    profiler.clear()
    mod = _make_module()
    mgr = elastic.CheckpointManager(str(tmp_path), async_=False)
    mgr.attach(mod)
    mgr._step = 1
    mgr.save(sync=True)
    other = _make_module(seed=9)
    elastic.resume(elastic.CheckpointManager(str(tmp_path)), other)
    text = profiler.summary(print_out=False)
    assert 'ckpt_snapshots=1' in text
    assert 'ckpt_restores=1' in text
    fname = str(tmp_path / 'prof.json')
    profiler.profiler_set_config(mode='symbolic', filename=fname)
    path = profiler.dump_profile()
    meta = [e for e in json.load(open(path))['traceEvents']
            if e.get('name') == 'checkpoint']
    assert meta and meta[0]['args']['ckpt_snapshots'] == 1


def test_metric_state_roundtrip_composite():
    from mxnet_tpu import metric as metric_mod
    comp = metric_mod.CompositeEvalMetric(
        [metric_mod.Accuracy(), metric_mod.MSE()])
    comp.metrics[0].sum_metric = 7.0
    comp.metrics[0].num_inst = 9
    comp.metrics[1].sum_metric = 1.5
    comp.metrics[1].num_inst = 3
    state = elastic._metric_state(comp)
    comp2 = metric_mod.CompositeEvalMetric(
        [type(comp.metrics[0])(), type(comp.metrics[1])()])
    elastic._restore_metric(comp2, state)
    assert comp2.metrics[0].get() == comp.metrics[0].get()
    assert comp2.metrics[1].get() == comp.metrics[1].get()


# ---------------------------------------------------------------------------
# subprocess fit worker (test_fit_sigkill_subprocess_resume)
# ---------------------------------------------------------------------------

def _fit_worker(ckdir, out_path):
    """Child: fit 2 epochs with a 2-step checkpoint cadence; under
    MXNET_TPU_FAULT_KILL_AT_STEP the manager SIGKILLs mid-epoch.  On a
    clean finish, dump the final params for the parent's parity
    check.  Steps are PACED (a real model's step is ms-to-100ms of
    device work; this toy step is ~free, and an unpaced SIGKILL would
    land before the async writer ever commits a cadence
    checkpoint)."""
    import time as _time
    mod = mx.mod.Module(_mlp_symbol())
    mgr = elastic.CheckpointManager(ckdir, every_n_steps=2)
    _fit(mod, ckpt=mgr, cb=lambda param: _time.sleep(0.08))
    if mgr.last_resume is not None:
        print('RESUMED step=%d' % mgr.last_resume.step)
    params, auxs = mod.get_params()
    np.savez(out_path, **{n: v.asnumpy() for n, v in params.items()})
    mgr.close()
    print('FIT_WORKER_DONE')


if __name__ == '__main__':
    if len(sys.argv) >= 4 and sys.argv[1] == 'fit-worker':
        _fit_worker(sys.argv[2], sys.argv[3])
    else:
        raise SystemExit('usage: test_elastic.py fit-worker <ckdir> '
                         '<out.npz>')
