"""Sparse embedding tier (parallel/embedding.py + the fused paths):
mesh-row-striped tables, touched-rows-only COO gradients and rows-only
optimizer updates inside the single donated dispatch (gluon fuse_step
AND Module), the unique-count bucket ladder (zero steady-state
recompiles, re-created trainers included), full-entry elastic
checkpoints that restore across a dp-width change, the hot-row serving
cache, and the satellite op contracts (Embedding clip pinning, take
unknown-mode refusal, accumulating _backward_gather_nd, the registered
sparse_sgd(_mom)_update ops).

Parity contract under test: with plain SGD (wd or not) the rows-only
update is BITWISE equal to the dense path whenever it touches the same
rows; with momentum the semantics are LAZY (untouched rows keep their
momentum frozen — optimizer_ops.py docstring), so momentum parity is
asserted only on full-coverage id streams where lazy == dense.
"""
import numpy as np
import pytest
import jax.numpy as jnp

import mxnet_tpu as mx
from mxnet_tpu import exec_cache, gluon, nd, profiler
from mxnet_tpu.base import MXNetError
from mxnet_tpu.gluon import nn

VOCAB = 64
DIM = 8
BATCH = 16
_LOSS = gluon.loss.L2Loss()


def _make_net(sparse, seed=3, ctxs=None, vocab=VOCAB, dim=DIM):
    net = nn.HybridSequential()
    net.add(nn.Embedding(vocab, dim, sparse_grad=sparse))
    net.add(nn.Dense(4, flatten=False, in_units=dim))
    net.initialize(force_reinit=True, ctx=ctxs)
    rs = np.random.RandomState(seed)
    for _, p in sorted(net.collect_params().items()):
        p.set_data(nd.array(
            (rs.rand(*p.shape).astype(np.float32) - 0.5) * 0.2))
    return net


def _batches(n=4, lo=0, hi=VOCAB, batch=BATCH, seed=0):
    rs = np.random.RandomState(seed)
    return [(nd.array(rs.randint(lo, hi, size=(batch,))
                      .astype(np.float32)),
             nd.array(rs.randn(batch, 4).astype(np.float32)))
            for _ in range(n)]


def _full_coverage_batches(n=4, vocab=VOCAB, seed=0):
    """Every table row appears in every batch — the stream on which
    lazy momentum/wd equals dense momentum/wd."""
    rs = np.random.RandomState(seed)
    ids = np.arange(vocab, dtype=np.float32)
    return [(nd.array(ids),
             nd.array(rs.randn(vocab, 4).astype(np.float32)))
            for _ in range(n)]


def _pvals(net, fused=None, trainer=None):
    """Param values in sorted-name order; a mesh-striped sparse table
    reads through the fused step's canonical copy."""
    out = []
    for _, p in sorted(net.collect_params().items()):
        arr = None
        if fused is not None and getattr(p, 'sparse_grad', False):
            ent = fused._repl.get(id(p))
            if ent is not None:
                arr = np.asarray(ent[0])
        if arr is None:
            arr = p.list_data()[0].asnumpy()
        out.append(np.asarray(arr, dtype=np.float32))
    return out


def _train(net, opt_params, batches, **fuse_kw):
    tr = gluon.Trainer(net.collect_params(), 'sgd', dict(opt_params))
    fused = gluon.fuse_step(net, _LOSS, tr, **fuse_kw)
    for x, y in batches:
        fused(x, y)
    return fused, tr


# ---------------------------------------------------------------------------
# dense vs sparse parity — gluon fused path
# ---------------------------------------------------------------------------

def test_gluon_parity_plain_sgd_bitwise():
    batches = _batches(4)
    nd_net = _make_net(False)
    _train(nd_net, {'learning_rate': 0.1, 'wd': 0.0}, batches)
    sp_net = _make_net(True)
    fs, _ = _train(sp_net, {'learning_rate': 0.1, 'wd': 0.0}, batches)
    for a, b in zip(_pvals(nd_net), _pvals(sp_net, fs)):
        np.testing.assert_array_equal(a, b)


def test_gluon_parity_momentum_full_coverage():
    """Full-coverage ids: lazy momentum+wd degenerate to dense — the
    two program partitions agree to float32-ulp (not bitwise; XLA
    fuses the gather/scatter arm differently)."""
    batches = _full_coverage_batches(4)
    opt = {'learning_rate': 0.1, 'momentum': 0.9, 'wd': 1e-3}
    nd_net = _make_net(False)
    _train(nd_net, opt, batches)
    sp_net = _make_net(True)
    fs, _ = _train(sp_net, opt, batches)
    for a, b in zip(_pvals(nd_net), _pvals(sp_net, fs)):
        np.testing.assert_allclose(a, b, atol=1e-6)


def test_gluon_lazy_momentum_untouched_rows_frozen():
    """Ids confined to [0, 8): rows >= 8 must be exactly untouched
    (weight unchanged) even under momentum+wd — the touched-bytes
    contract, not just a tolerance."""
    batches = _batches(3, lo=0, hi=8)
    net = _make_net(True)
    w0 = _pvals(net)[0].copy()
    fs, _ = _train(net, {'learning_rate': 0.1, 'momentum': 0.9,
                         'wd': 1e-3}, batches)
    w1 = _pvals(net, fs)[0]
    np.testing.assert_array_equal(w0[8:], w1[8:])
    assert np.abs(w1[:8] - w0[:8]).max() > 0


def test_gluon_bulk_matches_single_sparse():
    batches = _batches(3)
    n1 = _make_net(True, seed=8)
    f1, _ = _train(n1, {'learning_rate': 0.1}, batches)
    nb = _make_net(True, seed=8)
    tr = gluon.Trainer(nb.collect_params(), 'sgd',
                       {'learning_rate': 0.1})
    fb = gluon.fuse_step(nb, _LOSS, tr)
    xs = nd.NDArray(jnp.stack([x._data for x, _ in batches]))
    ys = nd.NDArray(jnp.stack([y._data for _, y in batches]))
    losses = fb.bulk(xs, ys)
    assert losses.shape[0] == 3
    for a, b in zip(_pvals(n1, f1), _pvals(nb, fb)):
        np.testing.assert_array_equal(a, b)


def test_gluon_zero1_sparse_parity():
    """zero=1 (row-sharded momenta) composes with the sparse tier:
    same weights as zero=0 on the same 2-device mesh."""
    ctxs = [mx.cpu(0), mx.cpu(1)]
    batches = _batches(3)
    opt = {'learning_rate': 0.1, 'momentum': 0.9}
    outs = {}
    for zero in (0, 1):
        net = _make_net(True, ctxs=ctxs)
        fs, _ = _train(net, opt, batches, zero=zero)
        outs[zero] = _pvals(net, fs)
    for a, b in zip(outs[0], outs[1]):
        np.testing.assert_allclose(a, b, atol=1e-6)


def test_table_stripes_one_over_dp():
    """The sparse table's device residency really is ~1/dp of the
    table: exact here (vocab divisible by the 4-device mesh)."""
    ctxs = [mx.cpu(i) for i in range(4)]
    net = _make_net(True, ctxs=ctxs)
    tr = gluon.Trainer(net.collect_params(), 'sgd',
                       {'learning_rate': 0.1})
    fused = gluon.fuse_step(net, _LOSS, tr)
    for x, y in _batches(2):
        fused(x, y)
    p = next(p for p in tr._params if getattr(p, 'sparse_grad', False))
    ent = fused._repl.get(id(p))
    arr = ent[0] if ent else p.list_data()[0]._data
    total = int(np.prod(arr.shape))
    per_dev = max(int(np.prod(s.data.shape))
                  for s in arr.addressable_shards)
    assert len(arr.addressable_shards) == 4
    assert per_dev == total // 4


# ---------------------------------------------------------------------------
# bucket ladder: zero steady-state recompiles
# ---------------------------------------------------------------------------

def test_ladder_zero_steady_state_compiles():
    few = _batches(3, lo=0, hi=4, seed=1)      # tiny unique count
    many = _batches(3, lo=0, hi=VOCAB, seed=2)  # larger rung
    net = _make_net(True)
    fused, _ = _train(net, {'learning_rate': 0.1}, few + many)
    st0 = exec_cache.stats()
    # steady state: alternate distributions — re-bucketing between
    # rungs is a cache hit, never a compile
    for x, y in few + many + few:
        fused(x, y)
    st1 = exec_cache.stats()
    assert st1['misses'] == st0['misses']
    assert st1['total_compile_s'] == st0['total_compile_s']
    # a re-created net/trainer adopts the published trace facts and
    # lands on the cached programs without a discovery trace
    net2 = _make_net(True, seed=99)
    fused2, _ = _train(net2, {'learning_rate': 0.1}, few + many)
    st2 = exec_cache.stats()
    assert st2['misses'] == st1['misses']
    assert st2['total_compile_s'] == st1['total_compile_s']


def test_embed_counters_flow():
    profiler.clear()
    net = _make_net(True)
    _train(net, {'learning_rate': 0.1}, _batches(3))
    st = profiler.embed_stats()
    assert st['embed_steps'] >= 3
    assert st['embed_dispatches'] >= 3
    assert 0 < st['embed_touched_bytes'] < st['embed_dense_equiv_bytes']
    assert st['embed_max_rung'] >= 1
    assert 'embed' in profiler.summary(print_out=False)


# ---------------------------------------------------------------------------
# dense vs sparse parity — Module fused path
# ---------------------------------------------------------------------------

def _module(sparse, vocab=50, dim=4, seed=7):
    s = mx.sym
    data = s.Variable('data')
    emb = s.Embedding(data, name='emb', input_dim=vocab, output_dim=dim,
                      sparse_grad=sparse)
    net = s.SoftmaxOutput(s.FullyConnected(s.Flatten(emb), name='fc',
                                           num_hidden=3),
                          name='softmax')
    mod = mx.mod.Module(net)
    mod.bind(data_shapes=[mx.io.DataDesc('data', (8, 6))],
             label_shapes=[mx.io.DataDesc('softmax_label', (8,))])
    mx.random.seed(seed)
    mod.init_params(initializer=mx.init.Xavier())
    mod.init_optimizer(optimizer='sgd',
                       optimizer_params={'learning_rate': 0.1})
    return mod


def _module_batches(n=4, vocab=50, seed=0):
    rs = np.random.RandomState(seed)
    return [mx.io.DataBatch(
        data=[nd.array(rs.randint(0, vocab, size=(8, 6))
                       .astype(np.float32))],
        label=[nd.array((rs.rand(8) * 3).astype(np.float32))])
        for _ in range(n)]


def test_module_parity_plain_sgd_bitwise():
    batches = _module_batches()
    mods = [_module(False), _module(True)]
    for mod in mods:
        for b in batches:
            mod.forward_backward(b)
            mod.update()
    pa, _ = mods[0].get_params()
    pb, _ = mods[1].get_params()
    assert set(pa) == set(pb)
    for k in pa:
        np.testing.assert_array_equal(pa[k].asnumpy(), pb[k].asnumpy(),
                                      err_msg=k)


def test_module_refuses_graph_derived_ids():
    """Sparse tables looked up with COMPUTED ids can't ride the COO
    path (the host can't see the ids to dedup) — a typed refusal, not
    a silent densification."""
    s = mx.sym
    data = s.Variable('data')
    ids = data * 1.0                      # graph-derived, not an input
    emb = s.Embedding(ids, name='emb', input_dim=50, output_dim=4,
                      sparse_grad=True)
    net = s.SoftmaxOutput(s.FullyConnected(s.Flatten(emb), name='fc',
                                           num_hidden=3),
                          name='softmax')
    mod = mx.mod.Module(net)
    mod.bind(data_shapes=[mx.io.DataDesc('data', (8, 6))],
             label_shapes=[mx.io.DataDesc('softmax_label', (8,))])
    mod.init_params(initializer=mx.init.Xavier())
    # the refusal fires as soon as the fused updater is planned — at
    # init_optimizer, not at the first update
    with pytest.raises(MXNetError, match='graph-derived|sparse_grad'):
        mod.init_optimizer(optimizer='sgd',
                           optimizer_params={'learning_rate': 0.1})


# ---------------------------------------------------------------------------
# elastic checkpoints: full-entry tables restore across a dp-width change
# ---------------------------------------------------------------------------

def _elastic_run(tmpdir, ndev, batches, ckpt_every=None, start=0,
                 upto=None, seed=3):
    from mxnet_tpu import elastic
    ctxs = [mx.cpu(i) for i in range(ndev)]
    net = _make_net(True, seed=seed, ctxs=ctxs)
    tr = gluon.Trainer(net.collect_params(), 'sgd',
                       {'learning_rate': 0.1, 'momentum': 0.9})
    mgr = elastic.CheckpointManager(
        str(tmpdir), async_=False,
        **({'every_n_steps': ckpt_every} if ckpt_every else {})) \
        if tmpdir is not None else None
    fused = gluon.fuse_step(net, _LOSS, tr, checkpoint=mgr)
    upto = len(batches) if upto is None else upto
    for x, y in batches[start:upto]:
        fused(x, y)
    vals = _pvals(net, fused)
    if mgr is not None:
        mgr.close()
    return vals, mgr


def test_checkpoint_restores_across_dp_width_change(tmp_path):
    """Checkpoints store the FULL row-striped table (elastic.py
    _local_full assembles every shard) — so a 2-device run resumes on
    a 4-device mesh, re-striping the rows, and finishes with the same
    weights as the uninterrupted run."""
    from mxnet_tpu import elastic
    batches = _batches(6)
    truth, _ = _elastic_run(None, 2, batches)
    _elastic_run(tmp_path, 2, batches, ckpt_every=3, upto=3)
    assert elastic.list_checkpoints(str(tmp_path)) == [3]
    resumed, mgr2 = _elastic_run(tmp_path, 4, batches, start=3)
    assert mgr2.last_resume is not None and mgr2.last_resume.step == 3
    for a, b in zip(truth, resumed):
        np.testing.assert_allclose(a, b, atol=1e-5)


# ---------------------------------------------------------------------------
# hot-row serving cache
# ---------------------------------------------------------------------------

def _pred_module(vocab=200, dim=8, seed=11):
    s = mx.sym
    data = s.Variable('data')
    emb = s.Embedding(data, name='emb', input_dim=vocab, output_dim=dim)
    net = s.FullyConnected(s.Flatten(emb), name='fc', num_hidden=3)
    mx.random.seed(seed)
    mod = mx.mod.Module(net, label_names=None)
    mod.bind(data_shapes=[mx.io.DataDesc('data', (8, 4))],
             for_training=False)
    mod.init_params(initializer=mx.init.Xavier(rnd_type='gaussian'))
    return mod


def test_hot_row_cache_parity_counters_eviction():
    from mxnet_tpu.serving import InferenceEngine
    vocab, dim, cap = 200, 8, 48
    rng = np.random.RandomState(5)
    bs = [rng.randint(0, vocab, size=(8, 4)).astype(np.float32)
          for _ in range(6)]
    bs.append(bs[0].copy())              # repeat tail: hits expected
    ref = InferenceEngine(_pred_module(vocab, dim), max_batch=8,
                          quantize=False)
    want = [ref.predict(b) for b in bs]
    ref.close()
    eng = InferenceEngine(_pred_module(vocab, dim), max_batch=8,
                          quantize=False, hot_rows=cap)
    try:
        got = [eng.predict(b) for b in bs]
        for w, g in zip(want, got):
            np.testing.assert_allclose(w, g, atol=1e-5)
        st = eng.stats()['hot_rows']['emb_weight']
        assert st['capacity'] == cap
        assert st['hits'] > 0 and st['misses'] > 0
        assert st['evictions'] > 0       # 7 batches x ~30 uniq >> 48
        assert st['resident'] <= cap
        assert st['resident_bytes'] == cap * dim * 4
        assert st['table_bytes'] == vocab * dim * 4
        # device residency really is (C, dim), not the full table
        assert tuple(eng._hotrows['emb_weight'].arg._data.shape) == \
            (cap, dim)
    finally:
        eng.close()


def test_hot_row_prefetch_hits_and_budget(monkeypatch):
    # queued-request speculation: the dispatcher pages still-waiting
    # requests' rows in behind the in-flight dispatch, so by the time
    # they coalesce the demand path hits.  Budget discipline: never
    # evict beyond the LRU half of the cache for a guess
    from mxnet_tpu import profiler
    from mxnet_tpu.serving import InferenceEngine
    vocab, dim, cap = 200, 8, 48
    rng = np.random.RandomState(6)
    b1 = rng.randint(0, 100, size=(8, 4)).astype(np.float32)
    b2 = rng.randint(100, 120, size=(8, 4)).astype(np.float32)
    ref = InferenceEngine(_pred_module(vocab, dim), max_batch=8,
                          quantize=False)
    want = ref.predict(b2)
    ref.close()
    profiler.clear()
    eng = InferenceEngine(_pred_module(vocab, dim), max_batch=8,
                          quantize=False, hot_rows=cap)
    try:
        assert eng._hotrow_peek == 8         # default peek depth
        eng.predict(b1)                      # demand-warm the cache
        st0 = eng.stats()['hot_rows']['emb_weight']
        # what the dispatcher does with the still-queued heads' input
        # tuples while the b1 dispatch is in flight
        eng._hotrow_prefetch([(b2,)])
        st1 = eng.stats()['hot_rows']['emb_weight']
        assert st1['prefetch_rows'] > st0['prefetch_rows']
        got = eng.predict(b2)                # demand is now all hits
        st2 = eng.stats()['hot_rows']['emb_weight']
        assert st2['prefetch_hits'] > 0
        assert st2['misses'] == st1['misses']   # zero demand misses
        assert st2['resident'] <= cap
        np.testing.assert_allclose(want, got, atol=1e-5)
        es = profiler.embed_stats()
        assert es['hotrow_prefetched'] >= st1['prefetch_rows']
        assert es['hotrow_prefetch_hits'] >= st2['prefetch_hits']
    finally:
        eng.close()
        profiler.clear()
    # the peek knob: 'off' disables speculation entirely
    monkeypatch.setenv('MXNET_TPU_SERVE_HOTROW_PREFETCH', 'off')
    eng = InferenceEngine(_pred_module(vocab, dim), max_batch=8,
                          quantize=False, hot_rows=cap)
    try:
        assert eng._hotrow_peek == 0
    finally:
        eng.close()


def test_hot_row_refusals():
    from mxnet_tpu.serving import InferenceEngine
    with pytest.raises(MXNetError, match='capacity|worst'):
        InferenceEngine(_pred_module(), max_batch=8, quantize=False,
                        hot_rows=8)
    with pytest.raises(MXNetError, match='nope'):
        InferenceEngine(_pred_module(), max_batch=8, quantize=False,
                        hot_rows={'nope': 64})


# ---------------------------------------------------------------------------
# satellite op contracts
# ---------------------------------------------------------------------------

def test_embedding_clips_out_of_range_ids():
    w = nd.array(np.arange(12, dtype=np.float32).reshape(4, 3))
    ids = nd.array(np.array([-3, 0, 3, 9], dtype=np.float32))
    out = nd.Embedding(ids, w, input_dim=4, output_dim=3).asnumpy()
    np.testing.assert_array_equal(out[0], w.asnumpy()[0])   # clip low
    np.testing.assert_array_equal(out[3], w.asnumpy()[3])   # clip high


def test_take_unknown_mode_raises():
    a = nd.array(np.arange(6, dtype=np.float32))
    idx = nd.array(np.array([0, 5], dtype=np.float32))
    assert nd.take(a, idx, mode='clip').shape == (2,)
    with pytest.raises(MXNetError, match="mode"):
        nd.take(a, idx, mode='raise')


def test_backward_gather_nd_accumulates_duplicates():
    """scatter_nd keeps the reference's last-wins on duplicate indices;
    _backward_gather_nd (alias scatter_nd_acc) ADDS — the conformance
    split a sparse gradient path depends on."""
    data = nd.array(np.array([1.0, 2.0, 4.0], dtype=np.float32))
    idx = nd.array(np.array([[1, 1, 2]], dtype=np.float32))
    acc = nd._backward_gather_nd(data, idx, shape=(4,)).asnumpy()
    np.testing.assert_array_equal(acc, [0.0, 3.0, 4.0, 0.0])
    alias = nd.scatter_nd_acc(data, idx, shape=(4,)).asnumpy()
    np.testing.assert_array_equal(alias, acc)
    last = nd.scatter_nd(data, idx, shape=(4,)).asnumpy()
    assert last[1] in (1.0, 2.0) and last[2] == 4.0 and last[0] == 0.0


def test_sparse_sgd_update_ops():
    V, D, R = 10, 4, 6
    rng = np.random.RandomState(0)
    w0 = rng.randn(V, D).astype(np.float32)
    uids = np.array([1, 3, 5, 7, V, V], dtype=np.int32)  # padded tail
    rows = rng.randn(R, D).astype(np.float32)
    gd = np.zeros((V, D), np.float32)
    gd[uids[:4]] = rows[:4]
    w = nd.array(w0.copy())
    nd.sparse_sgd_update(w, nd.array(uids), nd.array(rows), out=w,
                         lr=0.1, wd=0.0, rescale_grad=0.5)
    wref = nd.array(w0.copy())
    nd.sgd_update(wref, nd.array(gd), out=wref, lr=0.1, wd=0.0,
                  rescale_grad=0.5)
    np.testing.assert_array_equal(w.asnumpy(), wref.asnumpy())

    # momentum, every row touched: matches dense sgd_mom_update
    uids_all = np.arange(V, dtype=np.int32)
    rows_all = rng.randn(V, D).astype(np.float32)
    w = nd.array(w0.copy())
    m = nd.zeros((V, D))
    wref = nd.array(w0.copy())
    mref = nd.zeros((V, D))
    for _ in range(3):
        nd.sparse_sgd_mom_update(w, nd.array(uids_all),
                                 nd.array(rows_all), m, out=w,
                                 lr=0.1, wd=0.01, momentum=0.9)
        nd.sgd_mom_update(wref, nd.array(rows_all), mref, out=wref,
                          lr=0.1, wd=0.01, momentum=0.9)
    np.testing.assert_allclose(w.asnumpy(), wref.asnumpy(), atol=1e-6)
    np.testing.assert_allclose(m.asnumpy(), mref.asnumpy(), atol=1e-6)

    # lazy: untouched row 0 frozen (weight AND momentum)
    w = nd.array(w0.copy())
    m = nd.zeros((V, D))
    nd.sparse_sgd_mom_update(w, nd.array(uids), nd.array(rows), m,
                             out=w, lr=0.1, momentum=0.9)
    np.testing.assert_array_equal(m.asnumpy()[0], np.zeros(D))
    np.testing.assert_array_equal(w.asnumpy()[0], w0[0])
    assert np.abs(m.asnumpy()[3]).max() > 0


# ---------------------------------------------------------------------------
# typed refusals: compositions the sparse tier rejects
# ---------------------------------------------------------------------------

def test_ema_refuses_sparse_tables():
    net = _make_net(True)
    tr = gluon.Trainer(net.collect_params(), 'sgd',
                       {'learning_rate': 0.1})
    fused = gluon.fuse_step(net, _LOSS, tr, ema_decay=0.99)
    x, y = _batches(1)[0]
    # the plan (and the refusal) materializes at the first dispatch
    with pytest.raises(MXNetError, match='ema_decay'):
        fused(x, y)


def test_pipeline_refuses_sparse_tables():
    ctxs = [mx.cpu(i) for i in range(4)]
    net = _make_net(True, ctxs=ctxs)
    tr = gluon.Trainer(net.collect_params(), 'sgd',
                       {'learning_rate': 0.1})
    with pytest.raises(MXNetError, match='pipeline'):
        gluon.fuse_step(net, _LOSS, tr, pipeline=(2, 2))
