"""Test configuration: run the suite on an 8-device virtual CPU mesh so
multi-device sharding paths are exercised without TPU hardware (the
reference's analogous trick is cpu(0)/cpu(1) contexts in
tests/python/unittest/test_multi_device_exec.py, and launcher=local
multi-process for dist kvstore — SURVEY.md §4).

Note: the axon TPU plugin's sitecustomize imports jax at interpreter
start, freezing JAX_PLATFORMS before this file runs — so the platform
must be forced via jax.config, not os.environ.
"""
import os

flags = os.environ.get('XLA_FLAGS', '')
if 'xla_force_host_platform_device_count' not in flags:
    os.environ['XLA_FLAGS'] = (
        flags + ' --xla_force_host_platform_device_count=8').strip()
os.environ['JAX_PLATFORMS'] = 'cpu'

import jax

jax.config.update('jax_platforms', 'cpu')
assert jax.default_backend() == 'cpu', 'tests must run on the CPU backend'
assert jax.device_count() == 8, 'tests expect 8 virtual CPU devices'
