"""SSD model tests (reference example/ssd — symbol structure and a
miniature end-to-end train/detect cycle)."""
import numpy as np

import mxnet_tpu as mx
from mxnet_tpu import nd, sym
from mxnet_tpu.models import ssd


def test_ssd300_symbol_shapes():
    net = ssd.get_symbol_train(num_classes=3)
    _, outs, _ = net.infer_shape(data=(1, 3, 300, 300), label=(1, 4, 5))
    a = outs[0][2]
    assert outs[0] == (1, 4, a)          # cls_prob (B, C+1, A)
    assert outs[1] == (1, a * 4)         # loc_loss
    assert outs[2] == (1, a)             # cls_label
    det = ssd.get_symbol(num_classes=3)
    _, o2, _ = det.infer_shape(data=(1, 3, 300, 300))
    assert o2 == [(1, a, 6)]


def _mini_ssd_train(num_classes=2):
    """Tiny single-scale SSD head on an 8x8 feature map."""
    data = sym.Variable('data')
    feat = sym.Convolution(data, kernel=(3, 3), pad=(1, 1), num_filter=8,
                           name='feat_conv')
    feat = sym.Activation(feat, act_type='relu')
    loc_preds, cls_preds, anchors = ssd.multibox_layer(
        [feat], num_classes, sizes=[[0.3, 0.4]], ratios=[[1, 2]])
    label = sym.Variable('label')
    loc_t, loc_m, cls_t = sym.MultiBoxTarget(
        anchors, label, cls_preds, overlap_threshold=0.5,
        negative_mining_ratio=3, negative_mining_thresh=0.5,
        name='multibox_target')
    cls_prob = sym.SoftmaxOutput(cls_preds, cls_t, ignore_label=-1,
                                 use_ignore=True, multi_output=True,
                                 normalization='valid', name='cls_prob')
    loc_loss = sym.MakeLoss(sym.smooth_l1(loc_m * (loc_preds - loc_t),
                                          scalar=1.0),
                            normalization='valid', name='loc_loss')
    return sym.Group([cls_prob, loc_loss])


def test_mini_ssd_trains():
    net = _mini_ssd_train()
    mod = mx.mod.Module(net, data_names=('data',), label_names=('label',))
    B = 2
    mod.bind(data_shapes=[mx.io.DataDesc('data', (B, 3, 8, 8))],
             label_shapes=[mx.io.DataDesc('label', (B, 2, 5))])
    mod.init_params(initializer=mx.init.Xavier())
    mod.init_optimizer(optimizer='sgd',
                       optimizer_params={'learning_rate': 0.1})
    rs = np.random.RandomState(0)
    x = rs.rand(B, 3, 8, 8).astype(np.float32)
    lab = np.full((B, 2, 5), -1, np.float32)
    lab[:, 0] = [0, 0.2, 0.2, 0.6, 0.6]      # one gt box, class 0
    batch = mx.io.DataBatch(data=[nd.array(x)], label=[nd.array(lab)])
    losses = []
    for _ in range(10):
        mod.forward_backward(batch)
        mod.update()
        out = mod.get_outputs()
        losses.append(float(out[1].asnumpy().sum()))
    assert np.isfinite(losses).all()
    assert losses[-1] <= losses[0] + 1e-3    # loc loss not diverging


def test_mini_ssd_detect():
    """Detection path produces sane, thresholded, NMS'd output."""
    data = sym.Variable('data')
    feat = sym.Convolution(data, kernel=(3, 3), pad=(1, 1), num_filter=8,
                           name='feat_conv')
    loc_preds, cls_preds, anchors = ssd.multibox_layer(
        [feat], 2, sizes=[[0.3, 0.4]], ratios=[[1, 2]])
    cls_prob = sym.softmax(cls_preds, axis=1)
    det = sym.MultiBoxDetection(cls_prob, loc_preds, anchors,
                                nms_threshold=0.5, threshold=0.2)
    ex = det.simple_bind(mx.cpu(), data=(1, 3, 8, 8), grad_req='null')
    for k, v in ex.arg_dict.items():
        if k != 'data':
            v[:] = np.random.RandomState(0).rand(*v.shape).astype(
                np.float32) * 0.1
    ex.arg_dict['data'][:] = np.random.RandomState(1).rand(
        1, 3, 8, 8).astype(np.float32)
    out = ex.forward(is_train=False)[0].asnumpy()
    assert out.shape[2] == 6
    kept = out[0][out[0, :, 0] >= 0]
    if len(kept):
        assert (kept[:, 1] >= 0.2 - 1e-6).all()
        assert (kept[:, 2:] >= -1e-5).all() and (kept[:, 2:] <= 1 + 1e-5).all()
