"""NDArray imperative API tests (model: reference
tests/python/unittest/test_ndarray.py)."""
import numpy as np
import pytest

import mxnet_tpu as mx
from mxnet_tpu import nd


def test_create_and_asnumpy():
    a = nd.array([[1, 2], [3, 4]])
    assert a.shape == (2, 2)
    assert a.dtype == np.float32
    np.testing.assert_allclose(a.asnumpy(), [[1, 2], [3, 4]])


def test_zeros_ones_full_arange():
    assert nd.zeros((2, 3)).asnumpy().sum() == 0
    assert nd.ones((2, 3)).asnumpy().sum() == 6
    np.testing.assert_allclose(nd.full((2,), 3.5).asnumpy(), [3.5, 3.5])
    np.testing.assert_allclose(nd.arange(0, 5).asnumpy(), np.arange(0, 5.0))


def test_elemwise_arith():
    a = nd.array([1.0, 2.0, 3.0])
    b = nd.array([4.0, 5.0, 6.0])
    np.testing.assert_allclose((a + b).asnumpy(), [5, 7, 9])
    np.testing.assert_allclose((a - b).asnumpy(), [-3, -3, -3])
    np.testing.assert_allclose((a * b).asnumpy(), [4, 10, 18])
    np.testing.assert_allclose((b / a).asnumpy(), [4, 2.5, 2])
    np.testing.assert_allclose((a + 1).asnumpy(), [2, 3, 4])
    np.testing.assert_allclose((1 - a).asnumpy(), [0, -1, -2])
    np.testing.assert_allclose((2 * a).asnumpy(), [2, 4, 6])
    np.testing.assert_allclose((6 / a).asnumpy(), [6, 3, 2])
    np.testing.assert_allclose((a ** 2).asnumpy(), [1, 4, 9])
    np.testing.assert_allclose((-a).asnumpy(), [-1, -2, -3])


def test_broadcast_in_dunder():
    a = nd.ones((2, 3))
    b = nd.array([1.0, 2.0, 3.0])
    np.testing.assert_allclose((a + b).asnumpy(), [[2, 3, 4], [2, 3, 4]])


def test_comparisons():
    a = nd.array([1.0, 2.0, 3.0])
    np.testing.assert_allclose((a > 1.5).asnumpy(), [0, 1, 1])
    np.testing.assert_allclose((a == 2).asnumpy(), [0, 1, 0])


def test_inplace():
    a = nd.ones((3,))
    a += 2
    np.testing.assert_allclose(a.asnumpy(), [3, 3, 3])
    a *= 2
    np.testing.assert_allclose(a.asnumpy(), [6, 6, 6])


def test_indexing():
    a = nd.array(np.arange(12).reshape(3, 4))
    np.testing.assert_allclose(a[1].asnumpy(), [4, 5, 6, 7])
    np.testing.assert_allclose(a[1:3].asnumpy(),
                               np.arange(12).reshape(3, 4)[1:3])
    a[:] = 0
    assert a.asnumpy().sum() == 0
    a[1] = 5
    np.testing.assert_allclose(a.asnumpy()[1], [5, 5, 5, 5])


def test_reshape_transpose():
    a = nd.array(np.arange(6).reshape(2, 3))
    assert a.reshape((3, 2)).shape == (3, 2)
    assert a.reshape((-1,)).shape == (6,)
    assert a.T.shape == (3, 2)
    assert a.reshape((0, -1)).shape == (2, 3)


def test_reshape_special_codes():
    a = nd.zeros((2, 3, 4))
    assert a.reshape((-2,)).shape == (2, 3, 4)
    assert a.reshape((0, -3)).shape == (2, 12)
    assert a.reshape((-4, 1, 2, 0, 0)).shape == (1, 2, 3, 4)


def test_reductions():
    a = nd.array(np.arange(6, dtype=np.float32).reshape(2, 3))
    assert a.sum().asscalar() == 15
    np.testing.assert_allclose(a.sum(axis=0).asnumpy(), [3, 5, 7])
    np.testing.assert_allclose(a.mean(axis=1).asnumpy(), [1, 4])
    np.testing.assert_allclose(a.max(axis=1).asnumpy(), [2, 5])
    np.testing.assert_allclose(a.argmax(axis=1).asnumpy(), [2, 2])
    np.testing.assert_allclose(a.norm().asnumpy(),
                               [np.sqrt((np.arange(6) ** 2).sum())], rtol=1e-6)


def test_dot():
    a = nd.array(np.random.rand(3, 4))
    b = nd.array(np.random.rand(4, 5))
    np.testing.assert_allclose(nd.dot(a, b).asnumpy(),
                               a.asnumpy() @ b.asnumpy(), rtol=1e-5)
    c = nd.dot(a, b, transpose_a=False, transpose_b=False)
    assert c.shape == (3, 5)
    d = nd.dot(b, a, transpose_a=True, transpose_b=True)
    assert d.shape == (5, 3)


def test_concat_split_stack():
    a = nd.ones((2, 3))
    b = nd.zeros((2, 3))
    c = nd.concatenate([a, b], axis=0)
    assert c.shape == (4, 3)
    c2 = nd.Concat(a, b, num_args=2, dim=1)
    assert c2.shape == (2, 6)
    parts = nd.SliceChannel(c2, num_outputs=2, axis=1)
    assert parts[0].shape == (2, 3)
    s = nd.stack(a, b, num_args=2, axis=0)
    assert s.shape == (2, 2, 3)


def test_unary_math():
    a = nd.array([1.0, 4.0, 9.0])
    np.testing.assert_allclose(nd.sqrt(a).asnumpy(), [1, 2, 3], rtol=1e-6)
    np.testing.assert_allclose(nd.square(a).asnumpy(), [1, 16, 81])
    np.testing.assert_allclose(nd.exp(nd.log(a)).asnumpy(), [1, 4, 9],
                               rtol=1e-5)


def test_save_load_dict(tmp_path):
    fname = str(tmp_path / 'test-0001.params')
    data = {'arg:w': nd.array(np.random.rand(3, 4)),
            'aux:m': nd.array(np.random.rand(7))}
    nd.save(fname, data)
    loaded = nd.load(fname)
    assert set(loaded) == set(data)
    for k in data:
        np.testing.assert_allclose(loaded[k].asnumpy(), data[k].asnumpy())


def test_save_load_list(tmp_path):
    fname = str(tmp_path / 'list.params')
    data = [nd.ones((2,)), nd.zeros((3, 3))]
    nd.save(fname, data)
    loaded = nd.load(fname)
    assert len(loaded) == 2
    assert loaded[1].shape == (3, 3)


def test_copyto_context():
    a = nd.ones((2, 2))
    b = a.copyto(mx.cpu(0))
    np.testing.assert_allclose(b.asnumpy(), a.asnumpy())
    c = nd.zeros((2, 2))
    a.copyto(c)
    np.testing.assert_allclose(c.asnumpy(), a.asnumpy())


def test_astype():
    a = nd.ones((2,))
    assert a.astype(np.int32).dtype == np.int32
    assert nd.Cast(a, dtype='int32').dtype == np.int32


def test_take_embedding_onehot():
    w = nd.array(np.arange(12, dtype=np.float32).reshape(4, 3))
    idx = nd.array([0, 2])
    out = nd.Embedding(idx, w, input_dim=4, output_dim=3)
    np.testing.assert_allclose(out.asnumpy(), [[0, 1, 2], [6, 7, 8]])
    oh = nd.one_hot(idx, depth=4)
    np.testing.assert_allclose(oh.asnumpy(),
                               [[1, 0, 0, 0], [0, 0, 1, 0]])


def test_topk_sort():
    a = nd.array([[3.0, 1.0, 2.0], [6.0, 5.0, 4.0]])
    v = nd.topk(a, k=2, ret_typ='value')
    np.testing.assert_allclose(v.asnumpy(), [[3, 2], [6, 5]])
    s = nd.sort(a, axis=1)
    np.testing.assert_allclose(s.asnumpy(), [[1, 2, 3], [4, 5, 6]])


def test_random_ops():
    mx.random.seed(42)
    u = nd.uniform(low=0, high=1, shape=(100,))
    assert u.shape == (100,)
    assert 0 <= u.asnumpy().min() and u.asnumpy().max() <= 1
    mx.random.seed(42)
    u2 = nd.uniform(low=0, high=1, shape=(100,))
    np.testing.assert_allclose(u.asnumpy(), u2.asnumpy())
    n = nd.normal(loc=5.0, scale=0.1, shape=(1000,))
    assert abs(n.asnumpy().mean() - 5.0) < 0.1


def test_waitall():
    a = nd.ones((4,)) * 2
    a.wait_to_read()
    nd.waitall()


def test_batchnorm_imperative():
    x = nd.array(np.random.rand(4, 3, 5, 5).astype(np.float32))
    gamma = nd.ones((3,))
    beta = nd.zeros((3,))
    mmean = nd.zeros((3,))
    mvar = nd.ones((3,))
    out = nd.BatchNorm(x, gamma, beta, mmean, mvar, fix_gamma=False)
    assert out.shape == x.shape


def test_convolution_imperative():
    x = nd.array(np.random.rand(1, 2, 5, 5).astype(np.float32))
    w = nd.array(np.random.rand(4, 2, 3, 3).astype(np.float32))
    b = nd.zeros((4,))
    out = nd.Convolution(x, w, b, kernel=(3, 3), num_filter=4)
    assert out.shape == (1, 4, 3, 3)
    out2 = nd.Convolution(x, w, b, kernel=(3, 3), num_filter=4,
                          stride=(2, 2), pad=(1, 1))
    assert out2.shape == (1, 4, 3, 3)


def test_pooling_imperative():
    x = nd.array(np.random.rand(1, 2, 4, 4).astype(np.float32))
    out = nd.Pooling(x, kernel=(2, 2), stride=(2, 2), pool_type='max')
    assert out.shape == (1, 2, 2, 2)
    g = nd.Pooling(x, global_pool=True, pool_type='avg', kernel=(2, 2))
    assert g.shape == (1, 2, 1, 1)
    np.testing.assert_allclose(g.asnumpy().reshape(2),
                               x.asnumpy().mean(axis=(0, 2, 3)), rtol=1e-6)


def test_fullyconnected_imperative():
    x = nd.array(np.random.rand(2, 8).astype(np.float32))
    w = nd.array(np.random.rand(4, 8).astype(np.float32))
    b = nd.zeros((4,))
    out = nd.FullyConnected(x, w, b, num_hidden=4)
    np.testing.assert_allclose(out.asnumpy(),
                               x.asnumpy() @ w.asnumpy().T, rtol=1e-5)
