"""dp×pipe 2D-mesh training through the user-facing trainers (round
16): gluon `fuse_step(pipeline=(S, M))` and `Module.fit(pipeline=)`
run the GPipe fill-drain schedule inside one donated XLA dispatch —
parity vs the single-device fused baseline, ZeRO-1 composition with
re-created-trainer bit parity at zero new compiles, per-device
param/optimizer-state residency, the expert-parallel `gluon.nn.MoE`
block with routed/dropped profiler counters, and the ring-attention
dispatch vs `full_attention`.

Sizing: CPU smoke shapes on the suite's 8 virtual devices (tier-1
runtime guard — every net is a few tiny Dense layers; distinct XLA
programs are the cost, so tests share one net/batch configuration and
re-created trainers warm from the process-wide exec_cache).

Tolerances: the pipelined program partitions the same math differently
(fill-drain scan + psum placement), so parity vs the single-device
fused baseline is float32-ulp-level (allclose), while re-running the
SAME pipelined program is bitwise.
"""
import os

import numpy as np
import pytest
import jax
import jax.numpy as jnp

import mxnet_tpu as mx
from mxnet_tpu import exec_cache, gluon, profiler
from mxnet_tpu import sym as S
from mxnet_tpu.base import MXNetError
from mxnet_tpu.gluon import nn
from mxnet_tpu.parallel import collectives, mesh as pmesh
from mxnet_tpu.parallel import moe as moe_mod
from mxnet_tpu.parallel import pipeline as pipe_mod
from mxnet_tpu.parallel.ring_attention import full_attention
from mxnet_tpu.parallel.transformer import attention

BATCH = 8
FEAT = 6
UNITS = 12
NCLS = 4
OPT = {'learning_rate': 0.1, 'momentum': 0.9, 'wd': 1e-3}
LOSS = gluon.loss.SoftmaxCrossEntropyLoss()


def _ctxs(n):
    return [mx.cpu(i) for i in range(n)]


def _batches(k=3, seed=42):
    rs = np.random.RandomState(seed)
    return [(mx.nd.array(rs.rand(BATCH, FEAT).astype(np.float32)),
             mx.nd.array((rs.rand(BATCH) * NCLS).astype(np.float32)))
            for _ in range(k)]


def _make_net(ctx=None, body=4, act='tanh'):
    """Stem Dense + `body` identical Dense layers + head Dense — the
    shape every pipelined test shares (so programs hit exec_cache)."""
    net = nn.HybridSequential()
    with net.name_scope():
        net.add(nn.Dense(UNITS, activation='relu', in_units=FEAT))
        for _ in range(body):
            net.add(nn.Dense(UNITS, activation=act, in_units=UNITS))
        net.add(nn.Dense(NCLS, in_units=UNITS))
    net.initialize(ctx=ctx)
    rs = np.random.RandomState(5)
    for _, p in sorted(net.collect_params().items()):
        p.set_data(mx.nd.array(
            (rs.rand(*p.shape).astype(np.float32) - 0.5) * 0.4))
    return net


def _pvals(net):
    return [p.list_data()[0].asnumpy()
            for _, p in sorted(net.collect_params().items())]


def _train_gluon(ctx, pipeline=None, zero=None, bulk=None, k=3):
    net = _make_net(ctx=ctx)
    tr = gluon.Trainer(net.collect_params(), 'sgd', dict(OPT))
    fs = gluon.fuse_step(net, LOSS, tr, pipeline=pipeline, zero=zero)
    bs = _batches(k)
    if bulk:
        xs = mx.nd.array(np.stack([x.asnumpy() for x, _ in bs]))
        ys = mx.nd.array(np.stack([y.asnumpy() for _, y in bs]))
        fs.bulk(xs, ys)
    else:
        for x, y in bs:
            fs(x, y)
    return net, fs


@pytest.fixture(scope='module')
def baseline():
    """Single-device fused training — the parity reference."""
    net, _ = _train_gluon(mx.cpu(0))
    return _pvals(net)


# ---------------------------------------------------------------------------
# gluon fuse_step(pipeline=)
# ---------------------------------------------------------------------------

def test_gluon_pipe_parity_2x2(baseline):
    net, fs = _train_gluon(_ctxs(4), pipeline=(2, 2))
    for a, b in zip(baseline, _pvals(net)):
        np.testing.assert_allclose(a, b, atol=3e-6, rtol=1e-4)
    # residency: each device holds 1/S of the stage body
    param_b, state_b = fs._pipe_state_accounting()
    repl_b = sum(int(np.prod(p.shape)) * np.dtype(p.dtype).itemsize
                 for _, p in sorted(net.collect_params().items()))
    assert param_b < repl_b
    assert state_b == param_b        # replicated momenta mirror

def test_gluon_pipe_4stage_parity(baseline):
    """All 8 suite devices as a 2(dp)×4(pipe) mesh, one layer/stage."""
    net, _ = _train_gluon(_ctxs(8), pipeline=(4, 2))
    for a, b in zip(baseline, _pvals(net)):
        np.testing.assert_allclose(a, b, atol=3e-6, rtol=1e-4)


def test_gluon_pipe_bulk_parity(baseline):
    net, _ = _train_gluon(_ctxs(4), pipeline=(2, 2), bulk=True)
    for a, b in zip(baseline, _pvals(net)):
        np.testing.assert_allclose(a, b, atol=3e-6, rtol=1e-4)


def test_gluon_pipe_zero_parity_and_residency(baseline):
    net, fs = _train_gluon(_ctxs(4), pipeline=(2, 2), zero=1)
    for a, b in zip(baseline, _pvals(net)):
        np.testing.assert_allclose(a, b, atol=3e-6, rtol=1e-4)
    param_b, state_b = fs._pipe_state_accounting()
    rep_param_b, rep_state_b = \
        _train_gluon(_ctxs(4), pipeline=(2, 2))[1]._pipe_state_accounting()
    assert param_b == rep_param_b
    # momentum buckets shard over dp=2 (bucket padding adds slack)
    assert state_b < rep_state_b
    assert state_b <= rep_state_b // 2 + 4096


def test_gluon_pipe_recreation_bitwise_zero_compiles():
    ref, _ = _train_gluon(_ctxs(4), pipeline=(2, 2), zero=1)
    st0 = exec_cache.stats()
    net, _ = _train_gluon(_ctxs(4), pipeline=(2, 2), zero=1)
    st1 = exec_cache.stats()
    assert st1['misses'] == st0['misses']
    assert st1['total_compile_s'] == st0['total_compile_s'], \
        're-created pipelined trainer recompiled'
    for a, b in zip(_pvals(ref), _pvals(net)):
        np.testing.assert_array_equal(a, b)


def test_gluon_pipe_sync_params_enables_eager_eval():
    """Stage weights live on their pipe row during training;
    sync_params() materializes ordinary per-context copies so
    imperative net(x) works, preserving the trained values, and the
    next fused step re-places them with zero new compiles."""
    net, fs = _train_gluon(_ctxs(4), pipeline=(2, 2), k=2)
    before = _pvals(net)
    fs.sync_params()
    for a, b in zip(before, _pvals(net)):
        np.testing.assert_array_equal(a, b)
    x, _ = _batches(1)[0]
    out = net(x)                      # eager forward on cpu(0)
    assert out.asnumpy().shape == (BATCH, NCLS)
    st0 = exec_cache.stats()
    fs(*_batches(1)[0])               # re-places, cached program
    assert exec_cache.stats()['total_compile_s'] == \
        st0['total_compile_s']


def test_gluon_pipe_int8_wire_parity_and_determinism(monkeypatch,
                                                     baseline):
    """MXNET_TPU_DIST_WIRE_DTYPE=int8|bf16 compresses the pipe
    trainer's dp gradient reduction (shard_map manual axes — the one
    fused path whose wire CAN compress in-graph).  Parity gate: the
    quantized-wire run tracks the fp32 single-device baseline at
    wire-noise tolerance, each mode is bitwise-deterministic across
    runs, and the modes produce genuinely different programs."""
    fp_net, _ = _train_gluon(_ctxs(4), pipeline=(2, 2))
    fp_p = _pvals(fp_net)
    monkeypatch.setenv('MXNET_TPU_DIST_WIRE_DTYPE', 'int8')
    n1, _ = _train_gluon(_ctxs(4), pipeline=(2, 2))
    n2, _ = _train_gluon(_ctxs(4), pipeline=(2, 2))
    p1, p2 = _pvals(n1), _pvals(n2)
    for a, b in zip(p1, p2):
        np.testing.assert_array_equal(a, b)     # per-mode bitwise
    assert not all(np.array_equal(a, b) for a, b in zip(fp_p, p1)), \
        'int8 wire produced the fp32 program (knob not baked in?)'
    for a, b in zip(baseline, p1):              # parity gate vs fp32
        np.testing.assert_allclose(a, b, atol=1e-3, rtol=1e-2)
    monkeypatch.setenv('MXNET_TPU_DIST_WIRE_DTYPE', 'bf16')
    nb, _ = _train_gluon(_ctxs(4), pipeline=(2, 2))
    for a, b in zip(baseline, _pvals(nb)):
        np.testing.assert_allclose(a, b, atol=1e-3, rtol=1e-2)


def test_gluon_pipe_env_knob(monkeypatch):
    monkeypatch.setenv('MXNET_TPU_PIPE', '2,2')
    net = _make_net(ctx=_ctxs(4))
    tr = gluon.Trainer(net.collect_params(), 'sgd', dict(OPT))
    fs = gluon.fuse_step(net, LOSS, tr)
    from mxnet_tpu.gluon.fused import PipelinedStep
    assert isinstance(fs, PipelinedStep)


def test_pipe_spec_validation():
    assert pipe_mod.pipe_spec((2, 4)) == (2, 4)
    assert pipe_mod.pipe_spec(None) is None
    with pytest.raises(ValueError):
        pipe_mod.pipe_spec((1, 4))      # 1 stage = plain dp
    with pytest.raises(ValueError):
        pipe_mod.pipe_spec((2, 0))
    os.environ['MXNET_TPU_PIPE'] = '3'
    try:
        with pytest.raises(ValueError):
            pipe_mod.pipe_spec(None)
    finally:
        del os.environ['MXNET_TPU_PIPE']


def test_bubble_fraction_math():
    # (S-1)/(M+S-1): GPipe fill-drain
    assert pipe_mod.bubble_fraction(4, 1) == pytest.approx(3 / 4)
    assert pipe_mod.bubble_fraction(2, 6) == pytest.approx(1 / 7)


def test_gluon_pipe_rejections():
    ctx4 = _ctxs(4)
    net = _make_net(ctx=ctx4)
    tr = gluon.Trainer(net.collect_params(), 'sgd', dict(OPT))
    # metric/ema/checkpoint do not compose with the pipelined mode
    with pytest.raises(ValueError, match='does not compose'):
        gluon.fuse_step(net, LOSS, tr, pipeline=(2, 2),
                        metric=mx.metric.Accuracy())
    with pytest.raises(ValueError, match='does not compose'):
        gluon.fuse_step(net, LOSS, tr, pipeline=(2, 2), ema_decay=0.9)
    with pytest.raises(ValueError, match='loss'):
        gluon.fuse_step(net, None, tr, pipeline=(2, 2))
    # contexts must divide into stages
    net3 = _make_net(ctx=_ctxs(3))
    tr3 = gluon.Trainer(net3.collect_params(), 'sgd', dict(OPT))
    with pytest.raises(ValueError, match='divide'):
        gluon.fuse_step(net3, LOSS, tr3, pipeline=(2, 2))
    # batch must divide by dp * num_micro
    fs = gluon.fuse_step(net, LOSS, tr, pipeline=(2, 2))
    with pytest.raises(ValueError, match='must divide'):
        fs(mx.nd.array(np.zeros((6, FEAT), np.float32)),
           mx.nd.array(np.zeros((6,), np.float32)))


def test_gluon_pipe_heterogeneous_stages_rejected():
    """Structurally identical but functionally different body layers
    (relu vs tanh) must be caught by the traced-jaxpr homogeneity
    check before any program runs stage 0's math on stage 1's
    weights."""
    net = nn.HybridSequential()
    with net.name_scope():
        net.add(nn.Dense(UNITS, activation='relu', in_units=FEAT))
        net.add(nn.Dense(UNITS, activation='tanh', in_units=UNITS))
        net.add(nn.Dense(UNITS, activation='relu', in_units=UNITS))
        net.add(nn.Dense(NCLS, in_units=UNITS))
    net.initialize(ctx=_ctxs(4))
    tr = gluon.Trainer(net.collect_params(), 'sgd', dict(OPT))
    fs = gluon.fuse_step(net, LOSS, tr, pipeline=(2, 2))
    x, y = _batches(1)[0]
    with pytest.raises(ValueError,
                       match='different computation|identical'):
        fs(x, y)


def test_gluon_pipe_aux_params_rejected():
    """BatchNorm running stats (grad_req=null aux state) are not
    composed with the pipelined schedule — loud error, not silent
    garbage."""
    net = nn.HybridSequential()
    with net.name_scope():
        net.add(nn.Dense(UNITS, in_units=FEAT))
        net.add(nn.BatchNorm(in_channels=UNITS))
        net.add(nn.BatchNorm(in_channels=UNITS))
        net.add(nn.Dense(NCLS, in_units=UNITS))
    net.initialize(ctx=_ctxs(4))
    tr = gluon.Trainer(net.collect_params(), 'sgd', dict(OPT))
    fs = gluon.fuse_step(net, LOSS, tr, pipeline=(2, 2))
    x, y = _batches(1)[0]
    with pytest.raises(ValueError, match='grad_req=null|aux'):
        fs(x, y)


def test_pipe_profiler_counters():
    profiler.clear()
    profiler.profiler_set_state('run')
    try:
        _train_gluon(_ctxs(4), pipeline=(2, 2), k=2)
    finally:
        profiler.profiler_set_state('stop')
    st = profiler.pipe_stats()
    assert st['pipe_dispatches'] == 2
    assert st['pipe_steps'] == 2
    assert st['pipe_stages'] == 2 and st['pipe_num_micro'] == 2
    assert st['pipe_microbatches'] == 4
    assert st['pipe_bubble_frac'] == pytest.approx(
        pipe_mod.bubble_fraction(2, 2))
    assert st['pipe_param_bytes_per_device'] > 0
    assert st['pipe_state_bytes_per_device'] > 0
    text = profiler.summary(print_out=False)
    assert 'pipe_dispatches=2' in text
    import json
    import tempfile
    fname = os.path.join(tempfile.mkdtemp(), 'prof.json')
    profiler.profiler_set_config(filename=fname)
    profiler.dump_profile()
    with open(fname) as f:
        events = json.load(f)['traceEvents']
    lanes = {e.get('name'): e.get('args') for e in events
             if e.get('ph') == 'M'}
    assert lanes['pipeline']['pipe_steps'] == 2
    assert 'moe_routed_tokens' in lanes['moe']


# ---------------------------------------------------------------------------
# Module.fit(pipeline=)
# ---------------------------------------------------------------------------

def _chain_symbol():
    d = S.Variable('data')
    h = S.FullyConnected(d, name='stem', num_hidden=UNITS)
    h = S.Activation(h, act_type='relu')
    for i in range(4):
        h = S.FullyConnected(h, name='body%d' % i, num_hidden=UNITS)
        h = S.Activation(h, act_type='tanh')
    h = S.FullyConnected(h, name='out', num_hidden=NCLS)
    return S.SoftmaxOutput(h, name='softmax')


@pytest.fixture(scope='module')
def chain_setup():
    sym = _chain_symbol()
    arg_shapes, _, _ = sym.infer_shape(data=(BATCH, FEAT))
    rs = np.random.RandomState(5)
    args = {n: mx.nd.array((rs.rand(*s).astype(np.float32) - 0.5) * 0.4)
            for n, s in zip(sym.list_arguments(), arg_shapes)
            if n not in ('data', 'softmax_label')}
    bs = _batches(3)
    X = np.concatenate([x.asnumpy() for x, _ in bs])
    y = np.concatenate([y.asnumpy() for _, y in bs])
    return sym, args, X, y


def _fit_module(chain_setup, ctx, pipeline=None, bulk=None):
    sym, args, X, y = chain_setup
    it = mx.io.NDArrayIter(X, y, batch_size=BATCH)
    mod = mx.mod.Module(sym, context=ctx)
    mod.fit(it, num_epoch=1, optimizer='sgd',
            optimizer_params=dict(OPT),
            arg_params={k: v.copy() for k, v in args.items()},
            initializer=None, pipeline=pipeline, bulk=bulk)
    ap, _ = mod.get_params()
    return {k: v.asnumpy() for k, v in sorted(ap.items())}


@pytest.fixture(scope='module')
def module_baseline(chain_setup):
    return _fit_module(chain_setup, mx.cpu(0))


def test_module_fit_pipeline_parity(chain_setup, module_baseline):
    got = _fit_module(chain_setup, _ctxs(4), pipeline=(2, 2))
    for k in module_baseline:
        np.testing.assert_allclose(module_baseline[k], got[k],
                                   atol=3e-6, rtol=1e-4, err_msg=k)


def test_module_fit_pipeline_bulk(chain_setup, module_baseline):
    got = _fit_module(chain_setup, _ctxs(4), pipeline=(2, 2), bulk=3)
    for k in module_baseline:
        np.testing.assert_allclose(module_baseline[k], got[k],
                                   atol=3e-6, rtol=1e-4, err_msg=k)


def test_module_fit_pipeline_rejections(chain_setup):
    sym, args, X, y = chain_setup
    it = mx.io.NDArrayIter(X, y, batch_size=BATCH)
    mod = mx.mod.Module(sym, context=_ctxs(4))
    with pytest.raises(ValueError, match='does not compose'):
        mod.fit(it, num_epoch=1, pipeline=(2, 2),
                monitor=mx.monitor.Monitor(1))
    # a branching (non-chain) symbol cannot partition
    d = S.Variable('data')
    a = S.FullyConnected(d, name='a', num_hidden=UNITS)
    b = S.FullyConnected(d, name='b', num_hidden=UNITS)
    net = S.SoftmaxOutput(a + b, name='softmax')
    mod2 = mx.mod.Module(net, context=_ctxs(4))
    it.reset()
    with pytest.raises(MXNetError, match='chain|graph inputs'):
        mod2.fit(it, num_epoch=1, optimizer='sgd',
                 optimizer_params=dict(OPT), pipeline=(2, 2))


def test_module_pipeline_rejects_dist_kvstore(chain_setup):
    """The pipelined dispatch reduces only over its own mesh dp axis;
    a distributed kvstore must be refused loudly, not silently left
    out of the step (workers would diverge)."""
    import types
    sym, args, X, y = chain_setup
    mod = mx.mod.Module(sym, context=_ctxs(4))
    mod.bind(data_shapes=[mx.io.DataDesc('data', (BATCH, FEAT))],
             label_shapes=[mx.io.DataDesc('softmax_label', (BATCH,))])
    mod.init_params()
    mod.init_optimizer(optimizer='sgd', optimizer_params=dict(OPT))
    mod._kvstore = types.SimpleNamespace(type='dist_sync')
    from mxnet_tpu.module.pipeline_fit import ModulePipeTrainer
    with pytest.raises(MXNetError, match='kvstore'):
        ModulePipeTrainer(mod, (2, 2))


def test_bucketing_module_fit_pipeline_unsupported():
    """Only Module partitions into stages; the shared fit() entry must
    refuse loudly elsewhere (BaseModule._fit_pipeline default)."""
    def gen(key):
        return _chain_symbol(), ('data',), ('softmax_label',)
    bmod = mx.mod.BucketingModule(gen, default_bucket_key=BATCH,
                                  context=_ctxs(4))
    X = np.zeros((BATCH, FEAT), np.float32)
    it = mx.io.NDArrayIter(X, np.zeros((BATCH,), np.float32),
                           batch_size=BATCH)
    with pytest.raises(NotImplementedError, match='only supported'):
        bmod.fit(it, num_epoch=1, pipeline=(2, 2))


# ---------------------------------------------------------------------------
# expert-parallel MoE
# ---------------------------------------------------------------------------

def test_switch_route_counts():
    rs = np.random.RandomState(0)
    x = jnp.asarray(rs.randn(16, FEAT).astype(np.float32))
    w = jnp.asarray(rs.randn(FEAT, 4).astype(np.float32))
    cap = moe_mod.capacity_for(16, 4, 1.0)          # = 4
    assert cap == 4
    disp, comb, aux, (routed, dropped) = moe_mod.switch_route(
        x, w, 4, cap, with_counts=True)
    routed, dropped = np.asarray(routed), np.asarray(dropped)
    assert routed.shape == (4,) and dropped.shape == (4,)
    assert int(routed.sum() + dropped.sum()) == 16
    assert (routed <= cap).all()
    # ample capacity: nothing can drop
    _, _, _, (r2, d2) = moe_mod.switch_route(
        x, w, 4, 16, with_counts=True)
    assert int(np.asarray(d2).sum()) == 0
    assert int(np.asarray(r2).sum()) == 16


def _make_moe_net(ctx):
    net = nn.HybridSequential()
    with net.name_scope():
        net.add(nn.Dense(FEAT, activation='relu', in_units=FEAT))
        net.add(nn.MoE(FEAT, 2 * FEAT, num_experts=4,
                       capacity_factor=1.0))
        net.add(nn.Dense(NCLS, in_units=FEAT))
    net.initialize(ctx=ctx)
    rs = np.random.RandomState(9)
    for _, p in sorted(net.collect_params().items()):
        if p.grad_req == 'null':
            continue
        p.set_data(mx.nd.array(
            (rs.rand(*p.shape).astype(np.float32) - 0.5) * 0.4))
    return net


def _train_moe(ctx, k=3):
    net = _make_moe_net(ctx)
    tr = gluon.Trainer(net.collect_params(), 'sgd',
                       {'learning_rate': 0.05, 'momentum': 0.9})
    fs = gluon.fuse_step(net, LOSS, tr)
    losses = [fs(x, y) for x, y in _batches(k)]
    return net, losses


def test_moe_trains_with_counters():
    profiler.clear()
    profiler.profiler_set_state('run')
    try:
        net, losses = _train_moe(_ctxs(4))
    finally:
        profiler.profiler_set_state('stop')
    assert all(np.isfinite(l.asnumpy()).all() for l in losses)
    st = profiler.moe_stats()
    assert st['moe_dispatches'] == 3
    # every token either routed to an expert or dropped at capacity
    assert st['moe_routed_tokens'] + st['moe_dropped_tokens'] == \
        3 * BATCH
    per = st['moe_experts']
    assert sum(e['routed'] for e in per.values()) == \
        st['moe_routed_tokens']
    assert sum(e['dropped'] for e in per.values()) == \
        st['moe_dropped_tokens']
    assert 0.0 <= st['moe_drop_frac'] <= 1.0
    text = profiler.summary(print_out=False)
    assert 'moe_routed_tokens=%d' % st['moe_routed_tokens'] in text
    # the block's cumulative device-resident counts agree
    rc = dropped = 0
    for _, p in net.collect_params().items():
        if getattr(p, '_moe_counter', None) == 'routed':
            rc = int(p.list_data()[0].asnumpy().sum())
        elif getattr(p, '_moe_counter', None) == 'dropped':
            dropped = int(p.list_data()[0].asnumpy().sum())
    assert rc == st['moe_routed_tokens']
    assert dropped == st['moe_dropped_tokens']


def test_moe_mesh_vs_single_device_parity():
    ref, _ = _train_moe(mx.cpu(0), k=2)
    got, _ = _train_moe(_ctxs(4), k=2)
    for (n1, a), (n2, b) in zip(sorted(ref.collect_params().items()),
                                sorted(got.collect_params().items())):
        np.testing.assert_allclose(
            a.list_data()[0].asnumpy(), b.list_data()[0].asnumpy(),
            atol=3e-6, rtol=1e-4, err_msg=n1)


def test_moe_rejected_in_pipeline_mode():
    """MoE counter aux params don't compose with the pipelined
    schedule — must raise, not silently drop counts."""
    net = nn.HybridSequential()
    with net.name_scope():
        net.add(nn.MoE(FEAT, 2 * FEAT, num_experts=2))
        net.add(nn.MoE(FEAT, 2 * FEAT, num_experts=2))
        net.add(nn.Dense(NCLS, in_units=FEAT))
    net.initialize(ctx=_ctxs(4))
    tr = gluon.Trainer(net.collect_params(), 'sgd', dict(OPT))
    fs = gluon.fuse_step(net, LOSS, tr, pipeline=(2, 2))
    x, y = _batches(1)[0]
    with pytest.raises(ValueError, match='grad_req=null|aux'):
        fs(x, y)


# ---------------------------------------------------------------------------
# ring attention
# ---------------------------------------------------------------------------

def test_attention_ring_matches_full():
    B, H, T, D = 2, 2, 32, 8
    rs = np.random.RandomState(13)
    q, k, v = (jnp.asarray(rs.randn(B, H, T, D).astype(np.float32))
               for _ in range(3))
    ref = np.asarray(full_attention(q, k, v, causal=True))
    smesh = pmesh.make_mesh({'sp': 8})
    with pmesh.use_mesh(smesh):
        out = np.asarray(jax.jit(
            lambda a, b, c: attention(a, b, c, causal=True,
                                      impl='ring'))(q, k, v))
        # 'auto' picks ring on the active sp mesh
        auto = np.asarray(attention(q, k, v, causal=True))
    np.testing.assert_allclose(out, ref, atol=2e-6, rtol=1e-6)
    np.testing.assert_allclose(auto, ref, atol=2e-6, rtol=1e-6)
    # a custom scale must thread through to the ring path
    ref_s = np.asarray(full_attention(q, k, v, causal=True, scale=0.5))
    with pmesh.use_mesh(smesh):
        out_s = np.asarray(attention(q, k, v, causal=True, scale=0.5,
                                     impl='ring'))
    np.testing.assert_allclose(out_s, ref_s, atol=2e-6, rtol=1e-6)
    assert np.abs(ref_s - ref).max() > 1e-3    # scale actually bites


def test_attention_dispatch_rules():
    B, H, T, D = 1, 2, 8, 4
    rs = np.random.RandomState(3)
    q, k, v = (jnp.asarray(rs.randn(B, H, T, D).astype(np.float32))
               for _ in range(3))
    # no active mesh: auto falls back to the dense path
    ref = np.asarray(full_attention(q, k, v))
    np.testing.assert_array_equal(np.asarray(attention(q, k, v)), ref)
    with pytest.raises(ValueError, match='ring'):
        attention(q, k, v, impl='ring')
    with pytest.raises(ValueError, match='impl'):
        attention(q, k, v, impl='nope')
    # sp axis not dividing T: auto falls back, ring refuses
    smesh = pmesh.make_mesh({'sp': 8})
    with pmesh.use_mesh(smesh):
        qq = jnp.asarray(rs.randn(1, 2, 12, 4).astype(np.float32))
        np.testing.assert_allclose(
            np.asarray(attention(qq, qq, qq)),
            np.asarray(full_attention(qq, qq, qq)), atol=1e-6)
        with pytest.raises(ValueError, match='ring'):
            attention(qq, qq, qq, impl='ring')
