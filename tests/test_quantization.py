"""Low-precision stack (PERF round 17): the shared quantization core,
int8 serving (weight-storage quantization + parity gate), quantized
registry residency/paging, and the int8/bf16 collective wire format
with error feedback.  CPU-sized — every engine here is a tiny MLP."""
import os
import threading

import numpy as np
import pytest

import mxnet_tpu as mx
from mxnet_tpu import dist, exec_cache, nd, profiler, sym
from mxnet_tpu import quantization as Q
from mxnet_tpu.base import MXNetError
from mxnet_tpu.predictor import Predictor
from mxnet_tpu.quantization import (QuantConfig, QuantParityError,
                                    WireCodec)
from mxnet_tpu.serving_fleet import ModelRegistry


def _mlp(dim=64, hidden=128, classes=8):
    data = sym.Variable('data')
    x = sym.Activation(sym.FullyConnected(data, num_hidden=hidden,
                                          name='fc1'), act_type='relu')
    x = sym.FullyConnected(x, num_hidden=classes, name='fc2')
    return sym.SoftmaxOutput(x, name='softmax')


def _params(net, dim=64, seed=0, scale=0.2):
    probe = net.simple_bind(mx.cpu(), grad_req='null', data=(1, dim))
    rng = np.random.RandomState(seed)
    return {k: nd.array(rng.randn(*v.shape).astype(np.float32) * scale)
            for k, v in probe.arg_dict.items() if k != 'data'}


def _predictor(seed=0):
    net = _mlp()
    return Predictor(symbol=net, arg_params=_params(net, seed=seed),
                     input_shapes={'data': (1, 64)})


# ---------------------------------------------------------------------------
# core math
# ---------------------------------------------------------------------------

def test_symmetric_int8_round_trip_and_edges():
    rng = np.random.RandomState(0)
    a = rng.randn(16, 32).astype(np.float32)
    for axis in (None, 0):
        q, s = Q.quantize_int8(a, axis=axis)
        assert q.dtype == np.int8
        assert int(q.min()) >= -127          # -128 never produced
        back = Q.dequantize_int8(q, s, axis=axis)
        step = np.max(np.abs(a)) / 127.0
        assert np.abs(back - a).max() <= step / 2 + 1e-7
    # exact extremes land on the extreme codes
    e = np.array([3.0, -3.0, 0.0], np.float32)
    q, s = Q.quantize_int8(e)
    np.testing.assert_array_equal(q, [127, -127, 0])


def test_zero_range_quantizes_to_exact_zeros():
    z = np.zeros((3, 3), np.float32)
    q, s = Q.quantize_int8(z)
    assert float(s) == 0.0
    np.testing.assert_array_equal(q, np.zeros((3, 3), np.int8))
    np.testing.assert_array_equal(Q.dequantize_int8(q, s), z)


def test_per_channel_beats_per_tensor_on_skewed_channels():
    rng = np.random.RandomState(1)
    a = rng.randn(4, 256).astype(np.float32)
    a[0] *= 100.0                            # one hot output channel
    qt, st = Q.quantize_int8(a)
    qc, sc = Q.quantize_int8(a, axis=0)
    err_t = np.abs(Q.dequantize_int8(qt, st) - a)[1:].max()
    err_c = np.abs(Q.dequantize_int8(qc, sc, axis=0) - a)[1:].max()
    assert err_c < err_t / 10


def test_calibrate_modes():
    batches = [np.linspace(-1, 1, 100, dtype=np.float32),
               np.asarray([50.0], np.float32)]     # one outlier
    lo, hi = Q.calibrate(batches, 'minmax')
    assert hi == 50.0 and lo == -1.0
    lo_p, hi_p = Q.calibrate(batches, 'percentile', percentile=99.0)
    assert hi_p < 2.0                        # outlier clipped
    with pytest.raises(MXNetError):
        Q.calibrate(batches, 'bogus')
    with pytest.raises(MXNetError):
        Q.calibrate([])


def test_wire_codec_int8_roundtrip_bytes_and_ef():
    rng = np.random.RandomState(2)
    arrays = [rng.randn(500).astype(np.float32),
              rng.randn(8, 8).astype(np.float32)]
    c = WireCodec('int8')
    p, s = c.encode(arrays)
    assert all(x.dtype == np.int8 for x in p)
    wire = WireCodec.wire_nbytes(p, s)
    assert wire * 3.5 < sum(a.nbytes for a in arrays)
    dec = c.decode(p, s, [np.float32] * 2)
    step = max(np.abs(a).max() for a in arrays) / 127.0
    assert max(np.abs(a - d).max()
               for a, d in zip(arrays, dec)) <= step / 2 + 1e-7
    # error feedback: encoding the SAME value repeatedly averages the
    # quantization bias out (the residual carries it forward)
    # (a constant array would round-trip EXACTLY — every element sits
    # at the max, whose code is always exact — so spread the values)
    x = [np.linspace(0.001, 0.0123, 50).astype(np.float32)]
    c2 = WireCodec('int8')
    p, s = c2.encode(x)
    assert c2.residual_norm() > 0.0          # first round's error held
    tot = c2.decode(p, s, [np.float32])[0].astype(np.float64)
    for _ in range(63):
        p, s = c2.encode(x)
        tot += c2.decode(p, s, [np.float32])[0]
    assert np.abs(tot / 64 - x[0]).max() < 2e-5
    # shape change resets the residual stream, never corrupts
    c2.encode([np.zeros(7, np.float32)])
    with pytest.raises(MXNetError):
        WireCodec('int4')


def test_wire_codec_bf16_and_fp32():
    a = [np.asarray([1.0, 2.0, 3.0], np.float32)]
    c = WireCodec('bf16')
    p, s = c.encode(a)
    assert p[0].nbytes == 6 and s.size == 0
    np.testing.assert_allclose(c.decode(p, s, [np.float32])[0], a[0],
                               rtol=1e-2)
    c32 = WireCodec('fp32')
    p, s = c32.encode(a)
    np.testing.assert_array_equal(p[0], a[0])
    assert c32.residual_norm() == 0.0


# ---------------------------------------------------------------------------
# int8 serving (arm a)
# ---------------------------------------------------------------------------

def test_int8_engine_parity_residency_and_bitwise_recreation():
    x = np.random.RandomState(3).randn(2, 64).astype(np.float32)
    p_fp = _predictor(seed=4)
    eng_fp = p_fp.serve(max_batch=4, max_wait_us=0)
    fp_out = eng_fp.predict(x)
    fp_bytes = eng_fp.resident_bytes()
    eng_fp.close()

    eng = _predictor(seed=4).serve(max_batch=4, max_wait_us=0,
                                   quantize='int8')
    q_out = eng.predict(x)
    st = eng.stats()
    # parity: int8 weights move the outputs only within the gate tol
    assert np.abs(fp_out - q_out).max() < 0.05
    assert st['quantized']['dtype'] == 'int8'
    assert st['quantized']['parity_measured'] <= 0.05
    # residency: int8 codes + scales ~4x below the fp engine
    assert eng.resident_bytes() * 3 < fp_bytes
    assert st['compiles_after_warmup'] == 0
    eng.close()

    # re-created engine: zero new compiles, bitwise-identical answers
    c0 = exec_cache.stats()['total_compile_s']
    eng2 = _predictor(seed=4).serve(max_batch=4, max_wait_us=0,
                                    quantize='int8')
    q2 = eng2.predict(x)
    assert exec_cache.stats()['total_compile_s'] == c0
    np.testing.assert_array_equal(q_out, q2)
    eng2.close()


def test_int8_engine_batching_parity_within_bucket():
    # rows sliced out of one padded bucket dispatch must not depend
    # on what they were co-batched with (row independence survives
    # the dequant path) — compare AT THE SAME RUNG: a 3-row request
    # pads to bucket 4, and its rows must bitwise-match the same rows
    # inside a full 4-row batch (whose 4th row differs)
    eng = _predictor(seed=5).serve(max_batch=4, max_wait_us=0,
                                   quantize='int8')
    rng = np.random.RandomState(6)
    xs = rng.randn(4, 64).astype(np.float32)
    full = eng.predict(xs)
    padded = eng.predict(xs[:3])
    np.testing.assert_array_equal(full[:3], padded)
    eng.close()


def test_parity_gate_refuses_and_mutates_nothing():
    pred = _predictor(seed=7)
    before = pred._executor.arg_dict['fc1_weight'].asnumpy().copy()
    with pytest.raises(QuantParityError):
        pred.serve(max_batch=4, quantize=QuantConfig(parity_tol=0.0))
    after = pred._executor.arg_dict['fc1_weight']
    assert np.dtype(after.dtype) == np.float32
    np.testing.assert_array_equal(before, after.asnumpy())
    # the refused predictor still serves fp
    eng = pred.serve(max_batch=4, max_wait_us=0)
    eng.predict(np.zeros((1, 64), np.float32))
    eng.close()


def test_quantize_rejects_model_without_quantizable_weights():
    data = sym.Variable('data')
    net = sym.SoftmaxOutput(
        sym.FullyConnected(data, num_hidden=2, name='t'), name='softmax')
    probe = net.simple_bind(mx.cpu(), grad_req='null', data=(1, 4))
    args = {k: nd.array(np.ones(v.shape, np.float32) * 0.1)
            for k, v in probe.arg_dict.items() if k != 'data'}
    pred = Predictor(symbol=net, arg_params=args,
                     input_shapes={'data': (1, 4)})
    with pytest.raises(MXNetError, match='no quantizable'):
        pred.serve(max_batch=2, quantize='int8')


def test_bf16_engine_mode():
    x = np.random.RandomState(8).randn(1, 64).astype(np.float32)
    p_fp = _predictor(seed=9)
    eng_fp = p_fp.serve(max_batch=2, max_wait_us=0)
    fp_out = eng_fp.predict(x)
    fp_bytes = eng_fp.resident_bytes()
    eng_fp.close()
    eng = _predictor(seed=9).serve(max_batch=2, max_wait_us=0,
                                   quantize='bf16')
    out = eng.predict(x)
    assert np.abs(fp_out - out).max() < 0.05
    assert eng.resident_bytes() * 1.5 < fp_bytes
    eng.close()


def test_quant_config_resolve_and_env_default(monkeypatch):
    assert QuantConfig.resolve(None) is None
    cfg = QuantConfig.resolve('int8')
    assert isinstance(cfg, QuantConfig) and cfg.dtype == 'int8'
    assert QuantConfig.resolve(cfg) is cfg
    with pytest.raises(MXNetError):
        QuantConfig.resolve('fp8')
    monkeypatch.setenv('MXNET_TPU_SERVE_QUANTIZE', 'int8')
    eng = _predictor(seed=10).serve(max_batch=2, max_wait_us=0)
    assert eng._quant_live
    eng.close()
    # disable-style env values mean OFF, not a crash
    for off in ('0', 'off', 'none', 'fp32'):
        monkeypatch.setenv('MXNET_TPU_SERVE_QUANTIZE', off)
        eng = _predictor(seed=10).serve(max_batch=2, max_wait_us=0)
        assert not eng._quant_live
        eng.close()


# ---------------------------------------------------------------------------
# quantized registry (arm b)
# ---------------------------------------------------------------------------

@pytest.fixture
def checkpoints(tmp_path):
    from mxnet_tpu.module import Module
    prefixes = []
    for i in range(3):
        net = _mlp()
        m = Module(net, data_names=['data'],
                   label_names=['softmax_label'], context=mx.cpu())
        m.bind(data_shapes=[('data', (4, 64))],
               label_shapes=[('softmax_label', (4,))])
        m.init_params(mx.init.Normal(0.2 + 0.01 * i))
        prefix = str(tmp_path / ('m%d' % i))
        m.save_checkpoint(prefix, 0)
        prefixes.append(prefix)
    return prefixes


def test_registry_quantized_residency_multiplier(checkpoints):
    fp_size = os.path.getsize(checkpoints[0] + '-0000.params')
    budget = int(fp_size * 1.2)              # fits ONE fp model
    x = np.random.RandomState(0).randn(1, 64).astype(np.float32)

    reg = ModelRegistry(budget_bytes=budget)
    for i, p in enumerate(checkpoints):
        reg.register('m%d' % i, prefix=p, epoch=0,
                      input_shapes={'data': (1, 64)}, max_batch=4)
    for i in range(3):
        reg.predict('m%d' % i, x)
    st = reg.stats()
    assert sum(1 for m in st['models'].values() if m['resident']) == 1
    assert st['evictions'] == 2
    reg.close()

    reg2 = ModelRegistry(budget_bytes=budget)
    for i, p in enumerate(checkpoints):
        reg2.register('q%d' % i, prefix=p, epoch=0,
                      input_shapes={'data': (1, 64)}, max_batch=4,
                      quantize='int8')
    for i in range(3):
        reg2.predict('q%d' % i, x)
    st = reg2.stats()
    # >= 2x more models live under the SAME budget (measured ~3.6x
    # per-model byte ratio, so all 3 fit)
    assert sum(1 for m in st['models'].values() if m['resident']) == 3
    assert st['evictions'] == 0
    assert st['resident_bytes'] <= budget
    # est_bytes honesty: the pre-load estimate counts the QUANTIZED
    # representation (satellite fix) — with fp32-file estimates the
    # strict budget would have refused the 2nd model
    assert st['peak_resident_bytes'] <= budget
    assert profiler.quant_stats()['quant_models_resident'] == 3
    # evict/re-warm a quantized model: zero new XLA compiles
    c0 = exec_cache.stats()['total_compile_s']
    reg2.evict('q0')
    reg2.predict('q0', x)
    assert exec_cache.stats()['total_compile_s'] == c0
    reg2.close()


def test_registry_strict_budget_uses_quantized_estimate(checkpoints,
                                                        monkeypatch):
    monkeypatch.setenv('MXNET_TPU_SERVE_STRICT_BUDGET', '1')
    fp_size = os.path.getsize(checkpoints[0] + '-0000.params')
    x = np.zeros((1, 64), np.float32)
    # budget below ONE fp32 file but above the int8 estimate: a
    # fp32-file estimate would 507 before even loading
    reg = ModelRegistry(budget_bytes=int(fp_size * 0.45))
    reg.register('q', prefix=checkpoints[0], epoch=0,
                 input_shapes={'data': (1, 64)}, max_batch=4,
                 quantize='int8')
    reg.predict('q', x)                      # loads fine
    st = reg.stats()
    assert st['models']['q']['resident']
    assert st['resident_bytes'] <= reg.budget_bytes
    reg.close()


def test_registry_page_dtype_round_trip(checkpoints):
    x = np.random.RandomState(1).randn(1, 64).astype(np.float32)
    reg = ModelRegistry()
    reg.register('p', prefix=checkpoints[0], epoch=0,
                 input_shapes={'data': (1, 64)}, max_batch=4,
                 page_dtype='int8')
    y1 = reg.predict('p', x)
    reg.evict('p')
    st = reg.stats()
    fp_size = os.path.getsize(checkpoints[0] + '-0000.params')
    assert 0 < st['paged_bytes'] < fp_size / 2
    assert st['models']['p']['paged']
    y2 = reg.predict('p', x)                 # page-in from the image
    st = reg.stats()
    assert st['page_ins'] == 1
    assert st['paged_bytes'] == 0            # image consumed
    # int8 round trip through the image moves outputs only slightly
    assert np.abs(np.asarray(y1) - np.asarray(y2)).max() < 0.05
    assert profiler.quant_stats()['quant_page_ins'] >= 1
    reg.close()


def test_registry_page_dtype_validation(checkpoints):
    reg = ModelRegistry()
    with pytest.raises(MXNetError, match='prefix'):
        reg.register('a', loader=lambda: None, page_dtype='int8')
    with pytest.raises(MXNetError, match='exclusive'):
        reg.register('b', prefix=checkpoints[0], epoch=0,
                     input_shapes={'data': (1, 64)},
                     quantize='int8', page_dtype='int8')
    reg.close()


def test_registry_env_quantize_respects_page_dtype(checkpoints,
                                                   monkeypatch):
    # the fleet-wide MXNET_TPU_SERVE_QUANTIZE default must resolve in
    # register(), not behind the registry's back in the engine: a
    # page_dtype model's holder weights must stay fp for the page-out
    # snapshot (env-quantizing them would image raw int8 codes as
    # 'fp' passthrough arrays — garbage on page-in), while a plain
    # model picks the env default up WITH the scaled byte estimate
    monkeypatch.setenv('MXNET_TPU_SERVE_QUANTIZE', 'int8')
    x = np.zeros((1, 64), np.float32)
    reg = ModelRegistry()
    reg.register('p', prefix=checkpoints[0], epoch=0,
                 input_shapes={'data': (1, 64)}, max_batch=4,
                 page_dtype='int8')
    reg.register('q', prefix=checkpoints[1], epoch=0,
                 input_shapes={'data': (1, 64)}, max_batch=4)
    y1 = reg.predict('p', x)
    ent = reg._entry('p')
    assert not ent.engine._quant_live        # env knob did NOT apply
    assert np.dtype(ent.holder._executor.arg_dict['fc1_weight'].dtype) \
        == np.float32
    reg.predict('q', x)
    assert reg._entry('q').engine._quant_live  # plain model DID
    fp_file = os.path.getsize(checkpoints[1] + '-0000.params')
    assert reg._entry('q').bytes < fp_file / 2  # measured, quantized
    reg.evict('p')
    y2 = reg.predict('p', x)                 # page round trip intact
    assert np.abs(np.asarray(y1) - np.asarray(y2)).max() < 0.05
    reg.close()


def test_registry_paged_budget_drops_oldest(checkpoints, monkeypatch):
    x = np.zeros((1, 64), np.float32)
    reg = ModelRegistry()
    for i, p in enumerate(checkpoints[:2]):
        reg.register('p%d' % i, prefix=p, epoch=0,
                     input_shapes={'data': (1, 64)}, max_batch=4,
                     page_dtype='int8')
    reg.predict('p0', x)
    reg.evict('p0')
    one_image = reg.stats()['paged_bytes']
    assert one_image > 0
    # budget for exactly one image: paging the second drops the first
    monkeypatch.setenv('MXNET_TPU_SERVE_PAGED_BYTES',
                       str(int(one_image * 1.5)))
    reg.predict('p1', x)
    reg.evict('p1')
    st = reg.stats()
    assert st['page_drops'] == 1
    assert st['models']['p1']['paged'] and not st['models']['p0']['paged']
    reg.close()


# ---------------------------------------------------------------------------
# collective wire format (arm c)
# ---------------------------------------------------------------------------

def _dist_pair():
    coord = dist.Coordinator(port=0, world=2, bind_addr='127.0.0.1',
                             dead_after=10).start()
    rts = [None, None]
    errs = [None, None]

    def mk(r):
        try:
            rts[r] = dist.DistRuntime(
                r, 2, address='127.0.0.1', port=coord.port,
                start_coordinator=False, timeout=15, hb_interval=0.2)
        except BaseException as e:
            errs[r] = e
    ts = [threading.Thread(target=mk, args=(r,)) for r in (0, 1)]
    for t in ts:
        t.start()
    for t in ts:
        t.join()
    assert all(e is None for e in errs), errs
    return coord, rts


def test_dist_allreduce_int8_wire_deterministic_and_4x():
    coord, rts = _dist_pair()
    try:
        results = {}

        def work(rank):
            rng = np.random.RandomState(rank)
            outs = []
            for step in range(4):
                arrays = [rng.randn(1000).astype(np.float32),
                          rng.randn(16, 16).astype(np.float32)]
                outs.append(rts[rank].allreduce(arrays, name='t',
                                                wire='int8'))
            results[rank] = outs
        b0 = profiler.dist_stats()['dist_allreduce_bytes']
        ts = [threading.Thread(target=work, args=(r,)) for r in (0, 1)]
        for t in ts:
            t.start()
        for t in ts:
            t.join(timeout=60)
        assert set(results) == {0, 1}
        # every rank decodes the identical compressed bytes
        for s in range(4):
            for a, b in zip(results[0][s], results[1][s]):
                np.testing.assert_array_equal(a, b)
        # the counter records ACTUAL wire bytes: ~4x below fp32
        wire = profiler.dist_stats()['dist_allreduce_bytes'] - b0
        fp = (1000 * 4 + 16 * 16 * 4) * 2 * 2 * 4
        assert wire * 3.5 < fp
        qs = profiler.quant_stats()
        assert qs['quant_wire_bytes_saved'] > 0
        assert qs['quant_error_feedback_norm'] > 0.0
    finally:
        for rt in reversed(rts):
            rt.shutdown()
        coord.stop()


def test_dist_allreduce_wire_error_feedback_converges():
    coord, rts = _dist_pair()
    try:
        sums = {}

        def work(rank):
            acc = np.zeros(64)
            val = np.full(64, 0.00789 * (rank + 1), np.float32)
            for _ in range(32):
                acc += rts[rank].allreduce([val], name='ef',
                                           wire='int8')[0]
            sums[rank] = acc
        ts = [threading.Thread(target=work, args=(r,)) for r in (0, 1)]
        for t in ts:
            t.start()
        for t in ts:
            t.join(timeout=60)
        exact = 32 * (0.00789 + 2 * 0.00789)
        # EF cancels the per-round quantization bias: the 32-round
        # accumulation lands within a fraction of ONE round's step
        assert np.abs(sums[0] - exact).max() < 5e-4
    finally:
        for rt in reversed(rts):
            rt.shutdown()
        coord.stop()


def test_dist_allreduce_wire_mismatch_and_bf16():
    coord, rts = _dist_pair()
    try:
        res = {}

        def work(rank, wire, name):
            try:
                res[rank] = rts[rank].allreduce(
                    [np.ones(8, np.float32) * (rank + 1)],
                    name=name, wire=wire)
            except MXNetError as e:
                res[rank] = e
        # bf16 wire sums fine
        ts = [threading.Thread(target=work, args=(r, 'bf16', 'b'))
              for r in (0, 1)]
        for t in ts:
            t.start()
        for t in ts:
            t.join(timeout=30)
        np.testing.assert_allclose(res[0][0], np.full(8, 3.0), rtol=1e-2)
        # mismatched wire modes fail typed, naming the knob
        ts = [threading.Thread(target=work,
                               args=(r, 'int8' if r == 0 else 'fp32',
                                     'mm'))
              for r in (0, 1)]
        for t in ts:
            t.start()
        for t in ts:
            t.join(timeout=30)
        assert any(isinstance(res[r], MXNetError) and
                   'WIRE_DTYPE' in str(res[r]) for r in (0, 1))
    finally:
        for rt in reversed(rts):
            rt.shutdown()
        coord.stop()


def test_quantized_allreduce_shardmap_parity():
    import jax
    import jax.numpy as jnp
    from jax.sharding import PartitionSpec as P
    from mxnet_tpu.parallel import collectives
    from mxnet_tpu.parallel._compat import shard_map
    from mxnet_tpu.parallel.mesh import make_mesh
    mesh = make_mesh()                       # all 8 virtual devices
    rng = np.random.RandomState(4)
    x = rng.randn(8, 32).astype(np.float32)

    def f(xs):
        return collectives.quantized_allreduce(xs, 'data')

    out = jax.jit(shard_map(f, mesh=mesh, in_specs=P('data'),
                            out_specs=P('data')))(jnp.asarray(x))
    # per-shard int8 quantization: each row's contribution rounds to
    # its own scale's grid; the sum of 8 shards stays within the sum
    # of half-steps of the true allreduce
    exact = x.sum(axis=0)
    tol = sum(np.abs(x[i]).max() / 127.0 / 2 for i in range(8)) + 1e-6
    got = np.asarray(out)
    for i in range(8):                       # identical on every shard
        np.testing.assert_array_equal(got[i], got[0])
    assert np.abs(got[0] - exact).max() <= tol


def test_wire_dtype_from_env(monkeypatch):
    assert Q.wire_dtype_from_env(None) == 'fp32'
    monkeypatch.setenv('MXNET_TPU_DIST_WIRE_DTYPE', 'int8')
    assert Q.wire_dtype_from_env(None) == 'int8'
    assert Q.wire_dtype_from_env('bf16') == 'bf16'   # explicit wins
    monkeypatch.setenv('MXNET_TPU_DIST_WIRE_DTYPE', 'nope')
    with pytest.raises(MXNetError):
        Q.wire_dtype_from_env(None)


# ---------------------------------------------------------------------------
# counters
# ---------------------------------------------------------------------------

def test_quant_counters_in_summary_and_dump(tmp_path):
    profiler.add_quant_stats(int8_rungs_warmed=2, wire_bytes_saved=100,
                             models_resident=1,
                             error_feedback_norm=0.5, page_ins=1,
                             paged_bytes=64)
    st = profiler.quant_stats()
    assert st['quant_int8_rungs_warmed'] >= 2
    assert st['quant_models_resident'] == 1
    assert st['quant_error_feedback_norm'] == 0.5
    text = profiler.summary(print_out=False)
    for key in ('quant_models_resident', 'quant_int8_rungs_warmed',
                'quant_wire_bytes_saved', 'quant_error_feedback_norm',
                'quant_page_ins', 'quant_paged_bytes'):
        assert key in text
    import json
    profiler.profiler_set_config(filename=str(tmp_path / 'p.json'))
    profiler.profiler_set_state('run')
    profiler.profiler_set_state('stop')
    path = profiler.dump_profile()
    lanes = {e.get('name'): e for e in
             json.load(open(path))['traceEvents'] if e.get('ph') == 'M'}
    assert 'quant' in lanes
    assert 'quant_wire_bytes_saved' in lanes['quant']['args']
    profiler.clear()
    assert profiler.quant_stats()['quant_models_resident'] == 0
