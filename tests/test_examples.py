"""Smoke checks for the examples/ ports (reference test strategy: each
example is an end-to-end regression of one distinct API surface —
input-gradient attacks, input optimization, embeddings, checkpoint
surgery)."""
import importlib.util
import os
import sys

import pytest

REPO = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))


def _load(relpath, name):
    spec = importlib.util.spec_from_file_location(
        name, os.path.join(REPO, relpath))
    mod = importlib.util.module_from_spec(spec)
    sys.modules[name] = mod
    spec.loader.exec_module(mod)
    return mod


@pytest.mark.slow
def test_fgsm_adversary():
    # slow (~7s, round-16 headroom): executor gradient access (the
    # attack's input-grad read) stays tier-1 via test_executor and
    # test_autograd; classifier training via test_multi_task
    mod = _load('examples/adversary/fgsm.py', 'ex_fgsm')
    clean, adv = mod.main(quick=True)
    assert clean > 0.9, clean
    assert adv < clean - 0.2, (clean, adv)


def test_matrix_factorization():
    mod = _load('examples/recommender/matrix_factorization.py', 'ex_mf')
    rmse, baseline = mod.main(quick=True)
    assert rmse < 0.6 * baseline, (rmse, baseline)


def test_neural_style():
    mod = _load('examples/neural_style/neural_style.py', 'ex_style')
    first, last = mod.main(quick=True)
    assert last < 0.5 * first, (first, last)


@pytest.mark.slow
def test_finetune():
    # slow (~17s, round-11 headroom): checkpoint load + layer freeze +
    # fit stays tier-1 via test_train.test_fused_sgd_state_roundtrip,
    # test_module set_params/save_checkpoint, and the gluon
    # frozen-params test (test_gluon_fused)
    mod = _load('examples/finetune/finetune.py', 'ex_finetune')
    base, head, full = mod.main(quick=True)
    assert base > 0.9, base
    assert full > 0.9, full
    assert head > 0.5, head


@pytest.mark.slow
def test_bi_lstm_sort():
    # slow (~29s): bidirectional-LSTM training itself is tier-1
    # covered by test_gluon_rnn/test_rnn; this end-to-end example
    # regression runs in full CI
    mod = _load('examples/bi_lstm_sort/sort.py', 'ex_sort')
    acc = mod.main(quick=True)
    assert acc > 0.8, acc


@pytest.mark.slow
def test_autoencoder():
    # slow (~6s, round-16 headroom): regression-objective MLP training
    # stays tier-1 via test_csv_tabular and
    # test_matrix_factorization (reconstruction-style objectives)
    mod = _load('examples/autoencoder/autoencoder.py', 'ex_ae')
    mse, var = mod.main(quick=True)
    assert mse < 0.05 * var, (mse, var)


def test_numpy_custom_op():
    mod = _load('examples/numpy_ops/custom_softmax.py', 'ex_npop')
    acc = mod.main(quick=True)
    assert acc > 0.9, acc


def test_multi_task():
    mod = _load('examples/multi_task/multi_task.py', 'ex_mt')
    scores = mod.main(quick=True)
    assert scores['accuracy'] > 0.9, scores
    assert scores['rmse'] < 0.5, scores


@pytest.mark.slow
def test_sgld_regression():
    # slow (~7s, round-16 headroom): custom-optimizer registration +
    # update math stay tier-1 via test_dsd_training's optimizer
    # subclass and test_train's optimizer round-trips; regression
    # training via test_csv_tabular
    mod = _load('examples/bayesian_methods/sgld_regression.py', 'ex_sgld')
    mu_err, sd, ratio = mod.main(quick=True)
    assert mu_err < 6 * sd, (mu_err, sd)
    assert 0.3 < ratio < 3.0, ratio


def test_csv_tabular():
    mod = _load('examples/csv_tabular/csv_train.py', 'ex_csv')
    acc = mod.main(quick=True)
    assert acc > 0.9, acc


def test_profiling_example():
    mod = _load('examples/profiling/profile_training.py', 'ex_prof')
    spans, seen = mod.main(quick=True)
    assert spans > 0, spans
    assert seen, seen


@pytest.mark.slow
def test_lstm_ocr_ctc():
    """LSTM + CTC (reference example/ctc/lstm_ocr.py role): greedy
    decode must read >70% of held-out digit sequences exactly.

    slow (~34s, round-14 headroom): CTC loss gradients stay tier-1 via
    test_contrib::test_ctc_loss_grad_flows and LSTM training via
    test_rnn::test_lstm_bucketing_training + test_gluon_rnn; this
    end-to-end OCR regression (the round-9 keeper for captcha_ocr)
    runs in full CI alongside it."""
    mod = _load('examples/ctc/lstm_ocr.py', 'ex_ctc')
    acc = mod.main(quick=True)
    assert acc > 0.7, acc


@pytest.mark.slow
def test_fcn_segmentation():
    """FCN upsample pipeline (reference example/fcn-xs role):
    Deconvolution + Crop + per-pixel softmax must beat the
    all-background baseline by 10 points and reach 0.9.

    slow (~38s, round-14 headroom): Deconvolution/Crop op+grad
    behavior stays tier-1 via test_op_conformance (both cases) and
    conv training via test_train::test_conv_fit_convergence +
    test_ssd; the end-to-end segmentation regression runs in full
    CI."""
    mod = _load('examples/fcn_xs/fcn_seg.py', 'ex_fcn')
    acc, bg = mod.main(quick=True)
    assert acc > max(0.9, bg + 0.1), (acc, bg)


@pytest.mark.slow
def test_nce_word_vectors():
    """NCE word vectors (reference example/nce-loss role): same-cluster
    retrieval precision@5 far above chance.

    slow (~10s, round-14 headroom): Embedding op+grad behavior stays
    tier-1 via test_op_conformance ('Embedding', grad-checked) and
    test_ndarray::test_take_embedding_onehot; the retrieval-quality
    regression runs in full CI."""
    mod = _load('examples/nce_loss/nce_words.py', 'ex_nce')
    prec = mod.main(quick=True)
    assert prec > 0.5, prec


@pytest.mark.slow
def test_cnn_text_classification():
    """TextCNN (reference example/cnn_text_classification role): the
    planted-bigram sentiment task needs the conv filters' locality —
    bag-of-words can't solve it.

    slow (~16s, round-11 headroom): Embedding+Conv training stays
    tier-1 via test_op_conformance ('Embedding', grad-checked) and the
    conv fit-convergence test (test_train)."""
    mod = _load('examples/cnn_text/text_cnn.py', 'ex_textcnn')
    acc = mod.main(quick=True)
    assert acc > 0.9, acc


@pytest.mark.slow
def test_actor_critic_rl():
    """Policy-gradient actor-critic (reference reinforcement-learning
    role): the imperative autograd loop must drive the chain MDP to
    near-optimal return.

    slow (~32s, round-14 headroom): the imperative autograd training
    loop stays tier-1 via test_autograd (tape/backward coverage) and
    test_gluon::test_hybridize_backward; the RL convergence
    regression runs in full CI."""
    mod = _load('examples/reinforcement_learning/actor_critic.py',
                'ex_rl')
    first, last = mod.main(quick=True)
    assert last > 0.7, (first, last)


@pytest.mark.slow
def test_faster_rcnn():
    """Two-stage detection (reference example/rcnn/): RPN with
    IoU-assigned anchor targets, Proposal + ROIPooling + smooth_l1,
    and the end-to-end backbone->RPN->Proposal->heads test graph.

    slow (~38s): Proposal/ROIPooling/multibox op behavior stays
    tier-1 in test_contrib/test_ssd/test_image_io; this end-to-end
    training regression runs in full CI."""
    mod = _load('examples/rcnn/train_faster_rcnn.py', 'ex_rcnn')
    rpn_recall, det_acc = mod.main(quick=True)
    assert rpn_recall > 0.8, rpn_recall
    assert det_acc > 0.7, det_acc


@pytest.mark.slow
def test_svm_mnist():
    """SVMOutput consumer (reference example/svm_mnist): both hinge
    objectives must learn; margins must actually separate.

    slow (~14s, round-14 headroom): SVMOutput op behavior stays
    tier-1 via test_operator_extra's hinge-loss test and
    test_op_conformance ('SVMOutput'); the end-to-end convergence
    regression runs in full CI."""
    mod = _load('examples/svm_mnist/svm_mnist.py', 'ex_svm')
    acc_l2, acc_l1, margin = mod.main(quick=True)
    assert acc_l2 > 0.9, acc_l2
    assert acc_l1 > 0.9, acc_l1
    assert margin > 0.7, margin


@pytest.mark.slow
def test_stochastic_depth():
    """User-defined BaseModule subclass inside SequentialModule
    (reference example/stochastic-depth): converges, gate statistics
    follow the death-rate schedule, expectation inference is
    deterministic.

    slow (~16s, round-11 headroom): SequentialModule training stays
    tier-1 via test_module's sequential coverage; the stochastic gate
    is example-specific composition."""
    mod = _load('examples/stochastic_depth/sd_mnist.py', 'ex_sd')
    acc, gate_err, determ = mod.main(quick=True)
    assert acc > 0.9, acc
    assert gate_err < 0.15, gate_err
    assert determ == 0.0, determ


@pytest.mark.slow
def test_dec_clustering():
    """Deep Embedded Clustering (reference example/dec): symbolic
    t-kernel soft assignment + KL refinement must not degrade the
    k-means init and must exceed 0.9 cluster accuracy.

    slow (~15s, round-14 headroom): the autoencoder pretrain path DEC
    builds on stays tier-1 via test_autoencoder; the seed-pinned
    clustering-accuracy regression (round-9 deflake note) runs in
    full CI."""
    mod = _load('examples/dec/dec.py', 'ex_dec')
    init_acc, final_acc = mod.main(quick=True)
    assert final_acc >= init_acc, (init_acc, final_acc)
    assert final_acc > 0.9, final_acc


@pytest.mark.slow
def test_captcha_ocr():
    """Multi-head captcha OCR (reference example/captcha): joint
    4-head Group training; sequence accuracy is the gate.

    slow (~27s): multi-output Group training stays tier-1 via
    test_multi_task and the CTC OCR path via test_lstm_ocr_ctc; this
    end-to-end example regression runs in full CI."""
    mod = _load('examples/captcha/captcha_ocr.py', 'ex_captcha')
    digit_acc, seq_acc = mod.main(quick=True)
    assert digit_acc > 0.93, digit_acc
    assert seq_acc > 0.8, seq_acc


def test_memcost():
    """Compiled-module memory census (reference example/memcost):
    backward temp memory is a multiple of inference temp memory and
    rematerialization never increases it."""
    mod = _load('examples/memcost/memcost.py', 'ex_memcost')
    fwd, bwd, remat = mod.main(quick=True)
    assert bwd > 2 * fwd, (fwd, bwd)
    assert remat <= bwd, (remat, bwd)


@pytest.mark.slow
def test_rnn_time_major():
    """Time-major unroll (reference example/rnn-time-major): layout
    parity in accuracy and exact cross-layout forward equivalence.

    slow (~22s, round-11 headroom): RNN unroll training stays tier-1
    via test_rnn.test_lstm_bucketing_training and
    test_gluon_rnn's cell unroll/backward tests."""
    mod = _load('examples/rnn_time_major/rnn_cell_demo.py', 'ex_tnc')
    acc_nt, acc_tn, max_dev = mod.main(quick=True)
    assert acc_nt > 0.9, acc_nt
    assert acc_tn > 0.9, acc_tn
    assert max_dev < 1e-5, max_dev


def test_dsd_training():
    """Dense-sparse-dense optimizer subclass (reference example/dsd):
    the pruning mask must actually hold during the sparse phase and
    accuracy must survive the full D-S-D cycle."""
    mod = _load('examples/dsd/mlp_dsd.py', 'ex_dsd')
    dense_acc, sparse_frac, sparse_acc, final_acc = mod.main(quick=True)
    assert sparse_frac > 0.65, sparse_frac
    assert sparse_acc > 0.9, sparse_acc
    assert final_acc > 0.9, final_acc
