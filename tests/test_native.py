"""Native runtime tests (C++ engine + recordio + image pipeline).
Modeled on reference tests/cpp/engine/threaded_engine_test.cc stress
coverage, run from Python through the ctypes ABI."""
import os
import tempfile
import threading
import time

import numpy as np
import pytest

import mxnet_tpu as mx
from mxnet_tpu import _core, engine as eng_mod, recordio

native = pytest.mark.skipif(not _core.available(),
                            reason='native runtime not built')


def _mk_engine():
    return eng_mod.Engine(num_workers=4)


@native
def test_engine_write_serialization():
    eng = _mk_engine()
    var = eng.new_variable()
    out = []
    for i in range(50):
        eng.push(lambda i=i: out.append(i), mutable_vars=(var,))
    eng.wait_all()
    assert out == list(range(50))


@native
def test_engine_read_write_ordering():
    eng = _mk_engine()
    var = eng.new_variable()
    state = {'x': 0}
    seen = []

    def write(v):
        def f():
            time.sleep(0.001)
            state['x'] = v
        return f

    def read():
        seen.append(state['x'])

    eng.push(write(1), mutable_vars=(var,))
    for _ in range(4):
        eng.push(read, const_vars=(var,))
    eng.push(write(2), mutable_vars=(var,))
    for _ in range(4):
        eng.push(read, const_vars=(var,))
    eng.wait_all()
    assert seen[:4] == [1, 1, 1, 1]
    assert seen[4:] == [2, 2, 2, 2]


@native
def test_engine_independent_parallelism():
    eng = _mk_engine()
    v1, v2 = eng.new_variable(), eng.new_variable()
    # structural check: record wall-clock intervals of each chain's ops
    # and assert the two chains overlapped (timing-threshold-free)
    intervals = []
    lock = threading.Lock()

    def op(tag):
        t0 = time.time()
        time.sleep(0.02)
        with lock:
            intervals.append((tag, t0, time.time()))
    for tag, v in (('a', v1), ('b', v2)):
        for _ in range(2):
            eng.push(lambda tag=tag: op(tag), mutable_vars=(v,))
    eng.wait_all()
    a = [(s, e) for t, s, e in intervals if t == 'a']
    b = [(s, e) for t, s, e in intervals if t == 'b']
    overlap = any(s1 < e2 and s2 < e1
                  for s1, e1 in a for s2, e2 in b)
    assert overlap, (a, b)


@native
def test_engine_wait_for_var():
    eng = _mk_engine()
    var = eng.new_variable()
    done = []
    eng.push(lambda: (time.sleep(0.02), done.append(1)),
             mutable_vars=(var,))
    eng.wait_for_var(var)
    assert done == [1]


def test_py_engine_fallback_semantics():
    eng = eng_mod._PyEngine(4)
    var = eng.new_variable()
    out = []
    for i in range(30):
        eng.push(lambda i=i: out.append(i), mutable_vars=(var,))
    eng.wait_all()
    assert out == list(range(30))


@native
def test_native_recordio_cross_compat(tmp_path):
    """C++ writer <-> Python reader and vice versa."""
    lib = _core.lib()
    path = str(tmp_path / 'native.rec')
    w = lib.MXTRecordWriterCreate(path.encode())
    assert w
    payloads = [b'hello', b'x' * 1000, b'abc' * 77, b'z']
    for p in payloads:
        assert lib.MXTRecordWriterWrite(w, p, len(p)) >= 0
    lib.MXTRecordWriterFree(w)
    # python reads what C++ wrote
    r = recordio.MXRecordIO(path, 'r')
    for p in payloads:
        assert r.read() == p
    assert r.read() is None
    r.close()
    # python writes, C++ reads
    path2 = str(tmp_path / 'py.rec')
    w2 = recordio.MXRecordIO(path2, 'w')
    for p in payloads:
        w2.write(p)
    w2.close()
    import ctypes
    rr = lib.MXTRecordReaderCreate(path2.encode())
    assert rr
    data_p = ctypes.c_char_p()
    size = ctypes.c_uint64()
    for p in payloads:
        ret = lib.MXTRecordReaderNext(rr, ctypes.byref(data_p),
                                      ctypes.byref(size))
        assert ret == 1
        assert ctypes.string_at(data_p, size.value) == p
    assert lib.MXTRecordReaderNext(rr, ctypes.byref(data_p),
                                   ctypes.byref(size)) == 0
    lib.MXTRecordReaderFree(rr)


def _write_img_rec(tmp_path, n=10, size=32):
    import cv2
    prefix = str(tmp_path / 'imgs')
    rec = recordio.MXIndexedRecordIO(prefix + '.idx', prefix + '.rec', 'w')
    rng = np.random.RandomState(0)
    for i in range(n):
        img = rng.randint(0, 255, (size, size, 3)).astype(np.uint8)
        ret, buf = cv2.imencode('.png', img)
        header = recordio.IRHeader(0, float(i % 4), i, 0)
        rec.write_idx(i, recordio.pack(header, buf.tobytes()))
    rec.close()
    return prefix


@native
def test_native_image_iter(tmp_path):
    prefix = _write_img_rec(tmp_path, n=10)
    it = mx.io.ImageRecordIter(
        path_imgrec=prefix + '.rec', data_shape=(3, 28, 28),
        batch_size=4, shuffle=False, use_native=True)
    assert isinstance(it._inner, mx.io._NativeImageRecordIter)
    batch = it.next()
    assert batch.data[0].shape == (4, 3, 28, 28)
    assert batch.label[0].shape == (4,)
    batches = [batch]
    try:
        while True:
            batches.append(it.next())
    except StopIteration:
        pass
    assert len(batches) == 3  # 10 samples, round-batch
    assert batches[-1].pad == 2
    # epoch 2 after reset
    it.reset()
    b2 = it.next()
    assert b2.data[0].shape == (4, 3, 28, 28)


@native
def test_native_matches_python_iter(tmp_path):
    """Native and Python pipelines agree on deterministic settings."""
    prefix = _write_img_rec(tmp_path, n=8)
    kw = dict(path_imgrec=prefix + '.rec', data_shape=(3, 32, 32),
              batch_size=4, shuffle=False, rand_crop=False,
              rand_mirror=False, mean_r=10., mean_g=20., mean_b=30.)
    it_n = mx.io.ImageRecordIter(use_native=True, **kw)
    it_p = mx.io.ImageRecordIter(use_native=False, **kw)
    bn = it_n.next()
    bp = it_p.next()
    np.testing.assert_allclose(bn.label[0].asnumpy(),
                               bp.label[0].asnumpy())
    np.testing.assert_allclose(bn.data[0].asnumpy(),
                               bp.data[0].asnumpy(), atol=1e-4)


@native
def test_native_iter_sharding(tmp_path):
    prefix = _write_img_rec(tmp_path, n=12)
    labels = []
    for part in range(3):
        it = mx.io.ImageRecordIter(
            path_imgrec=prefix + '.rec', data_shape=(3, 32, 32),
            batch_size=4, num_parts=3, part_index=part, use_native=True)
        b = it.next()
        labels.append(b.label[0].asnumpy())
    alll = np.concatenate(labels)
    assert len(alll) == 12
    assert sorted(alll.tolist()) == sorted(
        [float(i % 4) for i in range(12)])


def test_engine_error_propagates_at_wait():
    """Op failures surface at the next sync point instead of vanishing
    (both native and Python engines latch the first error)."""
    for eng in (eng_mod.Engine(num_workers=4),
                eng_mod._PyEngine(num_workers=2)):
        var = eng.new_variable() if hasattr(eng, 'new_variable') else None
        eng.push(lambda: (_ for _ in ()).throw(ValueError('boom')),
                 mutable_vars=(var,))
        with pytest.raises(Exception) as exc:
            eng.wait_all()
        assert 'engine op failed' in str(exc.value)
        # error is reported once; engine remains usable
        eng.push(lambda: None, mutable_vars=(var,))
        eng.wait_all()


def test_engine_rejects_duplicate_vars():
    for eng in (eng_mod.Engine(num_workers=2),
                eng_mod._PyEngine(num_workers=2)):
        v = eng.new_variable()
        with pytest.raises(Exception):
            eng.push(lambda: None, mutable_vars=(v, v))
        with pytest.raises(Exception):
            eng.push(lambda: None, const_vars=(v,), mutable_vars=(v,))
        # engine still functional afterwards
        eng.push(lambda: None, mutable_vars=(v,))
        eng.wait_all()


# ---------------------------------------------------------------------------
# C predict ABI (src/c_predict_api.cc — reference c_predict_api.cc)
# ---------------------------------------------------------------------------

def _train_and_save_mlp(tmp_path, prefix='deploy'):
    """Tiny trained classifier + checkpoint artifacts + one test
    sample whose class the model gets right."""
    from mxnet_tpu import sym, nd
    rng = np.random.RandomState(0)
    n, dim, classes = 256, 12, 4
    centers = rng.randn(classes, dim) * 3
    X = np.zeros((n, dim), np.float32)
    y = np.zeros((n,), np.float32)
    for i in range(n):
        c = i % classes
        X[i] = centers[c] + rng.randn(dim) * 0.3
        y[i] = c
    data = sym.Variable('data')
    fc1 = sym.FullyConnected(data, name='fc1', num_hidden=24)
    act = sym.Activation(fc1, act_type='relu')
    fc2 = sym.FullyConnected(act, name='fc2', num_hidden=classes)
    net = sym.SoftmaxOutput(fc2, name='softmax')
    mod = mx.mod.Module(net, context=[mx.cpu(0)])
    it = mx.io.NDArrayIter(X, y, batch_size=32, shuffle=False,
                           label_name='softmax_label')
    mod.fit(it, num_epoch=6, optimizer_params={'learning_rate': 0.3})
    prefix = str(tmp_path / prefix)
    mod.save_checkpoint(prefix, 1)
    sample = X[5]
    from mxnet_tpu.predictor import Predictor
    p = Predictor.from_checkpoint(prefix, 1, {'data': (1, dim)})
    expect = int(np.argmax(p.predict(sample[None])))
    assert expect == int(y[5])  # the model actually learned the blob
    return prefix, sample, expect


@native
def test_c_predict_abi_ctypes(tmp_path):
    """Drive the predict ABI in-process through ctypes: create from
    symbol JSON + param blob, set input, forward, read output — the
    reference MXPredCreate/SetInput/Forward/GetOutput contract."""
    import ctypes
    prefix, sample, expect = _train_and_save_mlp(tmp_path)
    lib = ctypes.CDLL(_core._LIB_PATH)
    lib.MXTPredGetLastError.restype = ctypes.c_char_p
    with open(prefix + '-symbol.json') as f:
        json_str = f.read().encode()
    with open(prefix + '-0001.params', 'rb') as f:
        params = f.read()
    shape = (ctypes.c_uint32 * 2)(1, sample.size)
    indptr = (ctypes.c_uint32 * 2)(0, 2)
    keys = (ctypes.c_char_p * 1)(b'data')
    handle = ctypes.c_void_p()
    rc = lib.MXTPredCreate(json_str, params, len(params), 1, 0, 1,
                           keys, indptr, shape, ctypes.byref(handle))
    assert rc == 0, lib.MXTPredGetLastError()
    # output shape BEFORE the first forward (the reference
    # alloc-before-forward flow): inferred from bound input shapes
    pre_shape = ctypes.POINTER(ctypes.c_uint32)()
    pre_ndim = ctypes.c_uint32()
    rc = lib.MXTPredGetOutputShape(handle, 0, ctypes.byref(pre_shape),
                                   ctypes.byref(pre_ndim))
    assert rc == 0, lib.MXTPredGetLastError()
    assert [pre_shape[i] for i in range(pre_ndim.value)] == [1, 4]
    buf = np.ascontiguousarray(sample, dtype='<f4')
    rc = lib.MXTPredSetInput(
        handle, b'data',
        buf.ctypes.data_as(ctypes.POINTER(ctypes.c_float)), buf.size)
    assert rc == 0, lib.MXTPredGetLastError()
    assert lib.MXTPredForward(handle) == 0, lib.MXTPredGetLastError()
    oshape = ctypes.POINTER(ctypes.c_uint32)()
    ondim = ctypes.c_uint32()
    rc = lib.MXTPredGetOutputShape(handle, 0, ctypes.byref(oshape),
                                   ctypes.byref(ondim))
    assert rc == 0, lib.MXTPredGetLastError()
    dims = [oshape[i] for i in range(ondim.value)]
    osize = int(np.prod(dims))
    out = np.zeros(osize, np.float32)
    rc = lib.MXTPredGetOutput(
        handle, 0, out.ctypes.data_as(ctypes.POINTER(ctypes.c_float)),
        osize)
    assert rc == 0, lib.MXTPredGetLastError()
    assert int(np.argmax(out)) == expect
    # wrong-size buffer is rejected, not overrun
    assert lib.MXTPredGetOutput(
        handle, 0, out.ctypes.data_as(ctypes.POINTER(ctypes.c_float)),
        osize + 3) != 0
    lib.MXTPredFree(handle)
    # NDList reads the same params blob
    nd_handle = ctypes.c_void_p()
    nd_len = ctypes.c_uint32()
    rc = lib.MXTNDListCreate(params, len(params),
                             ctypes.byref(nd_handle),
                             ctypes.byref(nd_len))
    assert rc == 0, lib.MXTPredGetLastError()
    assert nd_len.value == 4  # 2 weights + 2 biases
    key = ctypes.c_char_p()
    dptr = ctypes.POINTER(ctypes.c_float)()
    sptr = ctypes.POINTER(ctypes.c_uint32)()
    ndim2 = ctypes.c_uint32()
    rc = lib.MXTNDListGet(nd_handle, 0, ctypes.byref(key),
                          ctypes.byref(dptr), ctypes.byref(sptr),
                          ctypes.byref(ndim2))
    assert rc == 0
    assert key.value.decode().startswith('arg:')
    lib.MXTNDListFree(nd_handle)



def _build_and_run_native(tmp_path, src_path, run_args, compiler='g++',
                          timeout=300):
    """Compile one source file against libmxtpu + the cpp-package
    headers and run it with the repo on PYTHONPATH (shared scaffolding
    for every embedded-interpreter ABI test)."""
    import subprocess
    repo = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))
    libdir = os.path.join(repo, 'mxnet_tpu')
    exe = str(tmp_path / 'native_prog')
    cmd = [compiler, '-O2']
    if compiler == 'g++':
        cmd += ['-std=c++14',
                '-I' + os.path.join(repo, 'cpp-package', 'include')]
    cmd += [str(src_path), '-o', exe, '-L' + libdir, '-lmxtpu',
            '-Wl,-rpath,' + libdir, '-Wl,-rpath,/usr/local/lib']
    subprocess.run(cmd, check=True)
    env = dict(os.environ)
    env['PYTHONPATH'] = repo + os.pathsep + env.get('PYTHONPATH', '')
    env.setdefault('JAX_PLATFORMS', 'cpu')
    return subprocess.run([exe] + [str(a) for a in run_args],
                          capture_output=True, text=True, env=env,
                          timeout=timeout)


@native
def test_c_predict_standalone_program(tmp_path):
    """The VERDICT gate: a small C program (examples/c_predict/
    predict.c, zero Python in the source) links libmxtpu.so, loads a
    saved checkpoint, and classifies a sample correctly."""
    prefix, sample, expect = _train_and_save_mlp(tmp_path)
    repo = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))
    inp = str(tmp_path / 'input.f32')
    np.ascontiguousarray(sample, dtype='<f4').tofile(inp)
    proc = _build_and_run_native(
        tmp_path, os.path.join(repo, 'examples', 'c_predict', 'predict.c'),
        [prefix + '-symbol.json', prefix + '-0001.params', inp, 1,
         sample.size], compiler='gcc')
    assert proc.returncode == 0, proc.stderr
    assert 'predicted=%d' % expect in proc.stdout, \
        (proc.stdout, proc.stderr)


@native
def test_cpp_package_predictor(tmp_path):
    """cpp-package parity: the header-only C++ API
    (cpp-package/include/mxnet-tpu-cpp/MxTpuCpp.hpp) compiles and the
    ~35-line example classifies the same sample as the C ABI demo."""
    prefix, sample, expect = _train_and_save_mlp(tmp_path)
    repo = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))
    inp = str(tmp_path / 'input.f32')
    np.ascontiguousarray(sample, dtype='<f4').tofile(inp)
    proc = _build_and_run_native(
        tmp_path,
        os.path.join(repo, 'cpp-package', 'example', 'predict.cpp'),
        [prefix, 1, inp, 1, sample.size])
    assert proc.returncode == 0, proc.stderr
    assert 'predicted=%d' % expect in proc.stdout, \
        (proc.stdout, proc.stderr)


_CPP_SURFACE_SRC = r'''
// Exercises the widened C ABI from C++: NDArray save/load/slice/
// reshape, Symbol internals/attrs/infer-shape.  Zero Python in source.
#include <cassert>
#include <cstdio>
#include <vector>
#include "mxnet-tpu-cpp/MxTpuCpp.hpp"
namespace mc = mxtpu::cpp;

int main(int argc, char** argv) {
  const std::string params = std::string(argv[1]) + "/weights.params";
  // NDArray: build, reshape, slice, save, load
  std::vector<float> vals(12);
  for (int i = 0; i < 12; ++i) vals[i] = static_cast<float>(i);
  mc::NDArray a({3, 4}, vals);
  mc::NDArray r = a.Reshape({4, 3});
  assert(r.GetShape()[0] == 4 && r.GetShape()[1] == 3);
  mc::NDArray s = a.Slice(1, 3);
  assert(s.GetShape()[0] == 2);
  assert(s.ToVector()[0] == 4.0f);
  mc::NDArray::Save(params, {{"arg:w", &a}});
  auto loaded = mc::NDArray::Load(params);
  assert(loaded.size() == 1 && loaded[0].first == "arg:w");
  assert(loaded[0].second.ToVector()[5] == 5.0f);

  // Symbol: compose, attrs, internals, infer shape
  mc::Symbol data = mc::Symbol::Variable("data");
  mc::Symbol fc = mc::Symbol::Create(
      "FullyConnected", "fc", {{"num_hidden", "8"}}, {{"data", &data}});
  mc::Symbol act = mc::Symbol::Create(
      "Activation", "relu", {{"act_type", "relu"}}, {{"data", &fc}});
  act.SetAttr("lr_mult", "2.5");
  assert(act.GetAttr("lr_mult") == "2.5");
  std::string probe;
  assert(act.TryGetAttr("lr_mult", &probe) && probe == "2.5");
  assert(!act.TryGetAttr("never_set", &probe));
  mc::Symbol tap = act.GetInternalByName("fc_output");
  assert(tap.ListOutputs().size() == 1);
  mc::Symbol all = act.GetInternals();
  assert(all.ListOutputs().size() >= 3);
  std::vector<mc::Shape> args, outs, auxs;
  act.InferShape({{"data", {2, 6}}}, &args, &outs, &auxs);
  assert(outs.size() == 1 && outs[0][0] == 2 && outs[0][1] == 8);
  bool found_weight = false;
  auto names = act.ListArguments();
  for (size_t i = 0; i < names.size(); ++i)
    if (names[i] == "fc_weight") {
      found_weight = true;
      assert(args[i][0] == 8 && args[i][1] == 6);
    }
  assert(found_weight);
  std::printf("CPP_SURFACE_OK\n");
  return 0;
}
'''


@native
def test_cpp_surface_ndarray_symbol(tmp_path):
    """The widened C ABI (NDArray save/load/slice/reshape, Symbol
    internals/attrs/infer-shape) drives from C++ with zero Python in
    the source."""
    src = tmp_path / 'surface.cpp'
    src.write_text(_CPP_SURFACE_SRC)
    proc = _build_and_run_native(tmp_path, src, [tmp_path])
    assert proc.returncode == 0, (proc.stdout, proc.stderr)
    assert 'CPP_SURFACE_OK' in proc.stdout, proc.stdout


@native
def test_cpp_package_trains_mlp(tmp_path):
    """The round-4 VERDICT gate: a C++ program with ZERO Python in the
    source (cpp-package/example/mlp_train.cpp) composes an MLP through
    the training C ABI (src/c_api_train.cc: Symbol/Executor/Updater),
    runs minibatch SGD, and reaches >90% train accuracy — the parity
    bar set by the reference cpp-package's own trainable example."""
    repo = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))
    proc = _build_and_run_native(
        tmp_path,
        os.path.join(repo, 'cpp-package', 'example', 'mlp_train.cpp'),
        [], timeout=600)
    assert proc.returncode == 0, (proc.stdout, proc.stderr)
    assert 'final train-accuracy' in proc.stdout, proc.stdout


@native
def test_c_imperative_autograd_trains(tmp_path):
    """The round-5 VERDICT gate: a plain-C program
    (cpp-package/example/imperative_train.c, zero Python in the source)
    runs ops imperatively by registry name (MXTImperativeInvoke),
    records + backprops through the tape (MXTAutogradSetIsRecording/
    MarkVariables/Backward), applies SGD through the Updater, and
    replays the same graph through a CachedOp — mirroring the
    reference's imperative C surface (c_api_ndarray.cc:423-621)."""
    repo = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))
    proc = _build_and_run_native(
        tmp_path,
        os.path.join(repo, 'cpp-package', 'example', 'imperative_train.c'),
        [], compiler='gcc', timeout=600)
    assert proc.returncode == 0, (proc.stdout, proc.stderr)
    assert 'C IMPERATIVE/AUTOGRAD/CACHEDOP OK' in proc.stdout, proc.stdout


def _write_class_color_rec(tmp_path, n=160, edge=12, classes=10):
    """A .rec of color-coded class images: class c's images are
    dominated by a class-specific RGB mix + noise, so a tiny MLP
    separates them — the C++ DataIter example trains on this."""
    import cv2
    from mxnet_tpu import recordio
    prefix = str(tmp_path / 'colors')
    rec = recordio.MXIndexedRecordIO(prefix + '.idx', prefix + '.rec', 'w')
    rng = np.random.RandomState(3)
    centers = rng.randint(40, 215, (classes, 3))
    for i in range(n):
        c = i % classes
        img = (centers[c][None, None, :] +
               rng.randint(-25, 25, (edge, edge, 3))).clip(0, 255) \
            .astype(np.uint8)
        header = recordio.IRHeader(0, float(c), i, 0)
        ok, buf = cv2.imencode('.png', img)
        assert ok
        rec.write_idx(i, recordio.pack(header, buf.tobytes()))
    rec.close()
    return prefix + '.rec', edge, classes


@native
def test_cpp_trains_from_rec_dataiter(tmp_path):
    """The round-5 VERDICT gate: a C++ program with zero Python in the
    source (cpp-package/example/rec_train.cpp) trains from a .rec file
    through the DataIter C surface (MXTListDataIters/MXTDataIterCreate/
    Next/GetData/GetLabel + device-side input refill) — the reference's
    binding contract for data pipelines (c_api.cc iter block)."""
    rec_path, edge, classes = _write_class_color_rec(tmp_path)
    repo = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))
    proc = _build_and_run_native(
        tmp_path,
        os.path.join(repo, 'cpp-package', 'example', 'rec_train.cpp'),
        [rec_path, edge, classes], timeout=600)
    assert proc.returncode == 0, (proc.stdout, proc.stderr)
    assert 'final train-accuracy' in proc.stdout, proc.stdout


@native
def test_perl_binding_trains_mlp(tmp_path):
    """The round-5 VERDICT gate: a NON-C++ language with a plain C FFI
    binds the training ABI and trains — converting the bindings
    descope (docs/DESIGN.md) from argument to evidence.  The Perl
    package (perl-package/: hand-rolled XS in the role SWIG plays for
    the reference's AI::MXNet) builds against libmxtpu.so and
    example/mlp_train.pl reaches >90% train accuracy with zero Python
    and zero C++ in the caller."""
    import shutil
    import subprocess
    repo = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))
    pkg = tmp_path / 'perl-package'
    shutil.copytree(os.path.join(repo, 'perl-package'), pkg,
                    ignore=shutil.ignore_patterns('blib', '*.o', 'pm_to_blib',
                                                  'Makefile', 'MYMETA*',
                                                  'MxTpu.c'))
    env = dict(os.environ)
    env['PYTHONPATH'] = repo + os.pathsep + env.get('PYTHONPATH', '')
    env.setdefault('JAX_PLATFORMS', 'cpu')
    env['MXTPU_REPO'] = repo
    subprocess.run(['perl', 'Makefile.PL'], cwd=pkg, check=True,
                   capture_output=True, text=True, env=env)
    subprocess.run(['make'], cwd=pkg, check=True, capture_output=True,
                   text=True, env=env)
    proc = subprocess.run(
        ['perl', '-Mblib', 'example/mlp_train.pl'], cwd=pkg,
        capture_output=True, text=True, env=env, timeout=600)
    assert proc.returncode == 0, (proc.stdout, proc.stderr)
    assert 'PERL TRAINS OK' in proc.stdout, proc.stdout


@native
def test_stablehlo_runner_no_python(tmp_path):
    """The round-5 VERDICT gate: the exported deployment artifact
    EXECUTES without Python.  Predictor.export_artifact bakes the
    trained parameters into the lowered module as constants; the C++
    runner (tools/stablehlo_runner/runner.cc — XLA's PJRT CPU client
    out of the tensorflow wheel, no interpreter in the process)
    classifies the same digit as the in-framework predictor — the
    amalgamation role (reference amalgamation/mxnet_predict0.cc)."""
    import subprocess
    repo = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))
    tf_dir = None
    try:
        import tensorflow
        tf_dir = os.path.dirname(tensorflow.__file__)
    except ImportError:
        pytest.skip('tensorflow wheel (the XLA runtime source) absent')

    prefix, sample, expect = _train_and_save_mlp(tmp_path)
    from mxnet_tpu.predictor import Predictor
    pred = Predictor.from_checkpoint(prefix, 1,
                                     {'data': (1, sample.size)})
    art = str(tmp_path / 'mlp_art')
    pred.export_artifact(art)
    assert os.path.exists(art + '.hlo.pb')
    ref = pred.predict(sample.reshape(1, -1)).argmax(1)[0]
    assert int(ref) == int(expect)

    # The g++ compile against the TF headers dominates this test
    # (formerly ~85s of its runtime at -O2), so the binary is cached
    # across runs keyed by the runner sources + the full compile
    # command (flags, include paths, TF install) + TF version, and
    # built at -O0 (the runner executes ONE inference; compile time is
    # what matters).  A source, flag, or toolkit change rebuilds; the
    # executed coverage — artifact runs without Python — is unchanged.
    import getpass
    import hashlib
    try:
        user = getpass.getuser()
    except (KeyError, OSError):
        # containers often run as a UID with no passwd entry
        user = str(os.getuid())
    src = os.path.join(repo, 'tools', 'stablehlo_runner')
    cmd = ['g++', '-std=c++17', '-O0', '-DNDEBUG',
           os.path.join(src, 'runner.cc'),
           '-I' + os.path.join(src, 'mlir_stub'),
           '-I' + os.path.join(tf_dir, 'include'),
           '-I' + os.path.join(tf_dir, 'include', 'external',
                               'highwayhash'),
           '-I' + os.path.join(tf_dir, 'include', 'external',
                               'farmhash_archive', 'src'),
           '-L' + tf_dir, '-l:libtensorflow_cc.so.2',
           '-l:libtensorflow_framework.so.2',
           '-Wl,-rpath,' + tf_dir]
    h = hashlib.sha256(tensorflow.__version__.encode())
    h.update('\0'.join(cmd).encode())
    for root, _, files in sorted(os.walk(src)):
        for f in sorted(files):
            with open(os.path.join(root, f), 'rb') as fh:
                h.update(fh.read())
    # per-user 0700 cache dir: /tmp is world-writable, so a bare
    # predictable file name could be pre-planted by another local
    # user and executed below — own the directory or don't trust it
    # (fresh mkdtemp: cache lost, safety kept)
    cache_dir = os.path.join(tempfile.gettempdir(),
                             'mxtpu_shlo_cache_%s' % user)
    os.makedirs(cache_dir, mode=0o700, exist_ok=True)
    dstat = os.stat(cache_dir)
    if dstat.st_uid != os.getuid() or (dstat.st_mode & 0o077):
        cache_dir = tempfile.mkdtemp(prefix='mxtpu_shlo_cache_')
    exe = os.path.join(cache_dir,
                       'runner_%s' % h.hexdigest()[:16])
    if not os.path.exists(exe):
        tmp_exe = '%s.tmp.%d' % (exe, os.getpid())
        build = subprocess.run(cmd + ['-o', tmp_exe],
                               capture_output=True, text=True)
        assert build.returncode == 0, build.stderr[-2000:]
        os.replace(tmp_exe, exe)       # atomic: racing runs both win

    inp = str(tmp_path / 'input.raw')
    np.ascontiguousarray(sample.reshape(1, -1),
                         dtype='<f4').tofile(inp)
    proc = subprocess.run(
        [exe, art + '.hlo.pb', art + '.manifest', inp],
        capture_output=True, text=True, timeout=300)
    assert proc.returncode == 0, (proc.stdout, proc.stderr)
    assert 'STABLEHLO_RUNNER_OK' in proc.stdout, proc.stdout
    assert ('predicted=%d' % expect) in proc.stdout, \
        (expect, proc.stdout)


@native
def test_c_op_introspection():
    """Op registry introspection from C (reference
    MXSymbolListAtomicSymbolCreators + MXSymbolGetAtomicSymbolInfo —
    the pair a binding's codegen walks to build its op namespace):
    list every invokable name, resolve an op's canonical name and
    input names, and resolve an alias to its canonical op."""
    import ctypes
    lib = ctypes.CDLL(_core._LIB_PATH)
    lib.MXTTrainGetLastError.restype = ctypes.c_char_p

    n = ctypes.c_uint32()
    names = ctypes.POINTER(ctypes.c_char_p)()
    rc = lib.MXTListOpNames(ctypes.byref(n), ctypes.byref(names))
    assert rc == 0, lib.MXTTrainGetLastError()
    all_names = {names[i].decode() for i in range(n.value)}
    assert len(all_names) > 300, len(all_names)
    assert {'Convolution', 'FullyConnected', 'stop_gradient'} <= all_names

    canon = ctypes.c_char_p()
    desc = ctypes.c_char_p()
    ni = ctypes.c_uint32()
    ins = ctypes.POINTER(ctypes.c_char_p)()
    rc = lib.MXTOpGetInfo(b'FullyConnected', ctypes.byref(canon),
                          ctypes.byref(desc), ctypes.byref(ni),
                          ctypes.byref(ins))
    assert rc == 0, lib.MXTTrainGetLastError()
    assert canon.value == b'FullyConnected'
    inputs = [ins[i].decode() for i in range(ni.value)]
    assert inputs[0] == 'data' and 'weight' in inputs, inputs

    # alias resolves to the canonical registration
    rc = lib.MXTOpGetInfo(b'stop_gradient', ctypes.byref(canon),
                          ctypes.byref(desc), ctypes.byref(ni),
                          ctypes.byref(ins))
    assert rc == 0, lib.MXTTrainGetLastError()
    assert canon.value == b'BlockGrad', canon.value

    # unknown op: clean error, not a crash
    rc = lib.MXTOpGetInfo(b'NoSuchOpEver', ctypes.byref(canon),
                          ctypes.byref(desc), ctypes.byref(ni),
                          ctypes.byref(ins))
    assert rc != 0

    # runtime registration: the C caches rebuild when the Python
    # registry grows, so an op registered AFTER the first list call
    # still appears (ADVICE round-5; previously a first-call snapshot)
    from mxnet_tpu.ops import registry as _reg
    assert '_test_runtime_op' not in all_names

    @_reg.register('_test_runtime_op', input_names=('data',))
    def _rt_op(attrs, data):            # pragma: no cover - never run
        return data
    try:
        rc = lib.MXTListOpNames(ctypes.byref(n), ctypes.byref(names))
        assert rc == 0, lib.MXTTrainGetLastError()
        fresh = {names[i].decode() for i in range(n.value)}
        assert '_test_runtime_op' in fresh
        rc = lib.MXTOpGetInfo(b'_test_runtime_op', ctypes.byref(canon),
                              ctypes.byref(desc), ctypes.byref(ni),
                              ctypes.byref(ins))
        assert rc == 0, lib.MXTTrainGetLastError()
        assert canon.value == b'_test_runtime_op'
        assert [ins[i].decode() for i in range(ni.value)] == ['data']

        # RE-registering the same name keeps the dict sizes unchanged
        # but must still invalidate (generation stamp, not cardinality)
        @_reg.register('_test_runtime_op', input_names=('lhs', 'rhs'))
        def _rt_op2(attrs, lhs, rhs):   # pragma: no cover - never run
            return lhs
        rc = lib.MXTOpGetInfo(b'_test_runtime_op', ctypes.byref(canon),
                              ctypes.byref(desc), ctypes.byref(ni),
                              ctypes.byref(ins))
        assert rc == 0, lib.MXTTrainGetLastError()
        assert [ins[i].decode() for i in range(ni.value)] == \
            ['lhs', 'rhs']
    finally:
        _reg._OP_REGISTRY.pop('_test_runtime_op', None)


@native
def test_c_runtime_controls():
    """MXTRandomSeed + MXTNDArrayWaitAll (reference MXRandomSeed /
    MXNDArrayWaitAll): seeding from C makes the op RNG reproducible;
    WaitAll returns cleanly as a stream barrier."""
    import ctypes
    lib = ctypes.CDLL(_core._LIB_PATH)
    lib.MXTTrainGetLastError.restype = ctypes.c_char_p

    def draw():
        assert lib.MXTRandomSeed(1234) == 0, lib.MXTTrainGetLastError()
        out = (ctypes.c_void_p * 1)()
        n = ctypes.c_uint32()
        rc = lib.MXTImperativeInvoke(
            b'_random_uniform', 0, None, 2,
            (ctypes.c_char_p * 2)(b'shape', b'low'),
            (ctypes.c_char_p * 2)(b'(4,)', b'0.0'),
            ctypes.byref(n), out, 1)
        assert rc == 0, lib.MXTTrainGetLastError()
        buf = (ctypes.c_float * 4)()
        # explicit c_void_p/c_size_t: a bare Python int argument is
        # marshalled as 32-bit c_int, truncating the handle pointer
        assert lib.MXTNDArraySyncCopyToCPU(ctypes.c_void_p(out[0]), buf,
                                           ctypes.c_size_t(4)) == 0, \
            lib.MXTTrainGetLastError()
        lib.MXTNDArrayFree(ctypes.c_void_p(out[0]))
        return list(buf)

    a = draw()
    b = draw()
    assert a == b, (a, b)               # same seed -> same stream
    assert lib.MXTNDArrayWaitAll() == 0, lib.MXTTrainGetLastError()
