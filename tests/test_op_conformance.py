"""Auto-generated per-op conformance sweep (VERDICT.md item 3).

Model: reference tests/python/unittest/test_operator.py — there every
operator gets numeric-gradient-checked against finite differences and
cross-checked across dtypes (test_utils.py:439 check_numeric_gradient,
:784 check_consistency).  Here ONE parametrized test walks the whole op
registry; every primary op must either have a case in CASES or an entry
in SKIP with a reason — test_registry_fully_covered enforces it, so a
newly registered op fails CI until it's covered.

Each case runs up to three checks on tiny shapes:
  * forward: symbolic forward executes, outputs finite (unless the op
    intentionally emits non-finite values);
  * grad: symbolic backward vs central finite differences
    (check_numeric_gradient), for ops marked differentiable;
  * dtype: float32 vs bfloat16 forward consistency (the reference's
    check_consistency across dtypes), loose tolerance.
"""
import os

import numpy as np
import pytest

import mxnet_tpu as mx
from mxnet_tpu import nd, sym, ops
from mxnet_tpu.test_utils import check_numeric_gradient


class Case:
    def __init__(self, shapes, attrs=None, low=-1.0, high=1.0,
                 grad=True, dtype=True, finite=True, grad_nodes=None,
                 int_inputs=(), values=None, rtol=1e-2, atol=1e-3,
                 wrap=None, eps=1e-3):
        self.shapes = shapes          # list aligned with op arg names
        self.attrs = attrs or {}
        self.low, self.high = low, high
        self.grad = grad
        self.dtype = dtype
        self.finite = finite
        self.grad_nodes = grad_nodes  # None -> all float inputs
        self.int_inputs = int_inputs  # indices drawn as integers
        self.values = values          # explicit input arrays
        self.rtol, self.atol = rtol, atol
        self.eps = eps                # FD step (bigger when the loss
        #   magnitude makes 1e-3 steps vanish in f32 resolution)
        self.wrap = wrap              # 'square': check grads of out**2
        #   (for ops whose plain output-sum is constant by construction,
        #   e.g. BatchNorm: sum((x-mean)/std) == 0)


def u(low, high, shapes=((2, 3),), grad=True, **kw):
    return Case(list(shapes), low=low, high=high, grad=grad, **kw)


_S = [(2, 3)]          # default elementwise shape
_B = [(2, 3), (2, 3)]  # binary same-shape

CASES = {
    # -- elementwise unary: (domain, differentiable) ---------------------
    'abs': u(0.2, 1.0), 'negative': u(-1, 1), 'reciprocal': u(0.5, 2.0),
    'square': u(-1, 1), 'sqrt': u(0.3, 2.0), 'rsqrt': u(0.3, 2.0),
    'cbrt': u(0.3, 2.0), 'rcbrt': u(0.3, 2.0),
    'exp': u(-1, 1), 'expm1': u(-1, 1),
    'log': u(0.5, 2.0), 'log10': u(0.5, 2.0), 'log2': u(0.5, 2.0),
    'log1p': u(-0.5, 1.0),
    'sin': u(-1, 1), 'cos': u(-1, 1), 'tan': u(-0.5, 0.5),
    'arcsin': u(-0.8, 0.8), 'arccos': u(-0.8, 0.8), 'arctan': u(-1, 1),
    'sinh': u(-1, 1), 'cosh': u(-1, 1), 'tanh': u(-1, 1),
    'arcsinh': u(-1, 1), 'arccosh': u(1.2, 2.0), 'arctanh': u(-0.8, 0.8),
    'degrees': u(-1, 1), 'radians': u(-90, 90),
    'sigmoid': u(-2, 2), 'relu': u(0.2, 1.0), 'softsign': u(-1, 1),
    'gamma': u(1.2, 3.0), 'gammaln': u(1.2, 3.0),
    'sign': u(0.2, 1.0, grad=False), 'round': u(0.2, 0.4, grad=False),
    'rint': u(0.2, 0.4, grad=False), 'ceil': u(0.2, 0.4, grad=False),
    'floor': u(0.2, 0.4, grad=False), 'trunc': u(0.2, 0.4, grad=False),
    'fix': u(0.2, 0.4, grad=False),
    'zeros_like': u(-1, 1, grad=False), 'ones_like': u(-1, 1, grad=False),
    '_copy': u(-1, 1), 'BlockGrad': u(-1, 1, grad=False),
    'Cast': u(-1, 1, attrs={'dtype': 'float32'}),
    'clip': u(-2, 2, attrs={'a_min': -0.5, 'a_max': 0.5}, grad=False),
    'smooth_l1': u(-2, 2, attrs={'scalar': 1.0}),
    'make_loss': u(-1, 1, grad=False),
    'Flatten': Case([(2, 3, 4)]),
    'Reshape': Case([(2, 6)], attrs={'shape': (3, 4)}),
    'expand_dims': Case(_S, attrs={'axis': 1}),
    'Pad': Case([(2, 2, 3, 3)],
                attrs={'mode': 'constant',
                       'pad_width': (0, 0, 0, 0, 1, 1, 1, 1)}),

    # -- binary / scalar -------------------------------------------------
    'elemwise_add': Case(_B), 'elemwise_sub': Case(_B),
    'elemwise_mul': Case(_B),
    '_grad_add': Case(_B),
    '_identity_with_attr_like_rhs': Case(_B, grad=False),
    '_CrossDeviceCopy': u(-1, 1),
    'elemwise_div': Case(_B, low=0.5, high=2.0),
    '_power': Case(_B, low=0.5, high=2.0),
    '_maximum': Case(_B, grad=False), '_minimum': Case(_B, grad=False),
    '_hypot': Case(_B, low=0.5, high=2.0),
    '_mod': Case(_B, low=0.5, high=2.0, grad=False),
    '_equal': Case(_B, grad=False), '_not_equal': Case(_B, grad=False),
    '_greater': Case(_B, grad=False),
    '_greater_equal': Case(_B, grad=False),
    '_lesser': Case(_B, grad=False),
    '_lesser_equal': Case(_B, grad=False),
    '_plus_scalar': u(-1, 1, attrs={'scalar': 1.5}),
    '_minus_scalar': u(-1, 1, attrs={'scalar': 1.5}),
    '_rminus_scalar': u(-1, 1, attrs={'scalar': 1.5}),
    '_mul_scalar': u(-1, 1, attrs={'scalar': 1.5}),
    '_div_scalar': u(-1, 1, attrs={'scalar': 1.5}),
    '_rdiv_scalar': u(0.5, 2.0, attrs={'scalar': 1.5}),
    '_power_scalar': u(0.5, 2.0, attrs={'scalar': 2.0}),
    '_rpower_scalar': u(0.5, 2.0, attrs={'scalar': 2.0}),
    '_maximum_scalar': u(-1, 1, attrs={'scalar': 0.0}, grad=False),
    '_minimum_scalar': u(-1, 1, attrs={'scalar': 0.0}, grad=False),
    '_mod_scalar': u(0.5, 2.0, attrs={'scalar': 1.5}, grad=False),
    '_rmod_scalar': u(0.5, 2.0, attrs={'scalar': 1.5}, grad=False),
    '_hypot_scalar': u(0.5, 2.0, attrs={'scalar': 1.5}),
    '_equal_scalar': u(-1, 1, attrs={'scalar': 0.0}, grad=False),
    '_not_equal_scalar': u(-1, 1, attrs={'scalar': 0.0}, grad=False),
    '_greater_scalar': u(-1, 1, attrs={'scalar': 0.0}, grad=False),
    '_greater_equal_scalar': u(-1, 1, attrs={'scalar': 0.0}, grad=False),
    '_lesser_scalar': u(-1, 1, attrs={'scalar': 0.0}, grad=False),
    '_lesser_equal_scalar': u(-1, 1, attrs={'scalar': 0.0}, grad=False),

    # -- broadcast binary -------------------------------------------------
    'broadcast_add': Case([(2, 3), (1, 3)]),
    'broadcast_sub': Case([(2, 3), (1, 3)]),
    'broadcast_mul': Case([(2, 3), (1, 3)]),
    'broadcast_div': Case([(2, 3), (1, 3)], low=0.5, high=2.0),
    'broadcast_power': Case([(2, 3), (1, 3)], low=0.5, high=2.0),
    'broadcast_maximum': Case([(2, 3), (1, 3)], grad=False),
    'broadcast_minimum': Case([(2, 3), (1, 3)], grad=False),
    'broadcast_mod': Case([(2, 3), (1, 3)], low=0.5, high=2.0,
                          grad=False),
    'broadcast_hypot': Case([(2, 3), (1, 3)], low=0.5, high=2.0),
    'broadcast_equal': Case([(2, 3), (1, 3)], grad=False),
    'broadcast_not_equal': Case([(2, 3), (1, 3)], grad=False),
    'broadcast_greater': Case([(2, 3), (1, 3)], grad=False),
    'broadcast_greater_equal': Case([(2, 3), (1, 3)], grad=False),
    'broadcast_lesser': Case([(2, 3), (1, 3)], grad=False),
    'broadcast_lesser_equal': Case([(2, 3), (1, 3)], grad=False),
    'broadcast_plus': Case([(2, 3), (1, 3)]),
    'broadcast_minus': Case([(2, 3), (1, 3)]),
    'broadcast_to': Case([(1, 3)], attrs={'shape': (2, 3)}),
    'broadcast_axis': Case([(1, 3)], attrs={'axis': 0, 'size': 2}),

    # -- reductions --------------------------------------------------------
    'sum': Case(_S, attrs={'axis': 1}),
    'mean': Case(_S, attrs={'axis': 1}),
    'prod': Case(_S, attrs={'axis': 1}, low=0.5, high=1.5),
    'nansum': Case(_S, attrs={'axis': 1}),
    'nanprod': Case(_S, attrs={'axis': 1}, low=0.5, high=1.5),
    'max': Case(_S, attrs={'axis': 1}, grad=False),
    'min': Case(_S, attrs={'axis': 1}, grad=False),
    'norm': Case(_S, low=0.5, high=1.0),
    'argmax': Case(_S, grad=False, attrs={'axis': 1}, dtype=False),
    'argmin': Case(_S, grad=False, attrs={'axis': 1}, dtype=False),
    'argmax_channel': Case(_S, grad=False, dtype=False),

    # -- matrix / shape ----------------------------------------------------
    'dot': Case([(2, 3), (3, 2)]),
    'linalg_gemm': Case([(2, 3), (3, 2), (2, 2)]),
    'linalg_gemm2': Case([(2, 3), (3, 2)]),
    'linalg_potrf': Case([(3, 3)], values=[
        (lambda a: (a @ a.T + 3 * np.eye(3)).astype(np.float32))(
            np.random.RandomState(7).rand(3, 3))], grad=False,
        dtype=False),
    'linalg_potri': Case([(3, 3)], values=[
        np.linalg.cholesky((lambda a: a @ a.T + 3 * np.eye(3))(
            np.random.RandomState(7).rand(3, 3))).astype(np.float32)],
        grad=False, dtype=False),
    'linalg_sumlogdiag': Case([(3, 3)], low=0.5, high=2.0, grad=False),
    'linalg_syrk': Case([(2, 3)]),
    'linalg_trmm': Case([(3, 3), (3, 3)], values=[
        np.tril(np.random.RandomState(8).rand(3, 3) + 1).astype(
            np.float32), None], grad=False, dtype=False),
    'linalg_trsm': Case([(3, 3), (3, 3)], values=[
        np.tril(np.random.RandomState(8).rand(3, 3) + 1).astype(
            np.float32), None], grad=False, dtype=False),
    'batch_dot': Case([(2, 2, 3), (2, 3, 2)]),
    'transpose': Case(_S),
    'SwapAxis': Case([(2, 3, 4)], attrs={'dim1': 0, 'dim2': 2}),
    'slice': Case([(4, 4)], attrs={'begin': (1, 0), 'end': (3, 2)}),
    'slice_axis': Case([(4, 4)],
                       attrs={'axis': 1, 'begin': 1, 'end': 3}),
    'SliceChannel': Case([(2, 4)],
                         attrs={'num_outputs': 2, 'axis': 1}),
    'Concat': Case([(2, 2), (2, 3)],
                   attrs={'num_args': 2, 'dim': 1}),
    'stack': Case([(2, 3), (2, 3)], attrs={'num_args': 2, 'axis': 0}),
    'add_n': Case([(2, 3), (2, 3)], attrs={'num_args': 2}),
    'repeat': Case(_S, attrs={'repeats': 2, 'axis': 1}),
    'tile': Case(_S, attrs={'reps': (2, 1)}),
    'reverse': Case(_S, attrs={'axis': 1}),
    'flip': Case(_S, attrs={'axis': 1}),
    'depth_to_space': Case([(1, 4, 2, 2)], attrs={'block_size': 2}),
    'space_to_depth': Case([(1, 1, 4, 4)], attrs={'block_size': 2}),
    'Crop': Case([(1, 1, 4, 4)], attrs={'h_w': (2, 2), 'num_args': 1},
                 grad=False),
    '_eye': Case([], attrs={'N': 3}, grad=False, dtype=False),
    '_zeros': Case([], attrs={'shape': (2, 3)}, grad=False, dtype=False),
    '_ones': Case([], attrs={'shape': (2, 3)}, grad=False, dtype=False),
    '_full': Case([], attrs={'shape': (2, 3), 'value': 2.5}, grad=False,
                  dtype=False),
    '_arange': Case([], attrs={'start': 0, 'stop': 6}, grad=False,
                    dtype=False),
    'where': Case([(2, 3), (2, 3), (2, 3)], grad=False),

    # -- ordering ----------------------------------------------------------
    'sort': Case(_S, grad=False, dtype=False),
    'argsort': Case(_S, grad=False, dtype=False),
    'topk': Case(_S, attrs={'k': 2}, grad=False, dtype=False),
    'pick': Case([(3, 4), (3,)], grad_nodes=['arg0'], grad=False,
                 int_inputs=(1,)),

    # -- indexing ----------------------------------------------------------
    'take': Case([(4, 3), (2,)], grad=False, int_inputs=(1,)),
    'batch_take': Case([(3, 4), (3,)], grad=False, int_inputs=(1,)),
    'one_hot': Case([(4,)], attrs={'depth': 3}, grad=False,
                    int_inputs=(0,)),
    'Embedding': Case([(4,), (5, 3)],
                      attrs={'input_dim': 5, 'output_dim': 3},
                      grad=False, int_inputs=(0,)),
    'gather_nd': Case([(4, 3), (2, 2)], grad=False, int_inputs=(1,)),
    'scatter_nd': Case([(2,), (2, 2)],
                       attrs={'shape': (4, 3)}, grad=False,
                       int_inputs=(1,)),
    # accumulating variant (duplicate-index ADD semantics pinned by
    # tests/test_sparse_embed.py)
    '_backward_gather_nd': Case([(2,), (2, 2)],
                                attrs={'shape': (4, 3)}, grad=False,
                                int_inputs=(1,)),

    # -- neural network ----------------------------------------------------
    'FullyConnected': Case([(2, 3), (4, 3), (4,)],
                           attrs={'num_hidden': 4}),
    'Convolution': Case([(1, 2, 5, 5), (3, 2, 3, 3), (3,)],
                        attrs={'kernel': (3, 3), 'num_filter': 3,
                               'pad': (1, 1)}, rtol=2e-2),
    'Deconvolution': Case([(1, 2, 4, 4), (2, 3, 2, 2), (3,)],
                          attrs={'kernel': (2, 2), 'num_filter': 3,
                                 'stride': (2, 2)}, rtol=2e-2),
    'Pooling': Case([(1, 2, 4, 4)],
                    attrs={'kernel': (2, 2), 'pool_type': 'avg',
                           'stride': (2, 2)}),
    'Activation': Case(_S, attrs={'act_type': 'tanh'}),
    'LeakyReLU': Case(_S, attrs={'act_type': 'leaky', 'slope': 0.1},
                      low=0.2, high=1.0),
    'SoftmaxActivation': Case(_S),
    'softmax': Case(_S), 'log_softmax': Case(_S),
    'Dropout': Case(_S, attrs={'p': 0.5}, grad=False),
    'BatchNorm': Case([(2, 3, 4, 4), (3,), (3,)],
                      attrs={'fix_gamma': False}, low=0.5, high=1.5,
                      grad_nodes=['data'], rtol=5e-2, atol=5e-3,
                      wrap='square', eps=1e-2),
    'InstanceNorm': Case([(2, 3, 4), (3,), (3,)], low=0.5, high=1.5,
                         grad_nodes=['data'], rtol=5e-2, atol=5e-3,
                         wrap='square', eps=1e-2),
    'L2Normalization': Case([(2, 6)], low=0.5, high=1.5),
    'LRN': Case([(1, 4, 3, 3)], attrs={'nsize': 3}, low=0.5, high=1.5),
    'LSoftmax': Case([(3, 4), (5, 4), (3,)],
                     attrs={'num_hidden': 5, 'margin': 2},
                     grad=False, int_inputs=(2,)),
    'UpSampling': Case([(1, 2, 3, 3)],
                       attrs={'scale': 2, 'sample_type': 'nearest',
                              'num_args': 1}),
    'GridGenerator': Case([(1, 6)],
                          attrs={'transform_type': 'affine',
                                 'target_shape': (4, 4)}, grad=False),
    'BilinearSampler': Case([(1, 1, 4, 4), (1, 2, 3, 3)],
                            low=-0.8, high=0.8, grad=False),
    'SpatialTransformer': Case(
        [(1, 1, 4, 4), (1, 6)],
        attrs={'transform_type': 'affine', 'sampler_type': 'bilinear',
               'target_shape': (4, 4)}, low=-0.5, high=0.5, grad=False),
    'ROIPooling': Case([(1, 2, 6, 6), (1, 5)],
                       attrs={'pooled_size': (2, 2),
                              'spatial_scale': 1.0},
                       values=[None,
                               np.array([[0, 0, 0, 4, 4]], np.float32)],
                       grad=False),
    'Correlation': Case([(1, 2, 4, 4), (1, 2, 4, 4)],
                        attrs={'kernel_size': 1, 'max_displacement': 1,
                               'pad_size': 1}, grad=False),
    'Correlation1D': Case([(1, 2, 4, 6), (1, 2, 4, 6)],
                          attrs={'kernel_size': 1,
                                 'max_displacement': 1, 'pad_size': 1},
                          grad=False),
    'SequenceLast': Case([(3, 2, 4)], grad=False),
    'SequenceMask': Case([(3, 2, 4)], grad=False),
    'SequenceReverse': Case([(3, 2, 4)], grad=False),
    'IdentityAttachKLSparseReg': Case(_S, low=0.1, high=0.9,
                                      grad=False),

    # -- losses (head-grad-ignoring custom VJPs: fwd + finite bwd) --------
    'SoftmaxOutput': Case([(3, 4), (3,)], grad=False, int_inputs=(1,)),
    'LinearRegressionOutput': Case([(3, 2), (3, 2)], grad=False),
    'LogisticRegressionOutput': Case([(3, 2), (3, 2)], grad=False),
    'MAERegressionOutput': Case([(3, 2), (3, 2)], grad=False),
    'SVMOutput': Case([(3, 4), (3,)], grad=False, int_inputs=(1,)),
    'MultiLogistic': Case([(3, 2), (3, 2)], grad=False),
    'WeightedL1': Case([(3, 2), (3, 2)], grad=False),
    'softmax_cross_entropy': Case([(3, 4), (3,)], grad=False,
                                  int_inputs=(1,)),

    # -- random (shape/finiteness only) -----------------------------------
    '_random_uniform': Case([], attrs={'shape': (2, 3)}, grad=False,
                            dtype=False),
    '_random_normal': Case([], attrs={'shape': (2, 3)}, grad=False,
                           dtype=False),
    '_random_exponential': Case([], attrs={'shape': (2, 3)},
                                grad=False, dtype=False),
    '_random_gamma': Case([], attrs={'shape': (2, 3), 'alpha': 2.0},
                          grad=False, dtype=False),
    '_random_poisson': Case([], attrs={'shape': (2, 3), 'lam': 3.0},
                            grad=False, dtype=False),
    '_random_negative_binomial': Case(
        [], attrs={'shape': (2, 3), 'k': 2, 'p': 0.5}, grad=False,
        dtype=False),
    '_random_generalized_negative_binomial': Case(
        [], attrs={'shape': (2, 3), 'mu': 2.0, 'alpha': 0.5},
        grad=False, dtype=False),
    'sample_uniform': Case([(2,), (2,)], values=[
        np.zeros(2, np.float32), np.ones(2, np.float32)],
        grad=False, dtype=False),
    'sample_normal': Case([(2,), (2,)], values=[
        np.zeros(2, np.float32), np.ones(2, np.float32)],
        grad=False, dtype=False),
    'sample_gamma': Case([(2,), (2,)], values=[
        np.full(2, 2.0, np.float32), np.ones(2, np.float32)],
        grad=False, dtype=False),
    'sample_exponential': Case([(2,)], values=[
        np.ones(2, np.float32)], grad=False, dtype=False),
    'sample_poisson': Case([(2,)], values=[
        np.full(2, 3.0, np.float32)], grad=False, dtype=False),
    'sample_negative_binomial': Case([(2,), (2,)], values=[
        np.full(2, 2.0, np.float32), np.full(2, 0.5, np.float32)],
        grad=False, dtype=False),
    'sample_generalized_negative_binomial': Case([(2,), (2,)], values=[
        np.full(2, 2.0, np.float32), np.full(2, 0.5, np.float32)],
        grad=False, dtype=False),
    '_sample_multinomial': Case([(2, 4)], low=0.1, high=0.9,
                                grad=False, dtype=False),

    # -- contrib -----------------------------------------------------------
    'fft': Case([(2, 4)], grad=False),
    'ifft': Case([(2, 8)], grad=False),
    'count_sketch': Case([(2, 4), (4,), (4,)],
                         attrs={'out_dim': 3},
                         values=[None,
                                 np.array([1, -1, 1, -1], np.float32),
                                 np.array([0, 1, 2, 0], np.float32)],
                         grad=False),
    'quantize': Case([(2, 3), (1,), (1,)],
                     values=[None, np.array([-1.0], np.float32),
                             np.array([1.0], np.float32)],
                     grad=False, dtype=False),
    'dequantize': Case([(2, 3), (1,), (1,)],
                       values=[np.random.RandomState(0).randint(
                           0, 255, (2, 3)).astype(np.uint8),
                           np.array([-1.0], np.float32),
                           np.array([1.0], np.float32)],
                       grad=False, dtype=False),
    'ctc_loss': Case([(4, 2, 5), (2, 3)],
                     values=[None,
                             np.array([[1, 2, 0], [2, 3, 1]],
                                      np.float32)],
                     grad=False),
    'MultiBoxPrior': Case([(1, 2, 4, 4)],
                          attrs={'sizes': (0.5,), 'ratios': (1.0,)},
                          grad=False),
    'MultiBoxDetection': Case(
        [(1, 4, 2), (1, 8), (1, 2, 4)],
        values=[np.array([[[0.6, 0.4], [0.3, 0.7]]], np.float32)
                .transpose(0, 2, 1),
                np.zeros((1, 8), np.float32),
                np.array([[[0.1, 0.1, 0.4, 0.4],
                           [0.5, 0.5, 0.9, 0.9]]], np.float32)],
        grad=False),
    'MultiBoxTarget': Case(
        [(1, 2, 4), (1, 1, 5), (1, 2, 2)],
        values=[np.array([[[0.1, 0.1, 0.4, 0.4],
                           [0.5, 0.5, 0.9, 0.9]]], np.float32),
                np.array([[[0, 0.1, 0.1, 0.4, 0.4]]], np.float32),
                np.zeros((1, 2, 2), np.float32)],
        grad=False),
    'Proposal': Case(
        [(1, 2, 4, 4), (1, 4, 4, 4), (1, 3)],
        values=[None, None, np.array([[16.0, 16.0, 1.0]], np.float32)],
        attrs={'feature_stride': 4, 'scales': (4.0,), 'ratios': (1.0,),
               'rpn_pre_nms_top_n': 8, 'rpn_post_nms_top_n': 4,
               'rpn_min_size': 1},
        grad=False, dtype=False),
    'PSROIPooling': Case(
        [(1, 8, 4, 4), (1, 5)],
        attrs={'output_dim': 2, 'pooled_size': 2, 'spatial_scale': 1.0},
        values=[None, np.array([[0, 0, 0, 3, 3]], np.float32)],
        grad=False),
    'DeformableConvolution': Case(
        [(1, 2, 5, 5), (1, 18, 5, 5), (3, 2, 3, 3), (3,)],
        attrs={'kernel': (3, 3), 'num_filter': 3, 'pad': (1, 1),
               'num_deformable_group': 1},
        grad=False),
    'DeformablePSROIPooling': Case(
        [(1, 8, 4, 4), (1, 5), (1, 2, 2, 2)],
        attrs={'output_dim': 2, 'pooled_size': 2, 'group_size': 2,
               'spatial_scale': 1.0, 'trans_std': 0.1, 'no_trans': False,
               'part_size': 2, 'sample_per_part': 1},
        values=[None, np.array([[0, 0, 0, 3, 3]], np.float32), None],
        grad=False),
}

SKIP = {
    # exercised end-to-end by dedicated tests
    'RNN': 'scan-fused RNN covered by tests/test_rnn.py',
    'Custom': 'host-callback bridge covered by tests/test_autograd.py',
    '_Native': 'legacy bridge covered by tests/test_missing_ops.py',
    '_NDArray': 'legacy bridge covered by tests/test_missing_ops.py',
    'sgd_update': 'covered by tests/test_missing_ops.py',
    'sgd_mom_update': 'covered by tests/test_missing_ops.py',
    'mp_sgd_update': 'covered by tests/test_missing_ops.py',
    'mp_sgd_mom_update': 'covered by tests/test_missing_ops.py',
    'sparse_sgd_update': 'rows-only COO update parity covered by '
                         'tests/test_sparse_embed.py',
    'sparse_sgd_mom_update': 'rows-only lazy-momentum parity covered '
                             'by tests/test_sparse_embed.py',
    'adam_update': 'covered by tests/test_missing_ops.py',
    'rmsprop_update': 'covered by tests/test_missing_ops.py',
    'rmspropalex_update': 'covered by tests/test_missing_ops.py',
    '_slice_assign': 'covered by tests/test_missing_ops.py',
    '_crop_assign_scalar': 'covered by tests/test_missing_ops.py',
    'MultiProposal': 'batch variant of Proposal (same kernel), '
                     'covered by tests/test_contrib.py',
    '_NoGradient': 'zero-input placeholder node (reference '
                   'init_op.cc); nothing to gradient-check',
}


def test_reference_registry_parity():
    """Every registration name in the reference (314 NNVM_REGISTER_OP +
    MXNET_REGISTER_OP_PROPERTY sites, vendored in
    tests/data_reference_op_names.txt) is either a registered op here
    or carries an explicit N/A reason in ops.registry.REFERENCE_NA —
    the mechanical op diff vs the reference is empty-or-annotated."""
    from mxnet_tpu.ops import registry as reg
    path = os.path.join(os.path.dirname(__file__),
                        'data_reference_op_names.txt')
    names = [ln.strip() for ln in open(path) if ln.strip()]
    assert len(names) > 300
    unaccounted = [n for n in names
                   if not reg.exists(n)
                   and reg.reference_na_reason(n) is None]
    assert not unaccounted, (
        'reference registration names neither registered nor '
        'N/A-annotated: %s' % unaccounted)


def _primary_ops():
    return sorted(n for n in ops.list_ops()
                  if ops.get(n).name == n)


def test_registry_fully_covered():
    """Every primary op has a conformance case or an explicit skip."""
    missing = [n for n in _primary_ops()
               if n not in CASES and n not in SKIP]
    assert not missing, ('ops with neither a conformance case nor a '
                         'skip reason: %s' % missing)


def _build(op_name, case, dtype=np.float32):
    op = ops.get(op_name)
    attrs = dict(case.attrs)
    arg_names = op.arg_names(attrs)
    n_in = len(case.shapes)
    rng = np.random.RandomState(42)
    variables = []
    location = {}
    for i in range(n_in):
        name = arg_names[i] if i < len(arg_names) else 'arg%d' % i
        name = 'arg%d_%s' % (i, name)
        variables.append(sym.Variable(name))
        if case.values is not None and case.values[i] is not None:
            arr = np.asarray(case.values[i])
        elif i in case.int_inputs:
            arr = rng.randint(0, 3, case.shapes[i]).astype(np.float32)
        else:
            arr = rng.uniform(case.low, case.high,
                              case.shapes[i]).astype(dtype)
        location[name] = arr
    fn = getattr(sym, op_name)
    net = fn(*variables, **attrs)
    if case.wrap == 'square':
        net = sym.square(net if len(net.list_outputs()) == 1 else net[0])
    return net, location


@pytest.mark.parametrize('op_name', sorted(CASES))
def test_op_conformance(op_name):
    case = CASES[op_name]
    net, location = _build(op_name, case)
    shapes = {k: v.shape for k, v in location.items()}
    ex = net.simple_bind(mx.cpu(), grad_req='null', **shapes)
    ex.forward(is_train=False, **location)
    outs = [o.asnumpy() for o in ex.outputs]
    if case.finite:
        for o in outs:
            assert np.isfinite(o).all(), '%s: non-finite forward' % op_name

    if case.grad:
        grad_nodes = case.grad_nodes
        if grad_nodes is None:
            grad_nodes = [k for i, k in enumerate(location)
                          if i not in case.int_inputs]
        else:
            grad_nodes = [k for k in location
                          if any(k.endswith('_' + g) or k == g
                                 for g in grad_nodes)]
        check_numeric_gradient(net, location, numeric_eps=case.eps,
                               rtol=case.rtol, atol=case.atol or 1e-3,
                               grad_nodes=grad_nodes)

    if case.dtype:
        # bfloat16 forward consistency vs float32 (reference
        # check_consistency across dtype list, test_utils.py:784)
        import jax.numpy as jnp
        loc16 = {k: v for k, v in location.items()}
        ex16 = net.simple_bind(mx.cpu(), grad_req='null',
                               type_dict={k: jnp.bfloat16
                                          for i, k in
                                          enumerate(location)
                                          if i not in case.int_inputs},
                               **shapes)
        ex16.forward(is_train=False, **loc16)
        for o32, o16 in zip(outs, ex16.outputs):
            got = np.asarray(o16.asnumpy(), np.float32)
            if not np.issubdtype(np.asarray(o32).dtype, np.floating):
                continue
            np.testing.assert_allclose(
                got, o32, rtol=0.06, atol=0.06,
                err_msg='%s: bf16 vs f32 forward diverged' % op_name)


# ---------------------------------------------------------------------------
# contrib quantize/dequantize: the signed int8 mode's edge semantics
# (reference contrib/quantize-inl.h — symmetric ±max(|min|,|max|) onto
# ±127, round half away from zero, code -128 never produced) and the
# zero-range guard both modes share (PERF round 17 satellite)
# ---------------------------------------------------------------------------

def _run_quantize(data, lo, hi, **attrs):
    d = sym.Variable('data')
    mn = sym.Variable('mn')
    mx_ = sym.Variable('mx')
    net = sym.quantize(d, mn, mx_, **attrs)
    ex = net.simple_bind(mx.cpu(), grad_req='null',
                         data=data.shape, mn=(1,), mx=(1,))
    ex.forward(is_train=False, data=data,
               mn=np.asarray([lo], np.float32),
               mx=np.asarray([hi], np.float32))
    return [o.asnumpy() for o in ex.outputs]


def _run_dequantize(q, lo, hi):
    d = sym.Variable('data')
    mn = sym.Variable('mn')
    mx_ = sym.Variable('mx')
    net = sym.dequantize(d, mn, mx_)
    ex = net.simple_bind(mx.cpu(), grad_req='null',
                         data=q.shape, mn=(1,), mx=(1,),
                         type_dict={'data': q.dtype})
    ex.forward(is_train=False, data=q,
               mn=np.asarray([lo], np.float32),
               mx=np.asarray([hi], np.float32))
    return ex.outputs[0].asnumpy()


def test_quantize_int8_symmetric_edges():
    # exact ±range lands on ±127; the asymmetric min widens nothing
    data = np.array([[2.0, -2.0, 1.0, -1.0, 0.0, 1.999]], np.float32)
    q, mn, mx_ = _run_quantize(data, -1.0, 2.0, out_type='int8')
    assert q.dtype == np.int8
    np.testing.assert_array_equal(
        q[0], [127, -127, 64, -64, 0, 127])   # 1.999*127/2 -> 126.9 + .5
    # symmetric range reported: ∓max(|min|,|max|)
    assert mn[0] == -2.0 and mx_[0] == 2.0
    # beyond-range inputs SATURATE at ±127 (never wrap to -128)
    wild = np.array([[50.0, -50.0]], np.float32)
    q, _, _ = _run_quantize(wild, -1.0, 1.0, out_type='int8')
    np.testing.assert_array_equal(q[0], [127, -127])


def test_quantize_int8_rounding_half_away_from_zero():
    # codes at exactly x.5 round AWAY from zero (reference std::round),
    # not to even: 0.5/127ths -> 1, -0.5/127ths -> -1
    step = 1.0 / 127.0
    data = np.array([[0.5 * step, -0.5 * step, 1.5 * step]], np.float32)
    q, _, _ = _run_quantize(data, -1.0, 1.0, out_type='int8')
    np.testing.assert_array_equal(q[0], [1, -1, 2])


def test_quantize_zero_range_inputs():
    # min == max == 0 (an all-zero tensor's calibrated range): both
    # modes map to code 0 and dequantize back to exact zeros — no
    # division by zero, no NaNs
    zeros = np.zeros((2, 3), np.float32)
    for out_type in ('uint8', 'int8'):
        q, mn, mx_ = _run_quantize(zeros, 0.0, 0.0, out_type=out_type)
        assert np.isfinite(q.astype(np.float32)).all()
        np.testing.assert_array_equal(q, np.zeros((2, 3)))
        back = _run_dequantize(q, float(mn[0]), float(mx_[0]))
        np.testing.assert_array_equal(back, zeros)


def test_quantize_int8_round_trip():
    # quantize -> dequantize round trip error bounded by half a step
    rng = np.random.RandomState(7)
    data = rng.uniform(-3, 3, (4, 5)).astype(np.float32)
    q, mn, mx_ = _run_quantize(data, float(data.min()),
                               float(data.max()), out_type='int8')
    back = _run_dequantize(q, float(mn[0]), float(mx_[0]))
    step = max(abs(data.min()), abs(data.max())) / 127.0
    assert np.abs(back - data).max() <= step / 2 + 1e-7
