"""Self-healing fleet tests (fleet_supervisor: ReplicaServer +
FleetRouter + FleetSupervisor + canary/shadow deployment).

Covers the ISSUE-11 contract: the replica-death window (requests in
flight when a replica dies either complete via retry on a survivor or
fail typed within the SLO deadline — never hang, never double-execute
a non-idempotent submit), fast 503s from a fully-dead fleet, the
Retry-After-honoring client helper, canary auto-rollback under the
injected degrade knob / auto-promote when healthy, shadow-replay
divergence counting, the wedge/kill fault knobs, the pure ScalePolicy
hysteresis, replica admin load/unload ops, and the fleet_supervisor_*
profiler family.

The precise fault shapes (connection refused vs connection dropped
after delivery) run against in-process raw-socket stubs and in-process
ReplicaServers — these are the fast tier-1 behavior-keepers for the
end-to-end subprocess drill (test_supervisor_sigkill_respawn_e2e here,
plus the BENCH_FLEET --supervisor arm), which spawns real replica
processes and SIGKILLs one mid-load.
"""
import json
import signal
import socket
import threading
import time
import urllib.request

import numpy as np
import pytest

import mxnet_tpu as mx
from mxnet_tpu import model as model_mod, nd, profiler, sym
from mxnet_tpu import fleet_supervisor as fs
from mxnet_tpu.base import MXNetError
from mxnet_tpu.fleet_supervisor import (FleetRouter, FleetSupervisor,
                                        ReplicaServer, ScalePolicy,
                                        post_with_backoff)
from mxnet_tpu.predictor import Predictor

DIM = 6
HID = 8
OUT = 3


def _mlp():
    data = sym.Variable('data')
    fc1 = sym.FullyConnected(data, num_hidden=HID, name='fc1')
    act = sym.Activation(fc1, act_type='relu')
    return sym.FullyConnected(act, num_hidden=OUT, name='fc2')


def _params(seed=7):
    rs = np.random.RandomState(seed)
    return {
        'fc1_weight': nd.array(rs.randn(HID, DIM).astype(np.float32) * .5),
        'fc1_bias': nd.array(rs.randn(HID).astype(np.float32) * .1),
        'fc2_weight': nd.array(rs.randn(OUT, HID).astype(np.float32) * .5),
        'fc2_bias': nd.array(rs.randn(OUT).astype(np.float32) * .1),
    }


def _loader(seed):
    return lambda: Predictor(symbol=_mlp(), arg_params=_params(seed),
                             input_shapes={'data': (1, DIM)})


def _spec(seed, name='m'):
    return {'name': name, 'loader': _loader(seed), 'max_batch': 4,
            'max_wait_us': 0}


def _x(rows=1, seed=0):
    return np.random.RandomState(seed).randn(rows, DIM).astype(
        np.float32)


def _post_router(router, name='m', seed=0, headers=None, timeout=30):
    host, port = router.address
    req = urllib.request.Request(
        'http://%s:%d/v1/models/%s:predict' % (host, port, name),
        data=json.dumps({'instances': _x(seed=seed).tolist()}).encode(),
        headers=dict({'Content-Type': 'application/json'},
                     **(headers or {})))
    return urllib.request.urlopen(req, timeout=timeout)


# ---------------------------------------------------------------------------
# raw-socket stub backends: precise fault shapes the router must handle
# ---------------------------------------------------------------------------

class _Stub(object):
    """Minimal raw HTTP backend with a scripted behavior per request
    (last entry repeats): 'ok' answers 200, 'drop' reads the full
    request then closes the connection WITHOUT replying (the crash-
    after-delivery shape), '429' answers the overload contract,
    'sleep' stalls 2s then answers (the wedged-service shape)."""

    def __init__(self, script=('ok',)):
        self.script = list(script)
        self.received = []
        self._lock = threading.Lock()
        self._sock = socket.socket()
        self._sock.setsockopt(socket.SOL_SOCKET, socket.SO_REUSEADDR, 1)
        self._sock.bind(('127.0.0.1', 0))
        self._sock.listen(8)
        self.port = self._sock.getsockname()[1]
        self._closed = False
        threading.Thread(target=self._loop, daemon=True).start()

    def _loop(self):
        while not self._closed:
            try:
                conn, _ = self._sock.accept()
            except OSError:
                return
            threading.Thread(target=self._serve, args=(conn,),
                             daemon=True).start()

    def _serve(self, conn):
        try:
            buf = b''
            while b'\r\n\r\n' not in buf:
                chunk = conn.recv(65536)
                if not chunk:
                    return
                buf += chunk
            head, _, body = buf.partition(b'\r\n\r\n')
            n = 0
            for line in head.split(b'\r\n'):
                if line.lower().startswith(b'content-length:'):
                    n = int(line.split(b':', 1)[1])
            while len(body) < n:
                chunk = conn.recv(65536)
                if not chunk:
                    break
                body += chunk
            with self._lock:
                mode = self.script.pop(0) if len(self.script) > 1 \
                    else self.script[0]
                self.received.append(body)
            if mode == 'drop':
                conn.close()
                return
            if mode == 'sleep':
                time.sleep(2.0)
                mode = 'ok'
            if mode == '429':
                payload = (b'{"error": "overloaded", '
                           b'"retry_after_ms": 150}')
                status = b'429 Too Many Requests'
            else:
                payload = b'{"outputs": [[[1.0, 2.0, 3.0]]]}'
                status = b'200 OK'
            conn.sendall(
                b'HTTP/1.1 ' + status +
                b'\r\nContent-Type: application/json'
                b'\r\nContent-Length: ' + str(len(payload)).encode() +
                b'\r\nConnection: close\r\n\r\n' + payload)
            conn.close()
        except OSError:
            pass

    def n_received(self):
        with self._lock:
            return len(self.received)

    def close(self):
        self._closed = True
        try:
            self._sock.close()
        except OSError:
            pass


def _refused_port():
    """A port with no listener: connecting is refused instantly."""
    s = socket.socket()
    s.bind(('127.0.0.1', 0))
    port = s.getsockname()[1]
    s.close()
    return port


# ---------------------------------------------------------------------------
# client retry helper (satellite: Retry-After honor)
# ---------------------------------------------------------------------------

def test_post_with_backoff_honors_retry_after():
    stub = _Stub(script=['429', '429', 'ok'])
    try:
        t0 = time.monotonic()
        status, body = post_with_backoff(
            'http://127.0.0.1:%d/v1/models/m:predict' % stub.port,
            {'instances': [[0.0]]}, deadline_s=30)
        dt = time.monotonic() - t0
        assert status == 200 and 'outputs' in body
        assert stub.n_received() == 3       # two 429s then success
        # backed off per retry_after_ms=150 twice, not a hot loop
        assert dt >= 0.25
    finally:
        stub.close()


def test_post_with_backoff_deadline_is_bounded():
    port = _refused_port()
    t0 = time.monotonic()
    with pytest.raises(MXNetError, match='within'):
        post_with_backoff('http://127.0.0.1:%d/x' % port, {},
                          deadline_s=0.5)
    assert time.monotonic() - t0 < 5.0


# ---------------------------------------------------------------------------
# router: the replica-death window
# ---------------------------------------------------------------------------

def test_router_retries_refused_replica_to_survivor():
    profiler.clear()
    ok = _Stub(script=['ok'])
    with FleetRouter(port=0) as router:
        router.start()
        # insertion [ok, dead]: round robin picks index 1 (dead) first
        router.add_backend('ok', '127.0.0.1', ok.port)
        router.add_backend('dead', '127.0.0.1', _refused_port())
        resp = _post_router(router)
        assert resp.status == 200
        assert json.loads(resp.read())['outputs']
        assert router.stats()['retries'] == 1
        assert ok.n_received() == 1
    ok.close()
    assert profiler.fleet_supervisor_stats()[
        'fleet_supervisor_router_retries'] >= 1


def test_router_replica_death_mid_request_retries_idempotent():
    # the stub that READS the request then drops the connection is the
    # replica-crashed-mid-request shape: the router redispatches the
    # (idempotent) predict to the survivor — the caller sees one clean
    # 200, within the deadline, no hang
    ok = _Stub(script=['ok'])
    dropper = _Stub(script=['drop'])
    with FleetRouter(port=0) as router:
        router.start()
        router.add_backend('ok', '127.0.0.1', ok.port)
        router.add_backend('dropper', '127.0.0.1', dropper.port)
        t0 = time.monotonic()
        resp = _post_router(router)
        assert resp.status == 200
        assert time.monotonic() - t0 < 10.0
        assert dropper.n_received() == 1    # delivered once
        assert ok.n_received() == 1         # retried to the survivor
        assert router.stats()['retries'] == 1
    ok.close()
    dropper.close()


def test_router_never_double_executes_non_idempotent():
    # same crash shape, but the request is marked non-idempotent: a
    # redispatch could double-execute it on the survivor, so the
    # router must fail typed instead — the survivor receives NOTHING
    ok = _Stub(script=['ok'])
    dropper = _Stub(script=['drop'])
    with FleetRouter(port=0) as router:
        router.start()
        router.add_backend('ok', '127.0.0.1', ok.port)
        router.add_backend('dropper', '127.0.0.1', dropper.port)
        with pytest.raises(urllib.error.HTTPError) as ei:
            _post_router(router,
                         headers={'X-Mxtpu-Non-Idempotent': '1'})
        assert ei.value.code == 502
        body = json.loads(ei.value.read())
        assert body['retriable'] is False
        assert dropper.n_received() == 1
        assert ok.n_received() == 0         # never double-executed
        # a non-idempotent request that was NEVER DELIVERED (connect
        # refused) is still safe to redispatch
        router.remove_backend('dropper')
        router.add_backend('dead', '127.0.0.1', _refused_port())
        resp = _post_router(router,
                            headers={'X-Mxtpu-Non-Idempotent': '1'})
        assert resp.status == 200
        assert ok.n_received() == 1
    ok.close()
    dropper.close()


def test_router_dead_fleet_fast_503_and_deadline_bound():
    profiler.clear()
    with FleetRouter(port=0, deadlines={'m': 500.0}) as router:
        router.start()
        host, port = router.address
        # (1) zero backends: fast typed 503 + Retry-After, no hang
        t0 = time.monotonic()
        status, hdrs, body = fs._http_json(
            'POST', host, port, '/v1/models/m:predict',
            {'instances': _x().tolist()}, timeout=10)
        assert status == 503 and body['error'] == 'fleet unavailable'
        assert 'Retry-After' in hdrs
        assert time.monotonic() - t0 < 2.0
        # (2) every backend refused: exhausts the fleet fast
        router.add_backend('d1', '127.0.0.1', _refused_port())
        router.add_backend('d2', '127.0.0.1', _refused_port())
        status, _h, body = fs._http_json(
            'POST', host, port, '/v1/models/m:predict',
            {'instances': _x().tolist()}, timeout=10)
        assert status == 503
        # (3) a wedged replica: the 500ms SLO deadline bounds the
        # wait — typed 503 within ~the deadline, never a hang
        slow = _Stub(script=['sleep'])
        router.remove_backend('d1')
        router.remove_backend('d2')
        router.add_backend('slow', '127.0.0.1', slow.port)
        t0 = time.monotonic()
        status, _h, body = fs._http_json(
            'POST', host, port, '/v1/models/m:predict',
            {'instances': _x().tolist()}, timeout=10)
        dt = time.monotonic() - t0
        assert status == 503
        assert 0.4 <= dt < 1.9, dt          # deadline, not the 2s stall
        slow.close()
    assert profiler.fleet_supervisor_stats()[
        'fleet_supervisor_router_503'] >= 3


# ---------------------------------------------------------------------------
# canary / shadow deployment (in-process replicas)
# ---------------------------------------------------------------------------

def _two_replica_router(monkeypatch=None):
    r1 = ReplicaServer(models=[_spec(1)], index=0).start()
    r2 = ReplicaServer(models=[_spec(1)], index=1).start()
    router = FleetRouter(port=0).start()
    router.add_backend('r0', *r1.address)
    router.add_backend('r1', *r2.address)
    return r1, r2, router


def test_canary_auto_rollback_on_injected_degrade(monkeypatch):
    profiler.clear()
    monkeypatch.setenv('MXNET_TPU_FAULT_CANARY_DEGRADE_MS', '60')
    monkeypatch.setenv('MXNET_TPU_FLEET_CANARY_MIN_SAMPLES', '5')
    r1, r2, router = _two_replica_router()
    try:
        for r in (r1, r2):
            r.load_model('m@v1', _spec(2, name='m@v1'))
        router.start_canary('m', 'm@v1', frac=0.5)
        for i in range(40):
            assert _post_router(router, seed=i).status == 200
            if router.canary_report('m')['state'] != 'running':
                break
        rep = router.canary_report('m')
        assert rep['state'] == 'rolled_back'
        # medians, not p99s: a cold-start outlier in the small stable
        # window can push stable_p99 ABOVE the degraded candidate's —
        # exactly the case the median decision branch exists for
        assert rep['cand_p50_ms'] > rep['stable_p50_ms']
        assert router.stable_arm('m') == 'm'    # stable survived
        # traffic keeps flowing, all on the stable arm
        before = rep['cand_samples']
        for i in range(4):
            assert _post_router(router, seed=i).status == 200
        assert router.canary_report('m')['cand_samples'] == before
        # the candidate arm is unloaded from the replicas
        deadline = time.time() + 10
        while time.time() < deadline and any(
                'm@v1' in r.registry.models() for r in (r1, r2)):
            time.sleep(0.05)
        assert all('m@v1' not in r.registry.models()
                   for r in (r1, r2))
        st = router.statsz()
        assert st['fleet_supervisor'][
            'fleet_supervisor_canary_rollbacks'] >= 1
        assert st['canary']['m']['state'] == 'rolled_back'
    finally:
        router.close()
        r1.close()
        r2.close()


def test_canary_auto_promote_when_healthy(monkeypatch):
    monkeypatch.delenv('MXNET_TPU_FAULT_CANARY_DEGRADE_MS',
                       raising=False)
    monkeypatch.setenv('MXNET_TPU_FLEET_CANARY_MIN_SAMPLES', '4')
    monkeypatch.setenv('MXNET_TPU_FLEET_CANARY_PROMOTE_SAMPLES', '8')
    # identical arms: this test exercises the PROMOTE mechanics, so a
    # throttle spike in the tiny windows must not fake a regression
    monkeypatch.setenv('MXNET_TPU_FLEET_CANARY_REGRESS_FACTOR', '8')
    events = []
    r1, r2, router = _two_replica_router()
    router.on_event = lambda kind, name, info: events.append(
        (kind, name, info['candidate']))
    try:
        for r in (r1, r2):
            r.load_model('m@v1', _spec(1, name='m@v1'))
        router.start_canary('m', 'm@v1', frac=0.5)
        for i in range(60):
            assert _post_router(router, seed=i).status == 200
            if router.canary_report('m')['state'] != 'running':
                break
        assert router.canary_report('m')['state'] == 'promoted'
        assert router.stable_arm('m') == 'm@v1'
        assert events == [('promote', 'm', 'm@v1')]
        # public name still serves (now from the promoted arm), even
        # after the old stable registration is dropped
        deadline = time.time() + 10
        while time.time() < deadline and any(
                'm' in r.registry.models() for r in (r1, r2)):
            time.sleep(0.05)
        assert _post_router(router).status == 200
    finally:
        router.close()
        r1.close()
        r2.close()


def test_canary_served_nowhere_rolls_back_and_serves_stable(
        monkeypatch):
    """A candidate arm that NO replica serves (its loaders all died /
    never converged): clients still get 200s (the router falls back
    to the stable arm per request), the all-backends-404 misses
    accumulate as candidate failures, and the canary ROLLS BACK
    instead of staying pending forever (which would silently wedge
    the train->serve pusher)."""
    monkeypatch.delenv('MXNET_TPU_FAULT_CANARY_DEGRADE_MS',
                       raising=False)
    monkeypatch.setenv('MXNET_TPU_FLEET_CANARY_MIN_SAMPLES', '4')
    r1, r2, router = _two_replica_router()
    try:
        router.start_canary('m', 'm@ghost', frac=1.0)   # served nowhere
        for i in range(16):
            assert _post_router(router, seed=i).status == 200
            if router.canary_report('m')['state'] != 'running':
                break
        rep = router.canary_report('m')
        assert rep['state'] == 'rolled_back'
        assert rep['cand_err_frac'] == 1.0
        assert router.stable_arm('m') == 'm'
    finally:
        router.close()
        r1.close()
        r2.close()


def test_shadow_tee_counts_divergences(monkeypatch):
    profiler.clear()
    monkeypatch.delenv('MXNET_TPU_FAULT_CANARY_DEGRADE_MS',
                       raising=False)
    r1, r2, router = _two_replica_router()
    try:
        # identical weights -> zero divergence
        for r in (r1, r2):
            r.load_model('m@same', _spec(1, name='m@same'))
        router.start_canary('m', 'm@same', mode='shadow')
        for i in range(6):
            assert _post_router(router, seed=i).status == 200
        assert router.shadow_drain(timeout=30)
        rep = router.canary_report('m')
        assert rep['mode'] == 'shadow'
        assert rep['shadow_requests'] >= 6
        assert rep['shadow_divergences'] == 0
        assert rep['cand_samples'] == 0     # candidate never served
        # different weights -> every teed request diverges
        for r in (r1, r2):
            r.load_model('m@diff', _spec(2, name='m@diff'))
        router.start_canary('m', 'm@diff', mode='shadow')
        for i in range(6):
            assert _post_router(router, seed=i).status == 200
        assert router.shadow_drain(timeout=30)
        rep = router.canary_report('m')
        assert rep['shadow_divergences'] >= 5
        # replay of the logged bodies against an arm, on demand
        out = router.replay('m', arm='m@diff')
        assert out['replayed'] >= 6
        assert out['divergences'] == out['replayed']
        out = router.replay('m', arm='m@same')
        assert out['divergences'] == 0
        fsn = profiler.fleet_supervisor_stats()
        assert fsn['fleet_supervisor_shadow_requests'] >= 12
        assert fsn['fleet_supervisor_shadow_divergences'] >= 5
    finally:
        router.close()
        r1.close()
        r2.close()


# ---------------------------------------------------------------------------
# replica admin ops + fault knobs
# ---------------------------------------------------------------------------

def test_replica_admin_load_unload_roundtrip(tmp_path):
    prefix = str(tmp_path / 'admin_m')
    model_mod.save_checkpoint(prefix, 2, _mlp(), _params(9), {})
    with ReplicaServer(models=[], index=0) as rs:
        rs.start()
        host, port = rs.address
        spec = {'prefix': prefix, 'epoch': 2,
                'input_shapes': {'data': [1, DIM]},
                'max_batch': 4, 'max_wait_us': 0}
        status, _h, body = fs._http_json(
            'POST', host, port, '/v1/models/hot:load', spec)
        assert status == 200 and body['status'] == 'loaded'
        # idempotent re-load (a supervisor retry) is not an error
        status, _h, body = fs._http_json(
            'POST', host, port, '/v1/models/hot:load', spec)
        assert status == 200 and body['status'] == 'already'
        status, _h, body = fs._http_json(
            'POST', host, port, '/v1/models/hot:predict',
            {'instances': _x().tolist()})
        assert status == 200
        assert np.asarray(body['outputs'][0]).shape == (1, OUT)
        status, _h, body = fs._http_json(
            'POST', host, port, '/v1/models/hot:unload', {})
        assert status == 200
        status, _h, body = fs._http_json(
            'POST', host, port, '/v1/models/hot:predict',
            {'instances': _x().tolist()})
        assert status == 404
        status, _h, body = fs._http_json(
            'POST', host, port, '/v1/models/ghost:unload', {})
        assert status == 404


def test_fault_knob_parsers(monkeypatch):
    monkeypatch.setenv('MXNET_TPU_FAULT_REPLICA_KILL_AFTER_S', '3.5')
    assert fs.replica_kill_after_s(0) == 3.5
    assert fs.replica_kill_after_s(2) == 3.5
    monkeypatch.setenv('MXNET_TPU_FAULT_REPLICA_KILL_AFTER_S', '1:2.0')
    assert fs.replica_kill_after_s(0) is None
    assert fs.replica_kill_after_s(1) == 2.0
    monkeypatch.delenv('MXNET_TPU_FAULT_REPLICA_KILL_AFTER_S')
    assert fs.replica_kill_after_s(0) is None
    monkeypatch.setenv('MXNET_TPU_FAULT_REPLICA_WEDGE', '0,2')
    assert fs.replica_wedged(0, 0.0) and fs.replica_wedged(2, 99.0)
    assert not fs.replica_wedged(1, 99.0)
    monkeypatch.setenv('MXNET_TPU_FAULT_REPLICA_WEDGE', '1:5')
    assert not fs.replica_wedged(1, 4.0)
    assert fs.replica_wedged(1, 6.0)
    assert not fs.replica_wedged(0, 6.0)
    monkeypatch.setenv('MXNET_TPU_FAULT_CANARY_DEGRADE_MS', '80')
    assert fs.canary_degrade_ms() == 80.0
    assert fs.canary_degrade_ms('m@v1') == 80.0    # bare MS: any arm
    monkeypatch.setenv('MXNET_TPU_FAULT_CANARY_DEGRADE_MS', '@v1:90')
    assert fs.canary_degrade_ms('m@v1') == 90.0
    assert fs.canary_degrade_ms('m@v2') == 0.0     # other arms healthy
    assert fs.canary_degrade_ms() == 0.0           # no name: no match
    monkeypatch.delenv('MXNET_TPU_FAULT_CANARY_DEGRADE_MS')
    assert fs.canary_degrade_ms() == 0.0
    monkeypatch.setenv('MXNET_TPU_FAULT_PUSH_FAIL', '2')
    assert fs.push_fail_n() == 2
    monkeypatch.delenv('MXNET_TPU_FAULT_PUSH_FAIL')
    assert fs.push_fail_n() is None


# ---------------------------------------------------------------------------
# scale policy (pure decision over the PR-10 counter windows)
# ---------------------------------------------------------------------------

def test_scale_policy_hysteresis():
    p = ScalePolicy(up_after=3, down_after=4, backlog_hot=64)
    hot = {'p99_over_deadline': True, 'backlog_rows': 0,
           'requests_delta': 5}
    idle = {'p99_over_deadline': False, 'backlog_rows': 0,
            'requests_delta': 0}
    busy = {'p99_over_deadline': False, 'backlog_rows': 3,
            'requests_delta': 9}
    assert [p.decide(hot) for _ in range(3)] == [0, 0, 1]
    # a healthy-busy window resets the idle streak — no flapping
    assert [p.decide(idle) for _ in range(3)] == [0, 0, 0]
    assert p.decide(busy) == 0
    assert [p.decide(idle) for _ in range(4)] == [0, 0, 0, -1]
    # backlog alone (no deadline) also counts as hot
    deep = {'p99_over_deadline': False, 'backlog_rows': 100,
            'requests_delta': 1}
    assert [p.decide(deep) for _ in range(3)] == [0, 0, 1]


# ---------------------------------------------------------------------------
# supervisor: wedge detection, restart budget (no subprocesses)
# ---------------------------------------------------------------------------

def _fake_supervisor(tmp_path):
    return FleetSupervisor(
        models=[{'name': 'm', 'prefix': str(tmp_path / 'nope'),
                 'input_shapes': {'data': [1, DIM]}}], replicas=1)


def test_supervisor_declares_wedged_replica_dead(monkeypatch,
                                                tmp_path):
    profiler.clear()
    monkeypatch.setenv('MXNET_TPU_FLEET_DEAD_AFTER_S', '0.3')
    monkeypatch.setenv('MXNET_TPU_FAULT_REPLICA_WEDGE', '7')
    wedged = ReplicaServer(models=[_spec(1)], index=7).start()
    sup = _fake_supervisor(tmp_path)
    try:
        rep = fs._Replica(7)
        rep.host, rep.port = wedged.address
        rep.last_ok = time.monotonic() - 10.0
        sup._replicas.append(rep)
        sup.router.add_backend(rep.bid, rep.host, rep.port)
        monkeypatch.setattr(sup, '_respawn_due', lambda: None)
        t0 = time.monotonic()
        sup._health_once()
        # the wedge answers nothing: detection is by probe TIMEOUT
        assert time.monotonic() - t0 < 5.0
        assert sup.router.backends() == []      # routing stopped
        assert sup._dead_pending and \
            sup._dead_pending[0].index == 7     # respawn scheduled
        assert rep.backoff >= fs.restart_backoff_s()
        assert rep.next_attempt > t0
    finally:
        sup.router.close()
        wedged.close()


def test_supervisor_restart_budget_abandons_slot(monkeypatch,
                                                 tmp_path):
    monkeypatch.setenv('MXNET_TPU_FLEET_MAX_RESTARTS', '1')
    sup = _fake_supervisor(tmp_path)
    try:
        rep = fs._Replica(0)
        rep.host, rep.port = '127.0.0.1', _refused_port()
        sup._declare_dead(rep, 'test kill 1')
        assert len(sup._dead_pending) == 1      # within budget
        sup._dead_pending.clear()
        sup._declare_dead(rep, 'test kill 2')
        assert sup._dead_pending == []          # budget exhausted
        assert sup.stats()['abandoned_slots'] == 1
    finally:
        sup.router.close()


# ---------------------------------------------------------------------------
# push vs replica death/respawn (ISSUE-14 satellite: the reconcile fix)
# ---------------------------------------------------------------------------

def _ckpt_prefix(tmp_path, tag, seed):
    prefix = str(tmp_path / tag)
    model_mod.save_checkpoint(prefix, 0, _mlp(), _params(seed), {})
    return prefix


def _push_spec(prefix):
    return {'name': 'm', 'prefix': prefix, 'epoch': 0,
            'input_shapes': {'data': [1, DIM]},
            'max_batch': 4, 'max_wait_us': 0}


def _fake_rep(index, host, port):
    rep = fs._Replica(index)
    rep.host, rep.port = host, port
    return rep


def test_push_survives_dead_replica_mid_fanout(tmp_path):
    """A replica that died before/while the push fans out must NOT
    abort the push: the live replicas get the candidate, the canary
    opens, and the pending set keeps the candidate so the dead slot's
    respawn reconciles to it.  (Previously one OSError unwound the
    whole push.)"""
    prefix_a = _ckpt_prefix(tmp_path, 'stable', 1)
    prefix_b = _ckpt_prefix(tmp_path, 'cand', 2)
    live = ReplicaServer(models=[_push_spec(prefix_a)], index=0).start()
    sup = FleetSupervisor(models=[_push_spec(prefix_a)], replicas=2)
    try:
        sup._replicas = [
            _fake_rep(0, '127.0.0.1', _refused_port()),   # dead first
            _fake_rep(1, *live.address)]
        cand = sup.push('m', prefix_b, epoch=0, frac=0.5)
        assert cand in live.registry.models()
        assert sup.push_active('m')
        assert prefix_b in sup.active_prefixes('m')
        rep = sup.router.canary_report('m')
        assert rep is not None and rep['state'] == 'running'
    finally:
        sup.router.close()
        live.close()


def test_push_refused_by_live_replica_still_unwinds(tmp_path,
                                                    monkeypatch):
    """A REFUSAL (not a transport failure) keeps the abort semantics:
    the fleet must never route to an arm only some replicas serve."""
    monkeypatch.setenv('MXNET_TPU_SERVE_STRICT_BUDGET', '1')
    prefix_a = _ckpt_prefix(tmp_path, 'stable2', 1)
    prefix_b = _ckpt_prefix(tmp_path, 'cand2', 2)
    live = ReplicaServer(models=[], index=0,
                         budget_bytes=1).start()   # any load -> 507
    sup = FleetSupervisor(models=[_push_spec(prefix_a)], replicas=1)
    try:
        sup._replicas = [_fake_rep(0, *live.address)]
        with pytest.raises(MXNetError, match='refused'):
            sup.push('m', prefix_b, epoch=0)
        assert not sup.push_active('m')      # pending unwound
    finally:
        sup.router.close()
        live.close()


def test_respawn_reconciles_to_pushed_and_promoted_model(tmp_path):
    """The respawn-vs-push race closer: a replica that rejoins with
    the PRE-push arm set baked into its spawn config converges to the
    fleet's intended model set — the pending candidate while a push is
    judged, and the promoted arm (old stable dropped) afterwards."""
    prefix_a = _ckpt_prefix(tmp_path, 'stable3', 1)
    prefix_b = _ckpt_prefix(tmp_path, 'cand3', 2)
    live = ReplicaServer(models=[_push_spec(prefix_a)], index=0).start()
    sup = FleetSupervisor(models=[_push_spec(prefix_a)], replicas=1)
    try:
        sup._replicas = [_fake_rep(0, *live.address)]
        cand = sup.push('m', prefix_b, epoch=0, frac=0.5)
        # a "respawned" replica that booted from the pre-push config
        rejoin = ReplicaServer(models=[_push_spec(prefix_a)],
                               index=1).start()
        try:
            sup._reconcile(*rejoin.address, cfg_names=('m',))
            assert set(rejoin.registry.models()) == {'m', cand}
            # the push promotes: desired set flips to the candidate
            sup._on_router_event('promote', 'm',
                                 {'candidate': cand, 'report': None})
            assert not sup.push_active('m')
            assert sup.active_prefixes('m') == {prefix_b}
            sup._reconcile(*rejoin.address, cfg_names=('m', cand))
            assert set(rejoin.registry.models()) == {cand}
        finally:
            rejoin.close()
    finally:
        sup.router.close()
        live.close()


# ---------------------------------------------------------------------------
# the end-to-end drill: real replica processes, SIGKILL mid-load
# ---------------------------------------------------------------------------

def test_supervisor_sigkill_respawn_e2e(monkeypatch, tmp_path):
    """The acceptance window: requests in flight when a replica is
    SIGKILLed all complete (router retry + client backoff — zero lost
    accepted requests), and the supervisor respawns the replica within
    the grace window, visible in /statsz."""
    prefix = str(tmp_path / 'fleet_m')
    model_mod.save_checkpoint(prefix, 0, _mlp(), _params(1), {})
    monkeypatch.setenv('MXNET_TPU_FLEET_HEARTBEAT_S', '0.2')
    monkeypatch.setenv('MXNET_TPU_FLEET_DEAD_AFTER_S', '1.0')
    sup = FleetSupervisor(
        models=[{'name': 'm', 'prefix': prefix, 'epoch': 0,
                 'input_shapes': {'data': [1, DIM]},
                 'max_batch': 4, 'max_wait_us': 0,
                 'deadline_ms': 10000}],
        replicas=2)
    try:
        sup.start()
        sup.wait_healthy(timeout=120)
        host, port = sup.router.address
        url = 'http://%s:%d/v1/models/m:predict' % (host, port)
        x = _x().tolist()
        failures = []
        done = threading.Event()

        def client():
            for _ in range(30):
                try:
                    st, _ = post_with_backoff(url, {'instances': x},
                                              deadline_s=60)
                    if st != 200:
                        failures.append(st)
                except Exception as e:
                    failures.append(repr(e))
            done.set()

        t = threading.Thread(target=client)
        t.start()
        time.sleep(0.3)                 # requests in flight
        victim = sup.replicas()[0]
        victim.proc.send_signal(signal.SIGKILL)
        t_kill = time.monotonic()
        t.join(timeout=180)
        assert done.is_set(), 'client hung through the replica death'
        assert not failures, failures[:3]
        # the supervisor respawns within the grace window
        respawned = False
        while time.monotonic() - t_kill < 90:
            live = sup.replicas()
            if len(live) >= 2 and all(sup._probe(r) for r in live):
                respawned = True
                break
            time.sleep(0.2)
        assert respawned, 'replica not respawned within the window'
        assert sup.stats()['restarts'] >= 1
        st = json.loads(urllib.request.urlopen(
            'http://%s:%d/statsz' % (host, port), timeout=30).read())
        assert st['fleet_supervisor'][
            'fleet_supervisor_replica_restarts'] >= 1
        assert st['supervisor']['restarts'] >= 1
        assert len([r for r in st['supervisor']['replicas']
                    if r['alive']]) >= 2
    finally:
        sup.stop()


# ---------------------------------------------------------------------------
# profiler family
# ---------------------------------------------------------------------------

def test_fleet_supervisor_counters_in_summary_and_dump(tmp_path):
    profiler.clear()
    profiler.add_fleet_supervisor_stats(
        replica_spawns=3, replica_restarts=1, replica_retires=1,
        router_requests=10, router_retries=2, router_503=1,
        canary_pushes=1, canary_rollbacks=1, shadow_requests=4,
        shadow_divergences=2, replicas_live=2)
    fsn = profiler.fleet_supervisor_stats()
    assert fsn['fleet_supervisor_replica_spawns'] == 3
    assert fsn['fleet_supervisor_replicas_live'] == 2   # gauge
    profiler.add_fleet_supervisor_stats(replicas_live=3)
    assert profiler.fleet_supervisor_stats()[
        'fleet_supervisor_replicas_live'] == 3
    text = profiler.summary(print_out=False)
    for key in ('fleet_supervisor_replica_restarts',
                'fleet_supervisor_replicas_live',
                'fleet_supervisor_router_retries',
                'fleet_supervisor_canary_rollbacks',
                'fleet_supervisor_shadow_divergences'):
        assert key in text
    out = tmp_path / 'fleet_sup_profile.json'
    profiler.profiler_set_config(filename=str(out))
    profiler.dump_profile()
    events = json.loads(out.read_text())['traceEvents']
    meta = [e for e in events if e.get('name') == 'fleet_supervisor']
    assert meta and \
        meta[0]['args']['fleet_supervisor_replica_spawns'] == 3
    profiler.clear()
    assert profiler.fleet_supervisor_stats()[
        'fleet_supervisor_replica_spawns'] == 0
