"""Distributed KVStore tests (model: reference
tests/nightly/dist_sync_kvstore.py exact-arithmetic assertions, run as
threads in-process and as real processes via tools/launch.py — the
reference's launcher=local strategy, SURVEY.md §4)."""
import os
import subprocess
import sys
import threading

import numpy as np
import pytest

import mxnet_tpu as mx
from mxnet_tpu import kvstore_server as ps


@pytest.fixture(autouse=True)
def _ps_token(monkeypatch):
    """In-process PS tests run as a launched job would: launch.py mints
    a DMLC_PS_TOKEN per job (required by the set_optimizer channel).
    Tests probing the no-token policy delete it explicitly."""
    monkeypatch.setenv('DMLC_PS_TOKEN', 'test-job-secret')


def _start_server(num_workers, sync=True):
    srv = ps.KVStoreServer(0, num_workers, sync_mode=sync)
    t = threading.Thread(target=srv.run, daemon=True)
    t.start()
    return srv, t


def test_dist_sync_arithmetic():
    """value after R rounds of W workers pushing rank-dependent grads
    matches the exact sum (reference dist_sync_kvstore.py:50-58)."""
    import pickle
    W, R = 3, 4
    srv, t = _start_server(W)
    clients = [ps.DistServerClient('127.0.0.1', srv.port, 1)
               for _ in range(W)]
    shape = (4, 5)
    clients[0].init('w', np.zeros(shape, np.float32))
    # reference nightly sets the accumulate-grad 'test' optimizer
    # server-side; without an updater the server ASSIGNS the merged
    # gradient (reference CopyFromTo(merged, &stored))
    clients[0].set_optimizer(pickle.dumps(
        mx.optimizer.create('test', rescale_grad=1.0)))

    errs = []

    def worker(rank):
        try:
            c = clients[rank]
            for r in range(R):
                c.push('w', np.full(shape, float(rank + 1), np.float32))
                val = c.pull('w')
                # after round r+1: sum of (1+2+...+W) per round
                expect = (r + 1) * sum(range(1, W + 1))
                np.testing.assert_allclose(val, expect)
                c.barrier()
        except Exception as e:  # surface thread failures
            errs.append(e)

    threads = [threading.Thread(target=worker, args=(i,)) for i in range(W)]
    for th in threads:
        th.start()
    for th in threads:
        th.join(timeout=60)
    assert not errs, errs
    clients[0].stop_servers()
    t.join(timeout=10)


def test_dist_sync_server_side_optimizer(monkeypatch):
    """Optimizer runs on the server (reference set_optimizer pickles it
    to servers; weight = -lr * sum(grads) after one round).  The
    channel transports executable code, so it demands the real shared
    secret: without DMLC_PS_TOKEN the server refuses it."""
    import pickle
    W = 2
    srv, t = _start_server(W)
    clients = [ps.DistServerClient('127.0.0.1', srv.port, 1)
               for _ in range(W)]
    clients[0].init(3, np.zeros((3,), np.float32))
    opt = mx.optimizer.create('sgd', learning_rate=0.1, rescale_grad=1.0,
                              wd=0.0)
    monkeypatch.delenv('DMLC_PS_TOKEN', raising=False)
    from mxnet_tpu.base import MXNetError
    with pytest.raises(MXNetError, match='DMLC_PS_TOKEN'):
        clients[0].set_optimizer(pickle.dumps(opt))
    monkeypatch.setenv('DMLC_PS_TOKEN', 'job-secret')
    # NOTE: the token is read by _frame_key on BOTH ends; these
    # in-process clients pick it up via the same env
    clients[0].set_optimizer(pickle.dumps(opt))

    def worker(rank):
        clients[rank].push(3, np.ones((3,), np.float32))
        v = clients[rank].pull(3)
        np.testing.assert_allclose(v, -0.1 * W * np.ones(3), rtol=1e-6)

    ths = [threading.Thread(target=worker, args=(i,)) for i in range(W)]
    for th in ths:
        th.start()
    for th in ths:
        th.join(timeout=60)
    clients[0].stop_servers()


def test_dist_async_updates_immediately():
    srv, t = _start_server(2, sync=False)
    c = ps.DistServerClient('127.0.0.1', srv.port, 1)
    c.init('k', np.zeros((2,), np.float32))
    c.push('k', np.ones((2,), np.float32))
    # async: no waiting for the second worker
    np.testing.assert_allclose(c.pull('k'), 1.0)
    c.stop_servers()


def test_key_sharding_layout():
    assert ps._key_to_server(0, 3) == 0
    sids = {ps._key_to_server(k, 3) for k in range(20)}
    assert sids == {0, 1, 2}
    # string keys shard deterministically
    assert ps._key_to_server('fc_weight', 4) == \
        ps._key_to_server('fc_weight', 4)


def test_kvstore_dist_ps_facade():
    """mx.kv.create('dist_sync') with the DMLC env -> PS-backed store
    with reference push/pull/rank semantics."""
    srv, t = _start_server(1)
    old = dict(os.environ)
    os.environ.update({'DMLC_PS_ROOT_URI': '127.0.0.1',
                       'DMLC_PS_ROOT_PORT': str(srv.port),
                       'DMLC_NUM_WORKER': '1', 'DMLC_NUM_SERVER': '1',
                       'DMLC_WORKER_ID': '0'})
    try:
        kv = mx.kvstore.create('dist_sync')
        assert kv.rank == 0 and kv.num_workers == 1
        kv.init('p', mx.nd.array(np.arange(4, dtype=np.float32)))
        kv.push('p', mx.nd.array(np.ones(4, np.float32)))
        out = mx.nd.zeros((4,))
        kv.pull('p', out=out)
        np.testing.assert_allclose(out.asnumpy(), np.ones(4))
        kv.stop_servers()
    finally:
        os.environ.clear()
        os.environ.update(old)


_WORKER_SCRIPT = r'''
import os
import numpy as np
import mxnet_tpu as mx

kv = mx.kvstore.create('dist_sync')
rank, W = kv.rank, kv.num_workers
kv.init('x', mx.nd.zeros((2, 2)))
# every worker calls set_optimizer (Module.init_optimizer does); only
# rank 0 actually sends it to the servers
kv.set_optimizer(mx.optimizer.create('test', rescale_grad=1.0))
for r in range(3):
    kv.push('x', mx.nd.array(np.full((2, 2), float(rank + 1), np.float32)))
    out = mx.nd.zeros((2, 2))
    kv.pull('x', out=out)
    expect = (r + 1) * sum(range(1, W + 1))
    np.testing.assert_allclose(out.asnumpy(), expect)
    kv.barrier()
kv.barrier()
if rank == 0:
    kv.stop_servers()
print('WORKER_OK rank=%d' % rank)
'''


@pytest.mark.slow
def test_launch_local_multiprocess(tmp_path):
    """slow (~10s, round-16 headroom): the launcher-spawned dist_sync
    E2E also runs in dryrun phase (f); the PS protocol and sync-SGD
    arithmetic stay tier-1 via the in-process tests in this file, and
    launch.py process semantics via test_dist_runtime's launcher
    tests.

    Real multi-process dist_sync through tools/launch.py (the
    reference's `launch.py -n 2 --launcher local` nightly pattern)."""
    script = tmp_path / 'worker.py'
    script.write_text(_WORKER_SCRIPT)
    repo = os.path.abspath(os.path.join(os.path.dirname(__file__), '..'))
    env = dict(os.environ)
    env['JAX_PLATFORMS'] = 'cpu'
    env['PYTHONPATH'] = repo + os.pathsep + env.get('PYTHONPATH', '')
    env.pop('DMLC_PS_ROOT_URI', None)
    res = subprocess.run(
        [sys.executable, os.path.join(os.path.dirname(__file__), '..',
                                      'tools', 'launch.py'),
         '-n', '2', '-s', '1', '--launcher', 'local',
         sys.executable, str(script)],
        capture_output=True, text=True, timeout=300, env=env,
        cwd=os.path.join(os.path.dirname(__file__), '..'))
    assert res.returncode == 0, (res.stdout, res.stderr)
    assert 'WORKER_OK rank=0' in res.stdout
    assert 'WORKER_OK rank=1' in res.stdout


def test_torch_bridge():
    import torch
    a = mx.nd.array(np.arange(6, dtype=np.float32).reshape(2, 3))
    t = mx.th.as_torch(a)
    assert torch.is_tensor(t)
    back = mx.th.from_torch(t * 2)
    np.testing.assert_allclose(back.asnumpy(), a.asnumpy() * 2)
    mm = mx.th.function(torch.mm)
    out = mm(a, mx.nd.array(np.ones((3, 2), np.float32)))
    np.testing.assert_allclose(out.asnumpy(),
                               a.asnumpy() @ np.ones((3, 2), np.float32))
    # lazy attribute wrapping
    out2 = mx.th.relu(mx.nd.array(np.array([-1.0, 2.0], np.float32)))
    np.testing.assert_allclose(out2.asnumpy(), [0.0, 2.0])


def test_executor_manager_facade():
    from mxnet_tpu import sym
    data = sym.Variable('data')
    net = sym.SoftmaxOutput(sym.FullyConnected(data, num_hidden=4,
                                               name='fc'), name='softmax')
    it = mx.io.NDArrayIter(np.random.rand(8, 6).astype(np.float32),
                           np.zeros(8, np.float32), batch_size=8,
                           label_name='softmax_label')
    mgr = mx.executor_manager.DataParallelExecutorManager(
        net, mx.cpu(), it)
    assert 'fc_weight' in mgr.param_names
    batch = next(iter(it))
    mgr.load_data_batch(batch)
    mgr.forward(is_train=True)
    mgr.backward()
    assert mgr.grad_arrays[0] is not None


def test_split_input_slice():
    from mxnet_tpu.executor_manager import _split_input_slice
    s = _split_input_slice(10, [1, 1])
    assert s == [slice(0, 5), slice(5, 10)]
    s = _split_input_slice(9, [2, 1])
    assert s[0] == slice(0, 6) and s[1] == slice(6, 9)


def test_num_dead_node_heartbeats():
    """PS failure detection (reference ps-lite heartbeats ->
    get_num_dead_node, kvstore.h:287): never-seen workers age from
    server start; any RPC from an identified worker stamps liveness."""
    import time
    srv, t = _start_server(2)
    c0 = ps.DistServerClient('127.0.0.1', srv.port, 1, rank=0)
    time.sleep(0.15)
    c0.heartbeat(0)                  # worker 0 fresh
    # worker 1 NEVER connected: counts dead once the server has been up
    # longer than the timeout (startup-crash detection)
    assert c0.num_dead(timeout_sec=0.1) == 1
    # ordinary RPCs double as heartbeats: pull traffic keeps 0 alive
    c0.init('k', np.zeros(2, np.float32))
    time.sleep(0.15)
    c0.pull('k')
    c1 = ps.DistServerClient('127.0.0.1', srv.port, 1, rank=1)
    assert c0.num_dead(timeout_sec=0.12) == 0
    c0.stop_servers()


def test_frame_hmac_rejects_tampering():
    """Frames with bad HMAC tags must be dropped before unpickling
    (ADVICE.md: unauthenticated pickle-over-TCP surface)."""
    import socket as _socket
    import struct
    import pickle
    import hashlib
    import hmac as _hmac
    from mxnet_tpu import kvstore_server as srv
    a, b = _socket.socketpair()
    try:
        srv._send_msg(a, ('ping', 1))
        assert srv._recv_msg(b) == ('ping', 1)
        # tampered payload under a wrong key (hmac alg slot)
        payload = pickle.dumps(('evil',))
        bad_tag = _hmac.new(b'wrong-key', payload,
                            hashlib.sha256).digest()
        a.sendall(struct.pack('<QB', len(payload), srv._ALG_HMAC) +
                  b'\x00' * 16 + bad_tag + payload)
        import pytest as _pytest
        with _pytest.raises(ConnectionError):
            srv._recv_msg(b)
    finally:
        a.close()
        b.close()


def test_wire_codec_roundtrip_and_no_pickle():
    """The PS data path speaks a restricted codec: command tuples of
    scalars/strings/ndarrays round-trip exactly, and objects whose
    decoding could run code (arbitrary classes) are refused at encode
    time — a verified-but-malicious frame can corrupt numbers, never
    execute."""
    from mxnet_tpu import kvstore_server as srv
    cases = [
        ('push', 3, np.arange(12, dtype=np.float32).reshape(3, 4)),
        ('pull', 'fc1_weight', 0),
        ('ok', {'a': np.zeros((2, 2), np.float64), 7: np.ones(3)}),
        ('init', -5, np.array(2.5)),
        ('num_dead', 1.5), ('flag', True, False, None),
        ('blob', b'\x00\x01pickle-stays-opaque'),
        ('big', 2 ** 80),  # int keys are not range-limited
    ]
    for msg in cases:
        out = srv._decode(srv._encode(msg))
        assert out[0] == msg[0]
        for got, want in zip(out, msg):
            if isinstance(want, np.ndarray):
                assert got.dtype == want.dtype
                np.testing.assert_array_equal(got, want)
            elif isinstance(want, dict):
                for k in want:
                    np.testing.assert_array_equal(got[k], want[k])
            else:
                assert got == want and type(got) is type(want)

    class Evil:
        def __reduce__(self):
            return (print, ('pwned',))

    with pytest.raises(ValueError):
        srv._encode(('push', 1, Evil()))
    with pytest.raises(ValueError):
        srv._encode(('push', np.array([Evil()], dtype=object)))


def test_forged_frame_cannot_execute_code(tmp_path):
    """Even a frame with a VALID tag (attacker knows the derived key —
    the no-token loopback case) must not be able to run code: a pickle
    bomb on the data path fails to decode instead of executing."""
    import socket as _socket
    import struct
    import pickle
    import hashlib
    import hmac as _hmac
    from mxnet_tpu import kvstore_server as srv
    canary = tmp_path / 'pwned'

    class Bomb:
        def __reduce__(self):
            return (open, (str(canary), 'w'))

    payload = pickle.dumps(('push', 1, Bomb()))
    tag = _hmac.new(srv._frame_key(), payload, hashlib.sha256).digest()
    a, b = _socket.socketpair()
    try:
        a.sendall(struct.pack('<QB', len(payload), srv._ALG_HMAC) +
                  b'\x00' * 16 + tag + payload)
        with pytest.raises(ConnectionError):
            srv._recv_msg(b)
    finally:
        a.close()
        b.close()
    assert not canary.exists(), 'forged frame executed code'


def test_oversize_frame_rejected_before_allocation():
    """An unauthenticated peer must not be able to force a multi-GB
    allocation via the 64-bit length prefix (ADVICE.md round 3)."""
    import socket as _socket
    import struct
    from mxnet_tpu import kvstore_server as srv
    a, b = _socket.socketpair()
    try:
        a.sendall(struct.pack('<QB', srv._MAX_FRAME_BYTES + 1, 0) +
                  b'\x00' * 48)
        with pytest.raises(ConnectionError, match='exceeds limit'):
            srv._recv_msg(b)
    finally:
        a.close()
        b.close()


def test_frame_poly1305_roundtrip_and_tampering(monkeypatch):
    """The fast Poly1305 frame MAC (one-time key per nonce, derived
    through HMAC of the frame key — docs/PERF.md round 5): frames
    round-trip, and flipping one payload bit or the nonce fails
    verification."""
    import socket as _socket
    from mxnet_tpu import kvstore_server as srv
    if not srv._poly1305_cls():
        pytest.skip('cryptography not installed')
    monkeypatch.setenv('MXNET_TPU_PS_MAC', 'poly')
    a, b = _socket.socketpair()
    try:
        srv._send_msg(a, ('ping', np.arange(4096, dtype=np.float32)))
        out = srv._recv_msg(b)
        assert out[0] == 'ping'
        np.testing.assert_array_equal(
            out[1], np.arange(4096, dtype=np.float32))
        # flip a payload bit behind a valid header
        parts = srv._build_frame(('ping', 7))
        blob = bytearray(b''.join(bytes(p) for p in parts))
        blob[-1] ^= 1
        a.sendall(blob)
        with pytest.raises(ConnectionError, match='MAC verification'):
            srv._recv_msg(b)
        # flip a nonce bit (derives a different one-time key)
        parts = srv._build_frame(('ping', 8))
        blob = bytearray(b''.join(bytes(p) for p in parts))
        blob[9] ^= 1
        a.sendall(blob)
        with pytest.raises(ConnectionError, match='MAC verification'):
            srv._recv_msg(b)
    finally:
        a.close()
        b.close()


def test_wire_dtype_rejects_non_numeric():
    """Only numeric dtypes (plus the ml_dtypes whitelist) ride the wire;
    strN/void/datetime have surprising frombuffer semantics."""
    from mxnet_tpu import kvstore_server as srv
    for good in ('float32', 'int64', 'uint8', 'bool', 'complex64',
                 'bfloat16'):
        assert srv._wire_dtype(good).itemsize > 0
    for bad in ('U8', 'S16', 'V4', 'datetime64[ns]', 'object'):
        with pytest.raises(ValueError):
            srv._wire_dtype(bad)


def test_no_token_refuses_remote_bind(monkeypatch):
    """A server asked to bind a non-loopback interface without
    DMLC_PS_TOKEN must refuse to start (the derived frame key is
    guessable by anyone who can reach the port); with a token, or on
    loopback, it starts."""
    from mxnet_tpu import kvstore_server as srv
    monkeypatch.delenv('DMLC_PS_TOKEN', raising=False)
    monkeypatch.setenv('DMLC_PS_BIND_URI', '0.0.0.0')
    with pytest.raises(RuntimeError, match='DMLC_PS_TOKEN'):
        srv.KVStoreServer(0, 1)
    # with a token the same bind is allowed
    monkeypatch.setenv('DMLC_PS_TOKEN', 'secret')
    s = srv.KVStoreServer(0, 1)
    s.listener.close()
    # loopback without a token stays fine (single-host local mode)
    monkeypatch.delenv('DMLC_PS_TOKEN')
    monkeypatch.setenv('DMLC_PS_BIND_URI', '127.0.0.1')
    s = srv.KVStoreServer(0, 1)
    s.listener.close()


def test_sync_pull_cache_not_stale():
    """The sync-mode pull-frame cache must key on the ACTUAL snapshot
    version: a client re-pulling at the same min_version after the
    store advanced has to see the new weights (round-5 review repro:
    the requested-version key served version-0 weights forever)."""
    import threading
    from mxnet_tpu import kvstore_server as ps
    srv = ps.KVStoreServer(0, 1, sync_mode=True)
    t = threading.Thread(target=srv.run, daemon=True)
    t.start()
    c = ps.DistServerClient('127.0.0.1', srv.port, 1)
    try:
        c.init('w', np.zeros(4, np.float32))
        f0 = srv._pull_frame((('w', 0),))
        srv._handle_push('w', np.ones(4, np.float32))
        f1 = srv._pull_frame((('w', 0),))
        assert f0 != f1, 'cache served pre-push weights'
        v, ver = srv._pull_value('w', 0)
        assert ver == 1 and v[0] != 0.0
    finally:
        c.stop_servers()
