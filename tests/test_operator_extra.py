"""Spatial / loss / linalg / multisample operator tests, exercised through
the test_utils oracles (model: reference tests/python/unittest/
test_operator.py numeric-gradient style)."""
import numpy as np
import scipy.linalg  # noqa: F401  (availability check for trsm oracle)

import mxnet_tpu as mx
from mxnet_tpu import sym, nd
from mxnet_tpu.test_utils import (assert_almost_equal, check_numeric_gradient,
                                  check_symbolic_forward, check_consistency,
                                  default_context)


def test_assert_almost_equal_reports_violation():
    try:
        assert_almost_equal(np.array([1.0, 2.0]), np.array([1.0, 3.0]),
                            rtol=1e-3)
    except AssertionError as e:
        assert 'position' in str(e)
    else:
        raise AssertionError('expected failure')


def test_check_numeric_gradient_fc():
    data = sym.Variable('data')
    fc = sym.FullyConnected(data, name='fc', num_hidden=3)
    loc = {'data': np.random.rand(4, 5).astype(np.float32),
           'fc_weight': np.random.rand(3, 5).astype(np.float32),
           'fc_bias': np.random.rand(3).astype(np.float32)}
    check_numeric_gradient(fc, loc, rtol=1e-2, atol=1e-2)


def test_grid_generator_affine():
    data = sym.Variable('data')
    g = sym.GridGenerator(data, transform_type='affine', target_shape=(3, 4))
    # identity transform reproduces the regular grid
    theta = np.array([[1, 0, 0, 0, 1, 0]], dtype=np.float32)
    ex = g.bind(default_context(), {'data': nd.array(theta)})
    out = ex.forward()[0].asnumpy()
    assert out.shape == (1, 2, 3, 4)
    np.testing.assert_allclose(out[0, 0, 0], np.linspace(-1, 1, 4), atol=1e-6)
    np.testing.assert_allclose(out[0, 1, :, 0], np.linspace(-1, 1, 3),
                               atol=1e-6)


def test_bilinear_sampler_identity():
    data = sym.Variable('data')
    grid = sym.Variable('grid')
    out = sym.BilinearSampler(data, grid)
    n, c, h, w = 2, 3, 5, 4
    x = np.random.rand(n, c, h, w).astype(np.float32)
    gy, gx = np.meshgrid(np.linspace(-1, 1, h), np.linspace(-1, 1, w),
                         indexing='ij')
    g = np.stack([gx, gy], 0)[None].repeat(n, 0).astype(np.float32)
    ex = out.bind(default_context(), {'data': nd.array(x),
                                      'grid': nd.array(g)})
    y = ex.forward()[0].asnumpy()
    np.testing.assert_allclose(y, x, rtol=1e-5, atol=1e-5)


def test_spatial_transformer_identity():
    data = sym.Variable('data')
    loc = sym.Variable('loc')
    st = sym.SpatialTransformer(data, loc, target_shape=(6, 5),
                                transform_type='affine',
                                sampler_type='bilinear')
    x = np.random.rand(2, 3, 6, 5).astype(np.float32)
    theta = np.tile(np.array([1, 0, 0, 0, 1, 0], np.float32), (2, 1))
    ex = st.bind(default_context(), {'data': nd.array(x),
                                     'loc': nd.array(theta)})
    y = ex.forward()[0].asnumpy()
    np.testing.assert_allclose(y, x, rtol=1e-5, atol=1e-5)


def test_roi_pooling_forward():
    x = np.arange(1 * 1 * 6 * 6, dtype=np.float32).reshape(1, 1, 6, 6)
    rois = np.array([[0, 0, 0, 5, 5]], dtype=np.float32)
    data = sym.Variable('data')
    r = sym.Variable('rois')
    out = sym.ROIPooling(data, r, pooled_size=(2, 2), spatial_scale=1.0)
    ex = out.bind(default_context(), {'data': nd.array(x),
                                      'rois': nd.array(rois)})
    y = ex.forward()[0].asnumpy()
    # max over each 3x3 quadrant
    expect = np.array([[[[14, 17], [32, 35]]]], dtype=np.float32)
    np.testing.assert_allclose(y, expect)


def test_roi_pooling_batch_index():
    x = np.stack([np.zeros((1, 4, 4), np.float32),
                  np.ones((1, 4, 4), np.float32)])
    rois = np.array([[1, 0, 0, 3, 3]], dtype=np.float32)
    out = sym.ROIPooling(sym.Variable('data'), sym.Variable('rois'),
                         pooled_size=(1, 1), spatial_scale=1.0)
    ex = out.bind(default_context(), {'data': nd.array(x),
                                      'rois': nd.array(rois)})
    assert ex.forward()[0].asnumpy().item() == 1.0


def test_correlation_self_unit():
    # correlating an array with itself at zero displacement = mean of squares
    x = np.random.rand(1, 2, 4, 4).astype(np.float32)
    d1, d2 = sym.Variable('a'), sym.Variable('b')
    out = sym.Correlation(d1, d2, kernel_size=1, max_displacement=0,
                          stride1=1, stride2=1, pad_size=0)
    ex = out.bind(default_context(), {'a': nd.array(x), 'b': nd.array(x)})
    y = ex.forward()[0].asnumpy()
    np.testing.assert_allclose(y[0, 0], (x * x).mean(axis=1)[0], rtol=1e-5)


def test_svm_output_grad():
    data = sym.Variable('data')
    label = sym.Variable('label')
    out = sym.SVMOutput(data, label, margin=1.0,
                        regularization_coefficient=0.5)
    x = np.array([[0.1, 0.2, 0.9]], np.float32)
    lab = np.array([2], np.float32)
    ex = out.bind(default_context(), {'data': nd.array(x),
                                      'label': nd.array(lab)},
                  args_grad={'data': nd.zeros((1, 3))},
                  grad_req={'data': 'write', 'label': 'null'})
    y = ex.forward(is_train=True)[0].asnumpy()
    np.testing.assert_allclose(y, x)  # forward is identity
    ex.backward()
    g = ex.grad_dict['data'].asnumpy()
    # violations: margin + x_j - x_y for j=0: 1+0.1-0.9=0.2>0; j=1: 0.3>0
    expect = np.array([[2 * 0.5 * 0.2, 2 * 0.5 * 0.3,
                        -(2 * 0.5 * 0.2 + 2 * 0.5 * 0.3)]], np.float32)
    np.testing.assert_allclose(g, expect, rtol=1e-5)


def test_smooth_l1():
    data = sym.Variable('data')
    out = sym.smooth_l1(data, scalar=1.0)
    x = np.array([-2.0, -0.5, 0.0, 0.5, 2.0], np.float32)
    expect = np.where(np.abs(x) < 1, 0.5 * x * x, np.abs(x) - 0.5)
    check_symbolic_forward(out, {'data': x}, [expect])
    check_numeric_gradient(out, {'data': x}, rtol=1e-2, atol=1e-2)


def test_linalg_gemm():
    a = np.random.rand(2, 3, 4).astype(np.float32)
    b = np.random.rand(2, 4, 5).astype(np.float32)
    c = np.random.rand(2, 3, 5).astype(np.float32)
    out = sym.linalg_gemm(sym.Variable('A'), sym.Variable('B'),
                          sym.Variable('C'), alpha=2.0, beta=0.5)
    expect = 2.0 * np.matmul(a, b) + 0.5 * c
    check_symbolic_forward(out, {'A': a, 'B': b, 'C': c}, [expect],
                           rtol=1e-4)


def test_linalg_potrf_roundtrip():
    m = np.random.rand(3, 3).astype(np.float32)
    spd = (m @ m.T + 3 * np.eye(3)).astype(np.float32)
    lsym = sym.linalg_potrf(sym.Variable('A'))
    ex = lsym.bind(default_context(), {'A': nd.array(spd)})
    L = ex.forward()[0].asnumpy()
    np.testing.assert_allclose(L @ L.T, spd, rtol=1e-4, atol=1e-4)
    # potri: inverse of spd from its factor
    inv = sym.linalg_potri(sym.Variable('L'))
    ex2 = inv.bind(default_context(), {'L': nd.array(L)})
    np.testing.assert_allclose(ex2.forward()[0].asnumpy() @ spd, np.eye(3),
                               atol=1e-3)


def test_linalg_trsm():
    m = np.tril(np.random.rand(4, 4) + np.eye(4)).astype(np.float32)
    b = np.random.rand(4, 3).astype(np.float32)
    out = sym.linalg_trsm(sym.Variable('A'), sym.Variable('B'), alpha=1.0)
    ex = out.bind(default_context(), {'A': nd.array(m), 'B': nd.array(b)})
    x = ex.forward()[0].asnumpy()
    np.testing.assert_allclose(m @ x, b, rtol=1e-4, atol=1e-4)


def test_linalg_sumlogdiag():
    m = np.diag([1.0, 2.0, 4.0]).astype(np.float32)
    out = sym.linalg_sumlogdiag(sym.Variable('A'))
    check_symbolic_forward(out, {'A': m},
                           [np.array(np.log(8.0), np.float32)], rtol=1e-5)


def test_sample_uniform_shapes():
    low = nd.array(np.zeros(3, np.float32))
    high = nd.array(np.array([1.0, 10.0, 100.0], np.float32))
    out = nd.sample_uniform(low, high, shape=(50,))
    assert out.shape == (3, 50)
    v = out.asnumpy()
    assert (v[0] <= 1.0).all() and v[2].max() > 10.0


def test_sample_normal_moments():
    mu = nd.array(np.array([0.0, 5.0], np.float32))
    sigma = nd.array(np.array([1.0, 0.1], np.float32))
    v = nd.sample_normal(mu, sigma, shape=(2000,)).asnumpy()
    assert abs(v[0].mean()) < 0.2
    assert abs(v[1].mean() - 5.0) < 0.1


def test_sample_gamma_mean():
    alpha = nd.array(np.array([2.0], np.float32))
    beta = nd.array(np.array([3.0], np.float32))
    v = nd.sample_gamma(alpha, beta, shape=(3000,)).asnumpy()
    assert abs(v.mean() - 6.0) < 0.5


def test_check_consistency_dtype():
    data = sym.Variable('data')
    fc = sym.FullyConnected(data, name='fc', num_hidden=4)
    ctx = default_context()
    check_consistency(
        fc,
        [{'ctx': ctx, 'data': (3, 6)},
         {'ctx': ctx, 'data': (3, 6),
          'type_dict': {'data': np.float32}}],
        rtol=1e-3, atol=1e-3)


def test_kl_sparse_reg_identity_forward():
    data = sym.Variable('data')
    out = sym.IdentityAttachKLSparseReg(data, sparseness_target=0.1,
                                        penalty=0.001)
    x = np.random.rand(4, 6).astype(np.float32)
    ex = out.simple_bind(default_context(), data=(4, 6),
                         grad_req={'data': 'write'})
    y = ex.forward(is_train=True, data=nd.array(x))[0].asnumpy()
    np.testing.assert_allclose(y, x)
    ex.backward(nd.ones((4, 6)))
    g = ex.grad_dict['data'].asnumpy()
    assert g.shape == x.shape
    assert not np.allclose(g, 1.0)  # KL term was added
