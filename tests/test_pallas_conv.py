"""Equivalence + gradient tests for the fused conv+BN-stats Pallas
kernel (mxnet_tpu/pallas_conv.py) against the unfused XLA oracle.

Runs in interpret mode on the CPU test platform; the on-chip perf
comparison lives in tools/bench_conv_bn.py and docs/PERF.md."""
import numpy as np
import pytest

import jax
import jax.numpy as jnp

from mxnet_tpu import pallas_conv as pc


CASES = [
    ((4, 14, 14, 32), (3, 3, 32, 128), (1, 1), (1, 1)),
    ((4, 14, 14, 32), (1, 1, 32, 128), (1, 1), (0, 0)),
    ((4, 14, 14, 32), (1, 1, 32, 128), (2, 2), (0, 0)),
    ((8, 8, 8, 16), (3, 3, 16, 64), (1, 1), (1, 1)),
]


@pytest.mark.parametrize('xs,ws,stride,pad', CASES)
def test_conv_bn_stats_matches_xla(xs, ws, stride, pad):
    rng = np.random.RandomState(0)
    x = jnp.asarray(rng.randn(*xs), jnp.float32)
    w = jnp.asarray(rng.randn(*ws) * 0.1, jnp.float32)
    y, s1, s2 = pc.conv2d_bn_stats(x, w, stride, pad, True)
    yr, s1r, s2r = pc.reference_conv_bn_stats(x, w, stride, pad)
    np.testing.assert_allclose(np.asarray(y), np.asarray(yr),
                               rtol=1e-5, atol=1e-5)
    np.testing.assert_allclose(np.asarray(s1), np.asarray(s1r),
                               rtol=1e-4, atol=1e-3)
    np.testing.assert_allclose(np.asarray(s2), np.asarray(s2r),
                               rtol=1e-4, atol=1e-2)


def test_conv_bn_stats_gradients():
    """The custom VJP folds stats-output gradients into dy; both paths
    must agree exactly (same XLA transposed convs underneath)."""
    rng = np.random.RandomState(1)
    x = jnp.asarray(rng.randn(2, 8, 8, 16), jnp.float32)
    w = jnp.asarray(rng.randn(3, 3, 16, 64) * 0.1, jnp.float32)

    def loss_fused(x, w):
        y, s1, s2 = pc.conv2d_bn_stats(x, w, (1, 1), (1, 1), True)
        return (y * 0.3).sum() + (s1 * 0.7).sum() - (s2 * 0.2).sum()

    def loss_ref(x, w):
        y, s1, s2 = pc.reference_conv_bn_stats(x, w, (1, 1), (1, 1))
        return (y * 0.3).sum() + (s1 * 0.7).sum() - (s2 * 0.2).sum()

    gx, gw = jax.grad(loss_fused, (0, 1))(x, w)
    rx, rw = jax.grad(loss_ref, (0, 1))(x, w)
    np.testing.assert_allclose(np.asarray(gx), np.asarray(rx),
                               rtol=1e-4, atol=1e-4)
    np.testing.assert_allclose(np.asarray(gw), np.asarray(rw),
                               rtol=1e-4, atol=1e-4)


def test_supported_gates():
    bf16 = jnp.bfloat16
    assert pc.supported((256, 28, 28, 128), (3, 3, 128, 128), (1, 1),
                        (1, 1), bf16)
    assert pc.supported((256, 28, 28, 256), (1, 1, 256, 512), (2, 2),
                        (0, 0), bf16)
    # stem conv: Cin too small
    assert not pc.supported((256, 224, 224, 3), (7, 7, 3, 64), (2, 2),
                            (3, 3), bf16)
    # strided 3x3 not handled
    assert not pc.supported((256, 28, 28, 128), (3, 3, 128, 128), (2, 2),
                            (1, 1), bf16)
    # non-lane-aligned cout
    assert not pc.supported((256, 28, 28, 128), (1, 1, 128, 96), (1, 1),
                            (0, 0), bf16)
