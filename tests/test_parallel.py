"""Parallelism tests on the 8-device virtual CPU mesh: mesh building,
ring attention vs full attention, and the dp×tp×sp transformer train
step (the reference has no counterpart — SURVEY.md §5.7/§7 step 9;
multi-node testing model: launcher=local in §4)."""
import numpy as np
import jax
import jax.numpy as jnp
import pytest

from mxnet_tpu.parallel import (make_mesh, ring_attention, shard_batch,
                                collectives)
from mxnet_tpu.parallel.ring_attention import (ring_self_attention,
                                               full_attention)
from mxnet_tpu.parallel import transformer as tfm


def test_make_mesh():
    mesh = make_mesh()
    assert mesh.devices.size == 8
    mesh2 = make_mesh({'data': 2, 'model': 2})
    assert mesh2.axis_names == ('data', 'model')
    assert mesh2.devices.shape == (2, 2)


def test_shard_batch_placement():
    mesh = make_mesh({'data': 4})
    x = jnp.arange(32.0).reshape(8, 4)
    sx = shard_batch(mesh, x)
    assert sx.sharding.is_fully_replicated is False
    np.testing.assert_allclose(np.asarray(sx), np.asarray(x))


@pytest.mark.parametrize('causal', [False, True])
def test_ring_attention_matches_full(causal):
    rng = np.random.RandomState(0)
    B, H, T, D = 2, 2, 16, 8
    q = jnp.asarray(rng.randn(B, H, T, D), jnp.float32)
    k = jnp.asarray(rng.randn(B, H, T, D), jnp.float32)
    v = jnp.asarray(rng.randn(B, H, T, D), jnp.float32)
    mesh = make_mesh({'sp': 4})
    out_ring = ring_self_attention(q, k, v, mesh, seq_axis='sp',
                                   causal=causal)
    out_full = full_attention(q, k, v, causal=causal)
    np.testing.assert_allclose(np.asarray(out_ring), np.asarray(out_full),
                               rtol=2e-4, atol=2e-5)


@pytest.mark.slow
def test_transformer_train_step_flash_attention():
    """The dp x tp x sp train step with cfg['use_flash']: identical
    loss to the XLA ring path on the same data/params."""
    mesh = make_mesh({'data': 2, 'sp': 2, 'model': 2})
    rng = np.random.RandomState(0)
    tokens = jnp.asarray(rng.randint(0, 32, (4, 32)), jnp.int32)
    targets = jnp.asarray(np.roll(np.asarray(tokens), -1, axis=1),
                          jnp.int32)
    losses = {}
    for use_flash in (False, True):
        cfg = tfm.lm_config(vocab=32, dim=16, heads=4, layers=1,
                            use_flash=use_flash)
        params = tfm.place_params(
            tfm.init_params(cfg, jax.random.PRNGKey(0)), cfg, mesh)
        step = tfm.make_train_step(cfg, mesh, lr=0.05)
        loss, params = step(params, tokens, targets)
        losses[use_flash] = float(loss)
    assert np.isfinite(losses[True])
    np.testing.assert_allclose(losses[True], losses[False], rtol=1e-4)


@pytest.mark.slow
def test_ring_attention_flash_grad():
    """jax.grad flows through the flash-kernel ring (the with-lse
    custom VJP folds the merge's logsumexp cotangent into the fused
    backward) and matches the plain XLA ring's gradients."""
    rng = np.random.RandomState(2)
    B, H, T, D = 1, 2, 128, 16
    q = jnp.asarray(rng.randn(B, H, T, D) * 0.4, jnp.float32)
    k = jnp.asarray(rng.randn(B, H, T, D) * 0.4, jnp.float32)
    v = jnp.asarray(rng.randn(B, H, T, D) * 0.4, jnp.float32)
    g = jnp.asarray(rng.randn(B, H, T, D) * 0.3, jnp.float32)
    mesh = make_mesh({'sp': 4})

    def loss(q, use_flash):
        out = ring_self_attention(q, k, v, mesh, seq_axis='sp',
                                  causal=True, use_flash=use_flash)
        return jnp.sum(out * g)

    gflash = jax.grad(lambda q: loss(q, True))(q)
    gplain = jax.grad(lambda q: loss(q, False))(q)
    np.testing.assert_allclose(np.asarray(gflash), np.asarray(gplain),
                               rtol=2e-4, atol=2e-5)


@pytest.mark.parametrize('causal', [False, True])
def test_ring_attention_flash_hops(causal):
    """The flash-kernel ring (each hop through the Pallas kernel,
    logsumexp merge across hops) matches the dense reference — the
    long-context sp path without T_local^2 score blocks."""
    rng = np.random.RandomState(1)
    B, H, T, D = 1, 2, 128, 16
    q = jnp.asarray(rng.randn(B, H, T, D) * 0.4, jnp.float32)
    k = jnp.asarray(rng.randn(B, H, T, D) * 0.4, jnp.float32)
    v = jnp.asarray(rng.randn(B, H, T, D) * 0.4, jnp.float32)
    mesh = make_mesh({'sp': 4})
    out_ring = ring_self_attention(q, k, v, mesh, seq_axis='sp',
                                   causal=causal, use_flash=True)
    out_full = full_attention(q, k, v, causal=causal)
    np.testing.assert_allclose(np.asarray(out_ring),
                               np.asarray(out_full),
                               rtol=2e-4, atol=2e-5)


@pytest.mark.slow
def test_transformer_train_step_dp_tp_sp():
    """Full train step over a 3-axis mesh: loss decreases and sharded
    params stay consistent with a single-device run.

    slow (~15s, round-14 headroom): the 3-axis transformer step stays
    continuously exercised by dryrun_multichip phase (a) (the
    driver-checked deliverable) and tier-1 keeps
    test_transformer_train_step_flash_attention + the ring-attention
    parity tests; this single-device consistency sweep runs in full
    CI."""
    cfg = tfm.lm_config(vocab=32, dim=16, heads=4, layers=2)
    mesh = make_mesh({'data': 2, 'sp': 2, 'model': 2})
    key = jax.random.PRNGKey(0)
    params = tfm.init_params(cfg, key)
    params = tfm.place_params(params, cfg, mesh)
    step = tfm.make_train_step(cfg, mesh, lr=0.05)
    rng = np.random.RandomState(0)
    tokens = jnp.asarray(rng.randint(0, 32, (4, 8)), jnp.int32)
    targets = jnp.asarray(np.roll(np.asarray(tokens), -1, axis=1), jnp.int32)
    losses = []
    for _ in range(30):
        loss, params = step(params, tokens, targets)
        losses.append(float(loss))
    assert np.isfinite(losses).all()
    assert losses[-1] < losses[0] * 0.9, losses


def test_collectives_api():
    mesh = make_mesh({'data': 8})
    from mxnet_tpu.parallel._compat import shard_map
    from jax.sharding import PartitionSpec as P

    def f(x):
        s = collectives.allreduce_sum(x.sum(), 'data')
        return x * 0 + s

    out = shard_map(f, mesh=mesh, in_specs=P('data'), out_specs=P('data'))(
        jnp.ones((8, 2)))
    np.testing.assert_allclose(np.asarray(out), np.full((8, 2), 16.0))


# ---------------------------------------------------------------------------
# Pipeline parallelism (parallel/pipeline.py; new-design, SURVEY.md §7.9)
# ---------------------------------------------------------------------------

def test_pipeline_matches_sequential():
    """4-stage pipeline over the mesh == running the 4 stages in
    sequence on one device."""
    import jax
    import jax.numpy as jnp
    from mxnet_tpu.parallel import pipeline as pp
    from mxnet_tpu.parallel import make_mesh

    S, M, mb, D = 4, 8, 2, 6
    mesh = make_mesh({'pipe': S})
    rs = np.random.RandomState(0)
    stage_params = [
        {'w': jnp.asarray(rs.randn(D, D).astype(np.float32) * 0.3),
         'b': jnp.asarray(rs.randn(D).astype(np.float32) * 0.1)}
        for _ in range(S)]

    def stage_fn(p, x):
        return jnp.tanh(x @ p['w'] + p['b'])

    stacked = pp.stack_stage_params(stage_params)
    stacked = pp.place_pipeline_params(stacked, mesh)
    x = rs.randn(M, mb, D).astype(np.float32)

    import jax
    from mxnet_tpu.parallel._compat import shard_map
    from jax.sharding import PartitionSpec as P

    def run(params, micro):
        sp = jax.tree_util.tree_map(lambda p: p[0], params)
        outs = pp.pipeline_run(stage_fn, sp, micro, S, 'pipe')
        # valid outputs live on the last stage only; broadcast them
        idx = jax.lax.axis_index('pipe')
        return jax.lax.psum(jnp.where(idx == S - 1, outs, 0.0), 'pipe')

    outs = jax.jit(shard_map(
        run, mesh=mesh, in_specs=(P('pipe'), P()), out_specs=P(),
        check_vma=False))(stacked, jnp.asarray(x))
    ref = jnp.asarray(x)
    for p in stage_params:
        ref = jnp.tanh(ref @ p['w'] + p['b'])
    # fetch the last stage's shard
    np.testing.assert_allclose(np.asarray(outs), np.asarray(ref),
                               rtol=2e-4, atol=2e-4)


def test_pipeline_train_step_learns():
    import jax
    import jax.numpy as jnp
    from mxnet_tpu.parallel import pipeline as pp
    from mxnet_tpu.parallel import make_mesh

    S, B, D = 4, 16, 8
    mesh = make_mesh({'pipe': S})
    rs = np.random.RandomState(1)
    stage_params = [
        {'w': jnp.asarray((np.eye(D) + rs.randn(D, D) * 0.05)
                          .astype(np.float32))}
        for _ in range(S)]

    def stage_fn(p, x):
        return x @ p['w']

    def loss_fn(y, t):
        return jnp.mean((y - t) ** 2)

    step = pp.make_pipeline_train_step(stage_fn, loss_fn, mesh,
                                       num_micro=4, lr=0.05)
    params = pp.place_pipeline_params(
        pp.stack_stage_params(stage_params), mesh)
    x = rs.randn(B, D).astype(np.float32)
    t = (x * 2.0).astype(np.float32)
    losses = []
    for _ in range(30):
        loss, params = step(params, jnp.asarray(x), jnp.asarray(t))
        losses.append(float(loss))
    assert losses[-1] < losses[0] * 0.2, losses[::10]


# ---------------------------------------------------------------------------
# Expert parallelism (parallel/moe.py; new-design, SURVEY.md §7.9)
# ---------------------------------------------------------------------------

def test_moe_routing_dispatch_combine():
    import jax.numpy as jnp
    from mxnet_tpu.parallel.moe import switch_route

    rs = np.random.RandomState(0)
    T, D, E, C = 8, 4, 2, 8
    x = jnp.asarray(rs.randn(T, D).astype(np.float32))
    router = jnp.asarray(rs.randn(D, E).astype(np.float32))
    disp, combine, aux = switch_route(x, router, E, C)
    assert disp.shape == (E, C, D)
    assert combine.shape == (T, E, C)
    assert float(aux) > 0
    # identity experts: combine @ disp reconstructs gate-weighted tokens
    recon = jnp.einsum('tec,ecd->td', combine, disp)
    probs = np.asarray(jax.nn.softmax(x @ router, -1))
    gate = probs.max(-1)
    np.testing.assert_allclose(np.asarray(recon),
                               np.asarray(x) * gate[:, None], rtol=1e-5)


def test_moe_train_step_learns():
    import jax.numpy as jnp
    from mxnet_tpu.parallel.moe import (init_moe_params,
                                        make_moe_train_step,
                                        moe_param_specs)
    from mxnet_tpu.parallel import make_mesh
    from jax.sharding import NamedSharding

    E, D, H, C = 8, 4, 8, 16
    mesh = make_mesh({'expert': 8})
    params = init_moe_params(jax.random.PRNGKey(0), D, H, E)
    # fan-in-scaled init so the toy regression converges quickly (the
    # default 0.02 init starts the two-matmul product near zero)
    params = {'router': params['router'],
              'w1': params['w1'] * 25.0, 'w2': params['w2'] * 25.0}
    specs = moe_param_specs()
    params = jax.tree_util.tree_map(
        lambda x, s: jax.device_put(x, NamedSharding(mesh, s)),
        params, specs)
    step = make_moe_train_step(mesh, D, H, E, C, lr=2.0)
    rs = np.random.RandomState(0)
    x = rs.randn(64, D).astype(np.float32)
    y = np.tanh(x) * 0.5
    losses = []
    for _ in range(40):
        loss, params = step(params, jnp.asarray(x), jnp.asarray(y))
        losses.append(float(loss))
    assert losses[-1] < losses[0] * 0.7, losses[::10]


def test_pipeline_gradients_match_sequential():
    """Pipeline-parallel gradients == sequential autodiff (regression:
    a psum inside the differentiated loss scaled grads by num_stages)."""
    import jax
    import jax.numpy as jnp
    from mxnet_tpu.parallel import pipeline as pp
    from mxnet_tpu.parallel import make_mesh

    S, M, mb, D = 4, 8, 2, 4
    mesh = make_mesh({'pipe': S})
    rs = np.random.RandomState(0)
    Ws = [jnp.asarray((np.eye(D) + rs.randn(D, D) * 0.05)
                      .astype(np.float32)) for _ in range(S)]
    x = jnp.asarray(rs.randn(M * mb, D).astype(np.float32))
    t = x * 2.0

    step = pp.make_pipeline_train_step(
        lambda p, v: v @ p['w'],
        lambda y, tv: jnp.mean((y - tv) ** 2), mesh, num_micro=M, lr=1.0)
    params = pp.place_pipeline_params(
        pp.stack_stage_params([{'w': w} for w in Ws]), mesh)
    loss, newp = step(params, x, t)
    g_pipe = np.asarray(jnp.stack(Ws) - newp['w'])   # lr=1 -> grad

    def seq_loss(ws):
        y = x
        for w in ws:
            y = y @ w
        return jnp.mean((y - t) ** 2)

    ref_loss, g_ref = jax.value_and_grad(seq_loss)(Ws)
    np.testing.assert_allclose(float(loss), float(ref_loss), rtol=1e-5)
    for i in range(S):
        np.testing.assert_allclose(g_pipe[i], np.asarray(g_ref[i]),
                                   rtol=1e-4, atol=1e-5)


def test_model_parallel_ctx_group():
    """ctx_group model parallelism: layers placed on different devices
    via AttrScope + group2ctx (reference test_model_parallel.py — there
    cpu(0)/cpu(1); PlaceDevice's _CrossDeviceCopy becomes XLA device
    placement)."""
    import mxnet_tpu as mx
    from mxnet_tpu import sym, nd

    with mx.AttrScope(ctx_group='dev1'):
        data = sym.Variable('data')
        fc1 = sym.FullyConnected(data, num_hidden=8, name='fc1')
        act1 = sym.Activation(fc1, act_type='relu')
    with mx.AttrScope(ctx_group='dev2'):
        fc2 = sym.FullyConnected(act1, num_hidden=4, name='fc2')
        net = sym.SoftmaxOutput(fc2, name='softmax')

    ex = net.simple_bind(mx.cpu(0), data=(4, 6),
                         group2ctx={'dev1': mx.cpu(0),
                                    'dev2': mx.cpu(1)})
    rs = np.random.RandomState(0)
    for k, v in ex.arg_dict.items():
        v[:] = rs.rand(*v.shape).astype(np.float32)
    out = ex.forward(is_train=True)[0]
    # dev2-group ops executed on device 1 (the output is theirs)
    assert any(d.id == 1 for d in out.handle.devices()), \
        out.handle.devices()
    ex.backward()
    # gradients flow across the device boundary
    g = ex.grad_dict['fc1_weight'].asnumpy()
    assert np.isfinite(g).all() and np.abs(g).sum() > 0
    # numerics match the single-device run
    ex2 = net.simple_bind(mx.cpu(0), data=(4, 6))
    for k in ex.arg_dict:
        ex2.arg_dict[k][:] = ex.arg_dict[k].asnumpy()
    out2 = ex2.forward(is_train=False)[0]
    ex.forward(is_train=False)
    np.testing.assert_allclose(ex.outputs[0].asnumpy(), out2.asnumpy(),
                               rtol=1e-5, atol=1e-6)


def test_model_parallel_monitor_keeps_placement():
    """Monitor mode must not collapse ctx_group placement (regression:
    _fwd_monitor stayed jitted for grouped executors)."""
    import mxnet_tpu as mx
    from mxnet_tpu import sym

    with mx.AttrScope(ctx_group='a'):
        data = sym.Variable('data')
        fc1 = sym.FullyConnected(data, num_hidden=4, name='fc1')
    with mx.AttrScope(ctx_group='b'):
        net = sym.SoftmaxOutput(sym.FullyConnected(fc1, num_hidden=2,
                                                   name='fc2'),
                                name='softmax')
    ex = net.simple_bind(mx.cpu(0), data=(2, 4),
                         group2ctx={'a': mx.cpu(0), 'b': mx.cpu(1)})
    seen = []
    ex.set_monitor_callback(lambda name, arr: seen.append(name))
    out = ex.forward(is_train=False)[0]
    assert seen  # monitor fired
    assert any(d.id == 1 for d in out.handle.devices())


def test_group2ctx_without_groups_stays_jitted():
    """Passing group2ctx that matches no node must keep the fused jit
    path (regression: any non-empty dict forced eager dispatch)."""
    import mxnet_tpu as mx
    from mxnet_tpu import sym
    data = sym.Variable('data')
    net = sym.SoftmaxOutput(sym.FullyConnected(data, num_hidden=2,
                                               name='fc'), name='softmax')
    ex = net.simple_bind(mx.cpu(0), data=(2, 4),
                         group2ctx={'unused': mx.cpu(1)})
    assert not ex._grouped


# ---------------------------------------------------------------------------
# Pallas flash attention (pallas_ops.py)
# ---------------------------------------------------------------------------

@pytest.mark.parametrize('causal', [False, True])
def test_pallas_flash_attention_matches_reference(causal):
    from mxnet_tpu import pallas_ops

    rs = np.random.RandomState(0)
    B, H, T, D = 2, 3, 64, 16
    q = jnp.asarray(rs.randn(B, H, T, D).astype(np.float32))
    k = jnp.asarray(rs.randn(B, H, T, D).astype(np.float32))
    v = jnp.asarray(rs.randn(B, H, T, D).astype(np.float32))
    out = pallas_ops.flash_attention(q, k, v, causal=causal, block_q=32)
    ref = full_attention(q, k, v, causal=causal)
    np.testing.assert_allclose(np.asarray(out), np.asarray(ref),
                               rtol=2e-4, atol=2e-5)


def test_pallas_flash_attention_grad():
    """Recompute-based backward matches autodiff through the reference."""
    from mxnet_tpu import pallas_ops

    rs = np.random.RandomState(1)
    B, H, T, D = 1, 2, 32, 8
    q = jnp.asarray(rs.randn(B, H, T, D).astype(np.float32))
    k = jnp.asarray(rs.randn(B, H, T, D).astype(np.float32))
    v = jnp.asarray(rs.randn(B, H, T, D).astype(np.float32))

    def loss_flash(q, k, v):
        return jnp.sum(pallas_ops.flash_attention(q, k, v, causal=True,
                                                  block_q=16) ** 2)

    def loss_ref(q, k, v):
        return jnp.sum(full_attention(q, k, v, causal=True) ** 2)

    gf = jax.grad(loss_flash, argnums=(0, 1, 2))(q, k, v)
    gr = jax.grad(loss_ref, argnums=(0, 1, 2))(q, k, v)
    for a, b in zip(gf, gr):
        np.testing.assert_allclose(np.asarray(a), np.asarray(b),
                                   rtol=2e-3, atol=2e-4)


def test_pallas_flash_attention_odd_lengths():
    """block_q halves until it divides the sequence length."""
    from mxnet_tpu import pallas_ops
    rs = np.random.RandomState(2)
    q = jnp.asarray(rs.randn(1, 1, 48, 8).astype(np.float32))
    out = pallas_ops.flash_attention(q, q, q, block_q=32)
    ref = full_attention(q, q, q)
    np.testing.assert_allclose(np.asarray(out), np.asarray(ref),
                               rtol=2e-4, atol=2e-5)


def test_pallas_flash_streaming_schedule():
    """The 3D-grid streaming schedule (K/V never resident) matches the
    reference; forced by shrinking the residency threshold."""
    from mxnet_tpu import pallas_ops
    rs = np.random.RandomState(3)
    q = jnp.asarray(rs.randn(1, 2, 64, 16).astype(np.float32))
    old = pallas_ops._VMEM_RESIDENT_BYTES
    pallas_ops._VMEM_RESIDENT_BYTES = 1   # force streaming
    try:
        for causal in (False, True):
            out = pallas_ops.flash_attention(q, q, q, causal=causal,
                                             block_q=16)
            ref = full_attention(q, q, q, causal=causal)
            np.testing.assert_allclose(np.asarray(out), np.asarray(ref),
                                       rtol=2e-4, atol=2e-5)
    finally:
        pallas_ops._VMEM_RESIDENT_BYTES = old


@pytest.mark.slow
def test_pallas_flash_streaming_backward():
    """The streaming (non-resident) Pallas backward matches the dense
    oracle's gradients and is bitwise-identical to the resident
    schedule; forced by shrinking the residency threshold so the
    elif-branch (not the XLA blocked recompute) runs."""
    from mxnet_tpu import pallas_ops
    rs = np.random.RandomState(5)
    shape = (1, 2, 256, 32)
    q, k, v, g = (jnp.asarray(rs.randn(*shape).astype(np.float32) * 0.3)
                  for _ in range(4))
    for causal in (False, True):
        def loss_flash(q, k, v, causal=causal):
            return jnp.sum(pallas_ops.flash_attention(
                q, k, v, causal=causal, block_q=64) * g)

        def loss_ref(q, k, v, causal=causal):
            return jnp.sum(full_attention(q, k, v, causal=causal) * g)

        resident = jax.grad(loss_flash, argnums=(0, 1, 2))(q, k, v)
        old = pallas_ops._VMEM_RESIDENT_BYTES
        pallas_ops._VMEM_RESIDENT_BYTES = 1
        try:
            streamed = jax.grad(loss_flash, argnums=(0, 1, 2))(q, k, v)
        finally:
            pallas_ops._VMEM_RESIDENT_BYTES = old
        oracle = jax.grad(loss_ref, argnums=(0, 1, 2))(q, k, v)
        for s, r, o in zip(streamed, resident, oracle):
            np.testing.assert_array_equal(np.asarray(s), np.asarray(r))
            np.testing.assert_allclose(np.asarray(s), np.asarray(o),
                                       rtol=5e-3, atol=5e-4)


def test_flash_attention_with_lse():
    """The with-lse entry point: out/lse match the dense formulas, the
    lse cotangent is honored (the ring-merge currency), and odd
    sequence lengths fall back to the dense path."""
    from mxnet_tpu import pallas_ops
    rs = np.random.RandomState(4)
    B, H, T, D = 1, 2, 64, 16
    q, k, v = (jnp.asarray(rs.randn(B, H, T, D).astype(np.float32) * 0.4)
               for _ in range(3))
    out, lse = pallas_ops.flash_attention_with_lse(q, k, v, causal=True,
                                                   interpret=True)
    s = jnp.einsum('bhqd,bhkd->bhqk', q, k) * (D ** -0.5)
    mask = jnp.arange(T)[:, None] >= jnp.arange(T)[None, :]
    s = jnp.where(mask, s, -jnp.inf)
    lse_ref = jax.scipy.special.logsumexp(s, axis=-1)
    out_ref = jnp.einsum('bhqk,bhkd->bhqd',
                         jnp.exp(s - lse_ref[..., None]), v)
    np.testing.assert_allclose(np.asarray(out), np.asarray(out_ref),
                               rtol=2e-4, atol=2e-5)
    np.testing.assert_allclose(np.asarray(lse).reshape(B, H, T),
                               np.asarray(lse_ref), rtol=2e-4, atol=2e-5)

    w = jnp.asarray(rs.randn(B * H, T, 1).astype(np.float32) * 0.3)

    def loss_flash(q):
        o, l = pallas_ops.flash_attention_with_lse(q, k, v, causal=True,
                                                   interpret=True)
        return (o * out_ref).sum() + (l * w).sum()

    def loss_dense(q):
        s = jnp.einsum('bhqd,bhkd->bhqk', q, k) * (D ** -0.5)
        s = jnp.where(mask, s, -jnp.inf)
        l = jax.scipy.special.logsumexp(s, axis=-1)
        o = jnp.einsum('bhqk,bhkd->bhqd', jnp.exp(s - l[..., None]), v)
        return (o * out_ref).sum() + (l.reshape(B * H, T, 1) * w).sum()

    gf = jax.grad(loss_flash)(q)
    gd = jax.grad(loss_dense)(q)
    np.testing.assert_allclose(np.asarray(gf), np.asarray(gd),
                               rtol=5e-4, atol=5e-5)

    # prime-ish length -> dense fallback, still correct
    qq = jnp.asarray(rs.randn(1, 1, 30, 8).astype(np.float32))
    o2, l2 = pallas_ops.flash_attention_with_lse(qq, qq, qq)
    assert o2.shape == qq.shape and l2.shape == (1, 30, 1)


def test_pallas_flash_accepts_cross_attention():
    """Round 5 lifted the v1 square-only constraint: rectangular
    q/k shapes are first-class (conformance in
    test_pallas_flash_rectangular; this is the API-level check that
    the old rejection is gone)."""
    from mxnet_tpu import pallas_ops
    q = jnp.ones((1, 1, 4, 8))
    k = jnp.ones((1, 1, 16, 8))
    out = pallas_ops.flash_attention(q, k, k)
    assert out.shape == q.shape


@pytest.mark.parametrize('tq,tk', [
    pytest.param(128, 512, marks=pytest.mark.slow),
    pytest.param(8, 512, marks=pytest.mark.slow),
    pytest.param(128, 384, marks=pytest.mark.slow),
    (512, 128)])
def test_pallas_flash_rectangular(tq, tk):
    """q_len != kv_len (cross-attention / KV-cache decode): forward and
    all three gradients match the dense oracle under both causal
    conventions, on every schedule (resident + forced-streaming).
    Causal rows are SUFFIX-aligned to the keys (docs/PERF.md round 5);
    full_attention shares the same convention."""
    from mxnet_tpu import pallas_ops
    rs = np.random.RandomState(7)
    B, H, D = 2, 2, 32
    q = jnp.asarray(rs.randn(B, H, tq, D).astype(np.float32) * 0.3)
    k = jnp.asarray(rs.randn(B, H, tk, D).astype(np.float32) * 0.3)
    v = jnp.asarray(rs.randn(B, H, tk, D).astype(np.float32) * 0.3)
    g = jnp.asarray(rs.randn(B, H, tq, D).astype(np.float32))
    for causal in (False, True):
        if causal and tq > tk:
            continue  # rejected by design (suffix alignment)
        def loss_flash(q, k, v, causal=causal):
            return jnp.sum(pallas_ops.flash_attention(
                q, k, v, causal=causal, block_q=64) * g)

        def loss_ref(q, k, v, causal=causal):
            return jnp.sum(full_attention(q, k, v, causal=causal) * g)

        out = pallas_ops.flash_attention(q, k, v, causal=causal,
                                         block_q=64)
        ref = full_attention(q, k, v, causal=causal)
        np.testing.assert_allclose(np.asarray(out), np.asarray(ref),
                                   rtol=2e-3, atol=2e-4)
        resident = jax.grad(loss_flash, argnums=(0, 1, 2))(q, k, v)
        old = pallas_ops._VMEM_RESIDENT_BYTES
        pallas_ops._VMEM_RESIDENT_BYTES = 1
        try:
            streamed = jax.grad(loss_flash, argnums=(0, 1, 2))(q, k, v)
        finally:
            pallas_ops._VMEM_RESIDENT_BYTES = old
        oracle = jax.grad(loss_ref, argnums=(0, 1, 2))(q, k, v)
        for s, r, o in zip(streamed, resident, oracle):
            np.testing.assert_allclose(np.asarray(s), np.asarray(r),
                                       rtol=5e-3, atol=5e-4)
            np.testing.assert_allclose(np.asarray(s), np.asarray(o),
                                       rtol=5e-3, atol=5e-4)


def test_flash_rectangular_validation():
    from mxnet_tpu import pallas_ops
    q = jnp.zeros((1, 1, 64, 16))
    k = jnp.zeros((1, 1, 32, 16))
    v = jnp.zeros((1, 1, 32, 16))
    with pytest.raises(ValueError, match='q_len <= kv_len'):
        pallas_ops.flash_attention(q, k, v, causal=True)
    with pytest.raises(ValueError, match='identical k/v'):
        pallas_ops.flash_attention(q, k, jnp.zeros((1, 1, 16, 16)))
    # the dense fallback enforces the same convention
    with pytest.raises(ValueError, match='q_len <= kv_len'):
        full_attention(q, k, v, causal=True)
    # non-causal tq > tk is legal
    out = pallas_ops.flash_attention(q, k, v, causal=False)
    assert out.shape == q.shape


def test_pallas_flash_fallback_predicate_matches_kernels():
    """The dense-fallback predicate must derive k-block caps from the
    POST-fit q block exactly as the kernels do: with a pre-fit cap,
    (tq=8, tk=258, block_q=320) passed the predicate but the forward
    kernel raised instead of falling back (round-5 review repro)."""
    import jax.numpy as jnp
    from mxnet_tpu import pallas_ops
    rs = np.random.RandomState(0)
    q = jnp.asarray(rs.randn(1, 1, 8, 16).astype(np.float32))
    k = jnp.asarray(rs.randn(1, 1, 258, 16).astype(np.float32))
    v = jnp.asarray(rs.randn(1, 1, 258, 16).astype(np.float32))
    assert pallas_ops._needs_dense_fallback(8, 258, 320)
    out = pallas_ops.flash_attention(q, k, v, block_q=320)
    s = jnp.einsum('bhqd,bhkd->bhqk', q, k) / np.sqrt(16.0)
    ref = jnp.einsum('bhqk,bhkd->bhqd', jax.nn.softmax(s, axis=-1), v)
    np.testing.assert_allclose(np.asarray(out), np.asarray(ref),
                               rtol=2e-4, atol=2e-5)
