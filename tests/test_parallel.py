"""Parallelism tests on the 8-device virtual CPU mesh: mesh building,
ring attention vs full attention, and the dp×tp×sp transformer train
step (the reference has no counterpart — SURVEY.md §5.7/§7 step 9;
multi-node testing model: launcher=local in §4)."""
import numpy as np
import jax
import jax.numpy as jnp
import pytest

from mxnet_tpu.parallel import (make_mesh, ring_attention, shard_batch,
                                collectives)
from mxnet_tpu.parallel.ring_attention import (ring_self_attention,
                                               full_attention)
from mxnet_tpu.parallel import transformer as tfm


def test_make_mesh():
    mesh = make_mesh()
    assert mesh.devices.size == 8
    mesh2 = make_mesh({'data': 2, 'model': 2})
    assert mesh2.axis_names == ('data', 'model')
    assert mesh2.devices.shape == (2, 2)


def test_shard_batch_placement():
    mesh = make_mesh({'data': 4})
    x = jnp.arange(32.0).reshape(8, 4)
    sx = shard_batch(mesh, x)
    assert sx.sharding.is_fully_replicated is False
    np.testing.assert_allclose(np.asarray(sx), np.asarray(x))


@pytest.mark.parametrize('causal', [False, True])
def test_ring_attention_matches_full(causal):
    rng = np.random.RandomState(0)
    B, H, T, D = 2, 2, 16, 8
    q = jnp.asarray(rng.randn(B, H, T, D), jnp.float32)
    k = jnp.asarray(rng.randn(B, H, T, D), jnp.float32)
    v = jnp.asarray(rng.randn(B, H, T, D), jnp.float32)
    mesh = make_mesh({'sp': 4})
    out_ring = ring_self_attention(q, k, v, mesh, seq_axis='sp',
                                   causal=causal)
    out_full = full_attention(q, k, v, causal=causal)
    np.testing.assert_allclose(np.asarray(out_ring), np.asarray(out_full),
                               rtol=2e-4, atol=2e-5)


def test_transformer_train_step_dp_tp_sp():
    """Full train step over a 3-axis mesh: loss decreases and sharded
    params stay consistent with a single-device run."""
    cfg = tfm.lm_config(vocab=32, dim=16, heads=4, layers=2)
    mesh = make_mesh({'data': 2, 'sp': 2, 'model': 2})
    key = jax.random.PRNGKey(0)
    params = tfm.init_params(cfg, key)
    params = tfm.place_params(params, cfg, mesh)
    step = tfm.make_train_step(cfg, mesh, lr=0.05)
    rng = np.random.RandomState(0)
    tokens = jnp.asarray(rng.randint(0, 32, (4, 8)), jnp.int32)
    targets = jnp.asarray(np.roll(np.asarray(tokens), -1, axis=1), jnp.int32)
    losses = []
    for _ in range(30):
        loss, params = step(params, tokens, targets)
        losses.append(float(loss))
    assert np.isfinite(losses).all()
    assert losses[-1] < losses[0] * 0.9, losses


def test_collectives_api():
    mesh = make_mesh({'data': 8})
    from jax import shard_map
    from jax.sharding import PartitionSpec as P

    def f(x):
        s = collectives.allreduce_sum(x.sum(), 'data')
        return x * 0 + s

    out = shard_map(f, mesh=mesh, in_specs=P('data'), out_specs=P('data'))(
        jnp.ones((8, 2)))
    np.testing.assert_allclose(np.asarray(out), np.full((8, 2), 16.0))
