"""Fused bucket-ladder training (PERF round 12): pad-to-rung masked
parity, AOT ladder warmup / zero-compile steady state, per-bucket bulk
dispatch, shared optimizer state across rungs, and the bucketing
counters.  CPU-sized per the rig note in CHANGES.md."""
import os
import random

import numpy as np
import pytest

import mxnet_tpu as mx
from mxnet_tpu import exec_cache, profiler
from mxnet_tpu import ndarray as nd
from mxnet_tpu import symbol as sym

VOCAB = 12
EMBED = 6
BATCH = 4
MASK = 0


def sym_gen(seq_len):
    """Tiny per-position LM: Embedding -> FC -> SoftmaxOutput with the
    standard bucketing masking convention (use_ignore/ignore_label)."""
    data = sym.Variable('data')
    label = sym.Variable('softmax_label')
    emb = sym.Embedding(data, input_dim=VOCAB, output_dim=EMBED,
                        name='embed')
    h = sym.Reshape(emb, shape=(-1, EMBED))
    fc = sym.FullyConnected(h, num_hidden=VOCAB, name='pred')
    lab = sym.Reshape(label, shape=(-1,))
    out = sym.SoftmaxOutput(fc, label=lab, use_ignore=True,
                            ignore_label=MASK, name='softmax')
    return out, ('data',), ('softmax_label',)


def make_module(ladder=None, warmup=None, default_key=8):
    mod = mx.mod.BucketingModule(sym_gen, default_bucket_key=default_key,
                                 bucket_ladder=ladder, mask_label=MASK,
                                 warmup_buckets=warmup)
    mod.bind(data_shapes=[mx.io.DataDesc('data', (BATCH, default_key),
                                         layout='NT')],
             label_shapes=[mx.io.DataDesc('softmax_label',
                                          (BATCH, default_key),
                                          layout='NT')])
    mod.init_params(initializer=mx.init.Xavier())
    mod.init_optimizer(optimizer_params={'learning_rate': 0.1,
                                         'momentum': 0.9})
    return mod


def make_batch(seq_len, seed=0):
    rs = np.random.RandomState(100 * seed + seq_len)
    X = rs.randint(1, VOCAB, (BATCH, seq_len)).astype(np.float32)
    y = np.roll(X, -1, axis=1)
    y[:, -1] = MASK
    return mx.io.DataBatch(
        [nd.array(X)], [nd.array(y)], bucket_key=seq_len,
        provide_data=[mx.io.DataDesc('data', (BATCH, seq_len),
                                     layout='NT')],
        provide_label=[mx.io.DataDesc('softmax_label', (BATCH, seq_len),
                                      layout='NT')])


def params_np(mod):
    args, _ = mod.get_params()
    return {k: v.asnumpy().copy() for k, v in args.items()}


def max_param_diff(a, b):
    return max(float(np.abs(a[k] - b[k]).max()) for k in a)


# ---------------------------------------------------------------------------
# pad-to-rung masked parity
# ---------------------------------------------------------------------------

def test_padded_grad_and_update_parity():
    """A batch shorter than its rung, padded with mask_label, must
    produce the SAME gradients, parameter updates, and masked metric
    as the unpadded run (masked positions contribute exactly zero;
    float rounding differs across the two program shapes)."""
    padded = make_module(ladder=[8])        # L=5 runs at rung 8
    exact = make_module()                   # L=5 binds its own bucket
    exact.set_params(*padded.get_params())

    b = make_batch(5, seed=3)
    # gradient parity through the legacy fwd/bwd path
    padded.forward(b, is_train=True)
    padded.backward()
    exact.forward(b, is_train=True)
    exact.backward()
    gp = padded._buckets[8]._exec_group.executor
    ge = exact._buckets[5]._exec_group.executor
    for name in gp.grad_dict:
        np.testing.assert_allclose(
            gp.grad_dict[name].asnumpy(), ge.grad_dict[name].asnumpy(),
            atol=2e-6, err_msg='grad mismatch for %s' % name)

    # masked metric parity: the padded outputs/labels must score the
    # same perplexity as the unpadded run
    mp = mx.metric.Perplexity(ignore_label=MASK)
    me = mx.metric.Perplexity(ignore_label=MASK)
    padded.update_metric(mp, b.label)
    exact.update_metric(me, b.label)
    assert abs(mp.get()[1] - me.get()[1]) < 1e-4

    # fused-update trajectory parity over mixed lengths
    for i, seq_len in enumerate((5, 3, 8, 6, 5)):
        bb = make_batch(seq_len, seed=i)
        padded.forward_backward(bb)
        padded.update()
        exact.forward_backward(bb)
        exact.update()
    assert max_param_diff(params_np(padded), params_np(exact)) < 2e-6


def test_shared_optimizer_state_across_rungs():
    """ONE FusedSGD (momenta) is shared by every rung, and bucket
    switching must not fork or reset it: the ladder run's optimizer
    states match the exact-bucket run's after a mixed-length epoch."""
    padded = make_module(ladder=[4, 8])
    exact = make_module()
    exact.set_params(*padded.get_params())
    for i, seq_len in enumerate((3, 8, 4, 7, 2, 8)):
        bb = make_batch(seq_len, seed=i)
        padded.forward_backward(bb)
        padded.update()
        exact.forward_backward(bb)
        exact.update()
    fus = set(id(m._fused_updater) for m in padded._buckets.values())
    assert len(fus) == 1, 'rungs must share one fused updater'
    sp = padded._buckets[8]._fused_updater
    se = exact._buckets[8]._fused_updater
    for name in sp.states:
        np.testing.assert_allclose(
            np.asarray(sp.states[name]), np.asarray(se.states[name]),
            atol=2e-6, err_msg='momentum mismatch for %s' % name)


def test_rung_mapping_and_errors():
    mod = make_module(ladder=[4, 8])
    assert mod._rung_for(4) == 4 and mod._rung_for(8) == 8
    assert mod._rung_for(3) == 4 and mod._rung_for(5) == 8
    with pytest.raises(mx.base.MXNetError):
        mod._rung_for(9)        # exceeds every rung
    # tuple keys: elementwise cover; kind mismatch = no cover (clean
    # MXNetError from _rung_for, not a TypeError from exec_cache)
    lad = exec_cache.train_ladder([(4, 6), (8, 12)])
    assert exec_cache.ladder_rung(lad, (3, 5)) == (4, 6)
    assert exec_cache.ladder_rung(lad, (5, 5)) == (8, 12)
    assert exec_cache.ladder_rung(lad, (9, 2)) is None
    assert exec_cache.ladder_rung((4, 8), (2, 3)) is None  # int vs tuple
    with pytest.raises(mx.base.MXNetError):
        mod._rung_for((2, 3))
    nomask = mx.mod.BucketingModule(sym_gen, default_bucket_key=8,
                                    bucket_ladder=[8])
    nomask.bind(data_shapes=[mx.io.DataDesc('data', (BATCH, 8),
                                            layout='NT')],
                label_shapes=[mx.io.DataDesc('softmax_label', (BATCH, 8),
                                             layout='NT')])
    with pytest.raises(mx.base.MXNetError):
        nomask._rung_for(5)     # padding without mask_label


# ---------------------------------------------------------------------------
# AOT ladder warmup: zero-compile steady state + cached re-warm
# ---------------------------------------------------------------------------

def test_ladder_warmup_zero_compile_steady_state():
    mod = make_module(ladder=[4, 8], warmup=True)  # warms at init_optimizer
    assert sorted(mod._buckets) == [4, 8]
    s0 = exec_cache.stats()
    b0 = profiler.bucketing_stats()
    for i, seq_len in enumerate((3, 4, 8, 5, 7, 4, 8, 2)):
        mod.forward_backward(make_batch(seq_len, seed=i))
        mod.update()
    s1 = exec_cache.stats()
    assert s1['total_compile_s'] == s0['total_compile_s'], \
        'steady-state bucketed training must perform ZERO XLA compiles'
    assert s1['misses'] == s0['misses']
    b1 = profiler.bucketing_stats()
    for rung in ('4', '8'):
        assert b1['train_rungs'][rung]['compiles'] == \
            b0['train_rungs'].get(rung, {}).get('compiles', 0), \
            'rung %s paid a mid-epoch compile' % rung
    # pad accounting moved (lengths 3/5/7/2 padded up)
    assert b1['train_pad_waste_rows'] > b0['train_pad_waste_rows']
    assert b1['train_bucket_switches'] > b0['train_bucket_switches']


def test_recreated_module_rewarms_from_cache():
    make_module(ladder=[4, 8], warmup=True)     # populates exec_cache
    s0 = exec_cache.stats()
    mod2 = make_module(ladder=[4, 8])
    warmed = mod2.warmup_buckets()
    s1 = exec_cache.stats()
    assert warmed == [4, 8]
    assert s1['total_compile_s'] == s0['total_compile_s'], \
        're-created module must warm entirely from the program cache'
    assert s1['misses'] == s0['misses']


def test_warmup_mutates_no_state():
    mod = mx.mod.BucketingModule(sym_gen, default_bucket_key=8,
                                 bucket_ladder=[4, 8], mask_label=MASK)
    mod.bind(data_shapes=[mx.io.DataDesc('data', (BATCH, 8),
                                         layout='NT')],
             label_shapes=[mx.io.DataDesc('softmax_label', (BATCH, 8),
                                          layout='NT')])
    mod.init_params(initializer=mx.init.Xavier())
    # a STATEFUL scheduler: warmup evaluating lr at k step indices must
    # not advance it (FactorScheduler mutates base_lr/count in __call__)
    sched = mx.lr_scheduler.FactorScheduler(step=2, factor=0.5)
    mod.init_optimizer(optimizer_params={'learning_rate': 0.1,
                                         'momentum': 0.9,
                                         'lr_scheduler': sched})
    before = params_np(mod)
    opt = mod._curr_module._optimizer
    counts0 = dict(opt._index_update_count)
    nu0 = opt.num_update
    sched0 = dict(sched.__dict__)
    fu = mod._curr_module._fused_updater
    mod.warmup_buckets(bulk=5,
                       eval_metric=mx.metric.Perplexity(ignore_label=MASK))
    assert max_param_diff(params_np(mod), before) == 0.0
    assert opt._index_update_count == counts0
    assert opt.num_update == nu0
    assert sched.__dict__ == sched0, \
        'warmup advanced the stateful lr schedule'
    for name, v in fu.states.items():
        assert float(np.abs(np.asarray(v)).max()) == 0.0, \
            'warmup must not step momenta (%s)' % name
    # the first real step trains at the UNdecayed rate
    assert opt._get_lr(fu.param_names[0]) == 0.1


# ---------------------------------------------------------------------------
# per-bucket dispatch bulking
# ---------------------------------------------------------------------------

def test_bulk_step_one_dispatch_and_parity():
    bulk = make_module(ladder=[4, 8], warmup=True)
    ref = make_module(ladder=[4, 8])
    ref.set_params(*bulk.get_params())
    metric_b = mx.metric.Perplexity(ignore_label=MASK)
    metric_r = mx.metric.Perplexity(ignore_label=MASK)
    batches = [make_batch(7, seed=i) for i in range(4)]

    ex8 = bulk._buckets[8]._exec_group.executor
    d0 = ex8.fused_dispatches
    bulk.bulk_step(batches=batches, eval_metric=metric_b)
    assert ex8.fused_dispatches - d0 == 1, \
        '4 same-rung steps must run as ONE lax.scan dispatch'
    for b in batches:
        ref.forward_backward(b)
        ref.update()
        ref.update_metric(metric_r, b.label)
    assert max_param_diff(params_np(bulk), params_np(ref)) < 1e-5
    assert abs(metric_b.get()[1] - metric_r.get()[1]) < 1e-3

    with pytest.raises(mx.base.MXNetError):
        bulk.bulk_step(batches=[make_batch(3), make_batch(8)])


def test_fit_bulk_bucket_major_parity():
    """fit(bulk=K) over a bucket_major iterator: same final params and
    metric as the per-batch fit, zero mid-epoch compiles, and real
    multi-step dispatches."""
    rs = np.random.RandomState(0)
    sentences = []
    for _ in range(120):
        ln = int(rs.choice([3, 4, 6, 8]))
        s0 = int(rs.randint(1, VOCAB))
        sentences.append([max(1, (s0 + i) % VOCAB) for i in range(ln)])

    def run(bulk):
        random.seed(11)
        np.random.seed(11)
        mx.random.seed(11)
        it = mx.rnn.BucketSentenceIter(sentences, batch_size=BATCH,
                                       buckets=[3, 4, 6, 8],
                                       invalid_label=MASK,
                                       bucket_major=True)
        mod = mx.mod.BucketingModule(sym_gen, default_bucket_key=8,
                                     bucket_ladder=[4, 8],
                                     mask_label=MASK, warmup_buckets=True)
        metric = mx.metric.Perplexity(ignore_label=MASK)
        mod.fit(it, eval_metric=metric, num_epoch=1, bulk=bulk,
                initializer=mx.init.Xavier(),
                optimizer_params={'learning_rate': 0.1, 'momentum': 0.9})
        return params_np(mod), metric.get()[1]

    b0 = profiler.bucketing_stats()
    p_bulk, m_bulk = run(bulk=4)
    b1 = profiler.bucketing_stats()
    p_step, m_step = run(bulk=None)
    assert max_param_diff(p_bulk, p_step) < 1e-5
    assert abs(m_bulk - m_step) / m_step < 1e-3
    new_compiles = sum(
        v['compiles'] for v in b1['train_rungs'].values()) - sum(
        v['compiles'] for v in b0['train_rungs'].values())
    assert new_compiles == 0, 'fit(bulk) paid a mid-epoch compile'
    steps = sum(v['steps'] for v in b1['train_rungs'].values()) - sum(
        v['steps'] for v in b0['train_rungs'].values())
    dispatches = sum(
        v['dispatches'] for v in b1['train_rungs'].values()) - sum(
        v['dispatches'] for v in b0['train_rungs'].values())
    assert steps > dispatches, 'no multi-step dispatch ever ran'


def test_bucket_major_iter_contiguous_and_complete():
    rs = np.random.RandomState(1)
    sentences = [[int(w) + 1 for w in
                  rs.randint(0, 10, size=rs.randint(2, 12))]
                 for _ in range(200)]
    kwargs = dict(batch_size=8, buckets=[4, 8, 12], invalid_label=0)
    plain = mx.rnn.BucketSentenceIter(sentences, **kwargs)
    major = mx.rnn.BucketSentenceIter(sentences, bucket_major=True,
                                      **kwargs)
    assert sorted(plain.idx) == sorted(major.idx)  # same batches
    seen = [i for i, _ in major.idx]
    runs = 1 + sum(1 for a, b in zip(seen, seen[1:]) if a != b)
    assert runs == len(set(seen)), \
        'bucket_major epochs must be bucket-contiguous'
    major.reset()
    assert sorted(plain.idx) == sorted(major.idx)


def test_mesh_zero_ladder_composition():
    """The warmed ladder composes with the data mesh and ZeRO-1: both
    modes hit zero steady-state compiles and produce bit-identical
    parameters (the sharded update is schedule-only different)."""
    results = {}
    for zero in (0, 1):
        mx.random.seed(5)
        ctx = [mx.cpu(i) for i in range(4)]
        mod = mx.mod.BucketingModule(sym_gen, default_bucket_key=8,
                                     context=ctx, bucket_ladder=[4, 8],
                                     mask_label=MASK)
        mod.bind(data_shapes=[mx.io.DataDesc('data', (8, 8),
                                             layout='NT')],
                 label_shapes=[mx.io.DataDesc('softmax_label', (8, 8),
                                              layout='NT')])
        mod.init_params(initializer=mx.init.Xavier())
        mod.init_optimizer(optimizer_params={'learning_rate': 0.1,
                                             'momentum': 0.9},
                           zero=zero)   # forwarded to the inner Module
        mod.warmup_buckets()
        s0 = exec_cache.stats()['total_compile_s']
        for i, seq_len in enumerate((3, 8, 5, 4)):
            rs = np.random.RandomState(100 * i + seq_len)
            X = rs.randint(1, VOCAB, (8, seq_len)).astype(np.float32)
            y = np.roll(X, -1, axis=1)
            y[:, -1] = MASK
            b = mx.io.DataBatch(
                [nd.array(X)], [nd.array(y)], bucket_key=seq_len,
                provide_data=[mx.io.DataDesc('data', (8, seq_len),
                                             layout='NT')],
                provide_label=[mx.io.DataDesc('softmax_label',
                                              (8, seq_len),
                                              layout='NT')])
            mod.forward_backward(b)
            mod.update()
        assert exec_cache.stats()['total_compile_s'] == s0, \
            'mesh/zero=%d ladder paid a steady-state compile' % zero
        results[zero] = params_np(mod)
    assert max_param_diff(results[0], results[1]) == 0.0


# ---------------------------------------------------------------------------
# checkpointing
# ---------------------------------------------------------------------------

def test_checkpoint_roundtrip_across_rungs(tmp_path):
    mod = make_module(ladder=[4, 8], warmup=True)
    for i, seq_len in enumerate((3, 8, 4, 6)):
        mod.forward_backward(make_batch(seq_len, seed=i))
        mod.update()
    states = str(tmp_path / 'opt.states')
    mod._curr_module.save_optimizer_states(states)
    args, auxs = mod.get_params()

    mod2 = make_module(ladder=[4, 8], warmup=True)
    mod2.set_params(args, auxs)
    mod2._curr_module.load_optimizer_states(states)
    for i, seq_len in enumerate((7, 2, 8, 5)):
        b = make_batch(seq_len, seed=10 + i)
        mod.forward_backward(b)
        mod.update()
        mod2.forward_backward(b)
        mod2.update()
    assert max_param_diff(params_np(mod), params_np(mod2)) < 2e-6


# ---------------------------------------------------------------------------
# satellites
# ---------------------------------------------------------------------------

def test_monitor_installed_on_later_buckets():
    mod = make_module()
    mon = mx.mon.Monitor(1, pattern='.*')
    mod.install_monitor(mon)
    assert mod._buckets[8]._exec_group.executor._monitor_callback \
        is not None
    mod.forward(make_batch(5), is_train=False)  # creates bucket 5
    assert mod._buckets[5]._exec_group.executor._monitor_callback \
        is not None, 'bucket created after install_monitor missed it'


def test_init_params_allow_extra_forwarded():
    mod = make_module()
    args, auxs = mod.get_params()
    extra = dict(args)
    extra['not_a_param'] = nd.zeros((2, 2))
    with pytest.raises(mx.base.MXNetError):
        mod.set_params(extra, auxs)
    mod.set_params(extra, auxs, allow_extra=True)   # forwarded through


def test_masked_metric_device_folds():
    """Accuracy(ignore_label=) and Perplexity device folds mirror the
    host updates, masked positions excluded."""
    import jax.numpy as jnp
    rs = np.random.RandomState(0)
    probs = rs.dirichlet(np.ones(VOCAB), size=10).astype(np.float32)
    labels = rs.randint(0, VOCAB, size=10).astype(np.float32)
    labels[7:] = MASK
    for metric in (mx.metric.Accuracy(ignore_label=MASK),
                   mx.metric.Perplexity(ignore_label=MASK)):
        fold = mx.metric.device_fold(metric)
        assert fold is not None
        carry = fold.update(fold.init(),
                            {'softmax_label': jnp.asarray(labels)},
                            {'softmax_output': jnp.asarray(probs)})
        fold.commit(carry)
        dev = metric.get()[1]
        metric.reset()
        metric.update([nd.array(labels)], [nd.array(probs)])
        host = metric.get()[1]
        assert abs(dev - host) < 1e-4, (metric.name, dev, host)
    # unmasked Accuracy counts everything (unchanged default)
    acc = mx.metric.Accuracy()
    acc.update([nd.array(labels)], [nd.array(probs)])
    assert acc.num_inst == 10


def test_bucketing_counters_in_summary_and_dump(tmp_path):
    mod = make_module(ladder=[4, 8], warmup=True)
    for i, seq_len in enumerate((3, 8, 5)):
        mod.forward_backward(make_batch(seq_len, seed=i))
        mod.update()
    stats = profiler.bucketing_stats()
    assert stats['train_bucket_switches'] > 0
    assert stats['train_pad_waste_rows'] > 0
    assert 0.0 < stats['train_pad_waste_frac'] < 1.0
    assert stats['train_rungs']['8']['steps'] > 0
    text = profiler.summary(print_out=False)
    assert 'train_bucket_switches' in text and 'rung' in text
    import json
    profiler.profiler_set_config(
        filename=str(tmp_path / 'profile.json'))
    out = profiler.dump_profile()
    with open(out) as f:
        events = json.load(f)['traceEvents']
    meta = [e for e in events if e.get('name') == 'bucketing']
    assert meta and 'train_pad_waste_rows' in meta[0]['args']
