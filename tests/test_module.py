"""Module API tests — the end-to-end slice of SURVEY.md §7 step 5
(model: reference tests/python/unittest/test_module.py +
tests/python/train/test_mlp.py convergence runs)."""
import numpy as np
import pytest

import mxnet_tpu as mx
from mxnet_tpu import sym, nd


def _make_blobs(n=400, dim=10, classes=3, seed=0):
    rng = np.random.RandomState(seed)
    centers = rng.randn(classes, dim) * 3
    X = np.zeros((n, dim), dtype=np.float32)
    y = np.zeros((n,), dtype=np.float32)
    for i in range(n):
        c = i % classes
        X[i] = centers[c] + rng.randn(dim) * 0.5
        y[i] = c
    return X, y


def _mlp_sym(classes=3):
    data = sym.Variable('data')
    fc1 = sym.FullyConnected(data, name='fc1', num_hidden=32)
    act = sym.Activation(fc1, act_type='relu')
    fc2 = sym.FullyConnected(act, name='fc2', num_hidden=classes)
    return sym.SoftmaxOutput(fc2, name='softmax')


def test_module_fit_converges():
    X, y = _make_blobs()
    train = mx.io.NDArrayIter(X, y, batch_size=40, shuffle=True)
    mod = mx.mod.Module(_mlp_sym(), context=mx.cpu())
    mod.fit(train, num_epoch=10,
            optimizer_params={'learning_rate': 0.5})
    score = mod.score(mx.io.NDArrayIter(X, y, batch_size=40), 'acc')
    assert score[0][1] > 0.95, 'MLP failed to fit blobs: %s' % score


def test_module_multi_device_data_parallel():
    """Multi-context DP via mesh sharding (the reference tests this with
    cpu(0)/cpu(1), test_multi_device_exec.py)."""
    X, y = _make_blobs()
    train = mx.io.NDArrayIter(X, y, batch_size=40, shuffle=True)
    mod = mx.mod.Module(_mlp_sym(), context=[mx.cpu(i) for i in range(4)])
    mod.fit(train, num_epoch=8, optimizer_params={'learning_rate': 0.5})
    score = mod.score(mx.io.NDArrayIter(X, y, batch_size=40), 'acc')
    assert score[0][1] > 0.95, 'multi-device MLP failed: %s' % score


def test_module_predict_and_pad():
    X, y = _make_blobs(n=110)
    mod = mx.mod.Module(_mlp_sym(), context=mx.cpu())
    it = mx.io.NDArrayIter(X, y, batch_size=40)  # 110 -> pad 10 in last
    mod.bind(data_shapes=it.provide_data, label_shapes=it.provide_label,
             for_training=False)
    mod.init_params()
    out = mod.predict(it)
    assert out.shape == (110, 3)


def test_module_checkpoint_roundtrip(tmp_path):
    X, y = _make_blobs()
    train = mx.io.NDArrayIter(X, y, batch_size=40)
    mod = mx.mod.Module(_mlp_sym(), context=mx.cpu())
    mod.fit(train, num_epoch=2, optimizer_params={'learning_rate': 0.5})
    prefix = str(tmp_path / 'mlp')
    mod.save_checkpoint(prefix, 2, save_optimizer_states=True)

    mod2 = mx.mod.Module.load(prefix, 2, context=mx.cpu())
    it = mx.io.NDArrayIter(X, y, batch_size=40)
    mod2.bind(data_shapes=it.provide_data, label_shapes=it.provide_label)
    a1, _ = mod.get_params()
    a2, _ = mod2.get_params()
    for k in a1:
        np.testing.assert_allclose(a1[k].asnumpy(), a2[k].asnumpy(),
                                   rtol=1e-5)
    # predictions identical
    p1 = mod.predict(mx.io.NDArrayIter(X, y, batch_size=40)).asnumpy()
    p2 = mod2.predict(mx.io.NDArrayIter(X, y, batch_size=40)).asnumpy()
    np.testing.assert_allclose(p1, p2, rtol=1e-5)


def test_module_update_on_kvstore_matches_local():
    """push/pull-on-store and local-updater paths produce identical
    updates (the reference asserts exact sync-SGD arithmetic in
    tests/nightly/dist_sync_kvstore.py)."""
    X, y = _make_blobs(n=80)

    def run(kv):
        mx.random.seed(7)
        train = mx.io.NDArrayIter(X, y, batch_size=40)
        mod = mx.mod.Module(_mlp_sym(), context=mx.cpu())
        mod.fit(train, num_epoch=2, kvstore=kv,
                optimizer_params={'learning_rate': 0.1},
                initializer=mx.init.Xavier(),
                force_init=True)
        return {k: v.asnumpy() for k, v in mod.get_params()[0].items()}

    p_none = run(None)
    p_local = run('local')  # single device -> kv is None internally
    p_device = run('device')
    for k in p_none:
        np.testing.assert_allclose(p_none[k], p_local[k], rtol=1e-5)
        np.testing.assert_allclose(p_none[k], p_device[k], rtol=1e-5)


def test_lenet_trains():
    """Conv net end-to-end (reference tests/python/train/test_conv.py
    shape, synthetic data instead of MNIST download)."""
    rng = np.random.RandomState(0)
    n = 160
    X = np.zeros((n, 1, 12, 12), dtype=np.float32)
    y = np.zeros((n,), dtype=np.float32)
    for i in range(n):
        c = i % 2
        X[i, 0] = rng.rand(12, 12) * 0.2
        if c:
            X[i, 0, 3:9, 3:9] += 1.0  # bright square for class 1
        y[i] = c
    data = sym.Variable('data')
    c1 = sym.Convolution(data, name='c1', kernel=(3, 3), num_filter=8)
    a1 = sym.Activation(c1, act_type='relu')
    p1 = sym.Pooling(a1, kernel=(2, 2), stride=(2, 2), pool_type='max')
    fl = sym.Flatten(p1)
    fc = sym.FullyConnected(fl, name='fc', num_hidden=2)
    net = sym.SoftmaxOutput(fc, name='softmax')
    train = mx.io.NDArrayIter(X, y, batch_size=16, shuffle=True)
    mod = mx.mod.Module(net, context=mx.cpu())
    mod.fit(train, num_epoch=5, optimizer_params={'learning_rate': 0.1})
    score = mod.score(mx.io.NDArrayIter(X, y, batch_size=16), 'acc')
    assert score[0][1] > 0.95, 'LeNet-style net failed: %s' % score


def test_bucketing_module():
    """Variable-length training via bucketing (reference
    test_bucketing.py pattern, tiny scale)."""
    def sym_gen(seq_len):
        data = sym.Variable('data')
        label = sym.Variable('softmax_label')
        fc = sym.FullyConnected(data, name='fc_shared', num_hidden=8)
        act = sym.Activation(fc, act_type='relu')
        out = sym.FullyConnected(act, name='out_shared', num_hidden=2)
        net = sym.SoftmaxOutput(out, label=label, name='softmax')
        return net, ('data',), ('softmax_label',)

    mod = mx.mod.BucketingModule(sym_gen, default_bucket_key=8)
    rng = np.random.RandomState(0)

    def make_batch(seq_len, batch=8):
        X = rng.rand(batch, seq_len).astype(np.float32)
        y = (X.sum(axis=1) > seq_len / 2).astype(np.float32)
        return mx.io.DataBatch(
            data=[nd.array(X)], label=[nd.array(y)], bucket_key=seq_len,
            provide_data=[mx.io.DataDesc('data', (batch, seq_len))],
            provide_label=[mx.io.DataDesc('softmax_label', (batch,))])

    mod.bind(data_shapes=[mx.io.DataDesc('data', (8, 8))],
             label_shapes=[mx.io.DataDesc('softmax_label', (8,))])
    mod.init_params(initializer=mx.init.Xavier())
    mod.init_optimizer(optimizer_params={'learning_rate': 0.5})
    for i in range(300):
        batch = make_batch(8)
        mod.forward_backward(batch)
        mod.update()
    metric = mx.metric.create('acc')
    for _ in range(10):
        batch = make_batch(8)
        mod.forward(batch, is_train=False)
        mod.update_metric(metric, batch.label)
    assert metric.get()[1] > 0.65, metric.get()


def test_optimizers_step():
    """Each optimizer makes a step without error and reduces a quadratic."""
    for name in ['sgd', 'adam', 'rmsprop', 'adagrad', 'adadelta', 'nag',
                 'adamax', 'nadam', 'signum', 'ftrl']:
        opt = mx.optimizer.create(name, rescale_grad=1.0)
        w = nd.array([5.0])
        state = opt.create_state(0, w)
        for i in range(50):
            g = 2 * w  # d/dw w^2
            opt.update(0, w, g, state)
        assert abs(w.asscalar()) < 5.0, '%s failed to descend' % name


def test_lr_scheduler():
    sched = mx.lr_scheduler.FactorScheduler(step=10, factor=0.5)
    sched.base_lr = 1.0
    assert sched(5) == 1.0
    assert sched(11) == 0.5
    msched = mx.lr_scheduler.MultiFactorScheduler(step=[5, 10], factor=0.1)
    msched.base_lr = 1.0
    assert abs(msched(6) - 0.1) < 1e-9
    assert abs(msched(11) - 0.01) < 1e-9


def test_metrics():
    acc = mx.metric.create('acc')
    acc.update([nd.array([1, 0])], [nd.array([[0.3, 0.7], [0.6, 0.4]])])
    assert acc.get()[1] == 1.0
    mse = mx.metric.create('mse')
    mse.update([nd.array([1.0, 2.0])], [nd.array([[1.5], [2.5]])])
    assert abs(mse.get()[1] - 0.25) < 1e-6
    comp = mx.metric.create(['acc', 'mse'])
    assert isinstance(comp, mx.metric.CompositeEvalMetric)


def _bulk_mod(ctxs, ap=None, ax=None, batch=16, kvstore='local'):
    data = sym.Variable('data')
    fc1 = sym.FullyConnected(data, name='fc1', num_hidden=16)
    act = sym.Activation(fc1, act_type='relu')
    fc2 = sym.FullyConnected(act, name='fc2', num_hidden=4)
    net = sym.SoftmaxOutput(fc2, name='softmax')
    mod = mx.mod.Module(net, context=ctxs)
    mod.bind(data_shapes=[mx.io.DataDesc('data', (batch, 8))],
             label_shapes=[mx.io.DataDesc('softmax_label', (batch,))])
    if ap is None:
        mod.init_params(initializer=mx.init.Xavier())
    else:
        mod.init_params(initializer=None, arg_params=ap, aux_params=ax)
    mod.init_optimizer(kvstore=kvstore, optimizer='sgd',
                       optimizer_params={'learning_rate': 0.1,
                                         'momentum': 0.9})
    return mod


@pytest.mark.parametrize('n_ctx,kvstore', [(1, 'local'), (4, 'local'),
                                           (4, None), (8, 'local'),
                                           (8, None)])
def test_bulk_step_matches_per_step_loop(n_ctx, kvstore):
    """Module.bulk_step (K steps in one on-device lax.scan dispatch —
    the TPU analog of the reference's bulk-exec segments,
    graph_executor.cc:1135) must produce the same parameters as the
    plain forward_backward+update loop.  (4, 'local') exercises the
    kvstore fallback loop; (4, None) the fused mesh-sharded scan path
    with the stacked batch sharded along dim 1."""
    rng = np.random.RandomState(0)
    batches = [mx.io.DataBatch(
        data=[nd.array(rng.rand(16, 8).astype(np.float32))],
        label=[nd.array((rng.rand(16) * 4).astype(np.float32))])
        for _ in range(5)]
    seed_mod = _bulk_mod([mx.cpu(0)])
    ap, ax = seed_mod.get_params()
    ap = {k: v.copy() for k, v in ap.items()}
    ax = {k: v.copy() for k, v in ax.items()}
    ctxs = [mx.cpu(i) for i in range(n_ctx)]
    a = _bulk_mod(ctxs, ap, ax, kvstore=kvstore)
    b = _bulk_mod(ctxs, ap, ax, kvstore=kvstore)
    c = _bulk_mod(ctxs, ap, ax, kvstore=kvstore)
    d = _bulk_mod(ctxs, ap, ax, kvstore=kvstore)
    if kvstore is None:
        assert b._fused_updater is not None, \
            'kvstore=None must enable the fused whole-step path'
    for bt in batches:
        a.forward_backward(bt)
        a.update()
    b.bulk_step(batches=batches)
    pa, _ = a.get_params()
    pb, _ = b.get_params()
    for k in pa:
        np.testing.assert_allclose(pa[k].asnumpy(), pb[k].asnumpy(),
                                   rtol=2e-5, atol=2e-5)
    # repeat mode: K steps on one batch == per-step loop on that batch
    c.bulk_step(batch=batches[0], repeat=3)
    for _ in range(3):
        d.forward_backward(batches[0])
        d.update()
    pc, _ = c.get_params()
    pd, _ = d.get_params()
    for k in pc:
        np.testing.assert_allclose(pc[k].asnumpy(), pd[k].asnumpy(),
                                   rtol=2e-5, atol=2e-5)


def test_bulk_step_scan_dtype_storage():
    """bulk_step(scan_dtype=...) stores the stacked data batches in a
    narrower dtype and the fused step casts back before the graph
    (docs/PERF.md round 5) — for inputs the model itself quantizes on
    entry the result must match the default-storage path exactly, and
    labels must keep their bound dtype."""
    rng = np.random.RandomState(1)
    # quantize the data to bf16-representable values so bf16 storage is
    # lossless for this check regardless of the model's own entry cast
    raw = rng.rand(16, 8).astype(np.float32)
    import jax.numpy as jnp
    raw = np.asarray(jnp.asarray(raw, jnp.bfloat16).astype(jnp.float32))
    batches = [mx.io.DataBatch(
        data=[nd.array(raw * (2.0 ** i))],  # ×2^i stays bf16-exact
        label=[nd.array((rng.rand(16) * 4).astype(np.float32))])
        for i in range(3)]
    seed_mod = _bulk_mod([mx.cpu(0)], kvstore=None)
    ap, ax = seed_mod.get_params()
    ap = {k: v.copy() for k, v in ap.items()}
    ax = {k: v.copy() for k, v in ax.items()}
    a = _bulk_mod([mx.cpu(0)], ap, ax, kvstore=None)
    b = _bulk_mod([mx.cpu(0)], ap, ax, kvstore=None)
    a.bulk_step(batches=batches)
    b.bulk_step(batches=batches, scan_dtype='bfloat16')
    pa, _ = a.get_params()
    pb, _ = b.get_params()
    for k in pa:
        np.testing.assert_allclose(pa[k].asnumpy(), pb[k].asnumpy(),
                                   rtol=2e-5, atol=2e-5, err_msg=k)


def test_fused_step_with_device_kvstore_single_dispatch():
    """A single-process kvstore ('local'/'device') must not forfeit
    whole-step fusion: the grad all-reduce is already the in-step psum
    of the one SPMD program, so fit() should issue exactly ONE fused
    dispatch per batch instead of per-key eager push/pull (reference
    runs the eager path, model.py:106)."""
    X, y = _make_blobs(n=64, dim=8, classes=4, seed=7)
    train = mx.io.NDArrayIter(X, y, batch_size=16, shuffle=False,
                              label_name='softmax_label')
    ctxs = [mx.cpu(i) for i in range(8)]
    mod = mx.mod.Module(_mlp_sym(classes=4), context=ctxs)
    mod.fit(train, num_epoch=2, kvstore='device',
            optimizer_params={'learning_rate': 0.1})
    assert mod._fused_updater is not None, \
        "kvstore='device' must keep the fused whole-step path"
    assert not mod._update_on_kvstore
    ex = mod._exec_group.executor
    # 2 epochs x 4 batches, one donated dispatch each
    assert ex.fused_dispatches == 8, ex.fused_dispatches


def test_fused_kvstore_matches_no_kvstore():
    """kvstore='local' (fused in-step update) must produce identical
    parameters to kvstore=None — the store is a facade, not different
    math."""
    rng = np.random.RandomState(11)
    batches = [mx.io.DataBatch(
        data=[nd.array(rng.rand(16, 8).astype(np.float32))],
        label=[nd.array((rng.rand(16) * 4).astype(np.float32))])
        for _ in range(4)]
    seed_mod = _bulk_mod([mx.cpu(0)])
    ap, ax = seed_mod.get_params()
    ap = {k: v.copy() for k, v in ap.items()}
    ax = {k: v.copy() for k, v in ax.items()}
    ctxs = [mx.cpu(i) for i in range(4)]
    a = _bulk_mod(ctxs, ap, ax, kvstore='local')
    b = _bulk_mod(ctxs, ap, ax, kvstore=None)
    assert a._fused_updater is not None
    for bt in batches:
        a.forward_backward(bt)
        a.update()
        b.forward_backward(bt)
        b.update()
    pa, _ = a.get_params()
    pb, _ = b.get_params()
    for k in pa:
        np.testing.assert_allclose(pa[k].asnumpy(), pb[k].asnumpy(),
                                   rtol=1e-5, atol=1e-5)


def test_nhwc_layout_pass_matches_nchw():
    """The executor's NHWC layout pass (MXNET_TPU_LAYOUT_OPT=1) must be
    numerically equivalent to semantic NCHW execution across conv/BN/
    relu/pooling/residual-add/global-pool/FC — same outputs, params,
    and BN moving stats after training steps."""
    import os

    seed_params = {}
    prior = os.environ.get('MXNET_TPU_LAYOUT_OPT')

    def run(layout_env):
        os.environ['MXNET_TPU_LAYOUT_OPT'] = layout_env
        try:
            rng = np.random.RandomState(0)
            data = sym.Variable('data')
            c1 = sym.Convolution(data, name='c1', num_filter=8,
                                 kernel=(3, 3), pad=(1, 1))
            b1 = sym.BatchNorm(c1, name='b1', fix_gamma=False)
            a1 = sym.Activation(b1, act_type='relu')
            p1 = sym.Pooling(a1, kernel=(2, 2), stride=(2, 2),
                             pool_type='max')
            c2 = sym.Convolution(p1, name='c2', num_filter=8,
                                 kernel=(3, 3), pad=(1, 1))
            res = c2 + sym.Convolution(p1, name='sc', num_filter=8,
                                       kernel=(1, 1))
            b2 = sym.BatchNorm(res, name='b2', fix_gamma=False)
            gp = sym.Pooling(b2, global_pool=True, pool_type='avg',
                             kernel=(1, 1))
            fc = sym.FullyConnected(sym.Flatten(gp), num_hidden=4,
                                    name='fc')
            net = sym.SoftmaxOutput(fc, name='softmax')
            mod = mx.mod.Module(net, context=[mx.cpu(0)])
            mod.bind(data_shapes=[mx.io.DataDesc('data', (8, 3, 16, 16))],
                     label_shapes=[mx.io.DataDesc('softmax_label', (8,))])
            if seed_params:
                mod.init_params(initializer=None,
                                arg_params=seed_params['arg'],
                                aux_params=seed_params['aux'])
            else:
                mod.init_params(initializer=mx.init.Xavier())
                ap, ax = mod.get_params()
                seed_params['arg'] = {k: v.copy() for k, v in ap.items()}
                seed_params['aux'] = {k: v.copy() for k, v in ax.items()}
            mod.init_optimizer(optimizer_params={'learning_rate': 0.1})
            X = mx.nd.array(rng.rand(8, 3, 16, 16).astype(np.float32))
            y = mx.nd.array((rng.rand(8) * 4).astype(np.float32))
            bt = mx.io.DataBatch(data=[X], label=[y])
            for _ in range(3):
                mod.forward_backward(bt)
                mod.update()
            mod.forward(bt, is_train=False)
            out = mod.get_outputs()[0].asnumpy()
            params, aux = mod.get_params()
            return (out, {k: v.asnumpy() for k, v in params.items()},
                    {k: v.asnumpy() for k, v in aux.items()})
        finally:
            if prior is None:
                os.environ.pop('MXNET_TPU_LAYOUT_OPT', None)
            else:
                os.environ['MXNET_TPU_LAYOUT_OPT'] = prior

    o0, p0, a0 = run('0')
    o1, p1, a1 = run('1')
    np.testing.assert_allclose(o0, o1, rtol=2e-4, atol=2e-5)
    for k in p0:
        np.testing.assert_allclose(p0[k], p1[k], rtol=2e-4,
                                   atol=2e-5, err_msg=k)
    for k in a0:
        np.testing.assert_allclose(a0[k], a1[k], rtol=2e-4,
                                   atol=2e-5, err_msg=k)


def test_fused_step_deferred_materialization():
    """forward_backward defers when the whole step can fuse; accessing
    outputs before update() must still yield correct results, and the
    fused path must match the unfused two-dispatch path."""
    rng = np.random.RandomState(1)
    bt = mx.io.DataBatch(
        data=[nd.array(rng.rand(16, 8).astype(np.float32))],
        label=[nd.array((rng.rand(16) * 4).astype(np.float32))])
    seed_mod = _bulk_mod([mx.cpu(0)])
    ap, ax = seed_mod.get_params()
    ap = {k: v.copy() for k, v in ap.items()}
    ax = {k: v.copy() for k, v in ax.items()}
    a = _bulk_mod([mx.cpu(0)], ap, ax)
    b = _bulk_mod([mx.cpu(0)], ap, ax)
    # a: read outputs between fwd_bwd and update (materialization path)
    a.forward_backward(bt)
    out_a = a.get_outputs()[0].asnumpy()
    a.update()
    # b: straight fused path
    b.forward_backward(bt)
    b.update()
    out_b = b.get_outputs()[0].asnumpy()
    np.testing.assert_allclose(out_a, out_b, rtol=1e-5, atol=1e-6)
    pa, _ = a.get_params()
    pb, _ = b.get_params()
    for k in pa:
        np.testing.assert_allclose(pa[k].asnumpy(), pb[k].asnumpy(),
                                   rtol=2e-5, atol=2e-5)
