"""Headline benchmark: ResNet-50 training throughput on one TPU chip.

Baseline (BASELINE.md): reference MXNet trains ResNet-50 at 109 img/s on
1x K80 (batch 32).  The whole training step (fwd+bwd+fused SGD update)
compiles into ONE donated XLA dispatch, and `Module.bulk_step` loops K
steps on-device per dispatch (lax.scan device loop — the TPU analog of
the reference's bulk-exec segments, graph_executor.cc:1135), so host and
link latency amortize over K full steps.

Prints ONE JSON line: {"metric", "value", "unit", "vs_baseline", ...}.
The dtype rides in the JSON so the comparison basis is explicit
(bfloat16 mixed precision with fp32 master weights by default, matching
the reference's fp16 multi_precision headline mode — NEWS.md:18).
Env knobs: BENCH_BATCH (default tries 256,128,64), BENCH_STEPS (bulk
dispatches), BENCH_BULK (steps per dispatch), BENCH_DTYPE, BENCH_MODEL.
"""
import json
import os
import sys
import time

import numpy as np


def run(batch, steps, warmup, bulk, num_layers=50, dtype='float32'):
    import jax
    import mxnet_tpu as mx
    from mxnet_tpu.models import resnet

    ctx = mx.tpu() if any(d.platform != 'cpu' for d in jax.devices()) \
        else mx.cpu()
    sym = resnet.get_symbol(num_classes=1000, num_layers=num_layers,
                            dtype=dtype)
    mod = mx.mod.Module(sym, context=ctx)
    mod.bind(data_shapes=[mx.io.DataDesc('data', (batch, 3, 224, 224))],
             label_shapes=[mx.io.DataDesc('softmax_label', (batch,))])
    mod.init_params(initializer=mx.init.Xavier(rnd_type='gaussian',
                                               factor_type='in',
                                               magnitude=2))
    mod.init_optimizer(optimizer='sgd',
                       optimizer_params={'learning_rate': 0.1,
                                         'momentum': 0.9, 'wd': 1e-4,
                                         'multi_precision':
                                             dtype != 'float32'})
    rng = np.random.RandomState(0)
    batches = [
        mx.io.DataBatch(
            data=[mx.nd.array(
                rng.rand(batch, 3, 224, 224).astype(np.float32),
                ctx=ctx)],
            label=[mx.nd.array(
                (rng.rand(batch) * 1000).astype(np.float32), ctx=ctx)])
        for _ in range(bulk)]
    # mixed-precision models cast data to the compute dtype as their
    # first op, so storing the K stacked scan batches in that dtype is
    # value-preserving (bulk_step casts back before the graph) and
    # halves their footprint — which is what lets K reach 32
    scan_dtype = dtype if dtype != 'float32' else None

    def step():
        if bulk > 1:
            mod.bulk_step(batches=batches, scan_dtype=scan_dtype)
        else:
            mod.forward_backward(batches[0])
            mod.update()

    for _ in range(warmup):
        step()
    _block(mod)
    tic = time.time()
    for _ in range(steps):
        step()
    _block(mod)
    dt = time.time() - tic
    return batch * bulk * steps / dt


def _block(mod):
    """Force completion with a host fetch — block_until_ready alone can
    return before remote execution finishes on tunneled backends.  Fetch
    a single element (device-side slice) so the transfer itself is
    negligible."""
    w = mod._exec_group.executor.arg_dict['fc1_weight']
    float(w._data.ravel()[0])


def main():
    batches = [int(os.environ['BENCH_BATCH'])] if 'BENCH_BATCH' in os.environ \
        else [256, 128, 64]
    steps = int(os.environ.get('BENCH_STEPS', 6))
    warmup = int(os.environ.get('BENCH_WARMUP', 2))
    # 16 steps/dispatch measured +3.2% over 8 (the dependent-dispatch
    # tunnel RTT amortizes further); 32 fits under scan_dtype but
    # measured 2% SLOWER (round 5) — 16 stays the sweet spot
    bulk = int(os.environ.get('BENCH_BULK', 16))
    dtype = os.environ.get('BENCH_DTYPE', 'bfloat16')
    # BENCH_MODEL=resnet-N picks another family depth (the headline
    # metric stays resnet-50; tools/bench_family.py sweeps the whole
    # BASELINE.md table including inception-bn)
    model = os.environ.get('BENCH_MODEL', 'resnet-50')
    k80_map = {'resnet-18': 185.0, 'resnet-34': 172.0, 'resnet-50': 109.0,
               'resnet-101': 78.0, 'resnet-152': 57.0}
    if model not in k80_map:
        raise SystemExit(
            'BENCH_MODEL must be one of %s (tools/bench_family.py covers '
            'inception-bn and the rest of BASELINE.md)'
            % ', '.join(sorted(k80_map)))
    depth = int(model.split('-')[1])
    k80 = k80_map[model]
    best = None
    err = None
    for i, b in enumerate(batches):
        try:
            ips = run(b, steps, warmup, bulk, num_layers=depth,
                      dtype=dtype)
            if best is None or ips > best:
                best = ips
            break  # largest fitting batch wins
        except Exception as e:  # OOM at this batch -> retry smaller
            err = e
            if 'RESOURCE_EXHAUSTED' not in str(e) and \
                    'Out of memory' not in str(e):
                raise
            # the in-process TPU client stays poisoned after a
            # ResourceExhausted (smaller retries re-OOM; measured,
            # docs/PERF.md round 5) — re-exec each smaller attempt
            import subprocess
            for nb in batches[i + 1:]:
                env = dict(os.environ, BENCH_BATCH=str(nb))
                proc = subprocess.run([sys.executable,
                                       os.path.abspath(__file__)],
                                      env=env, capture_output=True,
                                      text=True)
                if proc.returncode == 0:
                    print(proc.stdout.strip().splitlines()[-1])
                    return
                err = RuntimeError(proc.stderr[-2000:])
            break
    if best is None:
        raise err
    baseline = k80  # per-model 1x K80 fp32 img/s, BASELINE.md
    print(json.dumps({
        'metric': '%s_train_throughput_1chip' % model.replace('-', ''),
        'value': round(best, 2),
        'unit': 'images/sec',
        'vs_baseline': round(best / baseline, 3),
        'dtype': dtype,
        'steps_per_dispatch': bulk,
        'baseline': 'K80 fp32 %.0f img/s (BASELINE.md)' % k80,
    }))


if __name__ == '__main__':
    main()
